"""ZeRO-3 fully-sharded parameters (``sharded_params: "zero3"``).

Coverage map:
- config surface: knob constraints, SMP_ZERO3 / SMP_ZERO3_BUCKET_MB env
  aliases, mutual exclusion with the legacy zero2d knob;
- spec machinery: largest-divisible-dim rdp placement, idempotence on
  specs already carrying rdp, the gathered-layout strip helpers, and
  ``describe_state_layout``'s param-sharding mode;
- the end-to-end gate (acceptance): parity vs the unsharded baseline at
  rdp=2 (losses/grads/updated params), the X-ray census showing
  per-layer rdp all-gathers + the bucketed reduce-scatter, ZERO
  replication findings, per-device param bytes == 1/rdp, the overlap /
  double-buffered-register evidence, and the committed golden
  fingerprint;
- composition (slow tier): pp2 x zero3 parity, the GSPMD fallback path
  with prefetch off, and the elastic round trips across world shapes
  (zero3 -> plain dp and back, bitwise);
- satellites: exec-cache knob facts (flip -> verified miss), the
  telemetry_report "-- zero --" section golden, and the perf-ledger
  ``zero_probe`` component schema/carry/render.
"""

import importlib.util
import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.config import ModelParallelConfig
from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS
from smdistributed_modelparallel_tpu.models.transformer_lm import TransformerLM
from smdistributed_modelparallel_tpu.parallel import zero
from smdistributed_modelparallel_tpu.utils import hlo_audit
from smdistributed_modelparallel_tpu.utils.exceptions import ConfigError

from tests.models import softmax_xent

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.join(_REPO, "scripts")

# The canonical zero3 model/config: identical to the golden generator's
# (tests/goldens/generate_hlo_fingerprints.py "zero3_rdp2").
CANON_MODEL = dict(vocab_size=32, max_len=12, d_model=16, n_layers=4,
                   n_heads=2)
Z3 = {"sharded_params": "zero3", "sdp_param_persistence_threshold": 1}


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _train(cfg, steps=3, lr=0.1, model_kwargs=None):
    smp.shutdown()
    smp.init(cfg)
    kwargs = dict(CANON_MODEL)
    kwargs.update(model_kwargs or {})
    model = smp.DistributedModel(TransformerLM(**kwargs))
    opt = smp.DistributedOptimizer(optax.sgd(lr), model)

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        loss = jnp.mean(softmax_xent(logits[:, :-1], ids[:, 1:]))
        model.backward(loss)
        return loss

    ids = jax.random.randint(jax.random.key(0), (8, 12), 0, 32)
    losses = []
    for _ in range(steps):
        out = train_step(model, ids)
        losses.append(float(out.reduce_mean()))
        opt.step()
    return losses, model, opt, train_step


def _np_tree(tree):
    return {
        str(path): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def _assert_trees_close(a, b, atol):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], atol=atol, err_msg=k)


def _rdp_sharded_leaves(params):
    n = 0
    for leaf in jax.tree_util.tree_leaves(params):
        spec = getattr(leaf.sharding, "spec", None) or ()
        if any(
            RDP_AXIS in (a if isinstance(a, tuple) else (a,))
            for a in spec if a is not None
        ):
            n += 1
    return n


def _param_device_bytes(params):
    per_device = total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        shard = leaf.sharding.shard_shape(leaf.shape)
        n = 1
        for d in shard:
            n *= int(d)
        per_device += n * leaf.dtype.itemsize
        total += int(leaf.size) * leaf.dtype.itemsize
    return per_device, total


# ----------------------------------------------------------------------
# Config surface
# ----------------------------------------------------------------------


class TestConfig:
    def test_zero3_requires_ddp(self):
        with pytest.raises(ConfigError):
            ModelParallelConfig({"sharded_params": "zero3"})

    def test_zero3_excludes_zero2d_degree(self):
        with pytest.raises(ConfigError):
            ModelParallelConfig({
                "sharded_params": "zero3", "ddp": True,
                "sharded_data_parallel_degree": 4,
            })

    def test_zero3_excludes_sdp_json(self):
        with pytest.raises(ConfigError):
            ModelParallelConfig({
                "sharded_params": "zero3", "ddp": True,
                "_sharded_data_parallelism_config": {
                    "zero_optimization": {"stage": 3},
                },
            })

    def test_enabled_property_and_default(self):
        cfg = ModelParallelConfig({"sharded_params": "zero3", "ddp": True})
        assert cfg.zero3_enabled and not cfg.zero2d_enabled
        assert ModelParallelConfig({}).sharded_params == "none"
        assert not ModelParallelConfig({}).zero3_enabled

    def test_env_alias(self, monkeypatch):
        monkeypatch.setenv("SMP_ZERO3", "1")
        assert ModelParallelConfig({"ddp": True}).zero3_enabled
        # Explicit config wins over the env alias.
        assert not ModelParallelConfig(
            {"ddp": True, "sharded_params": "none"}
        ).zero3_enabled
        monkeypatch.setenv("SMP_ZERO3", "garbage")
        with pytest.raises(ConfigError):
            ModelParallelConfig({"ddp": True})

    def test_bucket_env_alias(self, monkeypatch):
        monkeypatch.setenv("SMP_ZERO3_BUCKET_MB", "7")
        cfg = ModelParallelConfig({"ddp": True, "sharded_params": "zero3"})
        assert cfg.zero3_bucket_mb == 7
        monkeypatch.setenv("SMP_ZERO3_BUCKET_MB", "nope")
        with pytest.raises(ConfigError):
            ModelParallelConfig({"ddp": True})


# ----------------------------------------------------------------------
# Spec machinery
# ----------------------------------------------------------------------


class TestSpecs:
    def test_add_rdp_axis_prefers_largest_dim(self):
        # Scanned stack [L=4, in=32, out=64]: "first" grabs the layer
        # axis, "largest" the out dim — keeping the per-layer dynamic
        # slice local under zero3.
        assert zero.add_rdp_axis(None, (4, 32, 64), 2) == [RDP_AXIS, None, None]
        assert zero.add_rdp_axis(None, (4, 32, 64), 2, prefer="largest") == [
            None, None, RDP_AXIS,
        ]

    def test_add_rdp_axis_idempotent_on_rdp_specs(self):
        # A spec already carrying rdp (zero2d/zero3 param mirrored into
        # its optimizer moment) must come back unchanged — one mesh axis
        # cannot name two dims.
        spec = [RDP_AXIS, None]
        assert zero.add_rdp_axis(spec, (32, 64), 2) == [RDP_AXIS, None]

    def test_add_rdp_axis_threshold_and_indivisible(self):
        assert zero.add_rdp_axis(None, (3, 5), 2, prefer="largest") is None
        assert zero.add_rdp_axis(None, (8,), 2, persistence_threshold=100) is None

    def test_strip_rdp(self):
        from jax.sharding import PartitionSpec as P

        assert zero.strip_rdp(P(RDP_AXIS, None)) == P(None, None)
        assert zero.strip_rdp(P(("pp", RDP_AXIS), "tp")) == P(("pp",), "tp")

    def test_slice_batch_nonzero_axis(self):
        """input_split_axes can put the batch on a later dim: the slice
        split must land on THAT dim and still present the rdp slices as
        the leading vmap axis."""
        smp.shutdown()
        smp.init({"microbatches": 2, "ddp": True,
                  "_device_count_override": 2})
        leaf = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)
        out = jax.jit(lambda l: zero.zero3_slice_batch(l, 1, 2))(leaf)
        assert out.shape == (2, 2, 4, 3)
        np.testing.assert_array_equal(
            np.asarray(out[1]), np.asarray(leaf[:, 4:, :])
        )

    def test_outputs_mergeable_probe(self):
        S = jax.ShapeDtypeStruct
        f32 = jnp.float32
        # Leading batch dim scaling by rdp, scalars, and flattened
        # leading dims all merge exactly.
        assert zero.zero3_outputs_mergeable(
            {"loss": S((), f32), "logits": S((8, 12, 32), f32),
             "flat": S((96,), f32)},
            {"loss": S((), f32), "logits": S((4, 12, 32), f32),
             "flat": S((48,), f32)},
            2,
        )
        # Batch on a later axis does not scale dim 0 -> not mergeable.
        assert not zero.zero3_outputs_mergeable(
            {"stats": S((3, 8), f32)}, {"stats": S((3, 4), f32)}, 2
        )
        # A shape that coincidentally equals the sliced shape (no batch
        # dependence) must NOT be treated as mergeable either.
        assert not zero.zero3_outputs_mergeable(
            {"w": S((2, 2), f32)}, {"w": S((2, 2), f32)}, 2
        )

    def test_describe_state_layout_modes(self):
        d = zero.describe_state_layout({"sharded_params": "zero3"})
        assert d["zero3"] and d["sharded_params"] == "zero3"
        assert not d["zero2d"]
        d = zero.describe_state_layout({"sharded_data_parallel_degree": 4})
        assert d["zero2d"] and not d["zero3"]
        assert d["sharded_params"] == "none"


# ----------------------------------------------------------------------
# End-to-end acceptance gate (fast tier): parity + the X-ray evidence
# ----------------------------------------------------------------------


class TestZero3Gate:
    def test_parity_and_xray_gate(self):
        """THE acceptance test: at rdp=2, zero3 must (a) match the
        unsharded baseline bit-for-tolerance on losses/grads/updated
        params, (b) compile a program whose census shows per-layer
        rdp-attributed all-gathers and a bucketed rdp reduce-scatter,
        (c) report ZERO replicated params, (d) realize per-device param
        bytes at exactly 1/rdp of the logical total, and (e) match the
        committed golden fingerprint."""
        base_cfg = {"microbatches": 2, "ddp": True,
                    "_device_count_override": 2}
        base_l, base_model, _, base_step = _train(base_cfg)
        base_grads = _np_tree(base_model.grads)
        base_params = _np_tree(base_model.params)
        base_audit = hlo_audit.of_step_function(base_step)

        z3_l, model, _, train_step = _train(dict(base_cfg, **Z3))
        np.testing.assert_allclose(base_l, z3_l, atol=2e-5)
        _assert_trees_close(base_grads, _np_tree(model.grads), atol=2e-5)
        _assert_trees_close(base_params, _np_tree(model.params), atol=2e-5)

        # (b) collective census: per-layer gathers + bucketed scatter,
        # all attributed to the rdp axis.
        audit = hlo_audit.of_step_function(train_step)
        n_layers = CANON_MODEL["n_layers"]
        assert audit.collective_count("all-gather", RDP_AXIS) >= n_layers
        assert audit.collective_count("reduce-scatter", RDP_AXIS) >= 1
        assert audit.zero is not None
        assert audit.zero["gather_ops"] >= n_layers
        assert audit.zero["scatter_ops"] >= 1
        # Overlap evidence: every gather/scatter byte is issued inside a
        # loop body, and the double-buffered transfer registers are
        # structurally present (an all-gather parked in the scan carry,
        # untouched by the same iteration's dots).
        assert audit.zero["loop_gather_ops"] == audit.zero["gather_ops"]
        assert audit.zero["overlap_fraction"] == pytest.approx(1.0)
        assert audit.zero["prefetch_registers"] > 0

        # (c) replication detector: nothing replicated that should not be.
        assert audit.findings == []
        assert _rdp_sharded_leaves(model.params) == len(
            jax.tree_util.tree_leaves(model.params)
        )

        # (d) per-device param memory is exactly the 1/rdp shard; the
        # compiled program's argument bytes drop below the baseline's
        # (same batch, params halved).
        per_device, total = _param_device_bytes(model.params)
        assert per_device * 2 == total
        if audit.memory.get("argument_bytes") and base_audit is not None \
                and base_audit.memory.get("argument_bytes"):
            assert (audit.memory["argument_bytes"]
                    < base_audit.memory["argument_bytes"])

        # (e) committed golden (SEMANTIC_FIELDS diff, zero block included).
        from tests.conftest import assert_matches_hlo_golden

        assert_matches_hlo_golden(audit, "zero3_rdp2")

    def test_optimizer_moments_mirror_param_shards(self):
        smp.shutdown()
        smp.init(dict({"microbatches": 2, "ddp": True,
                       "_device_count_override": 2}, **Z3))
        model = smp.DistributedModel(TransformerLM(**CANON_MODEL))
        opt = smp.DistributedOptimizer(optax.adamw(1e-3), model)

        @smp.step
        def train_step(model, ids):
            logits = model(ids)
            loss = jnp.mean(softmax_xent(logits[:, :-1], ids[:, 1:]))
            model.backward(loss)
            return loss

        ids = jax.random.randint(jax.random.key(0), (8, 12), 0, 32)
        train_step(model, ids)
        opt.step()
        moment_leaves = [
            leaf for leaf in jax.tree_util.tree_leaves(opt.opt_state)
            if isinstance(leaf, jax.Array) and leaf.ndim >= 1
        ]
        assert moment_leaves
        sharded = sum(
            1 for leaf in moment_leaves
            if any(
                RDP_AXIS in (a if isinstance(a, tuple) else (a,))
                for a in (getattr(leaf.sharding, "spec", None) or ())
                if a is not None
            )
        )
        assert sharded > 0, "no optimizer moment sharded over rdp"


# ----------------------------------------------------------------------
# Composition (slow tier: extra multi-program compiles)
# ----------------------------------------------------------------------


class TestZero3Composition:
    def test_pp2_composition_parity(self):
        """pp2 x zero3: parity vs the unsharded pp=1 baseline, rdp
        gathers INSIDE the tick loop (per-stage gather scoping), pp
        permutes intact, zero findings."""
        base_cfg = {"microbatches": 4, "ddp": True,
                    "_device_count_override": 4}
        base_l, base_model, _, _ = _train(base_cfg)
        base_params = _np_tree(base_model.params)

        z3_l, model, _, train_step = _train(dict(
            base_cfg, pipeline_parallel_degree=2, **Z3
        ))
        np.testing.assert_allclose(base_l, z3_l, atol=1e-4)
        _assert_trees_close(base_params, _np_tree(model.params), atol=1e-4)
        audit = hlo_audit.of_step_function(train_step)
        assert audit.collective_count("all-gather", RDP_AXIS) > 0
        assert audit.collective_count("collective-permute", "pp") > 0
        assert audit.findings == []
        assert audit.zero is not None
        # Per-stage scoping: the rdp gathers live inside the tick loop.
        assert audit.zero["loop_gather_ops"] == audit.zero["gather_ops"] > 0

    def test_unmergeable_outputs_fall_back_exact(self):
        """A step fn returning an output whose batch is NOT on the
        leading dim must trip the output-shape probe into the GSPMD
        gradient path — outputs byte-exact vs the baseline, params still
        sharded."""
        def run(extra):
            smp.shutdown()
            cfg = {"microbatches": 2, "ddp": True,
                   "_device_count_override": 2}
            cfg.update(extra)
            smp.init(cfg)
            model = smp.DistributedModel(TransformerLM(**CANON_MODEL))

            @smp.step
            def train_step(model, ids):
                logits = model(ids)
                loss = jnp.mean(softmax_xent(logits[:, :-1], ids[:, 1:]))
                model.backward(loss)
                # [T, B] — batch on the trailing dim: not slice-mergeable.
                return loss, jnp.swapaxes(logits.sum(-1), 0, 1)

            ids = jax.random.randint(jax.random.key(0), (8, 12), 0, 32)
            out = train_step(model, ids)
            loss, swapped = out.outputs[0]
            return np.asarray(loss), np.asarray(swapped), model, train_step

        b_loss, b_swapped, _, _ = run({})
        z_loss, z_swapped, model, train_step = run(Z3)
        np.testing.assert_allclose(b_loss, z_loss, atol=2e-5)
        assert z_swapped.shape == b_swapped.shape
        np.testing.assert_allclose(b_swapped, z_swapped, atol=2e-4)
        # Fallback kept params sharded (the storage story is unaffected).
        assert _rdp_sharded_leaves(model.params) > 0
        audit = hlo_audit.of_step_function(train_step)
        # GSPMD grads: no manual reduce-scatter buckets on this program.
        assert audit.collective_count("reduce-scatter", RDP_AXIS) == 0
        assert audit.collective_count("all-gather", RDP_AXIS) > 0

    def test_prefetch_off_gspmd_path(self, monkeypatch):
        """SMP_ZERO3_PREFETCH=0: the lifted scan stays in place and GSPMD
        places the per-layer gathers; parity and the reduce-scatter grad
        path are unaffected."""
        base_cfg = {"microbatches": 2, "ddp": True,
                    "_device_count_override": 2}
        base_l, base_model, _, _ = _train(base_cfg)
        base_grads = _np_tree(base_model.grads)
        monkeypatch.setenv("SMP_ZERO3_PREFETCH", "0")
        z3_l, model, _, train_step = _train(dict(base_cfg, **Z3))
        np.testing.assert_allclose(base_l, z3_l, atol=2e-5)
        _assert_trees_close(base_grads, _np_tree(model.grads), atol=2e-5)
        audit = hlo_audit.of_step_function(train_step)
        assert audit.collective_count("all-gather", RDP_AXIS) > 0
        assert audit.collective_count("reduce-scatter", RDP_AXIS) >= 1
        # No transfer registers on this path — the gathers feed compute.
        assert audit.zero["prefetch_registers"] == 0


# ----------------------------------------------------------------------
# Elastic round trips across world shapes (slow tier)
# ----------------------------------------------------------------------


class TestZero3Elastic:
    def _ids(self):
        return jax.random.randint(jax.random.key(0), (8, 12), 0, 32)

    def _build(self, cfg):
        smp.shutdown()
        smp.init(cfg)
        model = smp.DistributedModel(TransformerLM(**CANON_MODEL))
        opt = smp.DistributedOptimizer(optax.sgd(0.1), model)

        @smp.step
        def train_step(model, ids):
            logits = model(ids)
            loss = jnp.mean(softmax_xent(logits[:, :-1], ids[:, 1:]))
            model.backward(loss)
            return loss

        train_step(model, self._ids())
        opt.step()
        return model, opt

    @pytest.mark.parametrize("direction", ["zero3_to_dp", "dp_to_zero3"])
    def test_round_trip_world_shape_change(self, tmp_path, direction):
        """Save under one layout, resume under the other: shard catalogs
        key by logical path + global bounds, so a zero3 checkpoint's
        1/rdp param pieces reassemble bitwise under plain dp — and a
        plain-dp checkpoint shards cleanly INTO zero3 (the supervisor's
        shrink-to-survivors recovery crosses exactly this boundary)."""
        dp_cfg = {"microbatches": 2, "ddp": True,
                  "_device_count_override": 2}
        z3_cfg = dict(dp_cfg, **Z3)
        src_cfg, dst_cfg = (
            (z3_cfg, dp_cfg) if direction == "zero3_to_dp"
            else (dp_cfg, z3_cfg)
        )
        model, opt = self._build(src_cfg)
        saved = _np_tree(model.params)
        smp.save_checkpoint(str(tmp_path), tag="t", model=model,
                            optimizer=opt, blocking=True)

        model2, _ = self._build(dst_cfg)
        # model2 is initialized, so the (elastic) resume applies
        # immediately: each leaf reassembles from logical bounds and
        # re-slices under the destination layout's shardings.
        smp.resume_from_checkpoint(str(tmp_path), tag="t")
        resumed = _np_tree(model2.params)
        assert saved.keys() == resumed.keys()
        for k in saved:
            np.testing.assert_array_equal(saved[k], resumed[k], err_msg=k)


# ----------------------------------------------------------------------
# Exec-cache knob facts: a knob flip can never warm-hit
# ----------------------------------------------------------------------


class TestCacheKnobs:
    def test_knob_facts_present(self):
        from smdistributed_modelparallel_tpu.utils import exec_cache

        smp.shutdown()
        smp.init(dict({"microbatches": 2, "ddp": True,
                       "_device_count_override": 2}, **Z3))
        knobs = exec_cache._knob_facts()
        assert knobs["sharded_params"] == "zero3"
        assert knobs["zero3_bucket_mb"] == 25
        assert knobs["sdp_param_persistence_threshold"] == 1
        assert knobs["zero3_prefetch"] == "on"

    def test_idle_knobs_canonicalized_when_off(self, monkeypatch):
        """With zero3 off, bucket/threshold/prefetch cannot affect the
        program — a stray SMP_ZERO3_PREFETCH (or a different bucket
        default) must NOT invalidate caches of byte-identical programs."""
        from smdistributed_modelparallel_tpu.utils import exec_cache

        smp.shutdown()
        monkeypatch.setenv("SMP_ZERO3_PREFETCH", "0")
        smp.init({"microbatches": 2, "ddp": True,
                  "_device_count_override": 2,
                  "zero3_bucket_mb": 13,
                  "sdp_param_persistence_threshold": 7})
        knobs = exec_cache._knob_facts()
        assert knobs["sharded_params"] == "none"
        assert knobs["zero3_bucket_mb"] == 0
        assert knobs["sdp_param_persistence_threshold"] == 0
        assert knobs["zero3_prefetch"] == "-"

    def test_prefetch_knob_normalized(self, monkeypatch):
        monkeypatch.setenv("SMP_ZERO3_PREFETCH", "0")
        assert zero.prefetch_knob() == "off"
        monkeypatch.setenv("SMP_ZERO3_PREFETCH", "off")
        assert zero.prefetch_knob() == "off"
        monkeypatch.delenv("SMP_ZERO3_PREFETCH")
        assert zero.prefetch_knob() == "on"

    def test_knob_flip_is_a_verified_miss(self, tmp_path, monkeypatch):
        """A disk entry stored under different zero3 knobs must be
        rejected at load (reject_version), exactly like a jaxlib skew —
        the belt-and-braces guard behind the step key's zero tuple."""
        from smdistributed_modelparallel_tpu.utils import exec_cache

        smp.shutdown()
        monkeypatch.setenv(exec_cache.ENV, "on")
        monkeypatch.setenv(exec_cache.DIR_ENV, str(tmp_path / "cache"))
        f = jax.jit(lambda x: x * 2.0)
        x = jnp.ones((4,), jnp.float32)
        lowered = f.lower(x)
        sha = exec_cache.module_hash(lowered)
        path = exec_cache.store("step", "k" * 16, lowered.compile(),
                                module_sha=sha)
        assert path
        # Same knobs -> verified hit.
        loaded, _ = exec_cache.load("step", "k" * 16, module_sha=sha)
        assert loaded is not None
        # Flip one zero3 knob in the stored facts -> rejected, entry kept
        # (it belongs to the other knob setting, not corrupt).
        meta_path = os.path.join(path, "meta.json")
        with open(meta_path) as fh:
            meta = json.load(fh)
        meta["knobs"]["sharded_params"] = "zero3"
        meta["knobs"]["zero3_bucket_mb"] = 13
        with open(meta_path, "w") as fh:
            json.dump(meta, fh)
        loaded, _ = exec_cache.load("step", "k" * 16, module_sha=sha)
        assert loaded is None
        assert os.path.exists(path)

    def test_step_key_carries_zero_tuple(self):
        """The in-memory step cache key embeds (mode, bucket, threshold):
        flipping any of them changes the disk key hash too."""
        from smdistributed_modelparallel_tpu.utils import exec_cache

        base = (("none", 25, 1000000, 1), "shapes...")
        flipped = (("zero3", 25, 1000000, 1), "shapes...")
        assert (exec_cache.stable_key_hash(base)
                != exec_cache.stable_key_hash(flipped))


# ----------------------------------------------------------------------
# telemetry_report "-- zero --" section (golden)
# ----------------------------------------------------------------------


def _gauge_family(series):
    return {"kind": "gauge", "help": "", "series": series}


class TestZeroReportSection:
    def _report(self):
        lab = {"step": "step"}
        metrics = {
            "smp_zero3_gather_ops": [({**lab}, 30)],
            "smp_zero3_gather_bytes": [({**lab}, 31296)],
            "smp_zero3_scatter_ops": [({**lab}, 1)],
            "smp_zero3_scatter_bytes": [({**lab}, 27712)],
            "smp_zero3_buckets": [({**lab}, 1)],
            "smp_zero3_bucket_bytes": [({**lab}, 55424)],
            "smp_zero3_sharded_params": [({**lab}, 16)],
            "smp_zero3_persistent_params": [({**lab}, 0)],
            "smp_zero3_overlap_fraction": [({**lab}, 1.0)],
            "smp_zero3_prefetch_registers": [({**lab}, 12)],
        }
        return {
            "meta": {"pid": 1, "phase": "run/step"},
            "metrics": {
                name: _gauge_family([
                    {"labels": labels, "value": value}
                    for labels, value in series
                ])
                for name, series in metrics.items()
            },
        }

    GOLDEN = (
        "\n-- zero --\n"
        "step:\n"
        "  param gathers: 30 op(s), 30.6 KiB/device   grad scatters: "
        "1 op(s), 27.1 KiB/device\n"
        "  reduce-scatter buckets: 1 (54.1 KiB grads/microbatch)\n"
        "  params: 16 rdp-sharded, 0 persistent (replicated)\n"
        "  overlap: 100.0% of gather/scatter bytes issued inside loop "
        "bodies; 12 double-buffered register gather(s)\n"
    )

    def test_single_dump_golden(self):
        mod = _load_script("telemetry_report")
        out = io.StringIO()
        mod.render(self._report(), out=out)
        text = out.getvalue()
        assert self.GOLDEN in text

    def test_dir_mode_aggregate_renders_section(self, tmp_path):
        mod = _load_script("telemetry_report")
        for rank in (0, 1):
            rep = self._report()
            rep["meta"]["rank"] = rank
            with open(tmp_path / f"telemetry.json.rank{rank}", "w") as f:
                json.dump(rep, f)
        reports = mod.load_rank_dumps(str(tmp_path))
        assert sorted(reports) == [0, 1]
        out = io.StringIO()
        mod.render_cross_rank(reports, out=out)
        # Gauges max across ranks: the aggregate section equals one rank's.
        assert self.GOLDEN in out.getvalue()

    def test_absent_gauges_omit_section(self):
        mod = _load_script("telemetry_report")
        out = io.StringIO()
        mod.render({"meta": {}, "metrics": {}}, out=out)
        assert "-- zero --" not in out.getvalue()


# ----------------------------------------------------------------------
# perf_ledger zero_probe component
# ----------------------------------------------------------------------


def _zero_probe_block(**over):
    block = {
        "component": "zero_probe", "rdp": 8,
        "zero2d_ms": 44.7, "zero3_ms": 40.1, "speedup": 1.1147,
        "memory": {
            "zero2d": {"param_bytes_per_device": 26720,
                       "param_bytes_total": 213760},
            "zero3": {"param_bytes_per_device": 26720,
                      "param_bytes_total": 213760},
        },
        "zero": {"overlap_fraction": 1.0},
        "blocks": 3, "on_tpu": True,
    }
    block.update(over)
    return block


class TestLedgerZeroProbe:
    @pytest.fixture()
    def ledger_mod(self):
        return _load_script("perf_ledger")

    def test_schema_accepts_and_rejects(self, ledger_mod):
        assert ledger_mod._zero_probe_schema_problem(None) is None
        assert ledger_mod._zero_probe_schema_problem(
            _zero_probe_block()
        ) is None
        assert "component" in ledger_mod._zero_probe_schema_problem(
            _zero_probe_block(component="nope")
        )
        assert "zero3_ms" in ledger_mod._zero_probe_schema_problem(
            _zero_probe_block(zero3_ms=None)
        )
        assert "inconsistent" in ledger_mod._zero_probe_schema_problem(
            _zero_probe_block(speedup=9.0)
        )

    def test_carried_and_rendered(self, tmp_path, ledger_mod):
        repo = str(tmp_path)
        with open(os.path.join(repo, "BASELINE.json"), "w") as f:
            json.dump({"metric": "m"}, f)
        parsed = {"metric": "tokens/sec/chip GPT-2-124M train step",
                  "value": 50000.0, "vs_baseline": 1.0,
                  "zero_probe": _zero_probe_block()}
        payload = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": parsed}
        with open(os.path.join(repo, "BENCH_r01.json"), "w") as f:
            json.dump(payload, f)
        ledger = ledger_mod.build_ledger(repo)
        assert ledger["ok"], ledger["problems"]
        assert ledger["rounds"][0]["zero_probe"]["speedup"] == 1.1147
        out = io.StringIO()
        ledger_mod.render_table(ledger, out=out)
        text = out.getvalue()
        assert "zero_probe:" in text
        assert "speedup 1.11x" in text
        assert "overlap 100%" in text

    def test_malformed_block_is_a_problem(self, tmp_path, ledger_mod):
        repo = str(tmp_path)
        with open(os.path.join(repo, "BASELINE.json"), "w") as f:
            json.dump({"metric": "m"}, f)
        parsed = {"metric": "m", "value": 1.0, "vs_baseline": 1.0,
                  "zero_probe": {"component": "zero_probe"}}
        payload = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": parsed}
        with open(os.path.join(repo, "BENCH_r01.json"), "w") as f:
            json.dump(payload, f)
        ledger = ledger_mod.build_ledger(repo)
        assert not ledger["ok"]
        assert any("zero_probe" in p for p in ledger["problems"])
        assert ledger["rounds"][0]["zero_probe"] is None


# ----------------------------------------------------------------------
# resilience_probe: saved param-sharding mode surfaces
# ----------------------------------------------------------------------


class TestResilienceProbeLayout:
    def test_state_layout_reported(self, tmp_path):
        import pickle

        mod = _load_script("resilience_probe")
        d = tmp_path / "t_partial"
        d.mkdir()
        (d / ".committed").write_text("")
        with open(d / "smp_config.pt", "wb") as fh:
            pickle.dump({
                "pipeline_parallel_degree": 1, "tensor_parallel_degree": 1,
                "sharded_data_parallel_degree": 1,
                "sharded_params": "zero3", "shard_optimizer_state": False,
                "microbatches": 2, "num_processes": 1,
            }, fh)
        info = mod.inspect_partial_dir(str(d))
        assert info["topology"]["sharded_params"] == "zero3"
        assert info["state_layout"]["zero3"] is True
        assert info["state_layout"]["zero2d"] is False
