"""In-job failure recovery: heartbeat detector classification, chaos
faults that drive it, host-collective deadlines, checkpoint agreement,
and the recovery-report tooling.

The detector units run against a fake bus with a manual clock — every
boundary (missed-beat budget, wedged-vs-slow, flap suppression) is a pure
function of (beats, steps, time), so no processes or sleeps are needed.
The real 2-process SIGKILL E2E lives in tests/test_multiprocess.py; the
real dead-link plumbing in tests/test_native.py.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.resilience.supervisor import (
    DEAD,
    HEARTBEAT_TX,
    PREEMPTED,
    WEDGED,
    FailureDetector,
    Supervisor,
    latest_committed_checkpoint,
    supervisor,
)
from smdistributed_modelparallel_tpu.resilience.preemption import (
    PREEMPT_NOTICE_TX,
)
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPCollectiveTimeout,
    SMPRecoveryError,
)
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeBus:
    def __init__(self, world=2, rank=0):
        self.world, self.rank = world, rank
        self.sent = []        # (dest, payload, tx) of send_raw
        self.inbox = {}       # (src, tx) -> [payload, ...]
        self.down = set()
        self.send_rc = {}     # dest -> forced send_raw rc

    def send_raw(self, dest, payload, tx):
        self.sent.append((dest, payload, tx))
        return self.send_rc.get(dest, 0)

    def drain_bytes(self, src, tx, limit=256):
        return self.inbox.pop((src, tx), [])

    def poll(self, src, tx):
        return bool(self.inbox.get((src, tx)))

    def peer_down(self, peer):
        return peer in self.down

    def beat(self, src, seq, step):
        self.inbox.setdefault((src, HEARTBEAT_TX), []).append(
            b"%d:%d" % (seq, step)
        )


def make_detector(bus, my_step=0, interval=0.1, budget=5, wedge=1.0):
    steps = {"n": my_step}
    det = FailureDetector(
        bus, my_step=lambda: steps["n"], interval=interval, budget=budget,
        wedge_s=wedge, clock=lambda: 0.0,
    )
    det._steps = steps  # test hook to advance "my" step edge
    return det


class TestDetectorClassification:
    def test_healthy_peer_stays_healthy(self):
        bus = FakeBus()
        det = make_detector(bus)
        for i in range(10):
            bus.beat(1, i, i)
            det._tick(now=i * 0.1)
        assert det.failures() == {}
        assert det.peers[1].beats == 10

    def test_missed_beat_budget_exhausted_is_dead(self):
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, budget=5)
        bus.beat(1, 0, 0)
        det._tick(now=0.0)
        det._tick(now=0.4)   # 0.4 < 0.5 budget: still healthy
        assert det.failures() == {}
        det._tick(now=0.6)   # budget exhausted
        assert det.failures() == {1: DEAD}

    def test_flap_below_budget_never_classifies(self):
        """heartbeat_drop-style gap shorter than the budget: no event."""
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, budget=5)
        bus.beat(1, 0, 0)
        det._tick(now=0.0)
        det._tick(now=0.2)   # two beats dropped
        det._tick(now=0.45)  # still inside the budget
        assert det.failures() == {}
        bus.beat(1, 1, 1)
        det._tick(now=0.5)   # beats resumed before exhaustion
        det._tick(now=0.9)
        assert det.failures() == {}

    def test_dead_then_revived_is_flap_cleared(self):
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, budget=5)
        bus.beat(1, 0, 0)
        det._tick(now=0.0)
        det._tick(now=1.0)
        assert det.failures() == {1: DEAD}
        assert det.marked_count == 1
        bus.beat(1, 1, 1)
        det._tick(now=1.1)   # fresh life BEFORE recovery began: cleared
        assert det.failures() == {}
        assert det.marked_count == 0  # step-edge short-circuit re-engages

    def test_no_flap_clear_once_recovering(self):
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, budget=5)
        bus.beat(1, 0, 0)
        det._tick(now=0.0)
        det._tick(now=1.0)
        assert det.failures() == {1: DEAD}
        det.recovering = True
        bus.beat(1, 1, 1)
        det._tick(now=1.1)
        assert det.failures() == {1: DEAD}  # stays excluded

    def test_link_dead_classifies_immediately(self):
        bus = FakeBus()
        det = make_detector(bus)
        bus.send_rc[1] = -2  # sender thread gave up
        det._tick(now=0.0)
        assert det.failures() == {1: DEAD}

    def test_recv_side_down_classifies_immediately(self):
        bus = FakeBus()
        det = make_detector(bus)
        bus.down.add(1)
        det._tick(now=0.0)
        assert det.failures() == {1: DEAD}

    def test_wedged_step_edge_stalls_past_timeout(self):
        bus = FakeBus()
        det = make_detector(bus, my_step=0, interval=0.1, wedge=1.0)
        t = 0.0
        for i in range(25):  # beats keep arriving, step stuck at 3
            bus.beat(1, i, 3)
            det._steps["n"] = 3 + i  # our own edge races ahead
            det._tick(now=t)
            t += 0.1
        assert det.failures() == {1: WEDGED}

    def test_slow_but_advancing_is_not_wedged(self):
        """Wedged-vs-slow boundary: the edge moves (slowly) within the
        timeout, so the peer is slow, not stuck."""
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, wedge=1.0)
        t = 0.0
        for i in range(25):
            bus.beat(1, i, i // 8)  # advances every 0.8s < 1.0s timeout
            det._steps["n"] = i
            det._tick(now=t)
            t += 0.1
        assert det.failures() == {}

    def test_globally_idle_world_wedges_nobody(self):
        """Our own edge never moved past the peer's: watchdog territory,
        not a peer failure."""
        bus = FakeBus()
        det = make_detector(bus, my_step=3, interval=0.1, wedge=1.0)
        t = 0.0
        for i in range(25):
            bus.beat(1, i, 3)
            det._tick(now=t)
            t += 0.1
        assert det.failures() == {}

    def test_preempt_notice_classifies_preempted_not_failed(self):
        bus = FakeBus()
        det = make_detector(bus)
        bus.inbox[(1, PREEMPT_NOTICE_TX)] = [b"preempt"]
        det._tick(now=0.0)
        assert det.peers[1].kind == PREEMPTED
        # Not a recovery target, and the notice is left for the
        # preemption listener to consume.
        assert det.failures() == {}
        assert bus.inbox[(1, PREEMPT_NOTICE_TX)] == [b"preempt"]

    def test_heartbeats_ride_reserved_tx(self):
        bus = FakeBus()
        det = make_detector(bus)
        det._tick(now=0.0)
        assert bus.sent and all(tx == HEARTBEAT_TX for _, _, tx in bus.sent)
        seq, _, step = bus.sent[0][1].partition(b":")
        assert int(seq) == 1 and int(step) == 0

    def test_force_dead_marks_only_healthy_peers(self):
        bus = FakeBus()
        det = make_detector(bus)
        det.force_dead(1, why="caller evidence")
        assert det.failures() == {1: DEAD}
        det.force_dead(1, why="again")  # no double-marking
        assert det.failures() == {1: DEAD}


class TestChaosFaults:
    def setup_method(self):
        os.environ.pop("SMP_CHAOS", None)
        chaos.reset()

    teardown_method = setup_method

    def test_kill_rule_delivers_sigkill(self, monkeypatch):
        import signal

        calls = []
        monkeypatch.setattr(os, "kill", lambda pid, sig: calls.append(sig))
        os.environ["SMP_CHAOS"] = "kill@step=2"
        chaos.on_step_edge(1)
        assert calls == []
        chaos.on_step_edge(2)
        assert calls == [signal.SIGKILL]
        chaos.on_step_edge(2)  # fires once
        assert calls == [signal.SIGKILL]

    def test_wedge_rule_hangs_dispatch(self):
        os.environ["SMP_CHAOS"] = "wedge@step=1:ms=80"
        t0 = time.monotonic()
        chaos.on_step_dispatch(0)
        assert time.monotonic() - t0 < 0.05  # wrong step: no hang
        t0 = time.monotonic()
        chaos.on_step_dispatch(1)
        assert time.monotonic() - t0 >= 0.08
        t0 = time.monotonic()
        chaos.on_step_dispatch(1)  # fires once
        assert time.monotonic() - t0 < 0.05

    def test_heartbeat_drop_drops_count_beats(self):
        os.environ["SMP_CHAOS"] = "heartbeat_drop@rank=0:count=3"
        drops = [chaos.on_heartbeat(1) for _ in range(5)]
        assert drops == [True, True, True, False, False]

    def test_heartbeat_drop_other_rank_is_noop(self):
        os.environ["SMP_CHAOS"] = "heartbeat_drop@rank=7:count=3"
        assert chaos.on_heartbeat(1) is False

    def test_detector_integration_drop_below_budget_no_false_positive(self):
        """The detector sends through the chaos seam: a drop burst below
        the miss budget must not classify the peer dead."""
        os.environ["SMP_CHAOS"] = "heartbeat_drop@rank=0:count=2"
        bus = FakeBus()
        det = make_detector(bus, interval=0.1, budget=5)
        for i in range(6):
            bus.beat(1, i, i)
            det._tick(now=i * 0.1)
        assert len(bus.sent) == 4  # 2 of 6 beats dropped
        assert det.failures() == {}

    def test_injections_counted(self):
        os.environ["SMP_CHAOS"] = "heartbeat_drop@rank=0:count=1"
        chaos.on_heartbeat(1)
        rep = telemetry.report()["metrics"]["smp_chaos_injected_total"]
        kinds = {
            s["labels"].get("fault"): s["value"] for s in rep["series"]
        }
        assert kinds.get("heartbeat_drop", 0) >= 1


class TestCollectiveTimeout:
    def test_int_recv_times_out_typed(self, monkeypatch):
        from smdistributed_modelparallel_tpu.backend.collectives import (
            CollectiveCommunicator,
        )

        class NeverBus:
            def recv_bytes(self, src, tx, timeout_ms=-1):
                assert timeout_ms == 100  # the env deadline, not -1
                raise TimeoutError("nothing")

        comm = CollectiveCommunicator()
        monkeypatch.setattr(comm, "_get_bus", lambda what: NeverBus())
        monkeypatch.setenv("SMP_COLLECTIVE_TIMEOUT", "0.1")
        with pytest.raises(SMPCollectiveTimeout) as ei:
            comm._int_recv(1, group="TP_GROUP", phase="allgather")
        assert ei.value.group == "TP_GROUP"
        assert ei.value.phase == "allgather"
        assert ei.value.last_seq >= 0

    def test_unset_env_keeps_unbounded_wait(self, monkeypatch):
        from smdistributed_modelparallel_tpu.backend.collectives import (
            CollectiveCommunicator,
        )

        class EchoBus:
            def recv_bytes(self, src, tx, timeout_ms=-1):
                assert timeout_ms == -1
                import pickle

                return pickle.dumps("ok")

        comm = CollectiveCommunicator()
        monkeypatch.setattr(comm, "_get_bus", lambda what: EchoBus())
        monkeypatch.delenv("SMP_COLLECTIVE_TIMEOUT", raising=False)
        out, _ = comm._int_recv(1, group="TP_GROUP")
        assert out == "ok"

    def test_barrier_deadline_is_typed(self, monkeypatch):
        import jax

        from smdistributed_modelparallel_tpu.backend import collectives

        comm = collectives.CollectiveCommunicator()

        class SlowBus:
            def barrier(self, ranks, timeout_ms=600000):
                time.sleep(timeout_ms / 1000.0)
                raise OSError("bus barrier over [0, 1] failed")

        monkeypatch.setattr(comm, "_get_bus", lambda what: SlowBus())
        monkeypatch.setattr(
            comm, "group_processes", lambda group=None: [0, 1]
        )
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setenv("SMP_COLLECTIVE_TIMEOUT", "0.1")
        from smdistributed_modelparallel_tpu.backend.collectives import (
            CommGroup,
        )

        with pytest.raises(SMPCollectiveTimeout) as ei:
            comm.barrier(group=CommGroup.TP_GROUP)
        assert ei.value.phase == "barrier"
        assert ei.value.group == "TP_GROUP"


class TestSupervisorOffIsFree:
    def test_off_by_default_no_thread_no_traffic(self, monkeypatch):
        monkeypatch.delenv("SMP_SUPERVISOR", raising=False)
        sup = Supervisor()
        assert sup.start() is False
        assert sup.active is False
        assert sup.detector is None

    def test_step_seam_is_one_attribute_test(self):
        """step.py guards the edge hook with `supervisor.active` — when
        off, on_step_edge is never entered."""
        src = open(os.path.join(
            _REPO, "smdistributed_modelparallel_tpu", "step.py"
        )).read()
        assert "if supervisor.active:" in src

    def test_recover_without_detector_reraises(self):
        sup = Supervisor()
        err = ValueError("boom")
        with pytest.raises(ValueError):
            sup.recover(error=err)
        with pytest.raises(SMPRecoveryError):
            sup.recover()


class TestCheckpointAgreement:
    def _mk_ckpt(self, root, tag, step, committed=True):
        import pickle

        d = os.path.join(root, f"{tag}_partial")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "smp_config.pt"), "wb") as fh:
            pickle.dump({"step_count": step}, fh)
        if committed:
            with open(os.path.join(d, ".committed"), "w") as fh:
                fh.write(tag)

    def test_latest_committed_prefers_newest_pointer(self, tmp_path):
        root = str(tmp_path)
        self._mk_ckpt(root, "a", 5)
        self._mk_ckpt(root, "b", 7)
        with open(os.path.join(root, "newest"), "w") as fh:
            fh.write("a")
        assert latest_committed_checkpoint(root) == ("a", 5)

    def test_latest_committed_falls_back_to_highest_step(self, tmp_path):
        root = str(tmp_path)
        self._mk_ckpt(root, "a", 5)
        self._mk_ckpt(root, "b", 7)
        self._mk_ckpt(root, "c", 9, committed=False)  # interrupted: skip
        assert latest_committed_checkpoint(root) == ("b", 7)

    def test_latest_committed_tag_parse_fallback(self, tmp_path):
        import pickle

        root = str(tmp_path)
        d = os.path.join(root, "step_12_partial")
        os.makedirs(d)
        with open(os.path.join(d, "smp_config.pt"), "wb") as fh:
            pickle.dump({}, fh)  # no step_count stamp (old checkpoint)
        with open(os.path.join(d, ".committed"), "w") as fh:
            fh.write("step_12")
        assert latest_committed_checkpoint(root) == ("step_12", 12)

    def test_latest_committed_empty(self, tmp_path):
        assert latest_committed_checkpoint(str(tmp_path)) is None
        assert latest_committed_checkpoint(None) is None

    def test_agreement_takes_weakest_report(self):
        sup = Supervisor()
        infos = {
            0: {"ckpt": ["step_7", 7]},
            2: {"ckpt": ["step_5", 5]},
        }
        assert sup._agree_checkpoint(infos, [0, 2]) == ("step_5", 5)

    def test_agreement_requires_every_survivor(self):
        sup = Supervisor()
        sup._recover_ckpt_path = "/nonexistent"
        infos = {0: {"ckpt": ["step_7", 7]}, 2: {"ckpt": None}}
        with pytest.raises(SMPRecoveryError):
            sup._agree_checkpoint(infos, [0, 2])


class RendezvousBus(FakeBus):
    """FakeBus + the barrier/exchange surface the rendezvous uses."""

    def __init__(self, world=3, rank=0):
        super().__init__(world=world, rank=rank)
        self.barrier_script = []   # per-call: None=ok, exc=raise
        self.barriers = []
        self.after_barrier = []    # (src, tx, obj) delivered post-barrier

    def barrier(self, ranks, timeout_ms=600000):
        self.barriers.append(list(ranks))
        if self.barrier_script:
            exc = self.barrier_script.pop(0)
            if exc is not None:
                raise exc
        # Peers' exchange frames land AFTER the barrier in the real
        # protocol (they are sent post-barrier) — pre-loaded frames would
        # be wiped by the rendezvous's stale-frame drain.
        for src, tx, obj in self.after_barrier:
            self.put(src, tx, obj)

    def send_bytes(self, dest, payload, tx):
        self.sent.append((dest, payload, tx))

    def recv_bytes(self, src, tx, timeout_ms=-1):
        q = self.inbox.get((src, tx))
        if q:
            return q.pop(0)
        raise TimeoutError(f"nothing from {src}")

    def put(self, src, tx, obj):
        self.inbox.setdefault((src, tx), []).append(
            json.dumps(obj).encode()
        )


class TestRendezvous:
    def _sup(self, tmp_path):
        sup = Supervisor()
        sup._recover_ckpt_path = str(tmp_path)
        return sup

    def test_exchange_converges(self, tmp_path):
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            RECOVERY_TX,
        )

        sup = self._sup(tmp_path)
        bus = RendezvousBus(world=3, rank=0)
        bus.after_barrier = [(2, RECOVERY_TX, {
            "rank": 2, "failed": [1], "step": 4, "ckpt": ["step_3", 3],
        })]
        survivors, infos = sup._rendezvous(bus, [0, 2], {1: DEAD}, 5.0)
        assert survivors == [0, 2]
        assert set(infos) == {0, 2}
        assert "coord" in infos[0]  # me == min survivor picks the endpoint

    def test_survivor_dying_at_barrier_is_dropped(self, tmp_path):
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPPeerLost,
        )

        sup = self._sup(tmp_path)
        bus = RendezvousBus(world=3, rank=0)
        bus.barrier_script = [SMPPeerLost(2)]
        failures = {1: DEAD}
        survivors, infos = sup._rendezvous(bus, [0, 2], failures, 5.0)
        assert survivors == [0]
        assert failures == {1: DEAD, 2: DEAD}
        assert 0 in infos  # solo fallback still reports a view

    def test_survivor_dying_before_info_is_dropped(self, tmp_path):
        """The exchange recv failing (timeout / peer lost) drops that
        peer and retries instead of aborting the whole recovery — and
        never leaves the return value unbound."""
        sup = self._sup(tmp_path)
        bus = RendezvousBus(world=3, rank=0)
        # Barrier always passes; peer 2's info never arrives.
        survivors, infos = sup._rendezvous(bus, [0, 2], {1: DEAD}, 5.0)
        assert survivors == [0]
        assert 0 in infos

    def test_self_in_failed_union_raises_evicted(self, tmp_path):
        from smdistributed_modelparallel_tpu.resilience.supervisor import (
            RECOVERY_TX,
        )
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPEvicted,
        )

        sup = self._sup(tmp_path)
        bus = RendezvousBus(world=3, rank=0)
        bus.after_barrier = [(2, RECOVERY_TX, {
            "rank": 2, "failed": [0, 1], "step": 4, "ckpt": ["step_3", 3],
        })]
        with pytest.raises(SMPEvicted):
            sup._rendezvous(bus, [0, 2], {1: DEAD}, 5.0)


class TestRecoverErrorHandling:
    def _armed(self):
        sup = Supervisor()
        bus = FakeBus(world=2, rank=0)
        sup.detector = FailureDetector(
            bus, my_step=lambda: 0, interval=0.01, budget=1, wedge_s=1.0,
            clock=time.monotonic,
        )
        return sup

    def test_non_peer_error_reraised_untouched(self, monkeypatch):
        """A step error with no peer failure behind it comes back as the
        ORIGINAL exception — no SMPRecoveryError wrapper, no abort dump —
        and the detector's flap-clearing is re-enabled afterwards."""
        monkeypatch.setenv("SMP_EMERGENCY_CKPT_PATH", "/nonexistent")
        sup = self._armed()
        boom = ValueError("oom-ish")
        aborts = []
        monkeypatch.setattr(sup, "_abort", lambda r: aborts.append(r))
        with pytest.raises(ValueError) as ei:
            sup.recover(error=boom)
        assert ei.value is boom
        assert aborts == []
        assert sup.detector.recovering is False
        assert sup._recovering is False

    def test_failed_recovery_reenables_flap_clearing(self, monkeypatch):
        monkeypatch.setenv("SMP_EMERGENCY_CKPT_PATH", "/nonexistent")
        sup = self._armed()
        sup.detector.force_dead(1, why="test")
        # ckpt root has no committed checkpoint -> rendezvous/agreement
        # fails -> SMPRecoveryError; the detector must come back usable.
        monkeypatch.setattr(sup, "_abort", lambda r: None)
        with pytest.raises(SMPRecoveryError):
            sup.recover()
        assert sup.detector is not None
        assert sup.detector.recovering is False


class TestRecoveryReportTool:
    def _write_dumps(self, root, with_abort=False, with_recovery=True):
        os.makedirs(root, exist_ok=True)
        tele = {
            "meta": {"rank": 0, "world": 2},
            "metrics": {
                "smp_failures_detected_total": {
                    "kind": "counter", "help": "", "series": [
                        {"labels": {"kind": "dead"}, "value": 1},
                    ],
                },
                "smp_recoveries_total": {
                    "kind": "counter", "help": "", "series": [
                        {"labels": {}, "value": 1 if with_recovery else 0},
                    ],
                },
            },
        }
        with open(os.path.join(root, "tm.json.rank0"), "w") as fh:
            json.dump(tele, fh)
        events = [
            {"kind": "meta", "rank": 0, "world": 2},
            {"kind": "supervisor", "event": "detect_dead", "peer": 1,
             "detail": "missed-beat budget", "wall_us": 1_000_000},
            {"kind": "supervisor", "event": "recover_begin", "peer": -1,
             "detail": "world=2", "wall_us": 2_000_000},
            {"kind": "supervisor", "event": "ckpt_agreed", "peer": -1,
             "detail": "tag=step_2 step=2", "wall_us": 2_100_000},
            {"kind": "supervisor", "event": "rendezvous_ok", "peer": -1,
             "detail": "survivors=[0]", "wall_us": 2_200_000},
            {"kind": "supervisor", "event": "resume_done", "peer": -1,
             "detail": "tag=step_2", "wall_us": 3_000_000},
        ]
        if with_recovery:
            events.append(
                {"kind": "supervisor", "event": "recovery_done", "peer": -1,
                 "detail": "mttr=4.200s detect=1.000 rendezvous=0.200 "
                           "reshard_load=2.000 first_step=1.000",
                 "wall_us": 4_000_000}
            )
        if with_abort:
            events.append(
                {"kind": "supervisor", "event": "abort", "peer": -1,
                 "detail": "no committed checkpoint", "wall_us": 5_000_000}
            )
        with open(os.path.join(root, "fr.jsonl.rank0"), "w") as fh:
            for ev in events:
                fh.write(json.dumps(ev) + "\n")

    def _run(self, *args):
        return subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "resilience_probe.py"),
             *args],
            capture_output=True, text=True, timeout=60,
        )

    def test_report_joins_dumps(self, tmp_path):
        root = str(tmp_path / "dumps")
        self._write_dumps(root)
        out = self._run(root, "--recovery", "--json")
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert rep["detections"] == {"dead": 1}
        assert rep["recoveries_total"] == 1
        assert len(rep["recoveries"]) == 1
        rec = rep["recoveries"][0]
        assert rec["mttr_s"] == pytest.approx(4.2)
        assert rec["phases"] == {
            "detect": 1.0, "rendezvous": 0.2,
            "reshard_load": 2.0, "first_step": 1.0,
        }
        assert rep["problems"] == []

    def test_check_gate_passes_clean(self, tmp_path):
        root = str(tmp_path / "dumps")
        self._write_dumps(root)
        out = self._run(root, "--recovery", "--check",
                        "--min-recoveries", "1")
        assert out.returncode == 0, out.stdout

    def test_check_gate_fails_on_abort(self, tmp_path):
        root = str(tmp_path / "dumps")
        self._write_dumps(root, with_abort=True)
        out = self._run(root, "--recovery", "--check")
        assert out.returncode == 2
        assert "abort" in out.stdout.lower()

    def test_check_gate_fails_on_count_mismatch(self, tmp_path):
        root = str(tmp_path / "dumps")
        self._write_dumps(root, with_recovery=False)
        # telemetry says 0 recoveries, ring has none either -> consistent;
        # min-recoveries makes it fail.
        out = self._run(root, "--recovery", "--check",
                        "--min-recoveries", "1")
        assert out.returncode == 2

    def test_check_gate_fails_on_slow_mttr(self, tmp_path):
        root = str(tmp_path / "dumps")
        self._write_dumps(root)
        out = self._run(root, "--recovery", "--check", "--max-mttr", "1")
        assert out.returncode == 2
        assert "exceeds" in out.stdout


class TestStepEdgeClosure:
    def test_pending_recovery_closes_at_first_step(self):
        sup = Supervisor()
        now = time.monotonic()
        sup._await_first_step = {
            "survivors": 1, "t_detect": now - 4.0,
            "t_resume_done": now - 1.0,
            "phases": {"detect": 1.0, "rendezvous": 0.5,
                       "reshard_load": 1.5},
        }
        sup.active = True
        sup.on_step_edge()
        assert sup._await_first_step is None
        assert sup.last_report is not None
        rep = telemetry.report()["metrics"]
        mttr = rep["smp_recovery_seconds"]["series"][0]["value"]
        assert 3.5 < mttr < 10.0
        phases = {
            s["labels"]["phase"]: s["value"]
            for s in rep["smp_recovery_phase_seconds"]["series"]
        }
        assert set(phases) == {
            "detect", "rendezvous", "reshard_load", "first_step"
        }
        assert phases["first_step"] >= 0.9
