"""PR-18 goodput ledger: exclusive-and-exhaustive wall-clock attribution
(the fake-clock sum-to-wall-clock invariant), the perf-regression
sentinel's change-point latch, the auto-forensics engine's cooldown /
cap rate limiting, the zero-cost-off contract, and the folds into the
watchdog dump, the time-series windows, the fleet windows, and the
slo_report / perf_ledger script gates.

Everything here is tier-1 host-only: ledgers are built with injected
fake clocks and fresh ``TelemetryRegistry`` instances, never the
process singletons.
"""

import json
import os
import sys

import pytest

from smdistributed_modelparallel_tpu.utils.goodput import (
    DEFAULT_FORENSICS_MAX,
    FORENSICS_PATH_ENV,
    GOODPUT_ENV,
    GOODPUT_MIN_ENV,
    PRODUCTIVE,
    REGRESSION_RATIO_ENV,
    STATES,
    ForensicsEngine,
    GoodputController,
    GoodputLedger,
    RegressionSentinel,
    classify_phase,
    goodput,
    goodput_enabled,
)
from smdistributed_modelparallel_tpu.utils.telemetry import (
    LATENCY_BUCKETS,
    TelemetryRegistry,
)

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

import perf_ledger  # noqa: E402
import slo_report  # noqa: E402


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


_GOODPUT_ENVS = (GOODPUT_ENV, GOODPUT_MIN_ENV, REGRESSION_RATIO_ENV,
                 FORENSICS_PATH_ENV)


@pytest.fixture
def clean_env(monkeypatch):
    for v in _GOODPUT_ENVS:
        monkeypatch.delenv(v, raising=False)
    return monkeypatch


def _ledger(clk=None, **kw):
    clk = clk if clk is not None else FakeClock()
    kw.setdefault("registry", TelemetryRegistry())
    kw.setdefault("min_goodput", 0)     # 0/None-able; 0 disables the gate
    kw.setdefault("regression_ratio", 0)
    led = GoodputLedger(clock=clk, wall=clk, **kw)
    return led, clk


def _counter(reg, name, **labels):
    fam = reg.report()["metrics"].get(name)
    for s in (fam or {}).get("series", []):
        if s["labels"] == labels:
            return s["value"]
    return None


# ----------------------------------------------------------------------
# The attribution state machine
# ----------------------------------------------------------------------


class TestLedgerInvariant:
    def test_sum_to_wall_clock_exact(self):
        """THE invariant: every second lands in exactly one state."""
        led, clk = _ledger()
        clk.t += 3.0                          # startup
        led.observe_phase("step_0/trace")
        clk.t += 2.0                          # trace
        led.observe_phase("compile/step_0")
        clk.t += 5.0                          # compile_fresh
        led.observe_phase("step_0")
        clk.t += 4.0                          # step
        with led.scope("ckpt_save"):
            clk.t += 7.0                      # ckpt_save
        clk.t += 1.0                          # back to step
        secs = led.seconds()
        assert sum(secs.values()) == pytest.approx(led.wall_seconds())
        assert secs["startup"] == pytest.approx(3.0)
        assert secs["trace"] == pytest.approx(2.0)
        assert secs["compile_fresh"] == pytest.approx(5.0)
        assert secs["step"] == pytest.approx(5.0)
        assert secs["ckpt_save"] == pytest.approx(7.0)
        assert led.goodput_fraction() == pytest.approx(5.0 / 22.0)
        assert set(secs) <= set(STATES)

    def test_invariant_holds_under_random_walk(self):
        import random

        rng = random.Random(18)
        led, clk = _ledger()
        phases = ["step_1/trace", "step_1", "compile/x", "barrier/y",
                  "init/mesh", "initialized", "unclassified/noise"]
        for _ in range(200):
            clk.t += rng.uniform(0.0, 3.0)
            op = rng.random()
            if op < 0.6:
                led.observe_phase(rng.choice(phases))
            elif op < 0.8:
                with led.scope(rng.choice(("ckpt_save", "data_wait",
                                           "preempt_drain"))):
                    clk.t += rng.uniform(0.0, 2.0)
            else:
                led.enter(rng.choice(("wedged", "recovery_first_step")))
        assert sum(led.seconds().values()) == pytest.approx(
            led.wall_seconds(), abs=1e-9
        )

    def test_scope_restores_enclosing_state(self):
        led, clk = _ledger()
        led.observe_phase("step_3")
        clk.t += 1.0
        with led.scope("ckpt_save"):
            clk.t += 2.0
        assert led.state == "step"

    def test_ambient_phase_under_scope_lands_at_base(self):
        """A phase observed while an explicit scope is open must not
        steal attribution from the scope — it retargets the BASE state
        the ledger returns to."""
        led, clk = _ledger()
        led.observe_phase("step_3")
        clk.t += 1.0
        with led.scope("preempt_drain"):
            clk.t += 4.0
            led.observe_phase("barrier/emergency")  # ambient, nested
            clk.t += 2.0
        secs = led.seconds()
        assert secs["preempt_drain"] == pytest.approx(6.0)
        assert led.state == "sync_wait"   # the retargeted base
        assert sum(secs.values()) == pytest.approx(led.wall_seconds())

    def test_nested_scopes(self):
        led, clk = _ledger()
        with led.scope("preempt_drain"):
            clk.t += 1.0
            with led.scope("ckpt_save"):
                clk.t += 2.0
            clk.t += 1.0
        secs = led.seconds()
        assert secs["preempt_drain"] == pytest.approx(2.0)
        assert secs["ckpt_save"] == pytest.approx(2.0)

    def test_mark_stalled_attributes_wedged(self):
        led, clk = _ledger()
        led.observe_phase("step_9")
        clk.t += 1.0
        led.mark_stalled("watchdog")
        clk.t += 30.0
        assert led.seconds()["wedged"] == pytest.approx(30.0)
        led.observe_phase("step_10")   # stall over: ambient phase resumes
        clk.t += 1.0
        assert led.state == "step"
        assert sum(led.seconds().values()) == pytest.approx(
            led.wall_seconds()
        )

    def test_note_compile_moves_disk_cache_seconds(self):
        led, clk = _ledger()
        led.observe_phase("compile/step_0")
        clk.t += 8.0
        led.observe_phase("step_0")
        led.note_compile("disk_cache", 6.0)
        secs = led.seconds()
        assert secs["compile_cache"] == pytest.approx(6.0)
        assert secs["compile_fresh"] == pytest.approx(2.0)
        assert sum(secs.values()) == pytest.approx(led.wall_seconds())

    def test_note_compile_clamps_to_accrued(self):
        led, clk = _ledger()
        led.observe_phase("compile/step_0")
        clk.t += 2.0
        led.note_compile("disk_cache", 100.0)
        secs = led.seconds()
        assert secs.get("compile_fresh", 0.0) == pytest.approx(0.0)
        assert secs["compile_cache"] == pytest.approx(2.0)
        assert sum(secs.values()) == pytest.approx(led.wall_seconds())

    def test_note_compile_fresh_is_noop(self):
        led, clk = _ledger()
        led.observe_phase("compile/step_0")
        clk.t += 2.0
        led.note_compile("fresh", 2.0)
        assert "compile_cache" not in led.seconds()

    def test_transitions_recorded(self):
        led, clk = _ledger()
        led.observe_phase("step_0/trace")
        clk.t += 1.0
        led.observe_phase("step_0")
        trans = led.transitions()
        assert [t["to"] for t in trans] == ["trace", "step"]
        snap = led.snapshot()
        assert snap["state"] == "step"
        assert snap["transitions"][-1]["to"] == "step"


class TestClassifyPhase:
    @pytest.mark.parametrize("phase,state", [
        ("step_12/trace", "trace"),
        ("step_12", "step"),
        ("run/loop", "step"),
        ("compile/step_12", "compile_fresh"),
        ("init/mesh", "startup"),
        ("startup", "startup"),
        ("initialized", "idle"),
        ("shutdown", "idle"),
        ("barrier/sync", "sync_wait"),
        ("recv_from/3", "sync_wait"),
        ("weird/other", None),
        ("", None),
        (None, None),
    ])
    def test_mapping(self, phase, state):
        assert classify_phase(phase) == state


# ----------------------------------------------------------------------
# Publishing: the counters the fleet merge sums
# ----------------------------------------------------------------------


class TestPublish:
    def test_counters_and_gauge(self):
        reg = TelemetryRegistry()
        led, clk = _ledger(registry=reg)
        led.observe_phase("step_0")
        clk.t += 9.0
        with led.scope("data_wait"):
            clk.t += 1.0
        frac = led.publish()
        assert frac == pytest.approx(0.9)
        assert _counter(reg, "smp_goodput_seconds_total") == pytest.approx(
            9.0
        )
        assert _counter(
            reg, "smp_badput_seconds_total", state="data_wait"
        ) == pytest.approx(1.0)
        # Second publish after more time: counters move by the DELTA
        # (stay monotonic), never re-add history.
        clk.t += 1.0
        led.publish()
        assert _counter(reg, "smp_goodput_seconds_total") == pytest.approx(
            10.0
        )

    def test_fleet_window_fold(self):
        """Two ranks' published counters merge into a rank-weighted
        fleet train_goodput + per-state badput breakdown."""
        from test_fleet import FakeClock as FleetClock, _plane, _snap

        regs = []
        for good, wait in [(9.0, 1.0), (4.0, 6.0)]:
            reg = TelemetryRegistry()
            led, clk = _ledger(registry=reg)
            led.observe_phase("step_0")
            clk.t += good
            with led.scope("data_wait"):
                clk.t += wait
            led.publish()
            regs.append(reg)

        fclk = FleetClock()
        plane = _plane(world=2, rank=0, registry=regs[0], clock=fclk)
        plane._ingest(1, _snap(regs[1], 1), fclk.t)
        fclk.t += 1.0
        window = plane.tick()
        assert window["train_goodput"] == pytest.approx(13.0 / 20.0)
        assert window["badput_by_state"]["data_wait"] == pytest.approx(7.0)
        assert set(window["goodput_by_rank"]["by_rank"]) == {"0", "1"}
        # The merged fraction also lands on the aggregator's gauge.
        assert _counter(
            regs[0], "smp_fleet_train_goodput"
        ) == pytest.approx(13.0 / 20.0)


# ----------------------------------------------------------------------
# The perf-regression sentinel
# ----------------------------------------------------------------------


def _observe_steps(reg, values):
    h = reg.histogram("smp_step_time_seconds", buckets=LATENCY_BUCKETS)
    for v in values:
        h.labels().observe(v)


class TestRegressionSentinel:
    def _sentinel(self, reg, ratio=1.5):
        return RegressionSentinel(registry=reg, ratio=ratio, min_count=8,
                                  baseline_windows=3)

    def test_fires_once_per_episode_and_clears(self):
        reg = TelemetryRegistry()
        s = self._sentinel(reg)
        _observe_steps(reg, [0.1] * 8)
        s.check(wall=0.0)                     # primes _prev, no window yet
        for i in range(3):                    # 3 baseline windows
            _observe_steps(reg, [0.1] * 8)
            assert s.check(wall=float(i)) == []
        # Regression: windowed p50 jumps ~20x past the 1.5x ratio.
        _observe_steps(reg, [2.0] * 8)
        fired = s.check(wall=10.0)
        assert len(fired) == 1
        assert fired[0]["source"] == "step_time"
        assert fired[0]["ratio"] > 1.5
        assert _counter(
            reg, "smp_perf_regression_total", source="step_time"
        ) == 1
        assert _counter(
            reg, "smp_perf_regression", source="step_time"
        ) == 1
        # Still slow: LATCHED, no second fire.
        _observe_steps(reg, [2.0] * 8)
        assert s.check(wall=11.0) == []
        assert _counter(
            reg, "smp_perf_regression_total", source="step_time"
        ) == 1
        # Recovery clears the latch (and the gauge)...
        _observe_steps(reg, [0.1] * 8)
        assert s.check(wall=12.0) == []
        assert _counter(
            reg, "smp_perf_regression", source="step_time"
        ) == 0
        # ...so a NEW episode fires again.
        for i in range(2):
            _observe_steps(reg, [0.1] * 8)
            s.check(wall=13.0 + i)
        _observe_steps(reg, [2.0] * 8)
        assert len(s.check(wall=20.0)) == 1

    def test_regressed_windows_do_not_poison_baseline(self):
        """A persistent regression must not normalize itself away: the
        degraded windows never extend the baseline."""
        reg = TelemetryRegistry()
        s = self._sentinel(reg)
        _observe_steps(reg, [0.1] * 8)
        s.check(wall=0.0)
        for i in range(3):
            _observe_steps(reg, [0.1] * 8)
            s.check(wall=float(i))
        baseline_before = list(s._baseline["step_time"])
        for i in range(5):
            _observe_steps(reg, [2.0] * 8)
            s.check(wall=10.0 + i)
        assert list(s._baseline["step_time"]) == baseline_before
        assert "step_time" in s.regressed

    def test_small_windows_skipped(self):
        reg = TelemetryRegistry()
        s = self._sentinel(reg)
        _observe_steps(reg, [0.1] * 8)
        s.check(wall=0.0)
        _observe_steps(reg, [0.1] * 3)     # < min_count: no window cut
        s.check(wall=1.0)
        assert list(s.windows["step_time"]) == []

    def test_disabled_without_ratio(self, clean_env):
        reg = TelemetryRegistry()
        s = RegressionSentinel(registry=reg)   # no env, no explicit ratio
        assert not s.enabled
        _observe_steps(reg, [0.1] * 8)
        assert s.check() == []


# ----------------------------------------------------------------------
# Auto-forensics: bounded, cooldown-rate-limited
# ----------------------------------------------------------------------


class TestForensics:
    def _engine(self, tmp_path, **kw):
        clk = kw.pop("clock", FakeClock())
        return ForensicsEngine(
            path=str(tmp_path / "forensics"), registry=TelemetryRegistry(),
            clock=clk, wall=clk, **kw
        ), clk

    def test_capture_writes_bundle(self, tmp_path):
        eng, clk = self._engine(tmp_path)
        bundle = eng.trigger("perf_regression", detail="p50 2x",
                             context={"goodput": {"state": "step"}})
        assert bundle is not None and os.path.isdir(bundle)
        assert "perf_regression" in os.path.basename(bundle)
        doc = json.load(open(os.path.join(bundle, "forensics.json")))
        assert doc["reason"] == "perf_regression"
        assert doc["goodput"] == {"state": "step"}
        assert doc["threads"]            # thread stacks captured
        assert os.path.exists(os.path.join(bundle, "flight_recorder.jsonl"))

    def test_cooldown_suppresses_then_allows(self, tmp_path):
        eng, clk = self._engine(tmp_path, cooldown=600.0)
        assert eng.trigger("a") is not None
        assert eng.trigger("b") is None            # inside cooldown
        clk.t += 599.0
        assert eng.trigger("c") is None            # still inside
        clk.t += 2.0
        assert eng.trigger("d") is not None        # cooldown elapsed
        reg = eng.registry
        assert _counter(reg, "smp_forensics_total",
                        outcome="captured") == 2
        assert _counter(reg, "smp_forensics_total",
                        outcome="suppressed") == 2

    def test_bundle_cap(self, tmp_path):
        eng, clk = self._engine(tmp_path, cooldown=0.0, max_bundles=3)
        captured = 0
        for i in range(10):
            clk.t += 1.0
            if eng.trigger(f"r{i}") is not None:
                captured += 1
        assert captured == 3 == DEFAULT_FORENSICS_MAX - 5
        assert len(eng.bundles) == 3

    def test_disabled_without_path(self, clean_env):
        eng = ForensicsEngine(path=None, registry=TelemetryRegistry())
        assert not eng.enabled
        assert eng.trigger("anything") is None

    def test_never_raises(self, tmp_path, monkeypatch):
        eng, clk = self._engine(tmp_path)
        monkeypatch.setattr(
            eng, "_capture",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
        )
        assert eng.trigger("a") is None


class TestLedgerClosedLoops:
    def test_min_goodput_triggers_forensics_once(self, tmp_path):
        clk = FakeClock()
        reg = TelemetryRegistry()
        eng = ForensicsEngine(path=str(tmp_path / "f"), registry=reg,
                              clock=clk, wall=clk, cooldown=0.0)
        led = GoodputLedger(registry=reg, clock=clk, wall=clk,
                            min_goodput=0.5, min_elapsed=60.0,
                            regression_ratio=0, forensics=eng)
        with led.scope("data_wait"):
            clk.t += 30.0
        led.tick()                 # below min, but < min_elapsed: holds
        assert not eng.bundles
        with led.scope("data_wait"):
            clk.t += 40.0
        led.tick()
        assert len(eng.bundles) == 1
        assert "goodput_min" in eng.bundles[0]
        led.tick()                 # fired once, stays fired
        assert len(eng.bundles) == 1

    def test_sentinel_fire_triggers_forensics_with_context(self, tmp_path):
        clk = FakeClock()
        reg = TelemetryRegistry()
        eng = ForensicsEngine(path=str(tmp_path / "f"), registry=reg,
                              clock=clk, wall=clk, cooldown=0.0)
        led = GoodputLedger(registry=reg, clock=clk, wall=clk,
                            min_goodput=0, regression_ratio=1.5,
                            forensics=eng)
        _observe_steps(reg, [0.1] * 8)
        led.tick()
        for _ in range(3):
            clk.t += 1.0
            _observe_steps(reg, [0.1] * 8)
            led.tick()
        clk.t += 1.0
        _observe_steps(reg, [2.0] * 8)
        led.tick()
        assert len(eng.bundles) == 1
        doc = json.load(
            open(os.path.join(eng.bundles[0], "forensics.json"))
        )
        assert doc["reason"] == "perf_regression"
        assert doc["goodput"]["state"]        # snapshot attached
        assert doc["sentinel"]["verdicts"]

    def test_bench_block_shape(self):
        led, clk = _ledger()
        led.observe_phase("step_0")
        clk.t += 5.0
        block = led.bench_block()
        assert perf_ledger._goodput_schema_problem(block) is None

    def test_maybe_tick_rate_limited(self):
        led, clk = _ledger(tick_seconds=5.0)
        led.observe_phase("step_0")
        clk.t += 1.0
        assert led.maybe_tick() is None       # < tick_seconds since t0
        clk.t += 5.0
        assert led.maybe_tick() is not None
        assert led.maybe_tick() is None       # immediately after: limited


# ----------------------------------------------------------------------
# Zero-cost-off + the controller lifecycle
# ----------------------------------------------------------------------


class TestController:
    def test_from_env_constructs_nothing_when_off(self, clean_env):
        assert not goodput_enabled()
        assert GoodputLedger.from_env() is None

    def test_dependent_knobs_arm_the_ledger(self, clean_env):
        for var, val in ((GOODPUT_MIN_ENV, "0.9"),
                         (REGRESSION_RATIO_ENV, "1.5"),
                         (FORENSICS_PATH_ENV, "/tmp/x")):
            clean_env.setenv(var, val)
            assert goodput_enabled()
            clean_env.delenv(var)
        clean_env.setenv(GOODPUT_ENV, "1")
        assert goodput_enabled()
        clean_env.setenv(GOODPUT_ENV, "off")
        assert not goodput_enabled()

    def test_disarmed_seams_are_noops(self, clean_env):
        ctl = GoodputController()
        assert ctl.ledger is None
        with ctl.scope("ckpt_save"):
            pass
        ctl.enter("wedged")
        ctl.on_step_edge(3)
        ctl.note_compile("disk_cache", 1.0)
        ctl.mark_stalled("x")
        assert ctl.trigger_forensics("r") is None
        assert ctl.snapshot() is None
        assert ctl.window_block() is None
        assert ctl.bench_block() is None

    def test_start_chains_phase_listener_and_stop_restores(self, clean_env):
        clean_env.setenv(GOODPUT_ENV, "1")
        reg = TelemetryRegistry()
        seen = []
        reg._phase_listener = seen.append     # the flight-recorder's slot
        ctl = GoodputController()
        led = ctl.start(registry=reg)
        assert led is not None
        assert ctl.start(registry=reg) is led    # idempotent
        reg.set_phase("step_4")
        assert seen == ["step_4"]                # prior listener still fed
        assert led.state == "step"
        ctl.stop()
        assert reg._phase_listener == seen.append   # prior listener back
        ctl.reset()
        assert ctl.ledger is None

    def test_watchdog_snapshot_helper(self, clean_env):
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            _goodput_snapshot,
        )

        assert _goodput_snapshot("stall") is None   # disarmed: absent
        clean_env.setenv(GOODPUT_ENV, "1")
        reg = TelemetryRegistry()
        ctl_prev = goodput.ledger
        try:
            goodput.ledger = GoodputLedger(
                registry=reg, min_goodput=0, regression_ratio=0,
                clock=FakeClock(), wall=FakeClock(),
            )
            snap = _goodput_snapshot("collective stuck")
            assert snap["state"] == "wedged"       # stall marked first
            assert "seconds" in snap and "transitions" in snap
        finally:
            goodput.ledger = ctl_prev


# ----------------------------------------------------------------------
# Script gates
# ----------------------------------------------------------------------


class TestScriptGates:
    def _fleet_feed(self, tmp_path, train_goodput):
        rec = {"kind": "fleet_window", "seq": 1, "t_wall": 1.0,
               "window_s": 1.0, "ranks": [0, 1],
               "slo": {"ok": True, "violations": {}}}
        if train_goodput is not None:
            rec["train_goodput"] = train_goodput
        p = tmp_path / "fleet.jsonl"
        p.write_text(json.dumps(rec) + "\n")
        return str(p)

    def test_min_train_goodput_pass_fail_absent(self, tmp_path, capsys):
        feed = self._fleet_feed(tmp_path, 0.95)
        assert slo_report.main(
            [feed, "--fleet", "--min-train-goodput", "0.9"]
        ) == 0
        assert slo_report.main(
            [feed, "--fleet", "--min-train-goodput", "0.99"]
        ) == 1
        bare = self._fleet_feed(tmp_path, None)
        assert slo_report.main(
            [bare, "--fleet", "--min-train-goodput", "0.9"]
        ) == 2
        # The gate is --fleet-scoped.
        assert slo_report.main(
            [feed, "--min-train-goodput", "0.9"]
        ) == 2
        capsys.readouterr()

    def test_min_train_goodput_combines_with_check(self, tmp_path, capsys):
        feed = self._fleet_feed(tmp_path, 0.5)
        assert slo_report.main(
            [feed, "--fleet", "--check", "--min-train-goodput", "0.9"]
        ) == 1
        capsys.readouterr()

    def test_perf_ledger_goodput_schema(self):
        good = {"fraction": 0.9, "wall_s": 100.0,
                "seconds": {"step": 90.0, "data_wait": 10.0},
                "sentinel": [], "forensics": []}
        assert perf_ledger._goodput_schema_problem(None) is None
        assert perf_ledger._goodput_schema_problem(good) is None
        bad = dict(good, fraction=1.5)
        assert "fraction" in perf_ledger._goodput_schema_problem(bad)
        leak = dict(good, seconds={"step": 50.0})
        assert "sum" in perf_ledger._goodput_schema_problem(leak)
        assert perf_ledger._goodput_schema_problem([1]) is not None
        assert perf_ledger._goodput_schema_problem(
            dict(good, sentinel="no")
        ) is not None


# ----------------------------------------------------------------------
# The time-series fold
# ----------------------------------------------------------------------


class TestTimeseriesFold:
    def test_window_carries_train_goodput(self, clean_env):
        from smdistributed_modelparallel_tpu.utils.timeseries import (
            MetricsTimeSeries,
        )

        reg = TelemetryRegistry()
        clk = FakeClock()
        led = GoodputLedger(registry=reg, clock=clk, wall=clk,
                            min_goodput=0, regression_ratio=0)
        prev = goodput.ledger
        goodput.ledger = led
        try:
            led.observe_phase("step_0")
            clk.t += 9.0
            with led.scope("data_wait"):
                clk.t += 1.0
            ts = MetricsTimeSeries(registry=reg, interval=1.0, path="",
                                   clock=FakeClock(), wall=FakeClock())
            ts._clock.t += 2.0
            ts.sample()
            window = ts.snapshots()[-1]
            assert window["train_goodput"] == pytest.approx(0.9)
            assert window["badput_seconds"]["data_wait"] == pytest.approx(
                1.0
            )
        finally:
            goodput.ledger = prev
