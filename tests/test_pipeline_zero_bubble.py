"""Zero-bubble (ZB-H1) pipeline schedule tests.

Covers: the (chunk, microbatch, pass) schedule builder's invariants via
the reusable property checker (``tests/schedule_checker.py``, run against
all three builders), the exact reduction of ZB-with-W-fused-into-B to the
interleaved schedule, the bubble bound (strictly below interleaved at the
same (pp, v, mb) and matching the measured occupancy gauge on the CPU
mesh — the PR-5-style acceptance gate), the W-queue/ring memory plan,
split-VJP numerical parity against the pp=1 baseline and the fill-drain
executor, the default-path byte-identity guard, and the ZB program's
replication guard (``smp.xray`` per-axis permute census + committed
golden fingerprint).
"""

import json
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.utils import hlo_audit
from smdistributed_modelparallel_tpu.parallel.memory import (
    zero_bubble_ring_plan,
)
from smdistributed_modelparallel_tpu.parallel.pipeline_1f1b import (
    build_1f1b_schedule,
    build_interleaved_1f1b_schedule,
    build_zero_bubble_schedule,
    schedule_occupancy,
    zero_bubble_phase_bounds,
    zero_bubble_theoretical_bubble,
)
from smdistributed_modelparallel_tpu.models.transformer_lm import TransformerLM
from tests.models import softmax_xent
from tests.schedule_checker import check_schedule

# The (pp, mb, v, window) sweep every builder's property check runs over.
SWEEP = [
    (2, 4, 3, 1), (2, 8, 4, 2), (2, 8, 4, 4), (4, 8, 8, 2),
    (3, 7, 6, 3), (2, 8, 2, 2), (4, 4, 2, 2), (2, 3, 1, 3),
    (1, 4, 2, 1), (3, 9, 6, 2), (4, 8, 2, 1), (2, 4, 3, 2),
]


class TestScheduleChecker:
    """Satellite: one dependency-order/no-deadlock/no-double-execution
    checker over (stage, tick) grids, run against all three builders."""

    @pytest.mark.parametrize("S,M,W,V", SWEEP)
    def test_plain_builder(self, S, M, W, V):
        fwd, bwd = build_1f1b_schedule(S, M, W)
        check_schedule(S, M, fwd, bwd, window=W)

    @pytest.mark.parametrize("S,M,W,V", SWEEP)
    def test_interleaved_builder(self, S, M, W, V):
        fk, fm, bk, bm = build_interleaved_1f1b_schedule(S, M, W, V)
        check_schedule(S, M, fm, bm, fwd_chunk=fk, bwd_chunk=bk,
                       virtual=V, window=W)

    @pytest.mark.parametrize("S,M,W,V", SWEEP)
    def test_zero_bubble_builder(self, S, M, W, V):
        fk, fm, bk, bm, wk, wm = build_zero_bubble_schedule(S, M, W, V)
        ticks = check_schedule(S, M, fm, bm, fwd_chunk=fk, bwd_chunk=bk,
                               wgt_mb=wm, wgt_chunk=wk, virtual=V, window=W)
        # One W per stage per tick is a real compute slot: every row of
        # the W grid has at most one entry per stage by construction;
        # additionally Ws are FIFO per stage (the executor's ring-slot
        # reuse proof relies on it).
        for s in range(S):
            w_rows = [(t, wm[t, s]) for t in range(wm.shape[0])
                      if wm[t, s] >= 0]
            assert len(w_rows) == V * M
        assert set(ticks) == {"F", "B", "W"}

    def test_checker_catches_violations(self):
        """The harness itself must fail on broken grids, or the sweep
        above proves nothing."""
        fwd, bwd = build_1f1b_schedule(2, 4, 3)
        # Double execution.
        broken = fwd.copy()
        t_busy = int(np.argwhere(broken[:, 0] >= 0)[0][0])
        t_idle = int(np.argwhere(broken[:, 0] < 0)[-1][0])
        broken[t_idle, 0] = broken[t_busy, 0]
        with pytest.raises(AssertionError, match="twice"):
            check_schedule(2, 4, broken, bwd)
        # Dependency order: backward before its own forward.
        early_b = bwd.copy()
        t_first = int(np.argwhere(bwd[:, 0] >= 0)[0][0])
        mb = early_b[t_first, 0]
        early_b[t_first, 0] = -1
        early_b[0, 0] = mb
        with pytest.raises(AssertionError):
            check_schedule(2, 4, fwd, early_b)

    @pytest.mark.parametrize("S,M,W,V", [
        (2, 4, 3, 1), (2, 8, 4, 2), (4, 8, 8, 2), (3, 7, 6, 3), (1, 4, 2, 2),
    ])
    def test_zb_with_w_fused_into_b_is_interleaved(self, S, M, W, V):
        """Satellite exact-reduction: drop the W grid (fuse W back into
        the B tick) and the ZB schedule IS the interleaved schedule
        tick-for-tick — the F/B sub-schedule never drifts."""
        fk, fm, bk, bm, wk, wm = build_zero_bubble_schedule(S, M, W, V)
        ik, im, jk, jm = build_interleaved_1f1b_schedule(S, M, W, V)
        n = im.shape[0]
        assert np.array_equal(fm[:n], im) and np.array_equal(fk[:n], ik)
        assert np.array_equal(bm[:n], jm) and np.array_equal(bk[:n], jk)
        # Trailing ticks (if any) exist only to drain the W queue.
        assert (fm[n:] < 0).all() and (bm[n:] < 0).all()
        assert (wm[n:] >= 0).any() or wm.shape[0] == n


class TestZeroBubbleBound:
    def test_bound_below_interleaved_everywhere(self):
        for S, M, V in [(2, 8, 1), (2, 8, 2), (4, 16, 2), (8, 32, 4)]:
            zb = zero_bubble_theoretical_bubble(S, M, V)
            inter = (S - 1) / (V * M + S - 1)
            assert zb < inter

    def test_acceptance_bound_pp2_mb8(self):
        """The tentpole numbers: ZB at (pp=2, mb=8) undercuts interleaved
        v=2's 1/17 bound, and the builder's occupancy over executed pass
        spans achieves the ZB formula exactly (what the executor gauge
        must then reproduce)."""
        inter_v2 = 1 / 17
        assert zero_bubble_theoretical_bubble(2, 8, 2) == pytest.approx(1 / 25)
        assert zero_bubble_theoretical_bubble(2, 8, 2) < inter_v2
        for V, want in ((1, 1 / 13), (2, 1 / 25)):
            fk, fm, bk, bm, wk, wm = build_zero_bubble_schedule(2, 8, 4, V)
            (fl, fh), (bl, bh), (wl, wh) = zero_bubble_phase_bounds(
                fm, bm, wm
            )
            busy, total = schedule_occupancy(
                fm, bm, fwd_ticks=fh - fl, bwd_ticks=bh - bl,
                wgt=wm, wgt_ticks=wh - wl,
            )
            assert busy == 3 * 2 * V * 8       # (chunk, mb, pass) units
            assert 1 - busy / total == pytest.approx(want)
            assert want == pytest.approx(
                zero_bubble_theoretical_bubble(2, 8, V)
            )

    def test_w_pass_packs_gapless_at_gate_config(self):
        """The packing policy's claim: the W span has zero idle sub-slots
        (every stage runs a W every tick of the span)."""
        for V in (1, 2):
            _, _, _, _, wk, wm = build_zero_bubble_schedule(2, 8, 4, V)
            (wl, wh) = zero_bubble_phase_bounds(wm, wm, wm)[2]
            assert (wm[wl:wh] >= 0).all()

    def test_phase_bounds(self):
        fk, fm, bk, bm, wk, wm = build_zero_bubble_schedule(2, 8, 4, 2)
        (fl, fh), (bl, bh), (wl, wh) = zero_bubble_phase_bounds(fm, bm, wm)
        assert fl == 0 < bl <= wl
        assert fh < bh <= wh == fm.shape[0]
        assert (bm[:bl] < 0).all() and (fm[fh:] < 0).all()
        assert (wm[:wl] < 0).all()


class TestRingPlan:
    """Satellite: the W-queue ring is accounted in the memory planner."""

    @pytest.mark.parametrize("S,M,W,V", SWEEP)
    def test_plan_bounds_alive_depth(self, S, M, W, V):
        sched = build_zero_bubble_schedule(S, M, W, V)
        plan = zero_bubble_ring_plan(*sched, num_stages=S, virtual=V,
                                     window=W)
        assert plan["ring_slots"] >= W + 1
        assert plan["ring_slots"] >= plan["stash_alive_peak"]
        assert plan["w_queue_peak"] >= 1
        assert plan["extra_ring_slots"] == plan["ring_slots"] - (W + 1)

    def test_default_window_fits_existing_ring(self):
        """ZB-H1's same-activation-memory claim at the default window
        (pp+2): the deferred W queue fits inside the window+1 ring the
        fused executors already allocate."""
        for S, M, V in [(2, 8, 1), (2, 8, 2), (4, 8, 2)]:
            W = min(S + 2, M)
            sched = build_zero_bubble_schedule(S, M, W, V)
            plan = zero_bubble_ring_plan(*sched, num_stages=S, virtual=V,
                                         window=W)
            assert plan["extra_ring_slots"] == 0, plan


class TestHealthTagUnits:
    def test_add_stage_stats_pass_suffix(self):
        """Stage tags gain the pass coordinate (unit level — the
        compiled-trip path is covered in TestZeroBubbleParity)."""
        from smdistributed_modelparallel_tpu.utils import health

        hc = health.HealthCollector("cheap")
        bad = jnp.zeros((2, 1), jnp.float32)
        first = jnp.full((2, 1), -1.0, jnp.float32)
        chunk_ids = np.array([[0], [1]])
        hc.add_stage_stats("zb", bad, bad, first, chunk_ids=chunk_ids,
                           pass_name="bwd_input")
        names = [n for (n, _, _, _) in hc.entries]
        assert names == ["pp/zb/stage0/chunk0/bwd_input",
                         "pp/zb/stage1/chunk1/bwd_input"]
        # No pass -> unchanged tag shape (the fused executors' format).
        hc.entries.clear()
        hc.add_stage_stats("1f1b", bad[:, 0], bad[:, 0], first[:, 0])
        assert [n for (n, _, _, _) in hc.entries] == [
            "pp/1f1b/stage0", "pp/1f1b/stage1",
        ]


class TestConfig:
    def test_zero_bubble_knob_accepted(self):
        cfg = smp.ModelParallelConfig({"pipeline": "zero_bubble"})
        assert cfg.pipeline == "zero_bubble"

    def test_virtual_composes_with_zero_bubble(self):
        cfg = smp.ModelParallelConfig({
            "pipeline": "zero_bubble", "virtual_pipeline_degree": 2,
        })
        assert cfg.virtual_pipeline_degree == 2

    def test_virtual_still_rejected_with_simple(self):
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            ConfigError,
        )

        with pytest.raises(ConfigError):
            smp.ModelParallelConfig({
                "pipeline": "simple", "virtual_pipeline_degree": 2,
            })


# ----------------------------------------------------------------------
# Executor tests (compiled; heavier cases are tiered slow in conftest)
# ----------------------------------------------------------------------


def _train(cfg, steps=2, n_layers=4, batch=8, step_fn=None):
    smp.reset()
    smp.init(cfg)
    module = TransformerLM(
        vocab_size=32, max_len=12, d_model=16, n_layers=n_layers, n_heads=2,
    )
    model = smp.DistributedModel(module)
    optimizer = smp.DistributedOptimizer(optax.sgd(0.1), model)
    ids = jax.random.randint(jax.random.key(0), (batch, 12), 0, 32)

    if step_fn is None:
        @smp.step
        def train_step(model, batch):
            logits = model(batch)
            loss = jnp.mean(softmax_xent(logits[:, :-1], batch[:, 1:]))
            model.backward(loss)
            return loss
    else:
        train_step = step_fn

    losses, grads = [], None
    for i in range(steps):
        out = train_step(model, ids)
        if i == 0:
            grads = jax.device_get(model.grads)
        losses.append(float(out.reduce_mean()))
        optimizer.step()
    return losses, grads, train_step


def _zb_gauges():
    from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

    metrics = telemetry.report()["metrics"]

    def one(name, **want):
        want.setdefault("schedule", "zb")
        for s in metrics.get(name, {}).get("series", []):
            if all(s.get("labels", {}).get(k) == v for k, v in want.items()):
                return s["value"]
        return None

    return one


class TestZeroBubbleAcceptance:
    def test_gate_pp2_mb8_v2_measured_matches_theoretical(self):
        """The PR-5-style acceptance gate on the CPU mesh: at
        (pp=2, mb=8, v=2) the compiled ZB program's occupancy gauge
        equals the ZB bound 1/25 — strictly below interleaved v=2's 1/17
        — with per-pass executed-span gauges and the W-queue accounting
        alongside; and losses match the pp=1 baseline."""
        zb, zb_grads, step_fn = _train({
            "pipeline_parallel_degree": 2, "microbatches": 8, "ddp": True,
            "pipeline": "zero_bubble", "virtual_pipeline_degree": 2,
        })
        one = _zb_gauges()
        measured = one("smp_pipeline_bubble_fraction")
        theoretical = one("smp_pipeline_bubble_fraction_theoretical")
        assert theoretical == pytest.approx(1 / 25)
        assert theoretical < 1 / 17          # interleaved v=2's bound
        assert measured == pytest.approx(theoretical)
        assert one("smp_pipeline_virtual_stages") == 2.0
        # Per-pass executed tick spans (satellite: phase gauge gains the
        # pass label): 17 F ticks, 17 B ticks, 16 gapless W ticks.
        for pass_name, want in (("fwd", 17.0), ("bwd_input", 17.0),
                                ("bwd_weight", 16.0)):
            assert one("smp_pipeline_phase_ticks", phase="executed",
                       **{"pass": pass_name}) == want
        # W-queue ring accounting: fits the existing window+1 ring.
        assert one("smp_pipeline_ring_slots") == 5.0
        assert one("smp_pipeline_wqueue_peak") >= 1.0
        # The step cache keyed the schedule kind (cfg.pipeline is in the
        # pipe tuple): a zero_bubble entry exists.
        assert any(k[1][1] == "zero_bubble" for k in step_fn._cache)

        base, base_grads, _ = _train({"microbatches": 8})
        np.testing.assert_allclose(zb, base, rtol=1e-4, atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-5),
            zb_grads, base_grads,
        )

    def test_slot_events_carry_pass_coordinate(self):
        """Satellite: flight-recorder SLOT events gain (chunk, mb, pass).
        Schedule-build level (no compile): record the ZB schedule the way
        the executor does and check the dumped fields."""
        from smdistributed_modelparallel_tpu.utils.flight_recorder import (
            flight_recorder,
        )

        S, M, W, V = 2, 4, 3, 2
        fk, fm, bk, bm, wk, wm = build_zero_bubble_schedule(S, M, W, V)
        flight_recorder.clear()
        flight_recorder.record_schedule(
            "zb",
            ((t, s, d, int(m_arr[t, s]), int(k_arr[t, s]) * S + s, p)
             for t in range(fm.shape[0]) for s in range(S)
             for d, p, k_arr, m_arr in (("fwd", "F", fk, fm),
                                        ("bwd_input", "B", bk, bm),
                                        ("bwd_weight", "W", wk, wm))
             if m_arr[t, s] >= 0),
        )
        slots = [e for e in flight_recorder.snapshot()
                 if e["kind"] == "slot" and e.get("schedule") == "zb"]
        flight_recorder.clear()
        assert len(slots) == 3 * S * V * M
        assert {e["pass"] for e in slots} == {"F", "B", "W"}
        assert {e["direction"] for e in slots} == {
            "fwd", "bwd_input", "bwd_weight"
        }
        assert all("chunk" in e and "microbatch" in e for e in slots)
        by_pass = {p: sum(1 for e in slots if e["pass"] == p)
                   for p in "FBW"}
        assert by_pass == {"F": S * V * M, "B": S * V * M, "W": S * V * M}


class TestTraceFusePassSlots:
    def test_report_splits_b_and_w_ticks(self, tmp_path):
        """Satellite: fused traces and the straggler report distinguish
        B from W ticks via the SLOT pass coordinate."""
        import json
        import os
        import subprocess
        import sys

        script = os.path.join(
            os.path.dirname(__file__), "..", "scripts", "trace_fuse.py"
        )
        with open(tmp_path / "ring.jsonl.rank0", "w") as f:
            f.write(json.dumps({
                "kind": "meta", "rank": 0, "anchor_unix_us": 10 ** 12,
            }) + "\n")
            slots = [("fwd", "F"), ("bwd_input", "B"), ("bwd_input", "B"),
                     ("bwd_weight", "W")]
            for i, (d, p) in enumerate(slots):
                f.write(json.dumps({
                    "id": i, "ts_us": 1000.0 + i, "kind": "slot",
                    "schedule": "zb", "tick": i, "stage": 0,
                    "direction": d, "microbatch": 0, "chunk": 0,
                    "pass": p,
                }) + "\n")
        out = subprocess.run(
            [sys.executable, script, "-o", str(tmp_path / "fused.json"),
             str(tmp_path / "ring.jsonl.rank0")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "schedule slots by pass" in out.stdout
        assert re.search(r"zb\s+bwd_input\s+B\s+2", out.stdout), out.stdout
        assert re.search(r"zb\s+bwd_weight\s+W\s+1", out.stdout), out.stdout
        fused = json.load(open(tmp_path / "fused.json"))
        names = [e["name"] for e in fused["traceEvents"]
                 if e.get("tid") == "flight_recorder"]
        assert any(n.startswith("bwd_weight:") and n.endswith("/W")
                   for n in names), names


def _mk_step():
    @smp.step
    def train_step(model, batch):
        logits = model(batch)
        loss = jnp.mean(softmax_xent(logits[:, :-1], batch[:, 1:]))
        model.backward(loss)
        return loss

    return train_step


def _audit_of(step_fn):
    audit = hlo_audit.of_step_function(step_fn)
    if audit is None:
        pytest.skip("AOT step executable unavailable on this backend")
    return audit


class TestDefaultPathGuard:
    # The acceptance guard that the DEFAULT program is untouched — plain
    # `pipeline: "interleaved"` explicit-vs-unset byte-identity — lives
    # with the PR 5 HLO guards in test_pipeline_1f1b.py
    # (TestVirtualHLOGuard::test_v1_explicit_knob_is_byte_identical),
    # which now also compares the explicit schedule knob: one compile
    # covers both knobs against the same default program.

    def test_zb_keeps_pipeline_permutes(self):
        """The ZB program must stay pipeline-partitioned (stage-axis pins
        survive the split-VJP path) with bounded static permute growth:
        the per-tick transfer rolls stay one-per-direction and the W
        sub-step adds none (weight grads are stage-local), so the op
        count scales with the segment count, not with mb or v. Guarded
        through the smp.xray census (per-axis attributed counts, robust
        to HLO text-format drift) plus the committed golden fingerprint."""
        step_a, step_b = _mk_step(), _mk_step()
        _train({"pipeline_parallel_degree": 2, "microbatches": 4,
                "ddp": True}, steps=1, step_fn=step_a)
        audit_v1 = _audit_of(step_a)
        _train({"pipeline_parallel_degree": 2, "microbatches": 4,
                "ddp": True, "pipeline": "zero_bubble"},
               steps=1, step_fn=step_b)
        audit_zb = _audit_of(step_b)
        v1_count = audit_v1.collective_count("collective-permute", axis="pp")
        zb_count = audit_zb.collective_count("collective-permute", axis="pp")
        assert v1_count > 0
        assert zb_count > 0, "zero-bubble program lost its pipeline partitioning"
        assert zb_count <= 10 * v1_count
        assert audit_zb.findings == []
        # Semantic regression gate against the committed golden: the ZB
        # double-forward's remat fraction, per-axis census, and findings
        # must recompile to a clean diff.
        from tests.conftest import assert_matches_hlo_golden

        assert_matches_hlo_golden(audit_zb, "zero_bubble_pp2_mb4")


class TestZeroBubbleParity:
    """Satellite: loss/grad parity vs plain 1F1B and fill-drain at the
    existing tolerances (heavy multi-compile cases; tiered slow)."""

    def test_v1_matches_baseline_fill_drain_and_1f1b(self):
        base, base_grads, _ = _train({"microbatches": 4})
        simple, s_grads, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 4,
            "pipeline": "simple", "ddp": True,
        })
        plain, p_grads, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 4, "ddp": True,
        })
        zb, zb_grads, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 4,
            "pipeline": "zero_bubble", "ddp": True,
        })
        np.testing.assert_allclose(zb, base, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(zb, simple, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(zb, plain, rtol=1e-4, atol=1e-5)
        for got, want in ((zb_grads, base_grads), (zb_grads, s_grads),
                          (zb_grads, p_grads)):
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=1e-3, atol=1e-5
                ),
                got, want,
            )

    def test_uneven_layers_and_tight_window(self):
        """Uneven chunking (L=6 over pp2 x v2) and a tight in-flight
        window both preserve parity through the split-VJP path."""
        base, base_grads, _ = _train({"microbatches": 4}, n_layers=6)
        zb, zb_grads, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 4,
            "pipeline": "zero_bubble", "virtual_pipeline_degree": 2,
            "ddp": True,
        }, n_layers=6)
        np.testing.assert_allclose(zb, base, rtol=1e-4, atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-5),
            zb_grads, base_grads,
        )
        base8, _, _ = _train({"microbatches": 8})
        tight, _, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 8,
            "pipeline": "zero_bubble", "active_microbatches": 2,
            "ddp": True,
        })
        np.testing.assert_allclose(tight, base8, rtol=1e-4, atol=1e-5)

    def test_health_cheap_mode_parity(self, monkeypatch):
        """The in-graph sentinel rides the ZB tick carries (fwd AND
        bwd_input grids) without perturbing numerics."""
        monkeypatch.setenv("SMP_HEALTH_CHECK", "cheap")
        zb, zb_grads, _ = _train({
            "pipeline_parallel_degree": 2, "microbatches": 4,
            "pipeline": "zero_bubble", "ddp": True,
        })
        monkeypatch.delenv("SMP_HEALTH_CHECK")
        base, base_grads, _ = _train({"microbatches": 4})
        np.testing.assert_allclose(zb, base, rtol=1e-4, atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-5),
            zb_grads, base_grads,
        )

    def test_health_trip_tags_carry_pass_coordinate(self, monkeypatch):
        """Satellite: a tripped sentinel under the ZB schedule attributes
        to (stage, chunk, pass) — NaN params on stage 1 trip the forward
        sentinel there and the input-cotangent sentinel on the ranks the
        bad cotangent flows through."""
        from smdistributed_modelparallel_tpu.utils import health

        monkeypatch.setenv("SMP_HEALTH_CHECK", "cheap")
        smp.reset()
        smp.init({"pipeline_parallel_degree": 2, "microbatches": 2,
                  "ddp": True, "pipeline": "zero_bubble"})
        module = TransformerLM(
            vocab_size=32, max_len=12, d_model=16, n_layers=4, n_heads=2,
        )
        model = smp.DistributedModel(module)
        opt = smp.DistributedOptimizer(optax.sgd(0.1), model)
        ids = jax.random.randint(jax.random.key(0), (8, 12), 0, 32)

        @smp.step
        def train_step(model, batch):
            logits = model(batch)
            loss = jnp.mean(softmax_xent(logits[:, :-1], batch[:, 1:]))
            model.backward(loss)
            return loss

        train_step(model, ids)
        opt.step()
        params = model.params
        kern = params["layers"]["block"]["attn"]["qkv"]["kernel"]
        params["layers"]["block"]["attn"]["qkv"]["kernel"] = (
            kern.at[2].set(jnp.nan)
        )
        model.params = params
        train_step(model, ids)
        health.monitor.flush()

        assert len(health.monitor.trips) == 1
        tags = health.monitor.trips[0]["tags"]
        # Stage 1 owns layers 2-3 (chunk id == stage at v=1): its forward
        # output goes non-finite, tagged with the fwd pass coordinate.
        assert "pp/zb/stage1/chunk1/fwd" in tags
        assert "pp/zb/stage0/chunk0/fwd" not in tags
        # The backward-input sentinel catches the poisoned cotangents.
        assert any(t.startswith("pp/zb/") and t.endswith("/bwd_input")
                   for t in tags), tags
