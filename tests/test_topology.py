"""DeviceTopology / mesh tests: mesh axis order matches placement strategy,
Ranker and mesh agree on device placement, smp.init wiring."""

import numpy as np
import pytest

import jax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.config import ModelParallelConfig
from smdistributed_modelparallel_tpu.backend.topology import DeviceTopology
from smdistributed_modelparallel_tpu.utils.exceptions import DeviceCountError


def test_mesh_axis_order_cluster():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True}
    )
    topo = DeviceTopology(cfg)
    # cluster == DPT: D-block (rdp, ep, cp) first, then pp, then tp.
    assert topo.axis_names == ("rdp", "ep", "cp", "pp", "tp")
    assert topo.mesh.shape["pp"] == 2
    assert topo.mesh.shape["tp"] == 2
    assert topo.mesh.shape["rdp"] == 2


def test_mesh_axis_order_spread():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True,
         "placement_strategy": "spread"}
    )
    topo = DeviceTopology(cfg)
    # spread == TPD
    assert topo.axis_names == ("tp", "pp", "rdp", "ep", "cp")


def test_mesh_matches_ranker():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True}
    )
    topo = DeviceTopology(cfg)
    devices = list(jax.devices())
    flat_mesh = list(topo.mesh.devices.flat)
    # Mesh is laid out in placement order, so flat index == global rank and
    # the ranker's grid must match device ids.
    for rank in range(topo.size):
        assert flat_mesh[rank] == devices[rank]
        coords = topo.coords(rank)
        assert coords["pp"] == topo.ranker.get_pp_rank(rank)
        assert coords["tp"] == topo.ranker.get_tp_rank(rank)
        assert coords["rdp"] == topo.ranker.get_rdp_rank(rank)


def test_device_count_validation():
    cfg = ModelParallelConfig({"pipeline_parallel_degree": 3, "microbatches": 3})
    with pytest.raises(DeviceCountError):
        DeviceTopology(cfg)


def test_device_count_override():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "_device_count_override": 4}
    )
    topo = DeviceTopology(cfg, devices=list(jax.devices()))
    assert topo.size == 4
    assert topo.rdp_size == 2


def test_cp_carved_from_dp():
    cfg = ModelParallelConfig({"context_parallel_degree": 2, "ddp": True})
    topo = DeviceTopology(cfg)
    assert topo.cp_size == 2
    assert topo.rdp_size == 4
    assert topo.d_size == 8  # reference "D" dim includes cp/ep
    for rank in range(8):
        assert topo.coords(rank)["cp"] in (0, 1)


def test_smp_init_api():
    smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True})
    assert smp.is_initialized()
    assert smp.size() == 8
    assert smp.pp_size() == 2
    assert smp.tp_size() == 2
    assert smp.rdp_size() == 2
    assert smp.dp_size() == 4
    assert smp.mp_size() == 4
    assert smp.rank() == 0
    assert sorted(smp.get_world_group()) == list(range(8))
    assert smp.get_mesh().shape["pp"] == 2
    assert len(smp.get_pp_group()) == 2
    assert len(smp.get_dp_group()) == 4


def test_collective_communicator_single_process():
    smp.init({})
    comm = smp.CollectiveCommunicator()
    assert comm.broadcast({"a": 1}) == {"a": 1}
    assert comm.allgather([1, 2]) == [[1, 2]]


def test_axis_group_cp():
    """axis_group returns the devices varying only along the given axis
    (backs CommGroup.CP_GROUP resolution in backend/collectives.py)."""
    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.backend.state import state
    from smdistributed_modelparallel_tpu.backend.topology import CP_AXIS, TP_AXIS

    smp.reset()
    smp.init({"context_parallel_degree": 2, "tensor_parallel_degree": 2,
              "ddp": True, "microbatches": 1})
    topo = state.topology
    for rank in range(topo.size):
        grp = topo.axis_group(rank, CP_AXIS)
        assert len(grp) == 2 and rank in grp
        my = topo.coords(rank)
        for r in grp:
            c = topo.coords(r)
            assert all(c[a] == my[a] for a in topo.axis_names if a != CP_AXIS)
    tp_grp = topo.axis_group(0, TP_AXIS)
    assert tp_grp == list(state.core.get_tp_group(0))
    assert state.core.get_cp_group(0) == topo.axis_group(0, CP_AXIS)


def test_instance_queries():
    """smp.instance_id / is_in_same_instance / is_multi_node (reference
    backend/core.py:479-489): ranks map to mesh devices; an "instance" is
    the host (jax process) owning the device. Single-process tier: every
    rank is on instance 0."""
    from smdistributed_modelparallel_tpu.utils.exceptions import (
        SMPValidationError,
    )

    smp.reset()
    smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2,
              "ddp": True, "microbatches": 1})
    assert smp.instance_id() == jax.process_index()
    for r in range(smp.size()):
        assert smp.instance_id(r) == 0
        assert smp.is_in_same_instance(r)
    assert smp.is_multi_node() == (jax.process_count() > 1)
    with pytest.raises(SMPValidationError):
        smp.instance_id(smp.size())
    with pytest.raises(SMPValidationError):
        smp.instance_id(-1)
