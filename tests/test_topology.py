"""DeviceTopology / mesh tests: mesh axis order matches placement strategy,
Ranker and mesh agree on device placement, smp.init wiring."""

import numpy as np
import pytest

import jax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.config import ModelParallelConfig
from smdistributed_modelparallel_tpu.backend.topology import DeviceTopology
from smdistributed_modelparallel_tpu.utils.exceptions import DeviceCountError


def test_mesh_axis_order_cluster():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True}
    )
    topo = DeviceTopology(cfg)
    # cluster == DPT: D-block (rdp, ep, cp) first, then pp, then tp.
    assert topo.axis_names == ("rdp", "ep", "cp", "pp", "tp")
    assert topo.mesh.shape["pp"] == 2
    assert topo.mesh.shape["tp"] == 2
    assert topo.mesh.shape["rdp"] == 2


def test_mesh_axis_order_spread():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True,
         "placement_strategy": "spread"}
    )
    topo = DeviceTopology(cfg)
    # spread == TPD
    assert topo.axis_names == ("tp", "pp", "rdp", "ep", "cp")


def test_mesh_matches_ranker():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True}
    )
    topo = DeviceTopology(cfg)
    devices = list(jax.devices())
    flat_mesh = list(topo.mesh.devices.flat)
    # Mesh is laid out in placement order, so flat index == global rank and
    # the ranker's grid must match device ids.
    for rank in range(topo.size):
        assert flat_mesh[rank] == devices[rank]
        coords = topo.coords(rank)
        assert coords["pp"] == topo.ranker.get_pp_rank(rank)
        assert coords["tp"] == topo.ranker.get_tp_rank(rank)
        assert coords["rdp"] == topo.ranker.get_rdp_rank(rank)


def test_device_count_validation():
    cfg = ModelParallelConfig({"pipeline_parallel_degree": 3, "microbatches": 3})
    with pytest.raises(DeviceCountError):
        DeviceTopology(cfg)


def test_device_count_override():
    cfg = ModelParallelConfig(
        {"pipeline_parallel_degree": 2, "_device_count_override": 4}
    )
    topo = DeviceTopology(cfg, devices=list(jax.devices()))
    assert topo.size == 4
    assert topo.rdp_size == 2


def test_cp_carved_from_dp():
    cfg = ModelParallelConfig({"context_parallel_degree": 2, "ddp": True})
    topo = DeviceTopology(cfg)
    assert topo.cp_size == 2
    assert topo.rdp_size == 4
    assert topo.d_size == 8  # reference "D" dim includes cp/ep
    for rank in range(8):
        assert topo.coords(rank)["cp"] in (0, 1)


def test_smp_init_api():
    smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2, "ddp": True})
    assert smp.is_initialized()
    assert smp.size() == 8
    assert smp.pp_size() == 2
    assert smp.tp_size() == 2
    assert smp.rdp_size() == 2
    assert smp.dp_size() == 4
    assert smp.mp_size() == 4
    assert smp.rank() == 0
    assert sorted(smp.get_world_group()) == list(range(8))
    assert smp.get_mesh().shape["pp"] == 2
    assert len(smp.get_pp_group()) == 2
    assert len(smp.get_dp_group()) == 4


def test_collective_communicator_single_process():
    smp.init({})
    comm = smp.CollectiveCommunicator()
    assert comm.broadcast({"a": 1}) == {"a": 1}
    assert comm.allgather([1, 2]) == [[1, 2]]


def test_axis_group_cp():
    """axis_group returns the devices varying only along the given axis
    (backs CommGroup.CP_GROUP resolution in backend/collectives.py)."""
    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.backend.state import state
    from smdistributed_modelparallel_tpu.backend.topology import CP_AXIS, TP_AXIS

    smp.reset()
    smp.init({"context_parallel_degree": 2, "tensor_parallel_degree": 2,
              "ddp": True, "microbatches": 1})
    topo = state.topology
    for rank in range(topo.size):
        grp = topo.axis_group(rank, CP_AXIS)
        assert len(grp) == 2 and rank in grp
        my = topo.coords(rank)
        for r in grp:
            c = topo.coords(r)
            assert all(c[a] == my[a] for a in topo.axis_names if a != CP_AXIS)
    tp_grp = topo.axis_group(0, TP_AXIS)
    assert tp_grp == list(state.core.get_tp_group(0))
    assert state.core.get_cp_group(0) == topo.axis_group(0, CP_AXIS)


def test_instance_queries():
    """smp.instance_id / is_in_same_instance / is_multi_node (reference
    backend/core.py:479-489): ranks map to mesh devices; an "instance" is
    the host (jax process) owning the device. Single-process tier: every
    rank is on instance 0."""
    from smdistributed_modelparallel_tpu.utils.exceptions import (
        SMPValidationError,
    )

    smp.reset()
    smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2,
              "ddp": True, "microbatches": 1})
    assert smp.instance_id() == jax.process_index()
    for r in range(smp.size()):
        assert smp.instance_id(r) == 0
        assert smp.is_in_same_instance(r)
    assert smp.is_multi_node() == (jax.process_count() > 1)
    with pytest.raises(SMPValidationError):
        smp.instance_id(smp.size())
    with pytest.raises(SMPValidationError):
        smp.instance_id(-1)


def test_rank_conversions():
    """smp.{pp,tp,rdp,dp,mp}_rank_to_rank (reference backend/core.py:
    439-477): invert the per-axis rank queries within this process's
    other-axis groups, for every placement strategy."""
    for placement in ("cluster", "spread"):
        smp.reset()
        smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2,
                  "ddp": True, "microbatches": 1,
                  "placement_strategy": placement})
        topo_size = smp.size()
        rk = smp.rank()
        # Round-trips: converting this rank's own per-axis rank yields
        # this rank back.
        assert smp.pp_rank_to_rank(smp.pp_rank()) == rk
        assert smp.tp_rank_to_rank(smp.tp_rank()) == rk
        assert smp.rdp_rank_to_rank(smp.rdp_rank()) == rk
        assert smp.dp_rank_to_rank(smp.dp_rank()) == rk
        assert smp.mp_rank_to_rank(smp.mp_rank()) == rk
        # Structural: pp_rank_to_rank enumerates this rank's pp group in
        # stage order; dp/mp likewise enumerate their composite groups.
        from smdistributed_modelparallel_tpu.backend.state import state
        ranker = state.topology.ranker
        pp_group = [smp.pp_rank_to_rank(i) for i in range(smp.pp_size())]
        assert sorted(pp_group) == sorted(smp.get_pp_group())
        assert [ranker.get_pp_rank(r) for r in pp_group] == list(
            range(smp.pp_size())
        )
        dp_group = [smp.dp_rank_to_rank(i) for i in range(smp.dp_size())]
        assert sorted(dp_group) == sorted(smp.get_dp_group())
        mp_group = [smp.mp_rank_to_rank(i) for i in range(smp.mp_size())]
        assert sorted(mp_group) == sorted(smp.get_mp_group())
        assert all(0 <= r < topo_size for r in pp_group + dp_group + mp_group)
        # No silent numpy wraparound or raw IndexError: out-of-range
        # per-axis ranks raise the API's validation error.
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPValidationError,
        )
        for fn, size in ((smp.pp_rank_to_rank, smp.pp_size()),
                         (smp.tp_rank_to_rank, smp.tp_size()),
                         (smp.rdp_rank_to_rank, smp.rdp_size()),
                         (smp.dp_rank_to_rank, smp.dp_size()),
                         (smp.mp_rank_to_rank, smp.mp_size())):
            with pytest.raises(SMPValidationError):
                fn(-1)
            with pytest.raises(SMPValidationError):
                fn(size)


def test_public_surface_queries():
    """Smoke every public rank/size/group/barrier query through the smp
    surface (several were previously only exercised via state.core) on a
    cp2 x pp2 x tp2 mesh — values must be mutually consistent."""
    smp.reset()
    smp.init({"context_parallel_degree": 2, "pipeline_parallel_degree": 2,
              "tensor_parallel_degree": 2, "ddp": True, "microbatches": 3})
    assert smp.local_rank() == 0
    assert smp.local_size() == jax.local_device_count()
    assert 0 <= smp.cp_rank() < smp.cp_size() == 2
    assert smp.num_microbatches() == 3
    assert smp.process_index() == 0 and smp.process_count() == 1
    assert not smp.is_tracing()
    tp_group = smp.get_tp_group()
    rdp_group = smp.get_rdp_group()
    assert len(tp_group) == 2 and smp.rank() in tp_group
    assert smp.rank() in rdp_group
    # Single-process tier: subgroup barriers complete without peers.
    smp.mp_barrier()
    smp.tp_barrier()
    smp.rdp_barrier()
    # get_partition reflects the partitioner's ASSIGNMENT (stage 0 until
    # a step has partitioned; pin honoring is covered in
    # test_config_honored) and validates its argument type.
    assert smp.get_partition("transformer/layer0") == 0
    from smdistributed_modelparallel_tpu.utils.exceptions import (
        SMPValidationError,
    )
    with pytest.raises(SMPValidationError):
        smp.get_partition(123)
