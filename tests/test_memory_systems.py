"""M4 tests: activation checkpointing, ZeRO sharding, fp16 loss scaling,
activation offloading.

Mirrors the reference tiers: ``test/torch/mpi_hybrid/test_zero.py`` /
``test_opt_sharding.py`` (sharded-vs-replicated loss parity),
``test/torch/test_checkpointing*`` (remat correctness), fp16 scaler unit
tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.nn.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedTransformerLMHead,
)

TINY = dict(
    num_layers=4, num_attention_heads=4, attention_head_size=8,
    hidden_size=32, intermediate_size=64, vocab_size=96, num_positions=32,
    causal_mask_size=32, pre_layernorm=True, post_layernorm=False,
    final_layernorm=True, attention_dropout_prob=0.0,
    hidden_dropout_prob=0.0, embedding_dropout_prob=0.0,
)


def _train(cfg, steps=3, model_kwargs=None, lr=0.1):
    smp.shutdown()
    smp.init(cfg)
    kwargs = dict(TINY)
    kwargs.update(model_kwargs or {})
    m = DistributedTransformerLMHead(**kwargs)
    model = smp.DistributedModel(m)
    opt = smp.DistributedOptimizer(optax.sgd(lr), model)

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        loss = jnp.mean(vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:]))
        model.backward(loss)
        return loss

    ids = jax.random.randint(jax.random.key(0), (8, 16), 0, 96)
    losses = []
    for _ in range(steps):
        out = train_step(model, ids)
        opt.step()
        losses.append(float(out.reduce_mean()))
    return losses, model, opt


class TestActivationCheckpointing:
    def test_loss_parity_with_remat(self):
        base, _, _ = _train({"microbatches": 2})
        ckpt, _, _ = _train(
            {"microbatches": 2},
            model_kwargs={"activation_checkpointing": True},
        )
        np.testing.assert_allclose(base, ckpt, atol=1e-5)

    def test_set_activation_checkpointing_api(self):
        smp.shutdown()
        smp.init({"microbatches": 2})
        smp.set_activation_checkpointing("transformer")
        m = DistributedTransformerLMHead(**TINY)
        model = smp.DistributedModel(m)
        assert model.module.activation_checkpointing

    def test_smp_checkpoint_function(self):
        smp.shutdown()
        smp.init({})

        def f(x):
            return jnp.sum(jnp.tanh(x) ** 2)

        x = jax.random.normal(jax.random.key(0), (8,))
        g1 = jax.grad(f)(x)
        g2 = jax.grad(lambda x: smp.checkpoint(f)(x))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)

    def test_checkpoint_sequential(self):
        smp.shutdown()
        smp.init({})
        fns = [jnp.tanh, jnp.sin, jnp.cos, jnp.tanh]
        x = jax.random.normal(jax.random.key(0), (4,))
        out = smp.checkpoint_sequential(fns, x, strategy="group_2")
        ref = x
        for f in fns:
            ref = f(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_pipeline_remat_parity(self):
        base, _, _ = _train({"microbatches": 4})
        pp, _, _ = _train(
            {"microbatches": 4, "pipeline_parallel_degree": 2, "ddp": True},
            model_kwargs={"activation_checkpointing": True},
        )
        np.testing.assert_allclose(base, pp, atol=1e-4)


class TestOptimizerStateSharding:
    def test_zero1_loss_parity(self):
        base, _, _ = _train({"microbatches": 2, "ddp": True})
        z1, model, opt = _train(
            {"microbatches": 2, "ddp": True, "shard_optimizer_state": True}
        )
        np.testing.assert_allclose(base, z1, atol=1e-5)
        # Adam-like state would shard; SGD has no moments. Re-check with adamw.

    def test_zero1_moments_sharded(self):
        smp.shutdown()
        smp.init({"microbatches": 2, "ddp": True, "shard_optimizer_state": True})
        m = DistributedTransformerLMHead(**TINY)
        model = smp.DistributedModel(m)
        opt = smp.DistributedOptimizer(optax.adamw(1e-3), model)

        @smp.step
        def train_step(model, ids):
            logits = model(ids)
            loss = jnp.mean(
                vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:])
            )
            model.backward(loss)
            return loss

        ids = jax.random.randint(jax.random.key(0), (8, 16), 0, 96)
        train_step(model, ids)
        opt.step()
        # Find a moment leaf and check it is sharded over rdp.
        from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS

        found_sharded = False
        for leaf in jax.tree_util.tree_leaves(opt.opt_state):
            if isinstance(leaf, jax.Array) and leaf.ndim >= 1:
                spec = getattr(leaf.sharding, "spec", None)
                if spec and any(
                    RDP_AXIS in (ax if isinstance(ax, tuple) else (ax,))
                    for ax in spec if ax is not None
                ):
                    found_sharded = True
                    break
        assert found_sharded, "no optimizer-state leaf sharded over rdp"


class TestShardedDataParallelism:
    def test_zero2d_loss_parity(self):
        base, _, _ = _train({"microbatches": 2, "ddp": True})
        z2, model, _ = _train({
            "microbatches": 2, "ddp": True,
            "sharded_data_parallel_degree": 8,
            "sdp_param_persistence_threshold": 100,
        })
        np.testing.assert_allclose(base, z2, atol=1e-5)

    def test_zero2d_params_sharded(self):
        smp.shutdown()
        smp.init({
            "microbatches": 2, "ddp": True,
            "sharded_data_parallel_degree": 8,
            "sdp_param_persistence_threshold": 100,
        })
        m = DistributedTransformerLMHead(**TINY)
        model = smp.DistributedModel(m)

        @smp.step
        def fwd(model, ids):
            logits = model(ids)
            loss = jnp.mean(
                vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:])
            )
            model.backward(loss)
            return loss

        ids = jax.random.randint(jax.random.key(0), (8, 16), 0, 96)
        fwd(model, ids)
        from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS

        sharded = 0
        for leaf in jax.tree_util.tree_leaves(model.params):
            spec = getattr(leaf.sharding, "spec", None)
            if spec and any(
                RDP_AXIS in (ax if isinstance(ax, tuple) else (ax,))
                for ax in spec if ax is not None
            ):
                sharded += 1
        assert sharded > 0, "no parameter sharded over rdp under zero2d"


class TestFp16LossScaling:
    def test_scaler_backoff_and_growth(self):
        from smdistributed_modelparallel_tpu.fp16 import DynamicLossScaler

        s = DynamicLossScaler(init_scale=1024.0, scale_window=2)
        s.update(True)
        assert s.loss_scale == 512.0
        s.update(False)
        s.update(False)
        assert s.loss_scale == 1024.0

    def test_fp16_training_runs_and_matches(self):
        base, _, _ = _train({"microbatches": 2}, lr=0.01)
        fp16, _, _ = _train({"microbatches": 2, "fp16": True}, lr=0.01)
        # Half precision: loose tolerance, but the curves must track.
        np.testing.assert_allclose(base, fp16, rtol=0.05)
        assert state.loss_scaler is not None

    def test_overflow_skips_step(self):
        smp.shutdown()
        smp.init({"microbatches": 1, "fp16": True})
        m = DistributedTransformerLMHead(**TINY)
        model = smp.DistributedModel(m)
        opt = smp.DistributedOptimizer(optax.sgd(0.1), model)

        @smp.step
        def bad_step(model, ids):
            logits = model(ids)
            loss = jnp.sum(logits) * jnp.inf  # force overflow
            model.backward(loss)
            return loss

        ids = jax.random.randint(jax.random.key(0), (2, 16), 0, 96)
        bad_step(model, ids)
        before = jax.device_get(jax.tree_util.tree_leaves(model.params)[0])
        scale_before = state.loss_scaler.loss_scale
        opt.step()
        after = jax.device_get(jax.tree_util.tree_leaves(model.params)[0])
        np.testing.assert_array_equal(before, after)  # update skipped
        assert state.loss_scaler.loss_scale < scale_before  # backed off


class TestActivationOffload:
    def test_offload_config_runs(self):
        # On backends without pinned_host this falls back to plain remat;
        # either way the step must run and match the baseline.
        base, _, _ = _train({"microbatches": 2})
        off, _, _ = _train(
            {"microbatches": 2, "offload_activations": True},
            model_kwargs={"activation_checkpointing": True},
        )
        np.testing.assert_allclose(base, off, atol=1e-5)
