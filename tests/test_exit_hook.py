"""Failure-detection tests (SURVEY §5.3).

Parity target: reference ``ExitHook`` (``backend/core.py:165-189``) +
``shutdown`` status derivation (``:226-231``).
"""

import sys

import pytest

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exit_hook import ExitHook


class TestExitHook:
    def test_captures_exit_code(self):
        hook = ExitHook()
        hook.hook()
        try:
            with pytest.raises(SystemExit):
                sys.exit(3)
            assert hook.exit_code == 3
            assert hook.success is False
        finally:
            hook.unhook()

    def test_clean_exit_is_success(self):
        hook = ExitHook()
        hook.hook()
        try:
            with pytest.raises(SystemExit):
                sys.exit(0)
            assert hook.exit_code == 0
            assert hook.success is True
        finally:
            hook.unhook()

    def test_captures_uncaught_exception(self):
        hook = ExitHook()
        hook.hook()
        try:
            err = RuntimeError("boom")
            # Simulate the interpreter's top-level dispatch.
            sys.excepthook(RuntimeError, err, None)
            assert hook.exception is err
            assert hook.success is False
        finally:
            hook.unhook()

    def test_unhook_restores(self):
        hook = ExitHook()
        orig_exit, orig_hook = sys.exit, sys.excepthook
        hook.hook()
        hook.unhook()
        assert sys.exit is orig_exit
        assert sys.excepthook is orig_hook

    def test_hook_idempotent(self):
        hook = ExitHook()
        hook.hook()
        try:
            hooked = sys.exit
            hook.hook()  # second install must not capture its own wrapper
            assert sys.exit is hooked
        finally:
            hook.unhook()


class TestCoreIntegration:
    def test_init_attaches_and_status_flows_to_shutdown(self, monkeypatch):
        smp.reset()
        smp.init({"microbatches": 1})
        core = state.core
        assert core.exit_hook is not None
        # Earlier tests' simulated exits/exceptions chain into this hook
        # (handlers wrap the previously-installed ones); reset for isolation.
        core.exit_hook.exit_code = None
        core.exit_hook.exception = None
        assert core.exit_status() is True
        try:
            with pytest.raises(SystemExit):
                sys.exit(7)
            assert core.exit_status() is False
            from smdistributed_modelparallel_tpu.backend import core as core_mod

            errors = []
            monkeypatch.setattr(
                core_mod.logger, "error",
                lambda msg, *a, **k: errors.append(msg % a if a else msg),
            )
            core.shutdown()
            assert any("failure" in m for m in errors)
        finally:
            core.exit_hook.exit_code = None
            core.exit_hook.unhook()
