"""Low-precision frontier (``smp.quant``): fp8 delayed-scaling training
matmuls + int8 paged-KV / weight-only-int8 serving.

Coverage map:
- config surface: the SMP_MATMUL_PRECISION env alias, schema rejects,
  and the canonicalization rules (bf16 under pp > 1 / zero3; the
  SMP_KV_QUANT / SMP_DECODE_WEIGHTS env readers and their rejects);
- THE training acceptance gate: bf16-vs-fp8 loss-trajectory parity over
  10 steps at the canonical TINY config, the X-ray ``quant`` census
  (e4m3 forward + e5m2 gradient casts, zero findings), the
  ``smp_quant_*`` gauges/counters, and the committed ``quant_fp8``
  golden fingerprint;
- the silently-upcast-matmul detector e2e: an fp8-requested program
  none of whose seams engaged must carry a ``quant_upcast`` finding;
- default-knob hygiene: bf16 programs carry NO quant block and no
  config fact (byte-identical contract);
- QuantState checkpointing (slow tier): amax/scale round-trip through
  save/resume at the exact coordinate AND through the elastic glob
  fallback;
- serving: int8 paged-KV pool bytes <= 0.55x bf16 (gauge-asserted via
  ``smp_serve_kv_bytes``) with greedy-exact token parity; weight-only
  int8 engine vs ``smp.generate`` parity incl. both knobs together
  (slow tier);
- satellites: step-cache/exec-cache quant knob facts (defaults omitted,
  stored-meta flip -> reject_version), the telemetry_report
  "-- quant --" section goldens (single dump + cross-rank dir mode),
  and the perf-ledger ``quant`` component schema/carry/render.
"""

import glob
import importlib.util
import io
import json
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu import quant
from smdistributed_modelparallel_tpu.backend.config import ModelParallelConfig
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.models.transformer_lm import (
    TransformerLM,
)
from smdistributed_modelparallel_tpu.nn.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedTransformerLMHead,
)
from smdistributed_modelparallel_tpu.serving import (
    ServeRequest,
    ServingEngine,
)
from smdistributed_modelparallel_tpu.utils import hlo_audit
from smdistributed_modelparallel_tpu.utils import telemetry as tel
from smdistributed_modelparallel_tpu.utils.exceptions import ConfigError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.join(_REPO, "scripts")

# The canonical model/config: identical to the golden generator's
# (tests/goldens/generate_hlo_fingerprints.py "quant_fp8").
TINY = dict(
    num_layers=2, num_attention_heads=4, attention_head_size=8,
    hidden_size=32, intermediate_size=64, vocab_size=96, num_positions=32,
    causal_mask_size=32, pre_layernorm=True, post_layernorm=False,
    final_layernorm=True, attention_dropout_prob=0.0,
    hidden_dropout_prob=0.0, embedding_dropout_prob=0.0,
)
BASE = {"microbatches": 2, "ddp": True}


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _train(cfg, steps=2):
    smp.shutdown()
    smp.init(cfg)
    model = smp.DistributedModel(DistributedTransformerLMHead(**TINY))
    opt = smp.DistributedOptimizer(optax.sgd(0.1), model)

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        loss = jnp.mean(
            vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:])
        )
        model.backward(loss)
        return loss

    ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 96)
    losses = []
    for _ in range(steps):
        out = train_step(model, ids)
        losses.append(float(out.reduce_mean()))
        opt.step()
    return losses, model, train_step


def _metric_series(name):
    return tel.telemetry.report()["metrics"].get(
        name, {"series": []}
    )["series"]


def _gauge(name, **labels):
    for s in _metric_series(name):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return None


# ----------------------------------------------------------------------
# Config surface + canonical modes
# ----------------------------------------------------------------------


class TestConfig:
    def test_defaults(self):
        cfg = ModelParallelConfig({})
        assert cfg.matmul_precision == "bf16"

    def test_schema_rejects_unknown_mode(self):
        with pytest.raises(ConfigError):
            ModelParallelConfig({"matmul_precision": "int4"})

    def test_env_alias(self, monkeypatch):
        monkeypatch.setenv("SMP_MATMUL_PRECISION", "fp8")
        assert ModelParallelConfig({}).matmul_precision == "fp8"
        # Explicit config wins over the env alias.
        assert ModelParallelConfig(
            {"matmul_precision": "bf16"}
        ).matmul_precision == "bf16"
        monkeypatch.setenv("SMP_MATMUL_PRECISION", "off")
        assert ModelParallelConfig({}).matmul_precision == "bf16"
        monkeypatch.setenv("SMP_MATMUL_PRECISION", "garbage")
        with pytest.raises(ConfigError):
            ModelParallelConfig({})

    def test_mode_canonicalization(self):
        # Plain data parallel: fp8 engages.
        cfg = ModelParallelConfig(dict(BASE, matmul_precision="fp8"))
        assert quant.matmul_precision_mode(cfg) == "fp8"
        # pp > 1: the pipelined executors own their grad plumbing ->
        # bf16 (warned once; an idle knob never moves a cache key).
        cfg = ModelParallelConfig({
            "matmul_precision": "fp8", "pipeline_parallel_degree": 2,
            "microbatches": 4, "ddp": True,
        })
        assert quant.matmul_precision_mode(cfg) == "bf16"
        # zero3: the manual-gradient path -> bf16.
        cfg = ModelParallelConfig(dict(
            BASE, matmul_precision="fp8", sharded_params="zero3",
        ))
        assert quant.matmul_precision_mode(cfg) == "bf16"
        assert quant.matmul_precision_mode(None) == "bf16"

    def test_kv_quant_env(self, monkeypatch):
        for v in ("", "0", "none", "off", "bf16"):
            monkeypatch.setenv("SMP_KV_QUANT", v)
            assert quant.kv_quant_mode() == "none"
        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        assert quant.kv_quant_mode() == "none"
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        assert quant.kv_quant_mode() == "int8"
        monkeypatch.setenv("SMP_KV_QUANT", "fp4")
        with pytest.raises(ValueError):
            quant.kv_quant_mode()

    def test_decode_weights_env(self, monkeypatch):
        monkeypatch.delenv("SMP_DECODE_WEIGHTS", raising=False)
        assert quant.decode_weights_mode() == "none"
        monkeypatch.setenv("SMP_DECODE_WEIGHTS", "int8")
        assert quant.decode_weights_mode() == "int8"
        monkeypatch.setenv("SMP_DECODE_WEIGHTS", "int2")
        with pytest.raises(ValueError):
            quant.decode_weights_mode()

    def test_serving_key_suffix(self, monkeypatch):
        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        monkeypatch.delenv("SMP_DECODE_WEIGHTS", raising=False)
        # Defaults contribute NOTHING — pre-knob key tuples.
        assert quant.serving_key_suffix() == ()
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        assert quant.serving_key_suffix() == ((("kv_quant", "int8"),))
        monkeypatch.setenv("SMP_DECODE_WEIGHTS", "int8")
        assert quant.serving_key_suffix() == (
            ("kv_quant", "int8"), ("decode_weights", "int8"),
        )


# ----------------------------------------------------------------------
# THE training acceptance gate: parity + the X-ray census + the golden
# ----------------------------------------------------------------------


class TestFp8Gate:
    def test_parity_census_gauges_and_golden(self):
        """THE acceptance test: at the canonical TINY config,
        ``matmul_precision: fp8`` must (a) track the bf16 loss
        trajectory over 10 steps, (b) compile a program whose X-ray
        ``quant`` census shows e4m3 forward AND e5m2 gradient casts
        with zero findings, (c) publish the ``smp_quant_*`` gauges and
        dispatch counters with a live delayed-scaling state, and
        (d) match the committed ``quant_fp8`` golden fingerprint."""
        base_l, _, _ = _train(BASE, steps=10)
        fp8_l, _, train_step = _train(
            dict(BASE, matmul_precision="fp8"), steps=10
        )
        # (a) the quantization error stays a small relative
        # perturbation of the trajectory (CPU smoke measures ~1e-4).
        np.testing.assert_allclose(base_l, fp8_l, rtol=2e-2)

        # (b) the census: e4m3 forward operands, e5m2 cotangents; the
        # detector stayed silent (the program IS quantized).
        audit = hlo_audit.of_step_function(train_step)
        assert audit.quant is not None
        assert audit.quant["f8_casts"]["e4m3"] > 0
        assert audit.quant["f8_casts"]["e5m2"] > 0
        assert audit.findings == []
        assert audit.config.get("matmul_precision") == "fp8"

        # (c) delayed scaling is LIVE: amax observations landed, scales
        # moved off the fresh-start 1.0, and the gauges mirror them.
        qs = state.quant_state
        assert qs is not None
        assert qs.amax_history[:, 0].any()
        assert (qs.scale != 1.0).any()
        assert _gauge("smp_quant_amax", site="qkv.x") > 0
        assert _gauge("smp_quant_scale", site="qkv.x") is not None
        disp = _metric_series("smp_quant_dispatch_total")
        assert any(
            s["labels"].get("path") == "fp8" and s["value"] > 0
            for s in disp
        )

        # (d) committed golden (SEMANTIC_FIELDS diff, quant block
        # included — evidence presence per bucket, not exact counts).
        from tests.conftest import assert_matches_hlo_golden

        assert_matches_hlo_golden(audit, "quant_fp8")

    def test_default_bf16_is_additive(self):
        """The byte-identical contract's fingerprint face: a default
        program carries NO quant block and no config fact."""
        _, _, train_step = _train(BASE, steps=1)
        audit = hlo_audit.of_step_function(train_step)
        assert audit.quant is None
        assert "matmul_precision" not in audit.config

    def test_upcast_detector_fires_when_no_seam_engages(self, monkeypatch):
        """Detector e2e: neuter every seam's dispatch while the config
        still claims fp8 — the program compiles with zero f8 evidence
        and the X-ray must flag ``quant_upcast`` instead of letting the
        low-precision claim stand."""
        monkeypatch.setattr(quant, "fp8_trace_active", lambda: False)
        _, _, train_step = _train(
            dict(BASE, matmul_precision="fp8"), steps=1
        )
        audit = hlo_audit.of_step_function(train_step)
        assert audit.quant is not None
        assert audit.quant["native_f8_dots"] == 0
        assert audit.quant["fp8_origin_dots"] == 0
        assert not any(audit.quant["f8_casts"].values())
        kinds = {f.get("kind") for f in audit.findings}
        assert "quant_upcast" in kinds


# ----------------------------------------------------------------------
# QuantState checkpointing: exact coordinate + elastic glob fallback
# ----------------------------------------------------------------------


class TestQuantCheckpoint:
    def test_amax_scale_roundtrip_and_elastic_resume(self, tmp_path):
        root = str(tmp_path / "ckpt")
        losses, model, step_fn = _train(
            dict(BASE, matmul_precision="fp8"), steps=4
        )
        want = state.quant_state.state_dict()
        assert want["amax_history"].any()
        smp.save_checkpoint(root, tag="q", model=model)
        files = glob.glob(
            os.path.join(root, "q_partial", "quant_states*.pt")
        )
        assert files, "quant_states file missing from the checkpoint"

        # Exact-coordinate resume: a fresh fp8 build starts zeroed and
        # restores the saved history/scales bit-for-bit.
        _, model2, step2 = _train(
            dict(BASE, matmul_precision="fp8"), steps=0
        )
        assert not state.quant_state.state_dict()["amax_history"].any()
        smp.resume_from_checkpoint(root, tag="q")
        got = state.quant_state.state_dict()
        np.testing.assert_array_equal(
            got["amax_history"], want["amax_history"]
        )
        np.testing.assert_array_equal(got["scale"], want["scale"])
        # Training continues under the restored scales.
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 96)
        step2(model2, ids)

        # Elastic fallback: rename the coordinate file to one no live
        # rank owns — the glob fallback still restores the state.
        src = glob.glob(
            os.path.join(root, "q_partial", "quant_states*.pt")
        )[0]
        shutil.move(
            src,
            os.path.join(os.path.dirname(src), "quant_states_7_0_0.pt"),
        )
        _, model3, step3 = _train(
            dict(BASE, matmul_precision="fp8"), steps=0
        )
        smp.resume_from_checkpoint(root, tag="q")
        got3 = state.quant_state.state_dict()
        np.testing.assert_array_equal(
            got3["amax_history"], want["amax_history"]
        )
        np.testing.assert_array_equal(got3["scale"], want["scale"])
        step3(model3, ids)


# ----------------------------------------------------------------------
# Serving: int8 paged-KV pool + weight-only int8 decode
# ----------------------------------------------------------------------


def _zoo(**kw):
    kw.setdefault("vocab_size", 97)
    kw.setdefault("max_len", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("pos_type", "rotary")
    return TransformerLM(**kw)


def _prompt(seed, length, vocab=97):
    return list(map(int, np.asarray(
        jax.random.randint(jax.random.key(seed), (length,), 0, vocab)
    )))


def _generate_ref(mod, params, prompt, max_new, **kw):
    out = np.asarray(smp.generate(
        mod, jnp.asarray(prompt, jnp.int32)[None, :], max_new,
        params=params, **kw,
    ))
    return list(out[0, len(prompt):])


def _engine(mod, params):
    return ServingEngine(
        mod, params=params, max_slots=3, num_blocks=13,
        block_tokens_override=4, prefill_chunk=4,
    )


SPECS = [
    ("q0", 40, 7, 6),
    ("q1", 41, 11, 4),
    ("q2", 42, 3, 8),
]


def _run(engine):
    return engine.run(
        [ServeRequest(rid, _prompt(seed, n), m)
         for rid, seed, n, m in SPECS],
        timeout_s=300,
    )


class TestServingInt8KV:
    def test_pool_bytes_halved_gauge_asserted_with_token_parity(
        self, monkeypatch
    ):
        """THE serving acceptance: the int8 pool's bytes/block (scale
        sidecars included) land at <= 0.55x the bf16 pool's — asserted
        off the ``smp_serve_kv_bytes`` gauge, not dtype names — while
        greedy decode stays token-for-token exact."""
        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        smp.init({})
        mod = _zoo()
        probe = jnp.zeros((1, 4), jnp.int32)
        params = mod.init(jax.random.key(0), probe)["params"]

        eng_b = _engine(mod, params)
        res_b = _run(eng_b)
        bytes_b = eng_b.kv_block_bytes
        assert bytes_b > 0
        total_b = _gauge("smp_serve_kv_bytes", state="total")
        assert total_b == eng_b.alloc.num_blocks * bytes_b

        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        eng_q = _engine(mod, params)
        res_q = _run(eng_q)
        bytes_q = eng_q.kv_block_bytes
        assert bytes_q <= 0.55 * bytes_b
        # The gauge reflects the quantized pool now.
        total_q = _gauge("smp_serve_kv_bytes", state="total")
        assert total_q == eng_q.alloc.num_blocks * bytes_q
        assert total_q <= 0.55 * total_b
        # Greedy token parity, int8 pool vs bf16 pool.
        for rid, _, _, _ in SPECS:
            assert list(res_q[rid]) == list(res_b[rid]), rid
        # The dispatch decision was counted.
        assert _gauge is not None
        disp = [
            s for s in _metric_series("smp_quant_dispatch_total")
            if s["labels"].get("site") == "kv_cache"
            and s["labels"].get("path") == "int8"
        ]
        assert disp and disp[0]["value"] >= 1

    def test_serving_key_moves_with_the_knob(self, monkeypatch):
        """A knob flip must recompile, never reuse the other layout's
        programs — the key suffix is the mechanism."""
        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        base = quant.serving_key_suffix()
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        assert quant.serving_key_suffix() != base


class TestDecodeWeightsInt8:
    def test_engine_matches_generate_fake_quant(self, monkeypatch):
        """Weight-only int8: the engine's store-int8+dequant programs
        and ``smp.generate``'s fake-quant path are numerics-identical,
        so the parity oracle holds under the knob — alone and combined
        with the int8 KV pool."""
        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        monkeypatch.setenv("SMP_DECODE_WEIGHTS", "int8")
        smp.init({})
        mod = _zoo()
        probe = jnp.zeros((1, 4), jnp.int32)
        params = mod.init(jax.random.key(0), probe)["params"]

        eng = _engine(mod, params)
        res = _run(eng)
        for rid, seed, n, m in SPECS:
            ref = _generate_ref(mod, params, _prompt(seed, n), m)
            assert list(res[rid]) == ref, rid
        disp = [
            s for s in _metric_series("smp_quant_dispatch_total")
            if s["labels"].get("site") == "decode_weights"
        ]
        assert disp and disp[0]["value"] >= 1

        # Both serving knobs together keep the same parity.
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        eng2 = _engine(mod, params)
        res2 = _run(eng2)
        for rid, seed, n, m in SPECS:
            ref = _generate_ref(mod, params, _prompt(seed, n), m)
            assert list(res2[rid]) == ref, rid


# ----------------------------------------------------------------------
# Step-cache / exec-cache knob facts
# ----------------------------------------------------------------------


class TestKnobFacts:
    def test_defaults_omit_all_quant_facts(self, monkeypatch):
        from smdistributed_modelparallel_tpu.utils import exec_cache

        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        monkeypatch.delenv("SMP_DECODE_WEIGHTS", raising=False)
        smp.shutdown()
        smp.init(dict(BASE))
        facts = exec_cache._knob_facts()
        assert "matmul_precision" not in facts
        assert "kv_quant" not in facts
        assert "decode_weights" not in facts

    def test_engaged_knobs_append_facts(self, monkeypatch):
        from smdistributed_modelparallel_tpu.utils import exec_cache

        smp.shutdown()
        smp.init(dict(BASE, matmul_precision="fp8"))
        assert exec_cache._knob_facts().get("matmul_precision") == "fp8"
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        monkeypatch.setenv("SMP_DECODE_WEIGHTS", "int8")
        facts = exec_cache._knob_facts()
        assert facts.get("kv_quant") == "int8"
        assert facts.get("decode_weights") == "int8"
        # Canonicalization keys the FACT, not the raw knob: fp8 under
        # pp > 1 resolves bf16, so the fact disappears.
        smp.shutdown()
        smp.init({
            "matmul_precision": "fp8", "pipeline_parallel_degree": 2,
            "microbatches": 4, "ddp": True,
        })
        assert "matmul_precision" not in exec_cache._knob_facts()

    def test_knob_flip_is_a_verified_miss(self, tmp_path, monkeypatch):
        """A disk entry stored at the defaults (no quant facts at all)
        must reject (version skew) once a live quant knob engages, and
        verify again when the knob drops back — the PR-12/13 contract."""
        from smdistributed_modelparallel_tpu.utils import exec_cache

        monkeypatch.delenv("SMP_KV_QUANT", raising=False)
        smp.shutdown()
        smp.init(dict(BASE))
        monkeypatch.setenv(exec_cache.ENV, "on")
        monkeypatch.setenv(exec_cache.DIR_ENV, str(tmp_path / "cache"))
        f = jax.jit(lambda x: x * 2.0)
        x = jnp.ones((4,), jnp.float32)
        lowered = f.lower(x)
        sha = exec_cache.module_hash(lowered)
        path = exec_cache.store(
            "step", "k" * 16, lowered.compile(), module_sha=sha
        )
        assert path
        loaded, _ = exec_cache.load("step", "k" * 16, module_sha=sha)
        assert loaded is not None
        with open(os.path.join(path, "meta.json")) as fh:
            meta = json.load(fh)
        # Stored pre-knob: defaults omit every quant fact.
        assert "matmul_precision" not in meta["knobs"]
        assert "kv_quant" not in meta["knobs"]
        # Flip a LIVE knob on: the pre-knob entry belongs to the other
        # program -> rejected, entry kept on disk for its own env.
        monkeypatch.setenv("SMP_KV_QUANT", "int8")
        loaded, _ = exec_cache.load("step", "k" * 16, module_sha=sha)
        assert loaded is None
        assert os.path.exists(path)
        # Back at the default the same entry verifies again.
        monkeypatch.delenv("SMP_KV_QUANT")
        loaded, _ = exec_cache.load("step", "k" * 16, module_sha=sha)
        assert loaded is not None


# ----------------------------------------------------------------------
# telemetry_report "-- quant --" section (golden)
# ----------------------------------------------------------------------


class TestQuantReportSection:
    def _report(self, with_counters=True):
        metrics = {
            "smp_quant_amax": {
                "kind": "gauge", "help": "", "series": [
                    {"labels": {"site": "qkv.x"}, "value": 2.0},
                    {"labels": {"site": "qkv.w"}, "value": 0.0},
                ],
            },
            "smp_quant_scale": {
                "kind": "gauge", "help": "", "series": [
                    {"labels": {"site": "qkv.x"}, "value": 0.5},
                    {"labels": {"site": "qkv.w"}, "value": 1.0},
                ],
            },
            "smp_serve_kv_bytes": {
                "kind": "gauge", "help": "", "series": [
                    {"labels": {"state": "used"}, "value": 4224},
                    {"labels": {"state": "total"}, "value": 27456},
                ],
            },
        }
        if with_counters:
            metrics["smp_quant_dispatch_total"] = {
                "kind": "counter", "help": "", "series": [
                    {"labels": {"site": "qkv", "path": "fp8"},
                     "value": 2},
                    {"labels": {"site": "kv_cache", "path": "int8"},
                     "value": 1},
                ],
            }
        return {
            "meta": {"pid": 1, "phase": "run/step"},
            "metrics": metrics,
        }

    GOLDEN = (
        "\n-- quant --\n"
        "  dispatch decisions: kv_cache/int8 x1  qkv/fp8 x2\n"
        "  site                    amax       scale\n"
        "  qkv.x                      2         0.5\n"
        "  (1 slot(s) never observed — scale held at 1.0)\n"
        "  kv pool bytes: 4.1 KiB used / 26.8 KiB total\n"
    )

    def test_single_dump_golden(self):
        mod = _load_script("telemetry_report")
        out = io.StringIO()
        mod.render(self._report(), out=out)
        assert self.GOLDEN in out.getvalue()

    def test_dir_mode_aggregate_renders_section(self, tmp_path):
        mod = _load_script("telemetry_report")
        for rank in (0, 1):
            rep = self._report(with_counters=False)
            rep["meta"]["rank"] = rank
            with open(tmp_path / f"telemetry.json.rank{rank}", "w") as f:
                json.dump(rep, f)
        reports = mod.load_rank_dumps(str(tmp_path))
        assert sorted(reports) == [0, 1]
        out = io.StringIO()
        mod.render_cross_rank(reports, out=out)
        text = out.getvalue()
        # Gauges max across ranks (exact for the replicated SPMD quant
        # state): the aggregate table equals one rank's.
        assert "-- quant --" in text
        assert "  qkv.x                      2         0.5\n" in text
        assert "  kv pool bytes: 4.1 KiB used / 26.8 KiB total\n" in text

    def test_absent_gauges_omit_section(self):
        mod = _load_script("telemetry_report")
        out = io.StringIO()
        mod.render({"meta": {}, "metrics": {}}, out=out)
        assert "-- quant --" not in out.getvalue()


# ----------------------------------------------------------------------
# perf_ledger quant component
# ----------------------------------------------------------------------


def _quant_probe_block(**over):
    block = {
        "component": "quant",
        "train": {
            "bf16_ms": 5.4, "fp8_ms": 8.5, "speedup_fp8": 0.6353,
            "loss_rel_diff": 9.6e-05, "steps_compared": 10,
            "quant_xray": {
                "native_f8_dots": 0, "fp8_origin_dots": 0,
                "f8_casts": {"e4m3": 79, "e5m2": 4},
            },
        },
        "decode": {
            "bf16_tokens_per_sec": 120.0,
            "int8_kv_tokens_per_sec": 110.0, "speedup_kv": 0.9167,
            "kv_block_bytes_bf16": 8192, "kv_block_bytes_int8": 2112,
            "kv_bytes_ratio": 0.2578, "token_parity": True,
            "requests": 6,
        },
        "on_tpu": False,
    }
    block.update(over)
    return block


class TestLedgerQuantProbe:
    @pytest.fixture()
    def ledger_mod(self):
        return _load_script("perf_ledger")

    def test_schema_accepts_and_rejects(self, ledger_mod):
        check = ledger_mod._quant_probe_schema_problem
        assert check(None) is None
        assert check(_quant_probe_block()) is None
        # Either leg alone is a valid block; neither is not.
        assert check(_quant_probe_block(decode=None)) is None
        assert check(_quant_probe_block(train=None)) is None
        assert "neither" in check(
            _quant_probe_block(train=None, decode=None)
        )
        assert "component" in check(_quant_probe_block(component="nope"))
        blk = _quant_probe_block()
        blk["train"]["fp8_ms"] = None
        assert "fp8_ms" in check(blk)
        blk = _quant_probe_block()
        blk["train"]["speedup_fp8"] = 9.0
        assert "inconsistent" in check(blk)
        blk = _quant_probe_block()
        blk["train"]["quant_xray"] = "not-a-dict"
        assert "quant_xray" in check(blk)
        blk = _quant_probe_block()
        blk["decode"]["kv_bytes_ratio"] = 0.9
        assert "inconsistent" in check(blk)
        blk = _quant_probe_block()
        blk["decode"]["token_parity"] = False
        assert "token_parity" in check(blk)

    def test_carried_and_rendered(self, tmp_path, ledger_mod):
        repo = str(tmp_path)
        with open(os.path.join(repo, "BASELINE.json"), "w") as f:
            json.dump({"metric": "m"}, f)
        parsed = {"metric": "tokens/sec/chip GPT-2-124M train step",
                  "value": 50000.0, "vs_baseline": 1.0,
                  "quant": _quant_probe_block()}
        payload = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": parsed}
        with open(os.path.join(repo, "BENCH_r01.json"), "w") as f:
            json.dump(payload, f)
        ledger = ledger_mod.build_ledger(repo)
        assert ledger["ok"], ledger["problems"]
        assert ledger["rounds"][0]["quant"]["train"]["fp8_ms"] == 8.5
        out = io.StringIO()
        ledger_mod.render_table(ledger, out=out)
        text = out.getvalue()
        assert "quant train:" in text
        assert "speedup 0.64x" in text
        assert "loss drift 0.01%" in text
        assert "f8 casts e4m3=79 e5m2=4" in text
        assert "quant decode:" in text
        assert "kv bytes/block 8,192B -> 2,112B (0.26x)" in text
        assert "parity ok" in text

    def test_malformed_block_is_a_problem(self, tmp_path, ledger_mod):
        repo = str(tmp_path)
        with open(os.path.join(repo, "BASELINE.json"), "w") as f:
            json.dump({"metric": "m"}, f)
        parsed = {"metric": "m", "value": 1.0, "vs_baseline": 1.0,
                  "quant": {"component": "quant"}}
        payload = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": parsed}
        with open(os.path.join(repo, "BENCH_r01.json"), "w") as f:
            json.dump(payload, f)
        ledger = ledger_mod.build_ledger(repo)
        assert not ledger["ok"]
        assert any("quant" in p for p in ledger["problems"])
        assert ledger["rounds"][0]["quant"] is None
