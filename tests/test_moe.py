"""Mixture-of-Experts / expert-parallelism tests.

New capability (SURVEY §2.6: MoE/EP absent in the reference). Covers the
dense-dispatch math, capacity semantics, gradient flow, aux loss, ep-mesh
sharded execution parity, and the transformer integration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.nn.moe import DistributedMoE, moe_aux_losses


def _mk(B=2, T=8, D=16, **kw):
    module = DistributedMoE(
        hidden_size=D, intermediate_size=32, deterministic=True, **kw
    )
    x = jax.random.normal(jax.random.key(0), (B, T, D), jnp.float32)
    params = module.init(jax.random.key(1), x)["params"]
    return module, params, x


class TestDispatchMath:
    def test_single_expert_equals_dense_ffn(self):
        """E=1, k=1, ample capacity: every token routes to the one expert
        with gate 1.0 — output must equal the plain FFN on that expert."""
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=1, top_k=1, capacity_factor=2.0)
        out = module.apply({"params": params}, x)
        D = x.shape[-1]
        w1 = np.asarray(params["fc/kernel"])[0]
        b1 = np.asarray(params["fc/bias"])[0]
        w2 = np.asarray(params["proj/kernel"])[0]
        b2 = np.asarray(params["proj/bias"])[0]
        xf = np.asarray(x).reshape(-1, D)
        ref = jax.nn.gelu(xf @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(np.asarray(out).reshape(-1, D), ref,
                                   atol=1e-5, rtol=1e-5)

    def test_gates_form_convex_combination(self):
        """With ample capacity, each token's combine weights sum to 1."""
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=4, top_k=2, capacity_factor=8.0)
        # Reach into the math: zero FFN and identity-like check via aux of
        # the output — instead verify through linearity: doubling every
        # expert output doubles the MoE output (combine is linear with
        # weights independent of expert params).
        out1 = module.apply({"params": params}, x)
        params2 = dict(params)
        params2["proj/kernel"] = params["proj/kernel"] * 2.0
        params2["proj/bias"] = params["proj/bias"] * 2.0
        out2 = module.apply({"params": params2}, x)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out1) * 2.0,
                                   atol=1e-5, rtol=1e-5)

    def test_capacity_drops_tokens(self):
        """Tiny capacity: dropped assignments contribute nothing (outputs
        differ from the ample-capacity run, and some tokens see zero
        update)."""
        smp.reset()
        smp.init({"microbatches": 1})
        module_small = DistributedMoE(
            hidden_size=16, intermediate_size=32, num_experts=2, top_k=1,
            capacity_factor=0.25, deterministic=True,
        )
        module_big = DistributedMoE(
            hidden_size=16, intermediate_size=32, num_experts=2, top_k=1,
            capacity_factor=8.0, deterministic=True,
        )
        x = jax.random.normal(jax.random.key(0), (2, 16, 16), jnp.float32)
        params = module_big.init(jax.random.key(1), x)["params"]
        out_small = np.asarray(module_small.apply({"params": params}, x))
        out_big = np.asarray(module_big.apply({"params": params}, x))
        assert not np.allclose(out_small, out_big)
        # Dropped tokens produce exact zeros (residual fall-through).
        zero_rows = np.all(out_small.reshape(-1, 16) == 0.0, axis=-1)
        assert zero_rows.any()

    def test_gradients_flow_to_router_and_experts(self):
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=4, top_k=2)

        def loss(p):
            return jnp.sum(module.apply({"params": p}, x) ** 2)

        grads = jax.grad(loss)(params)
        for key in ("router/kernel", "fc/kernel", "proj/kernel"):
            assert float(jnp.sum(jnp.abs(grads[key]))) > 0.0, key

    def test_top1_router_gets_task_gradient(self):
        """Switch top-1: expert outputs scale by the RAW softmax gate (a
        renormalized g/g == 1 would freeze the router)."""
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=4, top_k=1)

        def loss(p):
            return jnp.sum(module.apply({"params": p}, x) ** 2)

        g = jax.grad(loss)(params)["router/kernel"]
        assert float(jnp.sum(jnp.abs(g))) > 1e-4

    def test_aux_loss_sown_and_bounded(self):
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=4, top_k=2, aux_loss_coef=1.0)
        _, inter = module.apply(
            {"params": params}, x, mutable=["intermediates"]
        )
        aux = moe_aux_losses(inter["intermediates"])
        # Switch aux: minimized at 1.0 under perfect balance; >= 1.0 always.
        assert float(aux) >= 1.0 - 1e-5




def _lm_loss_step():
    """Shared @smp.step LM-loss train step used by the e2e MoE tests."""

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, ids[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        loss = jnp.mean(lse - tgt.astype(jnp.float32))
        model.backward(loss)
        return loss

    return train_step


def _train_moe_lmhead(n_steps, ids, **lmhead_kwargs):
    """Build an MoE LMHead, train n_steps with Adam, return (model, losses)."""
    module = smp.nn.DistributedTransformerLMHead(
        num_attention_heads=2, vocab_size=64,
        pre_layernorm=True, post_layernorm=False, final_layernorm=True,
        attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
        embedding_dropout_prob=0.0, deterministic=True, **lmhead_kwargs,
    )
    model = smp.DistributedModel(module)
    opt = smp.DistributedOptimizer(optax.adam(1e-2), model)
    train_step = _lm_loss_step()
    losses = []
    for _ in range(n_steps):
        out = train_step(model, ids)
        opt.step()
        losses.append(float(out.reduce_mean()))
    return model, losses


class TestExpertParallel:
    def test_ep4_matches_ep1(self):
        """The same params/input produce the same output whether experts
        are sharded over an ep=4 mesh or run unsharded."""
        smp.reset()
        smp.init({"microbatches": 1})
        module, params, x = _mk(num_experts=4, top_k=2, capacity_factor=4.0)
        ref = np.asarray(module.apply({"params": params}, x))

        smp.reset()
        smp.init({"expert_parallel_degree": 4, "ddp": True, "microbatches": 1})
        from smdistributed_modelparallel_tpu.backend.state import state

        with jax.set_mesh(state.mesh):
            out = np.asarray(
                jax.jit(lambda p, x: module.apply({"params": p}, x))(params, x)
            )
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_transformer_layer_moe_trains(self):
        """num_experts on the stacked transformer: full smp.step training
        loop under an ep mesh decreases the loss."""
        smp.reset()
        smp.init({"expert_parallel_degree": 2, "ddp": True, "microbatches": 2})
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        model, losses = _train_moe_lmhead(
            5, ids, num_layers=2, attention_head_size=16, hidden_size=32,
            intermediate_size=64, num_positions=16, causal_mask_size=16,
            num_experts=4,
        )
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        # Expert params exist with the [L, E, ...] stacked layout.
        lay = model.params["transformer"]["seq_layers"]["layer"]["output"]
        assert lay["fc/kernel"].shape[:2] == (2, 4)  # [L, E, D, F]


_LMHEAD_KW = dict(
    num_attention_heads=2, vocab_size=64, num_layers=2,
    attention_head_size=8, hidden_size=16, intermediate_size=32,
    num_positions=16, causal_mask_size=16, num_experts=4,
    pre_layernorm=True, post_layernorm=False, final_layernorm=True,
    attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
    embedding_dropout_prob=0.0, deterministic=True,
)


def _skew_routers(model, bias=3.0):
    """Bias every router kernel toward expert 0 (imbalanced start)."""

    def skew(path, leaf):
        if any(getattr(k, "key", None) == "router/kernel" for k in path):
            return leaf.at[..., 0].add(bias)
        return leaf

    model.params = jax.device_put(
        jax.tree_util.tree_map_with_path(skew, model.params),
        model._param_shardings,
    )


def _measured_aux(model, ids):
    """Sown aux loss of a direct forward on the current params (balance
    metric: aux_loss_coef * E * sum(frac * mean_gate), min at balance)."""
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    _, inter = module.apply(
        {"params": model.params}, ids, mutable=["intermediates"]
    )
    return float(moe_aux_losses(inter["intermediates"]))


class TestAuxLossPlumbing:
    """VERDICT r3 weak #2: the router load-balancing loss must reach the
    differentiated loss through the STANDARD paths (DistributedModel call,
    fill-drain and 1F1B pipeline executors), weighted by the
    moe_aux_loss_weight config key. (The balance tests double as the
    router-gradient probe: weight 0 and weight 20 runs share the init and
    diverge only through the aux term.)"""

    def _one_step_grads(self, cfg_extra, weight, ids):
        smp.reset()
        cfg = {"ddp": True, "microbatches": 2, "moe_aux_loss_weight": weight}
        cfg.update(cfg_extra)
        smp.init(cfg)
        model = smp.DistributedModel(
            smp.nn.DistributedTransformerLMHead(**_LMHEAD_KW)
        )
        train_step = _lm_loss_step()
        train_step(model, ids)
        return jax.device_get(model.grads)

    def test_balance_improves_with_aux_under_dp(self):
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        finals = {}
        for weight in (0.0, 20.0):
            smp.reset()
            smp.init({"ddp": True, "microbatches": 2,
                      "moe_aux_loss_weight": weight})
            model = smp.DistributedModel(
                smp.nn.DistributedTransformerLMHead(**_LMHEAD_KW)
            )
            opt = smp.DistributedOptimizer(optax.adam(1e-2), model)
            train_step = _lm_loss_step()
            train_step(model, ids)  # init
            _skew_routers(model)
            start = _measured_aux(model, ids)
            for _ in range(10):
                train_step(model, ids)
                opt.step()
            finals[weight] = _measured_aux(model, ids)
        assert finals[20.0] < finals[0.0] - 1e-4
        assert finals[20.0] < start

    @pytest.mark.slow
    def test_balance_improves_with_aux_under_pp(self):
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        finals = {}
        for weight in (0.0, 20.0):
            smp.reset()
            smp.init({"pipeline_parallel_degree": 2, "ddp": True,
                      "microbatches": 2, "moe_aux_loss_weight": weight})
            model = smp.DistributedModel(
                smp.nn.DistributedTransformerLMHead(**_LMHEAD_KW)
            )
            opt = smp.DistributedOptimizer(optax.adam(1e-2), model)
            train_step = _lm_loss_step()
            train_step(model, ids)
            _skew_routers(model)
            for _ in range(10):
                train_step(model, ids)
                opt.step()
            finals[weight] = _measured_aux(model, ids)
        assert finals[20.0] < finals[0.0] - 1e-4

    @pytest.mark.slow
    def test_pipeline_grads_match_single_stage_with_aux(self):
        """Both pipeline executors must produce the SAME aux-inclusive
        gradients as the non-pipelined path (proves the 1F1B aux cotangent
        seeding and the fill-drain fold are correct, not just nonzero)."""
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        base = self._one_step_grads({}, 5.0, ids)
        simple = self._one_step_grads(
            {"pipeline_parallel_degree": 2, "pipeline": "simple"}, 5.0, ids
        )
        inter = self._one_step_grads(
            {"pipeline_parallel_degree": 2, "pipeline": "interleaved"},
            5.0, ids,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-5),
            simple, base,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-5),
            inter, base,
        )


@pytest.mark.slow
class TestMoEPipeline:
    def test_moe_under_pipeline_parallelism(self):
        """MoE layers ([L, E, ...] stacked params) slice cleanly into the
        1F1B executor's [S, maxp, ...] stage views and train."""
        smp.reset()
        smp.init({"pipeline_parallel_degree": 2, "ddp": True,
                  "microbatches": 2})
        ids = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        model, losses = _train_moe_lmhead(
            3, ids, num_layers=4, attention_head_size=8, hidden_size=16,
            intermediate_size=32, num_positions=16, causal_mask_size=16,
            num_experts=2,
        )
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        lay = model.params["transformer"]["seq_layers"]["layer"]["output"]
        assert lay["fc/kernel"].shape[:2] == (4, 2)  # [L, E, D, F]
