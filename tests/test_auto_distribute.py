"""Tests for tp-registry auto-distribution (M3c).

Mirrors the reference's tp_registry tier (``test/torch/mpi_hybrid`` TP
module replacement + ``torch/tp_registry.py`` debug weight matching): a
user model with marked submodules gets them swapped for smp.nn versions,
with output parity against the undistributed original.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.state import state


class UserNet(nn.Module):
    dense1: nn.Module
    dense2: nn.Module

    def __call__(self, x):
        return self.dense2(nn.relu(self.dense1(x)))


class TestContextMarking:
    def test_tensor_parallelism_context_swaps_dense(self):
        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True})
        from smdistributed_modelparallel_tpu.nn import DistributedLinear

        with smp.tensor_parallelism():
            d1 = nn.Dense(64)
        d2 = nn.Dense(16)
        net = UserNet(dense1=d1, dense2=d2)
        model = smp.DistributedModel(net)
        assert isinstance(model.module.dense1, DistributedLinear)
        assert isinstance(model.module.dense2, nn.Dense)
        assert model._tp_replaced == ["dense1"]

    def test_model_creation_context(self):
        """smp.model_creation (reference torch/model.py:79): bundles the
        tp-construction marking and the always-delayed param init; dtype
        must agree with the configured compute dtype."""
        import jax.numpy as jnp
        import pytest

        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPValidationError,
        )

        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True, "bf16": True})
        from smdistributed_modelparallel_tpu.nn import DistributedLinear

        with smp.model_creation(tensor_parallelism=True):
            d1 = nn.Dense(64)
        net = UserNet(dense1=d1, dense2=nn.Dense(16))
        model = smp.DistributedModel(net)
        assert isinstance(model.module.dense1, DistributedLinear)
        # dtype agreeing with the config (bf16 or fp32 master) is fine...
        with smp.model_creation(dtype=jnp.bfloat16):
            pass
        with smp.model_creation(dtype=jnp.float32):
            pass
        # ...a conflicting half dtype raises instead of diverging.
        with pytest.raises(SMPValidationError, match="dtype"):
            with smp.model_creation(dtype=jnp.float16):
                pass
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPUnsupportedError,
        )

        with pytest.raises(SMPUnsupportedError, match="not supported"):
            with smp.delay_param_initialization(enabled=False):
                pass
        with smp.delay_param_initialization():
            pass
        # After shutdown the dead config must not validate dtypes.
        smp.shutdown()
        with pytest.raises(SMPValidationError, match="smp.init"):
            with smp.model_creation(dtype=jnp.bfloat16):
                pass

    def test_user_kernel_init_carried_into_distributed_dense(self):
        """VERDICT r3 weak #8: a custom kernel_init on a distributed
        nn.Dense survives the swap (seed-consistent values, not the
        default sharded initializer)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True})
        from smdistributed_modelparallel_tpu.nn import DistributedLinear

        const_init = nn.initializers.constant(0.5)
        with smp.tensor_parallelism():
            d1 = nn.Dense(64, kernel_init=const_init)
        net = UserNet(dense1=d1, dense2=nn.Dense(16))
        model = smp.DistributedModel(net)
        assert isinstance(model.module.dense1, DistributedLinear)
        assert model.module.dense1.kernel_init is const_init
        x = jnp.ones((2, 8))
        from smdistributed_modelparallel_tpu.backend.state import state

        with jax.set_mesh(state.mesh):
            params = jax.jit(model.module.init)(jax.random.key(0), x)["params"]
        from flax.core import meta as flax_meta

        kernel = np.asarray(flax_meta.unbox(params)["dense1"]["kernel"])
        np.testing.assert_array_equal(kernel, 0.5)

    def test_path_marking_swaps(self):
        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True})
        from smdistributed_modelparallel_tpu.nn import DistributedLinear

        net = UserNet(dense1=nn.Dense(64), dense2=nn.Dense(16))
        smp.set_tensor_parallelism("dense2")
        model = smp.DistributedModel(net)
        assert isinstance(model.module.dense2, DistributedLinear)
        assert isinstance(model.module.dense1, nn.Dense)

    def test_partition_context_records_stage(self):
        smp.shutdown()
        smp.init({"pipeline_parallel_degree": 2, "ddp": True})
        with smp.partition(1):
            d1 = nn.Dense(8)
        net = UserNet(dense1=d1, dense2=nn.Dense(8))
        model = smp.DistributedModel(net)
        assert model.module_manager.get_manual_partitions().get("dense1") == 1

    def test_output_parity_after_distribution(self):
        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True})
        net = UserNet(dense1=nn.Dense(64), dense2=nn.Dense(16))
        smp.set_tensor_parallelism("dense1")
        smp.set_tensor_parallelism("dense2")
        model = smp.DistributedModel(net)
        x = jax.random.normal(jax.random.key(0), (4, 16))

        # Distributed apply (params initialized through the model path).
        mod = model.module
        params = meta.unbox(mod.init(jax.random.key(1), x)["params"])
        with jax.set_mesh(state.mesh):
            out = jax.jit(lambda p, x: mod.apply({"params": p}, x))(params, x)

        # Undistributed reference with the same weights.
        ref = net.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_embed_registration(self):
        smp.shutdown()
        smp.init({"tensor_parallel_degree": 4, "ddp": True})
        from smdistributed_modelparallel_tpu.nn import DistributedEmbedding

        class EmbNet(nn.Module):
            emb: nn.Module

            def __call__(self, ids):
                return self.emb(ids)

        with smp.tensor_parallelism():
            e = nn.Embed(64, 16)
        model = smp.DistributedModel(EmbNet(emb=e))
        assert isinstance(model.module.emb, DistributedEmbedding)
