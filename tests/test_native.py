"""Native host-runtime tests: message bus (N2 parity) + timeline (N5).

The multi-process tests spawn raw OS processes that load libsmptpu.so via
ctypes and talk over real TCP on 127.0.0.1 — the same cluster-free strategy
the reference uses for its backend tests (single-node MPI with N processes,
SURVEY §4), with the bus's endpoint list standing in for MPI's rendezvous.
"""

import json
import multiprocessing as mp
import pickle

import pytest

from smdistributed_modelparallel_tpu.backend import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _make_bus():
    lib = native.load()
    bus = native.MessageBus(lib)
    return bus


# ---------------------------------------------------------------------------
# single-process (self-send) behavior


def test_self_send_roundtrip():
    bus = _make_bus()
    port = bus.listen(0)
    assert port > 0
    bus.connect(0, 1, [f"127.0.0.1:{port}"])
    payload = pickle.dumps({"hello": [1, 2, 3]})
    bus.send_bytes(0, payload, tx=7)
    assert bus.poll(0, 7)
    assert not bus.poll(0, 8)
    out = bus.recv_bytes(0, 7, timeout_ms=1000)
    assert pickle.loads(out) == {"hello": [1, 2, 3]}
    assert not bus.poll(0, 7)  # consumed
    bus.shutdown()


def test_recv_timeout_and_clean():
    bus = _make_bus()
    port = bus.listen(0)
    bus.connect(0, 1, [f"127.0.0.1:{port}"])
    with pytest.raises(TimeoutError):
        bus.recv_bytes(0, 99, timeout_ms=50)
    bus.send_bytes(0, b"x", tx=5)
    bus.clean(0, 5)
    assert not bus.poll(0, 5)
    bus.shutdown()


def test_out_of_order_transactions():
    bus = _make_bus()
    port = bus.listen(0)
    bus.connect(0, 1, [f"127.0.0.1:{port}"])
    for tx in (3, 1, 2):
        bus.send_bytes(0, str(tx).encode(), tx=tx)
    # Retrieval keyed by tx, independent of arrival order.
    assert bus.recv_bytes(0, 2, 1000) == b"2"
    assert bus.recv_bytes(0, 3, 1000) == b"3"
    assert bus.recv_bytes(0, 1, 1000) == b"1"
    bus.shutdown()


# ---------------------------------------------------------------------------
# true multi-process TCP mesh


def _worker(rank, world, ports, conn, payload_kb):
    from smdistributed_modelparallel_tpu.backend import native as nat

    lib = nat.load()
    bus = nat.MessageBus(lib)
    port = bus.listen(ports[rank])
    assert port == ports[rank]
    endpoints = [f"127.0.0.1:{p}" for p in ports]
    bus.connect(rank, world, endpoints)
    try:
        # Ring: send a tagged blob to (rank+1)%world, receive from left.
        blob = bytes([rank]) * (payload_kb * 1024)
        bus.send_bytes((rank + 1) % world, blob, tx=101)
        got = bus.recv_bytes((rank - 1) % world, 101, timeout_ms=30000)
        assert got == bytes([(rank - 1) % world]) * (payload_kb * 1024)

        # Many interleaved transactions to one peer (0 gathers).
        for tx in range(10):
            bus.send_bytes(0, f"{rank}:{tx}".encode(), tx=1000 + tx)
        if rank == 0:
            for src in range(world):
                for tx in range(10):
                    msg = bus.recv_bytes(src, 1000 + tx, timeout_ms=30000)
                    assert msg == f"{src}:{tx}".encode()

        # Subgroup barrier (even ranks), then full barrier, repeated.
        evens = [r for r in range(world) if r % 2 == 0]
        for _ in range(3):
            if rank in evens:
                bus.barrier(evens, timeout_ms=30000)
            bus.barrier(list(range(world)), timeout_ms=30000)
        conn.send(("ok", rank))
    except Exception as e:  # pragma: no cover - surfaced in parent assert
        conn.send(("err", f"rank {rank}: {type(e).__name__}: {e}"))
    finally:
        bus.shutdown()


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


@pytest.mark.parametrize("world", [2, 4])
def test_multiprocess_mesh(world):
    ctx = mp.get_context("spawn")
    ports = _free_ports(world)
    parents, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_worker, args=(rank, world, ports, child, 64), daemon=True
        )
        p.start()
        parents.append(parent)
        procs.append(p)
    results = []
    for parent, p in zip(parents, procs):
        assert parent.poll(120), "worker timed out"
        results.append(parent.recv())
        p.join(timeout=30)
    errs = [r for r in results if r[0] != "ok"]
    assert not errs, errs


# ---------------------------------------------------------------------------
# receive-side dead-peer detection (satellite of the recovery supervisor):
# a wait blocking on a peer whose link the bus has marked dead raises a
# typed SMPPeerLost immediately instead of burning the full timeout.


def test_send_raw_and_drain_bytes_self():
    bus = _make_bus()
    port = bus.listen(0)
    bus.connect(0, 1, [f"127.0.0.1:{port}"])
    assert bus.send_raw(0, b"1:7", -4) == 0
    assert bus.send_raw(0, b"2:8", -4) == 0
    assert bus.drain_bytes(0, -4) == [b"1:7", b"2:8"]
    assert bus.drain_bytes(0, -4) == []
    assert not bus.peer_down(0)
    bus.shutdown()


def _dead_peer_victim(rank, world, ports, conn):
    """Rank 0: receives one frame from rank 1 (establishing the inbound
    connection + its source identity), then expects rank 1's death to
    surface as SMPPeerLost on both a recv wait and a group barrier —
    quickly, not after the 30s timeouts."""
    import time as _time

    from smdistributed_modelparallel_tpu.backend import native as nat
    from smdistributed_modelparallel_tpu.utils.exceptions import SMPPeerLost

    lib = nat.load()
    bus = nat.MessageBus(lib)
    bus.listen(ports[rank])
    bus.connect(rank, world, [f"127.0.0.1:{p}" for p in ports])
    try:
        assert bus.recv_bytes(1, 500, timeout_ms=30000) == b"hello"
        # Peer dies now (no second message ever sent). The recv must fail
        # typed and fast once the EOF lands, and so must a barrier.
        t0 = _time.monotonic()
        try:
            bus.recv_bytes(1, 501, timeout_ms=30000)
            conn.send(("err", "recv returned instead of raising"))
            return
        except SMPPeerLost as e:
            assert e.peer == 1, e.peer
        recv_s = _time.monotonic() - t0
        t0 = _time.monotonic()
        try:
            bus.barrier([0, 1], timeout_ms=30000)
            conn.send(("err", "barrier returned instead of raising"))
            return
        except SMPPeerLost as e:
            assert e.peer == 1, e.peer
        barrier_s = _time.monotonic() - t0
        assert bus.peer_down(1)
        # "Immediately": well under the 30s waits (EOF + one probe slice).
        assert recv_s < 15 and barrier_s < 15, (recv_s, barrier_s)
        conn.send(("ok", rank))
    except Exception as e:  # pragma: no cover - surfaced in parent
        conn.send(("err", f"rank {rank}: {type(e).__name__}: {e}"))
    finally:
        bus.shutdown()


def _dead_peer_casualty(rank, world, ports, conn):
    """Rank 1: send one frame (so rank 0 learns this connection's source),
    then die hard — os._exit with no bus shutdown, like a SIGKILL."""
    import os as _os
    import time as _time

    from smdistributed_modelparallel_tpu.backend import native as nat

    lib = nat.load()
    bus = nat.MessageBus(lib)
    bus.listen(ports[rank])
    bus.connect(rank, world, [f"127.0.0.1:{p}" for p in ports])
    bus.send_bytes(0, b"hello", 500)
    _time.sleep(1.0)  # let the frame land before dying
    conn.send(("ok", rank))
    _os._exit(0)  # hard exit: kernel closes the sockets, no goodbye


def test_recv_and_barrier_raise_peer_lost_on_dead_peer():
    ctx = mp.get_context("spawn")
    ports = _free_ports(2)
    targets = [_dead_peer_victim, _dead_peer_casualty]
    parents, procs = [], []
    for rank in range(2):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=targets[rank], args=(rank, 2, ports, child), daemon=True
        )
        p.start()
        parents.append(parent)
        procs.append(p)
    results = []
    for parent, p in zip(parents, procs):
        assert parent.poll(120), "worker timed out"
        results.append(parent.recv())
        p.join(timeout=30)
    errs = [r for r in results if r[0] != "ok"]
    assert not errs, errs


# ---------------------------------------------------------------------------
# communicator integration (single process)


def test_communicator_send_recv_single_process(tmp_path):
    import smdistributed_modelparallel_tpu as smp

    smp.reset()
    smp.init({"microbatches": 1})
    smp.send({"k": 1}, dest=0)
    assert smp.recv_from(0) == {"k": 1}
    # In-order per-pair sequencing.
    smp.send("a", dest=0)
    smp.send("b", dest=0)
    assert smp.recv_from(0) == "a"
    assert smp.recv_from(0) == "b"
    # Group barriers are no-ops single-process but must not raise.
    smp.barrier(smp.TP_GROUP)
    smp.pp_barrier()
    smp.dp_barrier()


# ---------------------------------------------------------------------------
# native timeline


def test_native_timeline_roundtrip(tmp_path):
    lib = native.load()
    path = str(tmp_path / "trace.json")
    tl = native.NativeTimeline(lib, path)
    tl.start_step(0)
    tl.record_event("fwd_mb0", 10.0, 25.5, microbatch=0)
    tl.record_event("bwd_mb0", 30.0, 55.0, microbatch=0, track="bwd")
    tl.record_instant("step_0_end", 60.0)
    tl.end_step(0)
    assert tl.event_count() == 3
    assert tl.flush(pid=42) == 3
    with open(path) as f:
        data = json.load(f)
    events = data["traceEvents"]
    assert len(events) == 3
    by_name = {e["name"]: e for e in events}
    assert by_name["fwd_mb0"]["dur"] == pytest.approx(15.5)
    assert by_name["fwd_mb0"]["args"]["microbatch"] == 0
    assert by_name["fwd_mb0"]["args"]["step"] == 0
    assert by_name["bwd_mb0"]["tid"] == "bwd"
    assert by_name["step_0_end"]["ph"] == "i"
    assert all(e["pid"] == 42 for e in events)
    tl.close()


def test_python_timeline_uses_native(tmp_path, monkeypatch):
    from smdistributed_modelparallel_tpu.utils.timeline import Timeline

    path = str(tmp_path / "t.json")
    monkeypatch.setenv("SMP_TIMELINE_PATH", path)
    tl = Timeline()
    assert tl.enabled
    assert tl._native is not None
    tl.start_step(3)
    with tl.span("phase", microbatch=1):
        pass
    tl.end_step(3)
    tl.flush()
    with open(path) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "phase" in names and "step_3_begin" in names
