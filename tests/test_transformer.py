"""Tests for the smp.nn Distributed transformer family (M3b).

Mirrors the reference's hybrid-parallel parity tier
(``test/torch/mpi_hybrid/test_gpt2.py``, ``test_final_loss_equal.py``): the
same model is run without parallelism and with tp / pp x tp, and outputs /
losses are compared.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.core import meta

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.nn.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedTransformer,
    DistributedTransformerLayer,
    DistributedTransformerLMHead,
    apply_rotary,
)

TINY = dict(
    num_layers=4, num_attention_heads=4, attention_head_size=8,
    hidden_size=32, intermediate_size=64, vocab_size=96, num_positions=32,
    causal_mask_size=32, pre_layernorm=True, post_layernorm=False,
    final_layernorm=True, attention_dropout_prob=0.0,
    hidden_dropout_prob=0.0, embedding_dropout_prob=0.0,
)


def _forward(cfg, model_kwargs=None, seed=0):
    smp.shutdown()
    smp.init(cfg)
    kwargs = dict(TINY)
    kwargs.update(model_kwargs or {})
    m = DistributedTransformerLMHead(**kwargs)
    ids = jax.random.randint(jax.random.key(seed), (4, 16), 0, kwargs["vocab_size"])
    params = meta.unbox(m.init(jax.random.key(1), ids)["params"])
    with jax.set_mesh(state.mesh):
        out = jax.jit(lambda p, i: m.apply({"params": p}, i))(params, ids)
    return np.asarray(out)


class TestLMHeadTPParity:
    def test_speed_layout(self):
        base = _forward({})
        tp = _forward({"tensor_parallel_degree": 4, "ddp": True})
        np.testing.assert_allclose(base, tp, atol=2e-5)

    def test_memory_layout(self):
        base = _forward({})
        tp = _forward(
            {"tensor_parallel_degree": 4, "ddp": True, "optimize": "memory"}
        )
        np.testing.assert_allclose(base, tp, atol=2e-5)

    def test_distributed_embedding(self):
        base = _forward({}, {"distribute_embedding": True})
        tp = _forward(
            {"tensor_parallel_degree": 4, "ddp": True},
            {"distribute_embedding": True},
        )
        np.testing.assert_allclose(base, tp, atol=2e-5)

    def test_prescaled_batch(self):
        base = _forward({})
        tp = _forward(
            {"tensor_parallel_degree": 4, "ddp": True, "prescaled_batch": True}
        )
        np.testing.assert_allclose(base, tp, atol=2e-5)


class TestLMHeadVariants:
    def test_untied_head_and_rotary(self):
        out = _forward({}, {
            "tie_input_output_embedding": False,
            "use_positional_embedding": False,
            "rotary_dim": 4,
        })
        assert out.shape == (4, 16, 96)
        assert np.isfinite(out).all()

    def test_neox_rotary_differs_from_gptj(self):
        q = jax.random.normal(jax.random.key(0), (1, 8, 2, 8))
        k = jax.random.normal(jax.random.key(1), (1, 8, 2, 8))
        qj, _ = apply_rotary(q, k, 8, neox_style=False)
        qn, _ = apply_rotary(q, k, 8, neox_style=True)
        assert float(np.max(np.abs(np.asarray(qj) - np.asarray(qn)))) > 1e-3

    def test_parallel_attn_output(self):
        out = _forward({}, {"parallel_attn_output": True})
        assert np.isfinite(out).all()

    def test_attention_layers_type_local_global(self):
        out = _forward({}, {
            "attention_layers_type": ("global", "local", "global", "local"),
            "window_size": 4,
        })
        assert np.isfinite(out).all()

    def test_scale_attn_by_layer_idx(self):
        plain = _forward({})
        scaled = _forward({}, {"scale_attn_by_layer_idx": True})
        assert float(np.max(np.abs(plain - scaled))) > 1e-5


class TestCrossAttention:
    def test_encoder_decoder_block(self):
        smp.shutdown()
        smp.init({"tensor_parallel_degree": 2, "ddp": True})
        layer = DistributedTransformerLayer(
            num_attention_heads=4, attention_head_size=8, hidden_size=32,
            intermediate_size=64, add_cross_attention=True,
            causal_mask_size=32, pre_layernorm=True, post_layernorm=False,
            attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
        )
        x = jax.random.normal(jax.random.key(0), (2, 8, 32))
        enc = jax.random.normal(jax.random.key(1), (2, 12, 32))
        params = meta.unbox(
            layer.init(jax.random.key(2), x, cross_states=enc)["params"]
        )
        assert "crossattention" in params
        with jax.set_mesh(state.mesh):
            out = jax.jit(
                lambda p, x, e: layer.apply({"params": p}, x, cross_states=e)
            )(params, x, enc)
        assert np.isfinite(np.asarray(out)).all()


class TestStepIntegration:
    def _train(self, cfg, steps=3):
        smp.shutdown()
        smp.init(cfg)
        m = DistributedTransformerLMHead(**TINY)
        model = smp.DistributedModel(m)
        opt = smp.DistributedOptimizer(optax.sgd(0.1), model)

        @smp.step
        def train_step(model, ids):
            logits = model(ids)
            loss = jnp.mean(vocab_parallel_cross_entropy(logits[:, :-1], ids[:, 1:]))
            model.backward(loss)
            return loss

        ids = jax.random.randint(jax.random.key(0), (8, 16), 0, 96)
        losses = []
        for _ in range(steps):
            out = train_step(model, ids)
            opt.step()
            losses.append(float(out.reduce_mean()))
        return losses

    def test_tp_loss_parity_and_decrease(self):
        base = self._train({"microbatches": 4})
        tp = self._train({"microbatches": 4, "tensor_parallel_degree": 2, "ddp": True})
        np.testing.assert_allclose(base, tp, atol=1e-4)
        assert base[-1] < base[0]

    def test_pp_tp_loss_parity(self):
        base = self._train({"microbatches": 4})
        pptp = self._train({
            "microbatches": 4, "tensor_parallel_degree": 2,
            "pipeline_parallel_degree": 2, "ddp": True,
        })
        np.testing.assert_allclose(base, pptp, atol=1e-4)


class TestTrainEvalMode:
    def test_dropout_follows_model_mode(self):
        smp.shutdown()
        smp.init({"microbatches": 1})
        kwargs = dict(TINY)
        kwargs["hidden_dropout_prob"] = 0.5
        m = DistributedTransformerLMHead(**kwargs)
        model = smp.DistributedModel(m)

        @smp.step
        def fwd(model, ids):
            return model(ids)

        ids = jax.random.randint(jax.random.key(0), (2, 16), 0, 96)
        model.eval()
        e1 = np.asarray(fwd(model, ids).concat())
        e2 = np.asarray(fwd(model, ids).concat())
        np.testing.assert_allclose(e1, e2)  # dropout off in eval
        model.train()
        t1 = np.asarray(fwd(model, ids).concat())
        t2 = np.asarray(fwd(model, ids).concat())
        assert float(np.max(np.abs(t1 - t2))) > 1e-6  # dropout active


class TestDistributedTransformerStandalone:
    def test_stack_runs_and_pipelines(self):
        smp.shutdown()
        smp.init({"pipeline_parallel_degree": 2, "microbatches": 2, "ddp": True})
        m = DistributedTransformer(
            num_layers=4, num_attention_heads=2, attention_head_size=8,
            hidden_size=16, intermediate_size=32,
            pre_layernorm=True, post_layernorm=False,
            attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
        )
        model = smp.DistributedModel(m)

        @smp.step
        def fwd_step(model, x):
            out = model(x)
            return out

        x = jax.random.normal(jax.random.key(0), (4, 8, 16))
        out = fwd_step(model, x)
        stacked = out.concat()
        assert stacked.shape == (4, 8, 16)
        assert np.isfinite(np.asarray(stacked)).all()
