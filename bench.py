"""Benchmark: GPT-2 training throughput (tokens/sec/chip) with MFU accounting.

Runs on whatever accelerator is available (the driver provides one real TPU
chip). Single-chip benchmark = BASELINE config #1 (GPT-2 124M); the
north-star PP4xTP2 GPT-2 1.5B configuration needs a v4-32 and is exercised
multi-chip via ``__graft_entry__.dryrun_multichip``.

Methodology notes:
- Timing forces a device->host readback per boundary; through this image's
  tunneled TPU relay, ``block_until_ready`` does not reliably block, so
  async-dispatch timing under-measures by orders of magnitude.
- ``vs_baseline``: the reference ships no numbers in-tree (BASELINE.md), so
  the baseline is a hand-written plain-JAX train step of the same model,
  same microbatching, measured in the same run — the framework's "without
  smp" comparison, mirroring the reference's with/without-SMP parity tests.
  1.0 means zero framework overhead; >1.0 means faster than plain JAX.
- MFU = model matmul FLOPs (analytic; full, non-causal attention scores, as
  executed) / step time / chip peak bf16 FLOPs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import dataclasses
import functools
import json
import os
import sys
import time


def _chip_peak_tflops(device):
    """Peak dense bf16 TFLOP/s of ``device`` — single source of truth in
    utils/profiling.py (spec table by device kind, SMP_PEAK_TFLOPS
    override). Imported lazily: bench must not touch the package before
    the device-probe logic has decided the platform."""
    from smdistributed_modelparallel_tpu.utils.profiling import device_peaks

    flops, _ = device_peaks(device)
    return flops / 1e12 if flops else None


def _model_flops_per_step(n_layers, d_model, vocab, batch, seq):
    """Analytic train-step matmul FLOPs (fwd*3 for fwd+bwd)."""
    tokens = batch * seq
    per_layer = 2 * tokens * 12 * d_model * d_model   # qkv+proj+mlp fwd
    attn = 4 * tokens * seq * d_model                 # QK^T + PV fwd (full scores)
    head = 2 * tokens * d_model * vocab               # tied lm head fwd
    return 3 * (n_layers * (per_layer + attn) + head)


def _median(xs):
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _readback(x):
    import numpy as np

    return float(np.asarray(x.ravel()[0] if hasattr(x, "ravel") else x))


def _no_accelerator_reason():
    """A reason string when NO accelerator can ever appear in this process
    — or None when one might.

    The probe-retry window below exists for a flaky-but-configured TPU
    tunnel. When the environment pins the host platform
    (``JAX_PLATFORMS=cpu``) or carries no TPU configuration at all (no
    ``TPU_*``/``CLOUD_TPU_*``/``PJRT_*`` env, no libtpu, no PJRT device
    plugin installed), every probe is guaranteed to resolve the same way,
    and burning the full retry window on 150 s hung probes (BENCH_r05:
    rc=3 after 8 of them) buys nothing: fail fast into the CPU smoke
    block instead.
    """
    plats = os.environ.get("JAX_PLATFORMS", "")
    names = {p.strip().lower() for p in plats.split(",") if p.strip()}
    if names and names <= {"cpu"}:
        return "JAX_PLATFORMS=cpu pins the host platform"
    if any(k.startswith(("TPU_", "CLOUD_TPU_", "PJRT_")) for k in os.environ):
        return None
    try:
        import importlib.util
        import pkgutil

        if importlib.util.find_spec("libtpu") is not None:
            return None
        spec = importlib.util.find_spec("jax_plugins")
        if spec is not None and spec.submodule_search_locations:
            if any(pkgutil.iter_modules(list(spec.submodule_search_locations))):
                return None
    except Exception:
        return None  # cannot prove absence -> keep the retry window
    return ("no TPU tunnel/plugin configuration present "
            "(no TPU_*/PJRT_* env, no libtpu, no jax_plugins entries)")


def _wait_for_devices(probe_every=None, window=None, probe_timeout=150):
    """Bounded probe-retry for the flaky tunneled TPU backend.

    The tunnel has twice wedged exactly during the driver's bench window
    (BENCH_r03/BENCH_r04: rc=3 after a single 180 s probe). Instead of
    forfeiting the round's only hardware evidence to a transient wedge,
    poll ``jax.devices()`` in short-lived SUBPROCESSES (a wedged in-process
    probe blocks the C++ backend forever and cannot be retried) every
    ~2 min for up to ~20 min, then give up with the retry log on stderr.

    Env overrides: SMP_BENCH_PROBE_EVERY / SMP_BENCH_PROBE_WINDOW (seconds).
    """
    import subprocess

    if probe_every is None:
        probe_every = int(os.environ.get("SMP_BENCH_PROBE_EVERY", 120))
    if window is None:
        window = int(os.environ.get("SMP_BENCH_PROBE_WINDOW", 1200))
    # A wedged probe hangs until its subprocess timeout; cap it by the
    # window so short windows (tests, impatient drivers) expire promptly.
    probe_timeout = min(probe_timeout, max(window, 5))
    deadline = time.time() + window
    attempt = 0
    first_fast_fail = None
    while True:
        attempt += 1
        t0 = time.time()
        # Cap each probe by the REMAINING window too: a probe that wedges
        # just before the deadline must not extend the total wait to
        # window + probe_timeout (ADVICE round 5).
        this_timeout = max(min(probe_timeout, deadline - t0), 5)
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; assert len(jax.devices()) > 0"],
                timeout=this_timeout,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            )
            ok = r.returncode == 0
            err = r.stderr.decode(errors="replace").strip().splitlines()
            why = f"rc={r.returncode}" + (
                ": " + " | ".join(err[-3:]) if not ok and err else "")
        except subprocess.TimeoutExpired:
            ok, why = False, f"probe hung >{this_timeout:.0f}s (wedged tunnel?)"
        if ok:
            if attempt > 1:
                sys.stderr.write(
                    f"bench: device probe succeeded on attempt {attempt} "
                    f"after {time.time() - deadline + window:.0f}s.\n")
            return
        elapsed = time.time() - t0
        fast_fail = not why.startswith("probe hung") and elapsed < 20
        remaining = deadline - time.time()
        sys.stderr.write(
            f"bench: device probe attempt {attempt} failed ({why}); "
            f"{max(remaining, 0):.0f}s left in retry window.\n")
        sys.stderr.flush()
        # Fast nonzero exits could be deterministic (import error, broken
        # config) OR a transient outage that raises instead of hangs
        # (connection refused while the tunnel restarts). Retry them on a
        # short interval; give up rc=4 only once they have persisted
        # CONSECUTIVELY for 5 min — long enough for a tunnel restart, far
        # short of burning the whole window on a missing module. Any hang
        # or slow failure in between resets the fast-fail clock.
        if fast_fail:
            if first_fast_fail is None:
                first_fast_fail = t0
            threshold = min(window, 300)
            if time.time() - first_fast_fail >= threshold:
                sys.stderr.write(
                    f"bench: device probe failed fast for {threshold}s+ "
                    f"({why}) — deterministic failure, not retrying "
                    "(rc=4).\n")
                sys.stderr.flush()
                os._exit(4)
        else:
            first_fast_fail = None
        if remaining <= 0:
            sys.stderr.write(
                f"bench: no accelerator after {attempt} probes over "
                f"{window}s — giving up (rc=3).\n")
            sys.stderr.flush()
            os._exit(3)
        interval = 30 if fast_fail else probe_every
        time.sleep(max(0.0, min(interval - elapsed, remaining)))


def _devices_or_die(timeout_s=180):
    """jax.devices() with a watchdog: the tunneled TPU backend can wedge so
    hard that devices() never returns — fail with a diagnostic instead of
    hanging the driver. os._exit because the stuck thread is in C++."""
    import threading

    out = {}

    def probe():
        try:
            import jax

            out["devices"] = jax.devices()
        except BaseException as e:  # report, don't die silently in a thread
            out["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        sys.stderr.write(
            f"bench: jax.devices() did not return within {timeout_s}s — "
            "accelerator backend unreachable (wedged TPU tunnel?).\n"
        )
        sys.stderr.flush()
        os._exit(3)
    if "error" in out:
        sys.stderr.write(
            f"bench: accelerator backend failed to initialize: {out['error']!r}\n"
        )
        sys.stderr.flush()
        os._exit(4)
    return out["devices"]


def _health_overhead_probe(train_step, model, optimizer, ids, iters,
                           deadline):
    """SMP_BENCH_HEALTH_PROBE=1: measure the cheap-sentinel overhead.

    Same interleaved-A/B methodology as the main timing (off/cheap blocks
    alternate, medians of 3 — comparing one later cheap block against the
    earlier off median would fold clock/thermal drift straight into the
    overhead number). Both step programs stay cached across the env flips
    (the step cache keys on the health mode), so only the first cheap
    block pays a compile. The target is <2% (BENCH_NOTES.md); a miss logs
    a warning but never fails the bench. Respects the remaining probe
    window (``deadline``): skipped (or cut short between block pairs)
    rather than allowed to overrun the driver's cap.
    """
    if deadline - time.time() < 120:
        sys.stderr.write(
            f"bench: skipping health-overhead probe "
            f"({deadline - time.time():.0f}s left in window < 120s floor).\n")
        return
    prev = os.environ.get("SMP_HEALTH_CHECK")

    def set_mode(mode):
        if mode is None:
            os.environ.pop("SMP_HEALTH_CHECK", None)
        else:
            os.environ["SMP_HEALTH_CHECK"] = mode

    def timed_block(mode):
        set_mode(mode)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = train_step(model, ids)
            optimizer.step()
        _readback(out.reduce_mean())
        return (time.perf_counter() - t0) / iters

    off_times, cheap_times = [], []
    try:
        set_mode("cheap")
        out = train_step(model, ids)          # one-time recompile under cheap
        optimizer.step()
        _readback(out.reduce_mean())
        for _ in range(3):
            off_times.append(timed_block(None))
            cheap_times.append(timed_block("cheap"))
            if time.time() > deadline:
                sys.stderr.write(
                    "bench: health probe hit the window deadline; using the "
                    f"{len(cheap_times)} block pair(s) measured so far.\n")
                break
    finally:
        set_mode(prev)
    off_dt = sorted(off_times)[len(off_times) // 2]
    cheap_dt = sorted(cheap_times)[len(cheap_times) // 2]
    overhead = cheap_dt / off_dt - 1.0
    ok = overhead < 0.02
    sys.stderr.write(json.dumps({
        "component": "health_overhead",
        "off_ms": round(off_dt * 1e3, 3),
        "cheap_ms": round(cheap_dt * 1e3, 3),
        "overhead_frac": round(overhead, 4),
        "blocks": len(cheap_times),
        "ok": ok,
    }) + "\n")
    if not ok:
        sys.stderr.write(
            f"bench: WARNING cheap health mode cost {overhead * 100:.1f}% "
            "step time (target < 2%).\n")
    sys.stderr.flush()


def _pipeline_interleave_probe(deadline):
    """SMP_BENCH_PIPELINE_PROBE=1: 3-way pipeline-schedule A/B at pp=2,
    mb=8 — plain 1F1B (v=1) vs interleaved (v=2) vs zero-bubble ZB-H1
    (v=2, split backward).

    Same interleaved-pairs methodology as the health probe (alternating
    blocks, medians of up to 3 rounds, window-capped) with one forced
    difference: the variants cannot share a compiled program — the
    schedule kind and virtual degree change the partitioning and the
    baked schedule — so each block re-inits the framework and pays its
    compile during the per-block warmup steps, OUTSIDE the timed region.
    Emits one stderr JSON line {"component": "pipeline_schedule",
    schedules: {name: ms}, speedup_v2, speedup_zb, schedule_best, ...}
    (plus the legacy v1_ms/v2_ms/speedup fields); the pass criterion is a
    TPU criterion recorded in BENCH_NOTES.md (the CPU smoke number is
    compile/reduce-bound and only proves the plumbing). Never fails the
    bench.
    """
    import jax

    if len(jax.devices()) < 2:
        sys.stderr.write(
            "bench: skipping pipeline probe (needs >= 2 devices for "
            "pp=2).\n")
        return
    if deadline - time.time() < 240:
        sys.stderr.write(
            f"bench: skipping pipeline probe ({deadline - time.time():.0f}s "
            "left in window < 240s floor).\n")
        return
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.transformer_lm import (
        TransformerLM,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    n_layers, d_model, n_heads, seq, batch, vocab = (
        (8, 512, 8, 512, 16, 8192) if on_tpu else (4, 32, 2, 16, 8, 64)
    )
    iters = 10 if on_tpu else 3

    def build(v, schedule="interleaved"):
        smp.reset()
        smp.init({
            "pipeline_parallel_degree": 2, "microbatches": 8, "ddp": True,
            "virtual_pipeline_degree": v, "bf16": bool(on_tpu),
            "pipeline": schedule,
        })
        model = smp.DistributedModel(TransformerLM(
            vocab_size=vocab, max_len=seq, d_model=d_model,
            n_layers=n_layers, n_heads=n_heads,
        ))
        optimizer = smp.DistributedOptimizer(optax.sgd(1e-3), model)
        ids = jax.random.randint(jax.random.key(0), (batch, seq), 0, vocab)

        @smp.step
        def train_step(model, b):
            logits = model(b)
            lg = logits[:, :-1].astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            tgt = jnp.take_along_axis(lg, b[:, 1:, None], axis=-1)[..., 0]
            loss = jnp.mean(lse - tgt)
            model.backward(loss)
            return loss

        return model, optimizer, train_step, ids

    def timed_block(v, schedule="interleaved"):
        model, optimizer, train_step, ids = build(v, schedule)
        out = None
        for _ in range(2):      # warmup: compile + first dispatch
            out = train_step(model, ids)
            optimizer.step()
        _readback(out.reduce_mean())
        t0 = time.perf_counter()
        for _ in range(iters):
            out = train_step(model, ids)
            optimizer.step()
        _readback(out.reduce_mean())
        dt = (time.perf_counter() - t0) / iters
        # FLOP-weighted remat fraction + fingerprint from the compiled
        # program's X-ray: the schedule-level recompute cost of each
        # variant becomes ledger-verifiable on CPU (the wall-clock A/B
        # needs a chip; the census does not).
        remat = fp = None
        try:
            from smdistributed_modelparallel_tpu.utils import hlo_audit

            audit = hlo_audit.of_step_function(train_step)
            if audit is not None:
                remat = audit.remat.get("fraction")
                fp = audit.fingerprint_hash
        except Exception as e:  # the audit must never kill the probe
            sys.stderr.write(f"bench: pipeline-probe audit skipped ({e!r})\n")
        return dt, remat, fp

    # Variant order inside a round keeps the A/B/C blocks interleaved so
    # clock/thermal drift hits all three schedules alike.
    variants = (("1f1b", 1, "interleaved"),
                ("interleaved_v2", 2, "interleaved"),
                ("zb_h1", 2, "zero_bubble"))
    times = {name: [] for name, _, _ in variants}
    remats = {}
    fps = {}
    for _ in range(3):
        for name, v, schedule in variants:
            dt, remat, fp = timed_block(v, schedule)
            times[name].append(dt)
            if remat is not None:
                remats[name] = remat
            if fp is not None:
                fps[name] = fp
        if time.time() > deadline:
            sys.stderr.write(
                "bench: pipeline probe hit the window deadline; using the "
                f"{len(times['zb_h1'])} block round(s) measured so far.\n")
            break
    smp.reset()

    med = {name: _median(ts) for name, ts in times.items()}
    best = min(med, key=med.get)
    result = {
        "component": "pipeline_schedule",
        "pp": 2, "microbatches": 8,
        "schedules": {name: round(dt * 1e3, 3) for name, dt in med.items()},
        "schedule_best": best,
        # Per-schedule FLOP-weighted remat fraction + program fingerprint
        # from the compile-time X-ray (scripts/perf_ledger.py schema-checks
        # and renders these; empty dicts when no AOT executable exists).
        "remat_fraction": remats,
        "fingerprints": fps,
        "speedup_v2": round(med["1f1b"] / med["interleaved_v2"], 4),
        "speedup_zb": round(med["1f1b"] / med["zb_h1"], 4),
        # Legacy fields (round <= 5 consumers of the v1-vs-v2 probe).
        "v1_ms": round(med["1f1b"] * 1e3, 3),
        "v2_ms": round(med["interleaved_v2"] * 1e3, 3),
        "speedup": round(med["1f1b"] / med["interleaved_v2"], 4),
        "blocks": len(times["zb_h1"]),
        "on_tpu": on_tpu,
    }
    sys.stderr.write(json.dumps(result) + "\n")
    sys.stderr.flush()
    return result


def _zero_probe(deadline):
    """SMP_BENCH_ZERO_PROBE=1: zero2d vs zero3 A/B at full-rdp data
    parallelism — per-step wall time plus the memory story (per-device
    parameter bytes from the realized shardings, program argument/temp
    bytes from the X-ray memory breakdown).

    zero2d is the GSPMD-scheduled baseline (persistence-thresholded param
    sharding, implicit collectives); zero3 adds the explicit machinery
    this probe is for: just-in-time per-layer gathers, the double-buffered
    prefetch registers, and the bucketed reduce-scatter grad path. Same
    interleaved-blocks methodology as the pipeline probe (each block
    re-inits — the sharding mode changes the compiled program — and pays
    its compile in warmup, outside the timed region). Emits one stderr
    JSON line {"component": "zero_probe", zero2d_ms, zero3_ms, speedup,
    ...} and returns the dict for the stdout result block; the pass
    criterion is a TPU criterion recorded in BENCH_NOTES.md (CPU smoke
    serializes collectives and only proves the plumbing + memory split).
    Never fails the bench.
    """
    import jax

    if len(jax.devices()) < 2:
        sys.stderr.write(
            "bench: skipping zero probe (needs >= 2 devices for rdp).\n")
        return None
    if deadline - time.time() < 180:
        sys.stderr.write(
            f"bench: skipping zero probe ({deadline - time.time():.0f}s "
            "left in window < 180s floor).\n")
        return None
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from smdistributed_modelparallel_tpu.utils import hlo_audit

    on_tpu = jax.devices()[0].platform == "tpu"
    rdp = len(jax.devices())
    n_layers, d_model, n_heads, seq, vocab = (
        (8, 512, 8, 512, 8192) if on_tpu else (4, 32, 2, 16, 64)
    )
    # Per-microbatch batch must divide by rdp for the explicit
    # slice-grad + reduce-scatter path (mb=4 below).
    batch = 4 * rdp
    iters = 10 if on_tpu else 3
    threshold = 1 if not on_tpu else 4096

    def build(extra):
        smp.reset()
        cfg = {"microbatches": 4, "ddp": True, "bf16": bool(on_tpu),
               "sdp_param_persistence_threshold": threshold}
        cfg.update(extra)
        smp.init(cfg)
        model = smp.DistributedModel(TransformerLM(
            vocab_size=vocab, max_len=seq, d_model=d_model,
            n_layers=n_layers, n_heads=n_heads,
        ))
        optimizer = smp.DistributedOptimizer(optax.sgd(1e-3), model)
        ids = jax.random.randint(jax.random.key(0), (batch, seq), 0, vocab)

        @smp.step
        def train_step(model, b):
            logits = model(b)
            lg = logits[:, :-1].astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            tgt = jnp.take_along_axis(lg, b[:, 1:, None], axis=-1)[..., 0]
            loss = jnp.mean(lse - tgt)
            model.backward(loss)
            return loss

        return model, optimizer, train_step, ids

    def param_bytes(model):
        """(per-device shard bytes, logical total bytes): both variants
        shard at the same threshold, so the 1/rdp memory claim reads off
        the per-device/total ratio."""
        per_device = total = 0
        for leaf in jax.tree_util.tree_leaves(model.params):
            try:
                shard_shape = leaf.sharding.shard_shape(leaf.shape)
            except Exception:
                shard_shape = leaf.shape
            n = 1
            for d in shard_shape:
                n *= int(d)
            per_device += n * leaf.dtype.itemsize
            total += int(leaf.size) * leaf.dtype.itemsize
        return per_device, total

    variants = (
        ("zero2d", {"sharded_data_parallel_degree": rdp}),
        ("zero3", {"sharded_params": "zero3"}),
    )
    times = {name: [] for name, _ in variants}
    memory = {}
    zero_block = None
    for _round in range(3):
        for name, extra in variants:
            model, optimizer, train_step, ids = build(extra)
            out = None
            for _ in range(2):     # warmup: compile + first dispatch
                out = train_step(model, ids)
                optimizer.step()
            _readback(out.reduce_mean())
            if name not in memory:
                audit = hlo_audit.of_step_function(train_step)
                per_device, total = param_bytes(model)
                memory[name] = {
                    "param_bytes_per_device": per_device,
                    "param_bytes_total": total,
                    "program_memory": (audit.memory if audit else {}),
                }
                if name == "zero3" and audit is not None:
                    zero_block = audit.zero
            t0 = time.perf_counter()
            for _ in range(iters):
                out = train_step(model, ids)
                optimizer.step()
            _readback(out.reduce_mean())
            times[name].append((time.perf_counter() - t0) / iters)
        if time.time() > deadline:
            sys.stderr.write(
                "bench: zero probe hit the window deadline; using the "
                f"{len(times['zero3'])} block round(s) measured so far.\n")
            break
    smp.reset()

    med = {name: _median(ts) for name, ts in times.items()}
    result = {
        "component": "zero_probe",
        "rdp": rdp,
        "zero2d_ms": round(med["zero2d"] * 1e3, 3),
        "zero3_ms": round(med["zero3"] * 1e3, 3),
        "speedup": round(med["zero2d"] / med["zero3"], 4),
        "memory": memory,
        "zero": zero_block,
        "blocks": len(times["zero3"]),
        "on_tpu": on_tpu,
    }
    sys.stderr.write(json.dumps(result) + "\n")
    sys.stderr.flush()
    return result


def _tp_probe(deadline):
    """SMP_BENCH_TP_PROBE=1: overlapped-tensor-parallelism A/B at tp=2 —
    GSPMD (tp_overlap off) vs the ring decomposition vs ring + fused
    kernels (Pallas fused QKV + bias-GELU), on the smp.nn transformer
    family the ring lives in.

    Same interleaved-blocks methodology as the pipeline/zero probes
    (each block re-inits — the knob changes the compiled program — and
    pays its compile in warmup, outside the timed region). Emits one
    stderr JSON line {"component": "tp_overlap", off_ms, ring_ms,
    ring_fused_ms, speedup_ring, ...} plus the ring leg's X-ray
    ``tp_overlap`` block, and returns the dict for the stdout result
    block. The pass criterion is a TPU criterion recorded in
    BENCH_NOTES.md Round 15 — the CPU smoke serializes the ring's
    ppermute hops (no async collectives on XLA:CPU), so ring legs READ
    SLOWER there and the number only proves the plumbing, exactly like
    the zero3 probe. Never fails the bench.
    """
    import jax

    if len(jax.devices()) < 2:
        sys.stderr.write(
            "bench: skipping tp probe (needs >= 2 devices for tp=2).\n")
        return None
    if deadline - time.time() < 180:
        sys.stderr.write(
            f"bench: skipping tp probe ({deadline - time.time():.0f}s "
            "left in window < 180s floor).\n")
        return None
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.nn.cross_entropy import (
        vocab_parallel_cross_entropy,
    )
    from smdistributed_modelparallel_tpu.nn.transformer import (
        DistributedTransformerLMHead,
    )
    from smdistributed_modelparallel_tpu.utils import hlo_audit

    on_tpu = jax.devices()[0].platform == "tpu"
    n_layers, d_model, n_heads, hd, ff, seq, vocab = (
        (8, 1024, 16, 64, 4096, 1024, 32000) if on_tpu
        else (2, 32, 4, 8, 64, 16, 96)
    )
    batch = 8
    iters = 10 if on_tpu else 3

    def build(extra, fused_model=False):
        smp.reset()
        cfg = {"microbatches": 2, "ddp": True,
               "tensor_parallel_degree": 2, "bf16": bool(on_tpu)}
        cfg.update(extra)
        smp.init(cfg)
        model = smp.DistributedModel(DistributedTransformerLMHead(
            num_layers=n_layers, num_attention_heads=n_heads,
            attention_head_size=hd, hidden_size=d_model,
            intermediate_size=ff, vocab_size=vocab, num_positions=seq,
            causal_mask_size=seq, pre_layernorm=True,
            post_layernorm=False, final_layernorm=True,
            attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
            embedding_dropout_prob=0.0, fused_bias_gelu=fused_model,
        ))
        optimizer = smp.DistributedOptimizer(optax.sgd(1e-3), model)
        ids = jax.random.randint(jax.random.key(0), (batch, seq), 0, vocab)

        @smp.step
        def train_step(model, b):
            logits = model(b)
            loss = jnp.mean(
                vocab_parallel_cross_entropy(logits[:, :-1], b[:, 1:])
            )
            model.backward(loss)
            return loss

        return model, optimizer, train_step, ids

    variants = (
        ("off", {}, False),
        ("ring", {"tp_overlap": "ring"}, False),
        ("ring_fused", {"tp_overlap": "ring", "fused_qkv": True}, True),
    )
    times = {name: [] for name, _, _ in variants}
    tp_block = None

    def _pallas_qkv_dispatches():
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            telemetry,
        )

        fam = telemetry.report()["metrics"].get(
            "smp_fused_kernel_dispatch_total"
        )
        return sum(
            s["value"] for s in (fam["series"] if fam else ())
            if s["labels"].get("kernel") == "qkv"
            and s["labels"].get("path") == "pallas"
        )

    # Measured, not assumed: did the ring_fused leg's trace actually
    # dispatch the Pallas QKV kernel? (It won't off-TPU, or when
    # use_pallas_kernels is disabled, or when no VMEM tile fits.)
    fused_engaged = False
    for _round in range(3):
        for name, extra, fused_model in variants:
            model, optimizer, train_step, ids = build(
                extra, fused_model=fused_model
            )
            out = None
            d0 = _pallas_qkv_dispatches() if fused_model else 0
            for _ in range(2):     # warmup: compile + first dispatch
                out = train_step(model, ids)
                optimizer.step()
            _readback(out.reduce_mean())
            if fused_model and _pallas_qkv_dispatches() > d0:
                fused_engaged = True
            if name == "ring" and tp_block is None:
                audit = hlo_audit.of_step_function(train_step)
                if audit is not None:
                    tp_block = audit.tp_overlap
            t0 = time.perf_counter()
            for _ in range(iters):
                out = train_step(model, ids)
                optimizer.step()
            _readback(out.reduce_mean())
            times[name].append((time.perf_counter() - t0) / iters)
        if time.time() > deadline:
            sys.stderr.write(
                "bench: tp probe hit the window deadline; using the "
                f"{len(times['ring'])} block round(s) measured so far.\n")
            break
    smp.reset()

    med = {name: _median(ts) for name, ts in times.items()}
    result = {
        "component": "tp_overlap",
        "tp": 2,
        "off_ms": round(med["off"] * 1e3, 3),
        "ring_ms": round(med["ring"] * 1e3, 3),
        "ring_fused_ms": round(med["ring_fused"] * 1e3, 3),
        "speedup_ring": round(med["off"] / med["ring"], 4),
        "speedup_fused": round(med["off"] / med["ring_fused"], 4),
        "tp_overlap": tp_block,
        "fused_engaged": fused_engaged,
        "blocks": len(times["ring"]),
        "on_tpu": on_tpu,
    }
    sys.stderr.write(json.dumps(result) + "\n")
    sys.stderr.flush()
    return result


def _compile_cache_probe(deadline):
    """SMP_BENCH_COMPILE_PROBE=1: cold/warm compile A/B through the
    persistent executable cache (smp.exec_cache).

    Builds one small step config twice: the first build compiles fresh
    and stores the executable; the second (after a full smp.reset, the
    in-process analogue of a cold start) deserializes it from disk.
    ``cold_s``/``warm_s`` are the compile-phase walls (XLA compile vs
    deserialize+verify — the cost the cache removes; trace+lower is paid
    identically by both legs and reported as ``lower_s``);
    ``cold_wall_s``/``warm_wall_s`` are the full first-call walls a
    recovering/resuming job actually waits. Emits one stderr JSON line
    and returns the block stamped into BENCH_r*.json as ``"exec_cache"``
    (schema-checked by scripts/perf_ledger.py)."""
    import shutil
    import tempfile

    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.gpt2 import gpt2_124m
    from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

    if time.time() > deadline - 30:
        sys.stderr.write(
            "bench: compile probe skipped (probe window exhausted)\n"
        )
        return None
    user_dir = os.environ.get("SMP_EXEC_CACHE_DIR")
    prev_on = os.environ.get("SMP_EXEC_CACHE")
    tmp = None
    if user_dir is None:
        tmp = tempfile.mkdtemp(prefix="smp_exec_cache_bench_")
    os.environ["SMP_EXEC_CACHE"] = "on"
    os.environ["SMP_EXEC_CACHE_DIR"] = user_dir or tmp
    try:
        seq, batch = 64, 4
        ids = None

        def run_once():
            nonlocal ids
            smp.reset()
            smp.init({"microbatches": 2})
            import jax as _jax

            model = smp.DistributedModel(gpt2_124m(
                max_len=seq, d_model=128, n_layers=2, n_heads=4,
            ))
            optimizer = smp.DistributedOptimizer(optax.adamw(1e-4), model)

            @smp.step
            def train_step(model, batch_ids):
                logits = model(batch_ids)
                loss = jnp.mean(logits.astype(jnp.float32) ** 2)
                model.backward(loss)
                return loss

            if ids is None:
                ids = _jax.random.randint(
                    _jax.random.key(0), (batch, seq), 0, 50257
                )
            t0 = time.perf_counter()
            out = train_step(model, ids)
            optimizer.step()
            loss = _readback(out.reduce_mean())
            wall = time.perf_counter() - t0
            # Per-leg telemetry (run_once reset the registry on entry, so
            # only this leg's series exist).
            rep = telemetry.report()["metrics"]

            def _hsum(name, **labels):
                for s in rep.get(name, {"series": []})["series"]:
                    if all(s["labels"].get(k) == v
                           for k, v in labels.items()):
                        return s.get("sum", 0.0)
                return 0.0

            fam = rep.get("smp_exec_cache_total", {"series": []})
            outcomes = {
                s["labels"]["result"]: s["value"] for s in fam["series"]
            }
            return {
                "wall": wall, "loss": loss, "outcomes": outcomes,
                "fresh": _hsum("smp_step_compile_seconds", source="fresh"),
                "cached": _hsum(
                    "smp_step_compile_seconds", source="disk_cache"
                ),
                "lower": _hsum("smp_step_lower_seconds"),
            }

        cold = run_once()   # fresh compile + store
        warm = run_once()   # deserialize from disk
        hit = warm["outcomes"].get("hit", 0) >= 1
        if not hit:
            sys.stderr.write(
                "bench: compile probe's warm leg did NOT hit the cache "
                f"(outcomes {warm['outcomes']}) — speedup below reflects "
                "a recompile, not a warm start.\n"
            )
        cold_s = cold["fresh"]
        warm_s = warm["cached"] if hit else warm["fresh"]
        result = {
            "component": "exec_cache",
            "cold_s": round(cold_s, 3),
            "warm_s": round(warm_s, 3),
            "speedup": round(cold_s / warm_s, 3) if warm_s > 0 else None,
            "lower_s": round(warm["lower"], 3),
            "cold_wall_s": round(cold["wall"], 3),
            "warm_wall_s": round(warm["wall"], 3),
            "cache_hit": bool(hit),
            "bit_identical": bool(cold["loss"] == warm["loss"]),
        }
        sys.stderr.write(json.dumps(result) + "\n")
        sys.stderr.flush()
        return result
    except Exception as e:  # the probe must never kill the bench
        sys.stderr.write(f"bench: compile probe failed ({e!r})\n")
        return None
    finally:
        smp.reset()
        if prev_on is None:
            os.environ.pop("SMP_EXEC_CACHE", None)
        else:
            os.environ["SMP_EXEC_CACHE"] = prev_on
        if user_dir is None:
            os.environ.pop("SMP_EXEC_CACHE_DIR", None)
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def _serve_probe(deadline):
    """SMP_BENCH_SERVE_PROBE=1: static-batch ``smp.generate`` vs
    continuous batching (``smp.serving``) on a synthetic ragged-arrival
    trace.

    The trace is 12 greedy requests with ragged decode lengths arriving
    ``gap_s`` apart. The static baseline serves them the only way
    ``smp.generate`` can: FIFO batches of ``slots`` requests, each batch
    waiting for its last member to arrive and running to the batch's MAX
    max_new_tokens (short rows ride along as wasted steps, and nothing
    streams until the batch completes). Continuous batching admits each
    request on arrival, backfills freed slots, and retires rows at their
    own length. Token parity is asserted row-for-row (greedy), compile is
    excluded from both legs (warmed up beforehand), and the block stamped
    into BENCH_r*.json as ``"serving"`` carries
    ttft/itl mean + p50/p95/p99 and tokens_per_sec/speedup
    (schema-checked by scripts/perf_ledger.py). The probe also arms the
    observability artifacts: the metrics time-series JSONL
    (smp_serve_timeseries.jsonl, with idle tail windows so windowed
    tok/s visibly diverges from the lifetime rate) and the fused
    per-request span trace (smp_serve_trace.json via scripts/trace_fuse
    over the flight-ring dump). TPU criterion in BENCH_NOTES.md: same
    structure at serving batch sizes."""
    import numpy as np

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.transformer_lm import (
        TransformerLM,
    )

    if time.time() > deadline - 30:
        sys.stderr.write(
            "bench: serve probe skipped (probe window exhausted)\n"
        )
        return None
    # Arm the time-series feed AND the fleet metrics plane for the probe
    # run (caller env wins); restored in the finally so the probe leaves
    # no trace in os.environ. SMP_METRICS_PORT=0 binds an ephemeral port
    # so the probe can round-trip the /fleet scrape endpoint.
    ts_env_prev = {
        k: os.environ.get(k)
        for k in ("SMP_TIMESERIES_INTERVAL", "SMP_TIMESERIES_PATH",
                  "SMP_FLEET_INTERVAL", "SMP_FLEET_PATH",
                  "SMP_METRICS_PORT")
    }
    os.environ.setdefault("SMP_TIMESERIES_INTERVAL", "0.1")
    os.environ.setdefault(
        "SMP_TIMESERIES_PATH", "smp_serve_timeseries.jsonl"
    )
    os.environ.setdefault("SMP_FLEET_INTERVAL", "0.1")
    os.environ.setdefault("SMP_FLEET_PATH", "smp_fleet_windows.jsonl")
    os.environ.setdefault("SMP_METRICS_PORT", "0")
    if ts_env_prev["SMP_FLEET_PATH"] is None:
        # The fleet feed is append-only by design (it must survive
        # aggregator failover); when the probe owns the path, start it
        # fresh so the stamped window count is this run's.
        try:
            os.remove(os.environ["SMP_FLEET_PATH"])
        except OSError:
            pass
    engine = None
    try:
        import jax as _jax

        smp.reset()
        smp.init({})
        mod = TransformerLM(
            vocab_size=512, max_len=64, d_model=384, n_layers=4,
            n_heads=4,
        )
        # Extreme decode raggedness is where continuous batching earns
        # its keep: each FIFO batch of 4 carries one long stream, so the
        # static baseline burns batch-max steps on three retired rows AND
        # serializes the long streams across batches — the engine runs
        # the longs concurrently and backfills retired slots from the
        # queue.
        plen, slots, gap_s = 8, 4, 0.01
        max_news = [28, 4, 4, 4, 28, 4, 4, 4, 28, 4, 4, 4]
        prompts = [
            np.asarray(_jax.random.randint(
                _jax.random.key(100 + i), (plen,), 0, 128
            ))
            for i in range(len(max_news))
        ]
        params = mod.init(
            _jax.random.key(0), _jax.numpy.asarray(prompts[0])[None]
        )["params"]

        # -- static leg: FIFO batches, batch-max decode length ----------
        batches = [
            list(range(i, min(i + slots, len(max_news))))
            for i in range(0, len(max_news), slots)
        ]
        for b in batches:  # compile warmup (excluded from both legs)
            ids = _jax.numpy.asarray(np.stack([prompts[i] for i in b]))
            smp.generate(mod, ids, max(max_news[i] for i in b),
                         params=params)
        for m in set(max_news):
            # The engine's per-request key schedule is
            # split(key(seed), max_new) — prime the per-count threefry
            # compile the same way the static leg's generates were.
            _jax.random.split(_jax.random.key(0), m)
        t0 = time.perf_counter()
        static_out = {}
        static_ttft = []
        for b in batches:
            last_arrival = max(i * gap_s for i in b)
            now = time.perf_counter() - t0
            if now < last_arrival:
                time.sleep(last_arrival - now)
            ids = _jax.numpy.asarray(np.stack([prompts[i] for i in b]))
            out = np.asarray(smp.generate(
                mod, ids, max(max_news[i] for i in b), params=params
            ))
            done = time.perf_counter() - t0
            for row, i in enumerate(b):
                static_out[i] = list(out[row, plen:plen + max_news[i]])
                static_ttft.append(done - i * gap_s)
        static_wall = time.perf_counter() - t0
        useful_tokens = sum(max_news)
        static_tps = useful_tokens / static_wall

        # -- continuous leg ---------------------------------------------
        engine = smp.serving.ServingEngine(
            mod, params=params, max_slots=slots,
            block_tokens_override=8, prefill_chunk=8,
        )
        engine._program("prefill")   # compile warmup
        engine._program("decode")
        reqs = [
            smp.serving.ServeRequest(
                f"b{i}", list(map(int, prompts[i])), max_news[i],
                arrival_s=i * gap_s,
            )
            for i in range(len(max_news))
        ]
        t0 = time.perf_counter()
        results = engine.run(reqs, timeout_s=deadline - time.time())
        cont_wall = time.perf_counter() - t0
        cont_tps = useful_tokens / cont_wall

        parity = all(
            list(results[f"b{i}"]) == static_out[i]
            for i in range(len(max_news))
        )

        ts = engine.timeseries
        if ts is not None:
            # Two idle tail windows after the burst: windowed tok/s
            # decays to ~0 while the lifetime rate stays positive — the
            # divergence the autoscaler feed exists to carry.
            for _ in range(2):
                time.sleep(ts.interval)
                ts.maybe_sample()

        from smdistributed_modelparallel_tpu.utils.telemetry import (
            serve_latency_summary,
        )

        qs = (0.5, 0.95, 0.99)
        ttft = serve_latency_summary("ttft", qs=qs)
        itl = serve_latency_summary("itl", qs=qs)

        def _pct(summ, q):
            if not summ:
                return 0.0
            return round(1e3 * summ["quantiles_s"][q], 3)

        snaps = ts.snapshots() if ts is not None else []
        result = {
            "component": "serving",
            "ttft_ms": round(1e3 * ttft["mean_s"], 2) if ttft else 0.0,
            "itl_ms": round(1e3 * itl["mean_s"], 2) if itl else 0.0,
            "ttft_p50_ms": _pct(ttft, 0.5),
            "ttft_p95_ms": _pct(ttft, 0.95),
            "ttft_p99_ms": _pct(ttft, 0.99),
            "itl_p50_ms": _pct(itl, 0.5),
            "itl_p95_ms": _pct(itl, 0.95),
            "itl_p99_ms": _pct(itl, 0.99),
            "tokens_per_sec": round(cont_tps, 2),
            "static_tokens_per_sec": round(static_tps, 2),
            "static_ttft_ms": round(
                1e3 * sum(static_ttft) / len(static_ttft), 2
            ),
            "speedup": round(cont_tps / static_tps, 3),
            "requests": len(max_news),
            "decode_steps": int(engine.stats["decode_steps"]),
            "prefill_chunks": int(engine.stats["prefill_chunks"]),
            "token_parity": bool(parity),
            "timeseries_windows": len(snaps),
        }
        if snaps:
            result["tokens_per_sec_last_window"] = round(
                snaps[-1]["tokens_per_s"], 2
            )
            result["tokens_per_sec_lifetime"] = round(
                snaps[-1]["lifetime_tokens_per_s"], 2
            )

        # Fused span trace: dump the flight ring and run trace_fuse over
        # it. Best-effort — the trace artifact failing must not void the
        # probe numbers.
        try:
            from smdistributed_modelparallel_tpu.utils.flight_recorder import (
                flight_recorder,
            )

            ring_path = flight_recorder.dump("smp_serve_flight.jsonl")
            if ring_path:
                scripts_dir = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), "scripts"
                )
                if scripts_dir not in sys.path:
                    sys.path.insert(0, scripts_dir)
                import trace_fuse

                trace_fuse.main(
                    ["-o", "smp_serve_trace.json", "--no-report",
                     ring_path]
                )
                stream = trace_fuse.load_stream(ring_path)
                spans, _, findings = trace_fuse.serve_request_spans(
                    [e for e in stream.events if e.get("kind") == "serve"]
                )
                result["trace_slot_lanes"] = len({
                    sp["tid"] for sp in spans
                    if sp["tid"].startswith("slot ")
                })
                result["trace_open_spans"] = sum(
                    1 for f in findings if "left open" in f
                )
        except Exception as te:
            sys.stderr.write(
                f"bench: serve trace artifacts skipped ({te!r})\n"
            )

        # Fleet metrics plane block: windows aggregated, straggler
        # verdicts, and a live round-trip of the /fleet scrape endpoint.
        # Best-effort like the trace artifacts.
        try:
            from smdistributed_modelparallel_tpu.utils.fleet import (
                fleet as _fleet,
            )

            plane = _fleet.plane
            if plane is not None:
                plane.tick()  # ensure at least one window post-burst
                fleet_block = {
                    "windows": len(plane.windows()),
                    "ranks": plane.world,
                    "stragglers": sorted(plane.straggling),
                }
                if plane.bound_port:
                    import urllib.request

                    t_rt = time.perf_counter()
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{plane.bound_port}/fleet",
                        timeout=10,
                    ) as resp:
                        doc = json.loads(resp.read())
                    fleet_block["endpoint_roundtrip_ms"] = round(
                        1e3 * (time.perf_counter() - t_rt), 3
                    )
                    ttft_doc = doc.get("percentiles", {}).get("ttft")
                    if ttft_doc and ttft_doc.get("p99_s") is not None:
                        fleet_block["endpoint_ttft_p99_ms"] = round(
                            1e3 * ttft_doc["p99_s"], 3
                        )
                last = (plane.windows() or [{}])[-1]
                if last.get("slo"):
                    fleet_block["goodput"] = last["slo"].get("goodput")
                result["fleet"] = fleet_block
        except Exception as fe:
            sys.stderr.write(f"bench: fleet block skipped ({fe!r})\n")

        sys.stderr.write(json.dumps(result) + "\n")
        sys.stderr.flush()
        return result
    except Exception as e:  # the probe must never kill the bench
        sys.stderr.write(f"bench: serve probe failed ({e!r})\n")
        return None
    finally:
        for k, v in ts_env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if engine is not None:
            engine.close()
        smp.reset()


def _autoscale_probe(deadline):
    """SMP_BENCH_AUTOSCALE_PROBE=1: the same bursty ragged-arrival trace
    served by a STATIC single replica vs the SLO-driven autoscaler
    (``smp.serving.ServingController``) allowed to grow to two.

    The burst overruns one replica's two decode slots, the queue-depth
    SLO breaches for the hysteresis count, and the controller activates
    the standby replica (exec-cache warm start — the activation report's
    compile sources ride in the scale-event record); once the burst
    drains, sustained headroom scales back to one via the drain
    protocol. Token parity is asserted request-for-request against the
    static leg (zero dropped or duplicated tokens across the scale
    events), then a canaried LIVE weight update runs on the quiesced
    fleet (identical params under a new version: the parity gate must
    pass and promotion land with ZERO fresh compiles — the weight-free
    program-cache keys at work). The block stamped into BENCH_r*.json as
    ``"autoscale"`` carries scale_events / p99_ttft_ms_static /
    p99_ttft_ms_auto / weight_update_s / canary_verdict
    (schema-checked by scripts/perf_ledger.py). TPU criterion in
    BENCH_NOTES.md: same structure at serving batch sizes."""
    import numpy as np

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.transformer_lm import (
        TransformerLM,
    )

    if time.time() > deadline - 30:
        sys.stderr.write(
            "bench: autoscale probe skipped (probe window exhausted)\n"
        )
        return None
    env_prev = {
        k: os.environ.get(k)
        for k in ("SMP_AUTOSCALE", "SMP_SLO", "SMP_AUTOSCALE_COOLDOWN",
                  "SMP_AUTOSCALE_MIN", "SMP_AUTOSCALE_MAX",
                  "SMP_AUTOSCALE_HYSTERESIS", "SMP_CANARY_WINDOWS",
                  "SMP_CONTROLLER_PATH", "SMP_EXEC_CACHE",
                  "SMP_EXEC_CACHE_DIR")
    }
    os.environ["SMP_AUTOSCALE"] = "on"
    os.environ.setdefault("SMP_SLO", "queue_depth=2")
    os.environ.setdefault("SMP_AUTOSCALE_COOLDOWN", "0.3")
    os.environ.setdefault("SMP_AUTOSCALE_MIN", "1")
    os.environ.setdefault("SMP_AUTOSCALE_MAX", "2")
    os.environ.setdefault("SMP_AUTOSCALE_HYSTERESIS", "2")
    os.environ.setdefault("SMP_CANARY_WINDOWS", "1")
    os.environ.setdefault("SMP_CONTROLLER_PATH", "smp_controller.jsonl")
    os.environ.setdefault("SMP_EXEC_CACHE", "on")
    os.environ.setdefault("SMP_EXEC_CACHE_DIR", ".smp_bench_exec_cache")
    if env_prev["SMP_CONTROLLER_PATH"] is None:
        try:
            os.remove(os.environ["SMP_CONTROLLER_PATH"])
        except OSError:
            pass
    engines = []

    def _engine(mod, params, slots):
        eng = smp.serving.ServingEngine(
            mod, params=params, max_slots=slots,
            block_tokens_override=8, prefill_chunk=8,
        )
        eng._program("prefill")
        eng._program("decode")
        engines.append(eng)
        return eng

    try:
        import jax as _jax

        smp.reset()
        smp.init({})
        mod = TransformerLM(
            vocab_size=512, max_len=64, d_model=256, n_layers=2,
            n_heads=4,
        )
        plen, slots = 8, 2
        max_news = [20] * 32
        prompts = [
            np.asarray(_jax.random.randint(
                _jax.random.key(300 + i), (plen,), 0, 128
            ))
            for i in range(len(max_news))
        ]
        params = mod.init(
            _jax.random.key(0), _jax.numpy.asarray(prompts[0])[None]
        )["params"]

        # Calibrate the burst against THIS host's service rate: arrivals
        # land at 60% of the measured per-request service interval, so
        # one replica is reliably ~1.7x oversubscribed whatever the
        # machine — the queue-depth SLO must breach and the controller
        # must scale, on a laptop or a TPU host alike.  The first pass
        # only warms the engine (first-dispatch overhead inflates its
        # interval ~3x); only the second, warmed pass is timed.
        calib_eng = _engine(mod, params, slots)
        for tag in ("w", "c"):
            calib = [
                smp.serving.ServeRequest(
                    f"{tag}{i}", list(map(int, prompts[i])), max_news[i],
                )
                for i in range(4)
            ]
            t0 = time.perf_counter()
            calib_eng.run(
                calib, timeout_s=max(deadline - time.time(), 30.0)
            )
            gap_s = 0.6 * (time.perf_counter() - t0) / len(calib)

        def _reqs():
            return [
                smp.serving.ServeRequest(
                    f"a{i}", list(map(int, prompts[i])), max_news[i],
                )
                for i in range(len(max_news))
            ]

        from smdistributed_modelparallel_tpu.utils.telemetry import (
            serve_latency_summary,
        )

        def _p99_ms():
            summ = serve_latency_summary("ttft", qs=(0.5, 0.99))
            return round(1e3 * summ["quantiles_s"][0.99], 3) if summ else 0.0

        # -- static leg: ONE replica, no controller ---------------------
        static_eng = calib_eng
        static_reqs = [
            dataclasses.replace(r, arrival_s=i * gap_s)
            for i, r in enumerate(_reqs())
        ]
        static_results = static_eng.run(
            static_reqs, timeout_s=max(deadline - time.time(), 30.0)
        )
        static_tokens = {
            f"a{i}": list(static_results[f"a{i}"])
            for i in range(len(max_news))
        }
        p99_static = _p99_ms()

        # -- autoscaled leg: controller may grow 1 -> 2 -----------------
        smp.reset()   # fresh telemetry so the auto leg's p99 is its own
        smp.init({})
        eng_a = _engine(mod, params, slots)

        def _activate():
            return smp.serving.LocalReplicaHandle(
                "replica1", _engine(mod, params, slots), version=0,
            )

        wseq = [0]
        wlast = [0.0]

        def _win(ctl_router):
            now = time.perf_counter()
            if now - wlast[0] < 0.025:
                return None   # one synthetic window per 25ms
            wlast[0] = now
            wseq[0] += 1
            depth = max(
                (len(h.engine._queue) for h in ctl_router.live_handles()),
                default=0,
            )
            return {"seq": wseq[0], "t_wall": time.time(),
                    "queue_depth": depth}

        router = smp.serving.RequestRouter()
        ctl = smp.serving.ServingController.from_env(
            router=router, window_source=lambda: _win(router),
        )
        ctl.register_live(smp.serving.LocalReplicaHandle(
            "replica0", eng_a, version=0,
        ))
        ctl.add_standby("replica1", _activate)
        auto_reqs = _reqs()
        t0 = time.perf_counter()
        pending = list(range(len(auto_reqs)))
        loop_deadline = min(deadline, time.time() + 120.0)
        while time.time() < loop_deadline:
            now = time.perf_counter() - t0
            while pending and now >= pending[0] * gap_s:
                router.dispatch(auto_reqs[pending.pop(0)])
            busy = router.step_all()
            ctl.tick()
            if not pending and not busy \
                    and len(ctl.results()) >= len(auto_reqs):
                break
            if not busy:
                time.sleep(0.001)
        # Idle-tick long enough for the comfort streak to trigger the
        # drain-protocol scale-down (cooldown 0.3s + 2 windows).
        down_deadline = time.time() + 5.0
        while (ctl.replicas > 1 and time.time() < down_deadline
               and time.time() < loop_deadline):
            router.step_all()
            ctl.tick()
            time.sleep(0.01)
        p99_auto = _p99_ms()
        auto_results = ctl.results()
        parity = all(
            list(auto_results.get(rid, ())) == toks
            for rid, toks in static_tokens.items()
        )

        # -- canaried live weight update on the quiesced fleet ----------
        from smdistributed_modelparallel_tpu.utils import exec_cache

        new_params = _jax.tree_util.tree_map(lambda x: x, params)
        pinned = [
            dataclasses.replace(_reqs()[i], request_id=f"pin{i}")
            for i in (0, 1)
        ]
        mark = exec_cache.compile_event_mark()
        ctl.start_canary(new_params, version=1, pinned=pinned)
        while ctl.canary is not None and time.time() < loop_deadline:
            ctl.tick()
            time.sleep(0.01)
        fresh = sum(
            1 for e in exec_cache.compile_events_since(mark)
            if e.get("source") == "fresh"
        )
        if ctl.promotions:
            canary_verdict = "promoted"
        elif ctl.rollbacks:
            canary_verdict = "rolled_back"
        else:
            canary_verdict = "none"
        weight_update_s = 0.0
        try:
            with open(os.environ["SMP_CONTROLLER_PATH"]) as fh:
                for line in fh:
                    rec = json.loads(line)
                    if rec.get("kind") == "weight_update":
                        weight_update_s = float(rec["seconds"])
        except (OSError, ValueError):
            pass
        ctl.stop()

        result = {
            "component": "autoscale",
            "scale_events": len(ctl.scale_events),
            "p99_ttft_ms_static": p99_static,
            "p99_ttft_ms_auto": p99_auto,
            "weight_update_s": round(weight_update_s, 6),
            "canary_verdict": canary_verdict,
            "fresh_compiles": fresh,
            "token_parity": bool(parity),
            "requests": len(max_news),
            "replicas_max": max(
                (e["replicas"] for e in ctl.scale_events), default=1
            ),
        }
        sys.stderr.write(json.dumps(result) + "\n")
        sys.stderr.flush()
        return result
    except Exception as e:  # the probe must never kill the bench
        sys.stderr.write(f"bench: autoscale probe failed ({e!r})\n")
        return None
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for eng in engines:
            try:
                eng.close()
            except Exception:
                pass
        smp.reset()


def _quant_probe(deadline):
    """SMP_BENCH_QUANT_PROBE=1: the low-precision A/Bs behind smp.quant.

    Two legs, each window-capped and compile-excluded:

    - **train**: bf16 vs ``matmul_precision: fp8`` (delayed-scaling e4m3
      fwd / e5m2 grad) on the smp.nn transformer family the fp8 seams
      live in — median step ms per leg, the max relative loss deviation
      over the measured trajectory (the parity number the tolerance in
      docs/README quotes), and the fp8 leg's X-ray ``quant`` census.
    - **decode**: bf16 KV pool vs ``SMP_KV_QUANT=int8`` (per-block-per-
      head scales) through the serving engine on the same greedy request
      trace — tokens/sec per leg, per-block pool bytes per leg (the
      ``smp_serve_kv_bytes`` multiplier, so the ~2x concurrency claim is
      a measured byte ratio, not an inference), and row-for-row greedy
      token parity.

    The block stamped into BENCH_r*.json as ``"quant"`` is
    schema-checked by scripts/perf_ledger.py. The pass criterion is a
    TPU criterion recorded in BENCH_NOTES.md Round 20 — XLA:CPU has no
    native fp8 matmul units (the f8 ops lower to convert+f32 dots) and
    no int8 attention gather fusion, so BOTH quantized legs read slower
    on the CPU smoke; the CPU numbers prove plumbing, byte ratios, and
    parity only. Never fails the bench."""
    import jax
    import numpy as np

    if time.time() > deadline - 30:
        sys.stderr.write(
            "bench: quant probe skipped (probe window exhausted)\n"
        )
        return None
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.nn.cross_entropy import (
        vocab_parallel_cross_entropy,
    )
    from smdistributed_modelparallel_tpu.nn.transformer import (
        DistributedTransformerLMHead,
    )
    from smdistributed_modelparallel_tpu.utils import hlo_audit

    on_tpu = jax.devices()[0].platform == "tpu"
    n_layers, d_model, n_heads, hd, ff, seq, vocab = (
        (8, 1024, 16, 64, 4096, 1024, 32000) if on_tpu
        else (2, 32, 4, 8, 64, 16, 96)
    )
    batch = 8
    iters = 10 if on_tpu else 3
    env_prev = {k: os.environ.get(k)
                for k in ("SMP_KV_QUANT", "SMP_DECODE_WEIGHTS")}
    try:
        # ---- train leg: bf16 vs fp8 -----------------------------------
        def build(precision):
            smp.reset()
            smp.init({"microbatches": 2, "ddp": True,
                      "bf16": bool(on_tpu),
                      "matmul_precision": precision})
            model = smp.DistributedModel(DistributedTransformerLMHead(
                num_layers=n_layers, num_attention_heads=n_heads,
                attention_head_size=hd, hidden_size=d_model,
                intermediate_size=ff, vocab_size=vocab,
                num_positions=seq, causal_mask_size=seq,
                pre_layernorm=True, post_layernorm=False,
                final_layernorm=True, attention_dropout_prob=0.0,
                hidden_dropout_prob=0.0, embedding_dropout_prob=0.0,
            ))
            optimizer = smp.DistributedOptimizer(optax.sgd(1e-3), model)
            ids = jax.random.randint(
                jax.random.key(0), (batch, seq), 0, vocab
            )

            @smp.step
            def train_step(model, b):
                logits = model(b)
                loss = jnp.mean(
                    vocab_parallel_cross_entropy(logits[:, :-1], b[:, 1:])
                )
                model.backward(loss)
                return loss

            return model, optimizer, train_step, ids

        times = {"bf16": [], "fp8": []}
        losses = {"bf16": [], "fp8": []}
        quant_xray = None
        for _round in range(3):
            for precision in ("bf16", "fp8"):
                model, optimizer, train_step, ids = build(precision)
                out = None
                for _ in range(2):   # warmup: compile + first dispatch
                    out = train_step(model, ids)
                    optimizer.step()
                _readback(out.reduce_mean())
                if precision == "fp8" and quant_xray is None:
                    audit = hlo_audit.of_step_function(train_step)
                    if audit is not None:
                        quant_xray = audit.quant
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = train_step(model, ids)
                    optimizer.step()
                    if _round == 0:
                        losses[precision].append(
                            float(out.reduce_mean())
                        )
                if _round > 0:
                    _readback(out.reduce_mean())
                times[precision].append(
                    (time.perf_counter() - t0) / iters
                )
            if time.time() > deadline:
                sys.stderr.write(
                    "bench: quant train leg hit the window deadline; "
                    f"using the {len(times['fp8'])} round(s) measured "
                    "so far.\n")
                break
        med = {k: _median(v) for k, v in times.items()}
        n_cmp = min(len(losses["bf16"]), len(losses["fp8"]))
        loss_rel = max(
            (abs(losses["fp8"][i] - losses["bf16"][i])
             / max(abs(losses["bf16"][i]), 1e-12)
             for i in range(n_cmp)),
            default=0.0,
        )
        train_block = {
            "bf16_ms": round(med["bf16"] * 1e3, 3),
            "fp8_ms": round(med["fp8"] * 1e3, 3),
            "speedup_fp8": round(med["bf16"] / med["fp8"], 4),
            "loss_rel_diff": round(loss_rel, 6),
            "steps_compared": n_cmp,
            "quant_xray": quant_xray,
        }

        # ---- decode leg: bf16 KV vs int8 KV ---------------------------
        from smdistributed_modelparallel_tpu.models.transformer_lm import (
            TransformerLM,
        )

        plen = 8
        max_news = [16, 12, 16, 12, 16, 12]
        prompts = [
            list(map(int, np.asarray(jax.random.randint(
                jax.random.key(200 + i), (plen,), 0, 128
            ))))
            for i in range(len(max_news))
        ]

        def serve(kv_mode):
            if kv_mode == "none":
                os.environ.pop("SMP_KV_QUANT", None)
            else:
                os.environ["SMP_KV_QUANT"] = kv_mode
            smp.reset()
            smp.init({})
            mod = TransformerLM(
                vocab_size=512, max_len=64,
                d_model=384 if on_tpu else 64,
                n_layers=4 if on_tpu else 2, n_heads=4,
            )
            params = mod.init(
                jax.random.key(0), jnp.asarray(prompts[0])[None]
            )["params"]
            engine = smp.serving.ServingEngine(
                mod, params=params, max_slots=3,
                block_tokens_override=8, prefill_chunk=8,
            )
            engine._program("prefill")   # compile warmup
            engine._program("decode")
            reqs = [
                smp.serving.ServeRequest(f"q{i}", prompts[i], max_news[i])
                for i in range(len(max_news))
            ]
            t0 = time.perf_counter()
            results = engine.run(
                reqs, timeout_s=max(deadline - time.time(), 30)
            )
            wall = time.perf_counter() - t0
            toks = {
                rid: list(map(int, results[rid])) for rid in results
            }
            tps = sum(max_news) / wall
            bb = engine.kv_block_bytes
            engine.close()
            return toks, tps, bb

        base_toks, base_tps, base_bb = serve("none")
        kv_toks, kv_tps, kv_bb = serve("int8")
        decode_block = {
            "bf16_tokens_per_sec": round(base_tps, 2),
            "int8_kv_tokens_per_sec": round(kv_tps, 2),
            "speedup_kv": round(kv_tps / base_tps, 4),
            "kv_block_bytes_bf16": int(base_bb),
            "kv_block_bytes_int8": int(kv_bb),
            "kv_bytes_ratio": round(kv_bb / base_bb, 4),
            "token_parity": bool(kv_toks == base_toks),
            "requests": len(max_news),
        }

        result = {
            "component": "quant",
            "train": train_block,
            "decode": decode_block,
            "on_tpu": on_tpu,
        }
        sys.stderr.write(json.dumps(result) + "\n")
        sys.stderr.flush()
        return result
    except Exception as e:  # the probe must never kill the bench
        sys.stderr.write(f"bench: quant probe failed ({e!r})\n")
        return None
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        smp.reset()


def main():
    start_time = time.time()
    probe_window = int(os.environ.get("SMP_BENCH_PROBE_WINDOW", 1200))
    # Arm the wall-clock attribution ledger for the whole bench run; the
    # "goodput" block stamped below is schema-checked by perf_ledger.py.
    os.environ.setdefault("SMP_GOODPUT", "1")
    no_accel = _no_accelerator_reason()
    if no_accel:
        sys.stderr.write(
            f"bench: {no_accel} — no accelerator can appear; skipping the "
            "device retry window and emitting the CPU smoke block.\n")
        sys.stderr.flush()
        os.environ["JAX_PLATFORMS"] = "cpu"
        if (os.environ.get("SMP_BENCH_PIPELINE_PROBE", "0") == "1"
                or os.environ.get("SMP_BENCH_ZERO_PROBE", "0") == "1"
                or os.environ.get("SMP_BENCH_TP_PROBE", "0") == "1"):
            # The pp=2 / rdp / tp=2 A/B probes need a multi-device mesh;
            # provision
            # virtual CPU devices BEFORE the first jax import (the main
            # smoke numbers are single-core either way).
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
    else:
        _wait_for_devices()   # bounded retry window (subprocess probes)
        _devices_or_die()     # in-process backstop: probe ok but main wedges
    import jax

    if no_accel:
        # Some TPU plugins pin the platform regardless of JAX_PLATFORMS
        # (see __graft_entry__); the config update makes the cpu smoke
        # deterministic.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.gpt2 import gpt2_124m

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    seq_len = 1024 if on_tpu else 64
    batch = 8 if on_tpu else 4
    num_mb = 4
    d_model, n_layers, vocab = (768, 12, 50257)
    model_kwargs = {} if on_tpu else dict(d_model=128, n_layers=2, n_heads=4)
    if not on_tpu:
        d_model, n_layers = 128, 2
    iters = 10 if on_tpu else 3

    def ce_loss(logits, ids):
        # logsumexp form: the [N, V] fp32 log-softmax is never materialized
        # (the cast+reduce fuse); only the [N] lse and gathered target
        # logits are. Used by BOTH the plain-JAX baseline and the framework.
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, ids[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        return jnp.mean(lse - tgt.astype(jnp.float32))

    ids = jax.random.randint(jax.random.key(0), (batch, seq_len), 0, vocab)

    # ---- plain-JAX baseline (the "without framework" reference point) ----
    module = gpt2_124m(max_len=seq_len, **model_kwargs)
    params0 = jax.jit(module.init)(jax.random.key(0), ids)["params"]
    tx = optax.adamw(1e-4)

    def base_loss(params, mb):
        if on_tpu:
            params = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return ce_loss(module.apply({"params": params}, mb), mb)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def base_train(params, opt_state, ids):
        mbs = ids.reshape(num_mb, batch // num_mb, seq_len)

        def body(acc, mb):
            loss, g = jax.value_and_grad(base_loss)(params, mb)
            return jax.tree_util.tree_map(jnp.add, acc, g), loss

        acc0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        grads, losses = jax.lax.scan(body, acc0, mbs)
        grads = jax.tree_util.tree_map(lambda g: g / num_mb, grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, jnp.mean(losses)

    opt_state0 = jax.jit(tx.init)(params0)
    p, o, l = base_train(params0, opt_state0, ids)
    _readback(l)

    # ---- framework setup ----
    # fused_step_donation: the plain-JAX baseline donates params/opt_state
    # through its step (donate_argnums above); the framework plays by the
    # same rules — one launch, donated buffers.
    def build_framework(use_loss_mode):
        smp.reset()
        smp.init({"microbatches": num_mb, "bf16": bool(on_tpu),
                  "fused_step_donation": True})
        model = smp.DistributedModel(
            gpt2_124m(max_len=seq_len, **model_kwargs)
        )
        optimizer = smp.DistributedOptimizer(optax.adamw(1e-4), model)

        if use_loss_mode:
            @smp.step
            def train_step(model, batch_ids):
                # Fused LM-head CE (model(ids, targets=...)): the [N, V]
                # logits tensor never materializes on TPU — same
                # mean-over-predicted-positions loss as the baseline.
                tgt = jnp.concatenate(
                    [batch_ids[:, 1:],
                     jnp.full_like(batch_ids[:, :1], -100)],
                    axis=1,
                )
                per = model(batch_ids, targets=tgt)
                loss = jnp.sum(per) / (per.shape[0] * (per.shape[1] - 1))
                model.backward(loss)
                return loss
        else:
            @smp.step
            def train_step(model, batch_ids):
                loss = ce_loss(model(batch_ids), batch_ids)
                model.backward(loss)
                return loss

        out = None
        for _ in range(2):
            out = train_step(model, ids)
            optimizer.step()
        _readback(out.reduce_mean())
        return model, optimizer, train_step, out

    try:
        model, optimizer, train_step, out = build_framework(True)
    except Exception as e:  # kernel/backend failure must not kill the bench
        sys.stderr.write(
            f"bench: fused-CE loss mode failed ({e!r}); "
            "falling back to the logits path.\n"
        )
        os.environ["SMP_DISABLE_FUSED_CE"] = "1"
        model, optimizer, train_step, out = build_framework(False)

    # Pipeline schedule of the headline config, captured NOW (the probes
    # below re-init and reset the framework): "none" while the headline
    # runs unpipelined, the cfg knob once it moves to pp >= 2.
    from smdistributed_modelparallel_tpu.backend.state import state as _state

    headline_schedule = (
        _state.cfg.pipeline
        if _state.cfg is not None and _state.cfg.pipeline_parallel_degree > 1
        else "none"
    )

    # ---- interleaved timing (A/B/A/B) ----
    # Chip clock/thermal state drifts over tens of seconds; timing all
    # baseline iterations then all framework iterations folds that drift
    # straight into vs_baseline. Alternating blocks exposes both paths to
    # the same conditions; medians are robust to one slow block.
    base_times, times = [], []
    final_loss = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            p, o, l = base_train(p, o, ids)
        _readback(l)
        base_times.append((time.perf_counter() - t0) / iters)

        t0 = time.perf_counter()
        for _ in range(iters):
            out = train_step(model, ids)
            optimizer.step()
        final_loss = _readback(out.reduce_mean())
        times.append((time.perf_counter() - t0) / iters)
    base_dt = sorted(base_times)[1]  # median of 3 repeats
    dt = sorted(times)[1]
    del p, o

    if os.environ.get("SMP_BENCH_HEALTH_PROBE", "0") == "1":
        # Deadline shares the device-probe window budget: the driver's cap
        # covers waiting AND optional probes, never waiting + overrun.
        _health_overhead_probe(
            train_step, model, optimizer, ids, iters,
            deadline=start_time + probe_window,
        )

    tokens = batch * seq_len
    tok_per_sec_chip = tokens / dt / max(n_chips, 1)
    base_tok_per_sec = tokens / base_dt / max(n_chips, 1)

    flops = _model_flops_per_step(n_layers, d_model, vocab, batch, seq_len)
    peak = _chip_peak_tflops(jax.devices()[0]) if on_tpu else None
    mfu = (flops / dt / 1e12) / peak if peak else None

    # Roofline attribution (smp.profiling): analytic model FLOPs (the MFU
    # definition above, unchanged across rounds) joined with the compiled
    # step's bytes-accessed and the measured step time into the
    # compute/comm/bubble decomposition — recorded in every BENCH_r*.json
    # block so rounds feed scripts/perf_ledger.py without hand arithmetic.
    # On the CPU smoke the peaks are unknown and the fields stay null.
    roofline_out = None
    try:
        from smdistributed_modelparallel_tpu.utils import profiling

        runner = next(iter(train_step._cache.values()), None)
        compiled_exec = (
            runner.holder.get("compiled") if runner is not None else None
        )
        rep = profiling.roofline(
            "bench", step_time_s=dt, flops=float(flops),
            compiled=compiled_exec,
            peak_flops=peak * 1e12 if peak else None,
        )
        rd = rep.as_dict()
        roofline_out = {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in rd.items()
            if k in ("mfu", "bytes_accessed", "arithmetic_intensity",
                     "ridge_intensity", "bound", "compute_s", "memory_s",
                     "bubble_fraction", "bubble_s", "comm_s",
                     "achieved_flops_per_s", "achieved_bytes_per_s")
        }
    except Exception as e:  # attribution must never kill the bench
        sys.stderr.write(f"bench: roofline attribution unavailable ({e!r})\n")

    # Compiled-program X-ray (smp.xray): the headline program's audit
    # summary — collective ops/bytes by kind, remat fraction, replication
    # findings, and the program fingerprint — stamped into every
    # BENCH_r*.json so scripts/perf_ledger.py can flag fingerprint drift
    # between rounds (a schedule/sharding change that nobody documented).
    hlo_audit_out = None
    try:
        from smdistributed_modelparallel_tpu.utils import hlo_audit

        hlo_audit_out = hlo_audit.bench_summary(
            hlo_audit.of_step_function(train_step)
        )
    except Exception as e:  # the audit must never kill the bench
        sys.stderr.write(f"bench: hlo audit unavailable ({e!r})\n")

    # Optional component breakdown (stderr; stdout stays one JSON line).
    # SMP_BENCH_BREAKDOWN=1 localizes the MFU gap: fwd-only vs fwd+bwd vs
    # full step isolates optimizer+update cost; the attention and LM-head
    # microbenches bound the two dominant matmul groups. SMP_BENCH_PROFILE
    # =<dir> additionally captures an XLA trace of the framework loop.
    if os.environ.get("SMP_BENCH_BREAKDOWN", "0") == "1" and on_tpu:
        def timeit(f, *a, reps=20):
            f(*a)
            _readback(jax.tree_util.tree_leaves(f(*a))[0])
            t0 = time.perf_counter()
            for _ in range(reps):
                out_ = f(*a)
            _readback(jax.tree_util.tree_leaves(out_)[0])
            return (time.perf_counter() - t0) / reps * 1e3

        bp = jax.tree_util.tree_map(
            lambda p_: p_.astype(jnp.bfloat16)
            if jnp.issubdtype(p_.dtype, jnp.floating) else p_, model.params)
        mb = ids[: batch // num_mb]

        # Same loss path as the timed step (model loss mode, so the CE
        # dispatch policy applies identically) — the microbench must
        # decompose the step it is compared against.
        def _loss(p_, i_):
            tgt = jnp.concatenate(
                [i_[:, 1:], jnp.full_like(i_[:, :1], -100)], axis=1)
            per = model.module.apply({"params": p_}, i_, targets=tgt)
            return jnp.sum(per) / (per.shape[0] * (per.shape[1] - 1))

        fwd = jax.jit(_loss)
        fwdbwd = jax.jit(jax.grad(_loss))

        from smdistributed_modelparallel_tpu.ops.attention import attention_core

        # Random operands passed as ARGUMENTS: zeros (or closed-over
        # constants) let XLA fold the matmuls away and time nothing.
        kq = jax.random.key(7)
        qkv = jax.random.normal(
            kq, (batch // num_mb, seq_len, 12, 64), jnp.bfloat16)
        attn = jax.jit(jax.grad(lambda q_: jnp.sum(
            attention_core(q_, q_, q_, causal=True).astype(jnp.float32))))

        h = jax.random.normal(
            kq, (batch // num_mb * seq_len, d_model), jnp.bfloat16)
        wte = jax.random.normal(kq, (vocab, d_model), jnp.bfloat16)
        tgt = ids[: batch // num_mb].reshape(-1)
        head_fn = jax.jit(jax.grad(lambda h_, w_: jnp.sum(
            ce_loss((h_ @ w_.T)[None], tgt[None])), argnums=(0, 1)))

        for name_, ms in [
            ("fwd_only_microbatch", timeit(fwd, bp, mb)),
            ("fwd_bwd_microbatch", timeit(fwdbwd, bp, mb)),
            ("attention_fwdbwd_microbatch", timeit(attn, qkv)),
            ("lmhead_ce_fwdbwd_microbatch", timeit(head_fn, h, wte)),
        ]:
            sys.stderr.write(json.dumps(
                {"component": name_, "ms": round(ms, 3)}) + "\n")
        sys.stderr.flush()

    prof_dir = os.environ.get("SMP_BENCH_PROFILE")
    if prof_dir and on_tpu:
        with jax.profiler.trace(prof_dir):
            for _ in range(3):
                out = train_step(model, ids)
                optimizer.step()
            _readback(out.reduce_mean())
        sys.stderr.write(f"bench: profile written to {prof_dir}\n")

    pipeline_probe_out = None
    if os.environ.get("SMP_BENCH_PIPELINE_PROBE", "0") == "1":
        # Last probe: it re-inits the framework (virtual_pipeline_degree
        # changes the partitioning), so the single-chip model/step above
        # must not be used after it.
        pipeline_probe_out = _pipeline_interleave_probe(
            deadline=start_time + probe_window
        )

    zero_probe_out = None
    if os.environ.get("SMP_BENCH_ZERO_PROBE", "0") == "1":
        # Re-inits the framework per block (the sharding mode changes the
        # compiled program); the headline model/step must not be reused
        # afterwards.
        zero_probe_out = _zero_probe(deadline=start_time + probe_window)

    tp_probe_out = None
    if os.environ.get("SMP_BENCH_TP_PROBE", "0") == "1":
        # Re-inits the framework per block (tp_overlap changes the
        # compiled program); the headline model/step must not be reused
        # afterwards.
        tp_probe_out = _tp_probe(deadline=start_time + probe_window)

    exec_cache_out = None
    if os.environ.get("SMP_BENCH_COMPILE_PROBE", "0") == "1":
        # Also re-inits the framework; anything after this point must not
        # touch the headline model/step objects.
        exec_cache_out = _compile_cache_probe(
            deadline=start_time + probe_window
        )

    serving_out = None
    if os.environ.get("SMP_BENCH_SERVE_PROBE", "0") == "1":
        # Also re-inits the framework (single-device serving config).
        serving_out = _serve_probe(deadline=start_time + probe_window)

    autoscale_out = None
    if os.environ.get("SMP_BENCH_AUTOSCALE_PROBE", "0") == "1":
        # Also re-inits the framework (single-device serving config).
        autoscale_out = _autoscale_probe(deadline=start_time + probe_window)

    quant_out = None
    if os.environ.get("SMP_BENCH_QUANT_PROBE", "0") == "1":
        # Also re-inits the framework (the precision knob changes the
        # compiled step program).
        quant_out = _quant_probe(deadline=start_time + probe_window)

    from smdistributed_modelparallel_tpu.ops.attention import _pallas_ok

    q_probe = jnp.zeros((batch // num_mb, seq_len, 12, 64), jnp.bfloat16)
    attn_path = "pallas_flash" if _pallas_ok(q_probe, q_probe, q_probe) else "xla_jnp"

    result = {
        "metric": "tokens/sec/chip GPT-2-124M train step"
                  + ("" if on_tpu else " (CPU smoke, reduced model)"),
        "value": round(tok_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        # Pipeline schedule of the headline config (pp=1 runs none); the
        # perf ledger carries this so rounds that move the schedule knob
        # stay attributable.
        "schedule": headline_schedule,
        "vs_baseline": round(tok_per_sec_chip / base_tok_per_sec, 3),
        "baseline_def": "plain-JAX same-model train step, same run",
        "plain_jax_tokens_per_sec_chip": round(base_tok_per_sec, 2),
        "step_ms": round(dt * 1e3, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "model_tflops_per_step": round(flops / 1e12, 3),
        "chip_peak_bf16_tflops": peak,
        "attention_path": attn_path,
        "roofline": roofline_out,
        "hlo_audit": hlo_audit_out,
        "final_loss": round(final_loss, 4),
    }
    from smdistributed_modelparallel_tpu.utils.goodput import goodput

    gp_block = goodput.bench_block()
    if gp_block is not None:
        result["goodput"] = gp_block
    if exec_cache_out is not None:
        result["exec_cache"] = exec_cache_out
    if serving_out is not None:
        result["serving"] = serving_out
    if autoscale_out is not None:
        result["autoscale"] = autoscale_out
    if quant_out is not None:
        result["quant"] = quant_out
    if zero_probe_out is not None:
        result["zero_probe"] = zero_probe_out
    if tp_probe_out is not None:
        result["tp_overlap"] = tp_probe_out
    if pipeline_probe_out is not None:
        result["pipeline_probe"] = pipeline_probe_out
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
