"""Benchmark: GPT-2 training throughput (tokens/sec/chip).

Runs on whatever accelerator is available (the driver provides one real TPU
chip). Single-chip benchmark = BASELINE config #1 (GPT-2 124M); the
north-star PP4xTP2 GPT-2 1.5B configuration needs a v4-32 and is exercised
multi-chip via ``__graft_entry__.dryrun_multichip``.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is vs the reference's published number for this metric; the
reference ships none in-tree (BASELINE.md), so 1.0 is reported with the raw
value carrying the signal.
"""

import json
import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import optax

    import smdistributed_modelparallel_tpu as smp
    from smdistributed_modelparallel_tpu.models.gpt2 import gpt2_124m

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    seq_len = 1024 if on_tpu else 64
    batch = 8 if on_tpu else 4
    num_mb = 4

    smp.init({"microbatches": num_mb, "bf16": True if on_tpu else False})
    module = gpt2_124m(max_len=seq_len) if on_tpu else gpt2_124m(
        max_len=seq_len, d_model=128, n_layers=2, n_heads=4
    )
    model = smp.DistributedModel(module)
    optimizer = smp.DistributedOptimizer(optax.adamw(1e-4), model)

    @smp.step
    def train_step(model, batch_ids):
        logits = model(batch_ids)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
        tgt = jax.nn.one_hot(batch_ids[:, 1:], logits.shape[-1])
        loss = -jnp.mean(jnp.sum(logp * tgt, axis=-1))
        model.backward(loss)
        return loss

    ids = jax.random.randint(jax.random.key(0), (batch, seq_len), 0, 50257)

    # Warmup (compile).
    for _ in range(2):
        out = train_step(model, ids)
        optimizer.step()
    jax.block_until_ready(model.params)

    iters = 5 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = train_step(model, ids)
        optimizer.step()
    jax.block_until_ready(model.params)
    dt = time.perf_counter() - t0

    tokens = batch * seq_len * iters
    tok_per_sec_chip = tokens / dt / max(n_chips, 1)
    print(json.dumps({
        "metric": "tokens/sec/chip GPT-2-124M train step"
                  + ("" if on_tpu else " (CPU smoke, reduced model)"),
        "value": round(tok_per_sec_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    sys.exit(main())
