// Native pipeline-timeline recorder.
//
// Parity target: the reference's C++ timeline (SURVEY §2.1 N5:
// smp_create_timeline / smp_timeline_start_step / smp_timeline_end_step /
// smp_timeline_record_pipeline_event, bracketed around every server action
// in torch/server.py:366-478).  The reference records from a hot event loop,
// so it lives in C++; here the hot path is inside compiled XLA programs, but
// host-side step brackets still fire per step and per microbatch phase, and
// a Python append + dict build is measurable at small step times.  This
// recorder keeps a preallocated event arena behind a mutex (uncontended in
// the common single-recording-thread case) and serialises to Chrome-trace
// JSON (chrome://tracing / Perfetto) only at flush.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint32_t track_id;
  double ts_us;
  double dur_us;  // < 0 -> instant event
  int64_t step;
  int32_t microbatch;  // -1 -> absent
};

class Timeline {
 public:
  explicit Timeline(const std::string& path) : path_(path) {
    events_.reserve(1 << 16);
    names_.reserve(256);
    tracks_.reserve(16);
  }

  uint32_t Intern(std::vector<std::string>& pool, const char* s) {
    for (uint32_t i = 0; i < pool.size(); ++i)
      if (pool[i] == s) return i;
    pool.emplace_back(s);
    return static_cast<uint32_t>(pool.size() - 1);
  }

  void StartStep(int64_t step) {
    std::lock_guard<std::mutex> lk(mu_);
    step_ = step;
  }

  int64_t EndStep(int64_t step) {
    std::lock_guard<std::mutex> lk(mu_);
    if (step_ == step) step_ = -1;
    return static_cast<int64_t>(events_.size());
  }

  void Record(const char* name, double begin_us, double end_us, int mb,
              const char* track) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{Intern(names_, name), Intern(tracks_, track),
                            begin_us, end_us - begin_us, step_, mb});
  }

  void Instant(const char* name, double ts_us, const char* track) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{Intern(names_, name), Intern(tracks_, track),
                            ts_us, -1.0, step_, -1});
  }

  static std::string JsonEscape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\t': out += "\\t"; break;
        case '\r': out += "\\r"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += c;
          }
      }
    }
    return out;
  }

  int Flush(int pid) {
    std::lock_guard<std::mutex> lk(mu_);
    if (path_.empty()) return -1;
    FILE* f = std::fopen(path_.c_str(), "w");
    if (f == nullptr) return -1;
    std::vector<std::string> esc_names, esc_tracks;
    for (const auto& n : names_) esc_names.push_back(JsonEscape(n));
    for (const auto& t : tracks_) esc_tracks.push_back(JsonEscape(t));
    std::fputs("{\"displayTimeUnit\":\"ms\",\"traceEvents\":[", f);
    for (size_t i = 0; i < events_.size(); ++i) {
      const Event& e = events_[i];
      if (i) std::fputc(',', f);
      if (e.dur_us < 0) {
        std::fprintf(f,
                     "{\"name\":\"%s\",\"ph\":\"i\",\"ts\":%.3f,\"pid\":%d,"
                     "\"tid\":\"%s\",\"s\":\"g\"}",
                     esc_names[e.name_id].c_str(), e.ts_us, pid,
                     esc_tracks[e.track_id].c_str());
      } else {
        std::fprintf(f,
                     "{\"name\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,\"dur\":%.3f,"
                     "\"pid\":%d,\"tid\":\"%s\",\"args\":{\"step\":%lld",
                     esc_names[e.name_id].c_str(), e.ts_us, e.dur_us, pid,
                     esc_tracks[e.track_id].c_str(),
                     static_cast<long long>(e.step));
        if (e.microbatch >= 0)
          std::fprintf(f, ",\"microbatch\":%d", e.microbatch);
        std::fputs("}}", f);
      }
    }
    std::fputs("]}", f);
    std::fclose(f);
    return static_cast<int>(events_.size());
  }

  int64_t Count() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(events_.size());
  }

 private:
  std::string path_;
  std::mutex mu_;
  std::vector<Event> events_;
  std::vector<std::string> names_;
  std::vector<std::string> tracks_;
  int64_t step_ = -1;
};

}  // namespace

extern "C" {

void* smp_create_timeline(const char* path) {
  return new Timeline(path ? path : "");
}

void smp_destroy_timeline(void* t) { delete static_cast<Timeline*>(t); }

void smp_timeline_start_step(void* t, int64_t step) {
  static_cast<Timeline*>(t)->StartStep(step);
}

int64_t smp_timeline_end_step(void* t, int64_t step) {
  return static_cast<Timeline*>(t)->EndStep(step);
}

void smp_timeline_record_pipeline_event(void* t, const char* name,
                                        double begin_us, double end_us,
                                        int microbatch, const char* track) {
  static_cast<Timeline*>(t)->Record(name, begin_us, end_us, microbatch, track);
}

void smp_timeline_record_instant(void* t, const char* name, double ts_us,
                                 const char* track) {
  static_cast<Timeline*>(t)->Instant(name, ts_us, track);
}

int smp_timeline_flush(void* t, int pid) {
  return static_cast<Timeline*>(t)->Flush(pid);
}

int64_t smp_timeline_event_count(void* t) {
  return static_cast<Timeline*>(t)->Count();
}

}  // extern "C"
