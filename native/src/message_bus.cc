// Host-side control-plane message bus.
//
// Parity target: the reference's native async object collectives (SURVEY
// §2.1 N2): smp_async_send / smp_async_recv / smp_wait_recv / smp_poll_recv
// / smp_retrieve_object / smp_clean_recv_resources, called from
// backend/collectives.py:233-324 — pickled-bytes P2P keyed by
// (src, transaction-id), serviced by a background listener thread.
//
// The reference rides MPI; TPU pods have no MPI, and device-level data
// movement happens inside compiled XLA programs over ICI.  What the host
// control plane still needs — checkpoint rendezvous, partition-result
// exchange, user smp.send/smp.recv_from — is a small TCP mesh between
// *processes* (one per host), built here:
//
//   - one listener thread accepts peer connections and demultiplexes
//     frames into an (src, tx) -> payload-queue map;
//   - sends are enqueued and drained by one sender thread per peer, so
//     smp_async_send never blocks on the network;
//   - waits use a condition variable (no spin);
//   - a group barrier (all-to-min then release, reserved tx namespace)
//     gives smp.barrier(group) real subgroup semantics.
//
// Wire format per frame: magic(u32) src(i32) tx(i64) len(i64) payload[len].

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x534d5054;  // "SMPT"

struct Frame {
  int32_t src;
  int64_t tx;
  std::vector<uint8_t> payload;
};

struct FrameHeader {
  uint32_t magic;
  int32_t src;
  int64_t tx;
  int64_t len;
} __attribute__((packed));

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class MessageBus {
 public:
  MessageBus() = default;
  ~MessageBus() { Shutdown(); }

  // Phase 1: bind + start the listener; returns the bound port (supports
  // port 0 -> ephemeral, so Python can exchange real endpoints afterwards).
  int Listen(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return -1;
    if (::listen(listen_fd_, 64) < 0) return -1;
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return ntohs(addr.sin_port);
  }

  // Phase 2: record identity + peer endpoints ("host:port,host:port,...").
  int Connect(int rank, int world, const std::string& endpoints) {
    rank_ = rank;
    world_ = world;
    peers_.clear();
    size_t start = 0;
    while (start <= endpoints.size() && !endpoints.empty()) {
      size_t comma = endpoints.find(',', start);
      std::string item = endpoints.substr(
          start, comma == std::string::npos ? std::string::npos : comma - start);
      size_t colon = item.rfind(':');
      if (colon == std::string::npos) return -1;
      peers_.push_back({item.substr(0, colon),
                        std::stoi(item.substr(colon + 1))});
      if (comma == std::string::npos) break;
      start = comma + 1;
    }
    if (static_cast<int>(peers_.size()) != world) return -1;
    send_threads_.resize(world);
    send_queues_ = std::vector<SendQueue>(world);
    connected_ = true;
    return 0;
  }

  int AsyncSend(int dest, const uint8_t* data, int64_t len, int64_t tx) {
    if (dest == rank_ || (!connected_ && dest == 0 && rank_ == 0)) {
      // Self-send: deliver locally, no socket round-trip.  Also serves the
      // single-process (world=1, never-connected) configuration.
      Frame f{rank_, tx, std::vector<uint8_t>(data, data + len)};
      Deliver(std::move(f));
      return 0;
    }
    if (!connected_ || dest < 0 || dest >= world_) return -1;
    if (send_queues_[dest].dead.load()) {
      // Report the loss (-2), and after a cool-down allow one revival: a
      // fresh SendLoop with a full connect budget. The cool-down is
      // longer than the Python send_bytes retry burst, so a single send's
      // bounded retries still fail typed (SMPPeerLost) — but a LATER send
      // (peer restarted, operator retry) gets a genuine reconnect instead
      // of a permanently wedged link.
      auto& q = send_queues_[dest];
      std::lock_guard<std::mutex> lk(q.mu);
      if (q.dead.load() && NowMs() - q.death_ms.load() > 2000) {
        if (send_threads_[dest].joinable()) send_threads_[dest].join();
        // Frames queued before the link died were acked to their callers
        // but never delivered; replaying them to a RESTARTED peer would
        // inject stale protocol state (e.g. a pre-restart preemption
        // notice on tx -2 retriggering an emergency save). The revived
        // link starts empty — callers that cared got SMPPeerLost.
        q.frames.clear();
        q.thread_started = false;
        q.dead.store(false);
      }
      return -2;
    }
    {
      std::lock_guard<std::mutex> lk(send_queues_[dest].mu);
      send_queues_[dest].frames.push_back(
          Frame{rank_, tx, std::vector<uint8_t>(data, data + len)});
    }
    StartSender(dest);
    send_queues_[dest].cv.notify_all();
    return 0;
  }

  int PollRecv(int src, int64_t tx) {
    std::lock_guard<std::mutex> lk(recv_mu_);
    auto it = inbox_.find(Key(src, tx));
    return (it != inbox_.end() && !it->second.empty()) ? 1 : 0;
  }

  // Blocks until a frame for (src, tx) arrives; returns its length, or -1
  // on timeout (timeout_ms < 0 -> wait forever), or -2 on shutdown.
  int64_t WaitRecv(int src, int64_t tx, int timeout_ms) {
    std::unique_lock<std::mutex> lk(recv_mu_);
    auto ready = [&] {
      auto it = inbox_.find(Key(src, tx));
      return it != inbox_.end() && !it->second.empty();
    };
    if (timeout_ms < 0) {
      recv_cv_.wait(lk, [&] { return ready() || !running_.load(); });
    } else if (!recv_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  [&] { return ready() || !running_.load(); })) {
      return -1;
    }
    if (!ready()) return -2;
    return static_cast<int64_t>(inbox_[Key(src, tx)].front().size());
  }

  // Copies the frontmost (src, tx) payload out and removes it.
  int64_t Retrieve(int src, int64_t tx, uint8_t* out, int64_t cap) {
    std::lock_guard<std::mutex> lk(recv_mu_);
    auto it = inbox_.find(Key(src, tx));
    if (it == inbox_.end() || it->second.empty()) return -1;
    auto& payload = it->second.front();
    auto len = static_cast<int64_t>(payload.size());
    if (len > cap) return -3;
    std::memcpy(out, payload.data(), payload.size());
    it->second.pop_front();
    if (it->second.empty()) inbox_.erase(it);
    return len;
  }

  void CleanRecvResources(int src, int64_t tx) {
    std::lock_guard<std::mutex> lk(recv_mu_);
    inbox_.erase(Key(src, tx));
  }

  // Has the link to `peer` been marked down in EITHER direction?  Send
  // side: this process's sender thread gave up (connect budget exhausted
  // / write failed).  Receive side: a connection that had been carrying
  // `peer`'s frames hit EOF/error while the bus was still running (the
  // peer's process died — its kernel closed the socket).  A fresh frame
  // from the peer (restart, transient) clears the receive-side mark, and
  // the send side has its own revival cool-down in AsyncSend.
  bool PeerDown(int peer) {
    if (peer == rank_) return false;
    if (connected_ && peer >= 0 && peer < world_ &&
        send_queues_[peer].dead.load())
      return true;
    std::lock_guard<std::mutex> lk(down_mu_);
    return recv_down_.count(peer) > 0;
  }

  // WaitRecv sliced with a peer-death probe between slices: a wait on a
  // frame that can never arrive (the sender is dead) returns -100-src
  // immediately instead of burning the full timeout.  Frames already
  // delivered before the death are still handed out first.  `probe`
  // (optional) extends the death check beyond `src` — a barrier member
  // waiting for the ROOT's release must also fail when any OTHER member
  // died, because the root will never release in that case.
  int64_t WaitRecvOrPeerLost(int src, int64_t tx, int timeout_ms,
                             const std::vector<int>* probe = nullptr) {
    int64_t deadline = NowMs() + timeout_ms;
    while (true) {
      if (PollRecv(src, tx) == 0) {
        if (PeerDown(src)) return -100 - src;
        if (probe != nullptr) {
          for (int r : *probe) {
            if (r != rank_ && PeerDown(r)) return -100 - r;
          }
        }
      }
      int64_t left = deadline - NowMs();
      if (left <= 0) return -1;
      int slice = static_cast<int>(std::min<int64_t>(left, 200));
      int64_t n = WaitRecv(src, tx, slice);
      if (n != -1) return n;
    }
  }

  // Group barrier over the bus.  Every member sends a token to the lowest
  // member; the lowest waits for all, then sends a release to each.  Tx ids
  // live in a reserved negative namespace keyed by a per-group counter so
  // interleaved barriers on different groups never collide.
  // Returns 0 on success, -1 on timeout/misuse, -100-r when member `r`'s
  // link is known dead (so the caller can raise a TYPED peer-lost error
  // instead of a generic timeout).
  int Barrier(const int* ranks, int n, int timeout_ms) {
    if (n <= 1) return 0;
    std::vector<int> group(ranks, ranks + n);
    int root = *std::min_element(group.begin(), group.end());
    int64_t seq;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      seq = ++barrier_seq_[GroupHash(group)];
    }
    // tx = -(2*(hash*K + seq)) for arrive, -1 offset for release.
    // +16 reserves tx -1..-33 for control messages outside the barrier
    // namespace (exit-status relay -1, preemption notice -2,
    // backend/core.py / resilience/preemption.py): without the offset,
    // k = hash%100003 == 0 makes the first barriers produce -2/-3.
    int64_t base =
        -(((GroupHash(group) % 100003) * 1000003 + seq) + 16) * 2;
    uint8_t token = 1;
    if (rank_ == root) {
      for (int r : group) {
        if (r == root) continue;
        int64_t w = WaitRecvOrPeerLost(r, base, timeout_ms);
        if (w <= -100) return static_cast<int>(w);
        if (w < 0) return -1;
        Retrieve(r, base, &token, 1);
      }
      for (int r : group) {
        if (r == root) continue;
        int s = AsyncSend(r, &token, 1, base - 1);
        if (s == -2) return -100 - r;
        if (s != 0) return -1;
      }
    } else {
      int s = AsyncSend(root, &token, 1, base);
      if (s == -2) return -100 - root;
      if (s != 0) return -1;
      int64_t w = WaitRecvOrPeerLost(root, base - 1, timeout_ms, &group);
      if (w <= -100) return static_cast<int>(w);
      if (w < 0) return -1;
      Retrieve(root, base - 1, &token, 1);
    }
    return 0;
  }

  void Shutdown() {
    if (shut_.exchange(true)) return;
    // Phase 1: drain outgoing queues. Barrier releases and user sends are
    // async (enqueue-only), so a process may reach shutdown with frames
    // still queued for peers that are blocked waiting on them; killing the
    // senders first would strand those peers until their timeouts.
    send_stop_.store(true);
    for (auto& q : send_queues_) q.cv.notify_all();
    for (auto& t : send_threads_)
      if (t.joinable()) t.join();
    // Phase 2: stop the receive side. Shut accepted sockets down BEFORE
    // joining: RecvLoop threads block in read() on sockets whose remote end
    // is a peer also shutting down — joining first would deadlock two
    // exiting processes on each other.
    running_.store(false);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    recv_cv_.notify_all();
    {
      std::lock_guard<std::mutex> lk(fd_mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : conn_threads_)
      if (t.joinable()) t.join();
    std::lock_guard<std::mutex> lk(fd_mu_);
    for (int fd : conn_fds_) ::close(fd);
    conn_fds_.clear();
  }

 private:
  struct SendQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<Frame> frames;
    int fd = -1;
    bool thread_started = false;
    // Set by SendLoop when it gives up on this link (connect budget
    // exhausted or a write failed): the peer is unreachable and the
    // sender thread has exited, so further enqueues can never deliver.
    // AsyncSend revives the link (fresh thread, fresh connect budget)
    // once `death_ms` is old enough — see the cool-down there.
    std::atomic<bool> dead{false};
    std::atomic<int64_t> death_ms{0};
  };

  static int64_t NowMs() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  static void MarkDead(SendQueue& q) {
    q.death_ms.store(NowMs());
    q.dead.store(true);
  }

  static uint64_t Key(int src, int64_t tx) {
    return (static_cast<uint64_t>(static_cast<uint32_t>(src)) << 48) ^
           static_cast<uint64_t>(tx);
  }

  static uint64_t GroupHash(const std::vector<int>& group) {
    uint64_t h = 1469598103934665603ull;
    for (int r : group) {
      h ^= static_cast<uint64_t>(r) + 1;
      h *= 1099511628211ull;
    }
    return h;
  }

  void Deliver(Frame&& f) {
    {
      // A live frame from `src` is proof the peer is (again) reachable:
      // clear a receive-side down mark so a restarted/flapping peer is
      // not reported dead forever.
      std::lock_guard<std::mutex> lk(down_mu_);
      recv_down_.erase(f.src);
    }
    {
      std::lock_guard<std::mutex> lk(recv_mu_);
      inbox_[Key(f.src, f.tx)].push_back(std::move(f.payload));
    }
    recv_cv_.notify_all();
  }

  void AcceptLoop() {
    while (running_.load()) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = ::accept(listen_fd_, reinterpret_cast<sockaddr*>(&peer), &plen);
      if (fd < 0) break;
      {
        std::lock_guard<std::mutex> lk(fd_mu_);
        conn_fds_.push_back(fd);
        conn_threads_.emplace_back([this, fd] { RecvLoop(fd); });
      }
    }
  }

  void RecvLoop(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // The source rank this connection carries, learned from its frames
    // (each sender thread owns one connection; frames all bear one src).
    int last_src = -1;
    while (running_.load()) {
      FrameHeader h{};
      if (!read_exact(fd, &h, sizeof(h)) || h.magic != kMagic) break;
      Frame f;
      f.src = h.src;
      f.tx = h.tx;
      f.payload.resize(static_cast<size_t>(h.len));
      if (h.len > 0 && !read_exact(fd, f.payload.data(), f.payload.size()))
        break;
      last_src = f.src;
      Deliver(std::move(f));
    }
    // EOF/error while the bus is still running and the peer had
    // identified itself: its process died (or at least closed the
    // stream) — surface it to PeerDown so waits fail typed and fast
    // instead of burning their full timeout.
    if (running_.load() && !shut_.load() && last_src >= 0 &&
        last_src != rank_) {
      {
        std::lock_guard<std::mutex> lk(down_mu_);
        recv_down_.insert(last_src);
      }
      recv_cv_.notify_all();
    }
  }

  void StartSender(int dest) {
    std::lock_guard<std::mutex> lk(send_queues_[dest].mu);
    if (send_queues_[dest].thread_started) return;
    send_queues_[dest].thread_started = true;
    send_threads_[dest] = std::thread([this, dest] { SendLoop(dest); });
  }

  void SendLoop(int dest) {
    auto& q = send_queues_[dest];
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(peers_[dest].second));
    ::inet_pton(AF_INET, peers_[dest].first.c_str(), &addr.sin_addr);
    // Retry connect: peers come up in arbitrary order. The socket must be
    // RECREATED per attempt — a fd whose connect() failed (ECONNREFUSED
    // from a peer whose listener isn't up yet) is not reusable, and
    // retrying on it fails forever: the link stays silently dead in this
    // direction and the peer times out minutes later with no clue.
    int fd = -1;
    for (int attempt = 0; attempt < 600; ++attempt) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd >= 0 &&
          ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0)
        break;
      if (fd >= 0) ::close(fd);
      fd = -1;
      if (shut_.load()) return;
      if (attempt == 599) {
        MarkDead(q);  // peer never came up: link unrecoverable (for now)
        return;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    if (fd < 0) {
      MarkDead(q);
      return;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (true) {
      Frame f;
      {
        std::unique_lock<std::mutex> lk(q.mu);
        q.cv.wait(lk, [&] {
          return !q.frames.empty() || send_stop_.load();
        });
        if (q.frames.empty()) {
          if (send_stop_.load()) break;  // drained; safe to exit
          continue;
        }
        f = std::move(q.frames.front());
        q.frames.pop_front();
      }
      FrameHeader h{kMagic, f.src, f.tx,
                    static_cast<int64_t>(f.payload.size())};
      if (!write_exact(fd, &h, sizeof(h))) {
        if (!shut_.load()) MarkDead(q);  // peer died mid-stream
        break;
      }
      if (!f.payload.empty() &&
          !write_exact(fd, f.payload.data(), f.payload.size())) {
        if (!shut_.load()) MarkDead(q);
        break;
      }
    }
    ::close(fd);  // sender-owned; not in conn_fds_
  }

  std::atomic<bool> running_{false};
  std::atomic<bool> send_stop_{false};
  std::atomic<bool> shut_{false};
  bool connected_ = false;
  int rank_ = 0;
  int world_ = 1;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::vector<std::pair<std::string, int>> peers_;

  std::vector<SendQueue> send_queues_;
  std::vector<std::thread> send_threads_;
  std::vector<std::thread> conn_threads_;
  std::mutex fd_mu_;
  std::vector<int> conn_fds_;

  std::mutex recv_mu_;
  std::condition_variable recv_cv_;
  std::map<uint64_t, std::deque<std::vector<uint8_t>>> inbox_;

  std::mutex down_mu_;
  std::set<int> recv_down_;

  std::mutex barrier_mu_;
  std::map<uint64_t, int64_t> barrier_seq_;
};

MessageBus* g_bus = nullptr;
std::mutex g_bus_mu;

}  // namespace

extern "C" {

int smp_bus_listen(int port) {
  std::lock_guard<std::mutex> lk(g_bus_mu);
  if (g_bus == nullptr) g_bus = new MessageBus();
  return g_bus->Listen(port);
}

int smp_bus_connect(int rank, int world, const char* endpoints) {
  std::lock_guard<std::mutex> lk(g_bus_mu);
  if (g_bus == nullptr) return -1;
  return g_bus->Connect(rank, world, endpoints ? endpoints : "");
}

int smp_async_send(int dest, const uint8_t* data, int64_t len, int64_t tx) {
  if (g_bus == nullptr) return -1;
  return g_bus->AsyncSend(dest, data, len, tx);
}

int smp_poll_recv(int src, int64_t tx) {
  if (g_bus == nullptr) return 0;
  return g_bus->PollRecv(src, tx);
}

int64_t smp_wait_recv(int src, int64_t tx, int timeout_ms) {
  if (g_bus == nullptr) return -2;
  return g_bus->WaitRecv(src, tx, timeout_ms);
}

int64_t smp_retrieve_object(int src, int64_t tx, uint8_t* out, int64_t cap) {
  if (g_bus == nullptr) return -1;
  return g_bus->Retrieve(src, tx, out, cap);
}

void smp_clean_recv_resources(int src, int64_t tx) {
  if (g_bus != nullptr) g_bus->CleanRecvResources(src, tx);
}

int smp_bus_barrier(const int* ranks, int n, int timeout_ms) {
  if (g_bus == nullptr) return -1;
  return g_bus->Barrier(ranks, n, timeout_ms);
}

int smp_peer_down(int peer) {
  if (g_bus == nullptr) return 0;
  return g_bus->PeerDown(peer) ? 1 : 0;
}

void smp_bus_shutdown() {
  std::lock_guard<std::mutex> lk(g_bus_mu);
  if (g_bus != nullptr) {
    g_bus->Shutdown();
    delete g_bus;
    g_bus = nullptr;
  }
}

}  // extern "C"
