"""Train GPT-2 with pipeline + tensor parallelism on synthetic data.

Run on any host (uses an 8-virtual-device CPU mesh when no TPUs):
    python examples/train_gpt2_pp_tp.py
On a TPU slice, drop the platform overrides and scale the degrees.
"""

import os
import sys

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    # The env var alone is not enough on hosts whose TPU plugin pins the
    # platform; force it at the config level too.
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.models.gpt2 import gpt2


def main():
    smp.init({
        "pipeline_parallel_degree": 2,
        "tensor_parallel_degree": 2,
        "ddp": True,
        "microbatches": 4,
    })
    print(f"mesh: {dict(smp.get_mesh().shape)}")

    model = smp.DistributedModel(
        gpt2("gpt2_124m", vocab_size=256, max_len=32,
             d_model=32, n_layers=4, n_heads=2)
    )
    optimizer = smp.DistributedOptimizer(
        optax.adamw(3e-4), model, grad_clip_norm=1.0
    )

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, ids[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        loss = jnp.mean(lse - tgt.astype(jnp.float32))
        model.backward(loss)
        return loss

    def synthetic_batches(n, B=8, T=32):
        rng = np.random.RandomState(0)
        for _ in range(n):
            yield {"ids": rng.randint(0, 256, (B, T))}

    for step, batch in enumerate(smp.dataloader(synthetic_batches(4))):
        out = train_step(model, jnp.asarray(batch["ids"]))
        optimizer.step()
        print(f"step {step}: loss={float(out.reduce_mean()):.4f}")

    smp.save_checkpoint("/tmp/smp_example_ckpt", tag="final",
                        model=model, optimizer=optimizer, blocking=False)
    smp.wait_for_checkpoints()
    print("checkpoint saved; done.")


if __name__ == "__main__":
    main()
