"""Long-context ring attention + Mixture-of-Experts in one model.

The sequence axis shards over the cp mesh axis (zigzag ring attention with
causal load balancing); MoE experts shard over ep. Both are TPU-native
capabilities beyond the reference framework.
    python examples/long_context_moe.py
"""

import os
import sys

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import smdistributed_modelparallel_tpu as smp


def main():
    smp.init({
        "context_parallel_degree": 2,
        "expert_parallel_degree": 2,
        "context_parallel_impl": "ring",
        "ddp": True,
        "microbatches": 2,
    })
    print(f"mesh: {dict(smp.get_mesh().shape)}")

    model = smp.DistributedModel(smp.nn.DistributedTransformerLMHead(
        num_layers=2, num_attention_heads=4, attention_head_size=8,
        hidden_size=32, intermediate_size=64, vocab_size=256,
        num_positions=128, causal_mask_size=128,
        pre_layernorm=True, post_layernorm=False, final_layernorm=True,
        attention_dropout_prob=0.0, hidden_dropout_prob=0.0,
        embedding_dropout_prob=0.0,
        num_experts=4,            # MoE over ep
        deterministic=True,
    ))
    optimizer = smp.DistributedOptimizer(optax.adamw(3e-4), model)

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, ids[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        loss = jnp.mean(lse - tgt.astype(jnp.float32))
        model.backward(loss)
        return loss

    rng = np.random.RandomState(0)
    for step in range(3):
        ids = jnp.asarray(rng.randint(0, 256, (4, 128)))  # T=128 over cp=2
        out = train_step(model, ids)
        optimizer.step()
        print(f"step {step}: loss={float(out.reduce_mean()):.4f}")
    print("ring-attention + MoE training done.")


if __name__ == "__main__":
    main()
