"""Train-then-sample: fine-tune a GPT-2-style LM under tp2, then generate
continuations with the KV-cache decode path (``smp.generate``).

Generation is a TPU extension beyond the reference (a training library):
prefill + every decode step compile into ONE program (no per-token host
round trips), and the same tensor-parallel sharding that trained the
weights serves them.
    python examples/generate_after_finetune.py
"""

import os
import sys

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import smdistributed_modelparallel_tpu as smp
from smdistributed_modelparallel_tpu.models.gpt2 import gpt2


def main():
    smp.init({"tensor_parallel_degree": 2, "ddp": True, "microbatches": 2})
    print(f"mesh: {dict(smp.get_mesh().shape)}")

    vocab, seq = 257, 32
    model = smp.DistributedModel(
        gpt2(vocab_size=vocab, max_len=64, d_model=64, n_layers=2, n_heads=4)
    )
    optimizer = smp.DistributedOptimizer(optax.adamw(3e-3), model)

    # A toy skill the model can learn quickly: a fixed set of 4-token
    # motifs, each row one motif repeated. The transition statistics are
    # memorizable in tens of steps; continuation = keep the cycle.
    rng = np.random.default_rng(0)
    motifs = rng.integers(0, vocab, size=(6, 4))

    def batch(n=8):
        rows = motifs[rng.integers(0, len(motifs), size=n)]
        return np.tile(rows, (1, seq // 4))

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        tgt = ids[:, 1:]
        lse = jax.scipy.special.logsumexp(
            logits[:, :-1].astype(jnp.float32), axis=-1
        )
        picked = jnp.take_along_axis(
            logits[:, :-1], tgt[:, :, None], axis=-1
        )[..., 0].astype(jnp.float32)
        loss = jnp.mean(lse - picked)
        model.backward(loss)
        return loss

    for it in range(100):
        loss = train_step(model, jnp.asarray(batch())).reduce_mean()
        optimizer.step()
        if it % 20 == 0:
            print(f"step {it:3d}  loss {float(loss):.4f}")

    # Greedy continuation of fresh periodic prompts.
    full = batch(4)
    prompts = jnp.asarray(full[:, :8])
    out = np.asarray(model.generate(prompts, 8))
    correct = sum(
        int(np.array_equal(out[row, 8:], full[row, 8:16])) for row in range(4)
    )
    print(f"greedy continuations correct for {correct}/4 prompts")
    print("sampled:", np.asarray(
        model.generate(prompts, 8, temperature=0.8, top_k=20,
                       rng=jax.random.key(0))
    )[0, 8:])

    # ---- The same workflow under PIPELINE parallelism -----------------
    # Decode never runs the pipeline schedule: smp.generate regathers the
    # pp-stage-sharded layer stacks onto the full mesh automatically
    # (model.regather_for_decode, cached between calls), so training at
    # pp x tp and sampling need no topology change.
    trained = model.state_dict()
    smp.reset()
    smp.init({"pipeline_parallel_degree": 2, "tensor_parallel_degree": 2,
              "ddp": True, "microbatches": 2})
    print(f"\npp x tp mesh: {dict(smp.get_mesh().shape)}")
    model = smp.DistributedModel(
        gpt2(vocab_size=vocab, max_len=64, d_model=64, n_layers=2, n_heads=4)
    )
    optimizer = smp.DistributedOptimizer(optax.adamw(3e-3), model)
    loss = train_step(model, jnp.asarray(batch())).reduce_mean()
    optimizer.step()
    print(f"pp step loss {float(loss):.4f} (fresh init; now loading the "
          "tp-phase weights)")
    model.load_state_dict(trained)  # reuse the tp-phase weights
    out_pp = np.asarray(model.generate(prompts, 8))
    assert np.array_equal(out_pp, out), "pp decode must match tp decode"
    print("pp2 x tp2 generation matches the tp2 run token for token")


if __name__ == "__main__":
    main()
