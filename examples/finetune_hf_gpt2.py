"""Fine-tune a HuggingFace GPT-2 under tensor parallelism.

Loads HF weights via smp.from_hf, trains under tp, saves a full
checkpoint back in HF naming (loadable by transformers).
    python examples/finetune_hf_gpt2.py
"""

import os
import sys

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax
import transformers

import smdistributed_modelparallel_tpu as smp


def main():
    smp.init({"tensor_parallel_degree": 4, "ddp": True, "microbatches": 2})

    # A tiny random-weight GPT-2 stands in for a pretrained one; with real
    # weights this is transformers.GPT2LMHeadModel.from_pretrained("gpt2").
    config = transformers.GPT2Config(
        n_embd=64, n_layer=2, n_head=4, vocab_size=256, n_positions=32,
        attn_pdrop=0.0, resid_pdrop=0.0, embd_pdrop=0.0,
    )
    hf_model = transformers.GPT2LMHeadModel(config)

    model = smp.from_hf(hf_model)
    optimizer = smp.DistributedOptimizer(optax.adamw(1e-4), model)

    @smp.step
    def train_step(model, ids):
        logits = model(ids)
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, ids[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        loss = jnp.mean(lse - tgt.astype(jnp.float32))
        model.backward(loss)
        return loss

    rng = np.random.RandomState(0)
    for step in range(4):
        ids = jnp.asarray(rng.randint(0, 256, (8, 32)))
        out = train_step(model, ids)
        optimizer.step()
        print(f"step {step}: loss={float(out.reduce_mean()):.4f}")

    # Full checkpoint in HF naming; reloadable by transformers.
    smp.save_checkpoint("/tmp/smp_example_hf", tag="tuned", model=model,
                        partial=False, translate_if_full=True)
    import pickle

    with open("/tmp/smp_example_hf/tuned", "rb") as fh:
        sd = pickle.load(fh)["model"]
    import torch

    hf_model.load_state_dict(
        {k: torch.tensor(np.asarray(v)) for k, v in sd.items()}
    )
    print("tuned weights loaded back into the HF model; done.")

    # Sample from the tuned model in-framework (KV-cache decode on the
    # same tp mesh that trained it) and check token-exact agreement with
    # the exported HF model's own generate.
    prompts = jax.random.randint(jax.random.key(1), (2, 6), 1, 64)
    ours = np.asarray(model.generate(prompts, 6))
    hf_model.eval()
    with torch.no_grad():
        t_ids = torch.tensor(np.asarray(prompts))
        theirs = hf_model.generate(
            t_ids, attention_mask=torch.ones_like(t_ids),
            max_new_tokens=6, do_sample=False, pad_token_id=0,
        ).numpy()
    assert np.array_equal(ours, theirs), "in-framework vs exported-HF generate"
    print("generation: in-framework == exported-HF, tokens", ours[0, 6:].tolist())


if __name__ == "__main__":
    main()
