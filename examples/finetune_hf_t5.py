"""Fine-tune a HuggingFace T5 under pipeline x tensor parallelism.

Loads HF T5 weights via smp.from_hf (full encoder-decoder translation —
RMSNorm, relative-position buckets, tied-head rescale), trains under
pp2 x tp2 with activation checkpointing + offload (BASELINE config #5's
shape, scaled down), and exports the fine-tuned weights back to HF
naming (loadable by transformers).
    python examples/finetune_hf_t5.py
"""

import os
import sys

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not os.environ.get("SMP_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax
import torch
import transformers

import smdistributed_modelparallel_tpu as smp


def main():
    smp.init({
        "pipeline_parallel_degree": 2,
        "tensor_parallel_degree": 2,
        "ddp": True,
        "microbatches": 2,
        "offload_activations": True,
    })

    # A tiny random-weight T5 stands in for a pretrained one; with real
    # weights this is transformers.T5ForConditionalGeneration
    # .from_pretrained("t5-3b") (or a gated/untied v1.1 such as
    # "google/flan-t5-base" — both dialects translate).
    config = transformers.T5Config(
        vocab_size=256, d_model=64, d_kv=16, num_heads=4, num_layers=2,
        num_decoder_layers=4, d_ff=128, dropout_rate=0.0,
        feed_forward_proj="relu",
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(config).eval()

    # translate -> DistributedModel; the encoder runs inside the pipeline's
    # embed phase (tp/dp-parallel), the decoder stack is pipelined.
    model = smp.from_hf(hf, deterministic=True,
                        activation_checkpointing=True)
    opt = smp.DistributedOptimizer(optax.adamw(3e-4), model)

    @smp.step
    def train_step(model, enc, dec):
        logits = model(enc, dec)
        lg = logits[:, :-1]
        tgt = jnp.take_along_axis(lg, dec[:, 1:, None], axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(lg.astype(jnp.float32), axis=-1)
        loss = jnp.mean(lse - tgt.astype(jnp.float32))
        model.backward(loss)
        return loss

    rng = np.random.RandomState(0)
    enc = jnp.asarray(rng.randint(0, 256, (4, 16)))
    dec = jnp.asarray(rng.randint(0, 256, (4, 8)))
    for step in range(5):
        out = train_step(model, enc, dec)
        opt.step()
        print(f"step {step}: loss {float(out.reduce_mean()):.4f}")

    # Export back to HF naming and reload into a fresh transformers model.
    from smdistributed_modelparallel_tpu.module_manager import path_key
    from smdistributed_modelparallel_tpu.nn.huggingface import t5 as t5mod

    flat = {
        path_key(path): np.asarray(jax.device_get(leaf))
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(model.params)[0]
    }
    sd = t5mod.translate_state_dict_to_hf(flat, config=config)
    fresh = transformers.T5ForConditionalGeneration(config)
    missing, unexpected = fresh.load_state_dict(
        {k: torch.tensor(v) for k, v in sd.items()}, strict=False
    )
    assert not missing and not unexpected, (missing, unexpected)
    print("fine-tuned weights reloaded into transformers — OK")


if __name__ == "__main__":
    main()
