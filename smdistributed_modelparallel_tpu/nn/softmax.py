"""Fused scaled-masked softmax surfaces.

Parity target: reference ``torch/nn/softmax.py:15-93``
(``ScaledMaskedSoftmax`` / ``ScaledCausalMaskedSoftmax`` wrapping the
``smp_torch_cuda_lib`` fused kernels, SURVEY §2.1 N8; fp16/bf16 only, with
``can_use_fused_kernel`` dispatch at ``torch/nn/transformer.py:83-112``).

TPU-native re-design: the default path is plain jnp — XLA fuses
scale+mask+softmax into one HBM pass on TPU, which is what the reference's
hand-written CUDA kernel buys on GPU. A Pallas flash-attention kernel
(``ops/pallas_attention.py``) goes further and never materializes the
[T, T] score matrix; ``DistributedAttentionLayer`` dispatches to it when
``cfg.use_pallas_kernels`` and shapes allow.
"""

import jax
import jax.numpy as jnp


def scaled_masked_softmax(scores, mask, scale=1.0):
    """softmax(scores * scale + mask_bias) over the last axis.

    ``mask``: bool (True = keep) or additive-bias array broadcastable to
    ``scores``; None for no masking.
    """
    s = scores.astype(jnp.float32) * scale
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
        else:
            s = s + mask
    return jax.nn.softmax(s, axis=-1).astype(scores.dtype)


def scaled_causal_masked_softmax(scores, scale=1.0, window=None):
    """Causal (optionally windowed) variant; scores [..., T, S].

    Parity: ``ScaledCausalMaskedSoftmax`` + the windowed causal mask buffer
    (``torch/nn/transformer.py:1331-1352``).
    """
    from smdistributed_modelparallel_tpu.ops.attention import causal_window_mask

    T, S = scores.shape[-2], scores.shape[-1]
    mask = causal_window_mask(T, S, window)
    return scaled_masked_softmax(scores, mask, scale)
