"""DistributedLinear — tensor-parallel linear layer.

Parity target: reference ``torch/nn/linear.py:21-63``: input-partitioned
Linear (scatter-merge the input over tp ranks -> local matmul ->
reduce-scatter the output; bias applied on tp_rank 0 only).

TPU-native re-design: the weight's input dimension carries the ``tp`` mesh
axis (row-parallel); GSPMD inserts the reduce-scatter/allreduce the
reference codes as ``ScatterAndMergeForTP``/``ReduceScatterForTP``
(``torch/nn/utils.py:563-663``). A column-parallel variant (output
partition) is provided for building block use; the reference expresses the
same two layouts as ``initialize_with_input_partition`` /
``initialize_with_output_partition`` (``torch/nn/utils.py:155-249``).

Resharding audit (PR 15): back-to-back tp pairs (column -> row, the
Megatron block shape) were X-ray-probed on this GSPMD path for redundant
collectives from ``shard_activation`` re-constraining already-sharded
activations. The census shows the constraints are free — a matched pair
compiles to exactly its tp all-reduces, ZERO tp all-gathers (XLA elides
a ``sharding_constraint`` whose operand already carries the sharding) —
so no constraint-skipping special case is warranted;
``tests/test_tp_overlap.py::TestGspmdReshardPin`` pins that census.

``tp_overlap: "ring"`` (ops/collective_matmul.py) replaces the
GSPMD-inserted synchronous collectives of both layouts with ring
decompositions whose ppermute hops hide under partial matmuls; the
layers below dispatch there when the knob and geometry allow and keep
this GSPMD path byte-identical otherwise.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.nn.utils import (
    dense_init,
    partitioned,
    shard_activation,
    tp_ring_active as _ring_active,
)


def _maybe_fp8_matmul(x, w, site):
    """The GSPMD-path matmul, routed through the fp8 delayed-scaling
    seam when a quant step trace is active (matmul_precision: fp8);
    byte-identical ``x @ w`` otherwise."""
    from smdistributed_modelparallel_tpu import quant

    if quant.fp8_trace_active():
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_quant_dispatch,
        )

        record_quant_dispatch(site, "fp8")
        return quant.fp8_matmul(x, w, site)
    return x @ w


class DistributedLinear(nn.Module):
    """Row-parallel (input-partitioned) linear: y = x @ W + b.

    W: [in, out] sharded (tp, None) — each tp rank holds an input-slab;
    the partial products are combined by a GSPMD-inserted reduce.
    """

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init_scale: Optional[float] = None
    # User-provided initializer (e.g. carried over from a distributed
    # nn.Dense); seed-consistent — flax hands every tp shard the same key
    # and the partitioned wrapper slices the result, so the values match
    # an undistributed init of the same seed.
    kernel_init: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        init = self.kernel_init or dense_init(self.kernel_init_scale)
        kernel = self.param(
            "kernel",
            partitioned(init, (TP_AXIS, None)),
            (in_features, self.features),
            self.dtype or x.dtype,
        )
        y = None
        if x.ndim >= 2 and _ring_active():
            # Overlapped tp (tp_overlap: ring): the row-parallel output
            # reduce lowers to an accumulator ppermute ring instead of
            # the GSPMD all-reduce, and the output stays ROW-sharded
            # over tp on dim -2 (the Megatron-SP sequence-parallel
            # contract — a consuming ColumnParallelLinear's ring
            # regathers it hop by hop). The logical value is identical;
            # only the layout differs.
            from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                ring_rs_matmul,
            )

            y = ring_rs_matmul(x, kernel.astype(x.dtype), n_contract=1)
            if y is not None:
                y = shard_activation(
                    y, *([None] * (y.ndim - 2) + [TP_AXIS, None])
                )
        if y is None:
            # Input features sharded over tp: each rank computes a partial
            # matmul; XLA reduces. (Reference: scatter_and_merge input then
            # local matmul, torch/nn/linear.py:40-57.)
            x = shard_activation(x, *([None] * (x.ndim - 1) + [TP_AXIS]))
            y = _maybe_fp8_matmul(x, kernel.astype(x.dtype), "linear_row")
            y = shard_activation(y, *([None] * y.ndim))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), self.dtype or x.dtype
            )
            y = y + bias.astype(y.dtype)
        return y


class ColumnParallelLinear(nn.Module):
    """Output-partitioned linear: W [in, out] sharded (None, tp); output's
    feature dim stays sharded over tp (consumed by a row-parallel layer).

    Parity: reference ``initialize_with_output_partition`` users, e.g. the
    head-partitioned QKV projection (``torch/nn/transformer.py:1273-1290``).
    """

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init_scale: Optional[float] = None
    kernel_init: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            partitioned(
                self.kernel_init or dense_init(self.kernel_init_scale),
                (None, TP_AXIS),
            ),
            (in_features, self.features),
            self.dtype or x.dtype,
        )
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias",
                partitioned(nn.initializers.zeros, (TP_AXIS,)),
                (self.features,),
                self.dtype or x.dtype,
            )
        if x.ndim >= 2 and _ring_active():
            # Overlapped tp: the input arrives row-sharded over tp on
            # dim -2 (a preceding ring RowParallelLinear's layout, or a
            # free replicated->sharded slice) and regathers hop by hop
            # under the partial matmuls; bias folds into the chunks.
            from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                ring_ag_matmul,
            )

            y = ring_ag_matmul(
                x, kernel.astype(x.dtype),
                bias.astype(x.dtype) if bias is not None else None,
                w_tp_dim=1,
            )
            if y is not None:
                return shard_activation(
                    y, *([None] * (y.ndim - 1) + [TP_AXIS])
                )
        y = _maybe_fp8_matmul(x, kernel.astype(x.dtype), "linear_col")
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return shard_activation(y, *([None] * (y.ndim - 1) + [TP_AXIS]))


class RowParallelLinear(DistributedLinear):
    """Input-partitioned linear consuming a tp-sharded feature axis and
    producing a replicated output (the Megatron pair of ColumnParallel) —
    ``DistributedLinear`` under its building-block name."""
