"""Tensor-parallelism registry: maps module classes to distributed versions.

Parity target: reference ``torch/tp_registry.py:164-311``
(``TensorParallelismRegistry``): records constructor args of registered
classes, re-instantiates marked modules as their Distributed* counterparts
with translated arguments, and exposes ``smp.tp_register`` /
``smp.tp_register_with_module``. In the TPU build, modules are Flax modules;
"re-instantiation" swaps the module class at DistributedModel construction
time, with init-hook argument translation identical in spirit.
"""

from smdistributed_modelparallel_tpu.utils.exceptions import TensorParallelismError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


class TensorParallelismRegistry:
    def __init__(self):
        # original class -> (distributed class, init_hook, forward_hook, return_hook)
        self._map = {}
        self._translate_functions = {}  # dist class -> (to_hf, from_hf) state translators

    def register(self, origin_cls, dist_cls, init_hook=None, forward_hook=None,
                 return_hook=None, translate_functions=None):
        if origin_cls in self._map:
            logger.debug("Overwriting tp registration for %s", origin_cls.__name__)
        self._map[origin_cls] = (dist_cls, init_hook, forward_hook, return_hook)
        if translate_functions is not None:
            self._translate_functions[dist_cls] = translate_functions

    def is_supported(self, origin_cls):
        return origin_cls in self._map

    def distributed_class(self, origin_cls):
        try:
            return self._map[origin_cls][0]
        except KeyError:
            raise TensorParallelismError(
                f"{origin_cls.__name__} has no registered distributed counterpart; "
                f"use smp.tp_register / smp.tp_register_with_module."
            )

    def hooks(self, origin_cls):
        _, init_hook, forward_hook, return_hook = self._map[origin_cls]
        return init_hook, forward_hook, return_hook

    def distribute(self, origin_cls, args, kwargs, tp_config=None):
        """Build the distributed counterpart of origin_cls(*args, **kwargs).

        Returns None when the init hook declines (reference T5 relative-
        bias block). When forward/return hooks are registered, the module
        is wrapped in a scope-sharing shim that applies them at call time
        (parity: reference ``DistributedModule.__call__``,
        ``torch/nn/dist_module.py:5-32``).
        """
        dist_cls, init_hook, forward_hook, return_hook = self._map[origin_cls]
        if init_hook is not None:
            hooked = init_hook(*args, **kwargs)
            if hooked is None:
                return None
            args, kwargs = hooked
        kwargs = dict(kwargs)
        if tp_config:
            kwargs.update(tp_config)
        module = dist_cls(*args, **kwargs)
        if forward_hook is not None or return_hook is not None:
            from smdistributed_modelparallel_tpu.nn.auto_distribute import (
                HookedModule,
            )

            module = HookedModule(
                inner=module, fwd_hook=forward_hook, ret_hook=return_hook
            )
        return module

    def translate_functions(self, dist_cls):
        return self._translate_functions.get(dist_cls)


def tp_register(origin_cls, init_hook=None, forward_hook=None, return_hook=None,
                translate_functions=None):
    """Decorator form: ``@smp.tp_register(nn.Linear, ...) class DistLinear``.

    Parity: reference ``torch/tp_registry.py:282-296``.
    """

    def wrap(dist_cls):
        from smdistributed_modelparallel_tpu.backend.state import state

        registry = state.tp_registry or TensorParallelismRegistry()
        state.tp_registry = registry
        registry.register(origin_cls, dist_cls, init_hook, forward_hook, return_hook,
                          translate_functions)
        return dist_cls

    return wrap


def tp_register_with_module(origin_cls, dist_cls, init_hook=None, forward_hook=None,
                            return_hook=None, translate_functions=None):
    """Function form. Parity: reference ``torch/tp_registry.py:298-310``."""
    from smdistributed_modelparallel_tpu.backend.state import state

    registry = state.tp_registry or TensorParallelismRegistry()
    state.tp_registry = registry
    registry.register(origin_cls, dist_cls, init_hook, forward_hook, return_hook,
                      translate_functions)
