"""DistributedEmbedding — vocab- or dim-parallel embedding.

Parity target: reference ``torch/nn/embedding.py:26-290``:
``split="vocab"`` shards the vocabulary across tp ranks
(``DistVocabSplitFunction`` masks out-of-range ids and allreduces,
``:204-289``); ``split="dim"`` (``_distribute_embedding_dim``) shards the
embedding dimension and allgathers.

TPU-native re-design: the table carries the tp axis on the chosen dim; the
lookup is expressed as a one-hot matmul so the contraction maps onto the
MXU *and* GSPMD turns the vocab-sharded case into exactly the reference's
mask+partial-lookup+allreduce pattern — no hand-written masking. For
``split="dim"`` a plain take with the hidden axis sharded suffices.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.nn.utils import partitioned, shard_activation
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError


class DistributedEmbedding(nn.Module):
    """Tensor-parallel embedding table [num_embeddings, features]."""

    num_embeddings: int
    features: int
    split: str = "vocab"           # "vocab" | "dim"  (reference: split arg)
    dtype: Optional[jnp.dtype] = None
    init_scale: float = 0.02
    one_hot_lookup: Optional[bool] = None  # default: on for vocab-split

    def setup(self):
        if self.split not in ("vocab", "dim"):
            raise SMPValidationError(
                f"DistributedEmbedding split must be 'vocab' or 'dim', got {self.split!r}"
            )
        names = (TP_AXIS, None) if self.split == "vocab" else (None, TP_AXIS)
        self.embedding = self.param(
            "embedding",
            partitioned(nn.initializers.normal(stddev=self.init_scale), names),
            (self.num_embeddings, self.features),
            self.dtype or jnp.float32,
        )

    def __call__(self, ids):
        table = self.embedding
        use_one_hot = (
            self.one_hot_lookup
            if self.one_hot_lookup is not None
            else self.split == "vocab"
        )
        if use_one_hot:
            # One-hot contraction: MXU-friendly and GSPMD-partitionable on
            # the sharded vocab dim (each rank contracts only its slab; the
            # psum is the reference's allreduce, torch/nn/embedding.py:267).
            one_hot = jax.nn.one_hot(ids, self.num_embeddings, dtype=table.dtype)
            out = one_hot @ table
        else:
            out = jnp.take(table, ids, axis=0)
        if self.split == "dim":
            out = shard_activation(out, *([None] * (out.ndim - 1) + [TP_AXIS]))
        else:
            out = shard_activation(out, *([None] * out.ndim))
        return out

    def attend(self, x):
        """Tied-weights logits: x @ table.T — the LM head over the (possibly
        vocab-sharded) table; output vocab axis sharded over tp. Parity:
        tied lm_head in ``DistributedTransformerLMHead``
        (``torch/nn/transformer.py:520-548``)."""
        table = self.embedding
        logits = x @ table.astype(x.dtype).T
        if self.split == "vocab":
            spec = [None] * (logits.ndim - 1) + [TP_AXIS]
            logits = shard_activation(logits, *spec)
        return logits
