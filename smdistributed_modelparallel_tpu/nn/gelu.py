"""Fused bias-gelu.

Parity target: reference ``torch/nn/gelu.py:29-64`` (torchscript-fused
bias+gelu forward/backward). On TPU, XLA fuses the bias add and gelu into
the producing matmul's epilogue; the function exists for API parity and to
pin the tanh approximation the reference uses.
"""

import flax.linen as nn


def bias_gelu(x, bias):
    return nn.gelu(x + bias, approximate=True)


def gelu(x):
    return nn.gelu(x, approximate=True)
