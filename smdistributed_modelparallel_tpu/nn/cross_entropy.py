"""DistributedCrossEntropy — vocab-parallel cross-entropy.

Parity target: reference ``torch/nn/cross_entropy.py:28-112``
(Megatron-style): local max -> allreduce-max -> mask local target logits ->
allreduce of target-logit and sum-exp -> loss.

TPU-native re-design: written as a numerically-stable log-softmax over the
(tp-sharded) vocab axis with sharding constraints; GSPMD emits the same
max/sum allreduces the reference codes explicitly. The target-logit gather
is a one-hot contraction (MXU-friendly, partitionable over the sharded
vocab dim).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.nn.utils import shard_activation


def vocab_parallel_cross_entropy(logits, targets, label_smoothing=0.0):
    """Per-token cross-entropy loss.

    Args:
      logits: [..., vocab] (vocab axis may be tp-sharded).
      targets: [...] int ids.
    Returns:
      [...] per-token losses (fp32).
    """
    vocab = logits.shape[-1]
    spec = [None] * (logits.ndim - 1) + [TP_AXIS]
    logits = shard_activation(logits, *spec)
    logits_f = logits.astype(jnp.float32)
    # Stable logsumexp over the sharded vocab axis: GSPMD lowers max/sum to
    # the reference's allreduce(max)/allreduce(sum) pair
    # (torch/nn/cross_entropy.py:42-71).
    m = jax.lax.stop_gradient(jnp.max(logits_f, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits_f - m), axis=-1)) + m[..., 0]
    one_hot = jax.nn.one_hot(targets, vocab, dtype=logits_f.dtype)
    target_logit = jnp.sum(logits_f * one_hot, axis=-1)
    loss = lse - target_logit
    if label_smoothing > 0.0:
        # mean over vocab of -log_softmax == lse - mean(logits): reuses the
        # lse above instead of a second [.., V] fp32 log-softmax (and its
        # extra allreduce pair under tp).
        smooth = lse - jnp.mean(logits_f, axis=-1)
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    return loss


def masked_vocab_parallel_cross_entropy(logits, targets, ignore_index=-100,
                                        label_smoothing=0.0):
    """``vocab_parallel_cross_entropy`` with HF-convention ignored labels:
    ``ignore_index`` positions contribute 0 loss and no gradient."""
    valid = targets != ignore_index
    per = vocab_parallel_cross_entropy(
        logits, jnp.where(valid, targets, 0),
        label_smoothing=label_smoothing,
    )
    return jnp.where(valid, per, 0.0)


def _build_tp_fused_ce(mesh, v_global, block_n, block_v, interpret,
                       smoothing):
    """Vocab-parallel fused CE for the tp-sharded table (cached in
    ``pallas_ce.make_vocab_parallel_fused_ce``; partial-manual over tp
    only — dp/cp axes stay GSPMD-automatic)."""
    from smdistributed_modelparallel_tpu.ops.pallas_ce import (
        make_vocab_parallel_fused_ce,
    )

    return make_vocab_parallel_fused_ce(
        mesh, v_global, block_n, block_v, interpret, smoothing, TP_AXIS
    )


def _want_fused_ce(x, embedding_table, tp=1):
    """Policy half of the CE dispatch (capability half: ``pc.fused_ce_ok``).

    The blockwise kernel trades ~5/3 the head matmul flops (the backward
    recomputes logit blocks) for never materializing [N, V]. At transformer
    widths the recompute costs more wall-clock than the saved HBM traffic
    (measured: GPT-2 124M bench 114.5 -> 104.0 ms/step on v5e when switching
    to the logits path), so the kernel is a memory-CAPACITY lever: ``auto``
    engages it only when the logits (at the activation dtype) would be
    large enough to threaten HBM (fused_ce_auto_threshold_mb, default
    2 GB — e.g. 32k tokens x 50k vocab at bf16), where the logits path
    would OOM or evict everything else.
    """
    from smdistributed_modelparallel_tpu.backend.state import state

    mode = getattr(state.cfg, "fused_ce", "auto") if state.initialized else "auto"
    if mode is True:
        return True
    if mode is False:
        return False
    thresh_mb = (
        getattr(state.cfg, "fused_ce_auto_threshold_mb", 2048)
        if state.initialized else 2048
    )
    # Estimate the materialized path's logits at the ACTIVATION dtype
    # (fp32 activations materialize 4-byte logits plus the softmax's fp32
    # copy — underestimating here would defeat the capacity policy).
    # Under tp the vocab axis is sharded, so the per-chip logits are
    # [N, V/tp] — the capacity threshold applies to what one chip holds.
    itemsize = jnp.dtype(x.dtype).itemsize
    logits_mb = (
        x.shape[0] * embedding_table.shape[0] * itemsize / 2**20 / tp
    )
    return logits_mb > thresh_mb


def fused_lm_head_cross_entropy(hidden, embedding_table, targets,
                                ignore_index=-100, label_smoothing=0.0,
                                block_n=None, block_v=None):
    """Tied-LM-head cross-entropy WITHOUT materializing logits.

    TPU extension (no reference counterpart): computes per-token
    ``CE(hidden @ table^T, targets)`` through the blockwise Pallas kernels
    (``ops/pallas_ce.py``) — the [.., V] logits tensor, the single largest
    HBM intermediate of large-vocab LM training, never exists. Block sizes
    default to ``pallas_ce.auto_blocks`` (shrunk to fit VMEM for wide D).
    Under tensor parallelism the kernels run per-shard on the local
    [V/tp, D] table slice inside a tp manual region, combined with the
    same pmax/psum pair the materialized Megatron path uses — at modern
    256k vocabs this is where the capacity win matters most. Falls back
    to the materialized-logits ``vocab_parallel_cross_entropy`` path
    off-TPU; a forced ``fused_ce: True`` that cannot run logs a warning
    at trace time.

    Args:
      hidden: [..., D] final hidden states (post final-layernorm).
      embedding_table: [V, D] tied embedding table.
      targets: [...] int ids; ``ignore_index`` entries contribute 0 loss
        and no gradient.
    Returns: fp32 per-token losses shaped like ``targets``.
    """
    from smdistributed_modelparallel_tpu.backend.state import state
    from smdistributed_modelparallel_tpu.ops import pallas_ce as pc
    from smdistributed_modelparallel_tpu.utils.logger import get_logger

    lead = hidden.shape[:-1]
    D = hidden.shape[-1]
    x = hidden.reshape(-1, D)
    t = targets.reshape(-1)
    valid = t != ignore_index
    t_safe = jnp.where(valid, t, 0)
    tp = state.mesh.shape.get(TP_AXIS, 1) if state.initialized else 1
    want = _want_fused_ce(x, embedding_table, tp)
    V = embedding_table.shape[0]
    can = pc.fused_ce_ok(x, embedding_table, block_n, block_v) and (
        tp == 1 or V % tp == 0
    )
    if want and can:
        bn, bv = pc.auto_blocks(D, block_n, block_v)
        if tp == 1:
            per = pc.fused_lm_head_ce(x, embedding_table, t_safe,
                                      bn, bv, False,
                                      float(label_smoothing))
        else:
            # Vocab-parallel: per-shard kernels on the local [V/tp, D]
            # slice, pmax/psum-combined inside a tp manual region — the
            # Megatron composition of vocab_parallel_cross_entropy with
            # the logits never materialized.
            interp = jax.default_backend() != "tpu"
            fn = _build_tp_fused_ce(
                state.mesh, V, bn, bv, interp, float(label_smoothing)
            )
            per = fn(x, embedding_table, t_safe)
    else:
        if want and not can and state.initialized \
                and getattr(state.cfg, "fused_ce", "auto") is True:
            import os

            if tp > 1 and V % tp != 0:
                why = f"vocab {V} not divisible by tp {tp}"
            elif os.environ.get("SMP_DISABLE_FUSED_CE", "0") == "1":
                why = "SMP_DISABLE_FUSED_CE=1 is set"
            elif jax.default_backend() != "tpu":
                why = "not running on a TPU backend"
            elif (block_n, block_v) != (None, None) \
                    and pc.auto_blocks(D) is not None:
                why = ("explicit block_n=%s/block_v=%s does not fit VMEM "
                       "for D=%d (auto-selected blocks would — drop the "
                       "override)" % (block_n, block_v, D))
            else:
                why = "no block configuration fits VMEM for D=%d" % D
            get_logger().warning(
                "fused_ce: True requested but the kernel cannot run here "
                "(%s) — materializing [%d, %d] logits instead.",
                why, x.shape[0], embedding_table.shape[0],
            )
        logits = x @ embedding_table.T.astype(x.dtype)
        per = vocab_parallel_cross_entropy(
            logits, t_safe, label_smoothing=label_smoothing
        )
    per = jnp.where(valid, per, 0.0)
    return per.reshape(lead)


class DistributedCrossEntropy(nn.Module):
    """Module wrapper matching the reference class surface
    (``torch/nn/cross_entropy.py:28``); reduction over all tokens."""

    reduction: str = "mean"
    label_smoothing: float = 0.0

    def __call__(self, logits, targets):
        loss = vocab_parallel_cross_entropy(logits, targets, self.label_smoothing)
        if self.reduction == "mean":
            return jnp.mean(loss)
        if self.reduction == "sum":
            return jnp.sum(loss)
        return loss
