"""DistributedCrossEntropy — vocab-parallel cross-entropy.

Parity target: reference ``torch/nn/cross_entropy.py:28-112``
(Megatron-style): local max -> allreduce-max -> mask local target logits ->
allreduce of target-logit and sum-exp -> loss.

TPU-native re-design: written as a numerically-stable log-softmax over the
(tp-sharded) vocab axis with sharding constraints; GSPMD emits the same
max/sum allreduces the reference codes explicitly. The target-logit gather
is a one-hot contraction (MXU-friendly, partitionable over the sharded
vocab dim).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.nn.utils import shard_activation


def vocab_parallel_cross_entropy(logits, targets, label_smoothing=0.0):
    """Per-token cross-entropy loss.

    Args:
      logits: [..., vocab] (vocab axis may be tp-sharded).
      targets: [...] int ids.
    Returns:
      [...] per-token losses (fp32).
    """
    vocab = logits.shape[-1]
    spec = [None] * (logits.ndim - 1) + [TP_AXIS]
    logits = shard_activation(logits, *spec)
    logits_f = logits.astype(jnp.float32)
    # Stable logsumexp over the sharded vocab axis: GSPMD lowers max/sum to
    # the reference's allreduce(max)/allreduce(sum) pair
    # (torch/nn/cross_entropy.py:42-71).
    m = jax.lax.stop_gradient(jnp.max(logits_f, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits_f - m), axis=-1)) + m[..., 0]
    one_hot = jax.nn.one_hot(targets, vocab, dtype=logits_f.dtype)
    target_logit = jnp.sum(logits_f * one_hot, axis=-1)
    loss = lse - target_logit
    if label_smoothing > 0.0:
        smooth = -jnp.mean(jax.nn.log_softmax(logits_f, axis=-1), axis=-1)
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    return loss


class DistributedCrossEntropy(nn.Module):
    """Module wrapper matching the reference class surface
    (``torch/nn/cross_entropy.py:28``); reduction over all tokens."""

    reduction: str = "mean"
    label_smoothing: float = 0.0

    def __call__(self, logits, targets):
        loss = vocab_parallel_cross_entropy(logits, targets, self.label_smoothing)
        if self.reduction == "mean":
            return jnp.mean(loss)
        if self.reduction == "sum":
            return jnp.sum(loss)
        return loss
