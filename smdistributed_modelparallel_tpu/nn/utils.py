"""TP utilities: parameter partitioning metadata + activation sharding.

Parity target: reference ``torch/nn/utils.py`` — ``parameter_creation_scope``
(marks params distributed/scaled-batch, ``:120-154``),
``initialize_with_input_partition`` / ``initialize_with_output_partition``
(slice fan-in/fan-out per tp_rank, ``:155-249``), and the autograd
collectives ``NarrowForTP`` / ``AllgatherForTP`` / ``ForwardAllreduceForTP``
/ ``BackwardAllreduceForTP`` / ``ReduceScatterForTP`` /
``ScatterAndMergeForTP`` (``:465-663``).

TPU-native re-design: none of those collectives are written by hand. A
parameter is "input/output partitioned" by carrying a PartitionSpec with the
``tp`` mesh axis on the corresponding dimension (flax ``with_partitioning``
metadata, unboxed by ``DistributedModel``); activations are steered with
``with_sharding_constraint``. GSPMD then inserts exactly the
allgather/reduce-scatter/allreduce pairs the reference implements as
autograd Functions — including their transposes for backward. The explicit
collectives that remain (Ulysses all-to-all, ring permute) live in
``smp.ops``.
"""

import functools

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import (
    CP_AXIS,
    RDP_AXIS,
    EP_AXIS,
    TP_AXIS,
)


def tp_size():
    if state.cfg is None:
        return 1
    return state.cfg.tensor_parallel_degree


def tp_enabled():
    return tp_size() > 1


def _mesh():
    return state.mesh if state.initialized else None


def shard_activation(x, *spec):
    """Constrain an activation to a PartitionSpec over the mesh.

    No-op when the framework is uninitialized or the mesh axes named in the
    spec are all size 1 (e.g. tp_degree=1) — the constraint would be a
    trivial replication and only add noise to the jaxpr.
    """
    mesh = _mesh()
    if mesh is None:
        return x
    sizes = mesh.shape
    if _axes_all_trivial(spec):
        return x
    # Drop axes that don't divide the dim (tiny test shapes).
    fixed = []
    for dim, axes in enumerate(spec):
        if axes is None:
            fixed.append(None)
            continue
        axes_t = axes if isinstance(axes, tuple) else (axes,)
        total = 1
        for a in axes_t:
            total *= sizes.get(a, 1)
        if dim < x.ndim and x.shape[dim] % total == 0:
            fixed.append(axes)
        else:
            fixed.append(None)
    full = fixed + [None] * (x.ndim - len(fixed))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*full))
    )


def batch_seq_spec(extra=()):
    """Leading (batch, seq) axes of an activation: batch over the data axes,
    sequence over cp. ``extra`` appends trailing-dim axes."""
    return (( RDP_AXIS, EP_AXIS), CP_AXIS) + tuple(extra)


def _axes_all_trivial(names):
    """True when every mesh axis named in `names` (entries may be axis
    names, tuples of names, or None) has size 1 on the current mesh — i.e.
    partitioning over them would be a trivial replication."""
    mesh = _mesh()
    if mesh is None:
        return True
    sizes = mesh.shape
    involved = [
        a for n in names if n
        for a in (n if isinstance(n, tuple) else (n,))
    ]
    return all(sizes.get(a, 1) == 1 for a in involved)


def partitioned(init_fn, names):
    """Wrap a flax param init with tp partitioning metadata.

    ``names`` is a tuple with one entry per dim: a mesh axis name or None.
    When tp is disabled the init is returned unwrapped so parameter trees
    are plain arrays in the single-device path.
    """
    if not tp_enabled() or not any(n for n in names):
        return init_fn
    return nn.with_partitioning(init_fn, tuple(names))


def axis_partitioned(init_fn, names):
    """Like ``partitioned`` but gated on ANY named mesh axis being > 1
    (MoE expert params shard over ep, optionally combined with tp)."""
    if not any(n for n in names) or _axes_all_trivial(names):
        return init_fn
    return nn.with_partitioning(init_fn, tuple(names))


def tp_ring_active():
    """Whether the overlapped-tp ring path applies right now — the one
    lazy wrapper over ``ops.collective_matmul.tp_overlap_active`` the tp
    layer family (nn/linear.py, nn/transformer.py) shares, so gating
    changes cannot silently split between the two."""
    from smdistributed_modelparallel_tpu.ops.collective_matmul import (
        tp_overlap_active,
    )

    return tp_overlap_active()


@functools.lru_cache(maxsize=64)
def _fused_bias_gelu_region(mesh, ndim, interpret):
    from smdistributed_modelparallel_tpu.ops.pallas_gelu import bias_gelu
    from smdistributed_modelparallel_tpu.parallel.sharding import (
        single_axis_spec,
    )
    from smdistributed_modelparallel_tpu.utils.jax_compat import shard_map

    h_spec = single_axis_spec(ndim, ndim - 1, TP_AXIS)
    b_spec = single_axis_spec(1, 0, TP_AXIS)
    return jax.jit(shard_map(
        lambda h, b: bias_gelu(h, b, interpret),
        mesh=mesh, in_specs=(h_spec, b_spec), out_specs=h_spec,
        axis_names={TP_AXIS}, check_vma=False,
    ))


def fused_bias_gelu(h, b):
    """Dispatch ``gelu(h + b)`` to the fused Pallas kernel
    (``ops/pallas_gelu.py``). Under tensor parallelism the activation's
    feature dim is tp-sharded, so the call runs inside a tp manual
    region handing the kernel its local block (a plain pallas_call on
    the sharded array would force a gather); at tp=1 it is a direct
    call. Callers guard with ``pallas_gelu.bias_gelu_ok``.

    Under ``matmul_precision: fp8`` the epilogue INPUT rounds to the
    e4m3 grid with the ``gelu_in`` slot's delayed scale (straight-
    through gradient) before the kernel — the handoff between the fp8
    matmul and the fused activation carries fp8 information content,
    matching what a fused fp8-epilogue kernel would hand over."""
    from smdistributed_modelparallel_tpu.ops.pallas_gelu import bias_gelu

    from smdistributed_modelparallel_tpu import quant

    if quant.fp8_trace_active():
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_quant_dispatch,
        )

        record_quant_dispatch("gelu_in", "fp8")
        h = quant.fake_quant(h, "gelu_in.x")
    interpret = jax.default_backend() != "tpu"
    mesh = _mesh()
    tp = mesh.shape.get(TP_AXIS, 1) if mesh is not None else 1
    if tp <= 1 or h.shape[-1] % tp != 0:
        return bias_gelu(h, b, interpret)
    h = shard_activation(h, *([None] * (h.ndim - 1) + [TP_AXIS]))
    return _fused_bias_gelu_region(mesh, h.ndim, interpret)(h, b)


def dense_init(scale=None, stddev=0.02):
    if scale is not None:
        return nn.initializers.normal(stddev=scale)
    return nn.initializers.normal(stddev=stddev)


def resolve_deterministic(explicit):
    """Whether dropout should be skipped.

    ``explicit`` is a module's ``deterministic`` field: an explicit bool
    wins; None defers to the wrapping ``DistributedModel``'s train/eval
    mode (parity: the reference's modules are nn.Modules following
    ``model.train()``/``.eval()``; flax needs the flag threaded).
    """
    if explicit is not None:
        return explicit
    model = state.model
    if model is not None:
        return not model.training
    return True


# ----------------------------------------------------------------------
# Sequence sharding helpers (parity: reference torch/nn/utils.py:45-70
# shard_sequence / unshard_sequence).
# ----------------------------------------------------------------------


def shard_sequence(x, axis=1):
    """Constrain the sequence axis over the tp axis (the reference slices
    the sequence per tp_rank; here it is a resharding constraint)."""
    spec = [None] * x.ndim
    spec[axis] = TP_AXIS
    return shard_activation(x, *spec)


def unshard_sequence(x, axis=1):
    spec = [None] * x.ndim
    return shard_activation(x, *spec)


def mask_keep_2d(mask):
    """Boolean [B, T] keep-flags from an attention mask in any accepted
    form ([B, T] or [B, 1, 1, T]; bool / 0-1 int / additive float), or
    None when absent or not reducible to per-key flags."""
    if mask is None:
        return None
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        mask = mask[:, 0, 0, :]
    if mask.ndim != 2:
        return None
    if mask.dtype == jnp.bool_:
        return mask
    if jnp.issubdtype(mask.dtype, jnp.integer):
        return mask != 0
    return mask > -1.0  # additive: 0 keep, large-negative drop


def half_cast(params, half):
    """Cast floating leaves to the half dtype (None = no-op). The ONE
    definition of the training/generation compute-dtype cast — step.py,
    pipeline_1f1b.py, and generation.py all share this predicate."""
    if half is None:
        return params
    return jax.tree_util.tree_map(
        lambda p: p.astype(half)
        if jnp.issubdtype(p.dtype, jnp.floating) else p,
        params,
    )


def pad_row_offset(mask):
    """Per-row position offset ([B] int32, <= 0) for LEFT-padded prompts,
    or None when no mask applies.

    With left padding, pad count = width - sum(keep) at prefill ([B, T]
    prompt mask) and at decode steps ([B, 1, 1, C] mask with generated
    columns kept) alike, so the offset derives statelessly from whatever
    mask arrives. Rows whose keep pattern is NOT a left-pad shape
    (0..0 1..1 monotone) get offset 0 — an arbitrary key-blocking mask
    excludes slots from attention but must not shift positions."""
    keep = mask_keep_2d(mask)
    if keep is None:
        return None
    is_leftpad = jnp.all(keep[:, 1:] >= keep[:, :-1], axis=1)
    off = jnp.sum(keep, axis=1).astype(jnp.int32) - keep.shape[1]
    return jnp.where(is_leftpad, off, 0)


# ----------------------------------------------------------------------
# KV cache for autoregressive decoding (TPU extension, no reference
# counterpart: the reference is a training library; generation support
# makes the switch complete for fine-tune-then-sample users). Used by the
# attention layers under ``decode=True`` and driven by ``smp.generate``.
# ----------------------------------------------------------------------


class DecodeKVCache:
    """Fixed-length per-layer K/V cache held in flax "cache" variables.

    Protocol (see ``generation.py``): the first call on a fresh cache is
    the PREFILL — a whole-prompt chunk attends causally over itself (the
    cache is empty before it, so chunk-causal equals cache semantics, and
    the chunk keeps the flash-attention fast path). Every later call is a
    T=1 DECODE step attending over the written prefix of the cache. Both
    write their K/V into ``cache_len`` fixed slots at ``cache_index``.

    The chunk-size distinction is static (Python ``T > 1``), so prefill
    and decode compile as two separate programs — no traced branching.
    """

    def __init__(self, mod, shape, dtype):
        B, C, H, hd = shape
        if C is None:
            raise ValueError(
                "decode=True requires decode_cache_len (total generation "
                "length) on the module."
            )
        # Static protocol guard state: True iff this apply CREATES the
        # cache (the only call allowed to carry a multi-token chunk).
        self._fresh = not mod.has_variable("cache", "cached_key")
        self._ck = mod.variable(
            "cache", "cached_key", lambda: jnp.zeros((B, C, H, hd), dtype)
        )
        self._cv = mod.variable(
            "cache", "cached_value", lambda: jnp.zeros((B, C, H, hd), dtype)
        )
        self._idx = mod.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        self.cache_len = C

    @property
    def index(self):
        """Positions filled so far (int32 scalar; 0 at prefill)."""
        return self._idx.value

    def append(self, k, v, window=None):
        """Write chunk K/V ([B, T, H, hd]) at the current index.

        Returns ``(k_attend, v_attend, mask)``: for a prefill chunk the
        chunk itself with ``mask=None`` (caller runs plain causal
        attention); for a decode step the full cache plus a
        [1, 1, 1, cache_len] boolean mask selecting positions <= index
        (banded to ``window`` when set).
        """
        T = k.shape[1]
        if T > 1 and not self._fresh:
            raise ValueError(
                "KV-cache protocol violation: a multi-token (prefill) "
                "chunk is only valid on a fresh cache; later calls must "
                "decode one token at a time (the chunk would silently "
                "ignore all previously cached positions)."
            )
        i = self._idx.value
        self._ck.value = jax.lax.dynamic_update_slice(
            self._ck.value, k, (0, i, 0, 0)
        )
        self._cv.value = jax.lax.dynamic_update_slice(
            self._cv.value, v, (0, i, 0, 0)
        )
        self._idx.value = i + T
        if T > 1:
            return k, v, None
        cols = jnp.arange(self.cache_len)
        keep = cols <= i
        if window is not None:
            keep = keep & (i - cols < window)
        return (
            self._ck.value,
            self._cv.value,
            keep[None, None, None, :],
        )


class PagedKVCache:
    """Block-pooled per-layer K/V cache for continuous-batching serving.

    Where ``DecodeKVCache`` gives every sequence a private contiguous
    [B, cache_len, H, hd] buffer, this holds ONE pool of
    ``num_blocks`` fixed-size token blocks ([num_blocks, block_tokens,
    H, hd] per layer) shared by every in-flight sequence. A host-side
    allocator (``serving/kv_cache.BlockAllocator``) hands out blocks and
    builds per-sequence BLOCK TABLES — ordered pool-block ids, logical
    block ``j`` of a sequence living at pool block ``table[j]`` — passed
    into the compiled program as device arrays, so sequences of wildly
    different lengths share the pool and a finished sequence's blocks are
    reusable the moment the host frees them. Pool block 0 is reserved as
    the TRASH block: unused table entries point at it, so writes from
    inactive decode slots and padded prefill tail positions land there
    harmlessly (and are never attended — the mask is position-derived).

    Like ``DecodeKVCache`` the pool shards over tp on the head axis
    (``shard_activation``), so the serving KV footprint per device is
    ``pool_bytes / tp`` and the X-ray's KV replication detector
    (``hlo_audit.serving_kv_findings``) can hold it to that.

    Call protocol (one compiled program each; driven by
    ``serving/engine.py``):

    - decode step: ``k``/``v`` are [S, 1, H, hd] (one token per decode
      slot), ``positions[b]`` is the token's absolute position, and the
      returned attend set is the whole gathered table ([S, T_max, H, hd]
      where ``T_max = max_blocks * block_tokens``) with a
      ``col <= position`` boolean mask.
    - prefill chunk: ``k``/``v`` are [B, C, H, hd] (usually B=1), written
      at ``positions[b] + t``; ``valid[b]`` marks how many of the C
      chunk rows are real (the last chunk of a prompt is padded) — the
      tail's writes are routed to the trash block. The mask is chunk-
      causal against absolute positions: col ``j`` is visible to chunk
      row ``t`` iff ``j <= positions[b] + t``.
    """

    def __init__(self, mod, num_blocks, block_tokens, heads, head_dim,
                 dtype):
        from smdistributed_modelparallel_tpu import quant as _quant

        # SMP_KV_QUANT=int8: the pools store int8 with per-block-per-head
        # scale sidecars ([num_blocks, H] f32 — running block maxima that
        # only grow), halving the pool bytes; decode dequantizes at the
        # gather. The knob is static env config, so the two layouts are
        # different compiled programs (serving keys carry the suffix).
        self._quant = _quant.kv_quant_mode() == "int8"
        self._dtype = dtype
        shape = (num_blocks, block_tokens, heads, head_dim)
        pool_dtype = _quant.kv_pool_dtype(dtype)
        self._pk = mod.variable(
            "cache", "pool_key", lambda: jnp.zeros(shape, pool_dtype)
        )
        self._pv = mod.variable(
            "cache", "pool_value", lambda: jnp.zeros(shape, pool_dtype)
        )
        if self._quant:
            self._sk = mod.variable(
                "cache", "scale_key",
                lambda: jnp.zeros((num_blocks, heads), jnp.float32),
            )
            self._sv = mod.variable(
                "cache", "scale_value",
                lambda: jnp.zeros((num_blocks, heads), jnp.float32),
            )
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens

    def _shard(self, pool):
        # tp shards the head axis, exactly like the activations/contiguous
        # caches; trivial-axis meshes make this a no-op.
        return shard_activation(pool, None, None, TP_AXIS, None)

    def _shard_scale(self, scale):
        # The scale sidecars shard with the pools' head axis.
        return shard_activation(scale, None, TP_AXIS)

    def append(self, k, v, block_tables, positions, valid=None,
               window=None):
        """Write chunk K/V and return ``(k_all, v_all, mask)``.

        Args:
          k, v: [B, T, H, hd] chunk K/V (T=1 decode, T=chunk prefill).
          block_tables: [B, max_blocks] int32 pool-block ids in sequence
            order; unused entries 0 (the trash block).
          positions: [B] int32 absolute position of the chunk's first
            token (number of tokens already cached for that sequence).
          valid: optional [B] int32 — rows ``t >= valid[b]`` of the chunk
            are padding: their writes go to the trash block.
          window: optional local-attention band width.
        """
        B, T = k.shape[:2]
        bt = self.block_tokens
        max_blocks = block_tables.shape[1]
        pos = positions[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        blk = jnp.take_along_axis(
            block_tables, jnp.clip(pos // bt, 0, max_blocks - 1), axis=1
        )
        dest = blk * bt + pos % bt                              # [B, T]
        if valid is not None:
            # Padded chunk tail: route the write into the trash block
            # (offset by t so a wide chunk never scatters twice into one
            # slot of it — the winner would be nondeterministic).
            trash = jnp.arange(T, dtype=jnp.int32)[None, :] % bt
            dest = jnp.where(
                jnp.arange(T)[None, :] < valid[:, None], dest, trash
            )
        flat = dest.reshape(-1)
        H, hd = k.shape[2], k.shape[3]
        if self._quant:
            from smdistributed_modelparallel_tpu import quant as _quant

            # int8 pools: grow the touched blocks' scales by the incoming
            # tokens' per-head amax, requantize the pool under the grown
            # scales, then write the tokens quantized LAST (so they land
            # on the final grid — one rounding, not two).
            blk_flat = flat // bt
            pk8, sk, qk = _quant.kv_quantize_append(
                self._pk.value, self._sk.value, k.reshape(B * T, H, hd),
                blk_flat,
            )
            pv8, sv, qv = _quant.kv_quantize_append(
                self._pv.value, self._sv.value, v.reshape(B * T, H, hd),
                blk_flat,
            )
            pk = pk8.reshape(self.num_blocks * bt, H, hd).at[flat].set(qk)
            pv = pv8.reshape(self.num_blocks * bt, H, hd).at[flat].set(qv)
            self._sk.value = self._shard_scale(sk)
            self._sv.value = self._shard_scale(sv)
        else:
            pk = self._pk.value.reshape(self.num_blocks * bt, H, hd)
            pv = self._pv.value.reshape(self.num_blocks * bt, H, hd)
            pk = pk.at[flat].set(k.reshape(B * T, H, hd))
            pv = pv.at[flat].set(v.reshape(B * T, H, hd))
        self._pk.value = self._shard(
            pk.reshape(self.num_blocks, bt, H, hd)
        )
        self._pv.value = self._shard(
            pv.reshape(self.num_blocks, bt, H, hd)
        )
        # Gather every table slot: logical position of gathered column j
        # IS j (tables list blocks in sequence order).
        slots = (
            block_tables[:, :, None] * bt
            + jnp.arange(bt, dtype=jnp.int32)[None, None, :]
        ).reshape(B, max_blocks * bt)
        pk_flat = self._pk.value.reshape(self.num_blocks * bt, H, hd)
        pv_flat = self._pv.value.reshape(self.num_blocks * bt, H, hd)
        k_all = jnp.take(pk_flat, slots, axis=0)        # [B, S, H, hd]
        v_all = jnp.take(pv_flat, slots, axis=0)
        if self._quant:
            slot_blocks = slots // bt                   # [B, S]
            k_all = _quant.kv_dequantize_gather(
                k_all, self._sk.value, slot_blocks, self._dtype
            )
            v_all = _quant.kv_dequantize_gather(
                v_all, self._sv.value, slot_blocks, self._dtype
            )
        cols = jnp.arange(max_blocks * bt, dtype=jnp.int32)
        # keep[b, t, j]: column j visible to chunk row t of sequence b.
        keep = cols[None, None, :] <= pos[:, :, None]
        if window is not None:
            keep = keep & (pos[:, :, None] - cols[None, None, :] < window)
        return k_all, v_all, keep[:, None, :, :]
