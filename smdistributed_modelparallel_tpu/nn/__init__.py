"""smp.nn — tensor-parallel module library.

Parity target: reference ``torch/nn/__init__.py:24-35`` exports. Populated
across M3; the registry is available from M0.
"""

from smdistributed_modelparallel_tpu.nn.tp_registry import (
    TensorParallelismRegistry,
    tp_register,
    tp_register_with_module,
)
