"""smp.nn — tensor-parallel module library.

Parity target: reference ``torch/nn/__init__.py:24-35`` exports. Populated
across M3; the registry is available from M0.
"""

from smdistributed_modelparallel_tpu.nn.tp_registry import (
    TensorParallelismRegistry,
    tp_register,
    tp_register_with_module,
)
from smdistributed_modelparallel_tpu.nn.linear import (
    ColumnParallelLinear,
    DistributedLinear,
    RowParallelLinear,
)
from smdistributed_modelparallel_tpu.nn.embedding import DistributedEmbedding
from smdistributed_modelparallel_tpu.nn.layer_norm import (
    DistributedLayerNorm,
    FusedLayerNorm,
)
from smdistributed_modelparallel_tpu.nn.cross_entropy import (
    DistributedCrossEntropy,
    fused_lm_head_cross_entropy,
    vocab_parallel_cross_entropy,
)
from smdistributed_modelparallel_tpu.nn.softmax import (
    scaled_causal_masked_softmax,
    scaled_masked_softmax,
)
from smdistributed_modelparallel_tpu.nn.gelu import bias_gelu, gelu
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedAttentionLayer,
    DistributedTransformer,
    DistributedTransformerLayer,
    DistributedTransformerLMHead,
    DistributedTransformerOutputLayer,
)
from smdistributed_modelparallel_tpu.nn.moe import (
    DistributedMoE,
    moe_aux_losses,
)
