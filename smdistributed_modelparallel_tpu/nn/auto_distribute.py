"""Automatic module distribution: swap marked modules for smp.nn versions.

Parity target: reference ``DistributedModel._replace_tp_counterparts``
(``torch/model.py:285-333``) + ``TensorParallelismRegistry.distribute``
(``torch/tp_registry.py:201-264``): modules marked for tensor parallelism
(via ``smp.tensor_parallelism()`` context or ``smp.set_tensor_parallelism``)
are re-instantiated as their Distributed* counterparts with translated
constructor arguments. The reference records ctor args by patching
``nn.Module.__init__`` (``torch/patches/__init__.py``).

TPU-native re-design: flax modules are frozen dataclasses, so "recorded
ctor args" are simply the dataclass fields. Construction-context marks are
stamped onto instances by a ``flax.linen.Module.__post_init__`` patch
(`install_construction_hooks`); `distribute_tree` then rebuilds the module
tree with marked-and-registered children replaced. Children created inside
``setup()``/``@nn.compact`` bodies are invisible pre-bind — those use
``smp.nn`` classes directly (as the smp model zoo does), matching the
reference's guidance to use smp.nn for custom internals.
"""

import dataclasses
from typing import Callable, Optional

import flax.linen as nn

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


class HookedModule(nn.Module):
    """Transparent wrapper applying registered forward/return hooks.

    Parity: reference ``DistributedModule.__call__``
    (``torch/nn/dist_module.py:5-32``) — the forward hook translates the
    original module's call signature into the distributed module's, the
    return hook translates the output back. ``nn.share_scope`` keeps the
    inner module's parameter paths unchanged (the wrapper adds no scope
    level).
    """

    inner: nn.Module
    fwd_hook: Optional[Callable] = None
    ret_hook: Optional[Callable] = None

    def setup(self):
        nn.share_scope(self, self.inner)

    def __call__(self, *args, **kwargs):
        if self.fwd_hook is not None:
            args, kwargs = self.fwd_hook(*args, **kwargs)
        out = self.inner(*args, **kwargs)
        if self.ret_hook is not None:
            out = self.ret_hook(out)
        return out

    @nn.nowrap
    def pipeline_spec(self):
        """Delegate pipeline discovery to the wrapped module."""
        fn = getattr(self.inner, "pipeline_spec", None)
        if fn is None:
            return None
        return fn() if callable(fn) else fn


def unwrap_hooks(module):
    """The module the pipeline should drive: HookedModule shares its scope
    with the wrapped module, so applying inner methods uses the same
    parameter paths (hooks only shape the direct-call signature)."""
    while isinstance(module, HookedModule):
        module = module.inner
    return module

_hooks_installed = False
_TP_MARK = "_smp_tp_mark"
_PARTITION_MARK = "_smp_partition"


def install_construction_hooks():
    """Patch flax Module construction to stamp active smp context marks.

    Parity: reference ``patch_manager.patch_constructor``
    (``torch/__init__.py:137``) recording ctor args + tp/partition contexts.
    """
    global _hooks_installed
    if _hooks_installed:
        return
    orig = nn.Module.__post_init__

    def post_init(self):
        orig(self)
        mm = state.module_manager
        if mm is not None:
            tp = getattr(mm, "_active_tp", None)
            if tp and tp.get("enabled", True):
                object.__setattr__(self, _TP_MARK, dict(tp))
            part = getattr(mm, "_active_partition", None)
            if part is not None:
                object.__setattr__(self, _PARTITION_MARK, part)

    nn.Module.__post_init__ = post_init
    _hooks_installed = True


def _module_fields(module):
    """Dataclass fields of an unbound flax module, minus flax internals."""
    out = {}
    for f in dataclasses.fields(module):
        if f.name in ("parent", "name"):
            continue
        out[f.name] = getattr(module, f.name)
    return out


def _ckpt_config_touches(mm, path):
    """True if any activation-checkpoint config targets `path`, one of its
    ancestors, or one of its descendants."""
    for prefix in mm.checkpoint_configs:
        if (
            prefix == path
            or prefix == ""
            or path == ""
            or path.startswith(prefix + "/")
            or prefix.startswith(path + "/")
        ):
            return True
    return False


def _is_marked(child, path, mm):
    mark = getattr(child, _TP_MARK, None)
    if mark is None and mm is not None and mm.tp_marked(path):
        mark = mm.tp_config(path)
    if mark is None:
        return None
    cfg = dict(mark)
    cfg.pop("enabled", None)
    return cfg


def distribute_tree(module, mm=None, registry=None, prefix=""):
    """Rebuild `module` with tp-marked registered children distributed.

    Returns (new_module, replaced_paths). Also harvests construction-context
    partition stamps into the module manager.
    """
    registry = registry or state.tp_registry
    mm = mm or state.module_manager
    replaced = []

    def visit(m, path):
        part = getattr(m, _PARTITION_MARK, None)
        if part is not None and mm is not None:
            mm.set_partition(path or "", part)

        updates = {}
        # Activation-checkpoint configs turn on the module's own remat
        # support where it exists (smp.nn transformer family, model zoo).
        # A config targeting this module, an ancestor, or a setup()-defined
        # descendant (invisible to the walk, e.g. "transformer" inside
        # DistributedTransformerLMHead) all enable the module's remat.
        if (
            mm is not None
            and _ckpt_config_touches(mm, path)
            and any(
                f.name == "activation_checkpointing" for f in dataclasses.fields(m)
            )
            and not getattr(m, "activation_checkpointing", False)
        ):
            updates["activation_checkpointing"] = True
        for fname, value in _module_fields(m).items():
            child_path = f"{path}/{fname}" if path else fname
            new_value = _visit_value(value, child_path)
            if new_value is not value:
                updates[fname] = new_value
        if updates:
            m = type(m)(**{**_module_fields(m), **updates})
        return m

    def _visit_value(value, path):
        if isinstance(value, nn.Module):
            tp_cfg = _is_marked(value, path, mm)
            if tp_cfg is not None and registry is not None and registry.is_supported(type(value)):
                dist = registry.distribute(
                    type(value), (), _module_fields(value), tp_config=tp_cfg
                )
                if dist is not None:
                    replaced.append(path)
                    return dist
            return visit(value, path)
        if isinstance(value, (list, tuple)):
            new = [
                _visit_value(v, f"{path}/{i}")
                for i, v in enumerate(value)
            ]
            if any(a is not b for a, b in zip(new, value)):
                return type(value)(new)
            return value
        if isinstance(value, dict):
            new = {k: _visit_value(v, f"{path}/{k}") for k, v in value.items()}
            if any(new[k] is not value[k] for k in value):
                return new
            return value
        return value

    root_cfg = _is_marked(module, prefix, mm) if mm is not None else None
    if root_cfg is not None and registry is not None and registry.is_supported(type(module)):
        dist = registry.distribute(
            type(module), (), _module_fields(module), tp_config=root_cfg
        )
        if dist is not None:
            replaced.append(prefix or "<root>")
            return dist, replaced

    new_module = visit(module, prefix)
    if replaced:
        logger.info("Distributed %d tp-marked module(s): %s", len(replaced), replaced)
    return new_module, replaced


# ----------------------------------------------------------------------
# Built-in registrations (parity: reference torch/tp_registry.py:16-19 —
# nn.Linear -> DistributedLinear, nn.Embedding -> DistributedEmbedding).
# ----------------------------------------------------------------------


def _dense_init_hook(*args, **fields):
    keep = {
        "features": fields.get("features"),
        "use_bias": fields.get("use_bias", True),
    }
    # flax's `dtype` is the COMPUTE dtype (params stay param_dtype=f32);
    # DistributedLinear's `dtype` is the parameter-storage dtype, so
    # mapping them across would silently degrade master weights. Compute
    # dtype follows the input dtype in DistributedLinear, which preserves
    # the common bf16-compute intent.
    if fields.get("dtype") is not None:
        logger.debug(
            "nn.Dense dtype (compute) not mapped on distribution; "
            "DistributedLinear computes in the input dtype."
        )
    import flax.linen as fnn

    default_kinit = fnn.Dense.__dataclass_fields__["kernel_init"].default
    kinit = fields.get("kernel_init")
    if kinit not in (None, default_kinit):
        # Carry the user's initializer into the distributed layer: flax
        # gives the param the same key either way and the partitioning
        # wrapper only adds sharding metadata, so values are
        # seed-consistent with the undistributed module.
        keep["kernel_init"] = kinit
    return (), keep


def _embed_init_hook(*args, **fields):
    keep = {
        "num_embeddings": fields.get("num_embeddings"),
        "features": fields.get("features"),
    }
    return (), keep


def register_builtins(registry):
    from smdistributed_modelparallel_tpu.nn.embedding import DistributedEmbedding
    from smdistributed_modelparallel_tpu.nn.linear import DistributedLinear

    if not registry.is_supported(nn.Dense):
        registry.register(nn.Dense, DistributedLinear, init_hook=_dense_init_hook)
    if not registry.is_supported(nn.Embed):
        registry.register(nn.Embed, DistributedEmbedding, init_hook=_embed_init_hook)
