"""smp.nn Distributed transformer family.

Parity target: reference ``torch/nn/transformer.py``:
- ``DistributedTransformerLMHead`` (``:184-550``) — embeddings + transformer
  + (tied) LM head behind the ``_KEYS`` config surface (``:189-236``); all
  those keys are accepted here with the same names and defaults.
- ``DistributedTransformer`` (``:551-687``) — the layer stack.
- ``DistributedTransformerLayer`` — attention + output (MLP) sublayers with
  pre/post layernorm variants.
- ``DistributedAttentionLayer`` (``:1176-1835``) — dual TP strategies:
  ``optimize="speed"`` head-partitioned QKV (``:1273-1290``),
  ``optimize="memory"`` input-partitioned + scatter/gather (``:1237-1272``);
  rotary embeddings incl. NeoX variant (``:114-183``); causal/windowed
  masks (``:1331-1352``); query-key layer scaling; cross-attention;
  attention-in-fp32.
- ``DistributedTransformerOutputLayer`` (``:965-1175``) — the MLP with the
  same dual strategy.

TPU-native re-design: the hand-written TP collectives become parameter
PartitionSpecs + activation sharding constraints; GSPMD inserts the
allgather/reduce pairs (SURVEY §2.1 N4). ``optimize="speed"`` shards the
head/intermediate dims over tp; ``optimize="memory"`` additionally shards
the residual stream's sequence axis over tp between blocks (Megatron-SP
style reduce-scatter/allgather — the same memory/comm trade the reference's
input-partitioned all-to-all layout makes). Layers are built with
``flax.linen.scan`` so the stack compiles once and pipelines (M2); the
per-layer scan stream carries (layer_idx, is_local) for
query-key-layer-scaling and GPT-Neo-style alternating local/global
attention.
"""

from typing import Any, Optional

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import (
    CP_AXIS,
    EP_AXIS,
    RDP_AXIS,
    TP_AXIS,
)
from smdistributed_modelparallel_tpu.nn.embedding import DistributedEmbedding
from smdistributed_modelparallel_tpu.nn.layer_norm import DistributedLayerNorm
from smdistributed_modelparallel_tpu.nn.utils import (
    partitioned,
    resolve_deterministic,
    shard_activation,
    tp_ring_active as _ring_active,
)
from smdistributed_modelparallel_tpu.ops.attention import attention_core
from smdistributed_modelparallel_tpu.parallel.pipeline import PipelineSpec
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

BATCH_AXES = (RDP_AXIS, EP_AXIS)


def _cfg(name, default):
    cfg = state.cfg
    return getattr(cfg, name) if cfg is not None and name in cfg else default


def _activation(name):
    return {
        "gelu": lambda x: nn.gelu(x, approximate=True),
        "gelu_new": lambda x: nn.gelu(x, approximate=True),
        # Exact erf gelu (HF BERT's "gelu"; the tanh approximation above is
        # HF's "gelu_new" and the reference's fused bias_gelu).
        "gelu_erf": lambda x: nn.gelu(x, approximate=False),
        "relu": nn.relu,
        "silu": nn.silu,
        "swish": nn.silu,
    }[name]


def _seq_axes(memory_opt):
    """Sequence-dim mesh axes for the residual stream: cp always; tp too
    under optimize='memory' (sequence-parallel residual)."""
    return (CP_AXIS, TP_AXIS) if memory_opt else CP_AXIS


def _hidden_spec(memory_opt):
    return (BATCH_AXES, _seq_axes(memory_opt), None)


def _seq_parallel(memory_opt):
    """The residual stream is sequence-sharded over tp: explicitly via
    optimize='memory', or implicitly by the overlapped-tp ring."""
    return memory_opt or _ring_active()


def _init(range_, use_normal=True):
    return nn.initializers.normal(stddev=range_)


def _fp8_active():
    """Whether this trace dispatches the fp8 matmul seams (a quant step
    trace is installed — matmul_precision: fp8, training step only)."""
    from smdistributed_modelparallel_tpu import quant

    return quant.fp8_trace_active()


def _fp8_mm(x, w, site, **kw):
    """The fp8 delayed-scaling matmul for one transformer seam, with
    the dispatch decision counted (``smp_quant_dispatch_total``)."""
    from smdistributed_modelparallel_tpu import quant
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_quant_dispatch,
    )

    record_quant_dispatch(site, "fp8")
    return quant.fp8_matmul(x, w, site, **kw)


def apply_rotary(q, k, rotary_dim, base=10000.0, neox_style=False, offset=0):
    """Rotary position embedding on the first ``rotary_dim`` channels.

    Parity: reference ``torch/nn/transformer.py:114-183`` — interleaved
    (GPT-J) vs half-split (``gpt_neox_type_rotary``) variants.
    ``offset`` (int, traced scalar, or per-row [B] array) shifts the
    absolute positions — decode steps rotate the current chunk at its
    cache position; left-padded prompts shift each row by its pad count.
    """

    def rot(x):
        T = x.shape[1]
        d = rotary_dim
        x_rot, x_pass = x[..., :d], x[..., d:]
        half = d // 2
        freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
        off = jnp.asarray(offset, jnp.float32)
        t = off[..., None] + jnp.arange(T, dtype=jnp.float32)  # [T] or [B,T]
        angles = t[..., None] * freqs                 # [.., T, half]
        cos = jnp.cos(angles)[..., None, :]
        sin = jnp.sin(angles)[..., None, :]
        if cos.ndim == 3:                             # scalar offset
            cos = cos[None]
            sin = sin[None]
        if neox_style:
            x1, x2 = x_rot[..., :half], x_rot[..., half:]
            rotated = jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
            )
        else:
            x1 = x_rot[..., 0::2]
            x2 = x_rot[..., 1::2]
            r1 = x1 * cos - x2 * sin
            r2 = x2 * cos + x1 * sin
            rotated = jnp.stack([r1, r2], axis=-1).reshape(x_rot.shape)
        rotated = rotated.astype(x.dtype)
        return jnp.concatenate([rotated, x_pass], axis=-1)

    return rot(q), rot(k)


class DistributedAttentionLayer(nn.Module):
    """TP multi-head (self or cross) attention.

    Parity: reference ``DistributedAttentionLayer``
    (``torch/nn/transformer.py:1176-1835``). QKV is one [D, 3, H, hd] kernel
    with the head dim on tp (speed) — the reference's
    ``initialize_with_output_partition`` head split; the output projection
    is input-partitioned ([H, hd, D] with tp on heads) — the reference's
    fan-in slice + allreduce, which GSPMD inserts here.
    """

    num_attention_heads: int
    attention_head_size: int
    hidden_size: int
    attention_dropout_prob: float = 0.1
    hidden_dropout_prob: float = 0.1
    cross_attention: bool = False
    causal_mask_size: Optional[int] = None
    mask_value: float = -1e4
    attention_in_fp32: bool = False
    query_key_layer_scaling: bool = False
    scale_attention_scores: bool = True
    scale_attn_by_layer_idx: bool = False
    initializer_range: float = 0.02
    use_qkv_bias: bool = True
    use_attn_dense_bias: bool = True
    rotary_dim: Optional[int] = None
    rotary_emb_base: Optional[float] = None
    gpt_neox_type_rotary: bool = False
    window_size: Optional[int] = None
    # KV-cache decoding for smp.generate (nn/utils.DecodeKVCache); only
    # self-attention caches (cross-attention K/V are recomputed from the
    # encoder states passed each step).
    decode: bool = False
    decode_cache_len: Optional[int] = None
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.nowrap
    def _fused_qkv_wanted(self, D, ring):
        """Whether the fused QKV Pallas kernel should run: the config
        knob, the generic pallas gate, and the kernel's own dispatch
        precondition (at tp > 1 only inside the ring's manual region).
        The ACTUAL path taken is counted per trace by the caller
        (``_record_qkv_dispatch``) — a ring fallback after this gate
        passes still counts as ``fallback``."""
        if not (_cfg("fused_qkv", False)
                and _cfg("use_pallas_kernels", True)):
            return False
        from smdistributed_modelparallel_tpu.nn.utils import tp_size
        from smdistributed_modelparallel_tpu.ops.pallas_qkv import (
            fused_qkv_ok,
        )

        return fused_qkv_ok(D, ring=ring, tp=tp_size())

    @nn.nowrap
    def _record_qkv_dispatch(self, engaged):
        """One ``smp_fused_kernel_dispatch_total`` tick for the qkv
        kernel when the knob requested it, labeled with the path that
        actually ran (the gate can pass and the ring still fall back —
        indivisible sequence — leaving the plain einsum)."""
        if not (_cfg("fused_qkv", False)
                and _cfg("use_pallas_kernels", True)):
            return
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_fused_kernel_dispatch,
        )

        record_fused_kernel_dispatch(
            "qkv", "pallas" if engaged else "fallback"
        )

    @nn.compact
    def __call__(self, hidden, cross_states=None, attention_mask=None, xs=None):
        H, hd, D = self.num_attention_heads, self.attention_head_size, self.hidden_size
        B, T = hidden.shape[0], hidden.shape[1]
        dtype = self.dtype or hidden.dtype
        memory_opt = _cfg("optimize", "speed") == "memory"
        init = _init(self.initializer_range)

        if self.cross_attention:
            if cross_states is None:
                raise SMPValidationError(
                    "cross_attention=True requires cross_states input."
                )
            q_kernel = self.param(
                "query/kernel", partitioned(init, (None, TP_AXIS, None)), (D, H, hd), dtype
            )
            kv_kernel = self.param(
                "key_value/kernel",
                partitioned(init, (None, None, TP_AXIS, None)),
                (D, 2, H, hd),
                dtype,
            )
            if _fp8_active():
                q = _fp8_mm(hidden, q_kernel.astype(hidden.dtype), "qkv")
            else:
                q = jnp.einsum(
                    "btd,dhk->bthk", hidden, q_kernel.astype(hidden.dtype)
                )
            if self.use_qkv_bias:
                q_bias = self.param(
                    "query/bias", partitioned(nn.initializers.zeros, (TP_AXIS, None)),
                    (H, hd), dtype,
                )
                kv_bias = self.param(
                    "key_value/bias",
                    partitioned(nn.initializers.zeros, (None, TP_AXIS, None)),
                    (2, H, hd), dtype,
                )
                q = q + q_bias.astype(q.dtype)

            def cross_kv():
                kv = jnp.einsum(
                    "bsd,dchk->bcshk", cross_states,
                    kv_kernel.astype(hidden.dtype),
                )
                if self.use_qkv_bias:
                    kv = kv + kv_bias[:, None].astype(kv.dtype)
                return kv

            if self.decode:
                # Encoder K/V are the same every decode step: computed once
                # when the cache variable is created (flax only runs the
                # init closure when the variable is missing), then reused.
                kv = self.variable("cache", "cross_kv", cross_kv).value
            else:
                kv = cross_kv()
            k, v = kv[:, 0], kv[:, 1]
        else:
            qkv_kernel = self.param(
                "qkv/kernel",
                partitioned(init, (None, None, TP_AXIS, None)),
                (D, 3, H, hd),
                dtype,
            )
            qkv_bias = None
            if self.use_qkv_bias:
                qkv_bias = self.param(
                    "qkv/bias",
                    partitioned(nn.initializers.zeros, (None, TP_AXIS, None)),
                    (3, H, hd),
                    dtype,
                )
            ring = not self.decode and _ring_active()
            fused_qkv = self._fused_qkv_wanted(D, ring)
            qkv5 = None
            if ring:
                # Overlapped tp: the column-parallel input all-gather
                # decomposes into a ppermute ring, each hop hidden under
                # the partial matmul on the sequence block in hand
                # (ops/collective_matmul.py); bias folds into the chunk
                # matmuls (the Pallas fused kernel under fused_qkv).
                from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                    ring_ag_matmul,
                )

                qkv5 = ring_ag_matmul(
                    hidden, qkv_kernel.astype(hidden.dtype),
                    qkv_bias.astype(hidden.dtype)
                    if qkv_bias is not None else None,
                    w_tp_dim=2, fused=fused_qkv,
                )   # [B, T, 3, H, hd] or None (fall through to GSPMD)
            if qkv5 is None and fused_qkv and not ring:
                # Fused QKV without the ring (tp=1 per fused_qkv_ok):
                # one Pallas matmul against the concatenated [D, 3*H*hd]
                # kernel, bias in the epilogue.
                from smdistributed_modelparallel_tpu.ops.pallas_qkv import (
                    matmul_bias,
                )

                if _fp8_active():
                    # The fp8 rung of the fused-QKV ladder: same tiling,
                    # e4m3 operand refs (pallas_qkv.matmul_bias_fp8),
                    # dequant + bias in the XLA epilogue.
                    qkv5 = _fp8_mm(
                        hidden.reshape(-1, D),
                        qkv_kernel.astype(hidden.dtype).reshape(
                            D, 3 * H * hd
                        ),
                        "qkv",
                        bias=qkv_bias.astype(hidden.dtype)
                        if qkv_bias is not None else None,
                        use_pallas=True,
                        interpret=jax.default_backend() != "tpu",
                    ).reshape(B, T, 3, H, hd)
                else:
                    qkv5 = matmul_bias(
                        hidden.reshape(-1, D),
                        qkv_kernel.astype(hidden.dtype).reshape(
                            D, 3 * H * hd
                        ),
                        qkv_bias.astype(hidden.dtype)
                        if qkv_bias is not None else None,
                        interpret=jax.default_backend() != "tpu",
                    ).reshape(B, T, 3, H, hd)
            self._record_qkv_dispatch(fused_qkv and qkv5 is not None)
            if qkv5 is not None:
                q, k, v = qkv5[:, :, 0], qkv5[:, :, 1], qkv5[:, :, 2]
            elif _fp8_active():
                # [B, T, 3, H, hd] (the fp8 path contracts D in place —
                # the c axis rides behind t instead of in front; the
                # slices below account for the layout).
                qkv = _fp8_mm(
                    hidden, qkv_kernel.astype(hidden.dtype), "qkv"
                )
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                if qkv_bias is not None:
                    q = q + qkv_bias[0].astype(q.dtype)
                    k = k + qkv_bias[1].astype(k.dtype)
                    v = v + qkv_bias[2].astype(v.dtype)
            else:
                qkv = jnp.einsum("btd,dchk->bcthk", hidden, qkv_kernel.astype(hidden.dtype))
                q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
                if qkv_bias is not None:
                    q = q + qkv_bias[0].astype(q.dtype)
                    k = k + qkv_bias[1].astype(k.dtype)
                    v = v + qkv_bias[2].astype(v.dtype)

        head_spec = (BATCH_AXES, CP_AXIS, TP_AXIS, None)
        q = shard_activation(q, *head_spec)
        k = shard_activation(k, *head_spec)
        v = shard_activation(v, *head_spec)

        cache = None
        pos_offset = 0
        decode_mask = None
        if self.decode and not self.cross_attention:
            from smdistributed_modelparallel_tpu.nn.utils import (
                DecodeKVCache,
                pad_row_offset,
            )

            if self.causal_mask_size is None:
                raise SMPValidationError(
                    "decode=True requires causal self-attention "
                    "(causal_mask_size set); BERT-family encoders do not "
                    "decode."
                )
            cache = DecodeKVCache(
                self, (B, self.decode_cache_len, H, hd), k.dtype
            )

            # Left-padded prompts: each row's absolute positions shift
            # back by its pad count (see nn/utils.pad_row_offset).
            row_off = pad_row_offset(attention_mask)
            pos_offset = (
                cache.index if row_off is None else cache.index + row_off
            )

        if self.rotary_dim is not None and not self.cross_attention:
            # The cache stores POST-rotary K: chunk q/k rotate once at
            # their absolute (cache-slot) positions.
            q, k = apply_rotary(
                q, k, self.rotary_dim,
                base=self.rotary_emb_base or 10000.0,
                neox_style=self.gpt_neox_type_rotary,
                offset=pos_offset,
            )

        if cache is not None:
            k, v, decode_mask = cache.append(k, v, window=self.window_size)
            if decode_mask is not None:
                # Combine with a caller mask (e.g. the T5 relative-position
                # bias, additive [1, H, 1, cache_len] for this step's row).
                if attention_mask is None:
                    attention_mask = decode_mask
                elif attention_mask.dtype == jnp.bool_:
                    attention_mask = attention_mask & decode_mask
                else:
                    attention_mask = attention_mask + jnp.where(
                        decode_mask, 0.0, self.mask_value
                    ).astype(attention_mask.dtype)

        scale = 1.0 / np.sqrt(hd) if self.scale_attention_scores else 1.0
        extra_scale = None
        qk_compensation = None
        layer_idx = None if xs is None else xs.get("layer_idx")
        if self.scale_attn_by_layer_idx and layer_idx is not None:
            # Net scores scaled by 1/(layer_idx+1) (reference
            # torch/nn/transformer.py:1754-1767).
            extra_scale = 1.0 / (layer_idx.astype(jnp.float32) + 1.0)
        if self.query_key_layer_scaling and layer_idx is not None:
            # Numerics-only: protects the half-precision score matmul from
            # overflow; compensated in fp32 before softmax (reference
            # torch/nn/transformer.py:1804-1836).
            qk_compensation = layer_idx.astype(jnp.float32) + 1.0

        local_select = None if xs is None else xs.get("is_local")
        # Causal iff a causal-mask size is configured (reference: GPT-family
        # hooks set causal_mask_size; BERT-family leave it None and mask via
        # attention_mask only). A decode step replaces causal/window with
        # the explicit cache mask (positions <= cache index, banded).
        causal = (
            self.causal_mask_size is not None
            and not self.cross_attention
            and decode_mask is None
        )
        dropout_rng = (
            None
            if resolve_deterministic(self.deterministic)
            or self.attention_dropout_prob == 0.0
            else self.make_rng("dropout")
        )
        if _fp8_active():
            # fp8 handoff precision for the score matmul: q/k round to
            # the e4m3 grid with their slots' delayed scales (straight-
            # through gradient), then the flash/jnp attention runs as
            # built — the values the score dot consumes are exactly the
            # ones a native-f8 kernel would see. A real in-kernel fp8
            # flash pass is the TPU follow-up (its backward would hand
            # f8-dtyped cotangents across the custom_vjp boundary).
            from smdistributed_modelparallel_tpu import quant as _quant

            q = _quant.fake_quant(q, "attn_q.x")
            k = _quant.fake_quant(k, "attn_k.x")
        ctx = attention_core(
            q, k, v,
            causal=causal,
            window=self.window_size if decode_mask is None else None,
            local_select=local_select,
            scale=scale,
            extra_scale=extra_scale,
            qk_compensation=qk_compensation,
            mask=attention_mask,
            mask_value=self.mask_value,
            attention_in_fp32=self.attention_in_fp32,
            dropout_rate=self.attention_dropout_prob,
            dropout_rng=dropout_rng,
            use_pallas=_cfg("use_pallas_kernels", True),
        )

        proj_kernel = self.param(
            "dense/kernel",
            partitioned(init, (TP_AXIS, None, None)),
            (H, hd, D),
            dtype,
        )
        out = None
        if not self.decode and not self.cross_attention and _ring_active():
            # Overlapped tp: the row-parallel output reduce-scatter
            # decomposes into an accumulator ring (the bias is added
            # once, after the reduction, below).
            from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                ring_rs_matmul,
            )

            out = ring_rs_matmul(
                ctx, proj_kernel.astype(ctx.dtype),
                n_contract=2, x_tp_dim=2,
            )
        if out is None:
            if _fp8_active():
                out = _fp8_mm(
                    ctx, proj_kernel.astype(ctx.dtype), "attn_proj",
                    n_contract=2,
                )
            else:
                out = jnp.einsum(
                    "bthk,hkd->btd", ctx, proj_kernel.astype(ctx.dtype)
                )
        out = shard_activation(out, *_hidden_spec(_seq_parallel(memory_opt)))
        if self.use_attn_dense_bias:
            proj_bias = self.param(
                "dense/bias", nn.initializers.zeros, (D,), dtype
            )
            out = out + proj_bias.astype(out.dtype)
        if self.hidden_dropout_prob > 0.0 and not resolve_deterministic(self.deterministic):
            out = nn.Dropout(self.hidden_dropout_prob, deterministic=False)(out)
        return out


class DistributedTransformerOutputLayer(nn.Module):
    """TP MLP block: fc (column-parallel) -> activation -> proj (row-
    parallel). Parity: reference ``DistributedTransformerOutputLayer``
    (``torch/nn/transformer.py:965-1175``), same dual speed/memory strategy.
    """

    hidden_size: int
    intermediate_size: int
    hidden_dropout_prob: float = 0.1
    activation: str = "gelu"
    initializer_range: float = 0.02
    fused_bias_gelu: bool = False
    use_mlp_bias: bool = True
    # Gated MLP (T5 v1.1 / flan-T5, LLaMA-style): out = act(gate(x)) *
    # fc(x) @ proj. Both input projections are column-parallel over tp.
    gated_mlp: bool = False
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.nowrap
    def _fused_gelu_wanted(self):
        """Whether the fused bias+GELU Pallas kernel should run: the
        module's ``fused_bias_gelu`` flag (the reference's knob, now
        actually dispatching), a bias to fold, the tanh-GELU family, and
        the generic pallas gate. Counted per trace
        (``smp_fused_kernel_dispatch_total``)."""
        if not (self.fused_bias_gelu and self.use_mlp_bias
                and not self.gated_mlp):
            return False
        if not _cfg("use_pallas_kernels", True):
            return False
        from smdistributed_modelparallel_tpu.ops.pallas_gelu import (
            bias_gelu_ok,
        )
        from smdistributed_modelparallel_tpu.utils.telemetry import (
            record_fused_kernel_dispatch,
        )

        ok = bias_gelu_ok(self.activation)
        record_fused_kernel_dispatch(
            "bias_gelu", "pallas" if ok else "fallback"
        )
        return ok

    @nn.compact
    def __call__(self, hidden):
        D, F = self.hidden_size, self.intermediate_size
        dtype = self.dtype or hidden.dtype
        memory_opt = _cfg("optimize", "speed") == "memory"
        init = _init(self.initializer_range)
        ring = _ring_active()
        fused_gelu = self._fused_gelu_wanted()

        fc_kernel = self.param(
            "fc/kernel", partitioned(init, (None, TP_AXIS)), (D, F), dtype
        )
        fc_bias = None
        if self.use_mlp_bias:
            fc_bias = self.param(
                "fc/bias", partitioned(nn.initializers.zeros, (TP_AXIS,)),
                (F,), dtype,
            )

        def col_matmul(kernel, bias):
            """Column-parallel ``hidden @ kernel (+ bias)``: the
            ring-decomposed overlapped form under tp_overlap, the GSPMD
            einsum otherwise (where XLA fuses the bias into the matmul
            epilogue — parity: fused_bias_gelu, torch/nn/gelu.py — or
            the explicit Pallas kernel takes it below)."""
            y = None
            if ring:
                from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                    ring_ag_matmul,
                )

                y = ring_ag_matmul(
                    hidden, kernel.astype(hidden.dtype),
                    bias.astype(hidden.dtype) if bias is not None else None,
                    w_tp_dim=1,
                )
            if y is None:
                if _fp8_active():
                    y = _fp8_mm(
                        hidden, kernel.astype(hidden.dtype), "mlp_fc"
                    )
                else:
                    y = hidden @ kernel.astype(hidden.dtype)
                y = shard_activation(y, BATCH_AXES, CP_AXIS, TP_AXIS)
                if bias is not None:
                    y = y + bias.astype(y.dtype)
            else:
                y = shard_activation(y, BATCH_AXES, CP_AXIS, TP_AXIS)
            return y

        if fused_gelu:
            from smdistributed_modelparallel_tpu.nn.utils import (
                fused_bias_gelu,
            )

            h = col_matmul(fc_kernel, None)
            h = fused_bias_gelu(h, fc_bias.astype(h.dtype))
        else:
            h = col_matmul(fc_kernel, fc_bias)
            if self.gated_mlp:
                gate_kernel = self.param(
                    "gate/kernel", partitioned(init, (None, TP_AXIS)),
                    (D, F), dtype,
                )
                g = col_matmul(gate_kernel, None)
                h = _activation(self.activation)(g) * h
            else:
                h = _activation(self.activation)(h)

        proj_kernel = self.param(
            "proj/kernel", partitioned(init, (TP_AXIS, None)), (F, D), dtype
        )
        out = None
        if ring:
            from smdistributed_modelparallel_tpu.ops.collective_matmul import (  # noqa: E501
                ring_rs_matmul,
            )

            out = ring_rs_matmul(h, proj_kernel.astype(h.dtype),
                                 n_contract=1)
        if out is None:
            if _fp8_active():
                out = _fp8_mm(h, proj_kernel.astype(h.dtype), "mlp_proj")
            else:
                out = h @ proj_kernel.astype(h.dtype)
        out = shard_activation(out, *_hidden_spec(_seq_parallel(memory_opt)))
        if self.use_mlp_bias:
            proj_bias = self.param(
                "proj/bias", nn.initializers.zeros, (D,), dtype
            )
            out = out + proj_bias.astype(out.dtype)
        if self.hidden_dropout_prob > 0.0 and not resolve_deterministic(self.deterministic):
            out = nn.Dropout(self.hidden_dropout_prob, deterministic=False)(out)
        return out


class DistributedTransformerLayer(nn.Module):
    """One transformer block: attention + MLP with pre/post-LN variants.

    Parity: reference ``DistributedTransformerLayer``; layernorm placement
    keys (``pre_layernorm``/``post_layernorm``/``single_pre_layernorm``),
    ``fp32_residual_addition``, optional cross-attention, GPT-J-style
    ``parallel_attn_output``.
    """

    num_attention_heads: int
    attention_head_size: int
    hidden_size: int
    intermediate_size: int
    attention_dropout_prob: float = 0.1
    hidden_dropout_prob: float = 0.1
    activation: str = "gelu"
    layernorm_epsilon: float = 1e-5
    mask_value: float = -1e4
    add_cross_attention: bool = False
    pre_layernorm: bool = False
    post_layernorm: bool = True
    single_pre_layernorm: bool = False
    attention_in_fp32: bool = False
    query_key_layer_scaling: bool = False
    scale_attention_scores: bool = True
    scale_attn_by_layer_idx: bool = False
    fp32_residual_addition: bool = False
    fused_bias_gelu: bool = False
    initializer_range: float = 0.02
    use_qkv_bias: bool = True
    use_attn_dense_bias: bool = True
    rotary_dim: Optional[int] = None
    rotary_emb_base: Optional[float] = None
    gpt_neox_type_rotary: bool = False
    window_size: Optional[int] = None
    parallel_attn_output: bool = False
    causal_mask_size: Optional[int] = None
    # T5-compat knobs (TPU extension beyond the reference's layer-level T5
    # hooks): RMS layernorms and bias-free MLP dense layers.
    layernorm_type: str = "layer"
    use_mlp_bias: bool = True
    gated_mlp: bool = False
    # MoE (TPU extension; reference has no MoE — SURVEY §2.6): when
    # num_experts > 0 the MLP block is a DistributedMoE routed over the
    # ep mesh axis instead of a dense DistributedTransformerOutputLayer.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    decode: bool = False
    decode_cache_len: Optional[int] = None
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, hidden, cross_states=None, attention_mask=None, xs=None):
        # attention_mask may be a (self_mask, cross_mask) pair: the stack's
        # carry protocol has one mask slot, and T5-style models need both a
        # per-head relative-position bias on self-attention and an encoder
        # key-padding mask on cross-attention.
        cross_attention_mask = None
        if isinstance(attention_mask, tuple):
            attention_mask, cross_attention_mask = attention_mask
        rms = self.layernorm_type == "rms"
        ln = lambda name: DistributedLayerNorm(
            epsilon=self.layernorm_epsilon, rms=rms, use_bias=not rms,
            name=name,
        )
        attn = DistributedAttentionLayer(
            num_attention_heads=self.num_attention_heads,
            attention_head_size=self.attention_head_size,
            hidden_size=self.hidden_size,
            attention_dropout_prob=self.attention_dropout_prob,
            hidden_dropout_prob=self.hidden_dropout_prob,
            causal_mask_size=self.causal_mask_size,
            mask_value=self.mask_value,
            attention_in_fp32=self.attention_in_fp32,
            query_key_layer_scaling=self.query_key_layer_scaling,
            scale_attention_scores=self.scale_attention_scores,
            scale_attn_by_layer_idx=self.scale_attn_by_layer_idx,
            initializer_range=self.initializer_range,
            use_qkv_bias=self.use_qkv_bias,
            use_attn_dense_bias=self.use_attn_dense_bias,
            rotary_dim=self.rotary_dim,
            rotary_emb_base=self.rotary_emb_base,
            gpt_neox_type_rotary=self.gpt_neox_type_rotary,
            window_size=self.window_size,
            decode=self.decode,
            decode_cache_len=self.decode_cache_len,
            deterministic=self.deterministic,
            dtype=self.dtype,
            name="attention",
        )
        if self.num_experts > 0:
            from smdistributed_modelparallel_tpu.nn.moe import DistributedMoE

            mlp = DistributedMoE(
                hidden_size=self.hidden_size,
                intermediate_size=self.intermediate_size,
                num_experts=self.num_experts,
                top_k=self.moe_top_k,
                capacity_factor=self.moe_capacity_factor,
                hidden_dropout_prob=self.hidden_dropout_prob,
                activation=self.activation,
                initializer_range=self.initializer_range,
                deterministic=self.deterministic,
                dtype=self.dtype,
                name="output",
            )
        else:
            mlp = DistributedTransformerOutputLayer(
                hidden_size=self.hidden_size,
                intermediate_size=self.intermediate_size,
                hidden_dropout_prob=self.hidden_dropout_prob,
                activation=self.activation,
                initializer_range=self.initializer_range,
                fused_bias_gelu=self.fused_bias_gelu,
                use_mlp_bias=self.use_mlp_bias,
                gated_mlp=self.gated_mlp,
                deterministic=self.deterministic,
                dtype=self.dtype,
                name="output",
            )

        res_dtype = jnp.float32 if self.fp32_residual_addition else hidden.dtype
        x = hidden

        if self.parallel_attn_output:
            # Parallel residual: GPT-J style shares one LN
            # (single_pre_layernorm); GPT-NeoX style (pre_layernorm, two
            # LNs) feeds the MLP from its own post-attention layernorm.
            h = ln("attention/layernorm")(x)
            if self.pre_layernorm and not self.single_pre_layernorm:
                h_mlp = ln("output/layernorm")(x)
            else:
                h_mlp = h
            a = attn(h, attention_mask=attention_mask, xs=xs)
            m = mlp(h_mlp)
            x = (x.astype(res_dtype) + a.astype(res_dtype) + m.astype(res_dtype)).astype(hidden.dtype)
            return x

        if self.pre_layernorm or self.single_pre_layernorm:
            h = ln("attention/layernorm")(x)
        else:
            h = x
        a = attn(h, attention_mask=attention_mask, xs=xs)
        x = (x.astype(res_dtype) + a.astype(res_dtype)).astype(hidden.dtype)
        if self.post_layernorm:
            x = ln("attention/post_layernorm")(x)

        if self.add_cross_attention and cross_states is not None:
            cross = DistributedAttentionLayer(
                num_attention_heads=self.num_attention_heads,
                attention_head_size=self.attention_head_size,
                hidden_size=self.hidden_size,
                attention_dropout_prob=self.attention_dropout_prob,
                hidden_dropout_prob=self.hidden_dropout_prob,
                cross_attention=True,
                mask_value=self.mask_value,
                attention_in_fp32=self.attention_in_fp32,
                scale_attention_scores=self.scale_attention_scores,
                initializer_range=self.initializer_range,
                use_qkv_bias=self.use_qkv_bias,
                use_attn_dense_bias=self.use_attn_dense_bias,
                deterministic=self.deterministic,
                dtype=self.dtype,
                name="crossattention",
            )
            h = ln("crossattention/layernorm")(x) if self.pre_layernorm else x
            c = cross(
                h, cross_states=cross_states,
                attention_mask=cross_attention_mask,
            )
            x = (x.astype(res_dtype) + c.astype(res_dtype)).astype(hidden.dtype)
            if self.post_layernorm:
                x = ln("crossattention/post_layernorm")(x)

        if (self.pre_layernorm and not self.single_pre_layernorm):
            h = ln("output/layernorm")(x)
        else:
            h = x
        m = mlp(h)
        x = (x.astype(res_dtype) + m.astype(res_dtype)).astype(hidden.dtype)
        if self.post_layernorm:
            x = ln("output/post_layernorm")(x)
        return x


class _LayerScanBody(nn.Module):
    """nn.scan body threading per-layer xs (layer_idx, is_local)."""

    layer_kwargs: dict

    @nn.compact
    def __call__(self, carry, xs):
        from smdistributed_modelparallel_tpu.parallel.memory import (
            name_layer_activation,
        )

        x, cross_states, attention_mask = carry
        out = DistributedTransformerLayer(**self.layer_kwargs, name="layer")(
            x, cross_states=cross_states, attention_mask=attention_mask, xs=xs
        )
        out = name_layer_activation(out)
        ys = None
        if _fp8_active():
            # The fp8 seams inside this body recorded amax observations
            # on THIS scan trace; drain them into per-layer ys so they
            # escape the nn.scan — the Python-side pending dict cannot
            # carry tracers across the scan boundary.
            from smdistributed_modelparallel_tpu import quant as _q

            qd = _q.scan_drain()
            if qd:
                ys = qd
        return (out, cross_states, attention_mask), ys


class DistributedTransformer(nn.Module):
    """The scanned transformer stack.

    Parity: reference ``DistributedTransformer`` (``torch/nn/transformer.py:
    551-687``) — ``seq_layers`` of DistributedTransformerLayer. Accepts the
    same per-layer config keys; ``attention_layers_type`` (GPT-Neo) selects
    local/global attention per layer.
    """

    num_layers: int = 12
    num_attention_heads: int = 32
    attention_head_size: int = 32
    hidden_size: int = 1024
    intermediate_size: int = 4096
    attention_dropout_prob: float = 0.1
    hidden_dropout_prob: float = 0.1
    activation: str = "gelu"
    layernorm_epsilon: float = 1e-5
    mask_value: float = -1e4
    add_cross_attention: bool = False
    pre_layernorm: bool = False
    post_layernorm: bool = True
    single_pre_layernorm: bool = False
    attention_in_fp32: bool = False
    query_key_layer_scaling: bool = False
    scale_attention_scores: bool = True
    scale_attn_by_layer_idx: bool = False
    fp32_residual_addition: bool = False
    fused_bias_gelu: bool = False
    initializer_range: float = 0.02
    use_qkv_bias: bool = True
    use_attn_dense_bias: bool = True
    rotary_dim: Optional[int] = None
    rotary_emb_base: Optional[float] = None
    gpt_neox_type_rotary: bool = False
    window_size: Optional[int] = None
    parallel_attn_output: bool = False
    causal_mask_size: Optional[int] = None
    layernorm_type: str = "layer"
    use_mlp_bias: bool = True
    gated_mlp: bool = False
    attention_layers_type: Optional[tuple] = None
    activation_checkpointing: bool = False
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    decode: bool = False
    decode_cache_len: Optional[int] = None
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.nowrap
    def _layer_kwargs(self):
        return dict(
            num_attention_heads=self.num_attention_heads,
            attention_head_size=self.attention_head_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            attention_dropout_prob=self.attention_dropout_prob,
            hidden_dropout_prob=self.hidden_dropout_prob,
            activation=self.activation,
            layernorm_epsilon=self.layernorm_epsilon,
            mask_value=self.mask_value,
            add_cross_attention=self.add_cross_attention,
            pre_layernorm=self.pre_layernorm,
            post_layernorm=self.post_layernorm,
            single_pre_layernorm=self.single_pre_layernorm,
            attention_in_fp32=self.attention_in_fp32,
            query_key_layer_scaling=self.query_key_layer_scaling,
            scale_attention_scores=self.scale_attention_scores,
            scale_attn_by_layer_idx=self.scale_attn_by_layer_idx,
            fp32_residual_addition=self.fp32_residual_addition,
            fused_bias_gelu=self.fused_bias_gelu,
            initializer_range=self.initializer_range,
            use_qkv_bias=self.use_qkv_bias,
            use_attn_dense_bias=self.use_attn_dense_bias,
            rotary_dim=self.rotary_dim,
            rotary_emb_base=self.rotary_emb_base,
            gpt_neox_type_rotary=self.gpt_neox_type_rotary,
            window_size=self.window_size,
            parallel_attn_output=self.parallel_attn_output,
            causal_mask_size=self.causal_mask_size,
            layernorm_type=self.layernorm_type,
            use_mlp_bias=self.use_mlp_bias,
            gated_mlp=self.gated_mlp,
            num_experts=self.num_experts,
            moe_top_k=self.moe_top_k,
            moe_capacity_factor=self.moe_capacity_factor,
            decode=self.decode,
            decode_cache_len=self.decode_cache_len,
            deterministic=self.deterministic,
            dtype=self.dtype,
        )

    @nn.nowrap
    def layer_xs(self):
        xs = {"layer_idx": jnp.arange(self.num_layers, dtype=jnp.int32)}
        # is_local only exists for per-layer local/global selection: a
        # traced selector disqualifies the static-window Pallas/CP fast
        # paths, and a homogeneous stack must keep window_size STATIC so
        # (a) windowed attention actually applies without
        # attention_layers_type and (b) the fast paths engage.
        if self.attention_layers_type is not None:
            if len(self.attention_layers_type) != self.num_layers:
                raise SMPValidationError(
                    "attention_layers_type must have num_layers entries."
                )
            xs["is_local"] = jnp.asarray(
                [t == "local" for t in self.attention_layers_type], dtype=bool
            )
        return xs

    def setup(self):
        body = _LayerScanBody
        if self.activation_checkpointing:
            from smdistributed_modelparallel_tpu.parallel.memory import remat_policy

            # Parity: reference set_activation_checkpointing on the layer
            # container (torch/module_manager.py:969-1010) -> per-layer
            # remat, optionally offloading the boundary activation.
            body = nn.remat(body, policy=remat_policy())
        ScanLayers = nn.scan(
            body,
            # intermediates: per-layer sown values (MoE aux losses) stack
            # on the layer axis when applied with mutable=["intermediates"];
            # cache: per-layer decode KV caches (smp.generate).
            variable_axes={"params": 0, "intermediates": 0, "cache": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.num_layers,
            in_axes=(0,),
            # The scan (layer) axis carries no TP name; its 'pp' sharding is
            # applied by the pipeline's spec provider at partition time.
            metadata_params={nn.meta.PARTITION_NAME: None},
        )
        self.seq_layers = ScanLayers(self._layer_kwargs(), name="seq_layers")

    def __call__(self, hidden, cross_states=None, attention_mask=None):
        (out, _, _), ys = self.seq_layers(
            (hidden, cross_states, attention_mask), self.layer_xs()
        )
        if ys is not None:
            # Stacked per-layer amax from the body's quant drain: fold
            # the max over layers back into the enclosing trace level
            # (the microbatch body re-drains it into ITS ys).
            from smdistributed_modelparallel_tpu import quant as _q

            _q.absorb_stacked(ys)
        return out

    # -- pipeline decomposition: identity embed/head carrying the side
    # inputs so attention_mask/cross_states survive pipelining ------------

    def embed(self, hidden, cross_states=None, attention_mask=None):
        return (hidden, cross_states, attention_mask)

    def head(self, carry):
        return carry[0] if isinstance(carry, tuple) else carry

    @nn.nowrap
    def pipeline_spec(self):
        return PipelineSpec(
            layer_path="seq_layers/layer",
            num_layers=self.num_layers,
            layer_module=DistributedTransformerLayer(**self._layer_kwargs()),
            layer_xs=self.layer_xs(),
            carry_is_tuple=True,
        )


class DistributedTransformerLMHead(nn.Module):
    """Embeddings + DistributedTransformer + LM head.

    Parity: reference ``DistributedTransformerLMHead``
    (``torch/nn/transformer.py:184-550``); the ``_KEYS`` config surface
    (``:189-236``) maps 1:1 onto these fields. ``prescaled_batch`` comes
    from the global smp config, as in the reference.
    """

    num_layers: int = 12
    num_attention_heads: int = 32
    attention_head_size: int = 32
    hidden_size: int = 1024
    intermediate_size: int = 4096
    vocab_size: int = 30522
    num_positions: int = 1024
    attention_dropout_prob: float = 0.1
    hidden_dropout_prob: float = 0.1
    embedding_dropout_prob: float = 0.1
    activation: str = "gelu"
    layernorm_epsilon: float = 1e-5
    mask_value: float = -1e4
    num_token_types: int = 0
    causal_mask_size: Optional[int] = None
    add_cross_attention: bool = False
    add_lm_head: bool = True
    initializer_range: float = 0.02
    use_normal_initialization: bool = False
    pre_layernorm: bool = False
    post_layernorm: bool = True
    attention_in_fp32: bool = False
    query_key_layer_scaling: bool = False
    fp32_residual_addition: bool = False
    fused_softmax: bool = True
    fused_bias_gelu: bool = False
    distribute_embedding: bool = False
    _scale_qkv_fan_out: bool = False
    _precision_test: bool = False
    rotary_dim: Optional[int] = None
    rotary_emb_base: Optional[float] = None
    gpt_neox_type_rotary: bool = False
    use_positional_embedding: bool = True
    # RoBERTa-style pad-aware positions: when set to the pad token id,
    # position ids are cumsum(ids != pad) * (ids != pad) + pad_id (HF
    # create_position_ids_from_input_ids) — pad tokens sit at the pad
    # position and real tokens skip pads (the embedding table carries the
    # pad_id + 1 extra rows).
    position_ids_from_padding: Optional[int] = None
    parallel_attn_output: bool = False
    use_lm_head_bias: bool = False
    attention_layers_type: Optional[tuple] = None
    use_qkv_bias: bool = True
    use_attn_dense_bias: bool = True
    window_size: Optional[int] = None
    final_layernorm: bool = False
    tie_input_output_embedding: bool = True
    single_pre_layernorm: bool = False
    scale_attention_scores: bool = True
    scale_attn_by_layer_idx: bool = False
    activation_checkpointing: bool = False
    use_embedding_layernorm: bool = False  # BERT-family post-embedding LN
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Loss-mode (targets=...) uniform label smoothing, HF/T5 convention.
    label_smoothing: float = 0.0
    # KV-cache decoding for smp.generate (see nn/utils.DecodeKVCache).
    decode: bool = False
    decode_cache_len: Optional[int] = None
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    def setup(self):
        if self.distribute_embedding:
            self.word_embedding = DistributedEmbedding(
                self.vocab_size, self.hidden_size,
                split="vocab",
                init_scale=self.initializer_range,
                name="word_embedding",
            )
        else:
            self.word_embedding = nn.Embed(
                self.vocab_size, self.hidden_size,
                embedding_init=_init(self.initializer_range),
                name="word_embedding",
            )
        if self.use_positional_embedding:
            self.position_embedding = nn.Embed(
                self.num_positions, self.hidden_size,
                embedding_init=_init(self.initializer_range),
                name="position_embedding",
            )
        if self.num_token_types > 0:
            self.token_type_embedding = nn.Embed(
                self.num_token_types, self.hidden_size,
                embedding_init=_init(self.initializer_range),
                name="token_type_embedding",
            )
        if self.use_embedding_layernorm:
            self.embedding_layernorm = DistributedLayerNorm(
                epsilon=self.layernorm_epsilon, name="embedding_layernorm"
            )
        self.transformer = DistributedTransformer(
            **self._transformer_kwargs(), name="transformer"
        )
        if self.final_layernorm or self.pre_layernorm:
            self.ln_f = DistributedLayerNorm(
                epsilon=self.layernorm_epsilon, name="ln_f"
            )
        if self.add_lm_head and not self.tie_input_output_embedding:
            self.lm_head = nn.Dense(
                self.vocab_size, use_bias=self.use_lm_head_bias,
                kernel_init=_init(self.initializer_range),
                name="lm_head",
            )
        if self.decode:
            # Top-level mirror of the per-layer cache indices (absolute
            # position offset for the learned position embedding).
            self._pos_index = self.variable(
                "cache", "position_index", lambda: jnp.zeros((), jnp.int32)
            )

    @nn.nowrap
    def _transformer_kwargs(self):
        return dict(
            num_layers=self.num_layers,
            num_attention_heads=self.num_attention_heads,
            attention_head_size=self.attention_head_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            attention_dropout_prob=self.attention_dropout_prob,
            hidden_dropout_prob=self.hidden_dropout_prob,
            activation=self.activation,
            layernorm_epsilon=self.layernorm_epsilon,
            mask_value=self.mask_value,
            add_cross_attention=self.add_cross_attention,
            pre_layernorm=self.pre_layernorm,
            post_layernorm=self.post_layernorm,
            single_pre_layernorm=self.single_pre_layernorm,
            attention_in_fp32=self.attention_in_fp32,
            query_key_layer_scaling=self.query_key_layer_scaling,
            scale_attention_scores=self.scale_attention_scores,
            scale_attn_by_layer_idx=self.scale_attn_by_layer_idx,
            fp32_residual_addition=self.fp32_residual_addition,
            fused_bias_gelu=self.fused_bias_gelu,
            initializer_range=self.initializer_range,
            use_qkv_bias=self.use_qkv_bias,
            use_attn_dense_bias=self.use_attn_dense_bias,
            rotary_dim=self.rotary_dim,
            rotary_emb_base=self.rotary_emb_base,
            gpt_neox_type_rotary=self.gpt_neox_type_rotary,
            window_size=self.window_size,
            parallel_attn_output=self.parallel_attn_output,
            causal_mask_size=self.causal_mask_size,
            attention_layers_type=self.attention_layers_type,
            activation_checkpointing=self.activation_checkpointing,
            num_experts=self.num_experts,
            moe_top_k=self.moe_top_k,
            moe_capacity_factor=self.moe_capacity_factor,
            decode=self.decode,
            decode_cache_len=self.decode_cache_len,
            deterministic=self.deterministic,
            dtype=self.dtype,
        )

    # -- pipeline decomposition (PipelineSpec protocol) -----------------

    def embed(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.word_embedding(input_ids)
        if self.use_positional_embedding:
            if self.position_ids_from_padding is not None:
                if self.decode:
                    raise SMPValidationError(
                        "decode=True is unsupported with "
                        "position_ids_from_padding (RoBERTa-style "
                        "pad-aware positions)."
                    )
                ne = (input_ids != self.position_ids_from_padding).astype(jnp.int32)
                pos = jnp.cumsum(ne, axis=-1) * ne + self.position_ids_from_padding
            else:
                start = 0
                if self.decode:
                    # Top-level mirror of the per-layer cache indices:
                    # learned positions need the absolute offset before
                    # the layer stack; left-padded prompts additionally
                    # shift each row by its pad count (see the attention
                    # layers' pos_offset).
                    from smdistributed_modelparallel_tpu.nn.utils import (
                        pad_row_offset,
                    )

                    idx = self._pos_index.value
                    self._pos_index.value = idx + input_ids.shape[-1]
                    row_off = pad_row_offset(attention_mask)
                    start = (
                        idx if row_off is None else (idx + row_off)[:, None]
                    )
                pos = jnp.maximum(
                    start + jnp.arange(input_ids.shape[-1])[None, :], 0
                )
            x = x + self.position_embedding(pos)
        if self.num_token_types > 0 and token_type_ids is not None:
            x = x + self.token_type_embedding(token_type_ids)
        if self.use_embedding_layernorm:
            x = self.embedding_layernorm(x)
        if self.embedding_dropout_prob > 0.0 and not resolve_deterministic(self.deterministic):
            x = nn.Dropout(self.embedding_dropout_prob, deterministic=False)(x)
        memory_opt = _cfg("optimize", "speed") == "memory"
        x = shard_activation(x, *_hidden_spec(_seq_parallel(memory_opt)))
        return (x, None, attention_mask)

    def head(self, carry, targets=None):
        x, _, _ = carry if isinstance(carry, tuple) else (carry, None, None)
        if self.final_layernorm or self.pre_layernorm:
            x = self.ln_f(x)
        if not self.add_lm_head:
            return x
        if targets is not None and self.tie_input_output_embedding:
            # Fused LM-head CE (TPU extension): per-token losses without
            # the [.., V] logits intermediate. The dispatcher falls back
            # to the Megatron vocab-parallel path under tp / off-TPU.
            from smdistributed_modelparallel_tpu.nn.cross_entropy import (
                fused_lm_head_cross_entropy,
            )

            return fused_lm_head_cross_entropy(
                x, self.word_embedding.embedding, targets,
                label_smoothing=self.label_smoothing,
            )
        if self.tie_input_output_embedding:
            logits = self.word_embedding.attend(x)
        else:
            logits = self.lm_head(x)
        if targets is None:
            return logits
        from smdistributed_modelparallel_tpu.nn.cross_entropy import (
            masked_vocab_parallel_cross_entropy,
        )

        return masked_vocab_parallel_cross_entropy(
            logits, targets, label_smoothing=self.label_smoothing
        )

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 targets=None):
        """ids -> logits; with ``targets`` ([B, T] int, -100 = ignored) ->
        per-token fp32 losses via the fused LM-head CE. Loss mode
        requires pp == 1 (the pipeline head protocol carries no
        targets)."""
        if targets is not None:
            if state.cfg is not None and state.cfg.pipeline_parallel_degree > 1:
                raise SMPValidationError(
                    "model(ids, targets=...) is not available under "
                    "pipeline parallelism; compute the loss from logits."
                )
        carry = self.embed(input_ids, token_type_ids, attention_mask)
        x, cross, amask = carry
        x = self.transformer(x, attention_mask=amask)
        return self.head((x, cross, amask), targets=targets)

    @nn.nowrap
    def pipeline_spec(self):
        return PipelineSpec(
            layer_path="transformer/seq_layers/layer",
            num_layers=self.num_layers,
            layer_module=DistributedTransformerLayer(
                **{
                    k: v
                    for k, v in self._transformer_kwargs().items()
                    if k not in (
                        "num_layers",
                        "attention_layers_type",
                        "activation_checkpointing",
                    )
                }
            ),
            layer_xs=DistributedTransformer(
                **self._transformer_kwargs()
            ).layer_xs(),
            carry_is_tuple=True,
        )
