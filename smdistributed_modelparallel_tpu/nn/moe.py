"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

NEW CAPABILITY relative to the reference: SURVEY §2.6 records MoE/EP as
absent from ``smdistributed.modelparallel`` v1.12.1. The TPU build carries
an ``ep`` mesh axis from the start (``backend/topology.py:33``), and this
module puts it to work with the GShard/Switch dense-dispatch formulation —
the design that maps best onto XLA:

- routing, position-in-expert bookkeeping, and capacity dropping are pure
  einsum/cumsum math on one-hot tensors (no scatters, no dynamic shapes —
  everything tiles onto the MXU and fuses);
- expert FFNs are ONE batched matmul over ``[E, C, D]`` with the expert
  axis sharded over ``ep`` (and the FFN hidden dim over ``tp``);
- the token->expert shuffle is not hand-written: tokens are batch-sharded
  over the data axes (which include ``ep``) while expert tensors are
  ep-sharded, so GSPMD lowers the dispatch/combine einsums to the
  all-to-all exchanges over ICI.

The router's load-balancing auxiliary loss (Switch-style
``E * sum(fraction_routed * mean_gate)``) is sown into the
``intermediates`` collection under ``moe_aux_loss``; callers training with
it add ``module.apply(..., mutable=["intermediates"])`` output, or read it
through ``smp.nn.moe_aux_losses(...)``.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from smdistributed_modelparallel_tpu.backend.topology import EP_AXIS, TP_AXIS
# Shared helpers with the dense MLP path (copies here would silently
# drift): activation table, init, config lookup, residual-stream spec.
from smdistributed_modelparallel_tpu.nn.transformer import (
    _activation,
    _cfg,
    _hidden_spec,
    _init,
)
from smdistributed_modelparallel_tpu.nn.utils import (
    axis_partitioned,
    resolve_deterministic,
    shard_activation,
)
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError


class DistributedMoE(nn.Module):
    """Drop-in MoE replacement for the transformer MLP block.

    Top-k routed mixture of expert FFNs with fixed per-expert capacity
    ``C = ceil(top_k * tokens * capacity_factor / num_experts)``; tokens
    beyond an expert's capacity fall through the residual (standard
    Switch/GShard semantics).
    """

    hidden_size: int
    intermediate_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    activation: str = "gelu"
    hidden_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    router_jitter: float = 0.0
    aux_loss_coef: float = 0.01
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, hidden):
        if self.top_k < 1 or self.top_k > self.num_experts:
            raise SMPValidationError(
                f"moe top_k ({self.top_k}) must be in [1, num_experts="
                f"{self.num_experts}]."
            )
        from smdistributed_modelparallel_tpu.backend.state import state

        ep = state.mesh.shape.get(EP_AXIS, 1) if state.initialized else 1
        if ep > 1 and self.num_experts % ep != 0:
            raise SMPValidationError(
                f"num_experts ({self.num_experts}) must be divisible by "
                f"expert_parallel_degree ({ep}) so experts shard evenly "
                "over the ep mesh axis."
            )
        D, F, E, K = (
            self.hidden_size, self.intermediate_size, self.num_experts,
            self.top_k,
        )
        dtype = self.dtype or hidden.dtype
        init = _init(self.initializer_range)
        deterministic = resolve_deterministic(self.deterministic)

        B, T = hidden.shape[0], hidden.shape[1]
        N = B * T
        x = hidden.reshape(N, D)

        # ---- router (fp32 for a stable softmax) -----------------------
        router_kernel = self.param("router/kernel", init, (D, E), jnp.float32)
        logits = x.astype(jnp.float32) @ router_kernel
        if self.router_jitter > 0.0 and not deterministic:
            noise = jax.random.uniform(
                self.make_rng("dropout"), logits.shape,
                minval=1.0 - self.router_jitter,
                maxval=1.0 + self.router_jitter,
            )
            logits = logits * noise
        gates = jax.nn.softmax(logits, axis=-1)            # [N, E]

        gate_vals, expert_idx = jax.lax.top_k(gates, K)    # [N, K]
        if K > 1:
            # Renormalize so the combine is a convex mixture. NOT for k=1:
            # Switch-style top-1 must scale by the raw softmax probability —
            # g/g == 1 would starve the router of task-loss gradient.
            gate_vals = gate_vals / jnp.maximum(
                jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
            )

        capacity = int(max(K, -(-K * N * self.capacity_factor // E)))

        # Position of each assignment within its expert, ordered k-major
        # (all first choices before any second choice) then token-major —
        # first choices are never dropped in favor of second choices.
        # Bookkeeping in int32: a float32 cumsum stops representing
        # consecutive integers past 2^24 assignments and would silently
        # collide capacity slots at pod-scale batches.
        sel = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [N, K, E]
        sel_i = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
        sel_km = sel_i.transpose(1, 0, 2).reshape(K * N, E)
        pos_km = jnp.cumsum(sel_km, axis=0) - sel_km
        pos = pos_km.reshape(K, N, E).transpose(1, 0, 2)        # [N, K, E]
        pos_k = jnp.sum(pos * sel_i, axis=-1)                   # [N, K] int32
        keep = (pos_k < capacity).astype(jnp.float32)

        pos_oh = jax.nn.one_hot(pos_k, capacity, dtype=jnp.float32)
        # combine[n, e, c]: gate weight of token n's assignment to slot
        # (e, c); dispatch is its 0/1 support.
        combine = jnp.einsum("nk,nke,nkc->nec", gate_vals * keep, sel, pos_oh)
        dispatch = jnp.einsum("nk,nke,nkc->nec", keep, sel, pos_oh)

        # ---- load-balance auxiliary (Switch eq. 4) --------------------
        frac_routed = jnp.mean(sel[:, 0, :], axis=0)       # top-1 fractions
        mean_gate = jnp.mean(gates, axis=0)
        aux = jnp.asarray(E, jnp.float32) * jnp.sum(frac_routed * mean_gate)
        self.sow("intermediates", "moe_aux_loss", self.aux_loss_coef * aux)

        # ---- expert FFNs (batched over the ep-sharded expert axis) ----
        fc_kernel = self.param(
            "fc/kernel", axis_partitioned(init, (EP_AXIS, None, TP_AXIS)),
            (E, D, F), dtype,
        )
        fc_bias = self.param(
            "fc/bias", axis_partitioned(nn.initializers.zeros, (EP_AXIS, TP_AXIS)),
            (E, F), dtype,
        )
        proj_kernel = self.param(
            "proj/kernel", axis_partitioned(init, (EP_AXIS, TP_AXIS, None)),
            (E, F, D), dtype,
        )
        proj_bias = self.param(
            "proj/bias", axis_partitioned(nn.initializers.zeros, (EP_AXIS, None)),
            (E, D), dtype,
        )

        expert_in = jnp.einsum(
            "nec,nd->ecd", dispatch.astype(hidden.dtype), x
        )
        expert_in = shard_activation(expert_in, EP_AXIS, None, None)
        h = jnp.einsum("ecd,edf->ecf", expert_in, fc_kernel.astype(expert_in.dtype))
        h = shard_activation(h, EP_AXIS, None, TP_AXIS)
        h = _activation(self.activation)(h + fc_bias[:, None].astype(h.dtype))
        y = jnp.einsum("ecf,efd->ecd", h, proj_kernel.astype(h.dtype))
        y = y + proj_bias[:, None].astype(y.dtype)
        y = shard_activation(y, EP_AXIS, None, None)

        out = jnp.einsum("nec,ecd->nd", combine.astype(y.dtype), y)
        out = out.reshape(B, T, D)
        # Residual-stream layout matches the dense MLP it replaces (incl.
        # the optimize='memory' sequence-parallel sharding).
        memory_opt = _cfg("optimize", "speed") == "memory"
        out = shard_activation(out, *_hidden_spec(memory_opt))
        if self.hidden_dropout_prob > 0.0 and not deterministic:
            out = nn.Dropout(self.hidden_dropout_prob, deterministic=False)(out)
        return out


def collect_moe_aux(intermediates):
    """Sum every sown ``moe_aux_loss`` in an intermediates tree, or None
    when nothing was sown (so MoE-free models add no term to traced
    losses). One entry per MoE layer; scanned stacks sow a [num_layers]
    vector."""
    if not intermediates:
        return None
    total = None
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        if any(
            getattr(k, "key", None) == "moe_aux_loss" for k in path
        ):
            s = jnp.sum(leaf)
            total = s if total is None else total + s
    return total


def moe_aux_losses(intermediates):
    """Sum every ``moe_aux_loss`` sown anywhere in an intermediates tree
    (0.0 when none). Kept for users reading aux losses from their own
    ``module.apply(..., mutable=["intermediates"])`` calls; the standard
    ``DistributedModel`` / pipeline paths fold the aux loss into the
    differentiated step loss automatically (weighted by the
    ``moe_aux_loss_weight`` config key)."""
    total = collect_moe_aux(intermediates)
    return 0.0 if total is None else total
