"""HF ViT translation.

Parity target: reference ``torch/nn/huggingface/vit.py`` —
``hf_vit_encoder_init_hook`` (``:33-51``) + encoder state-dict translators.
Scope matches the reference: the ENCODER stack only (``ViTEncoder`` ->
``DistributedTransformer``); patch/CLS/position embeddings, the final
layernorm, and the pooler stay outside (they are elementwise/embedding
work with no TP dimension worth distributing).

The family's ``target`` is therefore "transformer": ``translate_model``
builds a bare ``DistributedTransformer`` taking [B, tokens, D] hidden
states, and the flat key space is rooted at ``seq_layers/layer``.
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("ViTModel", "ViTForImageClassification")
TARGET = "transformer"

# DistributedTransformer standalone: no "transformer/" root.
L_ENC = "seq_layers/layer"


def config_to_smp(config):
    """HF ViTConfig -> DistributedTransformer kwargs (reference
    ``hf_vit_encoder_init_hook``)."""
    if config.hidden_size % config.num_attention_heads != 0:
        raise SMPValidationError(
            f"hidden_size ({config.hidden_size}) must be divisible by "
            f"num_attention_heads ({config.num_attention_heads})."
        )
    if config.hidden_act not in ("gelu", "gelu_new", "relu"):
        raise SMPValidationError(
            "Only gelu/gelu_new/relu activations are supported for ViT."
        )
    return {
        "num_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "attention_head_size": config.hidden_size // config.num_attention_heads,
        "hidden_size": config.hidden_size,
        "intermediate_size": config.intermediate_size,
        "activation": c.act_from_hf(config.hidden_act),
        "hidden_dropout_prob": config.hidden_dropout_prob,
        "attention_dropout_prob": config.attention_probs_dropout_prob,
        "initializer_range": config.initializer_range,
        "layernorm_epsilon": config.layer_norm_eps,
        "scale_attention_scores": True,
        # ViT blocks are pre-LN (layernorm_before / layernorm_after);
        # bidirectional (no causal mask).
        "pre_layernorm": True,
        "post_layernorm": False,
        "causal_mask_size": None,
        "use_qkv_bias": config.qkv_bias,
    }


def translate_hf_state_dict(sd, config=None):
    """HF ViT torch state dict (ViTModel or the bare encoder) -> flat
    '/'-keyed smp param dict for DistributedTransformer."""
    sd = {k: c.to_np(v) for k, v in sd.items()}
    prefix = next(
        (
            p for p in ("vit.encoder.layer.", "encoder.layer.", "layer.")
            if any(k.startswith(p) for k in sd)
        ),
        None,
    )
    if prefix is None:
        raise SMPValidationError("No ViT encoder layers found in state dict.")
    n_layers = c.num_layers_in(sd, prefix, prefix.count("."))
    if config is None:
        raise SMPValidationError("config required to infer head count.")
    H = config.num_attention_heads
    D = sd[f"{prefix}0.attention.output.dense.weight"].shape[0]
    hd = D // H

    layers = []
    for i in range(n_layers):
        p = f"{prefix}{i}"
        a = f"{p}.attention.attention"
        lay = {
            "attention/layernorm/scale": sd[f"{p}.layernorm_before.weight"],
            "attention/layernorm/bias": sd[f"{p}.layernorm_before.bias"],
            "attention/qkv/kernel": c.fused_qkv_from_separate(
                sd[f"{a}.query.weight"],
                sd[f"{a}.key.weight"],
                sd[f"{a}.value.weight"],
                H, hd, transpose=True,
            ),
            "attention/dense/kernel": c.attn_out_from_hf(
                sd[f"{p}.attention.output.dense.weight"], H, hd, transpose=True
            ),
            "attention/dense/bias": sd[f"{p}.attention.output.dense.bias"],
            "output/layernorm/scale": sd[f"{p}.layernorm_after.weight"],
            "output/layernorm/bias": sd[f"{p}.layernorm_after.bias"],
            "output/fc/kernel": sd[f"{p}.intermediate.dense.weight"].T,
            "output/fc/bias": sd[f"{p}.intermediate.dense.bias"],
            "output/proj/kernel": sd[f"{p}.output.dense.weight"].T,
            "output/proj/bias": sd[f"{p}.output.dense.bias"],
        }
        if f"{a}.query.bias" in sd:  # absent when config.qkv_bias=False
            lay["attention/qkv/bias"] = np.stack([
                sd[f"{a}.query.bias"].reshape(H, hd),
                sd[f"{a}.key.bias"].reshape(H, hd),
                sd[f"{a}.value.bias"].reshape(H, hd),
            ], axis=0)
        layers.append(lay)
    out = {}
    for k, v in c.stack_layers(layers).items():
        out[f"{L_ENC}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF ViT encoder naming (torch layout)."""
    n_layers = flat[f"{L_ENC}/attention/qkv/kernel"].shape[0]
    D = flat[f"{L_ENC}/attention/dense/bias"].shape[1]
    has_bias = f"{L_ENC}/attention/qkv/bias" in flat
    out = {}
    for i in range(n_layers):
        # Bare body keys — the registered ViTModel layout (wrapper models
        # like ViTForImageClassification prepend "vit." themselves).
        p = f"encoder.layer.{i}"
        a = f"{p}.attention.attention"
        g = lambda key: np.asarray(flat[f"{L_ENC}/{key}"][i])
        out[f"{p}.layernorm_before.weight"] = g("attention/layernorm/scale")
        out[f"{p}.layernorm_before.bias"] = g("attention/layernorm/bias")
        qw, kw, vw = c.separate_qkv_from_fused(
            g("attention/qkv/kernel"), transpose=True
        )
        out[f"{a}.query.weight"] = qw
        out[f"{a}.key.weight"] = kw
        out[f"{a}.value.weight"] = vw
        if has_bias:
            qb, kb, vb = (
                g("attention/qkv/bias")[j].reshape(-1) for j in range(3)
            )
            out[f"{a}.query.bias"] = qb
            out[f"{a}.key.bias"] = kb
            out[f"{a}.value.bias"] = vb
        out[f"{p}.attention.output.dense.weight"] = (
            g("attention/dense/kernel").reshape(-1, D).T
        )
        out[f"{p}.attention.output.dense.bias"] = g("attention/dense/bias")
        out[f"{p}.layernorm_after.weight"] = g("output/layernorm/scale")
        out[f"{p}.layernorm_after.bias"] = g("output/layernorm/bias")
        out[f"{p}.intermediate.dense.weight"] = g("output/fc/kernel").T
        out[f"{p}.intermediate.dense.bias"] = g("output/fc/bias")
        out[f"{p}.output.dense.weight"] = g("output/proj/kernel").T
        out[f"{p}.output.dense.bias"] = g("output/proj/bias")
    return out
