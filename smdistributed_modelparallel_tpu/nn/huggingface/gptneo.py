"""HF GPT-Neo translation.

Parity target: reference ``torch/nn/huggingface/gptneo.py`` —
``hf_gptneo_transformer_lm_head_init_hook`` (config mapping incl. the
``attention_types`` -> per-layer local/global expansion, ``:34-87``) and the
state-dict translators (``:146-300``).

Layernorm-placement note: as with GPT-2, the reference expresses GPT-Neo's
pre-LN blocks as (pre=True, post=True) in its own convention; in this
framework's semantics that is ``pre_layernorm=True, post_layernorm=False,
final_layernorm=True``.

Weight-layout notes: unlike GPT-2's Conv1D ([in, out]) weights, GPT-Neo
uses ``nn.Linear`` everywhere ([out, in] — transpose on the way in);
q/k/v are separate projections WITHOUT bias, the attention output
projection has bias.
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("GPTNeoForCausalLM", "GPTNeoModel")


def expand_attention_types(attention_types, num_layers):
    """HF ``attention_types`` ([[["global", "local"], 6]]) -> per-layer
    tuple. Parity: reference ``gptneo.py:44-52``."""
    layers = []
    for item in attention_types:
        kinds, repeat = item
        for _ in range(repeat):
            layers.extend(kinds)
    if len(layers) != num_layers:
        raise SMPValidationError(
            f"attention_types expands to {len(layers)} layers; expected "
            f"{num_layers}."
        )
    return tuple(layers)


def config_to_smp(config):
    """HF GPTNeoConfig -> DistributedTransformerLMHead kwargs."""
    if config.hidden_size % config.num_heads != 0:
        raise SMPValidationError(
            f"hidden_size ({config.hidden_size}) must be divisible by "
            f"num_heads ({config.num_heads})."
        )
    if config.activation_function not in ("gelu_new", "gelu", "relu"):
        raise SMPValidationError(
            "Only gelu_new/gelu/relu activations are supported for GPT-Neo."
        )
    return {
        "num_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "attention_head_size": config.hidden_size // config.num_heads,
        "hidden_size": config.hidden_size,
        "vocab_size": config.vocab_size,
        "activation": c.act_from_hf(config.activation_function),
        "add_lm_head": True,
        "tie_input_output_embedding": True,
        "intermediate_size": (
            config.intermediate_size
            if config.intermediate_size is not None
            else 4 * config.hidden_size
        ),
        "attention_dropout_prob": config.attention_dropout,
        "hidden_dropout_prob": config.resid_dropout,
        "embedding_dropout_prob": config.embed_dropout,
        "layernorm_epsilon": config.layer_norm_epsilon,
        "initializer_range": config.initializer_range,
        "attention_layers_type": expand_attention_types(
            config.attention_types, config.num_layers
        ),
        "use_normal_initialization": True,
        "pre_layernorm": True,
        "post_layernorm": False,
        "final_layernorm": True,
        "causal_mask_size": config.max_position_embeddings,
        "num_positions": config.max_position_embeddings,
        "window_size": config.window_size,
        "_scale_qkv_fan_out": True,
        # GPT-Neo does NOT scale scores by 1/sqrt(hd).
        "scale_attention_scores": False,
        "attention_in_fp32": True,
        "use_qkv_bias": False,
        "mask_value": -1e9,
    }


def translate_hf_state_dict(sd, config=None):
    """HF GPT-Neo torch state dict -> flat '/'-keyed smp param dict."""
    sd = {
        k: c.to_np(v) for k, v in sd.items()
        if not (k.endswith(".attn.bias") or k.endswith(".attn.masked_bias"))
    }
    prefix = "transformer." if "transformer.wte.weight" in sd else ""
    n_layers = c.num_layers_in(sd, f"{prefix}h.", 1 + (1 if prefix else 0))
    if config is None:
        raise SMPValidationError("config required to infer head count.")
    H = config.num_heads
    D = sd[f"{prefix}wte.weight"].shape[1]
    hd = D // H

    out = {
        c.WTE: sd[f"{prefix}wte.weight"],
        c.WPE: sd[f"{prefix}wpe.weight"],
        f"{c.LN_F}/scale": sd[f"{prefix}ln_f.weight"],
        f"{c.LN_F}/bias": sd[f"{prefix}ln_f.bias"],
    }
    layers = []
    for i in range(n_layers):
        p = f"{prefix}h.{i}"
        a = f"{p}.attn.attention"
        lay = {
            "attention/layernorm/scale": sd[f"{p}.ln_1.weight"],
            "attention/layernorm/bias": sd[f"{p}.ln_1.bias"],
            "attention/qkv/kernel": c.fused_qkv_from_separate(
                sd[f"{a}.q_proj.weight"],
                sd[f"{a}.k_proj.weight"],
                sd[f"{a}.v_proj.weight"],
                H, hd, transpose=True,
            ),
            "attention/dense/kernel": c.attn_out_from_hf(
                sd[f"{a}.out_proj.weight"], H, hd, transpose=True
            ),
            "attention/dense/bias": sd[f"{a}.out_proj.bias"],
            "output/layernorm/scale": sd[f"{p}.ln_2.weight"],
            "output/layernorm/bias": sd[f"{p}.ln_2.bias"],
            "output/fc/kernel": sd[f"{p}.mlp.c_fc.weight"].T,
            "output/fc/bias": sd[f"{p}.mlp.c_fc.bias"],
            "output/proj/kernel": sd[f"{p}.mlp.c_proj.weight"].T,
            "output/proj/bias": sd[f"{p}.mlp.c_proj.bias"],
        }
        layers.append(lay)
    for k, v in c.stack_layers(layers).items():
        out[f"{c.L}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF GPT-Neo naming (torch tensor layout)."""
    n_layers = flat[f"{c.L}/attention/qkv/kernel"].shape[0]
    D = flat[c.WTE].shape[1]
    out = {
        "transformer.wte.weight": flat[c.WTE],
        "transformer.wpe.weight": flat[c.WPE],
        "transformer.ln_f.weight": flat[f"{c.LN_F}/scale"],
        "transformer.ln_f.bias": flat[f"{c.LN_F}/bias"],
        "lm_head.weight": flat[c.WTE],
    }
    for i in range(n_layers):
        p = f"transformer.h.{i}"
        a = f"{p}.attn.attention"
        g = lambda key: np.asarray(flat[f"{c.L}/{key}"][i])
        out[f"{p}.ln_1.weight"] = g("attention/layernorm/scale")
        out[f"{p}.ln_1.bias"] = g("attention/layernorm/bias")
        qw, kw, vw = c.separate_qkv_from_fused(
            g("attention/qkv/kernel"), transpose=True
        )
        out[f"{a}.q_proj.weight"] = qw
        out[f"{a}.k_proj.weight"] = kw
        out[f"{a}.v_proj.weight"] = vw
        out[f"{a}.out_proj.weight"] = g("attention/dense/kernel").reshape(-1, D).T
        out[f"{a}.out_proj.bias"] = g("attention/dense/bias")
        out[f"{p}.ln_2.weight"] = g("output/layernorm/scale")
        out[f"{p}.ln_2.bias"] = g("output/layernorm/bias")
        out[f"{p}.mlp.c_fc.weight"] = g("output/fc/kernel").T
        out[f"{p}.mlp.c_fc.bias"] = g("output/fc/bias")
        out[f"{p}.mlp.c_proj.weight"] = g("output/proj/kernel").T
        out[f"{p}.mlp.c_proj.bias"] = g("output/proj/bias")
    return out
