"""HF GPT-2 translation.

Parity target: reference ``torch/nn/huggingface/gpt2.py`` —
``hf_gpt2_transformer_lm_head_init_hook`` (config mapping, ``:41-82``) and
``translate_hf_state_dict_to_smdistributed_gpt2`` /
``translate_state_dict_to_hf_gpt2`` (``:344-541``).

Layernorm-placement note: the reference maps GPT-2 with its own
(pre=True, post=True) convention; in this framework's semantics GPT-2 is
``pre_layernorm=True, post_layernorm=False, final_layernorm=True`` — the
actual pre-LN GPT-2 block structure.
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("GPT2LMHeadModel", "GPT2Model")


def config_to_smp(config):
    """HF GPT2Config -> DistributedTransformerLMHead kwargs."""
    if config.n_embd % config.n_head != 0:
        raise SMPValidationError(
            f"n_embd ({config.n_embd}) must be divisible by n_head ({config.n_head})."
        )
    if config.activation_function not in ("gelu_new", "gelu", "relu"):
        raise SMPValidationError(
            "Only gelu_new/gelu/relu activations are supported for GPT-2."
        )
    return {
        "num_layers": config.n_layer,
        "num_attention_heads": config.n_head,
        "attention_head_size": config.n_embd // config.n_head,
        "hidden_size": config.n_embd,
        "vocab_size": config.vocab_size,
        "activation": c.act_from_hf(config.activation_function),
        "add_lm_head": True,
        "tie_input_output_embedding": True,
        "intermediate_size": (
            config.n_inner if config.n_inner is not None else 4 * config.n_embd
        ),
        "attention_dropout_prob": config.attn_pdrop,
        "hidden_dropout_prob": config.resid_pdrop,
        "embedding_dropout_prob": config.embd_pdrop,
        "layernorm_epsilon": config.layer_norm_epsilon,
        "initializer_range": config.initializer_range,
        "use_normal_initialization": True,
        "pre_layernorm": True,
        "post_layernorm": False,
        "final_layernorm": True,
        "causal_mask_size": config.n_positions,
        "num_positions": config.n_positions,
        "scale_attention_scores": config.scale_attn_weights,
        "scale_attn_by_layer_idx": config.scale_attn_by_inverse_layer_idx,
        "query_key_layer_scaling": config.reorder_and_upcast_attn,
        "attention_in_fp32": config.reorder_and_upcast_attn,
    }


def translate_hf_state_dict(sd, config=None):
    """HF GPT-2 torch state dict -> flat '/'-keyed smp param dict."""
    sd = {k: c.to_np(v) for k, v in sd.items()}
    prefix = "transformer." if "transformer.wte.weight" in sd else ""
    n_layers = c.num_layers_in(sd, f"{prefix}h.", 1 + (1 if prefix else 0))
    D = sd[f"{prefix}wte.weight"].shape[1]
    qkv0 = sd[f"{prefix}h.0.attn.c_attn.weight"]
    H = config.n_head if config is not None else None
    if H is None:
        raise SMPValidationError("config required to infer head count.")
    hd = D // H

    out = {
        c.WTE: sd[f"{prefix}wte.weight"],
        c.WPE: sd[f"{prefix}wpe.weight"],
        f"{c.LN_F}/scale": sd[f"{prefix}ln_f.weight"],
        f"{c.LN_F}/bias": sd[f"{prefix}ln_f.bias"],
    }
    layers = []
    for i in range(n_layers):
        p = f"{prefix}h.{i}"
        lay = {}
        lay[f"attention/layernorm/scale"] = sd[f"{p}.ln_1.weight"]
        lay[f"attention/layernorm/bias"] = sd[f"{p}.ln_1.bias"]
        # Conv1D [in, out]: 3D out is (3, H, hd)-contiguous.
        lay["attention/qkv/kernel"] = sd[f"{p}.attn.c_attn.weight"].reshape(
            D, 3, H, hd
        )
        lay["attention/qkv/bias"] = sd[f"{p}.attn.c_attn.bias"].reshape(3, H, hd)
        lay["attention/dense/kernel"] = sd[f"{p}.attn.c_proj.weight"].reshape(
            H, hd, D
        )
        lay["attention/dense/bias"] = sd[f"{p}.attn.c_proj.bias"]
        lay["output/layernorm/scale"] = sd[f"{p}.ln_2.weight"]
        lay["output/layernorm/bias"] = sd[f"{p}.ln_2.bias"]
        lay["output/fc/kernel"] = sd[f"{p}.mlp.c_fc.weight"]
        lay["output/fc/bias"] = sd[f"{p}.mlp.c_fc.bias"]
        lay["output/proj/kernel"] = sd[f"{p}.mlp.c_proj.weight"]
        lay["output/proj/bias"] = sd[f"{p}.mlp.c_proj.bias"]
        layers.append(lay)
    stacked = c.stack_layers(layers)
    for k, v in stacked.items():
        out[f"{c.L}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF GPT-2 naming (torch tensor layout)."""
    n_layers = flat[f"{c.L}/attention/qkv/kernel"].shape[0]
    D = flat[c.WTE].shape[1]
    out = {
        "transformer.wte.weight": flat[c.WTE],
        "transformer.wpe.weight": flat[c.WPE],
        "transformer.ln_f.weight": flat[f"{c.LN_F}/scale"],
        "transformer.ln_f.bias": flat[f"{c.LN_F}/bias"],
        "lm_head.weight": flat[c.WTE],
    }
    for i in range(n_layers):
        p = f"transformer.h.{i}"
        g = lambda key: np.asarray(flat[f"{c.L}/{key}"][i])
        out[f"{p}.ln_1.weight"] = g("attention/layernorm/scale")
        out[f"{p}.ln_1.bias"] = g("attention/layernorm/bias")
        out[f"{p}.attn.c_attn.weight"] = g("attention/qkv/kernel").reshape(D, -1)
        out[f"{p}.attn.c_attn.bias"] = g("attention/qkv/bias").reshape(-1)
        out[f"{p}.attn.c_proj.weight"] = g("attention/dense/kernel").reshape(-1, D)
        out[f"{p}.attn.c_proj.bias"] = g("attention/dense/bias")
        out[f"{p}.ln_2.weight"] = g("output/layernorm/scale")
        out[f"{p}.ln_2.bias"] = g("output/layernorm/bias")
        out[f"{p}.mlp.c_fc.weight"] = g("output/fc/kernel")
        out[f"{p}.mlp.c_fc.bias"] = g("output/fc/bias")
        out[f"{p}.mlp.c_proj.weight"] = g("output/proj/kernel")
        out[f"{p}.mlp.c_proj.bias"] = g("output/proj/bias")
    return out
