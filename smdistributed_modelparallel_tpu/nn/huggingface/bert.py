"""HF BERT translation.

Parity target: reference ``torch/nn/huggingface/bert.py`` (the reference
distributes ``BertEncoder`` only, keeping HF embeddings; here the whole
``BertModel`` body — embeddings with token types + post-embedding
layernorm, post-LN encoder stack — maps onto
``DistributedTransformerLMHead``; the pooler has no counterpart and is
dropped, as in the reference).

State-dict convention: the from-HF translator accepts bare ``BertModel``
keys or ``bert.``-prefixed ones; the to-HF translator EMITS bare body keys
(the registered architecture's layout — wrapper models prepend their own
prefix).
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("BertModel", "BertForMaskedLM", "BertForPreTraining")


def config_to_smp(config):
    """HF BertConfig -> DistributedTransformerLMHead kwargs."""
    if config.hidden_size % config.num_attention_heads != 0:
        raise SMPValidationError(
            f"hidden_size ({config.hidden_size}) must be divisible by "
            f"num_attention_heads ({config.num_attention_heads})."
        )
    if config.hidden_act not in ("gelu", "gelu_new", "relu"):
        raise SMPValidationError(
            "Only gelu/gelu_new/relu activations are supported for BERT."
        )
    return {
        "num_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "attention_head_size": config.hidden_size // config.num_attention_heads,
        "hidden_size": config.hidden_size,
        "vocab_size": config.vocab_size,
        "intermediate_size": config.intermediate_size,
        "activation": c.act_from_hf(config.hidden_act),
        "attention_dropout_prob": config.attention_probs_dropout_prob,
        "hidden_dropout_prob": config.hidden_dropout_prob,
        "embedding_dropout_prob": config.hidden_dropout_prob,
        "layernorm_epsilon": config.layer_norm_eps,
        "initializer_range": config.initializer_range,
        "use_normal_initialization": True,
        # BERT is post-LN and bidirectional.
        "pre_layernorm": False,
        "post_layernorm": True,
        "final_layernorm": False,
        "causal_mask_size": None,
        "num_positions": config.max_position_embeddings,
        "num_token_types": config.type_vocab_size,
        "use_embedding_layernorm": True,
        "add_lm_head": False,
        "query_key_layer_scaling": False,
        "attention_in_fp32": False,
    }


def translate_hf_state_dict(sd, config=None):
    """HF BERT torch state dict -> flat '/'-keyed smp param dict."""
    sd = {k: c.to_np(v) for k, v in sd.items()}
    prefix = "bert." if "bert.embeddings.word_embeddings.weight" in sd else ""
    n_layers = c.num_layers_in(
        sd, f"{prefix}encoder.layer.", 2 + (1 if prefix else 0)
    )
    if config is None:
        raise SMPValidationError("config required to infer head count.")
    H = config.num_attention_heads
    D = sd[f"{prefix}embeddings.word_embeddings.weight"].shape[1]
    hd = D // H

    e = f"{prefix}embeddings"
    out = {
        c.WTE: sd[f"{e}.word_embeddings.weight"],
        c.WPE: sd[f"{e}.position_embeddings.weight"],
        c.TTE: sd[f"{e}.token_type_embeddings.weight"],
        f"{c.EMB_LN}/scale": sd[f"{e}.LayerNorm.weight"],
        f"{c.EMB_LN}/bias": sd[f"{e}.LayerNorm.bias"],
    }
    layers = []
    for i in range(n_layers):
        p = f"{prefix}encoder.layer.{i}"
        a = f"{p}.attention"
        lay = {
            "attention/qkv/kernel": c.fused_qkv_from_separate(
                sd[f"{a}.self.query.weight"],
                sd[f"{a}.self.key.weight"],
                sd[f"{a}.self.value.weight"],
                H, hd, transpose=True,
            ),
            "attention/qkv/bias": np.stack([
                sd[f"{a}.self.query.bias"].reshape(H, hd),
                sd[f"{a}.self.key.bias"].reshape(H, hd),
                sd[f"{a}.self.value.bias"].reshape(H, hd),
            ], axis=0),
            "attention/dense/kernel": c.attn_out_from_hf(
                sd[f"{a}.output.dense.weight"], H, hd, transpose=True
            ),
            "attention/dense/bias": sd[f"{a}.output.dense.bias"],
            "attention/post_layernorm/scale": sd[f"{a}.output.LayerNorm.weight"],
            "attention/post_layernorm/bias": sd[f"{a}.output.LayerNorm.bias"],
            "output/fc/kernel": sd[f"{p}.intermediate.dense.weight"].T,
            "output/fc/bias": sd[f"{p}.intermediate.dense.bias"],
            "output/proj/kernel": sd[f"{p}.output.dense.weight"].T,
            "output/proj/bias": sd[f"{p}.output.dense.bias"],
            "output/post_layernorm/scale": sd[f"{p}.output.LayerNorm.weight"],
            "output/post_layernorm/bias": sd[f"{p}.output.LayerNorm.bias"],
        }
        layers.append(lay)
    for k, v in c.stack_layers(layers).items():
        out[f"{c.L}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF BERT naming (torch tensor layout)."""
    n_layers = flat[f"{c.L}/attention/qkv/kernel"].shape[0]
    D = flat[c.WTE].shape[1]
    out = {
        "embeddings.word_embeddings.weight": flat[c.WTE],
        "embeddings.position_embeddings.weight": flat[c.WPE],
        "embeddings.token_type_embeddings.weight": flat[c.TTE],
        "embeddings.LayerNorm.weight": flat[f"{c.EMB_LN}/scale"],
        "embeddings.LayerNorm.bias": flat[f"{c.EMB_LN}/bias"],
    }
    for i in range(n_layers):
        p = f"encoder.layer.{i}"
        a = f"{p}.attention"
        g = lambda key: np.asarray(flat[f"{c.L}/{key}"][i])
        qw, kw, vw = c.separate_qkv_from_fused(
            g("attention/qkv/kernel"), transpose=True
        )
        qb, kb, vb = (g("attention/qkv/bias")[j].reshape(-1) for j in range(3))
        out[f"{a}.self.query.weight"] = qw
        out[f"{a}.self.query.bias"] = qb
        out[f"{a}.self.key.weight"] = kw
        out[f"{a}.self.key.bias"] = kb
        out[f"{a}.self.value.weight"] = vw
        out[f"{a}.self.value.bias"] = vb
        out[f"{a}.output.dense.weight"] = g("attention/dense/kernel").reshape(-1, D).T
        out[f"{a}.output.dense.bias"] = g("attention/dense/bias")
        out[f"{a}.output.LayerNorm.weight"] = g("attention/post_layernorm/scale")
        out[f"{a}.output.LayerNorm.bias"] = g("attention/post_layernorm/bias")
        out[f"{p}.intermediate.dense.weight"] = g("output/fc/kernel").T
        out[f"{p}.intermediate.dense.bias"] = g("output/fc/bias")
        out[f"{p}.output.dense.weight"] = g("output/proj/kernel").T
        out[f"{p}.output.dense.bias"] = g("output/proj/bias")
        out[f"{p}.output.LayerNorm.weight"] = g("output/post_layernorm/scale")
        out[f"{p}.output.LayerNorm.bias"] = g("output/post_layernorm/bias")
    return out
