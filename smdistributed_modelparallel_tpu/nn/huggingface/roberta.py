"""HF RoBERTa translation.

Parity target: reference ``torch/nn/huggingface/roberta.py`` (the reference
distributes ``RobertaEncoder`` only; here, as with BERT, the whole
``RobertaModel`` body maps onto ``DistributedTransformerLMHead``).

RoBERTa is architecturally BERT with one embedding quirk: position ids are
pad-aware (HF ``create_position_ids_from_input_ids`` — real tokens count
from ``padding_idx + 1`` skipping pads), and the position table carries
``max_position_embeddings`` (= 514 for the 512-token model) rows — carried
here by ``position_ids_from_padding``. Token-type table has a single row.

State-dict convention: translators accept either bare ``RobertaModel``
keys or ``roberta.``-prefixed ones, and EMIT bare body keys (the
registered architecture's layout).
"""

from smdistributed_modelparallel_tpu.nn.huggingface import bert
from smdistributed_modelparallel_tpu.nn.huggingface import common as c  # noqa: F401

HF_ARCHITECTURES = ("RobertaModel", "RobertaForMaskedLM", "RobertaForCausalLM")


def config_to_smp(config):
    """HF RobertaConfig -> DistributedTransformerLMHead kwargs."""
    out = bert.config_to_smp(config)
    # Pad-aware positions (HF create_position_ids_from_input_ids): real
    # tokens skip pads, pad tokens sit at the pad position.
    out["position_ids_from_padding"] = config.pad_token_id
    return out


def _reprefix(fn):
    def wrapped(sd, config=None):
        # BERT translator keys on the "bert." body prefix; RoBERTa's body
        # prefix is "roberta." (bare RobertaModel state dicts have none).
        sd = {
            (("bert." + k[len("roberta."):]) if k.startswith("roberta.") else k): v
            for k, v in sd.items()
        }
        return fn(sd, config=config)

    return wrapped


translate_hf_state_dict = _reprefix(bert.translate_hf_state_dict)


# bert's to-HF emitter already produces bare body keys, which is also the
# RobertaModel layout.
translate_state_dict_to_hf = bert.translate_state_dict_to_hf
