"""HF GPT-J translation.

Parity target: reference ``torch/nn/huggingface/gptj.py`` —
``hf_gptj_transformer_init_hook`` (config mapping) and the bidirectional
state-dict translators (``translate_hf_state_dict_to_smdistributed_gptj`` /
``translate_state_dict_to_hf_gptj``).

GPT-J structure: no positional embedding (rotary on the first
``rotary_dim`` channels), a SINGLE pre-layernorm feeding attention and MLP
in parallel (``parallel_attn_output`` + ``single_pre_layernorm``), no
qkv/attn-dense biases, untied LM head WITH bias.
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("GPTJForCausalLM", "GPTJModel")


def config_to_smp(config):
    """HF GPTJConfig -> DistributedTransformerLMHead kwargs.

    Mirrors reference ``hf_gptj_transformer_init_hook``
    (``torch/nn/huggingface/gptj.py:34-84``).
    """
    if config.n_embd % config.n_head != 0:
        raise SMPValidationError(
            f"n_embd ({config.n_embd}) must be divisible by n_head ({config.n_head})."
        )
    if config.activation_function not in ("gelu_new", "gelu", "relu"):
        raise SMPValidationError(
            "Only gelu_new/gelu/relu activations are supported for GPT-J."
        )
    return {
        "num_layers": config.n_layer,
        "num_attention_heads": config.n_head,
        "attention_head_size": config.n_embd // config.n_head,
        "hidden_size": config.n_embd,
        "vocab_size": config.vocab_size,
        "rotary_dim": config.rotary_dim,
        "mask_value": -1e9,
        "use_positional_embedding": False,
        "parallel_attn_output": True,
        "use_lm_head_bias": True,
        "tie_input_output_embedding": bool(config.tie_word_embeddings),
        "use_attn_dense_bias": False,
        "use_qkv_bias": False,
        "final_layernorm": True,
        "single_pre_layernorm": True,
        "activation": c.act_from_hf(config.activation_function),
        "add_lm_head": True,
        "intermediate_size": (
            config.n_inner if config.n_inner is not None else 4 * config.n_embd
        ),
        "attention_dropout_prob": config.attn_pdrop,
        "hidden_dropout_prob": config.resid_pdrop,
        "embedding_dropout_prob": config.embd_pdrop,
        "layernorm_epsilon": config.layer_norm_epsilon,
        "initializer_range": config.initializer_range,
        "use_normal_initialization": True,
        "pre_layernorm": False,
        "post_layernorm": False,
        "causal_mask_size": config.n_positions,
        "num_positions": config.n_positions,
        "scale_attention_scores": bool(getattr(config, "scale_attn_weights", True)),
        "_scale_qkv_fan_out": True,
        "query_key_layer_scaling": False,
        "attention_in_fp32": False,
    }


def translate_hf_state_dict(sd, config=None):
    """HF GPT-J torch state dict -> flat '/'-keyed smp param dict."""
    sd = {k: c.to_np(v) for k, v in sd.items()}
    prefix = "transformer." if "transformer.wte.weight" in sd else ""
    n_layers = c.num_layers_in(sd, f"{prefix}h.", 1 + (1 if prefix else 0))
    D = sd[f"{prefix}wte.weight"].shape[1]
    if config is None:
        raise SMPValidationError("config required to infer head count.")
    H = config.n_head
    hd = D // H

    out = {
        c.WTE: sd[f"{prefix}wte.weight"],
        f"{c.LN_F}/scale": sd[f"{prefix}ln_f.weight"],
        f"{c.LN_F}/bias": sd[f"{prefix}ln_f.bias"],
    }
    if "lm_head.weight" in sd:
        out[c.LM_HEAD] = sd["lm_head.weight"].T  # torch Linear [out, in]
        if "lm_head.bias" in sd:
            out["lm_head/bias"] = sd["lm_head.bias"]
    layers = []
    for i in range(n_layers):
        p = f"{prefix}h.{i}"
        lay = {
            "attention/layernorm/scale": sd[f"{p}.ln_1.weight"],
            "attention/layernorm/bias": sd[f"{p}.ln_1.bias"],
            # torch Linear [out, in]; no biases in GPT-J attention.
            "attention/qkv/kernel": c.fused_qkv_from_separate(
                sd[f"{p}.attn.q_proj.weight"],
                sd[f"{p}.attn.k_proj.weight"],
                sd[f"{p}.attn.v_proj.weight"],
                H, hd, transpose=True,
            ),
            "attention/dense/kernel": c.attn_out_from_hf(
                sd[f"{p}.attn.out_proj.weight"], H, hd, transpose=True
            ),
            "output/fc/kernel": sd[f"{p}.mlp.fc_in.weight"].T,
            "output/fc/bias": sd[f"{p}.mlp.fc_in.bias"],
            "output/proj/kernel": sd[f"{p}.mlp.fc_out.weight"].T,
            "output/proj/bias": sd[f"{p}.mlp.fc_out.bias"],
        }
        layers.append(lay)
    for k, v in c.stack_layers(layers).items():
        out[f"{c.L}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF GPT-J naming (torch tensor layout)."""
    n_layers = flat[f"{c.L}/attention/qkv/kernel"].shape[0]
    D = flat[c.WTE].shape[1]
    out = {
        "transformer.wte.weight": flat[c.WTE],
        "transformer.ln_f.weight": flat[f"{c.LN_F}/scale"],
        "transformer.ln_f.bias": flat[f"{c.LN_F}/bias"],
    }
    if c.LM_HEAD in flat:
        out["lm_head.weight"] = np.asarray(flat[c.LM_HEAD]).T
        if "lm_head/bias" in flat:
            out["lm_head.bias"] = flat["lm_head/bias"]
    else:  # tied
        out["lm_head.weight"] = flat[c.WTE]
    for i in range(n_layers):
        p = f"transformer.h.{i}"
        g = lambda key: np.asarray(flat[f"{c.L}/{key}"][i])
        out[f"{p}.ln_1.weight"] = g("attention/layernorm/scale")
        out[f"{p}.ln_1.bias"] = g("attention/layernorm/bias")
        qw, kw, vw = c.separate_qkv_from_fused(
            g("attention/qkv/kernel"), transpose=True
        )
        out[f"{p}.attn.q_proj.weight"] = qw
        out[f"{p}.attn.k_proj.weight"] = kw
        out[f"{p}.attn.v_proj.weight"] = vw
        out[f"{p}.attn.out_proj.weight"] = g("attention/dense/kernel").reshape(-1, D).T
        out[f"{p}.mlp.fc_in.weight"] = g("output/fc/kernel").T
        out[f"{p}.mlp.fc_in.bias"] = g("output/fc/bias")
        out[f"{p}.mlp.fc_out.weight"] = g("output/proj/kernel").T
        out[f"{p}.mlp.fc_out.bias"] = g("output/proj/bias")
    return out
