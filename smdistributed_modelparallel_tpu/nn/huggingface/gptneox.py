"""HF GPT-NeoX translation.

Parity target: reference ``torch/nn/huggingface/gptneox.py`` —
``hf_gptneox_transformer_init_hook`` and the bidirectional state-dict
translators.

GPT-NeoX structure: NeoX-style rotary on the first ``rotary_pct`` of each
head, parallel attention+MLP residual fed by TWO layernorms
(input_layernorm / post_attention_layernorm), fused qkv whose output dim is
[H, 3, hd]-interleaved (unlike GPT-2's [3, H, hd]), untied ``embed_out``
LM head without bias.
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("GPTNeoXForCausalLM", "GPTNeoXModel")


def config_to_smp(config):
    """HF GPTNeoXConfig -> DistributedTransformerLMHead kwargs.

    Mirrors reference ``hf_gptneox_transformer_init_hook``
    (``torch/nn/huggingface/gptneox.py:35-92``).
    """
    if config.hidden_size % config.num_attention_heads != 0:
        raise SMPValidationError(
            f"hidden_size ({config.hidden_size}) must be divisible by "
            f"num_attention_heads ({config.num_attention_heads})."
        )
    if config.hidden_act not in ("gelu", "gelu_new", "relu"):
        raise SMPValidationError(
            "Only gelu/gelu_new/relu activations are supported for GPT-NeoX."
        )
    hd = config.hidden_size // config.num_attention_heads
    rotary_pct = getattr(config, "rotary_pct", 1.0)
    rotary_base = getattr(
        config, "rotary_emb_base", getattr(config, "rope_theta", 10000.0)
    )
    return {
        "num_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "attention_head_size": hd,
        "hidden_size": config.hidden_size,
        "vocab_size": config.vocab_size,
        "rotary_dim": int(hd * rotary_pct),
        "rotary_emb_base": float(rotary_base),
        "gpt_neox_type_rotary": True,
        "mask_value": -1e9,
        "use_positional_embedding": False,
        "parallel_attn_output": bool(getattr(config, "use_parallel_residual", True)),
        "use_lm_head_bias": False,
        "tie_input_output_embedding": bool(config.tie_word_embeddings),
        "use_attn_dense_bias": True,
        "use_qkv_bias": True,
        "final_layernorm": True,
        "single_pre_layernorm": False,
        "activation": c.act_from_hf(config.hidden_act),
        "add_lm_head": True,
        "intermediate_size": config.intermediate_size,
        "attention_dropout_prob": 0.0,
        "hidden_dropout_prob": 0.0,
        "embedding_dropout_prob": 0.0,
        "layernorm_epsilon": config.layer_norm_eps,
        "initializer_range": config.initializer_range,
        "use_normal_initialization": True,
        "pre_layernorm": True,
        "post_layernorm": False,
        "causal_mask_size": config.max_position_embeddings,
        "num_positions": config.max_position_embeddings,
        "scale_attention_scores": True,
        "_scale_qkv_fan_out": True,
        "query_key_layer_scaling": False,
        "attention_in_fp32": False,
    }


def _qkv_from_neox(w, b, H, hd):
    """HF [3D, D] weight (out dim [H, 3, hd]-interleaved) + [3D] bias ->
    our [D, 3, H, hd] kernel and [3, H, hd] bias."""
    D = w.shape[1]
    kernel = w.reshape(H, 3, hd, D).transpose(3, 1, 0, 2)
    bias = b.reshape(H, 3, hd).transpose(1, 0, 2)
    return kernel, bias


def _qkv_to_neox(kernel, bias):
    """Our [D, 3, H, hd] / [3, H, hd] -> HF [3D, D] / [3D]."""
    D = kernel.shape[0]
    w = kernel.transpose(2, 1, 3, 0).reshape(-1, D)
    b = bias.transpose(1, 0, 2).reshape(-1)
    return w, b


def translate_hf_state_dict(sd, config=None):
    """HF GPT-NeoX torch state dict -> flat '/'-keyed smp param dict."""
    sd = {k: c.to_np(v) for k, v in sd.items()}
    prefix = "gpt_neox." if "gpt_neox.embed_in.weight" in sd else ""
    n_layers = c.num_layers_in(sd, f"{prefix}layers.", 1 + (1 if prefix else 0))
    if config is None:
        raise SMPValidationError("config required to infer head count.")
    H = config.num_attention_heads
    D = sd[f"{prefix}embed_in.weight"].shape[1]
    hd = D // H

    out = {
        c.WTE: sd[f"{prefix}embed_in.weight"],
        f"{c.LN_F}/scale": sd[f"{prefix}final_layer_norm.weight"],
        f"{c.LN_F}/bias": sd[f"{prefix}final_layer_norm.bias"],
    }
    if "embed_out.weight" in sd:
        out[c.LM_HEAD] = sd["embed_out.weight"].T
    layers = []
    for i in range(n_layers):
        p = f"{prefix}layers.{i}"
        qkv_w, qkv_b = _qkv_from_neox(
            sd[f"{p}.attention.query_key_value.weight"],
            sd[f"{p}.attention.query_key_value.bias"],
            H, hd,
        )
        lay = {
            "attention/layernorm/scale": sd[f"{p}.input_layernorm.weight"],
            "attention/layernorm/bias": sd[f"{p}.input_layernorm.bias"],
            "output/layernorm/scale": sd[f"{p}.post_attention_layernorm.weight"],
            "output/layernorm/bias": sd[f"{p}.post_attention_layernorm.bias"],
            "attention/qkv/kernel": qkv_w,
            "attention/qkv/bias": qkv_b,
            "attention/dense/kernel": c.attn_out_from_hf(
                sd[f"{p}.attention.dense.weight"], H, hd, transpose=True
            ),
            "attention/dense/bias": sd[f"{p}.attention.dense.bias"],
            "output/fc/kernel": sd[f"{p}.mlp.dense_h_to_4h.weight"].T,
            "output/fc/bias": sd[f"{p}.mlp.dense_h_to_4h.bias"],
            "output/proj/kernel": sd[f"{p}.mlp.dense_4h_to_h.weight"].T,
            "output/proj/bias": sd[f"{p}.mlp.dense_4h_to_h.bias"],
        }
        layers.append(lay)
    for k, v in c.stack_layers(layers).items():
        out[f"{c.L}/{k}"] = v
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF GPT-NeoX naming (torch tensor layout)."""
    n_layers = flat[f"{c.L}/attention/qkv/kernel"].shape[0]
    D = flat[c.WTE].shape[1]
    out = {
        "gpt_neox.embed_in.weight": flat[c.WTE],
        "gpt_neox.final_layer_norm.weight": flat[f"{c.LN_F}/scale"],
        "gpt_neox.final_layer_norm.bias": flat[f"{c.LN_F}/bias"],
    }
    if c.LM_HEAD in flat:
        out["embed_out.weight"] = np.asarray(flat[c.LM_HEAD]).T
    else:
        out["embed_out.weight"] = flat[c.WTE]
    for i in range(n_layers):
        p = f"gpt_neox.layers.{i}"
        g = lambda key: np.asarray(flat[f"{c.L}/{key}"][i])
        out[f"{p}.input_layernorm.weight"] = g("attention/layernorm/scale")
        out[f"{p}.input_layernorm.bias"] = g("attention/layernorm/bias")
        out[f"{p}.post_attention_layernorm.weight"] = g("output/layernorm/scale")
        out[f"{p}.post_attention_layernorm.bias"] = g("output/layernorm/bias")
        w, b = _qkv_to_neox(g("attention/qkv/kernel"), g("attention/qkv/bias"))
        out[f"{p}.attention.query_key_value.weight"] = w
        out[f"{p}.attention.query_key_value.bias"] = b
        out[f"{p}.attention.dense.weight"] = g("attention/dense/kernel").reshape(-1, D).T
        out[f"{p}.attention.dense.bias"] = g("attention/dense/bias")
        out[f"{p}.mlp.dense_h_to_4h.weight"] = g("output/fc/kernel").T
        out[f"{p}.mlp.dense_h_to_4h.bias"] = g("output/fc/bias")
        out[f"{p}.mlp.dense_4h_to_h.weight"] = g("output/proj/kernel").T
        out[f"{p}.mlp.dense_4h_to_h.bias"] = g("output/proj/bias")
    return out
