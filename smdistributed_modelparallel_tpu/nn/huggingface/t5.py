"""HF T5 translation hooks.

Parity target: reference ``torch/nn/huggingface/t5.py`` — which supports T5
at the LAYER level only (``T5Block`` -> ``DistributedTransformerLayer``),
declines the relative-attention-bias layer (the first block of each stack
stays undistributed), and ships NO state-dict translators. The same scope
applies here: ``config_to_smp_layer`` produces
``DistributedTransformerLayer`` kwargs for non-bias blocks; blocks with
``has_relative_attention_bias`` return None (kept undistributed), mirroring
``hf_t5_transformer_layer_init_hook`` (reference ``t5.py:11-31``).

Note: HF T5 uses RMSNorm (no bias/mean); the reference maps it onto its
standard-LayerNorm DistributedTransformerLayer with the same approximation
made here. Full-model T5 (enc-dec with relative bias) is intentionally out
of scope, as in the reference.
"""

from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("T5Block",)


def config_to_smp_layer(config, has_relative_attention_bias=False):
    """HF T5Config (+ block flag) -> DistributedTransformerLayer kwargs, or
    None for the relative-bias block (left undistributed)."""
    if has_relative_attention_bias:
        return None
    if config.d_kv * config.num_heads != config.d_model:
        raise SMPValidationError(
            f"d_kv ({config.d_kv}) * num_heads ({config.num_heads}) must "
            f"equal d_model ({config.d_model}) for T5."
        )
    return {
        "num_attention_heads": config.num_heads,
        "attention_head_size": config.d_kv,
        "hidden_size": config.d_model,
        "intermediate_size": config.d_ff,
        "attention_dropout_prob": config.dropout_rate,
        "hidden_dropout_prob": config.dropout_rate,
        "add_cross_attention": bool(config.is_decoder),
        "causal_mask_size": config.n_positions if config.is_decoder and hasattr(config, "n_positions") else None,
        "pre_layernorm": True,
        "post_layernorm": False,
        "use_qkv_bias": False,
        "use_attn_dense_bias": False,
        "scale_attention_scores": False,  # T5 does not scale by 1/sqrt(hd)
    }
