"""HF T5 translation.

Goes BEYOND the reference's T5 support: the reference handles T5 at the
LAYER level only (``torch/nn/huggingface/t5.py`` maps ``T5Block`` ->
``DistributedTransformerLayer``, declines the relative-attention-bias
block, and ships NO state-dict translators). Here the layer-level hook is
kept for parity (``config_to_smp_layer``), and a FULL-MODEL family is
added: ``T5ForConditionalGeneration``/``T5Model`` build the
``models.encoder_decoder.EncoderDecoderLM`` t5_compat dialect (RMSNorm,
bucketed relative-position bias, bias-free dense, unscaled attention,
tied-head rescale) with bidirectional state-dict translation — so
``smp.from_hf(t5_model)`` fine-tunes from HF weights and exports back
(BASELINE config #5's T5-3B path).

Scope: both T5 dialects — classic v1.0 (non-gated relu FFN, tied
embeddings: t5-small/base/large/3B/11B) and v1.1/flan-T5 (gated-gelu
wi_0/wi_1 FFN, untied lm_head).
"""

import numpy as np

from smdistributed_modelparallel_tpu.nn.huggingface import common as c
from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError

HF_ARCHITECTURES = ("T5ForConditionalGeneration", "T5Model")
TARGET = "encdec"

ENC = "encoder/seq_layers/layer"
DEC = "decoder/seq_layers/layer"


def config_to_smp(config):
    """HF T5Config -> EncoderDecoderLM (t5_compat) kwargs. Handles both
    the classic v1.0 dialect and gated/untied v1.1 (flan-T5)."""
    act = getattr(config, "dense_act_fn", "relu")
    return {
        "gated_mlp": bool(getattr(config, "is_gated_act", False)),
        "tie_embeddings": bool(getattr(config, "tie_word_embeddings", True)),
        "vocab_size": config.vocab_size,
        "d_model": config.d_model,
        "enc_layers": config.num_layers,
        "dec_layers": config.num_decoder_layers,
        "n_heads": config.num_heads,
        "d_ff": config.d_ff,
        "d_kv": config.d_kv,
        "max_len": getattr(config, "n_positions", 512),
        "dropout": config.dropout_rate,
        "activation": c.act_from_hf(act),
        "layernorm_epsilon": config.layer_norm_epsilon,
        "relative_attention_num_buckets":
            config.relative_attention_num_buckets,
        "relative_attention_max_distance":
            getattr(config, "relative_attention_max_distance", 128),
        "initializer_range": config.initializer_factor * 1.0,
        "t5_compat": True,
    }


def _qkv_from_hf(qw, kw, vw, H, hd):
    """torch [inner, D] q/k/v -> fused [D, 3, H, hd] kernel."""
    D = qw.shape[1]
    mats = [w.T.reshape(D, H, hd) for w in (qw, kw, vw)]
    return np.stack(mats, axis=1)


def _self_attn(lay, sd, p, H, hd):
    lay["attention/layernorm/scale"] = sd[f"{p}.layer.0.layer_norm.weight"]
    lay["attention/qkv/kernel"] = _qkv_from_hf(
        sd[f"{p}.layer.0.SelfAttention.q.weight"],
        sd[f"{p}.layer.0.SelfAttention.k.weight"],
        sd[f"{p}.layer.0.SelfAttention.v.weight"],
        H, hd,
    )
    ow = sd[f"{p}.layer.0.SelfAttention.o.weight"]  # [D, inner]
    lay["attention/dense/kernel"] = ow.T.reshape(H, hd, ow.shape[0])


def _mlp(lay, sd, p, li, gated):
    lay["output/layernorm/scale"] = sd[f"{p}.layer.{li}.layer_norm.weight"]
    if gated:
        # v1.1: wi_0 is the ACTIVATED branch (our "gate"), wi_1 the linear
        # multiplier (our "fc"): out = act(gate(x)) * fc(x) @ proj.
        lay["output/gate/kernel"] = (
            sd[f"{p}.layer.{li}.DenseReluDense.wi_0.weight"].T
        )
        lay["output/fc/kernel"] = (
            sd[f"{p}.layer.{li}.DenseReluDense.wi_1.weight"].T
        )
    else:
        lay["output/fc/kernel"] = (
            sd[f"{p}.layer.{li}.DenseReluDense.wi.weight"].T
        )
    lay["output/proj/kernel"] = sd[f"{p}.layer.{li}.DenseReluDense.wo.weight"].T


def translate_hf_state_dict(sd, config=None):
    """HF T5 torch state dict -> flat '/'-keyed smp param dict."""
    if config is None:
        raise SMPValidationError("config required for T5 translation.")
    if "decoder.block.0.layer.0.SelfAttention.q.weight" not in sd:
        # family_for's model_type fallback can route any t5-typed model
        # here (e.g. T5EncoderModel) — fail with a clear error instead of
        # a KeyError mid-translation.
        raise SMPValidationError(
            "State dict is not a full T5 encoder-decoder (no decoder "
            f"blocks); supported architectures: {HF_ARCHITECTURES}."
        )
    sd = {k: c.to_np(v) for k, v in sd.items()}
    H, hd = config.num_heads, config.d_kv
    gated = bool(getattr(config, "is_gated_act", False))
    tied = bool(getattr(config, "tie_word_embeddings", True))

    out = {
        "shared_embedding/embedding": sd["shared.weight"],
        "enc_rel_bias/embedding": sd[
            "encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ],
        "dec_rel_bias/embedding": sd[
            "decoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight"
        ],
        "encoder_ln/scale": sd["encoder.final_layer_norm.weight"],
        "decoder_ln/scale": sd["decoder.final_layer_norm.weight"],
    }

    enc_layers = []
    for i in range(config.num_layers):
        p = f"encoder.block.{i}"
        lay = {}
        _self_attn(lay, sd, p, H, hd)
        _mlp(lay, sd, p, 1, gated)
        enc_layers.append(lay)
    for k, v in c.stack_layers(enc_layers).items():
        out[f"{ENC}/{k}"] = v

    dec_layers = []
    for i in range(config.num_decoder_layers):
        p = f"decoder.block.{i}"
        lay = {}
        _self_attn(lay, sd, p, H, hd)
        # Cross attention (layer.1): separate q + fused kv kernels.
        D = config.d_model
        lay["crossattention/layernorm/scale"] = sd[
            f"{p}.layer.1.layer_norm.weight"
        ]
        lay["crossattention/query/kernel"] = (
            sd[f"{p}.layer.1.EncDecAttention.q.weight"].T.reshape(D, H, hd)
        )
        lay["crossattention/key_value/kernel"] = np.stack(
            [
                sd[f"{p}.layer.1.EncDecAttention.k.weight"].T.reshape(D, H, hd),
                sd[f"{p}.layer.1.EncDecAttention.v.weight"].T.reshape(D, H, hd),
            ],
            axis=1,
        )
        ow = sd[f"{p}.layer.1.EncDecAttention.o.weight"]
        lay["crossattention/dense/kernel"] = ow.T.reshape(H, hd, D)
        _mlp(lay, sd, p, 2, gated)
        dec_layers.append(lay)
    for k, v in c.stack_layers(dec_layers).items():
        out[f"{DEC}/{k}"] = v
    if not tied:
        out["lm_head/kernel"] = sd["lm_head.weight"].T
    return out


def translate_state_dict_to_hf(flat, config=None):
    """Flat smp param dict -> HF T5 naming (torch tensor layout)."""
    enc_qkv = flat[f"{ENC}/attention/qkv/kernel"]
    Le = enc_qkv.shape[0]
    Ld = flat[f"{DEC}/attention/qkv/kernel"].shape[0]
    D = enc_qkv.shape[1]
    inner = enc_qkv.shape[3] * enc_qkv.shape[4]

    gated = f"{ENC}/output/gate/kernel" in flat
    tied = "lm_head/kernel" not in flat
    shared = np.asarray(flat["shared_embedding/embedding"])
    out = {
        "shared.weight": shared,
        "encoder.embed_tokens.weight": shared,
        "decoder.embed_tokens.weight": shared,
        "lm_head.weight": (
            shared if tied else np.asarray(flat["lm_head/kernel"]).T
        ),
        "encoder.block.0.layer.0.SelfAttention"
        ".relative_attention_bias.weight":
            np.asarray(flat["enc_rel_bias/embedding"]),
        "decoder.block.0.layer.0.SelfAttention"
        ".relative_attention_bias.weight":
            np.asarray(flat["dec_rel_bias/embedding"]),
        "encoder.final_layer_norm.weight":
            np.asarray(flat["encoder_ln/scale"]),
        "decoder.final_layer_norm.weight":
            np.asarray(flat["decoder_ln/scale"]),
    }

    def put_self(p, stack_prefix, i):
        g = lambda key: np.asarray(flat[f"{stack_prefix}/{key}"][i])
        qkv = g("attention/qkv/kernel")          # [D, 3, H, hd]
        for j, name in enumerate(("q", "k", "v")):
            out[f"{p}.layer.0.SelfAttention.{name}.weight"] = (
                qkv[:, j].reshape(D, inner).T
            )
        out[f"{p}.layer.0.SelfAttention.o.weight"] = (
            g("attention/dense/kernel").reshape(inner, D).T
        )
        out[f"{p}.layer.0.layer_norm.weight"] = g("attention/layernorm/scale")

    def put_mlp(p, stack_prefix, i, li):
        g = lambda key: np.asarray(flat[f"{stack_prefix}/{key}"][i])
        if gated:
            out[f"{p}.layer.{li}.DenseReluDense.wi_0.weight"] = (
                g("output/gate/kernel").T
            )
            out[f"{p}.layer.{li}.DenseReluDense.wi_1.weight"] = (
                g("output/fc/kernel").T
            )
        else:
            out[f"{p}.layer.{li}.DenseReluDense.wi.weight"] = (
                g("output/fc/kernel").T
            )
        out[f"{p}.layer.{li}.DenseReluDense.wo.weight"] = g("output/proj/kernel").T
        out[f"{p}.layer.{li}.layer_norm.weight"] = g("output/layernorm/scale")

    for i in range(Le):
        p = f"encoder.block.{i}"
        put_self(p, ENC, i)
        put_mlp(p, ENC, i, 1)
    for i in range(Ld):
        p = f"decoder.block.{i}"
        put_self(p, DEC, i)
        g = lambda key: np.asarray(flat[f"{DEC}/{key}"][i])
        out[f"{p}.layer.1.EncDecAttention.q.weight"] = (
            g("crossattention/query/kernel").reshape(D, inner).T
        )
        kv = g("crossattention/key_value/kernel")  # [D, 2, H, hd]
        out[f"{p}.layer.1.EncDecAttention.k.weight"] = (
            kv[:, 0].reshape(D, inner).T
        )
        out[f"{p}.layer.1.EncDecAttention.v.weight"] = (
            kv[:, 1].reshape(D, inner).T
        )
        out[f"{p}.layer.1.EncDecAttention.o.weight"] = (
            g("crossattention/dense/kernel").reshape(inner, D).T
        )
        out[f"{p}.layer.1.layer_norm.weight"] = (
            g("crossattention/layernorm/scale")
        )
        put_mlp(p, DEC, i, 2)
    return out


def config_to_smp_layer(config, has_relative_attention_bias=False):
    """Layer-level hook (reference parity): HF T5Config (+ block flag) ->
    DistributedTransformerLayer kwargs, or None for the relative-bias
    block (left undistributed), mirroring
    ``hf_t5_transformer_layer_init_hook`` (reference ``t5.py:11-31``)."""
    if has_relative_attention_bias:
        return None
    if config.d_kv * config.num_heads != config.d_model:
        raise SMPValidationError(
            f"d_kv ({config.d_kv}) * num_heads ({config.num_heads}) must "
            f"equal d_model ({config.d_model}) for T5."
        )
    return {
        "num_attention_heads": config.num_heads,
        "attention_head_size": config.d_kv,
        "hidden_size": config.d_model,
        "intermediate_size": config.d_ff,
        "attention_dropout_prob": config.dropout_rate,
        "hidden_dropout_prob": config.dropout_rate,
        "add_cross_attention": bool(config.is_decoder),
        "causal_mask_size": config.n_positions if config.is_decoder and hasattr(config, "n_positions") else None,
        "pre_layernorm": True,
        "post_layernorm": False,
        "use_qkv_bias": False,
        "use_attn_dense_bias": False,
        "use_mlp_bias": False,
        "layernorm_type": "rms",          # exact T5 RMSNorm (goes beyond
        # the reference, which approximated with standard LayerNorm)
        "scale_attention_scores": False,  # T5 does not scale by 1/sqrt(hd)
    }
