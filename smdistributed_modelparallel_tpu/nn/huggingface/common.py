"""Shared helpers for HuggingFace config / state-dict translation.

Parity target: reference ``torch/nn/huggingface/*`` (per-class init hooks +
bidirectional state_dict translate functions, registered via
``torch/nn/predefined_hooks.py:56-168``).

TPU-native notes: our transformer stack is built with ``flax.linen.scan``,
so per-layer HF tensors are STACKED into a leading [num_layers] axis; the
flat key space is '/'-joined flax paths of
``smp.nn.DistributedTransformerLMHead``.
"""

import numpy as np

# Flat '/'-keyed paths of DistributedTransformerLMHead parameters.
L = "transformer/seq_layers/layer"
WTE = "word_embedding/embedding"
WPE = "position_embedding/embedding"
TTE = "token_type_embedding/embedding"
EMB_LN = "embedding_layernorm"
LN_F = "ln_f"
LM_HEAD = "lm_head/kernel"

ATTN_LN = f"{L}/attention/layernorm"
ATTN_POST_LN = f"{L}/attention/post_layernorm"
QKV_W = f"{L}/attention/qkv/kernel"
QKV_B = f"{L}/attention/qkv/bias"
ATTN_OUT_W = f"{L}/attention/dense/kernel"
ATTN_OUT_B = f"{L}/attention/dense/bias"
MLP_LN = f"{L}/output/layernorm"
MLP_POST_LN = f"{L}/output/post_layernorm"
FC_W = f"{L}/output/fc/kernel"
FC_B = f"{L}/output/fc/bias"
PROJ_W = f"{L}/output/proj/kernel"
PROJ_B = f"{L}/output/proj/bias"


def act_from_hf(name):
    """HF activation name -> ours. HF's "gelu" is the EXACT erf form
    (ACT2FN); "gelu_new"/"gelu_pytorch_tanh" are the tanh approximation
    (our "gelu")."""
    return {
        "gelu": "gelu_erf",
        "gelu_new": "gelu",
        "gelu_pytorch_tanh": "gelu",
        "relu": "relu",
    }[name]


def to_np(t):
    """torch tensor / array -> numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def stack_layers(per_layer):
    """[{key: arr} per layer] -> {key: arr stacked on a new leading axis}."""
    out = {}
    for key in per_layer[0]:
        out[key] = np.stack([d[key] for d in per_layer], axis=0)
    return out


def num_layers_in(sd, prefix, idx_pos):
    """Highest layer index + 1 for keys like '{prefix}.{i}.'."""
    last = -1
    for key in sd:
        if key.startswith(prefix):
            try:
                last = max(last, int(key.split(".")[idx_pos]))
            except (ValueError, IndexError):
                pass
    return last + 1


def fused_qkv_from_separate(qw, kw, vw, H, hd, transpose=False):
    """Separate q/k/v [D, D] (or torch [out,in] with transpose=True) ->
    our fused [D, 3, H, hd] kernel."""
    mats = []
    for w in (qw, kw, vw):
        w = to_np(w)
        if transpose:
            w = w.T  # torch Linear stores [out, in]
        D = w.shape[0]
        mats.append(w.reshape(D, H, hd))
    return np.stack(mats, axis=1)  # [D, 3, H, hd]


def separate_qkv_from_fused(kernel, transpose=False):
    """Our [D, 3, H, hd] -> three [D, D] (or [out, in] with transpose)."""
    D = kernel.shape[0]
    outs = []
    for c in range(3):
        w = kernel[:, c].reshape(D, -1)
        outs.append(w.T if transpose else w)
    return outs


def attn_out_from_hf(w, H, hd, transpose=False):
    """HF attention output proj [D_in, D_out] (Conv1D) or [out, in]
    (Linear, transpose=True) -> our [H, hd, D]."""
    w = to_np(w)
    if transpose:
        w = w.T
    D_out = w.shape[1]
    return w.reshape(H, hd, D_out)


def linear_from_hf(w, transpose=False):
    w = to_np(w)
    return w.T if transpose else w


def ln_from_hf(sd, hf_prefix, ours, out, layerwise=None):
    """Map an HF LayerNorm (weight/bias) onto ours (scale/bias)."""
    out[f"{ours}/scale"] = to_np(sd[f"{hf_prefix}.weight"])
    out[f"{ours}/bias"] = to_np(sd[f"{hf_prefix}.bias"])
