"""HuggingFace model-family translation registry.

Parity target: reference ``torch/nn/predefined_hooks.py:56-168``
(``PredefinedHookManager``): maps HF classes to distributed classes with
init-hook argument translation and bidirectional state-dict translators,
registered into the tp_registry at init.

TPU-native flow: HF models are torch modules, so "re-instantiation" means
building the equivalent ``smp.nn.DistributedTransformerLMHead`` from the HF
config (``config_to_smp``) and translating the torch state dict into the
stacked-flax layout (``translate_hf_state_dict``). ``smp.from_hf`` is the
one-call entry point; full (non-partial) checkpoints translate back to HF
naming through the registered ``translate_state_dict_to_hf``.
"""

from dataclasses import dataclass
from typing import Callable, Optional

from smdistributed_modelparallel_tpu.utils.exceptions import SMPValidationError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


@dataclass(frozen=True)
class HFFamily:
    name: str
    architectures: tuple
    config_to_smp: Callable
    translate_from_hf: Optional[Callable]  # hf sd -> flat smp dict
    translate_to_hf: Optional[Callable]    # flat smp dict -> hf sd
    # Distributed module the family maps onto: "lmhead" (full model ->
    # DistributedTransformerLMHead), "transformer" (encoder stack ->
    # DistributedTransformer; the reference's scope for ViT), or "encdec"
    # (T5 -> models.encoder_decoder.EncoderDecoderLM).
    target: str = "lmhead"


def _target_class(target):
    from smdistributed_modelparallel_tpu.nn.transformer import (
        DistributedTransformer,
        DistributedTransformerLMHead,
    )

    if target == "transformer":
        return DistributedTransformer
    if target == "encdec":
        from smdistributed_modelparallel_tpu.models.encoder_decoder import (
            EncoderDecoderLM,
        )

        return EncoderDecoderLM
    return DistributedTransformerLMHead


def _families():
    from smdistributed_modelparallel_tpu.nn.huggingface import (
        bert, gpt2, gptj, gptneo, gptneox, roberta, t5, vit,
    )

    fams = {}
    for name, mod in (
        ("gpt2", gpt2), ("gptj", gptj), ("gptneo", gptneo),
        ("gptneox", gptneox), ("bert", bert), ("roberta", roberta),
        ("vit", vit), ("t5", t5),
    ):
        fams[name] = HFFamily(
            name=name,
            architectures=mod.HF_ARCHITECTURES,
            config_to_smp=mod.config_to_smp,
            translate_from_hf=mod.translate_hf_state_dict,
            translate_to_hf=mod.translate_state_dict_to_hf,
            target=getattr(mod, "TARGET", "lmhead"),
        )
    return fams


_FAMILIES_CACHE = None


def families():
    global _FAMILIES_CACHE
    if _FAMILIES_CACHE is None:
        _FAMILIES_CACHE = _families()
    return _FAMILIES_CACHE


def family_for(config_or_model):
    """Resolve the HFFamily for a transformers model, config, or an
    architecture-name string."""
    if isinstance(config_or_model, str):
        candidates = [config_or_model]
    else:
        config = getattr(config_or_model, "config", config_or_model)
        candidates = [type(config_or_model).__name__]
        candidates += list(getattr(config, "architectures", None) or [])
        # Config-class fallback: GPT2Config -> model_type "gpt2".
        mt = getattr(config, "model_type", None)
        if mt:
            candidates.append(mt)
    for fam in families().values():
        for cand in candidates:
            norm = cand.lower().replace("-", "").replace("_", "")
            if cand in fam.architectures or norm == fam.name:
                return fam
    raise SMPValidationError(
        f"No HF translation registered for {candidates}; supported "
        f"architectures: "
        f"{[a for f in families().values() for a in f.architectures]}"
    )


_BODY_PREFIXES = (
    "bert.", "roberta.", "vit.", "transformer.", "gpt_neox.", "model.",
)


def _adapt_to_source_keys(to_hf, source_keys):
    """Wrap a family's to-HF translator so its output keys match a SPECIFIC
    source model's layout.

    Translators emit each family's canonical layout (bare body keys for
    encoder families, ``transformer.``-prefixed for the GPT LMHead
    families); wrapper architectures (``BertForMaskedLM`` -> ``bert.*``,
    bare ``GPT2Model`` -> unprefixed) differ only by a body prefix. The
    wrapper renames each emitted key by adding/stripping a known prefix
    when that makes it match the source state dict, so full-checkpoint
    exports load back into whatever class ``smp.from_hf`` was given.
    """
    source_keys = frozenset(source_keys)

    def adapted(flat, config=None):
        out = to_hf(flat, config=config)
        fixed = {}
        for k, v in out.items():
            if k in source_keys:
                fixed[k] = v
                continue
            hit = None
            for p in _BODY_PREFIXES:
                if p + k in source_keys:
                    hit = p + k
                    break
                if k.startswith(p) and k[len(p):] in source_keys:
                    hit = k[len(p):]
                    break
            fixed[hit or k] = v
        return fixed

    return adapted


def _match_weights_check(flat, to_hf, sd, config, name):
    """Distribute-time weight verification (reference ``_match_weights``
    debug mode, ``torch/tp_registry.py:47-161``): the reference copies
    source weights into the distributed module; under SPMD the
    distributed params ARE derived from the translation, so verifying the
    round-trip — translate back to HF layout and compare per key against
    the source state dict — is the equivalent check. Logs one warning per
    mismatched key (shape or value) plus a summary; returns the mismatch
    list for tests."""
    import numpy as np

    from smdistributed_modelparallel_tpu.nn.huggingface.common import to_np

    back = to_hf(flat, config=config)
    problems = []
    compared = 0
    skipped = []
    for k, src in sd.items():
        if k not in back:
            # Buffers (causal masks, inv_freq) legitimately don't
            # round-trip — but real weight keys missing here are exactly
            # the translator bug class this mode exists to catch, so
            # they are counted and reported below.
            skipped.append(k)
            continue
        compared += 1
        got = to_np(back[k])
        want = to_np(src)
        if got.shape != want.shape:
            problems.append(f"{k}: shape {got.shape} != {want.shape}")
            continue
        diff = float(np.max(np.abs(
            got.astype(np.float64) - want.astype(np.float64)
        ))) if got.size else 0.0
        if diff > 1e-5:
            problems.append(f"{k}: max |diff| {diff:.3e}")
    for p in problems:
        logger.warning("_match_weights [%s]: MISMATCH %s", name, p)
    if compared == 0:
        logger.warning(
            "_match_weights [%s]: NO source keys round-tripped (%d "
            "skipped: %s...) — the to-HF translator emits none of the "
            "source layout's keys, so nothing was verified.",
            name, len(skipped), skipped[:5],
        )
    elif problems:
        logger.warning(
            "_match_weights [%s]: %d of %d translated keys do not match "
            "the source model — the translator pair is inconsistent.",
            name, len(problems), compared,
        )
    else:
        logger.info(
            "_match_weights [%s]: all %d translated keys round-trip "
            "against the source model (%d source keys skipped as "
            "untranslated buffers).", name, compared, len(skipped),
        )
    return problems


def translate_model(model_or_config, **overrides):
    """Build the DistributedTransformerLMHead for an HF model/config.

    Returns ``(module, flat_params_or_None, family)`` — flat_params is the
    translated state dict when a model (with weights) was given, or None
    for a bare config.
    """
    from smdistributed_modelparallel_tpu.backend.state import state

    fam = family_for(model_or_config)
    config = getattr(model_or_config, "config", model_or_config)
    kwargs = fam.config_to_smp(config)
    kwargs.update(overrides)
    module = _target_class(fam.target)(**kwargs)
    flat = None
    if hasattr(model_or_config, "state_dict"):
        sd = model_or_config.state_dict()
        flat = fam.translate_from_hf(sd, config=config)
        adapted_to_hf = _adapt_to_source_keys(fam.translate_to_hf, sd.keys())
        if state.initialized and getattr(state.cfg, "_match_weights", False):
            _match_weights_check(flat, adapted_to_hf, sd, config, fam.name)
        fam = HFFamily(
            name=fam.name,
            architectures=fam.architectures,
            config_to_smp=fam.config_to_smp,
            translate_from_hf=fam.translate_from_hf,
            translate_to_hf=adapted_to_hf,
            target=fam.target,
        )
    return module, flat, fam


def register_predefined_hooks(registry):
    """Register HF classes in the tp_registry (parity: reference
    ``PredefinedHookManager``). Lazy: transformers is imported only if
    present; absence is not an error."""
    try:
        import transformers
    except Exception:  # pragma: no cover - transformers always in image
        logger.debug("transformers unavailable; HF hooks not registered.")
        return

    for fam in families().values():
        target_cls = _target_class(fam.target)
        for arch in fam.architectures:
            hf_cls = getattr(transformers, arch, None)
            if hf_cls is None:
                continue

            def _init_hook(config, _fam=fam, **kw):
                out = _fam.config_to_smp(config)
                out.update(kw)
                return (), out

            # translate_functions deliberately NOT registered here: the
            # registry keys them by distributed class, and the families
            # share their target classes — the accurate channel is the
            # per-instance functions smp.from_hf installs.
            registry.register(
                hf_cls,
                target_cls,
                init_hook=_init_hook,
            )

    # T5 layer-level hook (reference-parity surface, kept alongside the
    # full-model family above): T5Block -> DistributedTransformerLayer;
    # the relative-attention-bias block is declined by the hook returning
    # None, as in the reference.
    t5_block = getattr(
        getattr(getattr(transformers, "models", None), "t5", None),
        "modeling_t5", None,
    )
    t5_block = getattr(t5_block, "T5Block", None)
    if t5_block is not None:
        from smdistributed_modelparallel_tpu.nn.huggingface import t5
        from smdistributed_modelparallel_tpu.nn.transformer import (
            DistributedTransformerLayer,
        )

        def _t5_init_hook(config, has_relative_attention_bias=False, **kw):
            out = t5.config_to_smp_layer(config, has_relative_attention_bias)
            if out is None:
                return None
            out.update(kw)
            return (), out

        registry.register(
            t5_block, DistributedTransformerLayer, init_hook=_t5_init_hook
        )


def from_hf(model_or_config, rngs=("dropout",), **overrides):
    """One-call HF entry point: build + wrap + stage weights.

    ``smp.from_hf(hf_model_or_config)`` returns an ``smp.DistributedModel``
    whose parameters load from the translated HF weights on first use, and
    whose full checkpoints translate back to HF naming
    (``translate_if_full`` parity, reference
    ``torch/nn/predefined_hooks.py:82-151``).
    """
    from smdistributed_modelparallel_tpu.backend.state import state
    from smdistributed_modelparallel_tpu.model import DistributedModel

    module, flat, fam = translate_model(model_or_config, **overrides)
    model = DistributedModel(
        module, rngs=rngs,
        translate_functions=(fam.translate_to_hf, fam.translate_from_hf),
    )
    if flat is not None:
        if state.loaded_model_state is not None:
            logger.warning("Overwriting previously staged checkpoint state "
                           "with HF weights.")
        state.loaded_model_state = flat
    return model
