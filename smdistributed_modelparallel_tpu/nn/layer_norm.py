"""DistributedLayerNorm — layernorm over a tp-sharded hidden dimension.

Parity target: reference ``torch/nn/layer_norm.py:24-152``: two-phase CUDA
layernorm (per-rank partial mean/var -> allreduce -> finish; kernels
``forward_affine_mean_var`` / ``backward_affine_local_sums`` /
``backward_affine_finish``, SURVEY §2.1 N8) plus a re-export of apex
``FusedLayerNorm``.

TPU-native re-design: the moments are plain ``mean`` reductions over the
(possibly tp-sharded) hidden axis — GSPMD decomposes them into exactly the
partial-sums + cross-rank reduce + finish phases of the reference's kernel
pair, and XLA fuses the normalization arithmetic. The affine params carry
the same tp sharding as the activation's hidden axis so no gather is
needed.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.nn.utils import partitioned


class DistributedLayerNorm(nn.Module):
    """LayerNorm whose scale/bias (and input hidden axis) may be tp-sharded.

    Args:
      sharded: hidden axis of the input is sharded over tp (affine params
        follow). With sharded=False this is a standard LayerNorm kept for
        API parity with the reference's FusedLayerNorm re-export.
    """

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True
    sharded: bool = False
    # RMSNorm (T5-style): no mean subtraction, normalize by the root mean
    # square only. Callers typically pair this with use_bias=False.
    rms: bool = False
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        dtype = self.dtype or x.dtype
        # Moments in fp32 regardless of activation dtype (parity: reference
        # kernels accumulate in fp32).
        xf = x.astype(jnp.float32)
        if self.rms:
            var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
            y = xf * jax.lax.rsqrt(var + self.epsilon)
        else:
            mean = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
            y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        names = (TP_AXIS,) if self.sharded else (None,)
        if self.use_scale:
            scale = self.param(
                "scale", partitioned(nn.initializers.ones, names), (features,), dtype
            )
            y = y * scale.astype(jnp.float32)
        if self.use_bias:
            bias = self.param(
                "bias", partitioned(nn.initializers.zeros, names), (features,), dtype
            )
            y = y + bias.astype(jnp.float32)
        return y.astype(x.dtype)


# Reference also exposes apex FusedLayerNorm under this module; the XLA-fused
# DistributedLayerNorm covers both surfaces.
FusedLayerNorm = DistributedLayerNorm
