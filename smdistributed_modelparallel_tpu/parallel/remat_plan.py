"""Memory-budgeted recompute planner.

Decides, per (stage, chunk, pass), whether a pipeline backward pass
re-runs the chunk forward (activation recomputation — the seed behavior)
or reads stashed ``jax.vjp`` residuals captured by an earlier pass. The
knob (config ``recompute``, env alias ``SMP_RECOMPUTE``):

- ``"full"``    — recompute everywhere; every executor's compiled program
  is byte-identical to the pre-knob build (the untouched old code path).
- ``"stash_weight"`` — zero-bubble only: the B (input-grad) pass captures
  per-layer vjp residuals + per-layer output cotangents into stash rings
  sized by ``memory.recompute_ring_plan``; the deferred W (weight-grad)
  pass consumes them instead of re-running the chunk forward — the
  schedule's double-forward drops to a single forward per microbatch.
- ``"stash_all"`` — additionally capture residuals at the FORWARD pass so
  the B pass consumes them too (no backward-time forward at all); on the
  interleaved/1F1B executors (which have no W pass) this is the only
  stashing mode and removes the B recompute.
- ``"auto"``    — target the strongest stash the schedule supports, but
  budget the stash bytes against ``SMP_RECOMPUTE_BUDGET_MB`` (config
  ``recompute_budget_mb``; default: the XLA memory-breakdown temp bytes
  of the last audited program, else the ring-plan bound) and degrade
  per-(stage, chunk) back to recompute, highest chunk first, until the
  plan fits.

The plan is logged, published as ``smp_recompute_*`` gauges, recorded for
the compiled-program fingerprint (``utils/hlo_audit`` stamps a
``recompute`` block when a non-default plan is active), and
machine-checked by the extended ring plan: stash ring slots in the
executor equal the planner's prediction, and an ``auto`` plan never
exceeds its budget.

Non-pipeline paths (pp=1 microbatch scan, fill-drain) have no schedule
to plan over; there the knob maps onto ``jax.checkpoint`` policies in
``parallel/memory.remat_policy`` (``dots_with_no_batch_dims_saveable``
family), trading the same memory for the same FLOPs one level down.
"""

import os

from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

MODES = ("full", "stash_weight", "stash_all", "auto")
ENV = "SMP_RECOMPUTE"
BUDGET_ENV = "SMP_RECOMPUTE_BUDGET_MB"

#: Latest plan per schedule kind ("zb" / "1f1b") — read by the HLO-audit
#: fingerprint (``recompute`` block) and the telemetry report.
plans = {}


def resolve(cfg=None):
    """The effective knob value ("full" when unset/uninitialized)."""
    if cfg is None:
        try:
            from smdistributed_modelparallel_tpu.backend.state import state

            cfg = state.cfg
        except Exception:
            cfg = None
    mode = getattr(cfg, "recompute", None) if cfg is not None else None
    if mode is None:
        mode = os.environ.get(ENV, "full").strip().lower() or "full"
    if mode not in MODES:
        logger.warning("Unknown recompute mode %r; using 'full'.", mode)
        return "full"
    return mode


def budget_bytes(cfg=None):
    """The auto-mode stash budget in bytes, or None for "unbudgeted":
    config ``recompute_budget_mb`` (env ``SMP_RECOMPUTE_BUDGET_MB``),
    else the XLA memory-breakdown temp bytes of the last audited program
    (headroom the program already spends on temporaries), else None —
    the planner then falls back to its own ring-plan bound (stash
    everything the rings can hold)."""
    mb = getattr(cfg, "recompute_budget_mb", None) if cfg is not None else None
    if mb is None:
        env = os.environ.get(BUDGET_ENV)
        if env:
            try:
                mb = int(env)
            except ValueError:
                logger.warning("%s=%r is not an integer; ignored.",
                               BUDGET_ENV, env)
    if mb is not None:
        return int(mb) * (1 << 20)
    try:
        from smdistributed_modelparallel_tpu.utils import hlo_audit

        best = None
        for audit in hlo_audit.audits.values():
            tmp = (audit.memory or {}).get("temp_bytes")
            if tmp:
                best = int(tmp)
        if best:
            return best
    except Exception:
        pass
    return None


# Static executed-FLOP recompute model, in forward-equivalents per
# (chunk, microbatch) unit (fwd = dgrad = wgrad = 1 — the matmul classes
# cost the same): which passes run a forward / a dgrad chain / a wgrad,
# and how much of the executed dot work is recomputation. This is the
# planner's *executed* prediction; the X-ray remat census measures the
# compiled program's *structural* duplication, which additionally counts
# per-segment body copies — the census is the gate, this is the model.
_EXEC_MODEL = {
    # schedule -> mode -> (executed_units, recomputed_units)
    "zb": {
        "full": (6.0, 3.0),          # F:f  B:f+d  W:f+d+w
        "stash_weight": (4.0, 1.0),  # F:f  B:f+d  W:w
        "stash_all": (3.0, 0.0),     # F:f(capture)  B:d  W:w
    },
    "1f1b": {
        "full": (4.0, 1.0),          # F:f  B:f+d+w
        "stash_all": (3.0, 0.0),     # F:f(capture)  B:d+w
    },
}


def predicted_fraction(schedule, mode):
    """Executed-FLOP recompute fraction of the schedule under `mode`
    (None when the mode doesn't apply to the schedule)."""
    ent = _EXEC_MODEL.get(schedule, {}).get(mode)
    if ent is None:
        return None
    executed, recomputed = ent
    return recomputed / executed if executed else 0.0


def active_for(cfg):
    """The recompute block the HLO-audit fingerprint stamps for a
    program compiled under `cfg`, or None at the default knob (so
    default fingerprints — and every committed pre-knob golden — are
    byte-identical). Volatile fields (the budget default can come from
    the previous audit's memory breakdown) are excluded; the plan's
    DECISIONS (stash set, ring sizes, bytes) are what gate drift."""
    mode = resolve(cfg)
    if cfg is None or mode == "full":
        return None
    if int(getattr(cfg, "pipeline_parallel_degree", 1) or 1) <= 1:
        # Non-pipeline program: the knob maps onto a jax.checkpoint
        # policy (memory.remat_policy) — no ring plan to report.
        return {"mode": mode, "effective": "checkpoint_policy"}
    sched = ("zb" if getattr(cfg, "pipeline", "") == "zero_bubble"
             else "1f1b")
    p = plans.get(sched)
    if p is None:
        return {"mode": mode, "effective": "unplanned"}
    d = p.as_dict()
    d.pop("budget_bytes", None)
    return d


class RecomputePlan:
    """One resolved stash plan for one pipeline schedule build."""

    def __init__(self, schedule, mode, num_stages, virtual,
                 res_ring_slots, cot_ring_slots,
                 res_slot_bytes, cot_slot_bytes, budget=None):
        self.schedule = schedule          # "zb" | "1f1b"
        self.mode = mode                  # requested knob value
        self.num_stages = int(num_stages)
        self.virtual = int(virtual)
        self.res_ring_slots = int(res_ring_slots)
        self.cot_ring_slots = int(cot_ring_slots)
        self.res_slot_bytes = int(res_slot_bytes)
        self.cot_slot_bytes = int(cot_slot_bytes)
        self.budget_bytes = budget
        # Per-LOCAL-chunk decisions, uniform across stages (the SPMD
        # executors act symmetrically per stage; the per-(stage, chunk)
        # grid below expands this for reporting).
        self.stash_chunks = list(range(self.virtual))
        self.degraded_chunks = []
        if mode == "auto" and budget is not None:
            self._degrade_to_budget()

    # -- accounting -----------------------------------------------------

    def chunk_bytes(self):
        """Per-device stash bytes ONE stashed local chunk costs: its
        residual ring column plus its cotangent ring column."""
        return (self.res_ring_slots * self.res_slot_bytes
                + self.cot_ring_slots * self.cot_slot_bytes)

    @property
    def stash_bytes(self):
        """Per-device stash bytes of the planned rings."""
        return len(self.stash_chunks) * self.chunk_bytes()

    @property
    def effective(self):
        """The mode the executor should build: "full" when every chunk
        degraded, else the stash mode the plan realizes."""
        if not self.stash_chunks:
            return "full"
        if self.mode == "auto":
            # auto's target per schedule: 1f1b has only stash_all (no W
            # pass); on zero_bubble auto deliberately picks stash_weight,
            # NOT the stronger stash_all — its B->W rings cost exactly
            # the W-queue depth the deferral already pays, while
            # stash_all's F->W rings are strictly larger. stash_all is
            # an explicit opt-in.
            return "stash_all" if self.schedule == "1f1b" else "stash_weight"
        return self.mode

    def _degrade_to_budget(self):
        per_chunk = self.chunk_bytes()
        while self.stash_chunks and (
            len(self.stash_chunks) * per_chunk > self.budget_bytes
        ):
            # Highest chunk first: late chunks' stashes live shortest in
            # the schedule, so dropping them loses the least overlap.
            self.degraded_chunks.insert(0, self.stash_chunks.pop())

    # -- export ---------------------------------------------------------

    def grid(self):
        """Per-(stage, chunk) decision grid ("stash"/"recompute")."""
        return [
            ["stash" if k in self.stash_chunks else "recompute"
             for k in range(self.virtual)]
            for _ in range(self.num_stages)
        ]

    def as_dict(self):
        return {
            "schedule": self.schedule,
            "mode": self.mode,
            "effective": self.effective,
            "stash_chunks": list(self.stash_chunks),
            "degraded_chunks": list(self.degraded_chunks),
            "res_ring_slots": self.res_ring_slots,
            "cot_ring_slots": self.cot_ring_slots,
            "res_slot_bytes": self.res_slot_bytes,
            "cot_slot_bytes": self.cot_slot_bytes,
            "stash_bytes": self.stash_bytes,
            "budget_bytes": self.budget_bytes,
            "predicted_fraction_full": predicted_fraction(
                self.schedule, "full"
            ),
            "predicted_fraction_planned": predicted_fraction(
                self.schedule, self.effective
            ),
        }

    def summary(self):
        d = self.as_dict()
        return (
            f"recompute plan [{self.schedule}] mode={self.mode} -> "
            f"{d['effective']}: {len(self.stash_chunks)}/{self.virtual} "
            f"chunk(s) stashed ({len(self.degraded_chunks)} degraded), "
            f"rings res x{self.res_ring_slots} + cot x{self.cot_ring_slots}"
            f" = {self.stash_bytes:,} B/device"
            + (f" vs budget {self.budget_bytes:,} B"
               if self.budget_bytes is not None else " (unbudgeted)")
        )


def plan_pipeline(schedule, mode, num_stages, virtual,
                  res_ring_slots, cot_ring_slots,
                  res_slot_bytes, cot_slot_bytes, cfg=None):
    """Build, log, publish, and record the plan for one executor build."""
    budget = budget_bytes(cfg) if mode == "auto" else None
    p = RecomputePlan(
        schedule, mode, num_stages, virtual,
        res_ring_slots, cot_ring_slots, res_slot_bytes, cot_slot_bytes,
        budget=budget,
    )
    logger.info("%s", p.summary())
    publish(p)
    plans[schedule] = p
    return p


def publish(p):
    """smp_recompute_* gauges for the telemetry report."""
    try:
        from smdistributed_modelparallel_tpu.utils.telemetry import telemetry
    except Exception:  # pragma: no cover - defensive
        return
    lab = {"schedule": p.schedule}
    telemetry.gauge(
        "smp_recompute_mode_info",
        "active recompute plan (value 1; mode/effective in labels)",
    ).labels(mode=p.mode, effective=p.effective, **lab).set(1)
    telemetry.gauge(
        "smp_recompute_stash_bytes",
        "per-device bytes of the planned recompute stash rings",
    ).labels(**lab).set(p.stash_bytes)
    if p.budget_bytes is not None:
        telemetry.gauge(
            "smp_recompute_budget_bytes",
            "stash budget the auto recompute plan was held to",
        ).labels(**lab).set(p.budget_bytes)
    chunks = telemetry.gauge(
        "smp_recompute_chunks",
        "local chunks per stage by recompute-plan decision",
    )
    chunks.labels(decision="stash", **lab).set(len(p.stash_chunks))
    chunks.labels(decision="recompute", **lab).set(len(p.degraded_chunks))
    rings = telemetry.gauge(
        "smp_recompute_ring_slots",
        "stash ring slots per (stage, chunk) of the recompute plan",
    )
    rings.labels(ring="residual", **lab).set(p.res_ring_slots)
    rings.labels(ring="cotangent", **lab).set(p.cot_ring_slots)
    for when in ("full", "planned"):
        frac = predicted_fraction(
            p.schedule, "full" if when == "full" else p.effective
        )
        if frac is not None:
            telemetry.gauge(
                "smp_recompute_predicted_fraction",
                "planner's executed-FLOP recompute fraction (static model; "
                "the X-ray census measures the compiled program)",
            ).labels(when=when, **lab).set(frac)
