"""Parallelism strategies: sharding, pipeline, zero, context parallelism."""
