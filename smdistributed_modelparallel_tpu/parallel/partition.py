"""Pipeline auto-partitioner.

Parity target: reference ``torch/module_partition.py:182-905``
(``ModulePartitioner``): cost-model-driven assignment of modules to pipeline
stages (memory+time costs, tree BFS, d'Hondt device allocation). Fleshed out
in M2 (``parallel/pipeline.py`` consumes the assignment); M1 only needs the
single-stage fast path.
"""

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def maybe_auto_partition(model):
    """Run after the first-step init/trace pass. With pp == 1 everything is
    stage 0; with pp > 1 the partitioner assigns layers to stages (M2).
    ZeRO param sharding (M4) registers last so it only claims dims the
    pp/tp providers left free."""
    cfg = state.cfg
    from smdistributed_modelparallel_tpu.parallel.zero import maybe_register_zero2d

    if cfg.pipeline_parallel_degree == 1:
        maybe_register_zero2d(model)
        model.module_manager.set_partition_assignment({"": 0})
        model.post_partition({"": 0})
        return
    from smdistributed_modelparallel_tpu.parallel.pipeline import partition_for_pipeline

    loaded = _maybe_load_partition(model)
    if loaded is not None:
        assignment = loaded
    else:
        assignment = partition_for_pipeline(model)
        _maybe_save_partition(assignment)
    maybe_register_zero2d(model)
    model.module_manager.set_partition_assignment(assignment)
    model.post_partition(assignment)


def _maybe_load_partition(model):
    """``load_partition`` + ``partition_file``: reuse a saved stage
    assignment instead of re-running the partitioner.

    Parity: reference ``load_partition``/``partition_file``
    (``backend/config.yaml``; the reference serializes
    PartitioningAndTraceResults). The saved assignment is re-validated
    against the model's current layer count, then installed through the
    same pin path the manual partitioner uses.
    """
    import json
    import os

    cfg = state.cfg
    if not cfg.load_partition:
        return None
    path = cfg.partition_file
    if not path or not os.path.exists(path):
        from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError

        raise PartitionError(
            f"load_partition: True but partition_file not found: {path!r}"
        )
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("pipeline_parallel_degree") != cfg.pipeline_parallel_degree:
        from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError

        raise PartitionError(
            "partition_file was saved for pipeline_parallel_degree="
            f"{payload.get('pipeline_parallel_degree')}, current is "
            f"{cfg.pipeline_parallel_degree}."
        )
    assignment = {k: int(v) for k, v in payload["assignment"].items()}
    # Validate against the current model before installing: the pins are
    # silently ignored by the partitioner if prefixes don't match, so a
    # stale file must fail loudly, not fall back to cost-based boundaries.
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks
    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        get_pipeline_spec,
        partition_for_pipeline,
    )
    from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError

    spec = get_pipeline_spec(unwrap_hooks(model.module))
    if spec is not None:
        saved_layers = payload.get("num_layers")
        if saved_layers is not None and saved_layers != spec.num_layers:
            raise PartitionError(
                f"partition_file was saved for {saved_layers} layers, the "
                f"current model has {spec.num_layers}."
            )
        bad = [
            k for k in assignment
            if not k.startswith(spec.layer_path + "#")
        ]
        if bad:
            raise PartitionError(
                f"partition_file entries {bad[:3]}... do not match the "
                f"current model's layer path '{spec.layer_path}'."
            )
    # Install as pins and re-derive boundaries so the pipeline spec and
    # sharding providers are built exactly as in the computed path.
    for prefix, stage in assignment.items():
        model.module_manager.set_partition(prefix, stage)
    out = partition_for_pipeline(model)
    logger.info("Loaded pipeline partition from %s.", path)
    return out


def _maybe_save_partition(assignment):
    import json
    import os

    import jax

    cfg = state.cfg
    path = cfg.partition_file
    if not path or cfg.load_partition:
        return
    if jax.process_index() != 0:
        # One writer on shared filesystems (multi-host runs).
        return
    if int(getattr(cfg, "virtual_pipeline_degree", 1) or 1) > 1:
        # The chunked assignment (chunk c -> stage c % pp) is not a
        # contiguous stage order: a saved file could never be re-installed
        # (load_partition is rejected under virtual stages), so don't
        # write one that only fails later.
        logger.info(
            "partition_file not written: virtual_pipeline_degree > 1 "
            "assignments are derived, not loadable."
        )
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    num_layers = None
    if assignment:
        try:
            num_layers = max(
                int(k.rsplit("#", 1)[1]) for k in assignment
            ) + 1
        except (ValueError, IndexError):
            num_layers = None
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({
            "pipeline_parallel_degree": cfg.pipeline_parallel_degree,
            "num_layers": num_layers,
            "assignment": assignment,
        }, fh, indent=1)
    logger.info("Saved pipeline partition to %s.", path)
