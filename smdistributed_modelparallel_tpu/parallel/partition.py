"""Pipeline auto-partitioner.

Parity target: reference ``torch/module_partition.py:182-905``
(``ModulePartitioner``): cost-model-driven assignment of modules to pipeline
stages (memory+time costs, tree BFS, d'Hondt device allocation). Fleshed out
in M2 (``parallel/pipeline.py`` consumes the assignment); M1 only needs the
single-stage fast path.
"""

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def maybe_auto_partition(model):
    """Run after the first-step init/trace pass. With pp == 1 everything is
    stage 0; with pp > 1 the partitioner assigns layers to stages (M2).
    ZeRO param sharding (M4) registers last so it only claims dims the
    pp/tp providers left free."""
    cfg = state.cfg
    from smdistributed_modelparallel_tpu.parallel.zero import maybe_register_zero2d

    if cfg.pipeline_parallel_degree == 1:
        maybe_register_zero2d(model)
        model.module_manager.set_partition_assignment({"": 0})
        model.post_partition({"": 0})
        return
    from smdistributed_modelparallel_tpu.parallel.pipeline import partition_for_pipeline

    assignment = partition_for_pipeline(model)
    maybe_register_zero2d(model)
    model.module_manager.set_partition_assignment(assignment)
    model.post_partition(assignment)
