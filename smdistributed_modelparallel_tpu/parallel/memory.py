"""Activation checkpointing and host offloading.

Parity target: reference ``torch/patches/checkpoint.py`` (``smp.checkpoint``
/ ``smp.checkpoint_sequential`` / ``set_activation_checkpointing``) and
``torch/offload.py`` (``TensorOffloader``: pinned-CPU buffers, d2h/h2d
streams, ``activation_loading_horizon``).

TPU-native re-design: checkpointing is ``jax.checkpoint`` (remat) around
layer applications — the reference's enable_grad re-forward becomes XLA
rematerialization inside the backward. Offloading is a remat *policy*:
layer-boundary activations tagged ``checkpoint_name`` are offloaded to
``pinned_host`` memory by XLA, which also schedules the d2h/h2d copies to
overlap compute — subsuming the reference's hand-rolled stream pipeline and
its ``activation_loading_horizon`` knob.
"""

import jax
from jax.ad_checkpoint import checkpoint_name

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

LAYER_ACT_NAME = "smp_layer_act"
_warned_offload = False


def offload_supported():
    """Host offload needs a backend with pinned_host memory (TPU; recent
    CPU backends also support it)."""
    try:
        dev = jax.devices()[0]
        kinds = [m.kind for m in dev.addressable_memories()]
        return "pinned_host" in kinds
    except Exception:
        return False


def remat_policy():
    """Checkpoint policy for layer remat, honoring offload_activations."""
    global _warned_offload
    cfg = state.cfg
    if cfg is None or not cfg.offload_activations:
        return None  # full remat
    if not offload_supported():
        if not _warned_offload:
            logger.warning(
                "offload_activations requested but the backend exposes no "
                "pinned_host memory; falling back to plain rematerialization."
            )
            _warned_offload = True
        return None
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=[LAYER_ACT_NAME],
        offload_src="device",
        offload_dst="pinned_host",
    )


def name_layer_activation(x):
    """Tag a layer-boundary activation for the offload policy."""
    cfg = state.cfg
    if cfg is not None and cfg.offload_activations and offload_supported():
        return checkpoint_name(x, LAYER_ACT_NAME)
    return x


def checkpoint(fn, *args, **kwargs):
    """``smp.checkpoint``: run `fn` under rematerialization.

    Parity: reference ``smp.checkpoint(module, *args)``
    (``torch/patches/checkpoint.py:248-300``). Two call forms:
    ``smp.checkpoint(fn)(args...)`` (decorator) or
    ``smp.checkpoint(fn, args...)`` (immediate, reference-style).
    """
    wrapped = jax.checkpoint(fn, policy=remat_policy())
    if args or kwargs:
        return wrapped(*args, **kwargs)
    return wrapped


def checkpoint_sequential(fns, input, strategy="each"):
    """``smp.checkpoint_sequential``: remat a chain of callables.

    Parity: reference ``torch/patches/checkpoint.py:302-359`` (nn.Sequential
    with per-module or grouped strategies: "each" | "group_N").
    """
    if strategy == "each":
        group = 1
    elif strategy.startswith("group_"):
        group = int(strategy.split("_", 1)[1])
    else:
        raise ValueError(f"Unknown checkpoint_sequential strategy {strategy!r}")
    policy = remat_policy()
    x = input
    i = 0
    fns = list(fns)
    while i < len(fns):
        chunk = fns[i:i + group]

        def run_chunk(x, chunk=chunk):
            for f in chunk:
                x = f(x)
            return x

        x = jax.checkpoint(run_chunk, policy=policy)(x)
        i += group
    return x


def module_checkpoint_enabled(mm, *paths):
    """Whether any of the given module paths has an activation-checkpoint
    config registered (smp.set_activation_checkpointing)."""
    if mm is None:
        return False
    for p in paths:
        if mm.checkpoint_config(p) is not None:
            return True
    return False
