"""Activation checkpointing and host offloading.

Parity target: reference ``torch/patches/checkpoint.py`` (``smp.checkpoint``
/ ``smp.checkpoint_sequential`` / ``set_activation_checkpointing``) and
``torch/offload.py`` (``TensorOffloader``: pinned-CPU buffers, d2h/h2d
streams, ``activation_loading_horizon``).

TPU-native re-design: checkpointing is ``jax.checkpoint`` (remat) around
layer applications — the reference's enable_grad re-forward becomes XLA
rematerialization inside the backward. Offloading is a remat *policy*:
layer-boundary activations tagged ``checkpoint_name`` are offloaded to
``pinned_host`` memory by XLA, which also schedules the d2h/h2d copies to
overlap compute — subsuming the reference's hand-rolled stream pipeline and
its ``activation_loading_horizon`` knob.
"""

import jax
from jax.ad_checkpoint import checkpoint_name

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

LAYER_ACT_NAME = "smp_layer_act"
_warned_offload = False


def offload_supported():
    """Host offload needs a backend with pinned_host memory (TPU; recent
    CPU backends also support it)."""
    try:
        dev = jax.devices()[0]
        kinds = [m.kind for m in dev.addressable_memories()]
        return "pinned_host" in kinds
    except Exception:
        return False


def remat_policy():
    """Checkpoint policy for layer remat, honoring offload_activations
    and the ``recompute`` knob.

    ``recompute: "full"`` (the default) returns exactly what the pre-knob
    build returned — None (full remat) or the offload policy — so default
    programs stay byte-identical. The stash modes map onto the
    ``dots_with_no_batch_dims_saveable`` policy family: non-pipeline runs
    (pp=1 microbatch scan, fill-drain) have no schedule for the recompute
    planner to stash against, so the same memory-for-FLOPs trade is taken
    one level down, inside ``jax.checkpoint``: ``stash_weight``/``auto``
    save the weight-matmul outputs (the dominant recompute), ``stash_all``
    saves everything (checkpoint becomes a no-op boundary). Offloading
    takes precedence — an offload policy already saves the layer boundary
    to host, and combining the two would double-store.
    """
    global _warned_offload
    cfg = state.cfg
    if cfg is None or not cfg.offload_activations:
        if cfg is not None:
            from smdistributed_modelparallel_tpu.parallel import remat_plan

            mode = remat_plan.resolve(cfg)
            if mode in ("stash_weight", "auto"):
                return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            if mode == "stash_all":
                return jax.checkpoint_policies.everything_saveable
        return None  # full remat
    if not offload_supported():
        if not _warned_offload:
            logger.warning(
                "offload_activations requested but the backend exposes no "
                "pinned_host memory; falling back to plain rematerialization."
            )
            _warned_offload = True
        return None
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=[LAYER_ACT_NAME],
        offload_src="device",
        offload_dst="pinned_host",
    )


def name_layer_activation(x):
    """Tag a layer-boundary activation for the offload policy."""
    cfg = state.cfg
    if cfg is not None and cfg.offload_activations and offload_supported():
        return checkpoint_name(x, LAYER_ACT_NAME)
    return x


def checkpoint(fn, *args, **kwargs):
    """``smp.checkpoint``: run `fn` under rematerialization.

    Parity: reference ``smp.checkpoint(module, *args)``
    (``torch/patches/checkpoint.py:248-300``). Two call forms:
    ``smp.checkpoint(fn)(args...)`` (decorator) or
    ``smp.checkpoint(fn, args...)`` (immediate, reference-style).
    """
    wrapped = jax.checkpoint(fn, policy=remat_policy())
    if args or kwargs:
        return wrapped(*args, **kwargs)
    return wrapped


def checkpoint_sequential(fns, input, strategy="each"):
    """``smp.checkpoint_sequential``: remat a chain of callables.

    Parity: reference ``torch/patches/checkpoint.py:302-359`` (nn.Sequential
    with per-module or grouped strategies: "each" | "group_N").
    """
    if strategy == "each":
        group = 1
    elif strategy.startswith("group_"):
        group = int(strategy.split("_", 1)[1])
    else:
        raise ValueError(f"Unknown checkpoint_sequential strategy {strategy!r}")
    policy = remat_policy()
    x = input
    i = 0
    fns = list(fns)
    while i < len(fns):
        chunk = fns[i:i + group]

        def run_chunk(x, chunk=chunk):
            for f in chunk:
                x = f(x)
            return x

        x = jax.checkpoint(run_chunk, policy=policy)(x)
        i += group
    return x


def zero_bubble_ring_plan(fwd_k, fwd_m, bwd_k, bwd_m, wgt_k, wgt_m,
                          num_stages, virtual, window):
    """Ring-buffer budget of the zero-bubble (ZB-H1) executor.

    The split backward extends ring-entry lifetimes: a stashed chunk
    input and its retained output cotangent stay live from the forward
    until the DEFERRED weight-grad pass consumes them (the fused
    executors free them at the monolithic backward). This walks the
    static schedule and returns the exact peak:

    - ``stash_alive_peak``: max per-(stage, chunk) count of microbatches
      forwarded but not yet weight-graded at any tick (counting a
      same-tick F-write/W-read as overlapping — the executor's sub-step
      order writes the forward stash before the W pass reads);
    - ``w_queue_peak``: max per-(stage, chunk) count of deferred
      weight-grad units (input-graded, not yet weight-graded) — the
      "W-queue" depth the cooldown packing costs;
    - ``ring_slots``: slots the executor allocates per (stage, chunk)
      ring — ``max(stash_alive_peak, window + 1)``. The ``window + 1``
      floor is the fused executors' ring size (the in-flight input
      buffer needs it regardless of W deferral), so
      ``extra_ring_slots == 0`` means ZB's same-activation-memory claim
      holds exactly: the deferral fits in slack the in-flight cap
      already paid for. At the default window it always does; tighter
      windows may grow the ring and the executor's
      ``smp_pipeline_ring_slots`` gauge reports what was allocated.
    """
    S, V = int(num_stages), int(virtual)
    n_ticks = int(fwd_m.shape[0])
    C = S * V
    f_ticks = [[] for _ in range(C)]   # per global chunk, m-ordered
    b_ticks = [[] for _ in range(C)]
    w_ticks = [[] for _ in range(C)]
    for t in range(n_ticks):
        for s in range(S):
            if fwd_m[t, s] >= 0:
                f_ticks[int(fwd_k[t, s]) * S + s].append(t)
            if bwd_m[t, s] >= 0:
                b_ticks[int(bwd_k[t, s]) * S + s].append(t)
            if wgt_m[t, s] >= 0:
                w_ticks[int(wgt_k[t, s]) * S + s].append(t)
    import bisect

    stash_alive_peak = 0
    w_queue_peak = 0
    for c in range(C):
        fts, bts, wts = f_ticks[c], b_ticks[c], w_ticks[c]
        for m, ft in enumerate(fts):
            # Alive at F(c, m)'s tick: m+1 forwarded minus Ws strictly
            # before it (a same-tick W runs after the F write).
            freed = bisect.bisect_left(wts, ft)
            stash_alive_peak = max(stash_alive_peak, m + 1 - freed)
        for m, bt in enumerate(bts):
            # Deferred at B(c, m)'s tick: m+1 input-graded minus Ws
            # strictly before it (a same-tick W drains after B).
            drained = bisect.bisect_left(wts, bt)
            w_queue_peak = max(w_queue_peak, m + 1 - drained)
    ring_slots = max(stash_alive_peak, int(window) + 1, 2)
    return {
        "ring_slots": ring_slots,
        "stash_alive_peak": stash_alive_peak,
        "w_queue_peak": w_queue_peak,
        "extra_ring_slots": ring_slots - (int(window) + 1),
    }


def _ring_slots_for(write_ticks, read_ticks):
    """Minimum ``m % R`` ring size for one chunk's stash entries: entry m
    is written at ``write_ticks[m]`` and last read at ``read_ticks[m]``
    (both m-ordered — the schedules are FIFO per (stage, chunk)). The
    executors order sub-steps F -> B -> W within a tick and every stash
    write-pass precedes its read-pass, so a same-tick write of entry
    ``m + R`` lands BEFORE the read of entry ``m`` — strict inequality is
    required, i.e. entry ``m`` counts as alive through its read tick."""
    import bisect

    peak = 0
    for m, wt in enumerate(write_ticks):
        # Entries m' < m still alive at this write: read tick >= wt.
        first_alive = bisect.bisect_left(read_ticks, wt)
        peak = max(peak, m - first_alive + 1)
    return max(peak, 1)


def recompute_ring_plan(fwd_k, fwd_m, bwd_k, bwd_m, wgt_k=None, wgt_m=None,
                        num_stages=1, virtual=1):
    """Stash-ring budget of the recompute planner (``parallel/
    remat_plan.py``): exact per-(stage, chunk) ring sizes for the three
    residual-stash lifetimes the stash executors use, walked from the
    static schedule like ``zero_bubble_ring_plan``:

    - ``b_to_w``: entries written by the B pass, consumed by the W pass —
      the ``stash_weight`` residual + cotangent rings (== the W-queue
      depth under the strict write-before-read slot convention);
    - ``f_to_w``: written at F, consumed at W — the ``stash_all``
      residual ring on the zero-bubble schedule;
    - ``f_to_b``: written at F, consumed at B — the ``stash_all``
      residual ring on the interleaved/1F1B schedules (pass ``wgt_*`` as
      None for those).

    Returns ``{"b_to_w", "f_to_w", "f_to_b", "per_chunk": {name: [C]}}``
    (global-chunk-indexed per-chunk peaks; the scalar is their max).
    """
    import numpy as np

    S, V = int(num_stages), int(virtual)
    C = S * V
    n_ticks = int(np.asarray(fwd_m).shape[0])

    def ticks_of(k_arr, m_arr):
        out = [[] for _ in range(C)]
        if k_arr is None or m_arr is None:
            return None
        k_arr = np.asarray(k_arr)
        m_arr = np.asarray(m_arr)
        for t in range(n_ticks):
            for s in range(S):
                if m_arr[t, s] >= 0:
                    out[int(k_arr[t, s]) * S + s].append(t)
        return out

    f_ticks = ticks_of(fwd_k, fwd_m)
    b_ticks = ticks_of(bwd_k, bwd_m)
    w_ticks = ticks_of(wgt_k, wgt_m)

    per_chunk = {"b_to_w": [], "f_to_w": [], "f_to_b": []}
    for c in range(C):
        if w_ticks is not None:
            per_chunk["b_to_w"].append(
                _ring_slots_for(b_ticks[c], w_ticks[c])
            )
            per_chunk["f_to_w"].append(
                _ring_slots_for(f_ticks[c], w_ticks[c])
            )
        per_chunk["f_to_b"].append(_ring_slots_for(f_ticks[c], b_ticks[c]))
    return {
        "b_to_w": max(per_chunk["b_to_w"], default=0),
        "f_to_w": max(per_chunk["f_to_w"], default=0),
        "f_to_b": max(per_chunk["f_to_b"], default=0),
        "per_chunk": per_chunk,
    }


def module_checkpoint_enabled(mm, *paths):
    """Whether any of the given module paths has an activation-checkpoint
    config registered (smp.set_activation_checkpointing)."""
    if mm is None:
        return False
    for p in paths:
        if mm.checkpoint_config(p) is not None:
            return True
    return False
