"""Optimizer-state sharding (ZeRO-1) and sharded data parallelism (ZeRO-3).

Parity target: reference ``shard_optimizer_state`` (contiguous buffer +
virtual params, ``torch/model.py:1237-1340``,
``torch/optimizers/optimizer.py:355-391``) and "ZeRO-2D" sharded DP
(DeepSpeed stage-3 fork configured by ``backend/zero_config.py`` —
``sharded_data_parallel_degree`` + the ``sdp_*`` knobs).

TPU-native re-design: both are PartitionSpecs.
- ZeRO-1: optimizer-state leaves mirror their parameter's pp/tp spec and
  additionally shard a free dimension over rdp. The post-update parameter
  allgather the reference runs by hand (``optimizer.py:379-389``) is
  emitted by XLA from the spec mismatch between sharded state and
  replicated params.
- ZeRO-3 (zero2d): parameters themselves are sharded over rdp (above the
  ``sdp_param_persistence_threshold``); XLA inserts the forward/backward
  allgathers and gradient reduce-scatters that DeepSpeed stage 3 performs
  with explicit collectives, and schedules them (the ``sdp_max_live_
  parameters`` / hierarchical-allgather knobs become advisory).
"""

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS
from smdistributed_modelparallel_tpu.module_manager import path_key
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def add_rdp_axis(spec, shape, rdp_size, persistence_threshold=0):
    """Extend `spec` (list of axes per dim, or None) with rdp on the first
    free dimension divisible by rdp_size. Returns a list or None."""
    if rdp_size <= 1 or not shape:
        return None
    if int(np.prod(shape)) < persistence_threshold:
        return None
    base = list(spec) if spec is not None else [None] * len(shape)
    base += [None] * (len(shape) - len(base))
    for i, dim in enumerate(shape):
        if base[i] is None and dim % rdp_size == 0:
            base[i] = RDP_AXIS
            return base
    return None


def shard_spec_for_leaf(leaf, rdp_size, persistence_threshold=0):
    """Spec sharding a tensor over rdp on its first divisible dim, or None."""
    out = add_rdp_axis(None, getattr(leaf, "shape", ()), rdp_size,
                       persistence_threshold)
    return P(*out) if out is not None else None


def zero2d_param_provider(model):
    """Spec provider sharding parameters over rdp (ZeRO-3 / FSDP).

    Composes with pp/tp specs via the module manager's dimension-wise merge:
    this provider only names rdp on dims the earlier providers left free.
    """
    cfg = state.cfg
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = cfg.sdp_param_persistence_threshold
    mm = model.module_manager

    def provider(path, leaf):
        # Merge-safe: compute the spec the earlier providers produce, then
        # extend with rdp. Providers are consulted in registration order and
        # this one is registered last, so recursion is bounded by ordering:
        # we re-run only the providers registered before us.
        prior = [None] * getattr(leaf, "ndim", 0)
        for p in mm._spec_providers:
            if getattr(p, "_smp_name", None) == "zero2d":
                break
            got = p(path, leaf)
            if got is None:
                continue
            for i, axes in enumerate(got):
                if axes is not None and i < len(prior):
                    prior[i] = axes
        out = add_rdp_axis(prior, getattr(leaf, "shape", ()), rdp_size, threshold)
        return P(*out) if out is not None else None

    return provider


def maybe_register_zero2d(model):
    if state.cfg is not None and state.cfg.zero2d_enabled:
        model.module_manager.register_spec_provider(
            zero2d_param_provider(model), name="zero2d"
        )
        logger.info(
            "ZeRO sharded data parallelism: parameters >= %d elems sharded "
            "over rdp=%d.",
            state.cfg.sdp_param_persistence_threshold,
            state.mesh.shape[RDP_AXIS],
        )


def describe_state_layout(cfg_like):
    """Compact description of where optimizer/parameter state lives under a
    config — works on a live ``ModelParallelConfig`` or a saved checkpoint's
    plain-dict snapshot, so elastic resume (``resilience/elastic.py``) and
    ``scripts/resilience_probe.py`` can describe the layout transition a
    reshard performs. All three modes are PartitionSpec-only in this
    framework (module docstring), which is precisely why a checkpoint's
    logical arrays reshard freely across them: the rdp axis placement is
    re-derived from the resuming config, never read from the files."""
    if hasattr(cfg_like, "get"):
        get = cfg_like.get
    else:
        def get(k, d=None):
            return getattr(cfg_like, k, d)

    rdp = int(get("sharded_data_parallel_degree", 0) or 0)
    return {
        "zero1": bool(get("shard_optimizer_state", False)),
        "zero2d": rdp > 1,
        "sharded_data_parallel_degree": rdp,
        "pipeline_parallel_degree": int(get("pipeline_parallel_degree", 1) or 1),
        "tensor_parallel_degree": int(get("tensor_parallel_degree", 1) or 1),
    }


def opt_state_shardings(opt_state, model):
    """Shardings for the optimizer-state pytree.

    Moment-like leaves (same shape as a parameter, with the parameter's
    path as a suffix of their pytree path) mirror the parameter's spec;
    under ``shard_optimizer_state``/zero2d they are additionally sharded
    over rdp. Returns None when state should stay replicated-as-params.
    """
    cfg = state.cfg
    if cfg is None:
        return None
    zero1 = cfg.shard_optimizer_state
    zero2d = cfg.zero2d_enabled
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = cfg.sdp_param_persistence_threshold if zero2d else 0

    # Param path -> (shape, spec) for suffix matching.
    param_info = {}
    if model is not None and model.params is not None:
        for path, leaf in jax.tree_util.tree_flatten_with_path(model.params)[0]:
            key = path_key(path)
            spec = model.module_manager.spec_for(key, leaf)
            param_info[key] = (getattr(leaf, "shape", ()), list(spec))

    def leaf_sharding(path, leaf):
        key = path_key(path)
        shape = getattr(leaf, "shape", ())
        base = None
        for pkey, (pshape, pspec) in param_info.items():
            if key.endswith(pkey) and pshape == shape:
                base = list(pspec)
                break
        if zero1 or zero2d:
            extended = add_rdp_axis(base, shape, rdp_size, threshold)
            if extended is not None:
                return NamedSharding(mesh, P(*extended))
        if base is not None and any(a is not None for a in base):
            return NamedSharding(mesh, P(*base))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(leaf_sharding, opt_state)
