"""Optimizer-state sharding (ZeRO-1) and sharded data parallelism (ZeRO-2D /
ZeRO-3).

Parity target: reference ``shard_optimizer_state`` (contiguous buffer +
virtual params, ``torch/model.py:1237-1340``,
``torch/optimizers/optimizer.py:355-391``) and "ZeRO-2D" sharded DP
(DeepSpeed stage-3 fork configured by ``backend/zero_config.py`` —
``sharded_data_parallel_degree`` + the ``sdp_*`` knobs).

TPU-native re-design: all three are PartitionSpecs.
- ZeRO-1: optimizer-state leaves mirror their parameter's pp/tp spec and
  additionally shard a free dimension over rdp. The post-update parameter
  allgather the reference runs by hand (``optimizer.py:379-389``) is
  emitted by XLA from the spec mismatch between sharded state and
  replicated params.
- ZeRO-2D (zero2d): parameters themselves are sharded over rdp (above the
  ``sdp_param_persistence_threshold``); XLA inserts the forward/backward
  allgathers and gradient reduce-scatters that DeepSpeed stage 3 performs
  with explicit collectives, and schedules them (the ``sdp_max_live_
  parameters`` / hierarchical-allgather knobs become advisory).
- ZeRO-3 (``sharded_params: "zero3"``, arXiv 2004.13336): the fully
  explicit form of the same transformation. Parameters >= the persistence
  threshold live sharded over rdp on their LARGEST divisible free dim
  (balanced shards, and the layer axis of scanned stacks stays whole so
  the per-layer dynamic slice is local); the step program all-gathers each
  layer's parameters just-in-time in forward — inside the layer scan's
  while loop, so only one layer (two, double-buffered) is ever gathered —
  and REGATHERS in backward instead of stashing gathered copies
  (``zero3_prefetch_scan``'s custom-vjp layer saves only the sharded
  slice). Gradients are computed as genuine per-rdp-slice partial sums
  (the step engine vmaps the microbatch forward over an rdp-reshaped
  batch axis) and leave through ``zero3_grad_reduce``: bucketed
  ``psum_scatter`` reduce-scatters (``zero3_bucket_mb``) issued inside the
  microbatch scan so they overlap the next microbatch's backward compute.
  Below-threshold ("persistent", DeepSpeed terminology) parameters stay
  replicated and their gradients all-reduce as in plain DP.

Data-parallel contract (same as every DDP/FSDP system, reference
``torch/allreduce/ddp.py``): the explicit-reduce path assumes the
per-microbatch loss is the MEAN of the per-rdp-shard losses — true for
every per-example mean loss — and applies the same averaging to every
SCALAR step output (a sum-semantics scalar reads 1/rdp of its plain
value; return per-example arrays and reduce outside the step). Losses
mixing batch elements across rdp shards should keep
``sharded_params: none``.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS
from smdistributed_modelparallel_tpu.module_manager import path_key
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

PREFETCH_ENV = "SMP_ZERO3_PREFETCH"


def _has_rdp(axes):
    if axes is None:
        return False
    return RDP_AXIS in (axes if isinstance(axes, tuple) else (axes,))


def add_rdp_axis(spec, shape, rdp_size, persistence_threshold=0,
                 prefer="first"):
    """Extend `spec` (list of axes per dim, or None) with rdp on a free
    dimension divisible by rdp_size — the first such dim by default,
    the largest (ties -> first) under ``prefer="largest"`` (zero3: balanced
    shards, and a scanned stack's small layer axis loses the tie to the
    weight dims so the per-layer dynamic slice stays local). Specs already
    carrying rdp are returned unchanged (a mesh axis may name only one
    dim). Returns a list or None."""
    if rdp_size <= 1 or not shape:
        return None
    if int(np.prod(shape)) < persistence_threshold:
        return None
    base = list(spec) if spec is not None else [None] * len(shape)
    base += [None] * (len(shape) - len(base))
    if any(_has_rdp(a) for a in base):
        return base
    candidates = [
        (i, dim) for i, dim in enumerate(shape)
        if base[i] is None and dim % rdp_size == 0 and dim > 0
    ]
    if not candidates:
        return None
    if prefer == "largest":
        i, _ = max(candidates, key=lambda c: c[1])
    else:
        i, _ = candidates[0]
    base[i] = RDP_AXIS
    return base


def shard_spec_for_leaf(leaf, rdp_size, persistence_threshold=0,
                        prefer="first"):
    """Spec sharding a tensor over rdp on a divisible dim, or None."""
    out = add_rdp_axis(None, getattr(leaf, "shape", ()), rdp_size,
                       persistence_threshold, prefer=prefer)
    return P(*out) if out is not None else None


def _merged_prior_spec(mm, stop_name, path, leaf):
    """The dimension-wise merge of every provider registered before the
    named one — what the pp/tp layers assigned, so the ZeRO provider only
    claims dims they left free."""
    prior = [None] * getattr(leaf, "ndim", 0)
    for p in mm._spec_providers:
        if getattr(p, "_smp_name", None) == stop_name:
            break
        got = p(path, leaf)
        if got is None:
            continue
        for i, axes in enumerate(got):
            if axes is not None and i < len(prior):
                prior[i] = axes
    return prior


def zero2d_param_provider(model):
    """Spec provider sharding parameters over rdp (ZeRO-2D).

    Composes with pp/tp specs via the module manager's dimension-wise merge:
    this provider only names rdp on dims the earlier providers left free.
    """
    cfg = state.cfg
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = cfg.sdp_param_persistence_threshold
    mm = model.module_manager

    def provider(path, leaf):
        # Merge-safe: compute the spec the earlier providers produce, then
        # extend with rdp. Providers are consulted in registration order and
        # this one is registered last, so recursion is bounded by ordering:
        # we re-run only the providers registered before us.
        prior = _merged_prior_spec(mm, "zero2d", path, leaf)
        out = add_rdp_axis(prior, getattr(leaf, "shape", ()), rdp_size, threshold)
        return P(*out) if out is not None else None

    return provider


def zero3_param_provider(model):
    """Spec provider for fully-sharded parameters (``sharded_params:
    zero3``): every parameter >= the persistence threshold is sharded over
    rdp on its largest free divisible dim. Leaves with no divisible free
    dim stay replicated (counted, logged once) rather than unevenly
    padded — exactness over coverage."""
    cfg = state.cfg
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = cfg.sdp_param_persistence_threshold
    mm = model.module_manager
    unshardable = []

    def provider(path, leaf):
        prior = _merged_prior_spec(mm, "zero3", path, leaf)
        shape = getattr(leaf, "shape", ())
        out = add_rdp_axis(prior, shape, rdp_size, threshold,
                           prefer="largest")
        if (out is None and shape and
                int(np.prod(shape)) >= threshold and path not in unshardable):
            unshardable.append(path)
            logger.warning(
                "zero3: parameter '%s' %s has no free dim divisible by "
                "rdp=%d; kept replicated.", path, tuple(shape), rdp_size,
            )
        return P(*out) if out is not None else None

    return provider


def maybe_register_zero2d(model):
    """Register whichever ZeRO param-sharding mode the config enables
    (kept under the historical name — the partitioner calls it for both
    the zero2d and zero3 modes)."""
    if state.cfg is None:
        return
    if state.cfg.zero3_enabled:
        model.module_manager.register_spec_provider(
            zero3_param_provider(model), name="zero3"
        )
        logger.info(
            "ZeRO-3 fully-sharded parameters: params >= %d elems sharded "
            "over rdp=%d (largest divisible dim), bucket %d MiB.",
            state.cfg.sdp_param_persistence_threshold,
            state.mesh.shape[RDP_AXIS],
            state.cfg.zero3_bucket_mb,
        )
        return
    if state.cfg.zero2d_enabled:
        model.module_manager.register_spec_provider(
            zero2d_param_provider(model), name="zero2d"
        )
        logger.info(
            "ZeRO sharded data parallelism: parameters >= %d elems sharded "
            "over rdp=%d.",
            state.cfg.sdp_param_persistence_threshold,
            state.mesh.shape[RDP_AXIS],
        )


def describe_state_layout(cfg_like):
    """Compact description of where optimizer/parameter state lives under a
    config — works on a live ``ModelParallelConfig`` or a saved checkpoint's
    plain-dict snapshot, so elastic resume (``resilience/elastic.py``) and
    ``scripts/resilience_probe.py`` can describe the layout transition a
    reshard performs. All modes are PartitionSpec-only in this framework
    (module docstring), which is precisely why a checkpoint's logical
    arrays reshard freely across them: the rdp axis placement is re-derived
    from the resuming config, never read from the files."""
    if hasattr(cfg_like, "get"):
        get = cfg_like.get
    else:
        def get(k, d=None):
            return getattr(cfg_like, k, d)

    rdp = int(get("sharded_data_parallel_degree", 0) or 0)
    sharded_params = str(get("sharded_params", "none") or "none")
    return {
        "zero1": bool(get("shard_optimizer_state", False)),
        "zero2d": rdp > 1,
        "zero3": sharded_params == "zero3",
        "sharded_params": sharded_params,
        "sharded_data_parallel_degree": rdp,
        "pipeline_parallel_degree": int(get("pipeline_parallel_degree", 1) or 1),
        "tensor_parallel_degree": int(get("tensor_parallel_degree", 1) or 1),
    }


def opt_state_shardings(opt_state, model):
    """Shardings for the optimizer-state pytree.

    Moment-like leaves (same shape as a parameter, with the parameter's
    path as a suffix of their pytree path) mirror the parameter's spec;
    under ``shard_optimizer_state``/zero2d/zero3 they are additionally
    sharded over rdp. Returns None when state should stay
    replicated-as-params.
    """
    cfg = state.cfg
    if cfg is None:
        return None
    zero1 = cfg.shard_optimizer_state
    zero2d = cfg.zero2d_enabled
    zero3 = cfg.zero3_enabled
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = (
        cfg.sdp_param_persistence_threshold if (zero2d or zero3) else 0
    )

    # Param path -> (shape, spec) for suffix matching.
    param_info = {}
    if model is not None and model.params is not None:
        for path, leaf in jax.tree_util.tree_flatten_with_path(model.params)[0]:
            key = path_key(path)
            spec = model.module_manager.spec_for(key, leaf)
            param_info[key] = (getattr(leaf, "shape", ()), list(spec))

    def leaf_sharding(path, leaf):
        key = path_key(path)
        shape = getattr(leaf, "shape", ())
        base = None
        for pkey, (pshape, pspec) in param_info.items():
            if key.endswith(pkey) and pshape == shape:
                base = list(pspec)
                break
        if zero1 or zero2d or zero3:
            # Under zero2d/zero3 a moment's base spec already carries rdp
            # (mirroring its sharded parameter); add_rdp_axis returns it
            # unchanged then. The extension only fires for moments of
            # replicated params (zero1 semantics).
            extended = add_rdp_axis(
                base, shape, rdp_size, threshold,
                prefer="largest" if zero3 else "first",
            )
            if extended is not None:
                return NamedSharding(mesh, P(*extended))
        if base is not None and any(a is not None for a in base):
            return NamedSharding(mesh, P(*base))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(leaf_sharding, opt_state)


# ----------------------------------------------------------------------
# ZeRO-3: step-engine integration helpers
# ----------------------------------------------------------------------


def zero3_enabled(cfg=None):
    cfg = cfg if cfg is not None else state.cfg
    return bool(cfg is not None and cfg.zero3_enabled)


def zero3_manual_grads_supported(cfg=None):
    """True when the explicit per-slice-grad + bucketed reduce-scatter
    path applies: the rdp axis must be the ONLY nontrivial mesh axis (the
    reduce buckets run in a full-manual shard_map region on this jax —
    see utils/jax_compat.py — which would gather the other axes at region
    entry). Other compositions (pp x zero3, tp x zero3) keep sharded
    params + just-in-time gathers and leave the gradient reduction to
    GSPMD."""
    cfg = cfg if cfg is not None else state.cfg
    if cfg is None or not cfg.zero3_enabled:
        return False
    if (cfg.pipeline_parallel_degree > 1 or cfg.tensor_parallel_degree > 1
            or cfg.context_parallel_degree > 1
            or cfg.expert_parallel_degree > 1):
        return False
    mesh = state.mesh
    return mesh is not None and mesh.shape[RDP_AXIS] > 1


def rdp_size():
    mesh = state.mesh
    return int(mesh.shape[RDP_AXIS]) if mesh is not None else 1


def strip_rdp(spec):
    """PartitionSpec with every rdp entry removed (the gathered/compute
    layout of a zero3-sharded value)."""
    from smdistributed_modelparallel_tpu.parallel.sharding import strip_axis

    return strip_axis(spec, RDP_AXIS)


def zero3_pin_grads(grads, model):
    """Constrain a grads tree onto the parameters' (sharded) placements so
    the compiled program's grad outputs come back rdp-sharded — without
    this GSPMD is free to materialize them replicated, which both wastes
    rdp x memory and trips the X-ray replication detector."""
    if grads is None or model is None or model._param_shardings is None:
        return grads
    return jax.tree_util.tree_map(
        jax.lax.with_sharding_constraint, grads, model._param_shardings
    )


def zero3_slice_batch(leaf, axis, rdp):
    """Split a microbatch leaf's batch dim (at ``axis``) into rdp slices
    and move the slice dim to the FRONT, pinned over rdp: the per-device
    rows become the explicit leading axis the step engine vmaps over, so
    the vmapped forward computes each device's loss shard locally and the
    weight-grad dots never cross rdp — the cross-replica reduction
    happens ONLY in zero3_grad_reduce. The per-slice leaf keeps its batch
    rows at the original ``axis``, exactly what the user fn expects."""
    mesh = state.mesh
    shape = leaf.shape
    new_shape = shape[:axis] + (rdp, shape[axis] // rdp) + shape[axis + 1:]
    leaf = leaf.reshape(new_shape)
    if axis:
        leaf = jnp.moveaxis(leaf, axis, 0)
    spec = [None] * leaf.ndim
    spec[0] = RDP_AXIS
    return jax.lax.with_sharding_constraint(
        leaf, NamedSharding(mesh, P(*spec))
    )


def zero3_sliceable(stacked_leaves, mb_axes, rdp):
    """Every scan leaf's per-microbatch batch dim divisible by rdp (the
    reshape above must be exact). ``stacked_leaves`` carry the leading
    [num_mb] scan axis; ``mb_axes`` are the per-microbatch batch dims."""
    if not stacked_leaves:
        return False
    for leaf, axis in zip(stacked_leaves, mb_axes):
        shape = getattr(leaf, "shape", ())
        if len(shape) <= 1 + axis or shape[1 + axis] % rdp != 0:
            return False
    return True


def _grad_layout(params, model):
    """Per-leaf reduction plan: ``(paths, shard_dims)`` where shard_dims[i]
    is the rdp-sharded dim of leaf i (None -> replicated param, all-reduce
    bucket)."""
    mm = model.module_manager
    rdp = rdp_size()
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    paths, dims = [], []
    for path, leaf in flat:
        key = path_key(path)
        spec = list(mm.spec_for(key, leaf))
        spec += [None] * (getattr(leaf, "ndim", 0) - len(spec))
        d = next((i for i, a in enumerate(spec) if _has_rdp(a)), None)
        if d is not None and leaf.shape[d] % rdp != 0:
            d = None
        paths.append(key)
        dims.append(d)
    return paths, dims


def zero3_grad_reduce(pgrads, params, model, name="step"):
    """Reduce per-rdp-slice partial grads into rdp-sharded grads.

    ``pgrads`` leaves carry a leading [rdp] slice axis (vmapped grads of
    the per-slice losses). Sharded params' partials are packed shard-major
    into ``zero3_bucket_mb``-byte buckets and reduced with ONE
    ``psum_scatter`` (a real reduce-scatter instruction) per bucket inside
    a full-manual shard_map region; replicated (persistent) params'
    partials sum over the slice axis (GSPMD lowers the cross-shard sum to
    an all-reduce, exactly DDP's bucketing story). The result is divided
    by rdp — the per-microbatch gradient is the MEAN of the slice
    gradients, matching the plain path's mean-over-batch loss.
    """
    from smdistributed_modelparallel_tpu.utils.jax_compat import shard_map
    from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

    cfg = state.cfg
    mesh = state.mesh
    rdp = rdp_size()
    bucket_bytes = int(cfg.zero3_bucket_mb) * (1 << 20)

    paths, shard_dims = _grad_layout(params, model)
    g_leaves, g_def = jax.tree_util.tree_flatten(pgrads)
    p_leaves = jax.tree_util.tree_leaves(params)

    # Pin the partials' slice axis over rdp: each device holds exactly its
    # own slice's partial sums, so the shard_map in_specs below are a
    # layout no-op, not a reshard.
    def pin_partial(g):
        spec = [None] * g.ndim
        spec[0] = RDP_AXIS
        return jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, P(*spec))
        )

    g_leaves = [pin_partial(g) for g in g_leaves]

    rs_idx = [
        i for i, d in enumerate(shard_dims)
        if d is not None and p_leaves[i].size > 0
    ]
    sum_idx = [i for i in range(len(g_leaves)) if i not in rs_idx]

    # Greedy bucket fill, program (layer) order — reverse order would
    # micro-optimize the backward's tail, but grads arrive per-microbatch
    # here, and XLA schedules within the bucket anyway. Sized by the
    # PARTIAL-GRAD dtype (bf16 under half compute), not the fp32 master
    # params — the knob bounds the actual collective payload.
    buckets, cur, cur_bytes = [], [], 0
    for i in rs_idx:
        nbytes = int(p_leaves[i].size) * g_leaves[i].dtype.itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)

    out_leaves = [None] * len(g_leaves)

    for bucket in buckets:
        dims = [shard_dims[i] for i in bucket]
        shapes = [tuple(p_leaves[i].shape) for i in bucket]

        def body(*locals_, _dims=tuple(dims), _shapes=tuple(shapes)):
            # locals_[k]: this device's partial for bucket leaf k, full
            # param shape (the [rdp] slice axis is manual -> local [1,...]).
            flats, meta = [], []
            for g, d, s in zip(locals_, _dims, _shapes):
                gl = jnp.moveaxis(g[0], d, 0)        # shard dim leading
                rest = gl.shape[1:]
                flats.append(gl.reshape(rdp, -1))    # shard-major blocks
                meta.append((d, s[d] // rdp, rest, flats[-1].shape[1]))
            flat = (
                flats[0] if len(flats) == 1
                else jnp.concatenate(flats, axis=1)
            )
            reduced = jax.lax.psum_scatter(
                flat, RDP_AXIS, scatter_dimension=0, tiled=False
            )
            outs, off = [], 0
            for d, rows, rest, width in meta:
                piece = reduced[off:off + width].reshape((rows,) + rest)
                outs.append(jnp.moveaxis(piece, 0, d))
                off += width
            return tuple(outs)

        in_specs = tuple(
            P(*([RDP_AXIS] + [None] * p_leaves[i].ndim)) for i in bucket
        )
        out_specs = tuple(
            P(*(
                [None] * shard_dims[i] + [RDP_AXIS]
                + [None] * (p_leaves[i].ndim - shard_dims[i] - 1)
            ))
            for i in bucket
        )
        reduced = shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )(*(g_leaves[i] for i in bucket))
        for i, r in zip(bucket, reduced):
            out_leaves[i] = r

    for i in sum_idx:
        # Replicated param: plain cross-slice sum; GSPMD lowers the
        # sharded-axis reduction to an rdp all-reduce.
        out_leaves[i] = jnp.sum(g_leaves[i], axis=0)

    inv = 1.0 / rdp
    out_leaves = [
        (g * jnp.asarray(inv, g.dtype)) for g in out_leaves
    ]

    scatter_bytes = sum(
        int(p_leaves[i].size) * g_leaves[i].dtype.itemsize for i in rs_idx
    )
    telemetry.gauge(
        "smp_zero3_buckets",
        "gradient reduce-scatter buckets per microbatch under zero3",
    ).labels(step=name).set(len(buckets))
    telemetry.gauge(
        "smp_zero3_bucket_bytes",
        "logical gradient bytes entering reduce-scatter buckets per "
        "microbatch under zero3",
    ).labels(step=name).set(scatter_bytes)
    telemetry.gauge(
        "smp_zero3_sharded_params",
        "parameter leaves rdp-sharded under zero3",
    ).labels(step=name).set(len(rs_idx))
    telemetry.gauge(
        "smp_zero3_persistent_params",
        "parameter leaves kept replicated (persistence threshold / no "
        "divisible dim) under zero3",
    ).labels(step=name).set(len(sum_idx))
    return jax.tree_util.tree_unflatten(g_def, out_leaves)


def zero3_outputs_mergeable(plain_out, sliced_out, rdp):
    """Whether the user fn's outputs survive the slice-vmap round trip
    exactly: leaf-wise, the per-slice output must be the per-microbatch
    output with its LEADING dim divided by rdp (merged back losslessly by
    ``zero3_merge_outputs``), or a scalar in both (averaged — the mean
    contract). Anything else — batch on a later axis, shapes that do not
    scale — cannot be reassembled without guessing, so the step engine
    falls back to the GSPMD gradient path where outputs are untouched."""
    p_leaves = jax.tree_util.tree_leaves(plain_out)
    s_leaves = jax.tree_util.tree_leaves(sliced_out)
    if len(p_leaves) != len(s_leaves):
        return False
    for p, s in zip(p_leaves, s_leaves):
        ps = getattr(p, "shape", None)
        ss = getattr(s, "shape", None)
        if ps is None or ss is None:
            if ps != ss:
                return False
            continue
        if ps == () and ss == ():
            continue
        if (len(ps) == len(ss) and ps[1:] == ss[1:] and ss[0] * rdp == ps[0]
                and ps[0] > 0):
            continue
        return False
    return True


def zero3_merge_outputs(out):
    """Undo the vmapped forward's leading [rdp] slice axis on the user's
    per-microbatch outputs. The step engine's output-shape probe
    (``zero3_outputs_mergeable``) already guaranteed every array leaf's
    leading dim scales by rdp under slicing, so the merge is the exact
    inverse of the batch reshape; per-slice scalars (vmapped to [rdp])
    average, matching the mean-loss contract."""
    def merge(leaf):
        if leaf.ndim >= 2:
            return leaf.reshape((-1,) + leaf.shape[2:])
        return jnp.mean(leaf, axis=0) if leaf.ndim == 1 else leaf

    return jax.tree_util.tree_map(merge, out)


# ----------------------------------------------------------------------
# ZeRO-3: double-buffered just-in-time layer gather (PR-5 transfer
# registers, lifted from the pipeline executors' stage-boundary trick)
# ----------------------------------------------------------------------


def prefetch_knob():
    """Normalized SMP_ZERO3_PREFETCH value ("on"/"off") — the prefetch
    and lifted-scan programs differ at identical shapes, so this knob is
    part of the step cache key and the exec-cache knob facts."""
    raw = os.environ.get(PREFETCH_ENV, "1").lower()
    return "off" if raw in ("0", "off", "false") else "on"


def zero3_prefetch_active():
    """Whether scanned-layer models should run the double-buffered gather
    scan: zero3 on, rdp nontrivial, no pipeline (pp executors own the
    layer loop there), and not disabled via SMP_ZERO3_PREFETCH=0."""
    cfg = state.cfg
    if cfg is None or not cfg.zero3_enabled:
        return False
    if cfg.pipeline_parallel_degree > 1:
        return False
    if prefetch_knob() == "off":
        return False
    mesh = state.mesh
    return mesh is not None and mesh.shape[RDP_AXIS] > 1


def gathered_slice_specs(stacked_params, path_prefix):
    """Gather-target specs for one layer's params sliced from a stacked
    [num_layers, ...] tree: the registered spec minus the leading stack
    dim, with rdp stripped (the compute layout — pp/tp axes, were any
    present, survive)."""
    mm = state.module_manager
    mesh = state.mesh

    def spec_of(path, leaf):
        key = path_key(path)
        if path_prefix:
            key = f"{path_prefix}/{key}"
        spec = list(mm.spec_for(key, leaf))
        spec += [None] * (getattr(leaf, "ndim", 0) - len(spec))
        return NamedSharding(mesh, strip_rdp(P(*spec[1:])))

    return jax.tree_util.tree_map_with_path(spec_of, stacked_params)


@jax.custom_vjp
def _issue_before(nxt, h):
    """Optimization barrier tying the NEXT layer's gathered params to the
    current layer's input: XLA cannot sink the prefetch gather below the
    compute that consumes ``h``, so the gather issues while the current
    layer's dots run (the PR-5 'park in transfer registers' ordering).
    Identity on both operands; the barrier stays out of the transpose
    program (the backward re-gathers at use instead)."""
    return jax.lax.optimization_barrier((nxt, h))


def _issue_fwd(nxt, h):
    return _issue_before(nxt, h), None


def _issue_bwd(_, ct):
    return ct


_issue_before.defvjp(_issue_fwd, _issue_bwd)


def zero3_prefetch_scan(apply_layer, h, stacked_params, num_layers,
                        gather_specs):
    """Scan ``apply_layer(h, layer_params) -> h`` over a stacked layer
    tree with the next layer's all-gather double-buffered under the
    current layer's compute.

    Transfer registers in the scan carry hold layer i+1's GATHERED params
    (issued at tick i behind an optimization barrier) next to their
    sharded slice; the backward never sees the gathered register — a
    custom-vjp layer saves only the sharded slice and REGATHERS (plus
    recomputes the layer, standard FSDP-with-remat pairing) in the
    transpose loop, so per-device live gathered params stay at two layers
    in forward and one in backward.
    """
    from smdistributed_modelparallel_tpu.utils.jax_compat import (
        ensure_optimization_barrier_rules,
    )

    ensure_optimization_barrier_rules()

    def gather(tree):
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, tree, gather_specs
        )

    def slice_at(i):
        return jax.tree_util.tree_map(
            lambda w: jax.lax.dynamic_index_in_dim(w, i, 0, keepdims=False),
            stacked_params,
        )

    @jax.custom_vjp
    def run_layer(hh, reg, reg_shard):
        return apply_layer(hh, reg)

    def _run_fwd(hh, reg, reg_shard):
        return apply_layer(hh, reg), (hh, reg_shard)

    def _run_bwd(res, ct):
        hh, reg_shard = res
        w = gather(reg_shard)
        _, vjp = jax.vjp(apply_layer, hh, w)
        dh, dw = vjp(ct)
        # The gathered register's cotangent routes back through the carry
        # chain to the previous tick's gather, whose VJP is the
        # partial-sum -> rdp-sharded reshard of the stacked param grads;
        # the sharded slice itself contributed no forward value.
        return dh, dw, jax.tree_util.tree_map(jnp.zeros_like, reg_shard)

    run_layer.defvjp(_run_fwd, _run_bwd)

    s0 = slice_at(0)
    reg0 = gather(s0)

    def body(carry, i):
        hh, reg, reg_shard = carry
        nxt_shard = slice_at(jnp.minimum(i + 1, num_layers - 1))
        nxt = gather(nxt_shard)
        nxt, hh = _issue_before(nxt, hh)
        hh = run_layer(hh, reg, reg_shard)
        return (hh, nxt, nxt_shard), None

    (h, _, _), _ = jax.lax.scan(
        body, (h, reg0, s0), jnp.arange(num_layers)
    )
    return h
