"""Optimizer-state sharding (ZeRO-1) and sharded data parallelism (ZeRO-3).

Parity target: reference ``shard_optimizer_state`` (contiguous buffer +
virtual params, ``torch/model.py:1237-1340``,
``torch/optimizers/optimizer.py:355-391``) and "ZeRO-2D" sharded DP
(DeepSpeed stage-3 fork, ``backend/zero_config.py``). On TPU both reduce to
PartitionSpecs: optimizer-state leaves (and, for sharded DP, parameters)
are sharded over the rdp axis on their largest divisible dimension; XLA
emits the reduce-scatter / allgather traffic the reference implements by
hand. Completed in M4; M1 ships the spec machinery with pp=tp=1 paths.
"""

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import RDP_AXIS
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()


def shard_spec_for_leaf(leaf, rdp_size, persistence_threshold=0):
    """Spec sharding a tensor over rdp on its first divisible dim, or None."""
    shape = getattr(leaf, "shape", ())
    if rdp_size <= 1 or not shape:
        return None
    if int(np.prod(shape)) < persistence_threshold:
        return None
    for i, dim in enumerate(shape):
        if dim % rdp_size == 0:
            spec = [None] * len(shape)
            spec[i] = RDP_AXIS
            return P(*spec)
    return None


def opt_state_shardings(opt_state, model):
    """Shardings for the optimizer-state pytree under shard_optimizer_state.

    Moment vectors mirror their parameter's sharding, additionally sharded
    over rdp. Returns None when sharding is disabled (state replicated).
    """
    cfg = state.cfg
    if not (cfg.shard_optimizer_state or cfg.zero2d_enabled):
        return None
    mesh = state.mesh
    rdp_size = mesh.shape[RDP_AXIS]
    threshold = cfg.sdp_param_persistence_threshold if cfg.zero2d_enabled else 0

    def leaf_sharding(leaf):
        spec = shard_spec_for_leaf(leaf, rdp_size, threshold)
        return NamedSharding(mesh, spec if spec is not None else P())

    return jax.tree_util.tree_map(leaf_sharding, opt_state)
