"""Cost-model auto-partitioner for pipeline stage assignment.

Parity target: reference ``torch/module_partition.py:182-905``
(``ModulePartitioner``). Reimplemented algorithms (clean-room, from the
surveyed behavior):

- cost model: cost(node) = memory_weight * normalized_memory +
  (1 - memory_weight) * normalized_time, where memory is
  3*param_bytes + activation_bytes (params+grads+opt-ish weighting as in the
  reference) and time is a traced/estimated execution time quantized to 100
  levels (``populate_cost`` / ``normalize_costs``,
  reference ``module_partition.py:488-569``);
- segmentation: children of a node are split into contiguous segments
  minimizing the maximum segment cost (DP, reference ``get_segments``
  ``:837-904``);
- device allocation: stages are allocated to segments by the d'Hondt
  highest-averages method proportionally to segment cost (reference
  ``dhondt_allocate`` ``:788-835``);
- recursion: each segment with >1 allocated stage is recursively split over
  its own children (BFS over the tree, reference ``partition_nodes``
  ``:331-381``).

Under the SPMD executor only *contiguous uniform* layer splits are runnable
(``parallel/pipeline.py``); this module is the general assignment engine —
used to validate/report assignments, honor manual ``smp.set_partition``
pins, and choose the contiguous boundaries when layer costs are uneven.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError
from smdistributed_modelparallel_tpu.utils.logger import get_logger

logger = get_logger()

TIME_QUANT_LEVELS = 100


@dataclass
class ModuleNode:
    """A partitionable unit (module or group of modules sharing params)."""

    name: str
    param_bytes: float = 0.0
    activation_bytes: float = 0.0
    time: float = 0.0
    children: List["ModuleNode"] = field(default_factory=list)
    cost: float = 0.0  # filled by populate_costs

    def subtree_sum(self, attr):
        return getattr(self, attr) + sum(c.subtree_sum(attr) for c in self.children)


def populate_costs(root, memory_weight):
    """Normalized blended cost per node (reference ``populate_cost`` /
    ``normalize_costs``)."""
    total_mem = root.subtree_sum("param_bytes") * 3 + root.subtree_sum("activation_bytes")
    total_time = root.subtree_sum("time")

    def mem(node):
        return 3 * node.param_bytes + node.activation_bytes

    def quantized_time(node):
        if total_time <= 0:
            return 0.0
        q = round(node.time / total_time * TIME_QUANT_LEVELS) / TIME_QUANT_LEVELS
        return q

    def visit(node):
        m = mem(node) / total_mem if total_mem > 0 else 0.0
        node.cost = memory_weight * m + (1.0 - memory_weight) * quantized_time(node)
        for c in node.children:
            visit(c)

    visit(root)
    return root


def subtree_cost(node):
    return node.cost + sum(subtree_cost(c) for c in node.children)


def dhondt_allocate(num_devices, costs):
    """Allocate num_devices proportionally to costs (d'Hondt highest
    averages). Every segment with positive cost gets at least one device if
    possible; returns a list of allocations summing to num_devices."""
    n = len(costs)
    alloc = [0] * n
    if n == 0:
        return alloc
    for _ in range(num_devices):
        best, best_q = 0, -1.0
        for i, c in enumerate(costs):
            q = c / (alloc[i] + 1)
            if q > best_q:
                best, best_q = i, q
        alloc[best] += 1
    return alloc


def min_max_segments(costs, k):
    """Split `costs` into at most k contiguous segments minimizing the max
    segment sum. Returns list of (start, end) half-open ranges.

    DP over (i, j): best achievable max-cost splitting the first i items
    into j segments (reference ``get_segments``).
    """
    n = len(costs)
    k = min(k, n)
    if n == 0:
        return []
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    INF = float("inf")
    best = [[INF] * (k + 1) for _ in range(n + 1)]
    cut = [[0] * (k + 1) for _ in range(n + 1)]
    best[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(1, n + 1):
            for s in range(j - 1, i):
                seg = prefix[i] - prefix[s]
                cand = max(best[s][j - 1], seg)
                if cand < best[i][j]:
                    best[i][j] = cand
                    cut[i][j] = s
    # Choose the smallest number of segments achieving the optimum at k.
    segments = []
    i, j = n, k
    while j > 0:
        s = cut[i][j]
        segments.append((s, i))
        i, j = s, j - 1
    segments.reverse()
    # Drop degenerate empty segments (possible when k > n).
    return [(a, b) for a, b in segments if b > a]


def min_max_segments_pinned(costs, k, pins):
    """Split `costs` into exactly k contiguous (possibly empty) segments
    minimizing the max segment sum, subject to pins {item_index: segment}.

    Used for manual ``smp.set_partition`` layer pins: the pinned layer must
    land in its pinned stage while the rest of the boundary placement stays
    cost-optimal. Returns k (start, end) half-open ranges covering [0, n).
    """
    n = len(costs)
    for i, s in pins.items():
        if not (0 <= s < k):
            raise PartitionError(f"Pin {i}->{s} out of range [0, {k}).")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def feasible(a, b, seg):
        """Items [a, b) may live in segment `seg`: every pinned item inside
        is pinned to `seg`, and no item pinned to `seg` lies outside later
        handling (checked globally by the DP structure)."""
        for i in range(a, b):
            if i in pins and pins[i] != seg:
                return False
        return True

    INF = float("inf")
    # best[i][j]: minimized max-cost covering the first i items with the
    # first j segments, all pins among them satisfied.
    best = [[INF] * (k + 1) for _ in range(n + 1)]
    cut = [[-1] * (k + 1) for _ in range(n + 1)]
    best[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(0, n + 1):
            for s in range(0, i + 1):
                if best[s][j - 1] == INF:
                    continue
                if not feasible(s, i, j - 1):
                    continue
                # Items pinned to segment j-1 must not remain beyond i.
                if any(pins.get(t) == j - 1 for t in range(i, n)):
                    continue
                seg_cost = prefix[i] - prefix[s]
                cand = max(best[s][j - 1], seg_cost)
                if cand < best[i][j]:
                    best[i][j] = cand
                    cut[i][j] = s
    if best[n][k] == INF:
        raise PartitionError(
            f"No contiguous {k}-stage split satisfies pins {pins}: pins must "
            "be non-decreasing in layer order."
        )
    segments = []
    i, j = n, k
    while j > 0:
        s = cut[i][j]
        segments.append((s, i))
        i, j = s, j - 1
    segments.reverse()
    return segments


class ModulePartitioner:
    """Assign pipeline stages to a module-cost tree.

    Args:
      root: ModuleNode tree (costs not yet normalized).
      num_stages: pipeline_parallel_degree.
      memory_weight: blend factor (config ``memory_weight``).
      manual: dict name -> stage pins (``smp.set_partition``).
    """

    def __init__(self, root, num_stages, memory_weight=0.8, manual=None):
        self.root = root
        self.num_stages = num_stages
        self.memory_weight = memory_weight
        self.manual = dict(manual or {})

    def partition(self):
        populate_costs(self.root, self.memory_weight)
        assignment = {}
        # BFS: (node, stage_set) — a node with one stage pins its whole
        # subtree; multiple stages recurse over children.
        queue = [(self.root, list(range(self.num_stages)))]
        while queue:
            node, stages = queue.pop(0)
            if node.name in self.manual:
                stages = [self.manual[node.name]]
            if len(stages) == 1 or not node.children:
                self._assign_subtree(node, stages[0], assignment)
                continue
            assignment[node.name] = stages[0]
            child_costs = [subtree_cost(c) for c in node.children]
            segments = min_max_segments(child_costs, len(stages))
            allocs = dhondt_allocate(
                len(stages),
                [sum(child_costs[a:b]) for a, b in segments],
            )
            pos = 0
            for (a, b), count in zip(segments, allocs):
                seg_stages = stages[pos:pos + count]
                pos += count
                if not seg_stages:
                    seg_stages = [stages[min(pos, len(stages) - 1)]]
                for child in node.children[a:b]:
                    queue.append((child, seg_stages))
        return assignment

    def _assign_subtree(self, node, stage, assignment):
        assignment[node.name] = stage
        for c in node.children:
            self._assign_subtree(c, stage, assignment)


def uniform_layer_boundaries(layer_costs, num_stages):
    """Contiguous stage boundaries over a layer sequence minimizing max
    stage cost — used by the pipeline executor when layer costs are uneven
    but a contiguous split is required."""
    segments = min_max_segments(layer_costs, num_stages)
    if len(segments) != num_stages:
        # pad by splitting the largest segments is overkill; fall back to even
        n = len(layer_costs)
        per = n // num_stages
        segments = [(i * per, (i + 1) * per) for i in range(num_stages)]
        segments[-1] = (segments[-1][0], n)
    return segments
