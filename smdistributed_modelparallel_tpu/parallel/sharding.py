"""Sharding helpers: batch specs, data axes, replication.

TPU-native core with no single reference counterpart: encodes where the
reference's implicit "each rank gets its own batch shard" placement
(``backend/split.py`` + per-rank data loaders) becomes explicit
PartitionSpecs over the mesh.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.topology import (
    CP_AXIS,
    EP_AXIS,
    RDP_AXIS,
    TP_AXIS,
)


def data_axes(cfg):
    """Mesh axes across which distinct batch elements live.

    Parity: reference dp = tp x rdp (``backend/core.py:49-55``) — each GPU
    gets its own batch unless ``prescaled_batch``; ep/cp are TPU extensions
    carved from the data dimension (cp shards sequence, not batch, so it is
    excluded here and applied to the sequence axis).
    """
    axes = [RDP_AXIS, EP_AXIS]
    if cfg.tensor_parallel_degree > 1 and not cfg.prescaled_batch:
        axes.append(TP_AXIS)
    return tuple(axes)


def batch_spec(cfg, ndim, batch_axis=0, stacked=False):
    """PartitionSpec for a batch array: batch dim over data axes, sequence
    dim over cp (if enabled), everything else replicated.

    With ``stacked=True`` the array carries a leading [num_microbatches]
    axis (never sharded) and `batch_axis` refers to the post-stack layout.
    """
    spec = [None] * ndim
    offset = 1 if stacked else 0
    spec_batch = batch_axis + offset
    if spec_batch < ndim:
        spec[spec_batch] = data_axes(cfg)
    if cfg.context_parallel_degree > 1 and spec_batch + 1 < ndim:
        spec[spec_batch + 1] = CP_AXIS
    return P(*spec)


def replicated(mesh):
    return NamedSharding(mesh, P())


def named(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


def single_axis_spec(ndim, dim, axis):
    """PartitionSpec naming one mesh axis on one dim of an ndim-rank
    value, everything else replicated — the inverse building block of
    ``strip_axis``. Shared by the tp-overlap ring regions
    (``ops/collective_matmul.py``: sequence/feature block specs) and the
    fused bias+GELU tp wrapper (``nn/utils.py``)."""
    return P(*(axis if d == dim else None for d in range(ndim)))


def strip_axis(spec, axis):
    """PartitionSpec with every occurrence of one mesh axis removed —
    the "gathered over that axis" layout of a sharded value. Shared by
    the decode regather (strip pp, ``model.regather_for_decode``) and
    ZeRO-3's just-in-time param gathers (strip rdp,
    ``parallel/zero.strip_rdp``)."""
    def drop(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a != axis)
            return kept if kept else None
        return None if entry == axis else entry

    return P(*(drop(a) for a in spec))
