"""Sharding helpers: batch specs, data axes, replication.

TPU-native core with no single reference counterpart: encodes where the
reference's implicit "each rank gets its own batch shard" placement
(``backend/split.py`` + per-rank data loaders) becomes explicit
PartitionSpecs over the mesh.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from smdistributed_modelparallel_tpu.backend.topology import (
    CP_AXIS,
    EP_AXIS,
    RDP_AXIS,
    TP_AXIS,
)


def data_axes(cfg):
    """Mesh axes across which distinct batch elements live.

    Parity: reference dp = tp x rdp (``backend/core.py:49-55``) — each GPU
    gets its own batch unless ``prescaled_batch``; ep/cp are TPU extensions
    carved from the data dimension (cp shards sequence, not batch, so it is
    excluded here and applied to the sequence axis).
    """
    axes = [RDP_AXIS, EP_AXIS]
    if cfg.tensor_parallel_degree > 1 and not cfg.prescaled_batch:
        axes.append(TP_AXIS)
    return tuple(axes)


def batch_spec(cfg, ndim, batch_axis=0, stacked=False):
    """PartitionSpec for a batch array: batch dim over data axes, sequence
    dim over cp (if enabled), everything else replicated.

    With ``stacked=True`` the array carries a leading [num_microbatches]
    axis (never sharded) and `batch_axis` refers to the post-stack layout.
    """
    spec = [None] * ndim
    offset = 1 if stacked else 0
    spec_batch = batch_axis + offset
    if spec_batch < ndim:
        spec[spec_batch] = data_axes(cfg)
    if cfg.context_parallel_degree > 1 and spec_batch + 1 < ndim:
        spec[spec_batch + 1] = CP_AXIS
    return P(*spec)


def replicated(mesh):
    return NamedSharding(mesh, P())


def named(mesh, *spec):
    return NamedSharding(mesh, P(*spec))
