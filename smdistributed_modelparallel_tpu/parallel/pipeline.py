"""Pipeline parallelism: compiled SPMD microbatch pipelining.

Parity target: reference pipeline subsystem — ``torch/pipeline.py:24-145``
(microbatch state machine), ``torch/server.py`` (the MPMD event loop that
*creates* pipelining by task ordering), ``active_microbatches`` windowing.

TPU-native re-design (SURVEY §7-M2): the pipeline is not a server loop but a
``lax.scan`` over ticks inside the one compiled step:

- layer parameters live stacked with a leading ``[num_layers]`` axis (the
  model builds them with ``flax.linen.scan``), resharded per-stage as
  ``[S, layers_per_stage, ...]`` with the stage axis on the mesh's ``pp``
  axis;
- each tick ``vmap``s the stage body over the stage axis — GSPMD partitions
  the vmapped computation so each device executes only its own stage — and
  shifts the carry buffer one stage forward with ``jnp.roll`` on the
  pp-sharded axis, which XLA lowers to a collective-permute over ICI (the
  reference's NCCL P2P "links", SURVEY §2.1 N3);
- stage 0 consumes microbatch ``t`` at tick ``t``; the last stage emits
  microbatch ``t - (S-1)``; total ticks = num_microbatches + S - 1;
- backward is JAX AD through the tick scan (reverse-time pipeline). Both
  ``pipeline: simple`` and ``interleaved`` lower to this schedule; the
  interleaved memory advantage is recovered with per-layer rematerialization
  (``jax.checkpoint``) rather than schedule reordering.

Models opt in by exposing ``pipeline_spec()`` (see ``PipelineSpec``); the
``smp.nn`` transformer family and the model zoo implement it. Non-layered
modules cannot be pipelined under SPMD and raise a clear error.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS
from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.profiling import named_region

logger = get_logger()


@dataclass
class PipelineSpec:
    """How a module decomposes into embed -> repeated layer -> head.

    Attributes:
      layer_path: '/'-joined path of the parameter subtree whose leaves carry
        a leading [num_layers] axis (built with ``flax.linen.scan``).
      num_layers: total layer count L (must be divisible by pp_degree).
      layer_module: unbound flax module for ONE layer; applied per-slice
        during pipelining.
      embed_method / head_method: method names on the root module computing
        the pre-layer carry and the post-layer output. Both may use any
        non-layer parameters (they run replicated across stages; their
        parameters stay replicated on the pp axis). ``None`` = identity
        (the module IS the layer stack, e.g. DistributedTransformer).
      carry_remat: rematerialize each layer application (activation
        checkpointing inside the pipeline).
      layer_xs: optional pytree of stacked [num_layers, ...] per-layer
        inputs threaded into each layer application (e.g. layer_idx,
        is_local for GPT-Neo alternating attention).
      carry_is_tuple: carry is (hidden, cross_states, attention_mask) and
        the layer takes them as separate arguments (the smp.nn transformer
        family's calling convention).
    """

    layer_path: str
    num_layers: int
    layer_module: Any
    embed_method: Optional[str] = "embed"
    head_method: Optional[str] = "head"
    carry_remat: bool = False
    layer_xs: Any = None
    carry_is_tuple: bool = False
    layer_costs: Optional[list] = None   # per-layer relative time costs
    boundaries: Optional[list] = None    # [(start, end)] per chunk (filled
                                         # by partition_for_pipeline; one
                                         # entry per stage at v=1, pp*v
                                         # entries under virtual stages)
    virtual_degree: int = 1              # chunks per stage (Megatron-style
                                         # interleaved virtual pipeline)


def get_pipeline_spec(module):
    fn = getattr(module, "pipeline_spec", None)
    if fn is None:
        return None
    return fn() if callable(fn) else fn


def partition_for_pipeline(model):
    """Produce the stage assignment for a pipelineable model.

    Stage boundaries come from the cost-model partitioner
    (``parallel/module_partition.py`` — the reference's d'Hondt/min-max
    engine, ``torch/module_partition.py:182-905``) over per-layer costs
    (parameter bytes blended with time costs by ``memory_weight``).
    Manual ``smp.set_partition("<layer_path>#<i>", stage)`` pins constrain
    the boundaries. Non-uniform per-stage layer counts are supported — the
    executors pad stages to the max count with masked slots.
    """
    cfg = state.cfg
    pp = cfg.pipeline_parallel_degree
    virtual = int(getattr(cfg, "virtual_pipeline_degree", 1) or 1)
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    root = unwrap_hooks(model.module)
    spec = get_pipeline_spec(root)
    if spec is None:
        raise PartitionError(
            "pipeline_parallel_degree > 1 requires a pipelineable model: one "
            "exposing pipeline_spec() (smp.nn.DistributedTransformer* and the "
            "smp model zoo do). Arbitrary module graphs cannot be pipelined "
            "under SPMD."
        )
    L = spec.num_layers
    nchunks = pp * virtual
    if L < nchunks:
        raise PartitionError(
            f"num_layers={L} < pipeline_parallel_degree * "
            f"virtual_pipeline_degree = {pp} * {virtual} = {nchunks}: at "
            "least one layer per chunk is required."
        )
    if virtual > 1:
        # Chunked stage assignments are non-contiguous along the layer
        # sequence (chunk c -> stage c % pp), which the manual-partition
        # surfaces cannot express: each would silently produce a layout
        # the executor rejects, so fail with intent up front.
        mm = model.module_manager
        pinned = [
            p for p in mm.get_manual_partitions()
            if p.startswith(spec.layer_path + "#")
        ]
        if pinned or not cfg.auto_partition or cfg.load_partition:
            raise PartitionError(
                "virtual_pipeline_degree > 1 is incompatible with manual "
                "layer pins, auto_partition: False, and load_partition: the "
                "interleaved chunk placement (chunk c on stage c % pp) is "
                "not a contiguous stage assignment."
            )
    # Honor activation-checkpoint configs inside the pipeline: the stacked
    # executor applies layers directly (not via the module's own scan), so
    # the remat lives on the executor's layer application.
    if not spec.carry_remat:
        mm = model.module_manager
        if getattr(root, "activation_checkpointing", False):
            spec.carry_remat = True
        else:
            for prefix in mm.checkpoint_configs:
                if prefix == "" or spec.layer_path.startswith(prefix):
                    spec.carry_remat = True
                    break

    # One contiguous cost-balanced range per CHUNK; chunk c executes on
    # stage c % pp (at v=1 a chunk IS a stage, so this is the old layout).
    spec.virtual_degree = virtual
    spec.boundaries = _choose_boundaries(model, spec, nchunks)
    assignment = {}
    for c, (a, b) in enumerate(spec.boundaries):
        for layer in range(a, b):
            assignment[f"{spec.layer_path}#{layer}"] = c % pp
    model._pipeline_spec = spec
    model.module_manager.register_spec_provider(
        layer_param_sharding_provider(spec), name="pipeline_layers"
    )
    if virtual > 1:
        logger.info(
            "Pipeline partition: %d layers -> %d stages x %d virtual "
            "chunks %s.",
            L, pp, virtual, [b - a for a, b in spec.boundaries],
        )
    else:
        logger.info(
            "Pipeline partition: %d layers -> %d stages %s.",
            L, pp, [b - a for a, b in spec.boundaries],
        )
    return assignment


def _layer_cost_inputs(model, spec):
    """(param_bytes_per_layer, time_cost_per_layer) for the cost model.

    Parameter bytes come from the materialized stacked layer subtree
    (shapes are concrete by partition time). Time costs: declared
    ``spec.layer_costs`` first; otherwise, for heterogeneous stacks
    (distinct per-layer xs, e.g. GPT-Neo local/global alternation), each
    distinct layer variant is MEASURED with a one-time timed run on the
    current device — the reference's 5-trial timed trace
    (``torch/patches/tracing.py:41-86``, ``torch/module_manager.py:
    435-499``); homogeneous stacks stay uniform. ``skip_tracing`` disables
    the measurement.
    """
    L = spec.num_layers
    params = model._params
    pbytes = 0.0
    if params is not None:
        try:
            sub = _get_subtree(params, spec.layer_path)
            pbytes = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(sub)
            ) / max(L, 1)
        except (KeyError, TypeError):
            pbytes = 0.0
    times = list(spec.layer_costs) if spec.layer_costs else None
    if times is None:
        times = _measured_layer_times(model, spec)
    if times is None:
        times = [1.0] * L
    if len(times) != L:
        raise PartitionError(
            f"pipeline_spec.layer_costs has {len(times)} entries for "
            f"{L} layers."
        )
    return [pbytes] * L, times


# Test hook: callable(sig, fn, args) -> seconds, replacing the wall-clock
# timer (CPU test tiers can't observe kernel-level cost differences).
_LAYER_TIMER = None


def _time_call(sig, fn, *args):
    import time

    import numpy as np

    if _LAYER_TIMER is not None:
        return _LAYER_TIMER(sig, fn, args)

    def run():
        out = fn(*args)
        leaf = jax.tree_util.tree_leaves(out)[0]
        if getattr(leaf, "is_fully_addressable", True):
            # Force completion with a readback (block_until_ready is not
            # reliable through tunneled TPU transports).
            np.asarray(leaf).ravel()[:1]
        else:
            # Multi-host sharded output: a cross-process readback would
            # raise; completion-wait is the best available fence.
            jax.block_until_ready(leaf)

    run()  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def _measured_layer_times(model, spec):
    """Per-layer time costs measured per distinct xs variant, or None when
    measurement is off / impossible / pointless (homogeneous stack)."""
    import numpy as np

    cfg = state.cfg
    if cfg is None or cfg.skip_tracing or spec.layer_xs is None:
        return None
    if model._params is None:
        return None
    xs_np = {k: np.asarray(v) for k, v in spec.layer_xs.items()}
    keys = sorted(k for k in xs_np if k != "layer_idx")
    if not keys:
        return None
    L = spec.num_layers
    sigs = [tuple(xs_np[k][i].item() for k in keys) for i in range(L)]
    if len(set(sigs)) < 2:
        return None
    D = getattr(spec.layer_module, "hidden_size", None) or getattr(
        spec.layer_module, "d_model", None
    )
    if not D:
        return None
    try:
        sub = _get_subtree(model._params, spec.layer_path)
    except (KeyError, TypeError):
        return None
    lp = jax.tree_util.tree_map(lambda a: a[0], sub)
    T = int(getattr(spec.layer_module, "causal_mask_size", None) or 128)
    T = max(8, min(T, 512))
    x = jnp.zeros((2, T, D), jnp.float32)
    rngs = {"dropout": jax.random.key(0)}

    times_by_sig = {}
    # Only process 0 measures: its timings win the broadcast below anyway,
    # so peer processes skip the per-variant compiles + timed device runs
    # (at pod scale that is real init-critical-path work thrown away).
    if jax.process_index() == 0:
        for sig in sorted(set(sigs)):
            xs_one = {k: jnp.asarray(v) for k, v in zip(keys, sig)}
            if "layer_idx" in xs_np:
                xs_one["layer_idx"] = jnp.asarray(0, jnp.int32)

            def fn(lp, x, _xs=xs_one):
                if spec.carry_is_tuple:
                    return spec.layer_module.apply(
                        {"params": lp}, x, cross_states=None,
                        attention_mask=None, xs=_xs, rngs=rngs,
                    )
                return spec.layer_module.apply(
                    {"params": lp}, x, xs=_xs, rngs=rngs
                )

            times_by_sig[sig] = _time_call(sig, jax.jit(fn), lp, x)
    else:
        times_by_sig = {sig: 0.0 for sig in set(sigs)}
    if jax.process_count() > 1:
        # Multi-controller agreement: every process must derive the SAME
        # boundaries (different stage splits would compile divergent SPMD
        # programs and hang the first collective). Process 0's timings win
        # — the reference broadcasts its trace results the same way
        # (torch/server.py:264).
        from jax.experimental import multihost_utils

        vals = np.asarray([times_by_sig[s] for s in sorted(times_by_sig)])
        vals = multihost_utils.broadcast_one_to_all(vals)
        times_by_sig = dict(zip(sorted(times_by_sig), vals.tolist()))
    logger.info(
        "Measured layer-variant costs: %s",
        {str(k): round(v, 6) for k, v in times_by_sig.items()},
    )
    return [times_by_sig[s] for s in sigs]


def _choose_boundaries(model, spec, pp):
    """Contiguous per-stage layer ranges from costs + manual pins."""
    from smdistributed_modelparallel_tpu.parallel.module_partition import (
        ModuleNode,
        ModulePartitioner,
        min_max_segments_pinned,
    )

    cfg = state.cfg
    L = spec.num_layers
    pbytes, times = _layer_cost_inputs(model, spec)

    pins = {}
    for prefix, stage in model.module_manager.get_manual_partitions().items():
        if prefix.startswith(spec.layer_path + "#"):
            try:
                pins[int(prefix.rsplit("#", 1)[1])] = stage
            except ValueError:
                raise PartitionError(
                    f"Malformed layer pin '{prefix}': expected "
                    f"'{spec.layer_path}#<layer_index>'."
                )
    for idx, stage in pins.items():
        if not (0 <= idx < L):
            raise PartitionError(f"Pinned layer {idx} out of range [0, {L}).")

    if not cfg.auto_partition:
        # Manual partitioning (reference ``auto_partition: False`` +
        # ``default_partition`` semantics, ``backend/config.yaml:150-170``,
        # ``torch/module_manager.py:1061``): every layer goes to
        # ``default_partition`` unless explicitly pinned with
        # smp.set_partition.
        default = cfg.default_partition
        if default is None or not (0 <= default < pp):
            raise PartitionError(
                f"auto_partition: False requires default_partition in "
                f"[0, {pp}) (got {default})."
            )
        stages = [pins.get(i, default) for i in range(L)]
        if any(b < a for a, b in zip(stages, stages[1:])):
            raise PartitionError(
                f"Manual partition produced a non-contiguous stage order "
                f"{stages}; the SPMD executor requires non-decreasing "
                "stage assignments along the layer sequence."
            )
        bounds = []
        start = 0
        for s in range(pp):
            end = start
            while end < L and stages[end] == s:
                end += 1
            if end == start:
                raise PartitionError(
                    f"Manual partition leaves stage {s} empty "
                    f"(stages={stages}); every pipeline stage needs at "
                    "least one layer."
                )
            bounds.append((start, end))
            start = end
        return bounds

    mw = cfg.memory_weight
    total_m = sum(pbytes) or 1.0
    total_t = sum(times) or 1.0
    blended = [
        mw * (m / total_m) + (1.0 - mw) * (t / total_t)
        for m, t in zip(pbytes, times)
    ]
    if pins:
        return min_max_segments_pinned(blended, pp, pins)
    # No pins: run the reference-parity tree partitioner (min-max DP
    # segmentation + d'Hondt stage allocation) over the layer sequence.
    root = ModuleNode(name=spec.layer_path)
    root.children = [
        ModuleNode(name=f"{spec.layer_path}#{i}", param_bytes=pbytes[i],
                   time=times[i])
        for i in range(L)
    ]
    assignment = ModulePartitioner(
        root, pp, memory_weight=mw
    ).partition()
    stages = [assignment[f"{spec.layer_path}#{i}"] for i in range(L)]
    if any(b < a for a, b in zip(stages, stages[1:])):
        raise PartitionError(
            f"Partitioner produced a non-contiguous stage order {stages}; "
            "the SPMD executor requires contiguous stages."
        )
    bounds = []
    start = 0
    for s in range(pp):
        end = start
        while end < L and stages[end] == s:
            end += 1
        bounds.append((start, end))
        start = end
    if start != L:
        raise PartitionError(
            f"Partitioner left layers unassigned (stages={stages})."
        )
    return bounds


def stage_layout(spec, num_stages):
    """(layer_index_grid [S, maxp], active_mask [S, maxp], maxp) for the
    executors. Uniform boundaries collapse to the dense reshape layout."""
    import numpy as np

    bounds = spec.boundaries
    L = spec.num_layers
    if bounds is None:
        per = L // num_stages
        bounds = [(s * per, (s + 1) * per) for s in range(num_stages)]
    maxp = max(b - a for a, b in bounds)
    idx = np.zeros((num_stages, maxp), np.int32)
    active = np.zeros((num_stages, maxp), bool)
    for s, (a, b) in enumerate(bounds):
        n = b - a
        idx[s, :n] = np.arange(a, b)
        active[s, :n] = True
    return idx, active, maxp


def chunk_layout(spec, num_stages, virtual):
    """(layer_index_grid [S, V, maxp], active_mask [S, V, maxp], maxp) for
    the interleaved 1F1B executor: chunk ``c`` of ``spec.boundaries`` sits
    at ``[c % S, c // S]`` (stage, local chunk). The per-chunk grids come
    from ``stage_layout`` over the C = S*V chunk boundaries (one source of
    truth for bounds defaults and padding), re-laid to the interleaved
    placement."""
    C = num_stages * virtual
    if spec.boundaries is not None and len(spec.boundaries) != C:
        raise PartitionError(
            f"pipeline spec has {len(spec.boundaries)} chunk boundaries "
            f"for {num_stages} stages x {virtual} virtual chunks."
        )
    idx, active, maxp = stage_layout(spec, C)   # [C, maxp], chunk order
    shape = (virtual, num_stages, maxp)
    # Row c -> grid[c % S, c // S]: reshape to [V, S, .] and swap.
    return (idx.reshape(shape).transpose(1, 0, 2),
            active.reshape(shape).transpose(1, 0, 2), maxp)


# Stage views are index-gathers over the stacked layer axis only — inner
# dims (tp axes, zero3's rdp shards) ride along with their shardings
# unconstrained, so under ``sharded_params: zero3`` the per-layer rdp
# all-gather stays at each stage's point of use inside the schedule loop
# instead of being hoisted into an upfront whole-model gather. The 1F1B
# executors additionally pin the staged axis (``pin_stage_axis``) with
# UNCONSTRAINED inner dims for the same reason.
def staged_chunk_views(spec, layer_params, num_stages, virtual):
    """Stage the [L, ...] layer stack as ([S, V, maxp, ...] params,
    [S, V, maxp, ...] xs, [S, V, maxp] active mask) for the interleaved
    executor.

    The chunked placement (chunk c -> stage c % S) interleaves the layer
    axis across stages, so unlike the v=1 reshape this is always a gather
    across the even [L] storage sharding — one layer-param reshard per
    step, amortized over all V chunks' compute.
    """
    idx, active, maxp = chunk_layout(spec, num_stages, virtual)
    gidx = jnp.asarray(idx)
    staged_params = jax.tree_util.tree_map(lambda x: x[gidx], layer_params)
    staged_xs = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x)[gidx], spec.layer_xs
    )
    return staged_params, staged_xs, jnp.asarray(active)


def layer_param_sharding_provider(spec):
    """Spec provider: stacked layer params get their leading (layer) axis
    sharded over pp; everything else replicated across pp. When the layer
    count does not divide pp (uneven/padded boundaries) the stack stays
    replicated — the executor's per-stage gather distributes the compute."""
    from jax.sharding import PartitionSpec as P

    prefix = spec.layer_path.strip("/")
    pp = state.cfg.pipeline_parallel_degree if state.cfg else 1

    def provider(path, leaf):
        if path == prefix or path.startswith(prefix + "/"):
            ndim = getattr(leaf, "ndim", 0)
            if ndim >= 1 and leaf.shape[0] % pp == 0:
                return P(PP_AXIS, *([None] * (ndim - 1)))
        return None

    return provider


# ----------------------------------------------------------------------
# Execution
# ----------------------------------------------------------------------


def pipeline_forward(model, params, stacked_inputs, rngs_key, mb_kwargs=None):
    """Run the full pipelined forward for all microbatches.

    Args:
      model: DistributedModel with ``_pipeline_spec`` installed.
      params: full parameter tree; layer subtree leaves have leading [L].
      stacked_inputs: pytree of arrays with leading [num_microbatches] —
        the captured inputs of the user's single ``model(...)`` call.
      rngs_key: PRNG key for dropout etc. (folded per microbatch and layer).

    Returns:
      (stacked outputs with leading [num_microbatches], summed MoE aux loss
      over all microbatches and layers — a 0.0 scalar for MoE-free models).
    """
    spec = model._pipeline_spec
    cfg = state.cfg
    phys_stages = cfg.pipeline_parallel_degree
    virtual = int(getattr(spec, "virtual_degree", 1) or 1)
    # virtual_pipeline_degree > 1 cut the model into pp*v chunks; this
    # executor (forward-only path under the interleaved config) runs them
    # as pp*v sequential logical stages — same math, contiguous [C]
    # staging (chunk i on physical stage i // v). The interleaved chunk
    # placement lives in the 1F1B executor only; telemetry and health
    # below attribute back to PHYSICAL stage + chunk coordinates so
    # operators never see stages that don't exist.
    S = phys_stages * virtual
    num_mb = cfg.microbatches
    L = spec.num_layers
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    layer_module = spec.layer_module

    layer_params = _get_subtree(params, spec.layer_path)

    # embed/head also run with aux collection so an MoE living outside the
    # layer stack keeps its balancing loss under pp (parity with pp=1,
    # where DistributedModel.__call__ collects from the whole module).
    def embed_mb(mb_input, key):
        args, kwargs = mb_input
        if spec.embed_method is None:
            # The module IS the layer stack; the model(...) input is the carry.
            return args[0], jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": params}, *args,
            rngs=_mk_rngs(model, key, "embed"),
            method=spec.embed_method, **kwargs,
        )

    def head_mb(carry, key):
        # `carry` here is the collected hidden only (side values never
        # leave the layer stack).
        if spec.head_method is None:
            return carry, jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": params}, carry,
            rngs=_mk_rngs(model, key, "head"),
            method=spec.head_method,
        )

    apply_one_layer = make_layer_apply(model, spec, layer_module)

    if spec.carry_remat:
        from smdistributed_modelparallel_tpu.parallel.memory import remat_policy

        apply_one_layer = jax.checkpoint(apply_one_layer, policy=remat_policy())

    def stage_body(stage_layer_params, stage_layer_xs, carry, key, active_row):
        """Apply this stage's layer slots sequentially (scan over the local
        layer axis); padded slots pass the carry through unchanged. Returns
        (carry, summed MoE aux loss of the active slots)."""

        def body(c, xs):
            lp, lxs, i, act = xs
            new_c, aux = apply_one_layer(lp, c, lxs, jax.random.fold_in(key, i))
            out_c = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new_c, c
            )
            return out_c, jnp.where(act, aux, 0.0)

        idx = jnp.arange(active_row.shape[0])
        out, auxs = jax.lax.scan(
            body, carry, (stage_layer_params, stage_layer_xs, idx, active_row)
        )
        return out, jnp.sum(auxs)

    mb_keys = jax.random.split(rngs_key, num_mb)

    # Embed all microbatches upfront (the pipeline's input queue).
    with named_region("smp/pipeline/embed"):
        embedded, embed_auxs = _scan_map(embed_mb, stacked_inputs, mb_keys)

    # [L, ...] -> [S, maxp, ...]; dim 0 stays sharded on pp. Uniform
    # boundaries collapse to a reshape; uneven ones gather padded slots.
    staged_params, staged_xs, active_rows = staged_layer_views(
        spec, layer_params, S
    )

    n_ticks = num_mb + S - 1
    # Schedule occupancy -> measured bubble fraction. Fill-drain busy slots
    # are exactly num_mb per stage over num_mb + S - 1 ticks, so the
    # measured fraction coincides with the theoretical (pp-1)/(mb+pp-1);
    # recording both keeps the report honest when the executor changes.
    from smdistributed_modelparallel_tpu.utils import health
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_pipeline_occupancy,
    )

    # Gauges carry the PHYSICAL stage count; under chunked specs the
    # measured fraction (C-1)/(mb+C-1) sitting above the interleaved
    # theoretical bound is the honest report — this executor runs the
    # chunks sequentially, it does not interleave them.
    record_pipeline_occupancy(
        "fill_drain", phys_stages, num_mb, busy_slots=num_mb * S,
        total_slots=n_ticks * S, virtual=virtual,
    )
    # The busy (tick, stage) -> microbatch assignments land in the flight
    # recorder once per trace: a stall dump can then say which schedule
    # slot each rank's program was built to be in, not just "in step N".
    # Chunked specs record (physical stage, chunk) coordinates.
    # Chunked specs record (physical stage, GLOBAL chunk) coordinates —
    # the logical stage IS the boundary/chunk index here, matching the
    # chunk ids the 1F1B executor records for the same layers.
    flight_recorder.record_schedule(
        "fill_drain",
        ((t, s, "fwd", t - s) if virtual == 1
         else (t, s // virtual, "fwd", t - s, s)
         for t in range(n_ticks) for s in range(S)
         if 0 <= t - s < num_mb),
    )
    # Only the hidden flows stage-to-stage over the pp permute; tuple-carry
    # side values (cross_states, attention_mask) are static per-microbatch
    # inputs, gathered per stage per tick instead of rolled through ICI.
    if spec.carry_is_tuple:
        rolled = embedded[0]
        sides = embedded[1:]
    else:
        rolled = embedded
        sides = None
    carry_shape = jax.tree_util.tree_map(lambda x: x[0], rolled)
    # Stage input buffer: [S, ...carry]; buf[s] is the input consumed by
    # stage s at the next tick.
    buf0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros((S,) + x.shape, x.dtype), carry_shape
    )

    vmapped_stages = jax.vmap(stage_body, in_axes=(0, 0, 0, 0, 0))
    stage_keys = jax.random.split(rngs_key, S)
    stage_ids = jnp.arange(S)

    # Health sentinel (SMP_HEALTH_CHECK != off while this trace runs):
    # per-stage non-finite counts / finite abs-max of the stage-boundary
    # activations, plus the first bad microbatch per stage, accumulate in
    # the tick carry — one masked reduce per tick, no extra outputs until
    # the collector fuses them into the step's health word.
    hc = health.active()

    def tick(tick_carry, t):
        # Feed stage 0 with microbatch t (clamped; invalid ticks produce
        # garbage that is never collected — and whose aux loss is masked
        # out below).
        if hc is not None:
            buf, aux_acc, (hbad, habs, hmb) = tick_carry
        else:
            buf, aux_acc = tick_carry
        mb_idx = jnp.minimum(t, num_mb - 1)
        feed = jax.tree_util.tree_map(
            lambda e, b: b.at[0].set(
                jax.lax.dynamic_index_in_dim(e, mb_idx, 0, keepdims=False)
            ),
            rolled, buf,
        )
        if sides is not None:
            # Stage s processes microbatch t - s at tick t.
            stage_mbs = jnp.clip(t - stage_ids, 0, num_mb - 1)
            stage_sides = tuple(
                jax.tree_util.tree_map(
                    lambda a: jax.vmap(
                        lambda i: jax.lax.dynamic_index_in_dim(
                            a, i, 0, keepdims=False
                        )
                    )(stage_mbs),
                    side,
                )
                for side in sides
            )
            carry_in = (feed,) + stage_sides
        else:
            carry_in = feed
        # Distinct dropout keys per (stage, tick).
        tick_keys = jax.vmap(lambda k: jax.random.fold_in(k, t))(stage_keys)
        with named_region("smp/pipeline/tick_fwd"):
            outs, aux_row = vmapped_stages(
                staged_params, staged_xs, carry_in, tick_keys, active_rows
            )
        x_outs = outs[0] if sides is not None else outs
        # MoE aux: stage s holds microbatch t - s; fill/drain ticks where
        # that index is invalid computed on garbage/duplicate inputs and
        # must not contribute.
        valid = (t - stage_ids >= 0) & (t - stage_ids < num_mb)
        aux_acc = aux_acc + jnp.sum(jnp.where(valid, aux_row, 0.0))
        # Collect last stage's output (microbatch t - (S-1) when valid).
        tail = jax.tree_util.tree_map(lambda o: o[S - 1], x_outs)
        # Shift stage outputs forward one stage: collective-permute on pp.
        nxt = jax.tree_util.tree_map(
            lambda o: jnp.roll(o, shift=1, axis=0), x_outs
        )
        if hc is not None:
            brow, arow = health.stage_row_stats(x_outs, S)
            brow = jnp.where(valid, brow, 0.0)
            arow = jnp.where(valid, arow, 0.0)
            hmb_new = jnp.where(
                (hmb < 0) & (brow > 0),
                (t - stage_ids).astype(jnp.float32), hmb,
            )
            return (nxt, aux_acc,
                    (hbad + brow, jnp.maximum(habs, arow), hmb_new)), tail
        return (nxt, aux_acc), tail

    carry0 = (buf0, jnp.zeros((), jnp.float32))
    if hc is not None:
        carry0 = carry0 + ((
            jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.float32),
            jnp.full((S,), -1.0, jnp.float32),
        ),)
    with named_region("smp/pipeline/fill_drain"):
        carry_end, tails = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    if hc is not None:
        (_, aux_total, (hbad, habs, hmb)) = carry_end
        if virtual > 1:
            # Sequential chunk layout: logical stage i is global chunk i,
            # running on physical stage i // v — reshape so sentinel trips
            # attribute to stages that exist on the machine, tagged with
            # the global chunk (boundary) index.
            import numpy as np

            hbad = hbad.reshape(phys_stages, virtual)
            habs = habs.reshape(phys_stages, virtual)
            hmb = hmb.reshape(phys_stages, virtual)
            chunk_ids = np.arange(S).reshape(phys_stages, virtual)
            hc.add_stage_stats(
                "fill_drain", hbad, habs, hmb, chunk_ids=chunk_ids
            )
        else:
            hc.add_stage_stats("fill_drain", hbad, habs, hmb)
    else:
        (_, aux_total) = carry_end
    # tails[t] is microbatch t-(S-1); keep the last num_mb ticks.
    collected = jax.tree_util.tree_map(lambda x: x[S - 1:], tails)

    with named_region("smp/pipeline/head"):
        outputs, head_auxs = _scan_map(head_mb, collected, mb_keys)
    return outputs, aux_total + jnp.sum(embed_auxs) + jnp.sum(head_auxs)


def apply_collecting_aux(module, variables, *args, **kwargs):
    """Flax apply with ``mutable=["intermediates"]``: returns (out, aux)
    where ``aux`` is the summed sown MoE load-balancing loss as an f32
    scalar (0.0 when nothing was sown). Running with the collection mutable
    is what lets ``sow`` escape the apply — the executors fold the summed
    aux into the differentiated loss (see ``step.py`` /
    ``pipeline_1f1b.py``)."""
    from smdistributed_modelparallel_tpu.nn.moe import collect_moe_aux

    out, mut = module.apply(
        variables, *args, mutable=["intermediates"], **kwargs
    )
    aux = collect_moe_aux(mut.get("intermediates"))
    aux = (
        jnp.zeros((), jnp.float32) if aux is None else aux.astype(jnp.float32)
    )
    return out, aux


def make_layer_apply(model, spec, layer_module, side_in_carry=True):
    """Single-layer application shared by both pipeline executors.

    Returns ``apply_one_layer(lp, carry, layer_xs, key, side=None) ->
    (new_carry, aux)`` with ``aux`` the layer's MoE aux loss (0.0 for dense
    layers). For tuple-carry specs the two executors thread the side values
    differently: the fill-drain executor keeps them inside the carry
    (``side_in_carry=True``: carry is (x, cross, amask) in and out), while
    1F1B rolls only the hidden and passes (cross, amask) via ``side``
    (``side_in_carry=False``)."""
    from smdistributed_modelparallel_tpu.parallel.memory import (
        name_layer_activation,
    )

    def apply_one_layer(lp, carry, layer_xs, key, side=None):
        rngs = _mk_rngs(model, key, "layer")
        if spec.carry_is_tuple:
            if side_in_carry:
                x, cross, amask = carry
            else:
                x = carry
                cross, amask = side
            out, aux = apply_collecting_aux(
                layer_module, {"params": lp}, x, cross_states=cross,
                attention_mask=amask, xs=layer_xs, rngs=rngs,
            )
            new_c = (
                (name_layer_activation(out), cross, amask)
                if side_in_carry else name_layer_activation(out)
            )
            return new_c, aux
        if spec.layer_xs is not None:
            out, aux = apply_collecting_aux(
                layer_module, {"params": lp}, carry, xs=layer_xs, rngs=rngs
            )
        else:
            out, aux = apply_collecting_aux(
                layer_module, {"params": lp}, carry, rngs=rngs
            )
        return name_layer_activation(out), aux

    return apply_one_layer


def _scan_map(fn, stacked, keys):
    """Map fn over the leading microbatch axis via lax.scan (sequential, so
    per-microbatch activations do not coexist)."""

    def body(_, xs):
        tree, key = xs
        return 0, fn(tree, key)

    _, out = jax.lax.scan(body, 0, (stacked, keys))
    return out


def _mk_rngs(model, key, tag):
    import zlib

    return {
        s: jax.random.fold_in(key, zlib.crc32(f"{tag}/{s}".encode()))
        for s in model.rng_streams
    }


def staged_layer_views(spec, layer_params, num_stages):
    """Stage the [L, ...] layer stack as ([S, maxp, ...] params,
    [S, maxp, ...] xs, [S, maxp] active mask).

    Uniform boundaries are a plain reshape (dim 0 stays pp-sharded, no data
    movement); uneven boundaries gather into padded slots — the gather
    crosses the even [L] storage sharding, so uneven splits trade one
    layer-param reshard per step for balanced stage compute.
    """
    L = spec.num_layers
    idx, active, maxp = stage_layout(spec, num_stages)
    uniform = active.all() and L == num_stages * maxp
    if uniform:
        staged_params = jax.tree_util.tree_map(
            lambda x: x.reshape((num_stages, maxp) + x.shape[1:]), layer_params
        )
        staged_xs = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x).reshape(
                (num_stages, maxp) + jnp.asarray(x).shape[1:]
            ),
            spec.layer_xs,
        )
    else:
        gidx = jnp.asarray(idx)
        staged_params = jax.tree_util.tree_map(
            lambda x: x[gidx], layer_params
        )
        staged_xs = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x)[gidx], spec.layer_xs
        )
    return staged_params, staged_xs, jnp.asarray(active)


def _get_subtree(params, path):
    node = params
    for part in path.strip("/").split("/"):
        if part:
            node = node[part]
    return node
