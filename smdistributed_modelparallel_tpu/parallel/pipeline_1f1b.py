"""1F1B ("interleaved") pipeline schedule with bounded in-flight microbatches.

Parity target: reference ``torch/pipeline.py:136-145``
(``InterleavedPipeline.get_next_microbatch`` prioritizes ready-backwards over
new forwards) and ``torch/server_queue.py:629-676`` (the
``active_microbatches`` in-flight cap). The reference gets 1F1B behavior
dynamically from its server event loop; here the schedule is computed
statically in Python and baked into ONE ``lax.scan`` over ticks:

- each tick has a forward sub-step and a backward sub-step; per stage the
  static schedule says which microbatch (if any) to process in each;
- stage inputs are stashed into a ring buffer of ``active_microbatches + 1``
  slots; backward re-runs the stage forward from the stash under ``jax.vjp``
  (activation recomputation, Megatron-style 1F1B-with-remat) — peak live
  carries are O(S * active_microbatches) instead of the fill-drain
  executor's O(num_microbatches * S) saved scan carries;
- stage-to-stage transfers (forward activations and backward cotangents)
  move through pp-sharded buffers via ``jnp.roll`` on the stage axis, which
  GSPMD lowers to a collective-permute over ICI;
- the last stage's forward OUTPUT is stashed in its own ring; its backward
  tick runs only the cheap head + user-loss VJP on that stashed output to
  get (replicated/head param grads, the stage-output cotangent), and the
  uniform vmapped stage backward then treats the last stage like any other
  — no stage forward is ever executed twice, and the only replicated
  (non-stage-parallel) work per tick is the head/loss VJP itself;
  embedding gradients are applied after the tick loop from the collected
  stage-0 input cotangents.

The executor returns (mean_loss-scaled grads, stacked user outputs, stacked
losses); the step engine (``step.py``) divides out the loss scale exactly as
in the fill-drain path so the two schedules are numerically interchangeable.

Three executors share this module: the plain v=1 path (``pipeline_1f1b``
below, byte-stable by contract), the interleaved virtual-stage
generalization (``_pipeline_1f1b_virtual``: (chunk, microbatch) units over
``pp*v`` chunks), and the zero-bubble ZB-H1 executor
(``_pipeline_zero_bubble``: (chunk, microbatch, pass) units — backward
split into an input-grad pass and a deferred weight-grad pass that fills
the cooldown bubble; selected by ``pipeline: "zero_bubble"``).
"""

import numpy as np

import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils.exceptions import PartitionError
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.profiling import named_region

logger = get_logger()


def build_1f1b_schedule(num_stages, num_microbatches, window):
    """Static lockstep 1F1B schedule.

    Returns (fwd, bwd): int arrays [n_ticks, S]; entry = microbatch index the
    stage processes in that tick's sub-step, or -1 for idle. Invariants: a
    stage's forward of microbatch m runs only after stage s-1's forward of m
    (strictly earlier tick); a stage's backward of m runs only after its own
    forward of m (same tick allowed on the last stage — cotangent comes from
    the loss, not a neighbor) and after stage s+1's backward of m; at most
    ``window`` microbatches are in flight (forwarded, not yet backwarded)
    per stage at any tick.
    """
    S, M, W = num_stages, num_microbatches, window
    if W < 1:
        raise PartitionError(f"active_microbatches must be >= 1, got {W}")
    fwd_next = [0] * S
    bwd_next = [0] * S
    fwd_tick = {}
    bwd_tick = {}
    fwd_rows, bwd_rows = [], []
    t = 0
    limit = 4 * (M + S) * max(1, (S + W - 1) // W) + 16
    while any(b < M for b in bwd_next):
        frow, brow = [-1] * S, [-1] * S
        for s in range(S):
            m = fwd_next[s]
            if m < M and (fwd_next[s] - bwd_next[s]) < W:
                if s == 0 or fwd_tick.get((s - 1, m), limit) < t:
                    frow[s] = m
        for s in range(S):
            if frow[s] >= 0:
                fwd_tick[(s, frow[s])] = t
                fwd_next[s] += 1
        for s in range(S):
            m = bwd_next[s]
            if m < M and fwd_tick.get((s, m), limit) <= t:
                if s == S - 1 or bwd_tick.get((s + 1, m), limit) < t:
                    brow[s] = m
        for s in range(S):
            if brow[s] >= 0:
                bwd_tick[(s, brow[s])] = t
                bwd_next[s] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
        if t > limit:
            raise PartitionError(
                f"1F1B schedule did not converge (S={S}, M={M}, W={W})"
            )
    return np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32)


def schedule_occupancy(fwd, bwd, fwd_ticks=None, bwd_ticks=None, wgt=None,
                       wgt_ticks=None):
    """(busy_slots, total_slots) of a static 1F1B schedule.

    Each tick has a forward and a backward sub-step per stage; a sub-slot
    is busy when its schedule entry is a microbatch index (>= 0). The
    compiled program executes exactly this schedule, so this IS the
    measured occupancy. Under virtual pipeline stages the entries are
    (chunk, microbatch) units, so busy counts CHUNK sub-steps (busy ==
    2*S*V*M) and stays comparable across ``virtual_pipeline_degree``
    values; ``fwd_ticks``/``bwd_ticks`` then restrict the denominator to
    the ticks whose sub-step actually executes (the virtual executor's
    warmup ticks are forward-only and its cooldown ticks backward-only —
    idle sub-steps that are never compiled are not bubble).

    Zero-bubble schedules split the backward into input-grad (B) and
    weight-grad (W) passes: ``bwd`` then carries the B pass, ``wgt`` the
    W pass (with its own ``wgt_ticks`` executed-span bound), and busy
    counts (chunk, microbatch, pass) sub-steps — 3*S*V*M when every unit
    ran exactly once.
    """
    busy = int((fwd >= 0).sum()) + int((bwd >= 0).sum())
    if fwd_ticks is None:
        fwd_ticks = int(fwd.shape[0])
    if bwd_ticks is None:
        bwd_ticks = int(bwd.shape[0])
    total_ticks = fwd_ticks + bwd_ticks
    if wgt is not None:
        busy += int((wgt >= 0).sum())
        total_ticks += int(wgt.shape[0]) if wgt_ticks is None else wgt_ticks
    total = int(fwd.shape[1]) * total_ticks
    return busy, total


def build_interleaved_1f1b_schedule(num_stages, num_microbatches, window,
                                    virtual):
    """Static lockstep 1F1B schedule over ``virtual`` chunks per stage.

    Megatron-style virtual pipeline stages: the model is cut into
    ``C = num_stages * virtual`` chunks; global chunk ``c`` lives on stage
    ``c % num_stages`` as that stage's local chunk ``k = c // num_stages``.
    Returns ``(fwd_chunk, fwd_mb, bwd_chunk, bwd_mb)``: int32 arrays
    ``[n_ticks, S]``; per tick each stage processes at most one
    (chunk, microbatch) unit per direction (-1 = idle).

    Invariants (generalizing the v=1 schedule's):
      - every (chunk, microbatch) is forwarded and backwarded exactly once;
      - fwd of chunk c, mb m runs strictly after fwd of chunk c-1, mb m;
      - bwd of chunk c, mb m runs strictly after bwd of chunk c+1, mb m,
        and not before its own fwd (same tick allowed only on the LAST
        chunk, whose cotangent comes from the loss, not a neighbor);
      - per (stage, chunk), at most ``window`` microbatches are in flight
        (forwarded, not yet backwarded) at any tick.

    Greedy policy: each stage picks the highest eligible chunk in both
    directions (depth-first fwd pushes microbatches toward the loss so
    backwards start sooner; highest-chunk bwd drains cotangents down the
    chunk chain). At ``virtual=1`` this reduces EXACTLY to
    ``build_1f1b_schedule`` (one chunk per stage, identical arrays).

    Bubble: with ``window >= 2*num_stages`` the schedule achieves the
    interleaved floor — occupancy over executed sub-steps (forward-only
    warmup ticks + paired ticks + backward-only cooldown ticks, see
    ``interleaved_phase_bounds``) equals
    ``1 - (pp-1)/(v*mb + pp-1)``. The default ``active_microbatches``
    (pp+2) reaches it at pp=2; deeper pipelines trade the last bubble
    fraction against in-flight activation memory.
    """
    S, M, W, V = num_stages, num_microbatches, window, virtual
    if W < 1:
        raise PartitionError(f"active_microbatches must be >= 1, got {W}")
    if V < 1:
        raise PartitionError(f"virtual degree must be >= 1, got {V}")
    C = S * V
    fwd_next = [[0] * V for _ in range(S)]
    bwd_next = [[0] * V for _ in range(S)]
    fwd_tick = {}
    bwd_tick = {}
    fk_rows, fm_rows, bk_rows, bm_rows = [], [], [], []
    t = 0
    limit = 4 * V * (M + S) * max(1, (S + W - 1) // W) + 16 * V

    def fwd_candidate(s):
        """Highest eligible local chunk for stage s's fwd sub-step."""
        for k in range(V - 1, -1, -1):
            c = k * S + s
            m = fwd_next[s][k]
            if m < M and (fwd_next[s][k] - bwd_next[s][k]) < W:
                if c == 0 or fwd_tick.get((c - 1, m), limit) < t:
                    return k, m
        return -1, -1

    def bwd_candidate(s):
        for k in range(V - 1, -1, -1):
            c = k * S + s
            m = bwd_next[s][k]
            if m < M and fwd_tick.get((c, m), limit) <= t:
                if c == C - 1 or bwd_tick.get((c + 1, m), limit) < t:
                    return k, m
        return -1, -1

    while any(n < M for row in bwd_next for n in row):
        fk, fm = zip(*(fwd_candidate(s) for s in range(S)))
        for s in range(S):
            if fm[s] >= 0:
                fwd_tick[(fk[s] * S + s, fm[s])] = t
                fwd_next[s][fk[s]] += 1
        bk, bm = zip(*(bwd_candidate(s) for s in range(S)))
        for s in range(S):
            if bm[s] >= 0:
                bwd_tick[(bk[s] * S + s, bm[s])] = t
                bwd_next[s][bk[s]] += 1
        fk_rows.append(fk)
        fm_rows.append(fm)
        bk_rows.append(bk)
        bm_rows.append(bm)
        t += 1
        if t > limit:
            raise PartitionError(
                f"interleaved 1F1B schedule did not converge "
                f"(S={S}, M={M}, W={W}, V={V})"
            )
    return (np.asarray(fk_rows, np.int32), np.asarray(fm_rows, np.int32),
            np.asarray(bk_rows, np.int32), np.asarray(bm_rows, np.int32))


def interleaved_phase_bounds(fwd_mb, bwd_mb):
    """(t_bwd_start, t_fwd_end) of an interleaved schedule.

    Ticks ``[0, t_bwd_start)`` have no backward work anywhere (warmup:
    the executor compiles them as forward-only sub-steps) and ticks
    ``[t_fwd_end, n_ticks)`` no forward work (cooldown: backward-only).
    This phase split is what realizes the interleaved bubble win: the
    rigidly paired tick (one fwd + one bwd sub-step) would idle a full
    sub-step per warmup/cooldown tick, making the sub-slot bubble
    independent of the virtual degree.
    """
    n_ticks = int(fwd_mb.shape[0])
    bwd_any = (bwd_mb >= 0).any(axis=1)
    fwd_any = (fwd_mb >= 0).any(axis=1)
    t_b0 = int(np.argmax(bwd_any)) if bwd_any.any() else n_ticks
    t_fe = n_ticks - int(np.argmax(fwd_any[::-1])) if fwd_any.any() else 0
    return t_b0, t_fe


def build_zero_bubble_schedule(num_stages, num_microbatches, window,
                               virtual=1):
    """ZB-H1 zero-bubble schedule: (chunk, microbatch, pass) units.

    Splits the backward into an input-gradient pass (B, on the critical
    path: it feeds the upstream stage's cotangent) and a weight-gradient
    pass (W, deferrable: it depends only on the stage's own B), and packs
    the deferred Ws into ticks that the F/B schedule would otherwise
    leave idle — cooldown first. Returns
    ``(fwd_chunk, fwd_mb, bwd_chunk, bwd_mb, wgt_chunk, wgt_mb)``: int32
    arrays ``[n_ticks, S]``, one (chunk, microbatch) unit per stage per
    pass per tick (-1 = idle).

    Invariants (on top of the interleaved schedule's for F and B):
      - every (chunk, microbatch) runs each of F, B, W exactly once;
      - B(c, m) depends on F(c, m) and the downstream B(c+1, m) exactly
        as the interleaved schedule's monolithic backward does — the
        (F, B) sub-schedule here IS ``build_interleaved_1f1b_schedule``'s
        output tick-for-tick (fusing W back into B reproduces it);
      - W(c, m) depends ONLY on B(c, m); the same tick is legal because
        the executor orders sub-steps F -> B -> W within a tick;
      - per stage, at most one W per tick (it is a real compute slot).

    Packing policy: per stage, Ws run FIFO in B-completion order, shifted
    so no stage starts its W run before the LAST stage has started
    backwards (``w_lo = max_s first_B_tick(s)``). Early stages therefore
    defer weight grads into the B-drain cooldown — the ticks where their
    B slot idles waiting for upstream cotangents — instead of fusing them
    into warm B ticks and idling cold ones. At (pp=2, mb >= pp, default
    window) every stage's W run is gapless and the sub-slot bubble over
    executed pass spans reaches

        2*(pp-1) / (3*v*mb + 2*(pp-1))

    strictly below the interleaved floor (pp-1)/(v*mb + pp-1) for every
    v, mb (the F and B ramps keep their pp-1 idle sub-slots; the W pass
    contributes zero). The deferral depth this costs is bounded — the
    W-queue ring is accounted by ``parallel/memory.py::
    zero_bubble_ring_plan`` and stays within the existing ``window + 1``
    stash ring at the default window.
    """
    S, M, V = num_stages, num_microbatches, virtual
    fwd_k, fwd_m, bwd_k, bwd_m = build_interleaved_1f1b_schedule(
        S, M, window, V
    )
    n_fb = int(fwd_m.shape[0])
    # Per-stage B completions in tick order (== microbatch FIFO per
    # (stage, chunk): bwd_next only ever increments).
    per_stage = [[] for _ in range(S)]
    for t in range(n_fb):
        for s in range(S):
            if bwd_m[t, s] >= 0:
                per_stage[s].append((t, int(bwd_k[t, s]), int(bwd_m[t, s])))
    firsts = [rows[0][0] for rows in per_stage if rows]
    w_lo = max(firsts) if firsts else 0
    n_ticks = n_fb
    assign = [[] for _ in range(S)]
    for s in range(S):
        prev = -1
        for i, (bt, k, m) in enumerate(per_stage[s]):
            wt = max(w_lo + i, bt, prev + 1)
            prev = wt
            assign[s].append((wt, k, m))
            n_ticks = max(n_ticks, wt + 1)

    def pad(a):
        if n_ticks == a.shape[0]:
            return a
        tail = np.full((n_ticks - a.shape[0], S), -1, np.int32)
        return np.concatenate([a, tail])

    fwd_k, fwd_m, bwd_k, bwd_m = (pad(a) for a in (fwd_k, fwd_m,
                                                   bwd_k, bwd_m))
    wgt_k = np.full((n_ticks, S), -1, np.int32)
    wgt_m = np.full((n_ticks, S), -1, np.int32)
    for s in range(S):
        for wt, k, m in assign[s]:
            wgt_k[wt, s] = k
            wgt_m[wt, s] = m
    return fwd_k, fwd_m, bwd_k, bwd_m, wgt_k, wgt_m


def zero_bubble_phase_bounds(fwd_mb, bwd_mb, wgt_mb):
    """Executed-tick span ``(lo, hi)`` per pass: F, B, W.

    Generalizes ``interleaved_phase_bounds`` to three passes: ticks
    outside a pass's span never compile that pass's sub-step (the ZB
    executor scans per contiguous segment of active passes), so only
    in-span idle sub-slots are bubble. ``(0, 0)`` marks a pass with no
    work (degenerate schedules).
    """

    def span(arr):
        busy = (arr >= 0).any(axis=1)
        if not busy.any():
            return (0, 0)
        lo = int(np.argmax(busy))
        hi = int(arr.shape[0] - np.argmax(busy[::-1]))
        return (lo, hi)

    return span(fwd_mb), span(bwd_mb), span(wgt_mb)


def zero_bubble_theoretical_bubble(num_stages, num_microbatches, virtual=1):
    """ZB-H1 sub-slot bubble bound: 2*(pp-1)/(3*v*mb + 2*(pp-1)).

    Denominator: 3 passes of v*mb busy sub-slots per stage plus the F and
    B ramps' pp-1 extra span ticks each; numerator: those two ramps' idle
    sub-slots (the W pass packs gapless). Strictly below the interleaved
    bound (pp-1)/(v*mb + pp-1) whenever v*mb > 0.
    """
    S, M, V = num_stages, num_microbatches, virtual
    denom = 3 * V * M + 2 * (S - 1)
    return 2 * (S - 1) / denom if denom > 0 else 0.0


def _zb_segments(f_span, b_span, w_span, n_ticks):
    """Contiguous tick segments [a, b) with static per-pass flags
    (do_fwd, do_bwd, do_wgt) — the ZB executor compiles one scan per
    segment, so out-of-span sub-steps never enter the program (same
    trick as the interleaved warmup/steady/cooldown split, generalized
    to three passes)."""
    cuts = sorted({0, n_ticks, *f_span, *b_span, *w_span})
    segs = []
    for a, b in zip(cuts, cuts[1:]):
        if a >= b:
            continue
        flags = (f_span[0] <= a < f_span[1],
                 b_span[0] <= a < b_span[1],
                 w_span[0] <= a < w_span[1])
        if any(flags):
            segs.append((a, b, flags))
    return segs


def _zb_segment_region(do_fwd, do_bwd, do_wgt):
    """Profiler region name for a ZB schedule segment."""
    if do_fwd and not do_bwd:
        return "smp/pipeline/warmup"
    if do_fwd:
        return "smp/pipeline/steady"
    if do_bwd:
        return "smp/pipeline/cooldown"
    return "smp/pipeline/cooldown_weight" if do_wgt else "smp/pipeline/idle"


def _tree_zeros(avals_or_tree, like=None):
    src = avals_or_tree if like is None else like
    return jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), src)


def _inexact_leaves(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, l in enumerate(leaves)
           if jnp.issubdtype(jnp.result_type(l), jnp.inexact)]
    return leaves, treedef, idx


# ---- shared ring/scatter primitives of the chunk-generalized executors
# (_pipeline_1f1b_virtual and _pipeline_zero_bubble; the plain v=1
# executor keeps its own 2-level ring helpers so its traced program —
# byte-identity contract — is built from untouched code). All are pure
# in their arguments: ring geometry ([S, V, R, ...]) rides in the
# buffers themselves.


def _chunk_ring_set(buf, row_chunks, row_slots, row_vals, row_active):
    """buf[s, row_chunks[s], row_slots[s]] = row_vals[s] where active."""

    def upd(b, v):
        def one(bs, k, slot, vs, act):   # bs: [V, R, ...]
            sub = jax.lax.dynamic_index_in_dim(bs, k, 0, keepdims=False)
            new = jax.lax.dynamic_update_index_in_dim(
                sub, vs.astype(bs.dtype), slot, 0
            )
            new = jnp.where(act, new, sub)
            return jax.lax.dynamic_update_index_in_dim(bs, new, k, 0)

        return jax.vmap(one)(b, row_chunks, row_slots, v, row_active)

    return jax.tree_util.tree_map(upd, buf, row_vals)


def _chunk_ring_get(buf, row_chunks, row_slots):
    def one(bs, k, slot):
        sub = jax.lax.dynamic_index_in_dim(bs, k, 0, keepdims=False)
        return jax.lax.dynamic_index_in_dim(sub, slot, 0, keepdims=False)

    return jax.tree_util.tree_map(
        lambda b: jax.vmap(one)(b, row_chunks, row_slots), buf
    )


def _chunk_outbuf_set(buf, row_slots, row_vals, row_active):
    def upd(b, v):
        def one(bs, slot, vs, act):
            new = jax.lax.dynamic_update_index_in_dim(
                bs, vs.astype(bs.dtype), slot, 0
            )
            return jnp.where(act, new, bs)

        return jax.vmap(one)(b, row_slots, v, row_active)

    return jax.tree_util.tree_map(upd, buf, row_vals)


def _chunk_scatter_add_mb(buf, m, val, active):
    def upd(b, v):
        cur = jax.lax.dynamic_index_in_dim(b, m, 0, keepdims=False)
        new = cur + jnp.where(active, v.astype(b.dtype), jnp.zeros_like(cur))
        return jax.lax.dynamic_update_index_in_dim(b, new, m, 0)

    return jax.tree_util.tree_map(upd, buf, val)


def _chunk_scatter_set_mb(buf, m, val, active):
    def upd(b, v):
        cur = jax.lax.dynamic_index_in_dim(b, m, 0, keepdims=False)
        new = jnp.where(active, v.astype(b.dtype), cur)
        return jax.lax.dynamic_update_index_in_dim(b, new, m, 0)

    return jax.tree_util.tree_map(upd, buf, val)


def _chunk_scatter_add_leaf(buf, m, val, active):
    cur = jax.lax.dynamic_index_in_dim(buf, m, 0, keepdims=False)
    new = cur + jnp.where(active, val.astype(buf.dtype), jnp.zeros_like(cur))
    return jax.lax.dynamic_update_index_in_dim(buf, new, m, 0)


def _chunk_scatter_stat(acc, krow, vals, act, op):
    """acc[s, krow[s]] = op(acc[s, krow[s]], vals[s]) where act[s];
    acc is [S, V] (per-stage per-chunk health stats)."""

    def one(av, k, vv, m):
        cur = jax.lax.dynamic_index_in_dim(av, k, 0, keepdims=False)
        new = jnp.where(m, op(cur, vv), cur)
        return jax.lax.dynamic_update_index_in_dim(av, new, k, 0)

    return jax.vmap(one)(acc, krow, vals, act)


def _chunk_acc_rows(acc, rows, krow, act):
    """Accumulate [S, ...] grad rows into the per-(stage, chunk) slot."""

    def upd(a, r):
        def one(av, k, rv, m):
            cur = jax.lax.dynamic_index_in_dim(av, k, 0, keepdims=False)
            new = cur + jnp.where(m, rv.astype(av.dtype), 0)
            return jax.lax.dynamic_update_index_in_dim(av, new, k, 0)

        return jax.vmap(one)(a, krow, r, act)

    return jax.tree_util.tree_map(upd, acc, rows)


def _make_residual_split(apply_one_layer, cast_half, rng, maxp, aux_seed,
                         has_sides, side_leaf_avals=None):
    """Per-layer vjp split of one chunk application, for the recompute
    planner's stash modes (``parallel/remat_plan.py``).

    The fused executors differentiate the whole chunk under one
    ``jax.vjp``, so the deferred weight-grad pass must re-run the chunk
    forward to rebuild the vjp's residuals. Here the chunk forward is
    instead run with a PER-LAYER ``jax.vjp`` whose function output is
    returned as flattened pytree leaves (`jax.vjp`'s vjp function is a
    ``tree_util.Partial`` — its leaves ARE the saved residuals), so a
    later pass can rebuild each layer's vjp with ``tree_unflatten`` and
    the treedef captured at trace time:

    - ``capture_fwd``: the chunk forward, additionally returning the
      per-layer residual leaves stacked over the layer axis;
    - ``bwd_from_res``: the input-grad sweep from residuals — reverse
      per-layer vjp chain seeded by the chunk-output cotangent, returning
      (input cotangent, side cotangent leaves, per-layer OUTPUT
      cotangents). The per-layer weight cotangents are never used here,
      so XLA dead-code-eliminates their matmuls;
    - ``wgt_from_res``: the weight-grad pass — per-layer vjp calls from
      (residuals, stashed per-layer cotangents), keeping only the weight
      cotangents (the input-grad matmuls are dead and eliminated). No
      forward, no cotangent chain: weight-grad FLOPs only.

    The captured treedef (``captured["treedef"]``) comes from whichever
    trace runs first (the executors probe with ``jax.eval_shape``); the
    embedded backward is jaxpr-closed and trace-independent, so leaves
    written by one compiled segment reconstruct in another.
    """
    captured = {}

    def capture_fwd(chunk_lp, chunk_lxs, x, side, c_idx, m_idx, act_row):
        base = jax.random.fold_in(jax.random.fold_in(rng, c_idx), m_idx)

        def body(c, xs):
            lp, lxs, i, act = xs

            def one(lp_, c_, side_):
                new_c, aux = apply_one_layer(
                    cast_half(lp_), c_, lxs, jax.random.fold_in(base, i),
                    side_,
                )
                out_c = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(act, n, o), new_c, c_
                )
                return out_c, jnp.where(act, aux, 0.0)

            if has_sides:
                (out_c, aux), lvjp = jax.vjp(one, lp, c, side)
            else:
                (out_c, aux), lvjp = jax.vjp(
                    lambda lp_, c_: one(lp_, c_, None), lp, c
                )
            leaves, treedef = jax.tree_util.tree_flatten(lvjp)
            captured.setdefault("treedef", treedef)
            return out_c, (aux, tuple(leaves))

        idx = jnp.arange(maxp)
        out, (auxs, res) = jax.lax.scan(
            body, x, (chunk_lp, chunk_lxs, idx, act_row)
        )
        return out, jnp.sum(auxs), res

    def _unflatten(res_layer):
        return jax.tree_util.tree_unflatten(
            captured["treedef"], list(res_layer)
        )

    def bwd_from_res(res, cot):
        side_zeros = [
            jnp.zeros(a.shape, jnp.float32) for a in (side_leaf_avals or [])
        ]

        def body(carry, res_layer):
            cbar, side_acc = carry
            lvjp = _unflatten(res_layer)
            outs = lvjp((cbar, aux_seed))
            if has_sides:
                _d_lp, d_c, d_side = outs
                leaves, _, idx = _inexact_leaves(d_side)
                side_acc = [
                    a + leaves[i].astype(a.dtype)
                    for a, i in zip(side_acc, idx)
                ]
            else:
                _d_lp, d_c = outs
            # ys: this layer's OUTPUT cotangent — what its weight-grad
            # vjp call needs later. _d_lp is unused: dead code.
            return (d_c, side_acc), cbar

        (d_x, side_acc), cot_stack = jax.lax.scan(
            body, (cot, side_zeros), res, reverse=True
        )
        return d_x, side_acc, cot_stack

    def bwd_full_from_res(res, cot):
        """Monolithic backward from residuals (the interleaved/1F1B
        executors' B pass under ``stash_all``): one reverse sweep
        producing weight grads AND the input cotangent — no forward."""
        side_zeros = [
            jnp.zeros(a.shape, jnp.float32) for a in (side_leaf_avals or [])
        ]

        def body(carry, res_layer):
            cbar, side_acc = carry
            lvjp = _unflatten(res_layer)
            outs = lvjp((cbar, aux_seed))
            if has_sides:
                d_lp, d_c, d_side = outs
                leaves, _, idx = _inexact_leaves(d_side)
                side_acc = [
                    a + leaves[i].astype(a.dtype)
                    for a, i in zip(side_acc, idx)
                ]
            else:
                d_lp, d_c = outs
            return (d_c, side_acc), d_lp

        (d_x, side_acc), d_lp_stack = jax.lax.scan(
            body, (cot, side_zeros), res, reverse=True
        )
        return d_lp_stack, d_x, side_acc

    def wgt_from_res(res, cot_stack):
        def body(_, xs):
            res_layer, cot_layer = xs
            lvjp = _unflatten(res_layer)
            outs = lvjp((cot_layer, aux_seed))
            # Keep only the weight cotangent; d_c / d_side are dead.
            return (), outs[0]

        _, d_lp_stack = jax.lax.scan(body, (), (res, cot_stack))
        return d_lp_stack

    return capture_fwd, bwd_from_res, bwd_full_from_res, wgt_from_res, captured


def _stash_slot_bytes(avals):
    """Bytes one (stage, chunk, ring-slot) stash entry costs per device:
    the probe avals carry a leading stage axis (vmapped rows), which the
    ring shards over pp — drop it."""
    return int(sum(
        a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
        for a in jax.tree_util.tree_leaves(avals)
    ))


def _probe_stash_avals(S, staged_params, staged_xs, active_rows, carry_aval,
                       sides, capture_fwd, bwd_from_res=None):
    """Abstract-trace one vmapped chunk-row capture to learn the stash
    leaf shapes (and capture the per-layer vjp treedef as a side effect
    — this must run before any ``bwd_*_from_res`` trace). Returns the
    residual avals, or ``(res_avals, cot_avals)`` when ``bwd_from_res``
    is given (the zero-bubble executor also stashes the per-layer
    output cotangents)."""

    def row_aval(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct((S,) + a.shape[2:], a.dtype),
            tree,
        )

    def stage_rows_aval(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct((S,) + a.shape, a.dtype), tree
        )

    side_row_aval = None
    if sides is not None:
        side_row_aval = tuple(
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct((S,) + a.shape[1:], a.dtype),
                s,
            )
            for s in sides
        )

    def probe(ch_params, ch_xs, x, side, c_ids, mrow, act):
        _out, _aux, res = jax.vmap(
            capture_fwd,
            in_axes=(0, 0, 0, 0 if sides is not None else None, 0, 0, 0),
        )(ch_params, ch_xs, x, side, c_ids, mrow, act)
        if bwd_from_res is None:
            return res
        cot = jax.tree_util.tree_map(lambda a: jnp.zeros_like(a), x)
        _d_x, _side_acc, cot_stack = jax.vmap(bwd_from_res)(res, cot)
        return res, cot_stack

    return jax.eval_shape(
        probe,
        row_aval(staged_params), row_aval(staged_xs),
        stage_rows_aval(carry_aval), side_row_aval,
        jax.ShapeDtypeStruct((S,), jnp.int32),
        jax.ShapeDtypeStruct((S,), jnp.int32),
        row_aval(active_rows),
    )


def _stash_chunk_maps(plan, V):
    """Static per-local-chunk maps of a stash plan: ``(stash_of_arr,
    res_col_arr, Vs, all_stash)`` — whether chunk k stashes, and its
    column in the Vs-compressed stash rings."""
    Vs = len(plan.stash_chunks)
    stash_of_np = np.zeros((V,), bool)
    res_col_np = np.zeros((V,), np.int32)
    for col, k in enumerate(plan.stash_chunks):
        stash_of_np[k] = True
        res_col_np[k] = col
    return (jnp.asarray(stash_of_np), jnp.asarray(res_col_np), Vs, Vs == V)


def pipeline_1f1b(model, params, stacked_inputs, rng, mb_loss_fn,
                  loss_seed_scale):
    """Run the full 1F1B forward+backward for all microbatches.

    Args:
      model: DistributedModel with ``_pipeline_spec`` installed.
      params: master parameter tree (layer subtree leaves lead with [L]).
      stacked_inputs: pytree with leading [num_microbatches] — captured
        inputs of the user's single ``model(...)`` call.
      rng: PRNG key (dropout etc.; folded per stage/microbatch so backward
        recompute reproduces the forward exactly).
      mb_loss_fn(out, mb_index, key) -> (loss, user_out): the user step
        function re-run with the model call forced to ``out``.
      loss_seed_scale: scalar multiplied into the backward seed (the step
        engine passes loss_scale / num_microbatches so grads come out as
        d(mean(losses) * loss_scale)).

    Returns: (grads_tree, stacked_losses [M], stacked_user_outs [M, ...]).
    """
    spec = model._pipeline_spec
    cfg = state.cfg
    virtual = int(getattr(cfg, "virtual_pipeline_degree", 1) or 1)
    from smdistributed_modelparallel_tpu.parallel import remat_plan

    rmode = remat_plan.resolve(cfg)
    if getattr(cfg, "pipeline", "interleaved") == "zero_bubble":
        # ZB-H1: backward split into input-grad/weight-grad passes; the
        # executor is chunk-generalized for any v >= 1. A non-default
        # recompute plan routes to the stash executor (which itself
        # falls back here when the plan degrades every chunk).
        if rmode != "full":
            return _pipeline_zero_bubble_stash(
                model, params, stacked_inputs, rng, mb_loss_fn,
                loss_seed_scale, virtual, rmode,
            )
        return _pipeline_zero_bubble(
            model, params, stacked_inputs, rng, mb_loss_fn, loss_seed_scale,
            virtual,
        )
    if rmode == "stash_weight":
        # No deferred weight-grad pass to stash for on the fused
        # schedules: the SCHEDULE-level stash is inert here (the knob
        # still maps onto the jax.checkpoint policy in
        # memory.remat_policy for models that rematerialize, and the
        # fingerprint config snapshot keeps recording the knob).
        logger.warning(
            "recompute: 'stash_weight' targets the zero_bubble schedule's "
            "W pass; pipeline: %r has none — no schedule-level stash "
            "(use 'stash_all' to remove this schedule's B recompute).",
            getattr(cfg, "pipeline", "interleaved"),
        )
        rmode = "full"
    if virtual > 1 or rmode in ("stash_all", "auto"):
        # Interleaved virtual stages take the generalized executor; the
        # default path below stays byte-for-byte the v=1 program. The
        # stash modes also route v=1 through it (the plan needs the
        # chunked ring layout), leaving the plain executor untouched —
        # including when an auto plan later degrades every chunk: the
        # run then stays on the chunk-generalized executor at v=1
        # (numerically identical, chunk-ring program) rather than
        # re-entering this dispatch.
        return _pipeline_1f1b_virtual(
            model, params, stacked_inputs, rng, mb_loss_fn, loss_seed_scale,
            virtual, rmode=rmode,
        )
    S = cfg.pipeline_parallel_degree
    M = cfg.microbatches
    L = spec.num_layers
    W = min(cfg.active_microbatches or (S + 1), M)
    W1 = W + 1
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    layer_module = spec.layer_module
    half = cfg.half_dtype

    fwd_np, bwd_np = build_1f1b_schedule(S, M, W)
    n_ticks = fwd_np.shape[0]
    from smdistributed_modelparallel_tpu.utils import health
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_pipeline_occupancy,
    )

    busy, total = schedule_occupancy(fwd_np, bwd_np)
    record_pipeline_occupancy("1f1b", S, M, busy_slots=busy, total_slots=total)
    # Busy schedule slots (with microbatch ids) into the flight recorder,
    # once per trace — see pipeline.py for why.
    flight_recorder.record_schedule(
        "1f1b",
        ((t, s, d, int(sched[t, s]))
         for t in range(n_ticks) for s in range(S)
         for d, sched in (("fwd", fwd_np), ("bwd", bwd_np))
         if sched[t, s] >= 0),
    )
    fwd_sched = jnp.asarray(fwd_np)
    bwd_sched = jnp.asarray(bwd_np)

    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        _get_subtree,
        _mk_rngs,
        _scan_map,
        stage_layout,
        staged_layer_views,
    )

    def cast_half(tree):
        from smdistributed_modelparallel_tpu.nn.utils import half_cast

        return half_cast(tree, half)

    layer_params = _get_subtree(params, spec.layer_path)
    staged_params, staged_xs, active_rows = staged_layer_views(
        spec, layer_params, S
    )
    # The head/loss and embed VJPs differentiate only the NON-layer subtree
    # (head, tied/replicated, embedding params): layer gradients come from
    # the per-stage VJPs, so carrying full-tree zero cotangents through the
    # per-tick head VJP would add accumulator traffic proportional to total
    # params on every tick for nothing. Protocol note: embed/head methods
    # must not read the layer-stack subtree (true of every pipelineable
    # module in the package — the stack is applied only via
    # spec.layer_module).
    params_rest = _set_subtree(params, spec.layer_path, {})

    def with_layers(p_rest):
        return _set_subtree(p_rest, spec.layer_path, layer_params)
    idx_np, active_np, maxp = stage_layout(spec, S)

    mb_keys = jax.random.split(rng, M)

    # ---- embed all microbatches (the input queue) --------------------

    def embed_mb(mb_input, key):
        args, kwargs = mb_input
        if spec.embed_method is None:
            return args[0]
        return module.apply(
            {"params": cast_half(params)}, *args,
            rngs=_mk_rngs(model, key, "embed"),
            method=spec.embed_method, **kwargs,
        )

    with named_region("smp/pipeline/embed"):
        embedded = _scan_map(embed_mb, stacked_inputs, mb_keys)

    if spec.carry_is_tuple:
        hidden_q = embedded[0]
        sides = embedded[1:]
    else:
        hidden_q = embedded
        sides = None

    carry_aval = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), hidden_q
    )

    # ---- per-stage forward (pure in stage params and carry) ----------

    from smdistributed_modelparallel_tpu.parallel.memory import remat_policy
    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        apply_collecting_aux,
        make_layer_apply,
    )

    apply_one_layer = make_layer_apply(
        model, spec, layer_module, side_in_carry=False
    )

    if spec.carry_remat:
        apply_one_layer = jax.checkpoint(apply_one_layer, policy=remat_policy())

    def stage_fwd(stage_lp, stage_lxs, x, side, s_idx, m_idx, act_row):
        """Apply this stage's layer slots; keys derived from (stage, mb) so
        the backward recompute reproduces dropout exactly. Padded slots pass
        the carry through unchanged. Returns (carry, summed MoE aux loss of
        the active slots) — the aux output is what lets the backward VJP
        seed router load-balancing gradients (see stage_bwd)."""
        base = jax.random.fold_in(jax.random.fold_in(rng, s_idx), m_idx)
        stage_lp = cast_half(stage_lp)

        def body(c, xs):
            lp, lxs, i, act = xs
            new_c, aux = apply_one_layer(
                lp, c, lxs, jax.random.fold_in(base, i), side
            )
            out_c = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new_c, c
            )
            return out_c, jnp.where(act, aux, 0.0)

        idx = jnp.arange(maxp)
        out, auxs = jax.lax.scan(body, x, (stage_lp, stage_lxs, idx, act_row))
        return out, jnp.sum(auxs)

    def gather_mb(tree, m):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
            tree,
        )

    def gather_sides_rows(ms):
        """Per-stage side tuples for a [S] vector of microbatch indices."""
        if sides is None:
            return None
        return tuple(
            jax.tree_util.tree_map(
                lambda a: jax.vmap(
                    lambda i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
                )(ms),
                s,
            )
            for s in sides
        )

    # ---- head + user loss (last stage only) --------------------------

    def head_apply_aux(p, carry, key):
        if spec.head_method is None:
            return carry, jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": cast_half(p)}, carry,
            rngs=_mk_rngs(model, key, "head"), method=spec.head_method,
        )

    def head_apply(p, carry, key):
        return head_apply_aux(p, carry, key)[0]

    # Abstract shapes of (loss, user_out) for the collection buffers.
    loss_out_aval = jax.eval_shape(
        lambda c: mb_loss_fn(head_apply(params, c, mb_keys[0]), 0, mb_keys[0]),
        jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), carry_aval),
    )

    # ---- buffers ------------------------------------------------------

    def zeros_ring(n):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S, n) + a.shape, a.dtype), carry_aval
        )

    # Intermediate cotangent buffers (dembed/dsides) stay fp32; parameter
    # gradient accumulators follow the same policy as the fill-drain path
    # (step.py::_acc_dtype — fp32 under _fp32_grad_accumulation, else the
    # parameter's own dtype, which for master weights is fp32 anyway).
    grad_dtype = jnp.float32

    def _acc_dtype(dtype):
        if jnp.issubdtype(dtype, jnp.floating) and cfg._fp32_grad_accumulation:
            return jnp.float32
        return dtype

    def param_grad_zeros(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _acc_dtype(p.dtype)), tree
        )

    inbuf0 = zeros_ring(W1)      # inbuf[s, m % W1] = input for stage s's fwd of m
    stash0 = zeros_ring(W1)      # stash[s, m % W1] = input consumed by fwd of m
    cotbuf0 = zeros_ring(W1)     # cotbuf[s, m % W1] = cotangent for stage s's output of m
    outbuf0 = zeros_ring(W1)     # outbuf[S-1, m % W1] = last stage's fwd output of m
    #                              (only row S-1 is ever written; keeping the
    #                              [S] axis keeps the buffer pp-sharded like
    #                              its siblings instead of replicated)
    dlay0 = param_grad_zeros(staged_params)
    drep0 = param_grad_zeros(params_rest)     # head/tied/replicated contributions
    dembed0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, grad_dtype), carry_aval
    )
    side_leaves = side_treedef = side_idx = None
    dsides0 = None
    if sides is not None:
        side_leaves, side_treedef, side_idx = _inexact_leaves(
            tuple(jax.tree_util.tree_map(lambda a: a[0], s) for s in sides)
        )
        dsides0 = [
            jnp.zeros((M,) + side_leaves[i].shape, grad_dtype) for i in side_idx
        ]
    losses0 = jnp.zeros((M,), jnp.float32)
    outs0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, a.dtype), loss_out_aval[1]
    )

    stage_ids = jnp.arange(S)
    # MoE aux-loss backward seed: d(total_loss)/d(stage_aux) for one
    # microbatch under mean-over-microbatch semantics. loss_seed_scale is
    # loss_scale / num_microbatches, exactly the task-loss seed.
    aux_w = float(getattr(cfg, "moe_aux_loss_weight", 1.0))
    aux_seed = (
        jnp.asarray(aux_w, jnp.float32)
        * jnp.asarray(loss_seed_scale, jnp.float32)
    )

    def set_ring(buf, row_slots, row_vals, row_active):
        """buf[s, row_slots[s]] = row_vals[s] where row_active[s]."""

        def upd(b, v):
            def one(bs, slot, vs, act):
                new = jax.lax.dynamic_update_index_in_dim(bs, vs.astype(bs.dtype), slot, 0)
                return jnp.where(act, new, bs)

            return jax.vmap(one)(b, row_slots, v, row_active)

        return jax.tree_util.tree_map(upd, buf, row_vals)

    def get_ring(buf, row_slots):
        return jax.tree_util.tree_map(
            lambda b: jax.vmap(
                lambda bs, slot: jax.lax.dynamic_index_in_dim(bs, slot, 0, keepdims=False)
            )(b, row_slots),
            buf,
        )

    def scatter_add_mb(buf, m, val, active):
        """buf[m] += val if active (single microbatch row)."""

        def upd(b, v):
            cur = jax.lax.dynamic_index_in_dim(b, m, 0, keepdims=False)
            new = cur + jnp.where(active, v.astype(b.dtype), jnp.zeros_like(cur))
            return jax.lax.dynamic_update_index_in_dim(b, new, m, 0)

        return jax.tree_util.tree_map(upd, buf, val)

    def scatter_set_mb(buf, m, val, active):
        def upd(b, v):
            cur = jax.lax.dynamic_index_in_dim(b, m, 0, keepdims=False)
            new = jnp.where(active, v.astype(b.dtype), cur)
            return jax.lax.dynamic_update_index_in_dim(b, new, m, 0)

        return jax.tree_util.tree_map(upd, buf, val)

    # Health sentinel (utils/health.py): per-stage boundary-activation
    # stats accumulate in the tick carry; this scan runs in the step
    # trace itself, so the totals feed the collector directly after it.
    hc = health.active()

    def tick(carry, t):
        if hc is not None:
            (inbuf, stash, cotbuf, outbuf, dlay, drep, dembed, dsides,
             losses, outs, (hbad, habs, hmb)) = carry
        else:
            (inbuf, stash, cotbuf, outbuf, dlay, drep, dembed, dsides,
             losses, outs) = carry

        # ---------------- forward sub-step ----------------
        fm = fwd_sched[t]                       # [S]; -1 idle
        f_active = fm >= 0
        fmc = jnp.maximum(fm, 0)
        f_slots = fmc % W1
        # Stage 0 reads from the embedded queue; others from inbuf.
        from_q = gather_mb(hidden_q, fmc[0])
        buf_in = get_ring(inbuf, f_slots)
        x_in = jax.tree_util.tree_map(
            lambda q, b: b.at[0].set(q), from_q, buf_in
        )
        f_sides = gather_sides_rows(fmc)
        with named_region("smp/pipeline/tick_fwd"):
            outs_f, _aux_f = jax.vmap(
                stage_fwd,
                in_axes=(0, 0, 0, 0 if sides is not None else None, 0, 0, 0),
            )(staged_params, staged_xs, x_in, f_sides, stage_ids, fmc,
              active_rows)
        # Stash the consumed inputs for backward recompute.
        stash = set_ring(stash, f_slots, x_in, f_active)
        if hc is not None:
            brow, arow = health.stage_row_stats(outs_f, S)
            brow = jnp.where(f_active, brow, 0.0)
            arow = jnp.where(f_active, arow, 0.0)
            hmb = jnp.where(
                (hmb < 0) & (brow > 0), fmc.astype(jnp.float32), hmb
            )
            hbad = hbad + brow
            habs = jnp.maximum(habs, arow)
        # Ship outputs forward one stage (collective-permute on pp): the
        # value produced by stage s lands in inbuf[s+1] at slot m % W1.
        shifted_vals = jax.tree_util.tree_map(
            lambda o: jnp.roll(o, 1, axis=0), outs_f
        )
        shifted_slots = jnp.roll(f_slots, 1)
        shifted_active = jnp.roll(f_active, 1).at[0].set(False)
        inbuf = set_ring(inbuf, shifted_slots, shifted_vals, shifted_active)
        # The last stage's output feeds the head/loss at its backward tick.
        last_row_active = f_active & (stage_ids == S - 1)
        outbuf = set_ring(outbuf, f_slots, outs_f, last_row_active)

        # ---------------- backward sub-step ----------------
        bm = bwd_sched[t]
        b_active = bm >= 0
        bmc = jnp.maximum(bm, 0)
        b_slots = bmc % W1

        # Head + user loss VJP on the last stage's STASHED output: yields
        # the replicated/head param grads and the stage-output cotangent.
        # The stage forward itself is NOT in this VJP — the uniform vmapped
        # stage backward below recomputes it once, same as every stage.
        m_last = bmc[S - 1]
        key_last = jax.lax.dynamic_index_in_dim(mb_keys, m_last, 0, keepdims=False)
        out_last = jax.tree_util.tree_map(
            lambda ob: jax.lax.dynamic_index_in_dim(
                ob[S - 1], b_slots[S - 1], 0, keepdims=False
            ),
            outbuf,
        )

        def head_loss(p_rest, out):
            final, h_aux = head_apply_aux(with_layers(p_rest), out, key_last)
            loss, user_out = mb_loss_fn(final, m_last, key_last)
            # Head-resident MoE aux joins the differentiated loss with the
            # same weight as the layer-stack aux (parity with pp=1).
            loss = loss + jnp.asarray(aux_w, loss.dtype) * h_aux.astype(
                loss.dtype
            )
            return loss, user_out

        with named_region("smp/pipeline/head"):
            loss_m, head_vjp, user_out = jax.vjp(
                head_loss, params_rest, out_last, has_aux=True
            )
            seed = jnp.asarray(loss_seed_scale, jnp.float32) * jnp.where(
                b_active[S - 1], 1.0, 0.0
            )
            d_rep, d_out_last = head_vjp(seed.astype(loss_m.dtype))

        # All stages: plain stage VJP; cotangents come from cotbuf except
        # the last stage's, which is the head/loss cotangent just computed.
        cot_in = get_ring(cotbuf, b_slots)
        cot_in = jax.tree_util.tree_map(
            lambda c, d: c.at[S - 1].set(d.astype(c.dtype)), cot_in, d_out_last
        )
        b_sides = gather_sides_rows(bmc)
        stash_in = get_ring(stash, b_slots)

        def stage_bwd(lp, lxs, x, side, cot, s_idx, m_idx, act_row):
            def f(lp_, x_, side_):
                return stage_fwd(lp_, lxs, x_, side_, s_idx, m_idx, act_row)

            _, vjp = jax.vjp(f, lp, x, side)
            # Seed both outputs: the downstream cotangent for the hidden
            # carry, and the MoE aux-loss seed (same mean-loss scaling as
            # the task loss; idle-stage contributions are masked when
            # accumulated below).
            return vjp((cot, aux_seed))

        with named_region("smp/pipeline/tick_bwd"):
            d_lp_rows, d_x_rows, d_side_rows = jax.vmap(
                stage_bwd,
                in_axes=(0, 0, 0, 0 if sides is not None else None,
                         0, 0, 0, 0),
            )(staged_params, staged_xs, stash_in,
              b_sides, cot_in, stage_ids, bmc, active_rows)

        # Accumulate layer grads (mask idle rows).
        mask_b = b_active

        def acc_rows(acc, rows):
            def add(a, r):
                m = mask_b.reshape((S,) + (1,) * (r.ndim - 1))
                return a + jnp.where(m, r.astype(a.dtype), 0)

            return jax.tree_util.tree_map(add, acc, rows)

        dlay = acc_rows(dlay, d_lp_rows)

        # Replicated/head grads: only when the last stage was active.
        drep = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(b_active[S - 1], g.astype(a.dtype), 0),
            drep, d_rep,
        )

        # Route input cotangents: stage s's d_input goes to stage s-1's
        # output cotangent (cotbuf[s-1]); stage 0's goes to the embedding.
        shifted_cots = jax.tree_util.tree_map(
            lambda o: jnp.roll(o, -1, axis=0), d_x_rows
        )
        cot_slots = jnp.roll(b_slots, -1)
        cot_active = jnp.roll(b_active, -1).at[S - 1].set(False)
        cotbuf = set_ring(cotbuf, cot_slots, shifted_cots, cot_active)
        dembed = scatter_add_mb(
            dembed, bmc[0],
            jax.tree_util.tree_map(lambda r: r[0], d_x_rows),
            b_active[0],
        )

        # Side cotangents: every active stage contributes to its microbatch.
        if sides is not None and dsides is not None:
            def one_stage_side_add(ds, s):
                row_leaves, _, _ = _inexact_leaves(
                    jax.tree_util.tree_map(lambda r: r[s], d_side_rows)
                )
                vals = [row_leaves[i] for i in side_idx]
                return [
                    _scatter_add_leaf(d, bmc[s], v, b_active[s])
                    for d, v in zip(ds, vals)
                ]

            for s in range(S):
                dsides = one_stage_side_add(dsides, s)

        # Loss / user outputs at the last stage's backward tick.
        losses = losses.at[m_last].set(
            jnp.where(b_active[S - 1], loss_m.astype(jnp.float32), losses[m_last])
        )
        outs = scatter_set_mb(outs, m_last, user_out, b_active[S - 1])

        new_carry = (inbuf, stash, cotbuf, outbuf, dlay, drep, dembed,
                     dsides, losses, outs)
        if hc is not None:
            new_carry = new_carry + ((hbad, habs, hmb),)
        return new_carry, None

    def _scatter_add_leaf(buf, m, val, active):
        cur = jax.lax.dynamic_index_in_dim(buf, m, 0, keepdims=False)
        new = cur + jnp.where(active, val.astype(buf.dtype), jnp.zeros_like(cur))
        return jax.lax.dynamic_update_index_in_dim(buf, new, m, 0)

    carry0 = (inbuf0, stash0, cotbuf0, outbuf0, dlay0, drep0, dembed0,
              dsides0, losses0, outs0)
    if hc is not None:
        carry0 = carry0 + ((
            jnp.zeros((S,), jnp.float32), jnp.zeros((S,), jnp.float32),
            jnp.full((S,), -1.0, jnp.float32),
        ),)
    with named_region("smp/pipeline/steady"):
        carry_end, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    if hc is not None:
        (_, _, _, _, dlay, drep, dembed, dsides, losses, outs,
         (hbad, habs, hmb)) = carry_end
        hc.add_stage_stats("1f1b", hbad, habs, hmb)
    else:
        (_, _, _, _, dlay, drep, dembed, dsides, losses, outs) = carry_end

    # ---- embedding backward ------------------------------------------

    def embed_bwd(acc, xs):
        mb_input, key, dcarry, dside_row = xs

        def embed_inexact(p_rest):
            args, kwargs = mb_input
            out, aux = apply_collecting_aux(
                module, {"params": cast_half(with_layers(p_rest))}, *args,
                rngs=_mk_rngs(model, key, "embed"),
                method=spec.embed_method, **kwargs,
            )
            leaves, _, idx = _inexact_leaves(out)
            # The embed's own MoE aux (0.0 for dense embeds) rides along as
            # a final output so its balancing gradient is seeded below.
            return [leaves[i] for i in idx] + [aux]

        out_aval = jax.eval_shape(embed_inexact, params_rest)
        # Cotangent list: hidden cotangent (+ side cotangents for tuples),
        # then the aux seed.
        if sides is not None:
            cots = list(jax.tree_util.tree_leaves(dcarry)) + list(dside_row)
        else:
            cots = jax.tree_util.tree_leaves(dcarry)
        cots = cots + [aux_seed]
        cots = [c.astype(a.dtype) for c, a in zip(cots, out_aval)]
        _, vjp = jax.vjp(embed_inexact, params_rest)
        (dp,) = vjp(cots)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), acc, dp
        )
        return acc, None

    if spec.embed_method is not None:
        demb_params0 = param_grad_zeros(params_rest)
        dside_stack = tuple(dsides) if dsides is not None else ()
        demb_params, _ = jax.lax.scan(
            embed_bwd, demb_params0,
            (stacked_inputs, mb_keys, dembed, dside_stack),
        )
    else:
        demb_params = None

    # ---- assemble the full gradient tree -----------------------------

    # [S, maxp, ...] accumulated stage grads -> [L, ...] (scatter-add for
    # padded/uneven layouts; a pure reshape when the layout is dense).
    if active_np.all() and L == S * maxp:
        layer_grads = jax.tree_util.tree_map(
            lambda g: g.reshape((L,) + g.shape[2:]), dlay
        )
    else:
        flat_idx = jnp.asarray(idx_np.reshape(-1))
        flat_mask = active_np.reshape(-1)

        def to_layers(g):
            gf = g.reshape((S * maxp,) + g.shape[2:])
            gf = gf * flat_mask.reshape((-1,) + (1,) * (gf.ndim - 1))
            return jnp.zeros((L,) + g.shape[2:], g.dtype).at[flat_idx].add(gf)

        layer_grads = jax.tree_util.tree_map(to_layers, dlay)
    if demb_params is not None:
        # Embedding contributions (a rest-tree like drep; the layer
        # subtree never appears in either).
        drep = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(a.dtype), drep, demb_params
        )
    # Install the stage-accumulated layer grads into the rest-tree: the
    # result has the full parameter structure.
    grads = _set_subtree(drep, spec.layer_path, layer_grads)
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.result_type(p)), grads, params
    )
    return grads, losses, outs


def _pipeline_1f1b_virtual(model, params, stacked_inputs, rng, mb_loss_fn,
                           loss_seed_scale, virtual, rmode="full"):
    """1F1B with ``virtual`` interleaved model chunks per pipeline stage.

    ``rmode`` ("full" default) is the recompute-planner knob: under
    ``stash_all``/``auto`` the forward sub-step captures per-layer vjp
    residuals into a stash ring (``memory.recompute_ring_plan``'s
    ``f_to_b`` lifetime) and the backward sub-step consumes them instead
    of re-running the chunk forward under ``jax.vjp`` — the 1F1B
    B-recompute disappears where the plan stashes. At the default every
    code path below is untouched (the plan machinery never runs).

    Same numerical contract as the v=1 executor (grads/losses/outputs
    interchangeable with the fill-drain path), different schedule shape:

    - the partitioner cut the model into ``C = S*virtual`` chunks; global
      chunk ``c`` lives on stage ``c % S`` (``parallel/pipeline.py::
      chunk_layout``), so every chunk boundary crossing is a +1 rotation
      on the pp axis — ``jnp.roll`` -> one collective-permute, exactly as
      at v=1, just ``virtual`` times as often per microbatch;
    - ring buffers are keyed by (local chunk, microbatch): shape
      ``[S, V, W+1, ...]``;
    - stage transfers are DOUBLE-BUFFERED: tick t's fwd outputs / bwd
      cotangents park in transfer registers and the roll
      (collective-permute) + ring write happen at the START of tick t+1 —
      legal because the schedule's cross-chunk dependencies are strictly
      earlier-tick, and it places each permute next to compute that does
      not depend on it so the latency-hiding scheduler can overlap the
      t+1 transfer with tick t+1's first compute instead of serializing
      at the tick boundary;
    - the tick loop is split into three scans — forward-only warmup
      ticks, paired steady-state ticks, backward-only cooldown ticks
      (``interleaved_phase_bounds``). This is what makes the bubble
      shrink with ``virtual``: a rigidly paired tick would idle one full
      sub-step per warmup/cooldown tick and the sub-slot bubble would
      stay at its v=1 value no matter how many chunks exist.
    """
    spec = model._pipeline_spec
    cfg = state.cfg
    S = cfg.pipeline_parallel_degree
    M = cfg.microbatches
    L = spec.num_layers
    V = virtual
    W = min(cfg.active_microbatches or (S + 1), M)
    W1 = W + 1
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    layer_module = spec.layer_module
    half = cfg.half_dtype

    fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np = build_interleaved_1f1b_schedule(
        S, M, W, V
    )
    n_ticks = fwd_m_np.shape[0]
    t_b0, t_fe = interleaved_phase_bounds(fwd_m_np, bwd_m_np)
    from smdistributed_modelparallel_tpu.utils import health
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_pipeline_occupancy,
    )

    busy, total = schedule_occupancy(
        fwd_m_np, bwd_m_np, fwd_ticks=t_fe, bwd_ticks=n_ticks - t_b0
    )
    record_pipeline_occupancy(
        "1f1b", S, M, busy_slots=busy, total_slots=total, virtual=V
    )
    # Phase tick counts next to the occupancy gauges: the roofline
    # bubble attribution (utils/profiling.py) and the trace_fuse phase
    # view both read the warmup/steady/cooldown split from here.
    from smdistributed_modelparallel_tpu.utils.telemetry import telemetry

    _phase_gauge = telemetry.gauge(
        "smp_pipeline_phase_ticks",
        "ticks per interleaved schedule phase (warmup/steady/cooldown)",
    )
    _phase_gauge.labels(phase="warmup").set(t_b0)
    _phase_gauge.labels(phase="steady").set(t_fe - t_b0)
    _phase_gauge.labels(phase="cooldown").set(n_ticks - t_fe)
    # Slot events carry the GLOBAL chunk (boundary) index k*S + s: stage
    # says where the work ran, chunk identifies the layers — the same
    # coordinates the fill-drain executor records for chunked specs.
    flight_recorder.record_schedule(
        "1f1b",
        ((t, s, d, int(m_arr[t, s]), int(k_arr[t, s]) * S + s)
         for t in range(n_ticks) for s in range(S)
         for d, k_arr, m_arr in (("fwd", fwd_k_np, fwd_m_np),
                                 ("bwd", bwd_k_np, bwd_m_np))
         if m_arr[t, s] >= 0),
    )
    fwd_k_sched = jnp.asarray(fwd_k_np)
    fwd_m_sched = jnp.asarray(fwd_m_np)
    bwd_k_sched = jnp.asarray(bwd_k_np)
    bwd_m_sched = jnp.asarray(bwd_m_np)

    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        _get_subtree,
        _mk_rngs,
        _scan_map,
        chunk_layout,
        staged_chunk_views,
    )

    def cast_half(tree):
        from smdistributed_modelparallel_tpu.nn.utils import half_cast

        return half_cast(tree, half)

    layer_params = _get_subtree(params, spec.layer_path)
    staged_params, staged_xs, active_rows = staged_chunk_views(
        spec, layer_params, S, V
    )

    # The chunked gather ([L] -> [S, V, maxp]) breaks the sharding
    # propagation that gives the v=1 executor its stage placement for free
    # (a reshape keeps dim 0 on pp; a gather's output is unconstrained, and
    # GSPMD then happily replicates the whole tick loop). Pin ONLY the
    # leading stage axis of every stage-parallel value to the pp mesh axis
    # and leave the rest unconstrained so batch/tp shardings still
    # propagate.
    from jax.sharding import NamedSharding, PartitionSpec as _P

    from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS

    mesh = state.mesh
    _pp_size = dict(mesh.shape).get(PP_AXIS, 1) if mesh is not None else 1

    def pin_stage_axis(tree):
        if mesh is None or _pp_size <= 1:
            return tree

        # UNCONSTRAINED (not None) on the non-stage dims is load-bearing
        # for pp x zero3 composition: None would force the staged views
        # replicated, upfront-gathering every rdp-sharded parameter
        # before the tick loop. UNCONSTRAINED lets propagation keep the
        # rdp dims sharded, so the all-gather lands INSIDE the loop at
        # each stage's point of use (per-stage gather scoping — asserted
        # by the zero3 composition gate's loop_gather_ops census).
        def pin(x):
            if getattr(x, "ndim", 0) < 1 or x.shape[0] != S:
                return x
            rest = [_P.UNCONSTRAINED] * (x.ndim - 1)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _P(PP_AXIS, *rest))
            )

        return jax.tree_util.tree_map(pin, tree)

    staged_params = pin_stage_axis(staged_params)
    staged_xs = pin_stage_axis(staged_xs)
    params_rest = _set_subtree(params, spec.layer_path, {})

    def with_layers(p_rest):
        return _set_subtree(p_rest, spec.layer_path, layer_params)

    idx_np, active_np, maxp = chunk_layout(spec, S, V)

    mb_keys = jax.random.split(rng, M)

    # ---- embed all microbatches (the input queue) --------------------

    def embed_mb(mb_input, key):
        args, kwargs = mb_input
        if spec.embed_method is None:
            return args[0]
        return module.apply(
            {"params": cast_half(params)}, *args,
            rngs=_mk_rngs(model, key, "embed"),
            method=spec.embed_method, **kwargs,
        )

    with named_region("smp/pipeline/embed"):
        embedded = _scan_map(embed_mb, stacked_inputs, mb_keys)

    if spec.carry_is_tuple:
        hidden_q = embedded[0]
        sides = embedded[1:]
    else:
        hidden_q = embedded
        sides = None

    carry_aval = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), hidden_q
    )

    # ---- per-chunk forward (pure in chunk params and carry) ----------

    from smdistributed_modelparallel_tpu.parallel.memory import remat_policy
    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        apply_collecting_aux,
        make_layer_apply,
    )

    apply_one_layer = make_layer_apply(
        model, spec, layer_module, side_in_carry=False
    )

    if spec.carry_remat:
        apply_one_layer = jax.checkpoint(apply_one_layer, policy=remat_policy())

    def chunk_fwd(chunk_lp, chunk_lxs, x, side, c_idx, m_idx, act_row):
        """Apply one chunk's layer slots; keys derived from (global chunk,
        mb) — at V=1 the global chunk id IS the stage id, so the key
        schedule is the v=1 executor's. Returns (carry, summed MoE aux)."""
        base = jax.random.fold_in(jax.random.fold_in(rng, c_idx), m_idx)
        chunk_lp = cast_half(chunk_lp)

        def body(c, xs):
            lp, lxs, i, act = xs
            new_c, aux = apply_one_layer(
                lp, c, lxs, jax.random.fold_in(base, i), side
            )
            out_c = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new_c, c
            )
            return out_c, jnp.where(act, aux, 0.0)

        idx = jnp.arange(maxp)
        out, auxs = jax.lax.scan(body, x, (chunk_lp, chunk_lxs, idx, act_row))
        return out, jnp.sum(auxs)

    def gather_mb(tree, m):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
            tree,
        )

    def gather_sides_rows(ms):
        if sides is None:
            return None
        return tuple(
            jax.tree_util.tree_map(
                lambda a: jax.vmap(
                    lambda i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
                )(ms),
                s,
            )
            for s in sides
        )

    def select_chunk(tree, krow):
        """Per-stage view of one chunk: [S, V, ...] -> [S, ...] at krow[s]."""
        return jax.tree_util.tree_map(
            lambda a: jax.vmap(
                lambda av, k: jax.lax.dynamic_index_in_dim(av, k, 0, keepdims=False)
            )(a, krow),
            tree,
        )

    # ---- head + user loss (last stage, last chunk only) ---------------

    def head_apply_aux(p, carry, key):
        if spec.head_method is None:
            return carry, jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": cast_half(p)}, carry,
            rngs=_mk_rngs(model, key, "head"), method=spec.head_method,
        )

    def head_apply(p, carry, key):
        return head_apply_aux(p, carry, key)[0]

    loss_out_aval = jax.eval_shape(
        lambda c: mb_loss_fn(head_apply(params, c, mb_keys[0]), 0, mb_keys[0]),
        jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), carry_aval),
    )

    # ---- buffers ------------------------------------------------------

    def zeros_chunk_ring(n):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S, V, n) + a.shape, a.dtype), carry_aval
        )

    def zeros_stage_rows():
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S,) + a.shape, a.dtype), carry_aval
        )

    grad_dtype = jnp.float32

    def _acc_dtype(dtype):
        if jnp.issubdtype(dtype, jnp.floating) and cfg._fp32_grad_accumulation:
            return jnp.float32
        return dtype

    def param_grad_zeros(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _acc_dtype(p.dtype)), tree
        )

    inbuf0 = zeros_chunk_ring(W1)    # inbuf[s, k, m % W1]: fwd input of (k, m)
    stash0 = zeros_chunk_ring(W1)    # consumed fwd inputs (bwd recompute)
    cotbuf0 = zeros_chunk_ring(W1)   # output cotangent of (k, m)
    outbuf0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((S, W1) + a.shape, a.dtype), carry_aval
    )                                # last chunk's fwd output (row S-1 only)
    xfer_f0 = zeros_stage_rows()     # tick t's raw fwd outputs, rolled at t+1
    xfer_b0 = zeros_stage_rows()     # tick t's raw input cotangents, ditto
    dlay0 = param_grad_zeros(staged_params)
    drep0 = param_grad_zeros(params_rest)
    dembed0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, grad_dtype), carry_aval
    )
    side_leaves = side_treedef = side_idx = None
    dsides0 = None
    if sides is not None:
        side_leaves, side_treedef, side_idx = _inexact_leaves(
            tuple(jax.tree_util.tree_map(lambda a: a[0], s) for s in sides)
        )
        dsides0 = [
            jnp.zeros((M,) + side_leaves[i].shape, grad_dtype) for i in side_idx
        ]
    losses0 = jnp.zeros((M,), jnp.float32)
    outs0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, a.dtype), loss_out_aval[1]
    )

    stage_ids = jnp.arange(S)
    aux_w = float(getattr(cfg, "moe_aux_loss_weight", 1.0))
    aux_seed = (
        jnp.asarray(aux_w, jnp.float32)
        * jnp.asarray(loss_seed_scale, jnp.float32)
    )

    # Ring/scatter primitives shared with the zero-bubble executor
    # (module level — see _chunk_ring_set and friends above).
    set_ring = _chunk_ring_set
    get_ring = _chunk_ring_get
    set_outbuf = _chunk_outbuf_set
    scatter_add_mb = _chunk_scatter_add_mb
    scatter_set_mb = _chunk_scatter_set_mb
    _scatter_add_leaf = _chunk_scatter_add_leaf
    scatter_chunk_stat = _chunk_scatter_stat

    # ---- recompute planner (stash_all / auto): capture residuals at F,
    # consume at B — everything below is inert at rmode == "full".
    rstash = False
    all_rstash = True
    fres0 = None
    if rmode != "full":
        from smdistributed_modelparallel_tpu.parallel import remat_plan
        from smdistributed_modelparallel_tpu.parallel.memory import (
            recompute_ring_plan,
        )

        stash_rings = recompute_ring_plan(
            fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np,
            num_stages=S, virtual=V,
        )
        side_leaf_avals = (
            [side_leaves[i] for i in side_idx] if sides is not None else []
        )
        (capture_fwd, _bwd_in, bwd_full_from_res, _wgt,
         _captured) = _make_residual_split(
            apply_one_layer, cast_half, rng, maxp, aux_seed,
            sides is not None, side_leaf_avals=side_leaf_avals,
        )
        res_avals = _probe_stash_avals(
            S, staged_params, staged_xs, active_rows, carry_aval, sides,
            capture_fwd,
        )
        rplan = remat_plan.plan_pipeline(
            "1f1b", rmode, S, V,
            res_ring_slots=stash_rings["f_to_b"], cot_ring_slots=0,
            res_slot_bytes=_stash_slot_bytes(res_avals),
            cot_slot_bytes=0, cfg=cfg,
        )
        if rplan.effective != "full":
            rstash = True
            stash_of_arr, res_col_arr, Vs_r, all_rstash = (
                _stash_chunk_maps(rplan, V)
            )
            Rfb = rplan.res_ring_slots
            fres0 = jax.tree_util.tree_map(
                lambda a: jnp.zeros((S, Vs_r, Rfb) + a.shape[1:], a.dtype),
                res_avals,
            )

    hc = health.active()

    def tick_impl(carry, t, do_fwd, do_bwd):
        """One schedule tick. ``do_fwd``/``do_bwd`` are STATIC phase flags:
        warmup ticks compile only the forward sub-step, cooldown ticks only
        the backward one — the idle sub-steps are never part of the
        program, which is what the occupancy accounting assumes."""
        fres = None
        if rstash:
            fres = carry[-1]
            carry = carry[:-1]
        if hc is not None:
            (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay, drep,
             dembed, dsides, losses, outs, (hbad, habs, hmb)) = carry
        else:
            (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay, drep,
             dembed, dsides, losses, outs) = carry

        # ---------------- deferred stage transfers ----------------
        # Tick t-1's fwd outputs / bwd cotangents cross the pp axis here
        # (jnp.roll -> collective-permute) and land in the rings before
        # this tick's compute reads them. Chunk routing: fwd output of
        # (stage s, chunk k) feeds (s+1 mod S, k + [s == S-1]); bwd input
        # cotangent of (s, k) feeds (s-1 mod S, k - [s == 0]).
        prev = jnp.maximum(t - 1, 0)
        was_prev = t > 0
        # Forward merge only in fwd-capable phases: the last forward tick
        # can only contain LAST-chunk forwards (fwd(c,m) < fwd(c+1,m) and
        # nothing later could consume a non-last chunk's output), and those
        # route to outbuf, never the ring — so cooldown ticks would compile
        # a provably all-masked roll (one dead collective-permute per tick).
        if do_fwd:
            pk = fwd_k_sched[prev]
            pm = fwd_m_sched[prev]
            p_act = (pm >= 0) & was_prev
            dst_k = jnp.roll(pk, 1) + (stage_ids == 0)
            dst_m = jnp.roll(jnp.maximum(pm, 0), 1)
            # The last chunk's output (dst_k == V) is the head input, kept
            # in outbuf at its producing tick, not routed forward.
            dst_act = jnp.roll(p_act, 1) & (dst_k < V)
            inbuf = set_ring(
                inbuf, jnp.clip(dst_k, 0, V - 1), dst_m % W1,
                jax.tree_util.tree_map(lambda o: jnp.roll(o, 1, axis=0), xfer_f),
                dst_act,
            )
        if do_bwd:
            pbk = bwd_k_sched[prev]
            pbm = bwd_m_sched[prev]
            pb_act = (pbm >= 0) & was_prev
            dst_bk = jnp.roll(pbk, -1) - (stage_ids == S - 1)
            dst_bm = jnp.roll(jnp.maximum(pbm, 0), -1)
            # Global chunk 0's input cotangent (dst_bk == -1) went to the
            # embedding accumulator at its producing tick.
            dst_b_act = jnp.roll(pb_act, -1) & (dst_bk >= 0)
            cotbuf = set_ring(
                cotbuf, jnp.clip(dst_bk, 0, V - 1), dst_bm % W1,
                jax.tree_util.tree_map(lambda o: jnp.roll(o, -1, axis=0), xfer_b),
                dst_b_act,
            )

        # ---------------- forward sub-step ----------------
        if do_fwd:
            fk = fwd_k_sched[t]
            fm = fwd_m_sched[t]
            f_active = fm >= 0
            fkc = jnp.clip(fk, 0, V - 1)
            fmc = jnp.maximum(fm, 0)
            f_slots = fmc % W1
            ch_params = select_chunk(staged_params, fkc)
            ch_xs = select_chunk(staged_xs, fkc)
            ch_act = select_chunk(active_rows, fkc)
            # Stage 0 chunk 0 reads the embedded queue; everything else
            # reads its ring slot.
            from_q = gather_mb(hidden_q, fmc[0])
            buf_in = get_ring(inbuf, fkc, f_slots)
            x_in = jax.tree_util.tree_map(
                lambda q, b: b.at[0].set(jnp.where(fkc[0] == 0, q, b[0])),
                from_q, buf_in,
            )
            f_sides = gather_sides_rows(fmc)
            c_ids = fkc * S + stage_ids
            with named_region("smp/pipeline/tick_fwd"):
                if rstash:
                    # Same forward compute; the per-layer vjp capture
                    # additionally emits the residual leaves the backward
                    # sub-step will consume instead of re-running this.
                    outs_f, _aux_f, res_f = jax.vmap(
                        capture_fwd,
                        in_axes=(0, 0, 0, 0 if sides is not None else None,
                                 0, 0, 0),
                    )(ch_params, ch_xs, x_in, f_sides, c_ids, fmc, ch_act)
                    fres = set_ring(
                        fres, res_col_arr[fkc], fmc % Rfb, res_f,
                        f_active & stash_of_arr[fkc],
                    )
                else:
                    outs_f, _aux_f = jax.vmap(
                        chunk_fwd,
                        in_axes=(0, 0, 0, 0 if sides is not None else None,
                                 0, 0, 0),
                    )(ch_params, ch_xs, x_in, f_sides, c_ids, fmc, ch_act)
            outs_f = pin_stage_axis(outs_f)
            stash = set_ring(stash, fkc, f_slots, x_in, f_active)
            if hc is not None:
                brow, arow = health.stage_row_stats(outs_f, S)
                brow = jnp.where(f_active, brow, 0.0)
                arow = jnp.where(f_active, arow, 0.0)
                hmb = scatter_chunk_stat(
                    hmb, fkc, fmc.astype(jnp.float32),
                    f_active & (brow > 0),
                    lambda cur, mb: jnp.where(cur < 0, mb, cur),
                )
                hbad = scatter_chunk_stat(
                    hbad, fkc, brow, f_active, lambda cur, v: cur + v
                )
                habs = scatter_chunk_stat(
                    habs, fkc, arow, f_active, jnp.maximum
                )
            last_row_active = f_active & (stage_ids == S - 1) & (fkc == V - 1)
            outbuf = set_outbuf(outbuf, f_slots, outs_f, last_row_active)
            xfer_f = outs_f

        # ---------------- backward sub-step ----------------
        if do_bwd:
            bk = bwd_k_sched[t]
            bm = bwd_m_sched[t]
            b_active = bm >= 0
            bkc = jnp.clip(bk, 0, V - 1)
            bmc = jnp.maximum(bm, 0)
            b_slots = bmc % W1

            # Head + user loss VJP on the stashed LAST-chunk output: only
            # meaningful when stage S-1 backwards chunk V-1 this tick.
            is_lastk = b_active[S - 1] & (bkc[S - 1] == V - 1)
            m_last = bmc[S - 1]
            key_last = jax.lax.dynamic_index_in_dim(
                mb_keys, m_last, 0, keepdims=False
            )
            out_last = jax.tree_util.tree_map(
                lambda ob: jax.lax.dynamic_index_in_dim(
                    ob[S - 1], b_slots[S - 1], 0, keepdims=False
                ),
                outbuf,
            )

            def head_loss(p_rest, out):
                final, h_aux = head_apply_aux(with_layers(p_rest), out, key_last)
                loss, user_out = mb_loss_fn(final, m_last, key_last)
                loss = loss + jnp.asarray(aux_w, loss.dtype) * h_aux.astype(
                    loss.dtype
                )
                return loss, user_out

            def run_head():
                loss_m, head_vjp, user_out = jax.vjp(
                    head_loss, params_rest, out_last, has_aux=True
                )
                seed = jnp.asarray(loss_seed_scale, loss_m.dtype)
                d_rep, d_out_last = head_vjp(seed)
                return loss_m.astype(jnp.float32), d_rep, d_out_last, user_out

            # Only 1/V of the backward ticks carry the last chunk, but the
            # head+loss VJP is replicated (not stage-parallel) work: run it
            # under lax.cond so the other ticks skip it entirely instead of
            # computing it masked — at vocab-sized heads the masked version
            # would cost ~V x the v=1 executor's replicated compute.
            head_aval = jax.eval_shape(run_head)
            with named_region("smp/pipeline/head"):
                loss_m, d_rep, d_out_last, user_out = jax.lax.cond(
                    is_lastk,
                    run_head,
                    lambda: jax.tree_util.tree_map(
                        lambda a: jnp.zeros(a.shape, a.dtype), head_aval
                    ),
                )

            cot_in = get_ring(cotbuf, bkc, b_slots)
            cot_in = jax.tree_util.tree_map(
                lambda c, d: c.at[S - 1].set(
                    jnp.where(is_lastk, d.astype(c.dtype), c[S - 1])
                ),
                cot_in, d_out_last,
            )
            b_sides = gather_sides_rows(bmc)
            stash_in = get_ring(stash, bkc, b_slots)
            ch_params_b = select_chunk(staged_params, bkc)
            ch_xs_b = select_chunk(staged_xs, bkc)
            ch_act_b = select_chunk(active_rows, bkc)
            c_ids_b = bkc * S + stage_ids

            def chunk_bwd(lp, lxs, x, side, cot, c_idx, m_idx, act_row):
                def f(lp_, x_, side_):
                    return chunk_fwd(lp_, lxs, x_, side_, c_idx, m_idx, act_row)

                _, vjp = jax.vjp(f, lp, x, side)
                return vjp((cot, aux_seed))

            d_side_leaf_rows = None
            with named_region("smp/pipeline/tick_bwd"):
                if rstash:
                    # Backward from the residuals the forward sub-step
                    # stashed: no forward re-run for stashed chunks.
                    res_b = get_ring(fres, res_col_arr[bkc], bmc % Rfb)
                    d_lp_res, d_x_res, side_res = jax.vmap(
                        bwd_full_from_res
                    )(res_b, cot_in)
                    if all_rstash:
                        d_lp_rows, d_x_rows = d_lp_res, d_x_res
                        d_side_leaf_rows = side_res
                    else:
                        # Budget-degraded chunks keep the recompute path;
                        # a static per-chunk mask selects.
                        d_lp_rec, d_x_rec, d_side_rec = jax.vmap(
                            chunk_bwd,
                            in_axes=(0, 0, 0,
                                     0 if sides is not None else None,
                                     0, 0, 0, 0),
                        )(ch_params_b, ch_xs_b, stash_in,
                          b_sides, cot_in, c_ids_b, bmc, ch_act_b)
                        bmask = stash_of_arr[bkc]

                        def sel(a, b):
                            return jnp.where(
                                bmask.reshape((S,) + (1,) * (a.ndim - 1)),
                                a, b.astype(a.dtype),
                            )

                        d_lp_rows = jax.tree_util.tree_map(
                            sel, d_lp_res, d_lp_rec
                        )
                        d_x_rows = jax.tree_util.tree_map(
                            sel, d_x_res, d_x_rec
                        )
                        if sides is not None:
                            rec_all, _, _ = _inexact_leaves(d_side_rec)
                            d_side_leaf_rows = [
                                sel(a, rec_all[i])
                                for a, i in zip(side_res, side_idx)
                            ]
                else:
                    d_lp_rows, d_x_rows, d_side_rows = jax.vmap(
                        chunk_bwd,
                        in_axes=(0, 0, 0, 0 if sides is not None else None,
                                 0, 0, 0, 0),
                    )(ch_params_b, ch_xs_b, stash_in,
                      b_sides, cot_in, c_ids_b, bmc, ch_act_b)
            d_lp_rows = pin_stage_axis(d_lp_rows)
            d_x_rows = pin_stage_axis(d_x_rows)

            # Accumulate layer grads into the per-(stage, chunk) slot.
            dlay = _chunk_acc_rows(dlay, d_lp_rows, bkc, b_active)

            drep = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(is_lastk, g.astype(a.dtype), 0),
                drep, d_rep,
            )

            dembed = scatter_add_mb(
                dembed, bmc[0],
                jax.tree_util.tree_map(lambda r: r[0], d_x_rows),
                b_active[0] & (bkc[0] == 0),
            )

            if sides is not None and dsides is not None:
                if d_side_leaf_rows is not None:
                    for s in range(S):
                        dsides = [
                            _scatter_add_leaf(d, bmc[s], leaf[s], b_active[s])
                            for d, leaf in zip(dsides, d_side_leaf_rows)
                        ]
                else:
                    def one_stage_side_add(ds, s):
                        row_leaves, _, _ = _inexact_leaves(
                            jax.tree_util.tree_map(
                                lambda r: r[s], d_side_rows
                            )
                        )
                        vals = [row_leaves[i] for i in side_idx]
                        return [
                            _scatter_add_leaf(d, bmc[s], v, b_active[s])
                            for d, v in zip(ds, vals)
                        ]

                    for s in range(S):
                        dsides = one_stage_side_add(dsides, s)

            losses = losses.at[m_last].set(
                jnp.where(is_lastk, loss_m.astype(jnp.float32), losses[m_last])
            )
            outs = scatter_set_mb(outs, m_last, user_out, is_lastk)
            xfer_b = d_x_rows

        new_carry = (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay,
                     drep, dembed, dsides, losses, outs)
        if hc is not None:
            new_carry = new_carry + ((hbad, habs, hmb),)
        if rstash:
            new_carry = new_carry + (fres,)
        return new_carry, None

    carry0 = (
        pin_stage_axis(inbuf0), pin_stage_axis(stash0),
        pin_stage_axis(cotbuf0), pin_stage_axis(outbuf0),
        pin_stage_axis(xfer_f0), pin_stage_axis(xfer_b0),
        pin_stage_axis(dlay0), drep0, dembed0, dsides0, losses0, outs0,
    )
    if hc is not None:
        carry0 = carry0 + ((
            jnp.zeros((S, V), jnp.float32), jnp.zeros((S, V), jnp.float32),
            jnp.full((S, V), -1.0, jnp.float32),
        ),)
    if rstash:
        carry0 = carry0 + (pin_stage_axis(fres0),)

    # Named profiler regions per schedule phase: an XLA trace of the
    # compiled step shows the warmup/steady/cooldown loops as separately
    # labeled op groups, so bubble time is attributable to its ramp.
    with named_region("smp/pipeline/warmup"):
        carry_end, _ = jax.lax.scan(
            lambda c, t: tick_impl(c, t, True, False), carry0,
            jnp.arange(0, t_b0),
        )
    with named_region("smp/pipeline/steady"):
        carry_end, _ = jax.lax.scan(
            lambda c, t: tick_impl(c, t, True, True), carry_end,
            jnp.arange(t_b0, t_fe),
        )
    with named_region("smp/pipeline/cooldown"):
        carry_end, _ = jax.lax.scan(
            lambda c, t: tick_impl(c, t, False, True), carry_end,
            jnp.arange(t_fe, n_ticks),
        )
    if rstash:
        carry_end = carry_end[:-1]
    if hc is not None:
        (_, _, _, _, _, _, dlay, drep, dembed, dsides, losses, outs,
         (hbad, habs, hmb)) = carry_end
        # Grid position (s, k) holds GLOBAL chunk k*S + s.
        chunk_ids = np.arange(V)[None, :] * S + np.arange(S)[:, None]
        hc.add_stage_stats("1f1b", hbad, habs, hmb, chunk_ids=chunk_ids)
    else:
        (_, _, _, _, _, _, dlay, drep, dembed, dsides, losses,
         outs) = carry_end

    # ---- embedding backward ------------------------------------------

    def embed_bwd(acc, xs):
        mb_input, key, dcarry, dside_row = xs

        def embed_inexact(p_rest):
            args, kwargs = mb_input
            out, aux = apply_collecting_aux(
                module, {"params": cast_half(with_layers(p_rest))}, *args,
                rngs=_mk_rngs(model, key, "embed"),
                method=spec.embed_method, **kwargs,
            )
            leaves, _, idx = _inexact_leaves(out)
            return [leaves[i] for i in idx] + [aux]

        out_aval = jax.eval_shape(embed_inexact, params_rest)
        if sides is not None:
            cots = list(jax.tree_util.tree_leaves(dcarry)) + list(dside_row)
        else:
            cots = jax.tree_util.tree_leaves(dcarry)
        cots = cots + [aux_seed]
        cots = [c.astype(a.dtype) for c, a in zip(cots, out_aval)]
        _, vjp = jax.vjp(embed_inexact, params_rest)
        (dp,) = vjp(cots)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), acc, dp
        )
        return acc, None

    if spec.embed_method is not None:
        demb_params0 = param_grad_zeros(params_rest)
        dside_stack = tuple(dsides) if dsides is not None else ()
        demb_params, _ = jax.lax.scan(
            embed_bwd, demb_params0,
            (stacked_inputs, mb_keys, dembed, dside_stack),
        )
    else:
        demb_params = None

    # ---- assemble the full gradient tree -----------------------------

    # [S, V, maxp, ...] accumulated chunk grads -> [L, ...]. The chunked
    # placement interleaves the layer axis across stages, so this is
    # always a scatter-add (the v=1 dense-reshape shortcut cannot apply).
    flat_idx = jnp.asarray(idx_np.reshape(-1))
    flat_mask = active_np.reshape(-1)

    def to_layers(g):
        gf = g.reshape((S * V * maxp,) + g.shape[3:])
        gf = gf * flat_mask.reshape((-1,) + (1,) * (gf.ndim - 1))
        return jnp.zeros((L,) + g.shape[3:], g.dtype).at[flat_idx].add(gf)

    layer_grads = jax.tree_util.tree_map(to_layers, dlay)
    if demb_params is not None:
        drep = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(a.dtype), drep, demb_params
        )
    grads = _set_subtree(drep, spec.layer_path, layer_grads)
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.result_type(p)), grads, params
    )
    return grads, losses, outs


def _pipeline_zero_bubble(model, params, stacked_inputs, rng, mb_loss_fn,
                          loss_seed_scale, virtual):
    """ZB-H1 executor: backward split into B (input-grad) and W
    (weight-grad) passes over (chunk, microbatch, pass) schedule units.

    Same numerical contract as the 1F1B executors (grads/losses/outputs
    interchangeable with the fill-drain path at any (pp, v, mb, window));
    the schedule shape differs from ``_pipeline_1f1b_virtual`` in one
    way: each tick has up to THREE sub-steps — F, B, W — and the
    monolithic per-chunk VJP is split:

    - the B sub-step re-runs the chunk forward from the stashed input
      under ``jax.vjp`` w.r.t. (input, sides) ONLY: the input cotangent
      ships upstream immediately (it is the critical path) and the
      weight cotangent is never formed;
    - the W sub-step re-runs the same forward under ``jax.vjp`` w.r.t.
      the chunk params at a LATER tick, re-reading the stashed input and
      the retained output cotangent — the deferred weight-grad work that
      fills the B-drain cooldown, where the monolithic schedule idles;
    - the ring buffers double as the W-queue: stash/cotangent entries
      stay live until the W pass consumes them, so the ring slot count
      comes from ``parallel/memory.py::zero_bubble_ring_plan`` (exact
      alive-depth over the static schedule; == window+1 at the default
      window, i.e. ZB's same-activation-memory claim holds exactly);
    - the head/loss VJP stays monolithic at the last chunk's B tick (it
      produces the cotangent B needs; its param grads are replicated
      work, not a pipeline stage) and its output cotangent is written
      INTO the cotangent ring so the last chunk's W can re-read it.

    The tick loop compiles one scan per contiguous segment of active
    passes (``_zb_segments``): warmup ticks are F-only, the B-drain
    cooldown compiles B+W, and a possible W-only tail drains the queue —
    out-of-span sub-steps never enter the program, which is what the
    occupancy accounting (2*(pp-1)/(3*v*mb + 2*(pp-1)) at the packed
    configs) assumes. GSPMD stage-axis pins and the double-buffered
    transfer registers carry over from the virtual executor unchanged
    (W produces no transfers: weight grads stay stage-local).
    """
    spec = model._pipeline_spec
    cfg = state.cfg
    S = cfg.pipeline_parallel_degree
    M = cfg.microbatches
    L = spec.num_layers
    V = virtual
    W = min(cfg.active_microbatches or (S + 1), M)
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    layer_module = spec.layer_module
    half = cfg.half_dtype

    (fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np, wgt_k_np,
     wgt_m_np) = build_zero_bubble_schedule(S, M, W, V)
    n_ticks = fwd_m_np.shape[0]
    f_span, b_span, w_span = zero_bubble_phase_bounds(
        fwd_m_np, bwd_m_np, wgt_m_np
    )
    segments = _zb_segments(f_span, b_span, w_span, n_ticks)

    from smdistributed_modelparallel_tpu.parallel.memory import (
        zero_bubble_ring_plan,
    )

    plan = zero_bubble_ring_plan(
        fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np, wgt_k_np, wgt_m_np,
        num_stages=S, virtual=V, window=W,
    )
    R1 = plan["ring_slots"]

    from smdistributed_modelparallel_tpu.utils import health
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_pipeline_occupancy,
        telemetry,
    )

    f_len = f_span[1] - f_span[0]
    b_len = b_span[1] - b_span[0]
    w_len = w_span[1] - w_span[0]
    busy, total = schedule_occupancy(
        fwd_m_np, bwd_m_np, fwd_ticks=f_len, bwd_ticks=b_len,
        wgt=wgt_m_np, wgt_ticks=w_len,
    )
    record_pipeline_occupancy(
        "zb", S, M, busy_slots=busy, total_slots=total, virtual=V,
        passes=3,
        pass_ticks={"fwd": f_len, "bwd_input": b_len, "bwd_weight": w_len},
    )
    # W-queue accounting next to the occupancy gauges: ring slots actually
    # allocated per (stage, chunk) and the peak number of deferred
    # weight-grad units — the memory side of the bubble trade.
    _ring_gauge = telemetry.gauge(
        "smp_pipeline_ring_slots",
        "per-(stage, chunk) ring-buffer slots of the pipeline executor",
    )
    _ring_gauge.labels(schedule="zb").set(R1)
    telemetry.gauge(
        "smp_pipeline_wqueue_peak",
        "peak deferred weight-grad units per (stage, chunk) [zero-bubble]",
    ).labels(schedule="zb").set(plan["w_queue_peak"])
    flight_recorder.record_schedule(
        "zb",
        ((t, s, d, int(m_arr[t, s]), int(k_arr[t, s]) * S + s, p)
         for t in range(n_ticks) for s in range(S)
         for d, p, k_arr, m_arr in (
             ("fwd", "F", fwd_k_np, fwd_m_np),
             ("bwd_input", "B", bwd_k_np, bwd_m_np),
             ("bwd_weight", "W", wgt_k_np, wgt_m_np))
         if m_arr[t, s] >= 0),
    )
    fwd_k_sched = jnp.asarray(fwd_k_np)
    fwd_m_sched = jnp.asarray(fwd_m_np)
    bwd_k_sched = jnp.asarray(bwd_k_np)
    bwd_m_sched = jnp.asarray(bwd_m_np)
    wgt_k_sched = jnp.asarray(wgt_k_np)
    wgt_m_sched = jnp.asarray(wgt_m_np)

    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        _get_subtree,
        _mk_rngs,
        _scan_map,
        chunk_layout,
        staged_chunk_views,
    )

    def cast_half(tree):
        from smdistributed_modelparallel_tpu.nn.utils import half_cast

        return half_cast(tree, half)

    layer_params = _get_subtree(params, spec.layer_path)
    staged_params, staged_xs, active_rows = staged_chunk_views(
        spec, layer_params, S, V
    )

    # Stage-axis sharding pins: same rationale as the virtual executor
    # (the chunked gather breaks GSPMD's propagation; pin ONLY dim 0).
    from jax.sharding import NamedSharding, PartitionSpec as _P

    from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS

    mesh = state.mesh
    _pp_size = dict(mesh.shape).get(PP_AXIS, 1) if mesh is not None else 1

    def pin_stage_axis(tree):
        if mesh is None or _pp_size <= 1:
            return tree

        # UNCONSTRAINED (not None) on the non-stage dims is load-bearing
        # for pp x zero3 composition: None would force the staged views
        # replicated, upfront-gathering every rdp-sharded parameter
        # before the tick loop. UNCONSTRAINED lets propagation keep the
        # rdp dims sharded, so the all-gather lands INSIDE the loop at
        # each stage's point of use (per-stage gather scoping — asserted
        # by the zero3 composition gate's loop_gather_ops census).
        def pin(x):
            if getattr(x, "ndim", 0) < 1 or x.shape[0] != S:
                return x
            rest = [_P.UNCONSTRAINED] * (x.ndim - 1)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _P(PP_AXIS, *rest))
            )

        return jax.tree_util.tree_map(pin, tree)

    staged_params = pin_stage_axis(staged_params)
    staged_xs = pin_stage_axis(staged_xs)
    params_rest = _set_subtree(params, spec.layer_path, {})

    def with_layers(p_rest):
        return _set_subtree(p_rest, spec.layer_path, layer_params)

    idx_np, active_np, maxp = chunk_layout(spec, S, V)

    mb_keys = jax.random.split(rng, M)

    # ---- embed all microbatches (the input queue) --------------------

    def embed_mb(mb_input, key):
        args, kwargs = mb_input
        if spec.embed_method is None:
            return args[0]
        return module.apply(
            {"params": cast_half(params)}, *args,
            rngs=_mk_rngs(model, key, "embed"),
            method=spec.embed_method, **kwargs,
        )

    with named_region("smp/pipeline/embed"):
        embedded = _scan_map(embed_mb, stacked_inputs, mb_keys)

    if spec.carry_is_tuple:
        hidden_q = embedded[0]
        sides = embedded[1:]
    else:
        hidden_q = embedded
        sides = None

    carry_aval = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), hidden_q
    )

    # ---- per-chunk forward (pure in chunk params and carry) ----------

    from smdistributed_modelparallel_tpu.parallel.memory import remat_policy
    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        apply_collecting_aux,
        make_layer_apply,
    )

    apply_one_layer = make_layer_apply(
        model, spec, layer_module, side_in_carry=False
    )

    if spec.carry_remat:
        apply_one_layer = jax.checkpoint(apply_one_layer, policy=remat_policy())

    def chunk_fwd(chunk_lp, chunk_lxs, x, side, c_idx, m_idx, act_row):
        """Apply one chunk's layer slots; keys derived from (global chunk,
        mb), so the B and W recomputes reproduce the forward (dropout
        included) exactly. Returns (carry, summed MoE aux)."""
        base = jax.random.fold_in(jax.random.fold_in(rng, c_idx), m_idx)
        chunk_lp = cast_half(chunk_lp)

        def body(c, xs):
            lp, lxs, i, act = xs
            new_c, aux = apply_one_layer(
                lp, c, lxs, jax.random.fold_in(base, i), side
            )
            out_c = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new_c, c
            )
            return out_c, jnp.where(act, aux, 0.0)

        idx = jnp.arange(maxp)
        out, auxs = jax.lax.scan(body, x, (chunk_lp, chunk_lxs, idx, act_row))
        return out, jnp.sum(auxs)

    def gather_mb(tree, m):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
            tree,
        )

    def gather_sides_rows(ms):
        if sides is None:
            return None
        return tuple(
            jax.tree_util.tree_map(
                lambda a: jax.vmap(
                    lambda i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
                )(ms),
                s,
            )
            for s in sides
        )

    def select_chunk(tree, krow):
        """Per-stage view of one chunk: [S, V, ...] -> [S, ...] at krow[s]."""
        return jax.tree_util.tree_map(
            lambda a: jax.vmap(
                lambda av, k: jax.lax.dynamic_index_in_dim(av, k, 0, keepdims=False)
            )(a, krow),
            tree,
        )

    # ---- head + user loss (last stage, last chunk only) ---------------

    def head_apply_aux(p, carry, key):
        if spec.head_method is None:
            return carry, jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": cast_half(p)}, carry,
            rngs=_mk_rngs(model, key, "head"), method=spec.head_method,
        )

    def head_apply(p, carry, key):
        return head_apply_aux(p, carry, key)[0]

    loss_out_aval = jax.eval_shape(
        lambda c: mb_loss_fn(head_apply(params, c, mb_keys[0]), 0, mb_keys[0]),
        jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), carry_aval),
    )

    # ---- buffers ------------------------------------------------------

    def zeros_chunk_ring(n):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S, V, n) + a.shape, a.dtype), carry_aval
        )

    def zeros_stage_rows():
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S,) + a.shape, a.dtype), carry_aval
        )

    grad_dtype = jnp.float32

    def _acc_dtype(dtype):
        if jnp.issubdtype(dtype, jnp.floating) and cfg._fp32_grad_accumulation:
            return jnp.float32
        return dtype

    def param_grad_zeros(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _acc_dtype(p.dtype)), tree
        )

    # Ring slot count R1 comes from the memory plan: stash and cotangent
    # entries live until the W pass (not just B), so the alive depth can
    # exceed the 1F1B executors' window+1 — but never does at the default
    # window (the deferral hides inside the slack the in-flight cap
    # already paid for).
    inbuf0 = zeros_chunk_ring(R1)    # inbuf[s, k, m % R1]: fwd input of (k, m)
    stash0 = zeros_chunk_ring(R1)    # consumed fwd inputs (B AND W recompute)
    cotbuf0 = zeros_chunk_ring(R1)   # output cotangent of (k, m); W re-reads
    outbuf0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((S, R1) + a.shape, a.dtype), carry_aval
    )                                # last chunk's fwd output (row S-1 only)
    xfer_f0 = zeros_stage_rows()     # tick t's raw fwd outputs, rolled at t+1
    xfer_b0 = zeros_stage_rows()     # tick t's raw input cotangents, ditto
    dlay0 = param_grad_zeros(staged_params)
    drep0 = param_grad_zeros(params_rest)
    dembed0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, grad_dtype), carry_aval
    )
    side_leaves = side_treedef = side_idx = None
    dsides0 = None
    if sides is not None:
        side_leaves, side_treedef, side_idx = _inexact_leaves(
            tuple(jax.tree_util.tree_map(lambda a: a[0], s) for s in sides)
        )
        dsides0 = [
            jnp.zeros((M,) + side_leaves[i].shape, grad_dtype) for i in side_idx
        ]
    losses0 = jnp.zeros((M,), jnp.float32)
    outs0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, a.dtype), loss_out_aval[1]
    )

    stage_ids = jnp.arange(S)
    aux_w = float(getattr(cfg, "moe_aux_loss_weight", 1.0))
    aux_seed = (
        jnp.asarray(aux_w, jnp.float32)
        * jnp.asarray(loss_seed_scale, jnp.float32)
    )

    # Ring/scatter primitives are the module-level _chunk_* helpers,
    # shared with the virtual executor.
    hc = health.active()

    def tick_impl(carry, t, do_fwd, do_bwd, do_wgt):
        """One schedule tick. The pass flags are STATIC per segment:
        out-of-span sub-steps are never compiled. Sub-step order within a
        tick is F -> B -> W, which is what legalizes same-tick B(c,m)
        after F(c,m) (last chunk) and W(c,m) after B(c,m)."""
        if hc is not None:
            (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay, drep,
             dembed, dsides, losses, outs, hstats) = carry
            ((hbad, habs, hmb), (hbad_b, habs_b, hmb_b)) = hstats
        else:
            (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay, drep,
             dembed, dsides, losses, outs) = carry

        # ---------------- deferred stage transfers ----------------
        # Tick t-1's fwd outputs / input cotangents cross the pp axis
        # here (jnp.roll -> collective-permute), exactly as in the
        # virtual executor. Gating on the CURRENT segment's flags is
        # legal for the same reason as there: the last F tick can only
        # contain last-chunk forwards (routed to outbuf) and the last B
        # tick only chunk-0 backwards (routed to the embedding), so the
        # first tick outside a span has nothing to merge. W produces no
        # transfers at all — weight grads stay stage-local.
        prev = jnp.maximum(t - 1, 0)
        was_prev = t > 0
        if do_fwd:
            pk = fwd_k_sched[prev]
            pm = fwd_m_sched[prev]
            p_act = (pm >= 0) & was_prev
            dst_k = jnp.roll(pk, 1) + (stage_ids == 0)
            dst_m = jnp.roll(jnp.maximum(pm, 0), 1)
            dst_act = jnp.roll(p_act, 1) & (dst_k < V)
            inbuf = _chunk_ring_set(
                inbuf, jnp.clip(dst_k, 0, V - 1), dst_m % R1,
                jax.tree_util.tree_map(lambda o: jnp.roll(o, 1, axis=0), xfer_f),
                dst_act,
            )
        if do_bwd:
            pbk = bwd_k_sched[prev]
            pbm = bwd_m_sched[prev]
            pb_act = (pbm >= 0) & was_prev
            dst_bk = jnp.roll(pbk, -1) - (stage_ids == S - 1)
            dst_bm = jnp.roll(jnp.maximum(pbm, 0), -1)
            dst_b_act = jnp.roll(pb_act, -1) & (dst_bk >= 0)
            cotbuf = _chunk_ring_set(
                cotbuf, jnp.clip(dst_bk, 0, V - 1), dst_bm % R1,
                jax.tree_util.tree_map(lambda o: jnp.roll(o, -1, axis=0), xfer_b),
                dst_b_act,
            )

        # ---------------- forward sub-step ----------------
        if do_fwd:
            fk = fwd_k_sched[t]
            fm = fwd_m_sched[t]
            f_active = fm >= 0
            fkc = jnp.clip(fk, 0, V - 1)
            fmc = jnp.maximum(fm, 0)
            f_slots = fmc % R1
            ch_params = select_chunk(staged_params, fkc)
            ch_xs = select_chunk(staged_xs, fkc)
            ch_act = select_chunk(active_rows, fkc)
            from_q = gather_mb(hidden_q, fmc[0])
            buf_in = _chunk_ring_get(inbuf, fkc, f_slots)
            x_in = jax.tree_util.tree_map(
                lambda q, b: b.at[0].set(jnp.where(fkc[0] == 0, q, b[0])),
                from_q, buf_in,
            )
            f_sides = gather_sides_rows(fmc)
            c_ids = fkc * S + stage_ids
            with named_region("smp/pipeline/tick_fwd"):
                outs_f, _aux_f = jax.vmap(
                    chunk_fwd,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0),
                )(ch_params, ch_xs, x_in, f_sides, c_ids, fmc, ch_act)
            outs_f = pin_stage_axis(outs_f)
            stash = _chunk_ring_set(stash, fkc, f_slots, x_in, f_active)
            if hc is not None:
                brow, arow = health.stage_row_stats(outs_f, S)
                brow = jnp.where(f_active, brow, 0.0)
                arow = jnp.where(f_active, arow, 0.0)
                hmb = _chunk_scatter_stat(
                    hmb, fkc, fmc.astype(jnp.float32),
                    f_active & (brow > 0),
                    lambda cur, mb: jnp.where(cur < 0, mb, cur),
                )
                hbad = _chunk_scatter_stat(
                    hbad, fkc, brow, f_active, lambda cur, v: cur + v
                )
                habs = _chunk_scatter_stat(
                    habs, fkc, arow, f_active, jnp.maximum
                )
            last_row_active = f_active & (stage_ids == S - 1) & (fkc == V - 1)
            outbuf = _chunk_outbuf_set(outbuf, f_slots, outs_f, last_row_active)
            xfer_f = outs_f

        # ---------------- backward-input sub-step ----------------
        if do_bwd:
            bk = bwd_k_sched[t]
            bm = bwd_m_sched[t]
            b_active = bm >= 0
            bkc = jnp.clip(bk, 0, V - 1)
            bmc = jnp.maximum(bm, 0)
            b_slots = bmc % R1

            is_lastk = b_active[S - 1] & (bkc[S - 1] == V - 1)
            m_last = bmc[S - 1]
            key_last = jax.lax.dynamic_index_in_dim(
                mb_keys, m_last, 0, keepdims=False
            )
            out_last = jax.tree_util.tree_map(
                lambda ob: jax.lax.dynamic_index_in_dim(
                    ob[S - 1], b_slots[S - 1], 0, keepdims=False
                ),
                outbuf,
            )

            def head_loss(p_rest, out):
                final, h_aux = head_apply_aux(with_layers(p_rest), out, key_last)
                loss, user_out = mb_loss_fn(final, m_last, key_last)
                loss = loss + jnp.asarray(aux_w, loss.dtype) * h_aux.astype(
                    loss.dtype
                )
                return loss, user_out

            def run_head():
                loss_m, head_vjp, user_out = jax.vjp(
                    head_loss, params_rest, out_last, has_aux=True
                )
                seed = jnp.asarray(loss_seed_scale, loss_m.dtype)
                d_rep, d_out_last = head_vjp(seed)
                return loss_m.astype(jnp.float32), d_rep, d_out_last, user_out

            head_aval = jax.eval_shape(run_head)
            with named_region("smp/pipeline/head"):
                loss_m, d_rep, d_out_last, user_out = jax.lax.cond(
                    is_lastk,
                    run_head,
                    lambda: jax.tree_util.tree_map(
                        lambda a: jnp.zeros(a.shape, a.dtype), head_aval
                    ),
                )

            cot_in = _chunk_ring_get(cotbuf, bkc, b_slots)
            cot_in = jax.tree_util.tree_map(
                lambda c, d: c.at[S - 1].set(
                    jnp.where(is_lastk, d.astype(c.dtype), c[S - 1])
                ),
                cot_in, d_out_last,
            )
            # Retain the head cotangent in the ring: unlike the fused
            # executors, the last chunk's backward touches its cotangent
            # TWICE (B now, W later) and only B gets it from the head
            # VJP. Masked to the producing row so other stages' ring
            # entries are untouched.
            cotbuf = _chunk_ring_set(
                cotbuf, bkc, b_slots, cot_in,
                b_active & (stage_ids == S - 1) & (bkc == V - 1),
            )
            b_sides = gather_sides_rows(bmc)
            stash_in = _chunk_ring_get(stash, bkc, b_slots)
            ch_params_b = select_chunk(staged_params, bkc)
            ch_xs_b = select_chunk(staged_xs, bkc)
            ch_act_b = select_chunk(active_rows, bkc)
            c_ids_b = bkc * S + stage_ids

            def chunk_bwd_input(lp, lxs, x, side, cot, c_idx, m_idx, act_row):
                """Input-grad pass: VJP w.r.t. (input, sides) only — the
                weight cotangent is never formed here."""

                def f(x_, side_):
                    return chunk_fwd(lp, lxs, x_, side_, c_idx, m_idx, act_row)

                _, vjp = jax.vjp(f, x, side)
                return vjp((cot, aux_seed))

            with named_region("smp/pipeline/tick_bwd_input"):
                d_x_rows, d_side_rows = jax.vmap(
                    chunk_bwd_input,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0, 0),
                )(ch_params_b, ch_xs_b, stash_in,
                  b_sides, cot_in, c_ids_b, bmc, ch_act_b)
            d_x_rows = pin_stage_axis(d_x_rows)

            if hc is not None:
                brow_b, arow_b = health.stage_row_stats(d_x_rows, S)
                brow_b = jnp.where(b_active, brow_b, 0.0)
                arow_b = jnp.where(b_active, arow_b, 0.0)
                hmb_b = _chunk_scatter_stat(
                    hmb_b, bkc, bmc.astype(jnp.float32),
                    b_active & (brow_b > 0),
                    lambda cur, mb: jnp.where(cur < 0, mb, cur),
                )
                hbad_b = _chunk_scatter_stat(
                    hbad_b, bkc, brow_b, b_active, lambda cur, v: cur + v
                )
                habs_b = _chunk_scatter_stat(
                    habs_b, bkc, arow_b, b_active, jnp.maximum
                )

            drep = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(is_lastk, g.astype(a.dtype), 0),
                drep, d_rep,
            )

            dembed = _chunk_scatter_add_mb(
                dembed, bmc[0],
                jax.tree_util.tree_map(lambda r: r[0], d_x_rows),
                b_active[0] & (bkc[0] == 0),
            )

            if sides is not None and dsides is not None:
                def one_stage_side_add(ds, s):
                    row_leaves, _, _ = _inexact_leaves(
                        jax.tree_util.tree_map(lambda r: r[s], d_side_rows)
                    )
                    vals = [row_leaves[i] for i in side_idx]
                    return [
                        _chunk_scatter_add_leaf(d, bmc[s], v, b_active[s])
                        for d, v in zip(ds, vals)
                    ]

                for s in range(S):
                    dsides = one_stage_side_add(dsides, s)

            losses = losses.at[m_last].set(
                jnp.where(is_lastk, loss_m.astype(jnp.float32), losses[m_last])
            )
            outs = _chunk_scatter_set_mb(outs, m_last, user_out, is_lastk)
            xfer_b = d_x_rows

        # ---------------- weight-grad sub-step ----------------
        if do_wgt:
            wk = wgt_k_sched[t]
            wm = wgt_m_sched[t]
            w_active = wm >= 0
            wkc = jnp.clip(wk, 0, V - 1)
            wmc = jnp.maximum(wm, 0)
            w_slots = wmc % R1

            w_sides = gather_sides_rows(wmc)
            stash_w = _chunk_ring_get(stash, wkc, w_slots)
            cot_w = _chunk_ring_get(cotbuf, wkc, w_slots)
            ch_params_w = select_chunk(staged_params, wkc)
            ch_xs_w = select_chunk(staged_xs, wkc)
            ch_act_w = select_chunk(active_rows, wkc)
            c_ids_w = wkc * S + stage_ids

            def chunk_bwd_weight(lp, lxs, x, side, cot, c_idx, m_idx,
                                 act_row):
                """Weight-grad pass: VJP w.r.t. the chunk params only,
                re-reading the stashed input and retained cotangent."""

                def f(lp_):
                    return chunk_fwd(lp_, lxs, x, side, c_idx, m_idx, act_row)

                _, vjp = jax.vjp(f, lp)
                (d_lp,) = vjp((cot, aux_seed))
                return d_lp

            with named_region("smp/pipeline/tick_bwd_weight"):
                d_lp_rows = jax.vmap(
                    chunk_bwd_weight,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0, 0),
                )(ch_params_w, ch_xs_w, stash_w,
                  w_sides, cot_w, c_ids_w, wmc, ch_act_w)
            d_lp_rows = pin_stage_axis(d_lp_rows)
            dlay = _chunk_acc_rows(dlay, d_lp_rows, wkc, w_active)

        new_carry = (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, dlay,
                     drep, dembed, dsides, losses, outs)
        if hc is not None:
            new_carry = new_carry + (
                ((hbad, habs, hmb), (hbad_b, habs_b, hmb_b)),
            )
        return new_carry, None

    carry0 = (
        pin_stage_axis(inbuf0), pin_stage_axis(stash0),
        pin_stage_axis(cotbuf0), pin_stage_axis(outbuf0),
        pin_stage_axis(xfer_f0), pin_stage_axis(xfer_b0),
        pin_stage_axis(dlay0), drep0, dembed0, dsides0, losses0, outs0,
    )
    if hc is not None:

        def hgrids():
            return (
                jnp.zeros((S, V), jnp.float32), jnp.zeros((S, V), jnp.float32),
                jnp.full((S, V), -1.0, jnp.float32),
            )

        carry0 = carry0 + ((hgrids(), hgrids()),)

    carry_end = carry0
    for a, b, (do_f, do_b, do_w) in segments:
        with named_region(_zb_segment_region(do_f, do_b, do_w)):
            carry_end, _ = jax.lax.scan(
                lambda c, t, f=do_f, bb=do_b, w=do_w: tick_impl(
                    c, t, f, bb, w
                ),
                carry_end, jnp.arange(a, b),
            )
    if hc is not None:
        (_, _, _, _, _, _, dlay, drep, dembed, dsides, losses, outs,
         hstats) = carry_end
        ((hbad, habs, hmb), (hbad_b, habs_b, hmb_b)) = hstats
        # Grid position (s, k) holds GLOBAL chunk k*S + s; tags carry the
        # pass coordinate so a tripped sentinel attributes to the exact
        # (chunk, pass) — forward activations vs input cotangents.
        chunk_ids = np.arange(V)[None, :] * S + np.arange(S)[:, None]
        hc.add_stage_stats("zb", hbad, habs, hmb, chunk_ids=chunk_ids,
                           pass_name="fwd")
        hc.add_stage_stats("zb", hbad_b, habs_b, hmb_b, chunk_ids=chunk_ids,
                           pass_name="bwd_input")
    else:
        (_, _, _, _, _, _, dlay, drep, dembed, dsides, losses,
         outs) = carry_end

    # ---- embedding backward ------------------------------------------

    def embed_bwd(acc, xs):
        mb_input, key, dcarry, dside_row = xs

        def embed_inexact(p_rest):
            args, kwargs = mb_input
            out, aux = apply_collecting_aux(
                module, {"params": cast_half(with_layers(p_rest))}, *args,
                rngs=_mk_rngs(model, key, "embed"),
                method=spec.embed_method, **kwargs,
            )
            leaves, _, idx = _inexact_leaves(out)
            return [leaves[i] for i in idx] + [aux]

        out_aval = jax.eval_shape(embed_inexact, params_rest)
        if sides is not None:
            cots = list(jax.tree_util.tree_leaves(dcarry)) + list(dside_row)
        else:
            cots = jax.tree_util.tree_leaves(dcarry)
        cots = cots + [aux_seed]
        cots = [c.astype(a.dtype) for c, a in zip(cots, out_aval)]
        _, vjp = jax.vjp(embed_inexact, params_rest)
        (dp,) = vjp(cots)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), acc, dp
        )
        return acc, None

    if spec.embed_method is not None:
        demb_params0 = param_grad_zeros(params_rest)
        dside_stack = tuple(dsides) if dsides is not None else ()
        demb_params, _ = jax.lax.scan(
            embed_bwd, demb_params0,
            (stacked_inputs, mb_keys, dembed, dside_stack),
        )
    else:
        demb_params = None

    # ---- assemble the full gradient tree -----------------------------

    flat_idx = jnp.asarray(idx_np.reshape(-1))
    flat_mask = active_np.reshape(-1)

    def to_layers(g):
        gf = g.reshape((S * V * maxp,) + g.shape[3:])
        gf = gf * flat_mask.reshape((-1,) + (1,) * (gf.ndim - 1))
        return jnp.zeros((L,) + g.shape[3:], g.dtype).at[flat_idx].add(gf)

    layer_grads = jax.tree_util.tree_map(to_layers, dlay)
    if demb_params is not None:
        drep = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(a.dtype), drep, demb_params
        )
    grads = _set_subtree(drep, spec.layer_path, layer_grads)
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.result_type(p)), grads, params
    )
    return grads, losses, outs


def _pipeline_zero_bubble_stash(model, params, stacked_inputs, rng,
                                mb_loss_fn, loss_seed_scale, virtual, rmode):
    """ZB-H1 executor under a non-default recompute plan
    (``recompute: stash_weight | stash_all | auto``).

    Same numerical contract and schedule as ``_pipeline_zero_bubble``;
    two structural differences, both existing only on this knob-gated
    path (the default executor stays byte-identical):

    - **Residual stash instead of W-pass recompute**: the B sub-step
      runs the chunk forward as per-layer ``jax.vjp`` captures
      (``_make_residual_split``), writing the flattened residual leaves
      and the per-layer output cotangents into stash rings sized by
      ``memory.recompute_ring_plan``; the deferred W sub-step rebuilds
      each layer's vjp from the rings and computes weight-grad matmuls
      ONLY — no forward re-run, no cotangent chain. Under ``stash_all``
      the residuals are captured at the F sub-step itself, so B skips
      its forward too. ``auto`` plans per-(stage, chunk): degraded
      chunks keep the recompute path (both paths compile, selected by a
      static per-chunk mask).

    - **One scan, conditional sub-steps**: instead of one compiled scan
      per contiguous segment of active passes, the whole tick range is
      ONE scan whose F/B/W sub-steps run under ``lax.cond`` keyed by
      static per-tick activity arrays. Out-of-phase ticks skip their
      sub-steps at runtime (same executed work as the segmented loops,
      modulo rare mid-span gap ticks, which execute masked), and each
      pass's ops are compiled exactly ONCE — the segmented executor
      compiles every pass into each of its segments, which is most of
      what the structural remat census counts against the ZB schedule.
    """
    spec = model._pipeline_spec
    cfg = state.cfg
    S = cfg.pipeline_parallel_degree
    M = cfg.microbatches
    L = spec.num_layers
    V = virtual
    W = min(cfg.active_microbatches or (S + 1), M)
    from smdistributed_modelparallel_tpu.nn.auto_distribute import unwrap_hooks

    module = unwrap_hooks(model.module)
    layer_module = spec.layer_module
    half = cfg.half_dtype

    (fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np, wgt_k_np,
     wgt_m_np) = build_zero_bubble_schedule(S, M, W, V)
    n_ticks = fwd_m_np.shape[0]

    from smdistributed_modelparallel_tpu.parallel.memory import (
        recompute_ring_plan,
        zero_bubble_ring_plan,
    )
    from smdistributed_modelparallel_tpu.parallel import remat_plan

    plan_rings = zero_bubble_ring_plan(
        fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np, wgt_k_np, wgt_m_np,
        num_stages=S, virtual=V, window=W,
    )
    R1 = plan_rings["ring_slots"]
    stash_rings = recompute_ring_plan(
        fwd_k_np, fwd_m_np, bwd_k_np, bwd_m_np, wgt_k_np, wgt_m_np,
        num_stages=S, virtual=V,
    )

    from smdistributed_modelparallel_tpu.utils import health
    from smdistributed_modelparallel_tpu.utils.flight_recorder import (
        flight_recorder,
    )
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_pipeline_occupancy,
        telemetry,
    )

    # Static per-tick activity: which sub-steps this tick executes. A
    # sub-step also runs (masked) on a tick whose PREVIOUS tick produced
    # stage transfers that still need merging — the transfer registers
    # hold exactly one tick, so the merge cannot be deferred past it.
    stage_col = np.arange(S)[None, :]
    f_any = (fwd_m_np >= 0).any(axis=1)
    b_any = (bwd_m_np >= 0).any(axis=1)
    w_any = (wgt_m_np >= 0).any(axis=1)
    f_xfer = ((fwd_m_np >= 0)
              & ~((stage_col == S - 1) & (fwd_k_np == V - 1))).any(axis=1)
    b_xfer = ((bwd_m_np >= 0)
              & ~((stage_col == 0) & (bwd_k_np == 0))).any(axis=1)
    f_run = f_any.copy()
    f_run[1:] |= f_xfer[:-1]
    b_run = b_any.copy()
    b_run[1:] |= b_xfer[:-1]
    w_run = w_any

    busy, total = schedule_occupancy(
        fwd_m_np, bwd_m_np, fwd_ticks=int(f_run.sum()),
        bwd_ticks=int(b_run.sum()), wgt=wgt_m_np,
        wgt_ticks=int(w_run.sum()),
    )
    record_pipeline_occupancy(
        "zb", S, M, busy_slots=busy, total_slots=total, virtual=V,
        passes=3,
        pass_ticks={"fwd": int(f_run.sum()), "bwd_input": int(b_run.sum()),
                    "bwd_weight": int(w_run.sum())},
    )
    _ring_gauge = telemetry.gauge(
        "smp_pipeline_ring_slots",
        "per-(stage, chunk) ring-buffer slots of the pipeline executor",
    )
    _ring_gauge.labels(schedule="zb").set(R1)
    telemetry.gauge(
        "smp_pipeline_wqueue_peak",
        "peak deferred weight-grad units per (stage, chunk) [zero-bubble]",
    ).labels(schedule="zb").set(plan_rings["w_queue_peak"])
    flight_recorder.record_schedule(
        "zb",
        ((t, s, d, int(m_arr[t, s]), int(k_arr[t, s]) * S + s, p)
         for t in range(n_ticks) for s in range(S)
         for d, p, k_arr, m_arr in (
             ("fwd", "F", fwd_k_np, fwd_m_np),
             ("bwd_input", "B", bwd_k_np, bwd_m_np),
             ("bwd_weight", "W", wgt_k_np, wgt_m_np))
         if m_arr[t, s] >= 0),
    )
    fwd_k_sched = jnp.asarray(fwd_k_np)
    fwd_m_sched = jnp.asarray(fwd_m_np)
    bwd_k_sched = jnp.asarray(bwd_k_np)
    bwd_m_sched = jnp.asarray(bwd_m_np)
    wgt_k_sched = jnp.asarray(wgt_k_np)
    wgt_m_sched = jnp.asarray(wgt_m_np)
    f_run_sched = jnp.asarray(f_run)
    b_run_sched = jnp.asarray(b_run)
    w_run_sched = jnp.asarray(w_run)

    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        _get_subtree,
        _mk_rngs,
        _scan_map,
        chunk_layout,
        staged_chunk_views,
    )

    def cast_half(tree):
        from smdistributed_modelparallel_tpu.nn.utils import half_cast

        return half_cast(tree, half)

    layer_params = _get_subtree(params, spec.layer_path)
    staged_params, staged_xs, active_rows = staged_chunk_views(
        spec, layer_params, S, V
    )

    from jax.sharding import NamedSharding, PartitionSpec as _P

    from smdistributed_modelparallel_tpu.backend.topology import PP_AXIS

    mesh = state.mesh
    _pp_size = dict(mesh.shape).get(PP_AXIS, 1) if mesh is not None else 1

    def pin_stage_axis(tree):
        if mesh is None or _pp_size <= 1:
            return tree

        def pin(x):
            if getattr(x, "ndim", 0) < 1 or x.shape[0] != S:
                return x
            rest = [_P.UNCONSTRAINED] * (x.ndim - 1)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _P(PP_AXIS, *rest))
            )

        return jax.tree_util.tree_map(pin, tree)

    staged_params = pin_stage_axis(staged_params)
    staged_xs = pin_stage_axis(staged_xs)
    params_rest = _set_subtree(params, spec.layer_path, {})

    def with_layers(p_rest):
        return _set_subtree(p_rest, spec.layer_path, layer_params)

    idx_np, active_np, maxp = chunk_layout(spec, S, V)

    mb_keys = jax.random.split(rng, M)

    # ---- embed all microbatches (the input queue) --------------------

    def embed_mb(mb_input, key):
        args, kwargs = mb_input
        if spec.embed_method is None:
            return args[0]
        return module.apply(
            {"params": cast_half(params)}, *args,
            rngs=_mk_rngs(model, key, "embed"),
            method=spec.embed_method, **kwargs,
        )

    with named_region("smp/pipeline/embed"):
        embedded = _scan_map(embed_mb, stacked_inputs, mb_keys)

    if spec.carry_is_tuple:
        hidden_q = embedded[0]
        sides = embedded[1:]
    else:
        hidden_q = embedded
        sides = None

    carry_aval = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), hidden_q
    )

    # ---- per-chunk forward + residual split --------------------------

    from smdistributed_modelparallel_tpu.parallel.memory import remat_policy
    from smdistributed_modelparallel_tpu.parallel.pipeline import (
        apply_collecting_aux,
        make_layer_apply,
    )

    apply_one_layer = make_layer_apply(
        model, spec, layer_module, side_in_carry=False
    )

    if spec.carry_remat:
        apply_one_layer = jax.checkpoint(apply_one_layer, policy=remat_policy())

    def chunk_fwd(chunk_lp, chunk_lxs, x, side, c_idx, m_idx, act_row):
        base = jax.random.fold_in(jax.random.fold_in(rng, c_idx), m_idx)
        chunk_lp = cast_half(chunk_lp)

        def body(c, xs):
            lp, lxs, i, act = xs
            new_c, aux = apply_one_layer(
                lp, c, lxs, jax.random.fold_in(base, i), side
            )
            out_c = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act, n, o), new_c, c
            )
            return out_c, jnp.where(act, aux, 0.0)

        idx = jnp.arange(maxp)
        out, auxs = jax.lax.scan(body, x, (chunk_lp, chunk_lxs, idx, act_row))
        return out, jnp.sum(auxs)

    stage_ids = jnp.arange(S)
    aux_w = float(getattr(cfg, "moe_aux_loss_weight", 1.0))
    aux_seed = (
        jnp.asarray(aux_w, jnp.float32)
        * jnp.asarray(loss_seed_scale, jnp.float32)
    )

    side_leaves = side_treedef = side_idx = None
    if sides is not None:
        side_leaves, side_treedef, side_idx = _inexact_leaves(
            tuple(jax.tree_util.tree_map(lambda a: a[0], s) for s in sides)
        )
    side_leaf_avals = (
        [side_leaves[i] for i in side_idx] if sides is not None else []
    )

    capture_fwd, bwd_from_res, _bwd_full, wgt_from_res, _captured = (
        _make_residual_split(
            apply_one_layer, cast_half, rng, maxp, aux_seed,
            sides is not None, side_leaf_avals=side_leaf_avals,
        )
    )

    # Probe the residual/cotangent stash shapes (and capture the vjp
    # treedef) with an abstract trace of one B-style capture row sweep.
    res_avals, cot_avals = _probe_stash_avals(
        S, staged_params, staged_xs, active_rows, carry_aval, sides,
        capture_fwd, bwd_from_res=bwd_from_res,
    )
    _slot_bytes = _stash_slot_bytes

    capture_at_f_target = rmode == "stash_all"
    res_ring_slots = (
        stash_rings["f_to_w"] if capture_at_f_target
        else stash_rings["b_to_w"]
    )
    cot_ring_slots = stash_rings["b_to_w"]
    plan = remat_plan.plan_pipeline(
        "zb", rmode, S, V,
        res_ring_slots=res_ring_slots, cot_ring_slots=cot_ring_slots,
        res_slot_bytes=_slot_bytes(res_avals),
        cot_slot_bytes=_slot_bytes(cot_avals), cfg=cfg,
    )
    if plan.effective == "full":
        # Every chunk degraded (auto under a tight budget): the untouched
        # recompute executor IS the plan.
        return _pipeline_zero_bubble(
            model, params, stacked_inputs, rng, mb_loss_fn, loss_seed_scale,
            virtual,
        )
    capture_at_f = plan.effective == "stash_all"
    stash_of_arr, res_col_arr, Vs, all_stash = _stash_chunk_maps(plan, V)
    Rres = plan.res_ring_slots
    Rcot = plan.cot_ring_slots

    # ---- head + user loss (last stage, last chunk only) ---------------

    def head_apply_aux(p, carry, key):
        if spec.head_method is None:
            return carry, jnp.zeros((), jnp.float32)
        return apply_collecting_aux(
            module, {"params": cast_half(p)}, carry,
            rngs=_mk_rngs(model, key, "head"), method=spec.head_method,
        )

    def head_apply(p, carry, key):
        return head_apply_aux(p, carry, key)[0]

    loss_out_aval = jax.eval_shape(
        lambda c: mb_loss_fn(head_apply(params, c, mb_keys[0]), 0, mb_keys[0]),
        jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, a.dtype), carry_aval),
    )

    # ---- buffers ------------------------------------------------------

    def zeros_chunk_ring(n):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S, V, n) + a.shape, a.dtype), carry_aval
        )

    def zeros_stage_rows():
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S,) + a.shape, a.dtype), carry_aval
        )

    def zeros_stash_ring(avals, n):
        # [S, Vs, n, ...]: stage axis leads (pp-sharded like its
        # siblings); leaf shapes come from the probe avals (leading
        # stage axis dropped).
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((S, Vs, n) + a.shape[1:], a.dtype), avals
        )

    grad_dtype = jnp.float32

    def _acc_dtype(dtype):
        if jnp.issubdtype(dtype, jnp.floating) and cfg._fp32_grad_accumulation:
            return jnp.float32
        return dtype

    def param_grad_zeros(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, _acc_dtype(p.dtype)), tree
        )

    inbuf0 = zeros_chunk_ring(R1)
    stash0 = zeros_chunk_ring(R1)
    cotbuf0 = zeros_chunk_ring(R1)
    outbuf0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((S, R1) + a.shape, a.dtype), carry_aval
    )
    xfer_f0 = zeros_stage_rows()
    xfer_b0 = zeros_stage_rows()
    wres0 = zeros_stash_ring(res_avals, Rres)
    wcot0 = zeros_stash_ring(cot_avals, Rcot)
    dlay0 = param_grad_zeros(staged_params)
    drep0 = param_grad_zeros(params_rest)
    dembed0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, grad_dtype), carry_aval
    )
    dsides0 = None
    if sides is not None:
        dsides0 = [
            jnp.zeros((M,) + side_leaves[i].shape, grad_dtype) for i in side_idx
        ]
    losses0 = jnp.zeros((M,), jnp.float32)
    outs0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((M,) + a.shape, a.dtype), loss_out_aval[1]
    )

    hc = health.active()

    def gather_mb(tree, m):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
            tree,
        )

    def gather_sides_rows(ms):
        if sides is None:
            return None
        return tuple(
            jax.tree_util.tree_map(
                lambda a: jax.vmap(
                    lambda i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
                )(ms),
                s,
            )
            for s in sides
        )

    def select_chunk(tree, krow):
        return jax.tree_util.tree_map(
            lambda a: jax.vmap(
                lambda av, k: jax.lax.dynamic_index_in_dim(av, k, 0, keepdims=False)
            )(a, krow),
            tree,
        )

    # ---- sub-steps (each a lax.cond branch over the whole carry) ------

    def f_substep(carry, t):
        (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot, dlay,
         drep, dembed, dsides, losses, outs, hstats) = carry
        (hbad, habs, hmb), hstats_b = hstats

        prev = jnp.maximum(t - 1, 0)
        was_prev = t > 0
        pk = fwd_k_sched[prev]
        pm = fwd_m_sched[prev]
        p_act = (pm >= 0) & was_prev
        dst_k = jnp.roll(pk, 1) + (stage_ids == 0)
        dst_m = jnp.roll(jnp.maximum(pm, 0), 1)
        dst_act = jnp.roll(p_act, 1) & (dst_k < V)
        inbuf = _chunk_ring_set(
            inbuf, jnp.clip(dst_k, 0, V - 1), dst_m % R1,
            jax.tree_util.tree_map(lambda o: jnp.roll(o, 1, axis=0), xfer_f),
            dst_act,
        )

        fk = fwd_k_sched[t]
        fm = fwd_m_sched[t]
        f_active = fm >= 0
        fkc = jnp.clip(fk, 0, V - 1)
        fmc = jnp.maximum(fm, 0)
        f_slots = fmc % R1
        ch_params = select_chunk(staged_params, fkc)
        ch_xs = select_chunk(staged_xs, fkc)
        ch_act = select_chunk(active_rows, fkc)
        from_q = gather_mb(hidden_q, fmc[0])
        buf_in = _chunk_ring_get(inbuf, fkc, f_slots)
        x_in = jax.tree_util.tree_map(
            lambda q, b: b.at[0].set(jnp.where(fkc[0] == 0, q, b[0])),
            from_q, buf_in,
        )
        f_sides = gather_sides_rows(fmc)
        c_ids = fkc * S + stage_ids
        with named_region("smp/pipeline/tick_fwd"):
            if capture_at_f:
                outs_f, _aux_f, res_f = jax.vmap(
                    capture_fwd,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0),
                )(ch_params, ch_xs, x_in, f_sides, c_ids, fmc, ch_act)
                wres = _chunk_ring_set(
                    wres, res_col_arr[fkc], fmc % Rres, res_f,
                    f_active & stash_of_arr[fkc],
                )
            else:
                outs_f, _aux_f = jax.vmap(
                    chunk_fwd,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0),
                )(ch_params, ch_xs, x_in, f_sides, c_ids, fmc, ch_act)
        outs_f = pin_stage_axis(outs_f)
        stash = _chunk_ring_set(stash, fkc, f_slots, x_in, f_active)
        if hc is not None:
            brow, arow = health.stage_row_stats(outs_f, S)
            brow = jnp.where(f_active, brow, 0.0)
            arow = jnp.where(f_active, arow, 0.0)
            hmb = _chunk_scatter_stat(
                hmb, fkc, fmc.astype(jnp.float32),
                f_active & (brow > 0),
                lambda cur, mb: jnp.where(cur < 0, mb, cur),
            )
            hbad = _chunk_scatter_stat(
                hbad, fkc, brow, f_active, lambda cur, v: cur + v
            )
            habs = _chunk_scatter_stat(
                habs, fkc, arow, f_active, jnp.maximum
            )
        last_row_active = f_active & (stage_ids == S - 1) & (fkc == V - 1)
        outbuf = _chunk_outbuf_set(outbuf, f_slots, outs_f, last_row_active)
        xfer_f = outs_f
        return (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot,
                dlay, drep, dembed, dsides, losses, outs,
                ((hbad, habs, hmb), hstats_b))

    def b_substep(carry, t):
        (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot, dlay,
         drep, dembed, dsides, losses, outs, hstats) = carry
        hstats_f, (hbad_b, habs_b, hmb_b) = hstats

        prev = jnp.maximum(t - 1, 0)
        was_prev = t > 0
        pbk = bwd_k_sched[prev]
        pbm = bwd_m_sched[prev]
        pb_act = (pbm >= 0) & was_prev
        dst_bk = jnp.roll(pbk, -1) - (stage_ids == S - 1)
        dst_bm = jnp.roll(jnp.maximum(pbm, 0), -1)
        dst_b_act = jnp.roll(pb_act, -1) & (dst_bk >= 0)
        cotbuf = _chunk_ring_set(
            cotbuf, jnp.clip(dst_bk, 0, V - 1), dst_bm % R1,
            jax.tree_util.tree_map(lambda o: jnp.roll(o, -1, axis=0), xfer_b),
            dst_b_act,
        )

        bk = bwd_k_sched[t]
        bm = bwd_m_sched[t]
        b_active = bm >= 0
        bkc = jnp.clip(bk, 0, V - 1)
        bmc = jnp.maximum(bm, 0)
        b_slots = bmc % R1

        is_lastk = b_active[S - 1] & (bkc[S - 1] == V - 1)
        m_last = bmc[S - 1]
        key_last = jax.lax.dynamic_index_in_dim(
            mb_keys, m_last, 0, keepdims=False
        )
        out_last = jax.tree_util.tree_map(
            lambda ob: jax.lax.dynamic_index_in_dim(
                ob[S - 1], b_slots[S - 1], 0, keepdims=False
            ),
            outbuf,
        )

        def head_loss(p_rest, out):
            final, h_aux = head_apply_aux(with_layers(p_rest), out, key_last)
            loss, user_out = mb_loss_fn(final, m_last, key_last)
            loss = loss + jnp.asarray(aux_w, loss.dtype) * h_aux.astype(
                loss.dtype
            )
            return loss, user_out

        def run_head():
            loss_m, head_vjp, user_out = jax.vjp(
                head_loss, params_rest, out_last, has_aux=True
            )
            seed = jnp.asarray(loss_seed_scale, loss_m.dtype)
            d_rep, d_out_last = head_vjp(seed)
            return loss_m.astype(jnp.float32), d_rep, d_out_last, user_out

        head_aval = jax.eval_shape(run_head)
        with named_region("smp/pipeline/head"):
            loss_m, d_rep, d_out_last, user_out = jax.lax.cond(
                is_lastk,
                run_head,
                lambda: jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype), head_aval
                ),
            )

        cot_in = _chunk_ring_get(cotbuf, bkc, b_slots)
        cot_in = jax.tree_util.tree_map(
            lambda c, d: c.at[S - 1].set(
                jnp.where(is_lastk, d.astype(c.dtype), c[S - 1])
            ),
            cot_in, d_out_last,
        )
        # Retain the head cotangent for a possible RECOMPUTE W pass on a
        # degraded last chunk (mixed auto plans); harmless otherwise.
        cotbuf = _chunk_ring_set(
            cotbuf, bkc, b_slots, cot_in,
            b_active & (stage_ids == S - 1) & (bkc == V - 1),
        )
        b_sides = gather_sides_rows(bmc)
        stash_in = _chunk_ring_get(stash, bkc, b_slots)
        ch_params_b = select_chunk(staged_params, bkc)
        ch_xs_b = select_chunk(staged_xs, bkc)
        ch_act_b = select_chunk(active_rows, bkc)
        c_ids_b = bkc * S + stage_ids
        b_cols = res_col_arr[bkc]
        b_stash_act = b_active & stash_of_arr[bkc]

        with named_region("smp/pipeline/tick_bwd_input"):
            if capture_at_f:
                # Residuals were captured at F: no backward-time forward.
                # stash_all plans are never partial (only auto degrades
                # chunks, and auto targets stash_weight on this
                # schedule), so every chunk's residuals are in the ring.
                assert all_stash
                res_b = _chunk_ring_get(wres, b_cols, bmc % Rres)
            else:
                _out_b, _aux_b, res_b = jax.vmap(
                    capture_fwd,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0),
                )(ch_params_b, ch_xs_b, stash_in, b_sides, c_ids_b, bmc,
                  ch_act_b)
            d_x_rows, d_side_rows, cot_stack = jax.vmap(bwd_from_res)(
                res_b, cot_in
            )
        d_x_rows = pin_stage_axis(d_x_rows)
        # Stash for the deferred W pass (stashed chunks only).
        if not capture_at_f:
            wres = _chunk_ring_set(
                wres, b_cols, bmc % Rres, res_b, b_stash_act
            )
        wcot = _chunk_ring_set(
            wcot, b_cols, bmc % Rcot, cot_stack, b_stash_act
        )

        if hc is not None:
            brow_b, arow_b = health.stage_row_stats(d_x_rows, S)
            brow_b = jnp.where(b_active, brow_b, 0.0)
            arow_b = jnp.where(b_active, arow_b, 0.0)
            hmb_b = _chunk_scatter_stat(
                hmb_b, bkc, bmc.astype(jnp.float32),
                b_active & (brow_b > 0),
                lambda cur, mb: jnp.where(cur < 0, mb, cur),
            )
            hbad_b = _chunk_scatter_stat(
                hbad_b, bkc, brow_b, b_active, lambda cur, v: cur + v
            )
            habs_b = _chunk_scatter_stat(
                habs_b, bkc, arow_b, b_active, jnp.maximum
            )

        drep = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(is_lastk, g.astype(a.dtype), 0),
            drep, d_rep,
        )

        dembed = _chunk_scatter_add_mb(
            dembed, bmc[0],
            jax.tree_util.tree_map(lambda r: r[0], d_x_rows),
            b_active[0] & (bkc[0] == 0),
        )

        if sides is not None and dsides is not None:
            # d_side_rows: per-stage accumulated inexact side-cotangent
            # leaves (already filtered to side_idx order).
            for s in range(S):
                dsides = [
                    _chunk_scatter_add_leaf(d, bmc[s], leaf[s], b_active[s])
                    for d, leaf in zip(dsides, d_side_rows)
                ]

        losses = losses.at[m_last].set(
            jnp.where(is_lastk, loss_m.astype(jnp.float32), losses[m_last])
        )
        outs = _chunk_scatter_set_mb(outs, m_last, user_out, is_lastk)
        xfer_b = d_x_rows
        return (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot,
                dlay, drep, dembed, dsides, losses, outs,
                (hstats_f, (hbad_b, habs_b, hmb_b)))

    def w_substep(carry, t):
        (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot, dlay,
         drep, dembed, dsides, losses, outs, hstats) = carry

        wk = wgt_k_sched[t]
        wm = wgt_m_sched[t]
        w_active = wm >= 0
        wkc = jnp.clip(wk, 0, V - 1)
        wmc = jnp.maximum(wm, 0)
        w_cols = res_col_arr[wkc]
        w_stash = stash_of_arr[wkc]
        ch_act_w = select_chunk(active_rows, wkc)

        with named_region("smp/pipeline/tick_bwd_weight"):
            res_w = _chunk_ring_get(wres, w_cols, wmc % Rres)
            cot_w = _chunk_ring_get(wcot, w_cols, wmc % Rcot)
            d_lp_rows = jax.vmap(wgt_from_res)(res_w, cot_w)
            if not all_stash:
                # Degraded chunks keep the recompute path: vjp w.r.t. the
                # chunk params re-running the forward from the input
                # stash and the retained chunk-output cotangent.
                w_slots = wmc % R1
                w_sides = gather_sides_rows(wmc)
                stash_w = _chunk_ring_get(stash, wkc, w_slots)
                cotc_w = _chunk_ring_get(cotbuf, wkc, w_slots)
                ch_params_w = select_chunk(staged_params, wkc)
                ch_xs_w = select_chunk(staged_xs, wkc)
                c_ids_w = wkc * S + stage_ids

                def chunk_bwd_weight(lp, lxs, x, side, cot, c_idx, m_idx,
                                     act_row):
                    def g(lp_):
                        return chunk_fwd(lp_, lxs, x, side, c_idx, m_idx,
                                         act_row)

                    _, vjp = jax.vjp(g, lp)
                    (d_lp,) = vjp((cot, aux_seed))
                    return d_lp

                d_lp_rec = jax.vmap(
                    chunk_bwd_weight,
                    in_axes=(0, 0, 0, 0 if sides is not None else None,
                             0, 0, 0, 0),
                )(ch_params_w, ch_xs_w, stash_w, w_sides, cotc_w,
                  c_ids_w, wmc, ch_act_w)
                d_lp_rows = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(
                        w_stash.reshape((S,) + (1,) * (a.ndim - 1)), a, b
                    ),
                    d_lp_rows, d_lp_rec,
                )
        d_lp_rows = pin_stage_axis(d_lp_rows)
        dlay = _chunk_acc_rows(dlay, d_lp_rows, wkc, w_active)
        return (inbuf, stash, cotbuf, outbuf, xfer_f, xfer_b, wres, wcot,
                dlay, drep, dembed, dsides, losses, outs, hstats)

    def tick(carry, t):
        carry = jax.lax.cond(
            f_run_sched[t], lambda c: f_substep(c, t), lambda c: c, carry
        )
        carry = jax.lax.cond(
            b_run_sched[t], lambda c: b_substep(c, t), lambda c: c, carry
        )
        carry = jax.lax.cond(
            w_run_sched[t], lambda c: w_substep(c, t), lambda c: c, carry
        )
        return carry, None

    def hgrids():
        return (
            jnp.zeros((S, V), jnp.float32), jnp.zeros((S, V), jnp.float32),
            jnp.full((S, V), -1.0, jnp.float32),
        )

    carry0 = (
        pin_stage_axis(inbuf0), pin_stage_axis(stash0),
        pin_stage_axis(cotbuf0), pin_stage_axis(outbuf0),
        pin_stage_axis(xfer_f0), pin_stage_axis(xfer_b0),
        pin_stage_axis(wres0), pin_stage_axis(wcot0),
        pin_stage_axis(dlay0), drep0, dembed0, dsides0, losses0, outs0,
        (hgrids(), hgrids()),
    )
    with named_region("smp/pipeline/steady"):
        carry_end, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    (_, _, _, _, _, _, _, _, dlay, drep, dembed, dsides, losses, outs,
     hstats) = carry_end
    if hc is not None:
        ((hbad, habs, hmb), (hbad_b, habs_b, hmb_b)) = hstats
        chunk_ids = np.arange(V)[None, :] * S + np.arange(S)[:, None]
        hc.add_stage_stats("zb", hbad, habs, hmb, chunk_ids=chunk_ids,
                           pass_name="fwd")
        hc.add_stage_stats("zb", hbad_b, habs_b, hmb_b, chunk_ids=chunk_ids,
                           pass_name="bwd_input")

    # ---- embedding backward ------------------------------------------

    def embed_bwd(acc, xs):
        mb_input, key, dcarry, dside_row = xs

        def embed_inexact(p_rest):
            args, kwargs = mb_input
            out, aux = apply_collecting_aux(
                module, {"params": cast_half(with_layers(p_rest))}, *args,
                rngs=_mk_rngs(model, key, "embed"),
                method=spec.embed_method, **kwargs,
            )
            leaves, _, idx = _inexact_leaves(out)
            return [leaves[i] for i in idx] + [aux]

        out_aval = jax.eval_shape(embed_inexact, params_rest)
        if sides is not None:
            cots = list(jax.tree_util.tree_leaves(dcarry)) + list(dside_row)
        else:
            cots = jax.tree_util.tree_leaves(dcarry)
        cots = cots + [aux_seed]
        cots = [c.astype(a.dtype) for c, a in zip(cots, out_aval)]
        _, vjp = jax.vjp(embed_inexact, params_rest)
        (dp,) = vjp(cots)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), acc, dp
        )
        return acc, None

    if spec.embed_method is not None:
        demb_params0 = param_grad_zeros(params_rest)
        dside_stack = tuple(dsides) if dsides is not None else ()
        demb_params, _ = jax.lax.scan(
            embed_bwd, demb_params0,
            (stacked_inputs, mb_keys, dembed, dside_stack),
        )
    else:
        demb_params = None

    # ---- assemble the full gradient tree -----------------------------

    flat_idx = jnp.asarray(idx_np.reshape(-1))
    flat_mask = active_np.reshape(-1)

    def to_layers(g):
        gf = g.reshape((S * V * maxp,) + g.shape[3:])
        gf = gf * flat_mask.reshape((-1,) + (1,) * (gf.ndim - 1))
        return jnp.zeros((L,) + g.shape[3:], g.dtype).at[flat_idx].add(gf)

    layer_grads = jax.tree_util.tree_map(to_layers, dlay)
    if demb_params is not None:
        drep = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(a.dtype), drep, demb_params
        )
    grads = _set_subtree(drep, spec.layer_path, layer_grads)
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.result_type(p)), grads, params
    )
    return grads, losses, outs


def _set_subtree(tree, path, sub):
    """Return a copy of `tree` with the node at '/'-path replaced by `sub`."""
    parts = [p for p in path.strip("/").split("/") if p]

    def rec(node, i):
        if i == len(parts):
            return sub
        out = dict(node)
        out[parts[i]] = rec(node[parts[i]], i + 1)
        return out

    return rec(tree, 0)
