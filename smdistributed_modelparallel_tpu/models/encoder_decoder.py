"""Encoder-decoder (T5-style) model composition from smp.nn pieces.

BASELINE config #5 targets a T5-3B-scale encoder-decoder; the reference
distributes T5 at the LAYER level only (``torch/nn/huggingface/t5.py`` maps
``T5Block`` -> ``DistributedTransformerLayer`` and leaves the rest of the
HF model as user code). This module provides the standing model the user
would otherwise assemble: a bidirectional encoder stack, a causal decoder
stack with cross-attention, shared token embeddings, and a tied LM head —
all built on ``smp.nn.DistributedTransformer``, so tensor/data/context
parallelism and activation checkpointing apply unchanged.

Two architecture dialects:

- default: learned absolute positions + LayerNorm (the original zoo
  family; not HF-weight-compatible);
- ``t5_compat=True``: HF-T5-weight-compatible — RMSNorm, bucketed
  relative-position bias shared by every layer of a stack, no absolute
  positions, bias-free dense layers, unscaled attention scores, and the
  tied head's ``d_model**-0.5`` rescale. ``nn/huggingface/t5.py`` builds
  this dialect from a ``transformers.T5Config`` and translates weights in
  both directions (beyond the reference's layer-hook-only T5 support).

Pipeline parallelism decomposes as: encoder + embeddings in ``embed()``
(tp/dp/cp-parallel, replicated over pp stages), the DECODER stack as the
pipelined layer sequence, final norm + tied head in ``head()``. Encoder
padding masks apply to both encoder self-attention and (via the carry's
(self_mask, cross_mask) pair) decoder cross-attention.
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.nn.layer_norm import DistributedLayerNorm
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedTransformer,
    DistributedTransformerLayer,
)
from smdistributed_modelparallel_tpu.parallel.pipeline import PipelineSpec

NEG = -1e9


def _init(stddev):
    return nn.initializers.normal(stddev)


def relative_position_bucket(rel_pos, *, bidirectional, num_buckets,
                             max_distance):
    """T5's log-spaced relative-position bucketing (public algorithm:
    Raffel et al. 2020, eq. as implemented in the HF port). ``rel_pos`` is
    ``memory_position - query_position``."""
    ret = jnp.zeros_like(rel_pos)
    n = num_buckets
    if bidirectional:
        n = n // 2
        ret = ret + (rel_pos > 0).astype(jnp.int32) * n
        rel = jnp.abs(rel_pos)
    else:
        rel = -jnp.minimum(rel_pos, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    log_big = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (n - max_exact)
    ).astype(jnp.int32)
    log_big = jnp.minimum(log_big, n - 1)
    return ret + jnp.where(is_small, rel, log_big)


class EncoderDecoderLM(nn.Module):
    """Seq2seq LM: encoder ids + decoder ids -> decoder logits."""

    vocab_size: int
    d_model: int
    enc_layers: int
    dec_layers: int
    n_heads: int
    d_ff: int
    max_len: int
    # T5's attention width is n_heads * d_kv, decoupled from d_model
    # (T5-3B: d_model 1024 but d_kv 128 -> 4096-wide attention).
    d_kv: Optional[int] = None
    dropout: float = 0.0
    initializer_range: float = 0.02
    activation: str = "gelu"
    activation_checkpointing: bool = False
    # Vocab-parallel shared embedding + tied head (DistributedEmbedding);
    # off by default, matching DistributedTransformerLMHead's default.
    distribute_embedding: bool = False
    # HF-T5 weight compatibility (see module docstring).
    t5_compat: bool = False
    # T5 v1.1 (flan-T5) dialect: gated MLP (wi_0/wi_1) and an untied
    # lm_head; classic v1.0 is non-gated with tied embeddings.
    gated_mlp: bool = False
    tie_embeddings: bool = True
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layernorm_epsilon: float = 1e-5
    # KV-cache decoding for smp.generate: applies to the DECODER stack
    # only (self-attn caches grow; cross-attn K/V compute once). The
    # encoder is cache-free. See nn/utils.DecodeKVCache, generation.py.
    decode: bool = False
    decode_cache_len: Optional[int] = None
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    @nn.nowrap
    def _common(self):
        D, H = self.d_model, self.n_heads
        return dict(
            num_attention_heads=H,
            attention_head_size=self.d_kv or D // H,
            hidden_size=D,
            intermediate_size=self.d_ff,
            attention_dropout_prob=self.dropout,
            hidden_dropout_prob=self.dropout,
            activation=self.activation,
            pre_layernorm=True,
            post_layernorm=False,
            initializer_range=self.initializer_range,
            activation_checkpointing=self.activation_checkpointing,
            layernorm_epsilon=self.layernorm_epsilon,
            deterministic=self.deterministic,
            dtype=self.dtype,
            gated_mlp=self.gated_mlp,
            **(
                dict(
                    layernorm_type="rms",
                    use_mlp_bias=False,
                    use_qkv_bias=False,
                    use_attn_dense_bias=False,
                    scale_attention_scores=False,
                    mask_value=NEG,
                )
                if self.t5_compat else {}
            ),
        )

    def setup(self):
        D, H = self.d_model, self.n_heads
        common = self._common()
        rms = self.t5_compat
        if self.distribute_embedding:
            from smdistributed_modelparallel_tpu.nn.embedding import (
                DistributedEmbedding,
            )

            self.shared_embedding = DistributedEmbedding(
                self.vocab_size, D, split="vocab",
                init_scale=self.initializer_range,
                name="shared_embedding",
            )
        else:
            self.shared_embedding = nn.Embed(
                self.vocab_size, D,
                embedding_init=_init(self.initializer_range),
                name="shared_embedding",
            )
        if self.t5_compat:
            # Bucketed relative-position bias tables: ONE per stack, shared
            # by every layer of that stack (HF keeps the table on block 0).
            self.enc_rel_bias = nn.Embed(
                self.relative_attention_num_buckets, H,
                embedding_init=_init(self.initializer_range),
                name="enc_rel_bias",
            )
            self.dec_rel_bias = nn.Embed(
                self.relative_attention_num_buckets, H,
                embedding_init=_init(self.initializer_range),
                name="dec_rel_bias",
            )
        else:
            self.enc_position_embedding = nn.Embed(
                self.max_len, D, embedding_init=_init(self.initializer_range),
                name="enc_position_embedding",
            )
            self.dec_position_embedding = nn.Embed(
                self.max_len, D, embedding_init=_init(self.initializer_range),
                name="dec_position_embedding",
            )
        self.encoder = DistributedTransformer(
            num_layers=self.enc_layers,
            causal_mask_size=None,          # bidirectional
            name="encoder", **common,
        )
        self.encoder_ln = DistributedLayerNorm(
            epsilon=self.layernorm_epsilon, rms=rms, use_bias=not rms,
            name="encoder_ln",
        )
        self.decoder = DistributedTransformer(
            num_layers=self.dec_layers,
            causal_mask_size=self.max_len,  # causal
            add_cross_attention=True,
            decode=self.decode,
            decode_cache_len=self.decode_cache_len,
            name="decoder", **common,
        )
        if self.decode:
            # Absolute decoder position across decode steps (drives the
            # learned position embedding / relative bias row offsets).
            self._dec_pos = self.variable(
                "cache", "decoder_position", lambda: jnp.zeros((), jnp.int32)
            )
        self.decoder_ln = DistributedLayerNorm(
            epsilon=self.layernorm_epsilon, rms=rms, use_bias=not rms,
            name="decoder_ln",
        )
        if not self.tie_embeddings:
            self.lm_head = nn.Dense(
                self.vocab_size, use_bias=False,
                kernel_init=_init(self.initializer_range), name="lm_head",
            )

    # -- mask / bias assembly ------------------------------------------

    @nn.nowrap
    def _pad4d(self, encoder_mask):
        """[B, S] or [B, 1, 1, S] padding mask -> additive [B, 1, 1, S].

        Boolean AND integer masks are keep-flags (HF passes int64 0/1
        attention masks — treating those as additive would silently not
        mask anything); floats are already additive biases."""
        if encoder_mask is None:
            return None
        if encoder_mask.ndim == 2:
            encoder_mask = encoder_mask[:, None, None, :]
        if not jnp.issubdtype(encoder_mask.dtype, jnp.floating):
            return jnp.where(encoder_mask != 0, 0.0, NEG).astype(jnp.float32)
        return encoder_mask.astype(jnp.float32)

    def _rel_bias(self, table, T, S, bidirectional):
        """[1, H, T, S] additive bias from a bucket-embedding table."""
        ctx = jnp.arange(T)[:, None]
        mem = jnp.arange(S)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, bidirectional=bidirectional,
            num_buckets=self.relative_attention_num_buckets,
            max_distance=self.relative_attention_max_distance,
        )
        bias = table(buckets)                   # [T, S, H]
        return bias.transpose(2, 0, 1)[None].astype(jnp.float32)

    # -- pipeline decomposition ----------------------------------------

    def embed(self, encoder_ids, decoder_ids, encoder_mask=None):
        """Everything before the decoder layer stack: embeddings, the FULL
        encoder (tp/dp/cp-parallel; replicated across pp stages), and the
        decoder carry (hidden, cross_states, (self_mask, cross_mask))."""
        pad = self._pad4d(encoder_mask)
        h_e = self.encode(encoder_ids, encoder_mask)

        if self.t5_compat:
            T = decoder_ids.shape[-1]
            dec_mask = self._rel_bias(self.dec_rel_bias, T, T, False)
            h_d = self.shared_embedding(decoder_ids)
        else:
            dec_mask = None
            pos_d = jnp.arange(decoder_ids.shape[-1])[None, :]
            h_d = (
                self.shared_embedding(decoder_ids)
                + self.dec_position_embedding(pos_d)
            )
        # The decoder's mask slot carries (self_mask, cross_mask): the
        # relative bias on self-attention and the encoder padding on
        # cross-attention (see DistributedTransformerLayer).
        if dec_mask is not None or pad is not None:
            masks = (dec_mask, pad)
        else:
            masks = None
        return (h_d, h_e, masks)

    def head(self, carry):
        h_d = carry[0] if isinstance(carry, tuple) else carry
        h_d = self.decoder_ln(h_d)
        if not self.tie_embeddings:
            # Untied head (T5 v1.1): no rescale (HF rescales only when
            # tie_word_embeddings).
            return self.lm_head(h_d)
        if self.t5_compat:
            # Tied-head rescale (HF T5 with tie_word_embeddings).
            h_d = h_d * jnp.asarray(
                self.d_model ** -0.5, h_d.dtype
            )
        return self.shared_embedding.attend(h_d)

    def __call__(self, encoder_ids, decoder_ids, encoder_mask=None):
        h_d, h_e, masks = self.embed(encoder_ids, decoder_ids, encoder_mask)
        h_d = self.decoder(h_d, cross_states=h_e, attention_mask=masks)
        return self.head(h_d)

    # -- generation protocol (smp.generate seq2seq branch) --------------

    def encode(self, encoder_ids, encoder_mask=None):
        """Encoder forward only — run ONCE per generation."""
        pad = self._pad4d(encoder_mask)
        if self.t5_compat:
            S = encoder_ids.shape[-1]
            enc_mask = self._rel_bias(self.enc_rel_bias, S, S, True)
            if pad is not None:
                enc_mask = enc_mask + pad
            h_e = self.shared_embedding(encoder_ids)
        else:
            enc_mask = pad
            pos_e = jnp.arange(encoder_ids.shape[-1])[None, :]
            h_e = (
                self.shared_embedding(encoder_ids)
                + self.enc_position_embedding(pos_e)
            )
        return self.encoder_ln(self.encoder(h_e, attention_mask=enc_mask))

    def decode_step(self, decoder_ids, encoder_hidden, encoder_mask=None):
        """One KV-cached decoder chunk (requires ``decode=True``): embeds
        ``decoder_ids`` at the absolute cache position, runs the decoder
        over the cache, returns logits for the chunk."""
        pad = self._pad4d(encoder_mask)
        T = decoder_ids.shape[-1]
        start = self._dec_pos.value
        self._dec_pos.value = start + T
        if self.t5_compat:
            # Relative bias rows for the chunk's absolute positions. A T=1
            # step attends the full cache (the layer ANDs in the <=index
            # mask); a T>1 chunk (first call, empty cache) attends itself
            # chunk-causally — columns are the chunk's own positions.
            ctx = start + jnp.arange(T)[:, None]
            if T > 1:
                mem = start + jnp.arange(T)[None, :]
            else:
                mem = jnp.arange(self.decode_cache_len)[None, :]
            buckets = relative_position_bucket(
                mem - ctx, bidirectional=False,
                num_buckets=self.relative_attention_num_buckets,
                max_distance=self.relative_attention_max_distance,
            )
            dec_mask = (
                self.dec_rel_bias(buckets).transpose(2, 0, 1)[None]
                .astype(jnp.float32)
            )
            h_d = self.shared_embedding(decoder_ids)
        else:
            dec_mask = None
            pos_d = start + jnp.arange(T)[None, :]
            h_d = (
                self.shared_embedding(decoder_ids)
                + self.dec_position_embedding(pos_d)
            )
        masks = (dec_mask, pad) if (dec_mask is not None or pad is not None) else None
        h_d = self.decoder(
            h_d, cross_states=encoder_hidden, attention_mask=masks
        )
        return self.head(h_d)

    @nn.nowrap
    def pipeline_spec(self):
        layer_kw = dict(self._common())
        # Transformer-level knob; the per-layer remat is applied by the
        # executors via carry_remat (partition_for_pipeline harvests it).
        layer_kw.pop("activation_checkpointing", None)
        return PipelineSpec(
            layer_path="decoder/seq_layers/layer",
            num_layers=self.dec_layers,
            layer_module=DistributedTransformerLayer(
                causal_mask_size=self.max_len,
                add_cross_attention=True,
                **layer_kw,
            ),
            carry_remat=self.activation_checkpointing,
            layer_xs={
                "layer_idx": jnp.arange(self.dec_layers, dtype=jnp.int32)
            },
            carry_is_tuple=True,
        )


_CONFIGS = {
    # BASELINE #5 shape: T5-3B-scale (d_kv=128 -> 4096-wide attention,
    # like the published T5-3B; ~2.8B params).
    "t5_style_3b": dict(d_model=1024, enc_layers=24, dec_layers=24,
                        n_heads=32, d_kv=128, d_ff=16384),
    "t5_style_small": dict(d_model=512, enc_layers=6, dec_layers=6,
                           n_heads=8, d_kv=64, d_ff=2048),
}


def t5_style(size="t5_style_small", vocab_size=32128, max_len=512, **overrides):
    cfg = dict(_CONFIGS[size])
    cfg.update(vocab_size=vocab_size, max_len=max_len)
    cfg.update(overrides)
    return EncoderDecoderLM(**cfg)


def t5_style_3b(**overrides):
    return t5_style("t5_style_3b", **overrides)
