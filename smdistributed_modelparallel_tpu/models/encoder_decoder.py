"""Encoder-decoder (T5-style) model composition from smp.nn pieces.

BASELINE config #5 targets a T5-3B-scale encoder-decoder; the reference
distributes T5 at the LAYER level only (``torch/nn/huggingface/t5.py`` maps
``T5Block`` -> ``DistributedTransformerLayer`` and leaves the rest of the
HF model as user code). This module provides the standing model the user
would otherwise assemble: a bidirectional encoder stack, a causal decoder
stack with cross-attention, shared token embeddings, and a tied LM head —
all built on ``smp.nn.DistributedTransformer``, so tensor/data/context
parallelism and activation checkpointing apply unchanged.

T5-STYLE, not HF-T5-weight-compatible: learned absolute positions instead
of relative-position buckets, LayerNorm instead of RMSNorm (HF T5 weight
translation remains layer-level, the reference's scope). Pipeline
parallelism needs a single scanned stack and is rejected with the
standard pipelineable-model error for pp > 1; encoder padding masks apply
to encoder self-attention (cross-attention currently attends to all
encoder positions).
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.nn.layer_norm import DistributedLayerNorm
from smdistributed_modelparallel_tpu.nn.transformer import (
    DistributedTransformer,
)


def _init(stddev):
    return nn.initializers.normal(stddev)


class EncoderDecoderLM(nn.Module):
    """Seq2seq LM: encoder ids + decoder ids -> decoder logits."""

    vocab_size: int
    d_model: int
    enc_layers: int
    dec_layers: int
    n_heads: int
    d_ff: int
    max_len: int
    # T5's attention width is n_heads * d_kv, decoupled from d_model
    # (T5-3B: d_model 1024 but d_kv 128 -> 4096-wide attention).
    d_kv: Optional[int] = None
    dropout: float = 0.0
    initializer_range: float = 0.02
    activation: str = "gelu"
    activation_checkpointing: bool = False
    # Vocab-parallel shared embedding + tied head (DistributedEmbedding);
    # off by default, matching DistributedTransformerLMHead's default.
    distribute_embedding: bool = False
    deterministic: Optional[bool] = None
    dtype: Optional[Any] = None

    def setup(self):
        D, H = self.d_model, self.n_heads
        common = dict(
            num_attention_heads=H,
            attention_head_size=self.d_kv or D // H,
            hidden_size=D,
            intermediate_size=self.d_ff,
            attention_dropout_prob=self.dropout,
            hidden_dropout_prob=self.dropout,
            activation=self.activation,
            pre_layernorm=True,
            post_layernorm=False,
            initializer_range=self.initializer_range,
            activation_checkpointing=self.activation_checkpointing,
            deterministic=self.deterministic,
            dtype=self.dtype,
        )
        if self.distribute_embedding:
            from smdistributed_modelparallel_tpu.nn.embedding import (
                DistributedEmbedding,
            )

            self.shared_embedding = DistributedEmbedding(
                self.vocab_size, D, split="vocab",
                init_scale=self.initializer_range,
                name="shared_embedding",
            )
        else:
            self.shared_embedding = nn.Embed(
                self.vocab_size, D,
                embedding_init=_init(self.initializer_range),
                name="shared_embedding",
            )
        self.enc_position_embedding = nn.Embed(
            self.max_len, D, embedding_init=_init(self.initializer_range),
            name="enc_position_embedding",
        )
        self.dec_position_embedding = nn.Embed(
            self.max_len, D, embedding_init=_init(self.initializer_range),
            name="dec_position_embedding",
        )
        self.encoder = DistributedTransformer(
            num_layers=self.enc_layers,
            causal_mask_size=None,          # bidirectional
            name="encoder", **common,
        )
        self.encoder_ln = DistributedLayerNorm(name="encoder_ln")
        self.decoder = DistributedTransformer(
            num_layers=self.dec_layers,
            causal_mask_size=self.max_len,  # causal
            add_cross_attention=True,
            name="decoder", **common,
        )
        self.decoder_ln = DistributedLayerNorm(name="decoder_ln")

    def __call__(self, encoder_ids, decoder_ids, encoder_mask=None):
        if encoder_mask is not None and encoder_mask.ndim == 2:
            # Natural [B, S] padding mask -> the attention contract's
            # [B, 1, 1, S] (a raw 2-D mask would broadcast WRONG against
            # [B, H, T, S] scores on the jnp fallback path).
            encoder_mask = encoder_mask[:, None, None, :]
        pos_e = jnp.arange(encoder_ids.shape[-1])[None, :]
        h_e = self.shared_embedding(encoder_ids) + self.enc_position_embedding(pos_e)
        h_e = self.encoder(h_e, attention_mask=encoder_mask)
        h_e = self.encoder_ln(h_e)

        pos_d = jnp.arange(decoder_ids.shape[-1])[None, :]
        h_d = self.shared_embedding(decoder_ids) + self.dec_position_embedding(pos_d)
        h_d = self.decoder(h_d, cross_states=h_e)
        h_d = self.decoder_ln(h_d)
        return self.shared_embedding.attend(h_d)


_CONFIGS = {
    # BASELINE #5 shape: T5-3B-scale (d_kv=128 -> 4096-wide attention,
    # like the published T5-3B; ~2.8B params).
    "t5_style_3b": dict(d_model=1024, enc_layers=24, dec_layers=24,
                        n_heads=32, d_kv=128, d_ff=16384),
    "t5_style_small": dict(d_model=512, enc_layers=6, dec_layers=6,
                           n_heads=8, d_kv=64, d_ff=2048),
}


def t5_style(size="t5_style_small", vocab_size=32128, max_len=512, **overrides):
    cfg = dict(_CONFIGS[size])
    cfg.update(vocab_size=vocab_size, max_len=max_len)
    cfg.update(overrides)
    return EncoderDecoderLM(**cfg)


def t5_style_3b(**overrides):
    return t5_style("t5_style_3b", **overrides)
