"""GPT-2 model family.

Parity target: reference HF GPT-2 support
(``torch/nn/huggingface/gpt2.py``): the reference auto-translates
``GPT2LMHeadModel`` into ``DistributedTransformerLMHead``; here the family
is provided natively as ``TransformerLM`` configs. HF state-dict translation
lands with the checkpoint subsystem (M5).

Sizes follow the published GPT-2 family; ``gpt2_1p5b`` is BASELINE config #2
(the north-star benchmark model) and ``gpt2_124m`` BASELINE config #1.
"""

from smdistributed_modelparallel_tpu.models.transformer_lm import TransformerLM

_CONFIGS = {
    "gpt2_124m": dict(d_model=768, n_layers=12, n_heads=12),
    "gpt2_350m": dict(d_model=1024, n_layers=24, n_heads=16),
    "gpt2_774m": dict(d_model=1280, n_layers=36, n_heads=20),
    "gpt2_1p5b": dict(d_model=1600, n_layers=48, n_heads=25),
}


def gpt2(size="gpt2_124m", vocab_size=50257, max_len=1024, **overrides):
    cfg = dict(_CONFIGS[size])
    cfg.update(
        vocab_size=vocab_size,
        max_len=max_len,
        pos_type="learned",
        tie_weights=True,
    )
    cfg.update(overrides)
    return TransformerLM(**cfg)


def gpt2_124m(**overrides):
    return gpt2("gpt2_124m", **overrides)


def gpt2_1p5b(**overrides):
    return gpt2("gpt2_1p5b", **overrides)
