"""Decoder-only transformer LM — the flagship model family.

Parity target: reference ``torch/nn/transformer.py:184-550``
(``DistributedTransformerLMHead``: embeddings + transformer + tied LM head
behind ~40 config keys) re-designed flax-first:

- layers are built with ``flax.linen.scan`` so parameters carry a leading
  [num_layers] axis — one layer is traced/compiled once, and the stacked
  layout is exactly what the pipeline executor (``parallel/pipeline.py``)
  and per-layer rematerialization need;
- ``embed`` / ``head`` are standalone methods so the pipeline can run them
  around the layer stack (``PipelineSpec`` protocol);
- attention/MLP internals route through ``smp.nn`` functional ops so tensor
  parallelism (M3) applies the Megatron-style sharding without touching
  this file.

Model-zoo configs for GPT-2 sizes are in ``models/gpt2.py``.
"""

from dataclasses import field
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.parallel.pipeline import PipelineSpec


def _gelu(x):
    return nn.gelu(x, approximate=True)


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention.

    Parity: reference ``DistributedAttentionLayer``
    (``torch/nn/transformer.py:1176-1835``); TP sharding lands in M3 via
    sharding constraints on the head dimension.

    ``decode=True`` enables the KV-cache path for autoregressive
    generation (TPU extension, ``generation.py``): K/V of every chunk are
    written into fixed-length "cache" variables of ``decode_cache_len``
    slots; a T=1 call attends over the cache (prior positions only), a
    T>1 call is the prefill and attends causally over its own chunk (the
    cache is empty before it, so chunk-causal == cache semantics — and it
    keeps the flash-kernel path for the prompt pass).
    """

    d_model: int
    n_heads: int
    dropout: float = 0.0
    attention_in_fp32: bool = False
    rotary: bool = False
    rotary_dim: Optional[int] = None
    window: Optional[int] = None
    deterministic: bool = True
    decode: bool = False
    decode_cache_len: Optional[int] = None
    # Paged decoding for smp.serving (nn/utils.PagedKVCache): K/V live in
    # a shared block pool; per-call state (block tables, positions)
    # arrives via the ``paged`` argument. Mutually exclusive with decode.
    paged_blocks: Optional[int] = None
    paged_block_tokens: Optional[int] = None

    @nn.compact
    def __call__(self, x, attn_bias=None, paged=None):
        B, T, D = x.shape
        H = self.n_heads
        hd = D // H
        qkv = nn.Dense(3 * D, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)

        pos_offset = 0
        cache = None
        decode_mask = None
        if self.paged_blocks is not None:
            if paged is None:
                raise ValueError(
                    "paged KV-cache decoding needs the per-call paged "
                    "state (block_tables/positions) — drive this module "
                    "through smp.serving.ServingEngine."
                )
            pos_offset = paged["positions"]
        elif self.decode:
            from smdistributed_modelparallel_tpu.nn.utils import DecodeKVCache

            cache = DecodeKVCache(self, (B, self.decode_cache_len, H, hd),
                                  k.dtype)
            pos_offset = cache.index
        if self.rotary:
            from smdistributed_modelparallel_tpu.nn.transformer import apply_rotary

            rd = self.rotary_dim or hd
            # The cache stores POST-rotary K: chunk q/k rotate at their
            # absolute positions once, on write.
            q, k = apply_rotary(q, k, rd, neox_style=True, offset=pos_offset)
        if self.paged_blocks is not None:
            from smdistributed_modelparallel_tpu.nn.utils import PagedKVCache

            pool = PagedKVCache(
                self, self.paged_blocks, self.paged_block_tokens, H, hd,
                k.dtype,
            )
            k, v, decode_mask = pool.append(
                k, v, paged["block_tables"], paged["positions"],
                valid=paged.get("valid"), window=self.window,
            )
        elif cache is not None:
            k, v, decode_mask = cache.append(k, v, window=self.window)
        from smdistributed_modelparallel_tpu.ops.attention import attention_core

        drop_rng = None
        if self.dropout > 0.0 and not self.deterministic:
            drop_rng = self.make_rng("dropout")
        out = attention_core(
            q, k, v,
            causal=decode_mask is None,
            window=self.window if decode_mask is None else None,
            bias=attn_bias,
            mask=decode_mask,
            attention_in_fp32=self.attention_in_fp32,
            dropout_rate=self.dropout if not self.deterministic else 0.0,
            dropout_rng=drop_rng,
        ).reshape(B, T, D)
        return nn.Dense(D, name="proj")(out)


class TransformerLayer(nn.Module):
    """One pre/post-LN transformer block; applied per pipeline stage."""

    d_model: int
    n_heads: int
    d_ff: int
    dropout: float = 0.0
    pre_layernorm: bool = True
    post_layernorm: bool = False
    attention_in_fp32: bool = False
    rotary: bool = False
    rotary_dim: Optional[int] = None
    window: Optional[int] = None
    parallel_block: bool = False  # GPT-J style parallel attn+mlp
    deterministic: bool = True
    ln_eps: float = 1e-5
    decode: bool = False
    decode_cache_len: Optional[int] = None
    paged_blocks: Optional[int] = None
    paged_block_tokens: Optional[int] = None

    @nn.compact
    def __call__(self, x, paged=None):
        attn = CausalSelfAttention(
            self.d_model, self.n_heads, self.dropout, self.attention_in_fp32,
            self.rotary, self.rotary_dim, self.window, self.deterministic,
            self.decode, self.decode_cache_len,
            self.paged_blocks, self.paged_block_tokens,
            name="attn",
        )

        def mlp(h):
            h = nn.Dense(self.d_ff, name="fc")(h)
            h = _gelu(h)
            h = nn.Dense(self.d_model, name="proj")(h)
            return h

        if self.parallel_block:
            h = nn.LayerNorm(epsilon=self.ln_eps, name="ln1")(x)
            x = x + attn(h, paged=paged) + mlp(h)
        else:
            h = nn.LayerNorm(epsilon=self.ln_eps, name="ln1")(x) if self.pre_layernorm else x
            x = x + attn(h, paged=paged)
            if self.post_layernorm:
                x = nn.LayerNorm(epsilon=self.ln_eps, name="ln1_post")(x)
            h = nn.LayerNorm(epsilon=self.ln_eps, name="ln2")(x) if self.pre_layernorm else x
            x = x + mlp(h)
            if self.post_layernorm:
                x = nn.LayerNorm(epsilon=self.ln_eps, name="ln2_post")(x)
        if self.dropout > 0.0 and not self.deterministic:
            x = nn.Dropout(self.dropout, deterministic=False)(x)
        return x


class _ScanBody(nn.Module):
    """Carry-protocol wrapper for nn.scan over TransformerLayer. The
    second argument is the scan's xs slot — None in training/decode, the
    (broadcast) paged per-call state under smp.serving."""

    layer_kwargs: dict

    @nn.compact
    def __call__(self, x, paged):
        return (
            TransformerLayer(**self.layer_kwargs, name="block")(
                x, paged=paged
            ),
            None,
        )


class TransformerLM(nn.Module):
    """Embeddings + scanned transformer stack + (tied) LM head."""

    vocab_size: int
    max_len: int
    d_model: int
    n_layers: int
    n_heads: int
    d_ff: Optional[int] = None
    dropout: float = 0.0
    pos_type: str = "learned"      # learned | rotary | none
    tie_weights: bool = True
    parallel_block: bool = False
    attention_in_fp32: bool = False
    window: Optional[int] = None
    rotary_dim: Optional[int] = None
    deterministic: bool = True
    ln_eps: float = 1e-5
    # Loss-mode (targets=...) uniform label smoothing, HF/T5 convention.
    label_smoothing: float = 0.0
    # KV-cache decoding for smp.generate (see nn/utils.DecodeKVCache).
    decode: bool = False
    decode_cache_len: Optional[int] = None
    # Paged serving decode (smp.serving / nn/utils.PagedKVCache): the
    # block-pool geometry; per-call block tables/positions arrive via the
    # ``paged`` argument of ``__call__``.
    paged_blocks: Optional[int] = None
    paged_block_tokens: Optional[int] = None

    @nn.nowrap
    def _layer_kwargs(self):
        return dict(
            d_model=self.d_model,
            n_heads=self.n_heads,
            d_ff=self.d_ff or 4 * self.d_model,
            dropout=self.dropout,
            attention_in_fp32=self.attention_in_fp32,
            rotary=self.pos_type == "rotary",
            rotary_dim=self.rotary_dim,
            window=self.window,
            parallel_block=self.parallel_block,
            deterministic=self.deterministic,
            ln_eps=self.ln_eps,
            decode=self.decode,
            decode_cache_len=self.decode_cache_len,
            paged_blocks=self.paged_blocks,
            paged_block_tokens=self.paged_block_tokens,
        )

    def setup(self):
        self.wte = nn.Embed(self.vocab_size, self.d_model, name="wte")
        if self.pos_type == "learned":
            self.wpe = nn.Embed(self.max_len, self.d_model, name="wpe")
        scan_kwargs = {}
        if self.paged_blocks is not None:
            # The paged per-call state (block tables, positions) is the
            # same for every layer: broadcast it instead of scanning.
            # Only the paged clone changes its scan signature — the
            # training/decode paths keep the exact pre-serving transform.
            scan_kwargs["in_axes"] = nn.broadcast
        ScanLayers = nn.scan(
            _ScanBody,
            variable_axes={"params": 0, "cache": 0},
            split_rngs={"params": True, "dropout": True},
            length=self.n_layers,
            **scan_kwargs,
        )
        self.layers = ScanLayers(self._layer_kwargs(), name="layers")
        self.ln_f = nn.LayerNorm(epsilon=self.ln_eps, name="ln_f")
        if not self.tie_weights:
            self.lm_head = nn.Dense(self.vocab_size, use_bias=False, name="lm_head")
        if self.decode:
            # Top-level mirror of the per-layer cache indices: learned
            # positions need the absolute offset before the layer stack.
            self._pos_index = self.variable(
                "cache", "position_index", lambda: jnp.zeros((), jnp.int32)
            )

    # -- pipeline decomposition ----------------------------------------

    def embed(self, ids, paged=None):
        x = self.wte(ids)
        if self.pos_type == "learned":
            if paged is not None:
                # Per-row absolute positions (continuous batching mixes
                # sequences at different depths in one decode batch).
                pos = paged["positions"][:, None] + jnp.arange(
                    ids.shape[-1], dtype=jnp.int32
                )[None, :]
                return x + self.wpe(jnp.clip(pos, 0, self.max_len - 1))
            start = 0
            if self.decode:
                start = self._pos_index.value
                self._pos_index.value = start + ids.shape[-1]
            x = x + self.wpe(start + jnp.arange(ids.shape[-1])[None, :])
        return x

    def head(self, x, targets=None):
        x = self.ln_f(x)
        if targets is not None and self.tie_weights:
            # Fused LM-head CE (TPU extension): per-token losses without
            # the [.., V] logits intermediate (nn/cross_entropy.py).
            from smdistributed_modelparallel_tpu.nn.cross_entropy import (
                fused_lm_head_cross_entropy,
            )

            return fused_lm_head_cross_entropy(
                x, self.wte.embedding, targets,
                label_smoothing=self.label_smoothing,
            )
        logits = self.wte.attend(x) if self.tie_weights else self.lm_head(x)
        if targets is None:
            return logits
        from smdistributed_modelparallel_tpu.nn.cross_entropy import (
            masked_vocab_parallel_cross_entropy,
        )

        return masked_vocab_parallel_cross_entropy(
            logits, targets, label_smoothing=self.label_smoothing
        )

    def __call__(self, ids, targets=None, paged=None):
        """ids -> logits; with ``targets`` ([B, T] int, -100 = ignored) ->
        per-token fp32 losses instead, via the fused LM-head CE (the
        logits tensor never materializes on the TPU tied-head path).
        Loss mode requires pp == 1 (the pipeline head protocol carries no
        targets). ``paged`` is the smp.serving per-call decode state
        (block tables / positions / valid), only meaningful on a
        ``paged_blocks`` clone."""
        if targets is not None:
            from smdistributed_modelparallel_tpu.backend.state import state

            if state.cfg is not None and state.cfg.pipeline_parallel_degree > 1:
                raise ValueError(
                    "model(ids, targets=...) is not available under "
                    "pipeline parallelism; compute the loss from logits."
                )
        x = self.embed(ids, paged=paged)
        x = self._apply_layers(x, paged=paged)
        return self.head(x, targets)

    def _apply_layers(self, x, paged=None):
        """The layer stack: the lifted ``nn.scan`` normally, or — under
        ``sharded_params: zero3`` at pp=1 — the double-buffered
        just-in-time gather scan (``parallel/zero.zero3_prefetch_scan``):
        each tick all-gathers the NEXT layer's rdp-sharded param slice
        into a transfer register behind an optimization barrier while the
        current layer's matmuls run, and the backward regathers from the
        sharded slice (per-layer remat) instead of stashing gathered
        copies. Decode (mutable KV cache) and non-deterministic dropout
        need the lifted scan's collection/rng plumbing and keep it."""
        if not self.is_initializing() and not self.decode and (
                paged is None) and (
                self.dropout == 0.0 or self.deterministic):
            import jax as _jax

            from smdistributed_modelparallel_tpu.parallel import zero

            stacked = self.layers.variables.get("params", {}).get("block")
            if (stacked and isinstance(x, _jax.core.Tracer)
                    and zero.zero3_prefetch_active()):
                # parent=None: a detached functional module (same trick as
                # PipelineSpec.layer_module), not a registered submodule.
                layer = TransformerLayer(**self._layer_kwargs(), parent=None)
                specs = zero.gathered_slice_specs(stacked, "layers/block")

                def apply_layer(h, p):
                    return layer.apply({"params": p}, h)

                return zero.zero3_prefetch_scan(
                    apply_layer, x, stacked, self.n_layers, specs
                )
        x, _ = self.layers(x, paged)
        return x

    @nn.nowrap
    def pipeline_spec(self):
        return PipelineSpec(
            layer_path="layers/block",
            num_layers=self.n_layers,
            layer_module=TransformerLayer(**self._layer_kwargs()),
        )


