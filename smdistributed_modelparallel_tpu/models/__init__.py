"""Model zoo: flax implementations of the reference's supported families."""
