"""Model zoo: flax implementations of the reference's supported families."""

from smdistributed_modelparallel_tpu.models.encoder_decoder import (
    EncoderDecoderLM,
    t5_style,
    t5_style_3b,
)
from smdistributed_modelparallel_tpu.models.gpt2 import gpt2, gpt2_124m, gpt2_1p5b
from smdistributed_modelparallel_tpu.models.transformer_lm import TransformerLM
