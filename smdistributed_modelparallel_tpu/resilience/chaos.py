"""Deterministic chaos / fault-injection harness.

Production resilience claims are worthless untested: "we recover from a
preempted rank" has to be demonstrated against an *actual* preempted rank.
This module is the one switchboard for injecting those faults, driven by
the ``SMP_CHAOS`` environment variable so a chaos run needs no code
changes — the same training script, plus a fault spec.

Spec grammar (comma-separated rules; each ``fault@key=value[:key=value...]``)::

    SMP_CHAOS="sigterm@step=3:rank=0,delay_collective@group=pp:ms=200"

Faults:

- ``sigterm@step=N[:rank=R]`` — deliver SIGTERM to this process at the end
  of step ``N`` (the step-engine edge in ``step.py``). With the preemption
  listener installed (``resilience/preemption.py``) this exercises the full
  emergency-checkpoint path; without it the process dies like a real
  preemption with no grace handling.
- ``kill@step=N[:rank=R]`` — deliver SIGKILL at the end of step ``N``: no
  grace, no handler, no emergency checkpoint — the hard-failure case the
  in-job recovery supervisor (``resilience/supervisor.py``) exists for.
  Peers see missed heartbeats and a dead bus link, never a notice.
- ``wedge@step=N[:rank=R]:ms=M`` — hang for ``M`` ms INSIDE step ``N``'s
  dispatch (before the compiled program runs). Heartbeats keep flowing
  (the detector thread is alive) but this rank's reported step edge stops
  advancing: the peers' detectors classify it **wedged** once the stall
  exceeds ``SMP_WEDGE_TIMEOUT``.
- ``heartbeat_drop@rank=R:count=K`` — silently drop process ``R``'s next
  ``K`` outgoing heartbeats (all peers): false-positive/flap testing for
  the failure detector — ``K`` below the miss budget must NOT produce a
  dead classification, above it must.
- ``kill_replica@request=N[:rank=R]`` — SIGKILL a SERVING replica
  mid-decode: fires at the first decode-step boundary where the
  replica's ``N``-th admitted request (1-based) has produced at least
  one token and is still unfinished. The replica-failover layer
  (``serving/replica.py``) must detect the death over the heartbeat bus
  and the survivor re-admit every unfinished request from its mirrored
  logs.
- ``kill_replica@scale=K[:rank=R]`` — SIGKILL this process right after
  the controller's ``K``-th completed autoscale event (1-based,
  ``serving/controller.py`` seam): the scale-up/scale-down edge is
  exactly when replica bookkeeping is most easily corrupted, so the
  failover path must absorb a death there too.
- ``corrupt_weights@version=N[:rank=R]`` — perturb the parameter tree a
  serving replica adopts as weights version ``N`` (every float leaf
  mapped to ``x * 1.01 + 0.01`` — deterministic, and the affine shift
  breaks greedy token parity even where a pure rescale would preserve
  every argmax): the canary's token-parity gate must catch it and the
  controller auto-roll back, latching ``smp_canary_rollback_total`` and
  one forensics bundle.
- ``bus_drop@seq=N[:rank=R][:dest=D]`` — silently drop this process's
  ``N``-th native-bus send (0-based ordinal over all sends; heartbeats
  ride their own seam and do not consume ordinals). The receiver never
  sees the message: exercises watchdog/timeout recovery.
- ``bus_error@seq=N[:rank=R][:dest=D]`` — fail the ``N``-th send at the
  enqueue edge: exercises the bounded retry/backoff and ``SMPPeerLost``
  path in ``backend/native.py``.
- ``delay_collective@group=G:ms=M[:count=C]`` — sleep ``M`` ms before each
  host collective whose group name starts with ``G`` (case-insensitive;
  e.g. ``pp`` matches ``PP_GROUP``), at most ``C`` times (default
  unlimited): manufactures stragglers for the observability stack.

``rank=R`` restricts a rule to process index ``R`` (default: every
process). Rules are deterministic — ordinals and step numbers are exact,
never sampled — so a chaos failure reproduces byte-for-byte.

Seams live in ``step.py`` (``on_step_edge``, ``on_step_dispatch``),
``backend/native.py`` (``on_bus_send``), ``backend/collectives.py``
(``on_collective``) and ``resilience/supervisor.py`` (``on_heartbeat``). Every
seam's disabled path is one ``os.environ.get`` — a run without ``SMP_CHAOS``
pays nothing. Injections are counted in ``smp_chaos_injected_total`` and
recorded as flight-recorder ``chaos`` events so a post-mortem ring always
shows which faults were synthetic.

Import-hygiene contract: stdlib + the package logger/telemetry only.
"""

import os
import signal
import time

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_chaos,
    telemetry,
)

logger = get_logger()

CHAOS_ENV = "SMP_CHAOS"

_KNOWN_FAULTS = (
    "sigterm", "kill", "wedge", "heartbeat_drop",
    "bus_drop", "bus_error", "delay_collective", "kill_replica",
    "corrupt_weights",
)

# Argument value parsers: validated at PARSE time so a typo degrades to a
# skipped rule with a warning — never a ValueError at a seam mid-run.
_NUMERIC_KEYS = {
    "step": int, "rank": int, "seq": int, "dest": int, "count": int,
    "ms": float, "request": int, "scale": int, "version": int,
}


class _Rule:
    __slots__ = ("fault", "kv", "fired")

    def __init__(self, fault, kv):
        self.fault = fault
        self.kv = kv
        self.fired = 0

    def rank_matches(self):
        r = self.kv.get("rank")
        return r is None or int(r) == int(telemetry.process_index or 0)

    def __repr__(self):
        return f"_Rule({self.fault}, {self.kv}, fired={self.fired})"


def parse_spec(spec):
    """Parse an ``SMP_CHAOS`` spec string into rules. Malformed rules are
    skipped with a warning — a typo in a chaos spec must degrade to "no
    fault", never crash the training run it was meant to probe."""
    rules = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        fault, _, args = raw.partition("@")
        fault = fault.strip()
        if fault not in _KNOWN_FAULTS:
            logger.warning(
                "%s: unknown fault %r in rule %r (known: %s); skipping.",
                CHAOS_ENV, fault, raw, ", ".join(_KNOWN_FAULTS),
            )
            continue
        kv = {}
        ok = True
        for part in filter(None, args.split(":")):
            k, sep, v = part.partition("=")
            if not sep or not k or not v:
                logger.warning(
                    "%s: malformed argument %r in rule %r; skipping rule.",
                    CHAOS_ENV, part, raw,
                )
                ok = False
                break
            k, v = k.strip(), v.strip()
            conv = _NUMERIC_KEYS.get(k)
            if conv is not None:
                try:
                    conv(v)
                except ValueError:
                    logger.warning(
                        "%s: non-numeric %s=%r in rule %r; skipping rule.",
                        CHAOS_ENV, k, v, raw,
                    )
                    ok = False
                    break
            kv[k] = v
        if ok:
            rules.append(_Rule(fault, kv))
    return rules


class ChaosInjector:
    """Singleton switchboard; seams call the ``on_*`` hooks.

    The spec is re-read lazily (one env lookup + string compare per seam
    call) so tests and operators can arm/disarm faults mid-process; rule
    fire-counters and the bus-send ordinal reset when the spec changes.
    """

    def __init__(self):
        self._spec = ""
        self._rules = []
        self._bus_send_ordinal = 0

    def _sync(self):
        spec = os.environ.get(CHAOS_ENV, "")
        if spec != self._spec:
            self._spec = spec
            self._rules = parse_spec(spec) if spec else []
            self._bus_send_ordinal = 0
            if self._rules:
                logger.warning(
                    "chaos harness ARMED: %d rule(s) from %s=%r",
                    len(self._rules), CHAOS_ENV, spec,
                )
        return self._rules

    @property
    def enabled(self):
        return bool(self._sync())

    @property
    def rules(self):
        return list(self._sync())

    # -- seams ----------------------------------------------------------

    def on_step_edge(self, step):
        """step.py seam: called once per completed step with the step
        count. May deliver SIGTERM (rule ``sigterm``) — graceful, the
        preemption listener defers it — or SIGKILL (rule ``kill``) — the
        hard death the failure detector must notice on its own."""
        if not os.environ.get(CHAOS_ENV):
            return
        for r in self._sync():
            if (
                r.fault in ("sigterm", "kill")
                and not r.fired
                and r.rank_matches()
                and int(r.kv.get("step", -1)) == int(step)
            ):
                r.fired += 1
                record_chaos(r.fault, f"step={step}")
                signum = (
                    signal.SIGKILL if r.fault == "kill" else signal.SIGTERM
                )
                logger.warning(
                    "chaos: delivering %s to pid %d at step %s",
                    signum.name, os.getpid(), step,
                )
                os.kill(os.getpid(), signum)

    def on_step_dispatch(self, step):
        """step.py seam: called as step ``step``'s dispatch begins (before
        the compiled program runs). May hang this rank for ``ms``
        milliseconds (rule ``wedge``): its heartbeat thread keeps beating
        but the reported step edge stalls — the peers' detectors must
        classify it wedged, not dead."""
        if not os.environ.get(CHAOS_ENV):
            return
        for r in self._sync():
            if (
                r.fault == "wedge"
                and not r.fired
                and r.rank_matches()
                and int(r.kv.get("step", -1)) == int(step)
            ):
                r.fired += 1
                ms = float(r.kv.get("ms", 0))
                record_chaos("wedge", f"step={step} ms={ms:g}")
                logger.warning(
                    "chaos: wedging pid %d inside step %s dispatch for "
                    "%gms", os.getpid(), step, ms,
                )
                if ms > 0:
                    from smdistributed_modelparallel_tpu.utils.goodput import (
                        goodput,
                    )

                    # The injected stall is exactly what the ledger's
                    # `wedged` state models — attribute it there so the
                    # chaos smoke can assert the badput breakdown.
                    with goodput.scope("wedged"):
                        time.sleep(ms / 1000.0)

    def on_serve_decode(self, progress):
        """serving/engine.py seam: called once per decode-step boundary.
        ``progress(n)`` reports ``(tokens_emitted, finished)`` for the
        engine's n-th admitted request, or None when fewer than n were
        admitted. Rule ``kill_replica@request=N`` SIGKILLs this process
        the first time request N is mid-decode (>= 1 token, unfinished)
        — the hard replica death the serving failover must absorb."""
        if not os.environ.get(CHAOS_ENV):
            return
        for r in self._sync():
            if r.fault != "kill_replica" or r.fired or not r.rank_matches():
                continue
            n = int(r.kv.get("request", -1))
            got = progress(n) if n >= 1 else None
            if got is None:
                continue
            tokens, finished = got
            if finished or tokens < 1:
                continue
            r.fired += 1
            record_chaos("kill_replica", f"request={n} tokens={tokens}")
            logger.warning(
                "chaos: SIGKILL of serving replica pid %d with request "
                "#%d mid-decode (%d tokens emitted)",
                os.getpid(), n, tokens,
            )
            os.kill(os.getpid(), signal.SIGKILL)

    def on_scale_event(self, n):
        """serving/controller.py seam: called once after the controller's
        ``n``-th completed autoscale event (1-based). Rule
        ``kill_replica@scale=K`` SIGKILLs this process right at that
        edge — the moment replica bookkeeping (routing table, mirror
        shadows, standby handshakes) is most fragile."""
        if not os.environ.get(CHAOS_ENV):
            return
        for r in self._sync():
            if r.fault != "kill_replica" or r.fired or not r.rank_matches():
                continue
            k = int(r.kv.get("scale", -1))
            if k < 1 or k != int(n):
                continue
            r.fired += 1
            record_chaos("kill_replica", f"scale={k}")
            logger.warning(
                "chaos: SIGKILL of pid %d after autoscale event #%d",
                os.getpid(), k,
            )
            os.kill(os.getpid(), signal.SIGKILL)

    def on_weight_update(self, version, params):
        """serving/engine.py seam: called with the parameter tree a
        replica is about to adopt as weights version ``version``. Rule
        ``corrupt_weights@version=N`` returns a perturbed copy (every
        float leaf mapped to ``x * 1.01 + 0.01``) — silently wrong
        weights the canary's token-parity gate must catch. Returns
        ``params`` untouched otherwise."""
        if not os.environ.get(CHAOS_ENV):
            return params
        for r in self._sync():
            if (
                r.fault != "corrupt_weights"
                or r.fired
                or not r.rank_matches()
                or int(r.kv.get("version", -1)) != int(version)
            ):
                continue
            r.fired += 1
            record_chaos("corrupt_weights", f"version={version}")
            logger.warning(
                "chaos: corrupting weights version %s (float leaves "
                "-> x*1.01 + 0.01)", version,
            )
            import jax  # lazy: chaos must import without a backend

            def _perturb(x):
                if hasattr(x, "dtype") and "float" in str(x.dtype):
                    return x * 1.01 + 0.01
                return x

            return jax.tree_util.tree_map(_perturb, params)
        return params

    def on_heartbeat(self, dest):
        """supervisor.py seam: called once per outgoing heartbeat. Returns
        True to silently drop the beat (rule ``heartbeat_drop``; ``count``
        beats, counted per send, any destination). Deliberately separate
        from ``on_bus_send``: beats must not consume the deterministic
        bus-send ordinals that ``bus_drop``/``bus_error`` rules target."""
        if not os.environ.get(CHAOS_ENV):
            return False
        for r in self._sync():
            if r.fault != "heartbeat_drop" or not r.rank_matches():
                continue
            count = int(r.kv.get("count", 1) or 1)
            if r.fired >= count:
                continue
            r.fired += 1
            record_chaos("heartbeat_drop", f"dest={dest} n={r.fired}/{count}")
            return True
        return False

    def on_bus_send(self, dest):
        """native.py seam: called once per bus send (consumes one send
        ordinal). Returns ``"drop"`` (silently discard the payload),
        ``"error"`` (force the enqueue to fail) or None (send normally)."""
        if not os.environ.get(CHAOS_ENV):
            return None
        rules = self._sync()
        ordinal = self._bus_send_ordinal
        self._bus_send_ordinal += 1
        for r in rules:
            if r.fault not in ("bus_drop", "bus_error") or r.fired:
                continue
            if not r.rank_matches():
                continue
            if int(r.kv.get("seq", -1)) != ordinal:
                continue
            if "dest" in r.kv and int(r.kv["dest"]) != int(dest):
                continue
            r.fired += 1
            record_chaos(r.fault, f"dest={dest} seq={ordinal}")
            logger.warning(
                "chaos: %s of bus send #%d to process %d",
                r.fault, ordinal, dest,
            )
            return "drop" if r.fault == "bus_drop" else "error"
        return None

    def on_collective(self, op, group_name):
        """collectives.py seam: called before a host collective executes.
        May sleep (rule ``delay_collective``) to manufacture a straggler."""
        if not os.environ.get(CHAOS_ENV):
            return
        for r in self._sync():
            if r.fault != "delay_collective" or not r.rank_matches():
                continue
            count = int(r.kv.get("count", 0) or 0)
            if count and r.fired >= count:
                continue
            g = r.kv.get("group")
            if g and not str(group_name).lower().startswith(g.lower()):
                continue
            ms = float(r.kv.get("ms", 0))
            if ms <= 0:
                continue
            r.fired += 1
            record_chaos("delay_collective", f"op={op} group={group_name}")
            time.sleep(ms / 1000.0)

    def reset(self):
        """Testing hook: forget the cached spec, counters and ordinals."""
        self._spec = ""
        self._rules = []
        self._bus_send_ordinal = 0


chaos = ChaosInjector()
