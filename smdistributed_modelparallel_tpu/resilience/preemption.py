"""Preemption-aware emergency checkpointing.

TPU pods get maintenance events and spot preemptions as a matter of
course: the platform delivers SIGTERM (or touches a sentinel file) and
kills the host some grace period later. The reference library has no story
for this — a preempted run loses everything since its last scheduled
checkpoint. This module turns the grace period into a *coordinated
emergency partial checkpoint*:

1. **Listen**: ``install()`` (run at ``smp.init``) chains a SIGTERM
   handler and notes the ``SMP_PREEMPTION_FILE`` sentinel path. Either
   trigger flips a process-local flag; nothing else happens in signal
   context (async-signal-safe by construction: set a bool, record a
   timestamp).
2. **Detect at the step edge**: the step engine calls
   ``maybe_emergency_save()`` after every completed step — a flag test
   plus (when configured) one ``os.stat`` of the sentinel. A rank whose
   flag flipped also posts a best-effort preempt notice to every peer on
   the native bus (reserved tx ``-2``, next to the exit-status relay's
   ``-1``) so a *single-rank* SIGTERM still converges: peers poll the
   notice at their own step edges.
3. **Rendezvous + save**: all ranks drain pending async saves, meet at a
   grace-bounded HOST-bus barrier (never a device collective — a peer
   still blocked inside a step's jit cannot join one, and a device sync
   is uninterruptible; a bus barrier it never joins just times out and
   the save degrades to an uncoordinated best-effort one), and agree on
   the save edge — the MAXIMUM step edge across ranks. A rank whose trigger fired
   at an earlier edge than its slowest-to-know peer (the single-rank
   SIGTERM whose bus notice lands after the peer's same-numbered edge
   passed) would otherwise contribute shards from a different
   optimization step; instead it defers, keeps training to the agreed
   edge, and writes there. Every rank then writes one blocking partial
   checkpoint through the normal ``save_checkpoint`` machinery — the
   single-commit protocol already guarantees ``newest`` moves only after
   every rank's shards are on disk. The commit wait is bounded by
   ``SMP_PREEMPTION_GRACE_SECONDS`` (default 60): better a missing
   ``newest`` than a torn pointer published as the platform's axe falls.
4. **Exit**: by default the process then exits 0 (the SIGTERM was
   honored, on our schedule). Training loops that want to keep running
   (tests, custom supervisors) set ``preemption.exit_after_save = False``.
   A SECOND SIGTERM while the first is still deferred restores the
   previous disposition and re-raises — an insisting sender (impatient
   platform, operator double-kill) terminates the process instead of
   being silently swallowed; ``smp.shutdown`` likewise uninstalls the
   handler so a finished run dies normally on TERM.

Resuming is plain ``smp.resume_from_checkpoint(<SMP_EMERGENCY_CKPT_PATH>)``
— elastic by default, so the restarted job may come back on a *different*
topology (see ``resilience/elastic.py``).
"""

import os
import signal
import sys
import threading
import time

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import record_preemption

logger = get_logger()

PREEMPTION_FILE_ENV = "SMP_PREEMPTION_FILE"
GRACE_ENV = "SMP_PREEMPTION_GRACE_SECONDS"
EMERGENCY_PATH_ENV = "SMP_EMERGENCY_CKPT_PATH"
DEFAULT_EMERGENCY_PATH = "smp_emergency_ckpt"

# Reserved bus transaction ids for the preemption protocol. Control txs
# live at -1..-33: non-negative ids are the P2P streams (user odd,
# framework even), the exit-status relay owns -1 (backend/core.py), and
# barrier ids start below -33 (the +16 namespace offset in
# message_bus.cc's Barrier keeps them clear of this range).
PREEMPT_NOTICE_TX = -2
STEP_EXCHANGE_TX = -3


def grace_seconds():
    try:
        return float(os.environ.get(GRACE_ENV, "60") or 60)
    except ValueError:
        return 60.0


class PreemptionListener:
    """Process-local preemption state + the emergency-save driver."""

    def __init__(self):
        self._requested = None        # reason string once triggered
        self._requested_at = None     # time.monotonic() of the trigger
        self._prev_sigterm = None
        self._installed = False
        self._sigterm_seen = False
        self._notified_peers = False
        self._saving = False
        self._save_at_step = None     # deferred-save target edge (skew)
        self._deferred = None         # (path, tag, reason) while deferred
        self.emergency_saved = None   # (path, tag) after a successful save
        self.exit_after_save = True
        self._lock = threading.Lock()

    # -- trigger sources ------------------------------------------------

    def install(self):
        """Chain the SIGTERM handler. Idempotent; only possible from the
        main thread (signal module restriction) — elsewhere the sentinel
        file / peer notice remain as triggers."""
        if self._installed:
            return True
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:
            logger.warning(
                "preemption listener: not on the main thread; SIGTERM "
                "handling disabled (sentinel-file polling still active)."
            )
            return False
        self._installed = True
        return True

    def uninstall(self):
        if not self._installed:
            return
        try:
            signal.signal(signal.SIGTERM, self._prev_sigterm or signal.SIG_DFL)
        except (ValueError, TypeError):
            pass
        self._installed = False
        self._prev_sigterm = None

    def _on_sigterm(self, signum, frame):
        # Signal context: set state only. The actual work happens at the
        # next step edge, outside signal context.
        if self._sigterm_seen:
            # Second SIGTERM while the first is still deferred: the sender
            # is insisting (impatient platform, operator double-kill).
            # Restore the previous disposition and re-raise so the process
            # actually dies — deferral must not turn into swallowing every
            # TERM a hung process will ever receive.
            self._installed = False
            try:
                signal.signal(
                    signal.SIGTERM, self._prev_sigterm or signal.SIG_DFL
                )
            except (ValueError, TypeError):
                return
            os.kill(os.getpid(), signal.SIGTERM)
            return
        self._sigterm_seen = True
        if self._requested is None:
            self._requested = "sigterm"
            self._requested_at = time.monotonic()
        prev = self._prev_sigterm
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    def trigger(self, reason="api"):
        """Programmatic trigger (platform integrations, tests)."""
        if self._requested is None:
            self._requested = reason
            self._requested_at = time.monotonic()

    def _sentinel_path(self):
        return os.environ.get(PREEMPTION_FILE_ENV) or None

    @staticmethod
    def _peer_bus():
        """The live multi-process native bus, or None. Never raises — the
        preemption paths are all best-effort against a dead bus."""
        try:
            from smdistributed_modelparallel_tpu.backend.state import state

            comm = state._comm
            bus = comm._bus if comm is not None else None
            if bus is None or bus.world <= 1:
                return None
            return bus
        except Exception:
            return None

    def _poll_peers(self):
        """Best-effort: has any peer posted a preempt notice? Never raises
        and never blocks — a dead bus must not take the step loop down."""
        bus = self._peer_bus()
        if bus is None:
            return False
        try:
            for peer in range(bus.world):
                if peer != bus.rank and bus.poll(peer, PREEMPT_NOTICE_TX):
                    # CONSUME the frame: a notice left in the inbox would
                    # re-trigger a fresh preemption after reset() in the
                    # continue-without-exit flow (supervisors, tests).
                    try:
                        bus.recv_bytes(peer, PREEMPT_NOTICE_TX, timeout_ms=0)
                    except Exception:
                        pass
                    return True
        except Exception:
            return False
        return False

    def check(self):
        """Current preemption reason, or None. Cheap enough for a per-step
        call: a flag test, one optional stat, and (multi-process, bus up)
        one local poll per peer."""
        if self._requested is not None:
            return self._requested
        sentinel = self._sentinel_path()
        if sentinel and os.path.exists(sentinel):
            self._requested = "sentinel_file"
            self._requested_at = time.monotonic()
            return self._requested
        if self._poll_peers():
            self._requested = "peer_notice"
            self._requested_at = time.monotonic()
            return self._requested
        return None

    @property
    def requested(self):
        return self.check() is not None

    # -- cross-rank propagation -----------------------------------------

    def _notify_peers(self):
        """Post the preempt notice to every peer (reserved tx; one shot).
        Best-effort: peers discovering the preemption via their own signal
        or the sentinel file don't need it."""
        if self._notified_peers:
            return
        self._notified_peers = True
        bus = self._peer_bus()
        if bus is None:
            return
        for peer in range(bus.world):
            if peer == bus.rank:
                continue
            # Per-peer isolation: one dead peer (SMPPeerLost after the
            # retry budget) must not abort notification of the others —
            # they still need to reach the rendezvous.
            try:
                bus.send_bytes(peer, b"preempt", PREEMPT_NOTICE_TX)
            except Exception as e:
                logger.warning(
                    "preempt notice to process %d failed: %s", peer, e
                )

    # -- the emergency save ---------------------------------------------

    def _world_size(self):
        from smdistributed_modelparallel_tpu.backend.state import state

        if not state.initialized:
            return 1
        import jax

        return jax.process_count()

    def _remaining_grace(self):
        """Seconds left of the platform's grace budget, floored at 5s so
        even a late discovery gets one real attempt at each bounded wait."""
        grace = grace_seconds()
        elapsed = (
            time.monotonic() - self._requested_at
            if self._requested_at is not None else 0.0
        )
        return max(5.0, grace - elapsed)

    def _bus_rendezvous(self, deadline_s):
        """Meet every process at a step edge over the host bus (bounded by
        ``deadline_s``) and exchange step edges. Returns the per-process
        step-count list, or None when the rendezvous could not complete —
        a peer wedged mid-step never arrives at the bus barrier, the
        barrier times out, and the caller degrades to an uncoordinated
        save instead of hanging past the platform's deadline."""
        from smdistributed_modelparallel_tpu.backend.state import state

        bus = self._peer_bus()
        if bus is None:
            return None
        timeout_ms = max(int(deadline_s * 1000), 1000)
        step = state.step_count
        try:
            bus.barrier(list(range(bus.world)), timeout_ms=timeout_ms)
            # All ranks are now at a step edge: exchange the edges. (The
            # post-barrier recv is effectively instant — every peer sends
            # right after leaving the same barrier.)
            payload = str(step).encode()
            steps = [step] * bus.world
            for peer in range(bus.world):
                if peer != bus.rank:
                    bus.send_bytes(peer, payload, STEP_EXCHANGE_TX)
            for peer in range(bus.world):
                if peer != bus.rank:
                    steps[peer] = int(
                        bus.recv_bytes(
                            peer, STEP_EXCHANGE_TX, timeout_ms=timeout_ms
                        )
                    )
            return steps
        except Exception as e:
            logger.error("preemption bus rendezvous failed: %s", e)
            return None

    def maybe_emergency_save(self):
        """Step-engine edge hook: no-op until a preemption trigger fires,
        then runs the coordinated emergency save exactly once. Returns the
        (path, tag) of the committed checkpoint, or None (including while
        a skewed rendezvous is converging on its agreed save edge)."""
        if self._save_at_step is not None:
            from smdistributed_modelparallel_tpu.backend.state import state

            if state.step_count < self._save_at_step:
                return None
            return self._deferred_save()
        reason = self.check()
        if reason is None or self._saving or self.emergency_saved:
            return None
        return self.emergency_save(reason=reason)

    def emergency_save(self, path=None, tag=None, reason="api"):
        """Drain, rendezvous on a common save edge, and write one blocking
        partial checkpoint; then (by default) exit the process cleanly."""
        # NOTE: smp.checkpoint (the remat API) shadows the checkpoint
        # MODULE as a package attribute — import the functions directly.
        from smdistributed_modelparallel_tpu.checkpoint import (
            wait_for_checkpoints,
        )
        from smdistributed_modelparallel_tpu.backend.state import state

        with self._lock:
            if self._saving or self.emergency_saved:
                return self.emergency_saved
            self._saving = True
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        grace = grace_seconds()
        path = path or os.environ.get(EMERGENCY_PATH_ENV) or DEFAULT_EMERGENCY_PATH
        record_preemption("requested", step=state.step_count, detail=reason)
        logger.warning(
            "PREEMPTION (%s): writing emergency checkpoint under %s "
            "(grace %.0fs).", reason, path, grace,
        )
        self._notify_peers()
        try:
            # Everything from the trigger to the committed emergency
            # checkpoint is preemption drain in the goodput ledger (the
            # blocking shard write inside nests as ckpt_save).
            drain_scope = goodput.scope("preempt_drain")
            drain_scope.__enter__()
            # In-flight async saves first: they hold the single saver
            # thread, and their shards may be half-written — the emergency
            # save must not interleave with them.
            try:
                wait_for_checkpoints()
            except Exception as e:
                logger.error("pending async save failed pre-preemption: %s", e)
            # Rendezvous: every rank reaches a step edge before anyone
            # writes — the emergency checkpoint must be ONE consistent
            # step, not a mix of step N and N+1 trees. The rendezvous runs
            # over the native HOST bus with a grace-bounded timeout, never
            # the device collectives (sync_global_devices /
            # process_allgather): a peer still blocked INSIDE a step's jit
            # cannot join a device collective, and a device sync is not
            # interruptible from Python — the triggered rank would hang
            # past the platform's deadline with nothing on disk. A bus
            # rendezvous a wedged peer never joins just times out, and the
            # save degrades to an uncoordinated best-effort one.
            record_preemption("rendezvous", step=state.step_count)
            if self._world_size() > 1:
                steps = self._bus_rendezvous(self._remaining_grace())
                if steps is None:
                    record_preemption(
                        "rendezvous_degraded", step=state.step_count
                    )
                    logger.error(
                        "Preemption rendezvous failed (peer wedged mid-step "
                        "or bus down); writing this rank's emergency shards "
                        "uncoordinated. `newest` still only moves if every "
                        "rank's shards land within the commit wait."
                    )
                elif state.step_count < max(steps):
                    # A rank preempted alone may reach this edge BEHIND
                    # peers that discovered the trigger one step edge later
                    # (the bus notice landed after their same-numbered edge
                    # had already passed). Mixed-step shards would resume
                    # cleanly and be silently WRONG, so the ranks agree on
                    # the MAXIMUM edge: anyone behind it defers — keeps
                    # training, writes its shards when its own edge reaches
                    # the target. The commit (`newest`) waits for every
                    # rank's shards either way.
                    target = max(steps)
                    self._save_at_step = target
                    self._deferred = (path, tag, reason)
                    record_preemption(
                        "deferred", step=state.step_count,
                        detail=f"target={target}",
                    )
                    logger.warning(
                        "Preemption rendezvous: ranks sit at different step "
                        "edges (%s); deferring this rank's emergency shards "
                        "from edge %d to the agreed edge %d.",
                        steps, state.step_count, target,
                    )
                    return None
            tag = tag or f"preempt_step_{state.step_count}"
            return self._write_emergency_checkpoint(path, tag, reason)
        except Exception as e:
            record_preemption("failed", step=state.step_count, detail=str(e))
            logger.error("emergency checkpoint failed: %s", e)
            raise
        finally:
            drain_scope.__exit__(None, None, None)
            self._saving = False

    def _deferred_save(self):
        """Second half of a skewed rendezvous: this rank has now trained to
        the agreed edge; write its shards (the peers that were already
        there wrote theirs and are blocked in the commit wait)."""
        from smdistributed_modelparallel_tpu.backend.state import state

        with self._lock:
            if self._saving or self.emergency_saved:
                return self.emergency_saved
            self._saving = True
        path, tag, reason = self._deferred
        try:
            tag = tag or f"preempt_step_{state.step_count}"
            return self._write_emergency_checkpoint(path, tag, reason)
        except Exception as e:
            record_preemption("failed", step=state.step_count, detail=str(e))
            logger.error("emergency checkpoint failed: %s", e)
            raise
        finally:
            self._saving = False
            self._save_at_step = None
            self._deferred = None

    def _write_emergency_checkpoint(self, path, tag, reason):
        from smdistributed_modelparallel_tpu.checkpoint import save_checkpoint
        from smdistributed_modelparallel_tpu.backend.state import state

        # Bound the commit wait by the REMAINING grace budget (the drain
        # and rendezvous already spent part of it since the trigger): a
        # peer that dies mid-save must not wedge the survivors past the
        # platform's deadline (they'd be killed without even a partial
        # dir). Floor of 5s: a late discovery still gets one real commit
        # attempt.
        remaining = self._remaining_grace()
        prev_timeout = os.environ.get("SMP_CKPT_COMMIT_TIMEOUT")
        os.environ["SMP_CKPT_COMMIT_TIMEOUT"] = str(remaining)
        try:
            save_checkpoint(
                path, tag=tag, partial=True, blocking=True,
                user_content={
                    "preemption_reason": reason,
                    "step_count": state.step_count,
                },
            )
        finally:
            if prev_timeout is None:
                os.environ.pop("SMP_CKPT_COMMIT_TIMEOUT", None)
            else:
                os.environ["SMP_CKPT_COMMIT_TIMEOUT"] = prev_timeout
        # Non-committer ranks return from save_checkpoint as soon as their
        # own shards (and .done marker) are on disk; hold them here until
        # process 0 publishes .committed (or the grace budget runs out) so
        # no rank tears down its runtime while a deferred peer still needs
        # the world to finish training to the agreed edge, and so exit
        # order never races the commit.
        self._await_commit(path, tag)
        self.emergency_saved = (path, tag)
        record_preemption("saved", step=state.step_count, detail=tag)
        logger.warning(
            "Emergency checkpoint '%s' committed under %s.", tag, path
        )
        self._drain_peer_notices()
        if self.exit_after_save:
            logger.warning("Exiting after emergency checkpoint (preemption).")
            sys.exit(0)
        return self.emergency_saved

    def _await_commit(self, path, tag):
        """Block a non-committer rank until ``.committed`` lands (bounded
        by the remaining grace). Process 0 publishes the marker itself; a
        single-process world is its own committer."""
        from smdistributed_modelparallel_tpu.checkpoint import _process_index

        if self._world_size() <= 1 or _process_index() == 0:
            return
        marker = os.path.join(path, f"{tag}_partial", ".committed")
        deadline = time.monotonic() + self._remaining_grace()
        while not os.path.exists(marker):
            if time.monotonic() > deadline:
                logger.error(
                    "emergency checkpoint '%s': commit marker did not land "
                    "within the grace budget; exiting without it (the "
                    "platform's deadline is imminent).", tag,
                )
                return
            time.sleep(0.05)

    def _drain_peer_notices(self):
        """Best-effort: consume preemption-protocol frames still queued
        from peers — each rank posts a notice to EVERYONE, so a rank that
        triggered on its own signal has its peers' echoes sitting unread
        in its inbox, and the continue-without-exit flow (supervisors,
        tests) would re-trigger on the stale frame right after
        ``reset()``. Step-exchange frames from an aborted rendezvous are
        drained too so a later rendezvous never reads a stale edge.
        Frames still in flight can slip past this; ``reset()`` drains
        again."""
        bus = self._peer_bus()
        if bus is None:
            return
        try:
            for peer in range(bus.world):
                if peer == bus.rank:
                    continue
                for tx in (PREEMPT_NOTICE_TX, STEP_EXCHANGE_TX):
                    while bus.poll(peer, tx):
                        try:
                            bus.recv_bytes(peer, tx, timeout_ms=0)
                        except Exception:
                            break
        except Exception:
            pass

    def reset(self):
        """Testing hook: clear triggers and save state (handler stays)."""
        self._requested = None
        self._requested_at = None
        self._sigterm_seen = False
        self._notified_peers = False
        self._saving = False
        self._save_at_step = None
        self._deferred = None
        self.emergency_saved = None
        self.exit_after_save = True
        self._drain_peer_notices()


preemption = PreemptionListener()
