"""Elastic topology reshard-on-resume.

The reference library welds a partial checkpoint to the exact degree
layout it was saved under: ``verify_smp_config`` hard-fails on any
mismatch (reference ``torch/checkpoint.py:381+,487+``), because its
per-rank files hold rank-local *tensor fragments* whose meaning depends on
the saved (pp, tp, rdp) assignment. Under this framework's SPMD design
that weld is unnecessary — GSPMD-style sharding is an *annotation*, not a
data layout:

- parameter/optimizer trees have topology-invariant structure and logical
  shapes (pipeline stages shard the stacked layer axis over ``pp``; TP
  shards inner dims; ZeRO adds an ``rdp`` axis — all PartitionSpecs over
  the same logical arrays, see ``parallel/zero.py``);
- shard checkpoint files (``shard_io.py``) key every piece by logical
  path + **global element bounds**, not by rank coordinates.

So a checkpoint saved under (pp=2, tp=1) is, byte-for-byte, a catalog of
logical array regions — and resuming under (pp=1, tp=2) (or plain dp, or a
different world size) is exactly the existing
``ShardCatalog.load_tree``: each resuming process assembles the pieces
overlapping *its* addressable shards under the *new* mesh's shardings.

This module supplies the policy layer ``resume_from_checkpoint`` uses to
downgrade the reference's fatal mismatch into that reshard: classify the
mismatches, verify the checkpoint format can reshard, log/record the
transition. Genuine incompatibilities still fail loudly — at assembly
time, with the missing key/region named — rather than silently loading
garbage.
"""

from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_elastic_resume,
)

logger = get_logger()

# Degree/layout keys: a mismatch here changes WHERE state lives (which is
# exactly what the reshard path re-derives from the new topology).
LAYOUT_KEYS = (
    "pipeline_parallel_degree",
    "tensor_parallel_degree",
    "sharded_data_parallel_degree",
    "sharded_params",
    "shard_optimizer_state",
)

# Soft keys: verified by the reference because its runtime couples them to
# the saved partition; here they affect neither tree structure nor logical
# shapes, so a mismatch is informational.
SOFT_KEYS = (
    "microbatches",
    "optimize",
    "prescaled_batch",
    # Writer census (checkpoint.py snapshot, not a user config key): a
    # different world size is the NORMAL elastic case, and the count's
    # real consumer is the shard-file completeness check, not layout.
    "num_processes",
    # Step-edge stamp (checkpoint.py snapshot): consumed by the recovery
    # supervisor to restart the step engine; never layout-relevant.
    "step_count",
)


def classify_mismatches(saved, current):
    """Split saved-vs-current config mismatches into (layout, soft, other)
    dicts of ``key -> (saved_value, current_value)``."""
    layout, soft, other = {}, {}, {}
    keys = set(saved) | set(current)
    for k in keys:
        if k not in saved or k not in current:
            continue
        if saved[k] == current[k]:
            continue
        entry = (saved[k], current[k])
        if k in LAYOUT_KEYS:
            layout[k] = entry
        elif k in SOFT_KEYS:
            soft[k] = entry
        else:
            other[k] = entry
    return layout, soft, other


def begin_elastic_resume(saved_cfg, current_cfg, shard_format, what=""):
    """Authorize a topology-mismatched resume.

    Called by ``resume_from_checkpoint`` when ``verify_smp_config`` would
    have raised. Validates that the checkpoint format supports resharding
    (per-leaf shard catalogs, or a full gathered state dict — both are
    logical-layout representations), then logs and records the transition.
    Raises ``SMPValidationError`` only when the format genuinely cannot
    reshard (the legacy rank-coordinate pickle layout).
    """
    from smdistributed_modelparallel_tpu.parallel.zero import (
        describe_state_layout,
    )
    from smdistributed_modelparallel_tpu.utils.exceptions import (
        SMPValidationError,
    )

    layout, soft, other = classify_mismatches(saved_cfg, current_cfg)
    if not shard_format:
        raise SMPValidationError(
            "Elastic resume needs a reshardable checkpoint format (per-leaf "
            "shard catalogs or a full gathered state dict); this checkpoint "
            "uses the legacy per-rank pickle layout, whose fragments are "
            f"welded to the saved topology. Mismatches: {dict(layout, **soft)}"
        )
    saved_layout = describe_state_layout(saved_cfg)
    live_layout = describe_state_layout(current_cfg)
    detail = f"layout={layout} soft={soft}"
    logger.warning(
        "ELASTIC RESUME %s: checkpoint topology differs from the live "
        "config — resharding per-leaf from logical bounds. Degree/layout "
        "mismatches: %s; soft mismatches: %s; optimizer-state layout: "
        "%s -> %s.",
        what, layout or "{}", soft or "{}", saved_layout, live_layout,
    )
    if other:
        logger.warning(
            "elastic resume: non-topology config keys also differ (not "
            "verified, not resharded — make sure this is intended): %s",
            other,
        )
    from smdistributed_modelparallel_tpu.utils import exec_cache

    if layout and exec_cache.enabled():
        # Executable-cache interaction: entries are keyed by topology, so
        # a layout change can only warm-start from entries compiled at
        # the NEW layout (a previous recovery/resume at this world, or a
        # pre-warming run) — old-topology entries are simply not
        # candidates, never false hits.
        logger.info(
            "elastic resume: layout changed (%s); persistent executable "
            "cache will only serve entries compiled at the new topology.",
            sorted(layout),
        )
    record_elastic_resume(len(layout), len(soft), detail=detail)
    return layout, soft
