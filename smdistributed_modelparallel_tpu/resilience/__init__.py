"""smp.resilience — preemption checkpointing, elastic resume, chaos, and
in-job failure recovery.

Four cooperating pieces (each in its own module):

- ``preemption``: SIGTERM / ``SMP_PREEMPTION_FILE`` listener whose flag
  the step engine checks at every step edge; a trigger leads to a
  coordinated, committed emergency partial checkpoint within the
  ``SMP_PREEMPTION_GRACE_SECONDS`` budget (``preemption.py``).
- elastic reshard-on-resume: ``smp.resume_from_checkpoint`` loads a
  checkpoint saved under a *different* (pp, tp, rdp) layout by
  reassembling each leaf from logical shard bounds and re-slicing it per
  the resuming mesh (``elastic.py``; policy consumed by ``checkpoint.py``).
- ``supervisor``: the ``SMP_SUPERVISOR=on`` heartbeat failure detector
  (dead / wedged / preempted classification over the native bus) and the
  shrink-to-survivors recovery protocol — survivors rendezvous, agree on
  the newest committed checkpoint, re-initialize ``jax.distributed`` +
  mesh at the shrunken world, and resume in-job (``supervisor.py``).
- ``chaos``: the ``SMP_CHAOS`` deterministic fault injector (SIGTERM /
  SIGKILL at a step edge, an in-dispatch wedge, dropped heartbeats,
  dropped/failed bus sends, delayed collectives) that the resilience
  tests use to prove the recovery paths recover (``chaos.py``).
"""

from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.resilience.elastic import (
    classify_mismatches,
)
from smdistributed_modelparallel_tpu.resilience.preemption import preemption
from smdistributed_modelparallel_tpu.resilience.supervisor import supervisor


def reset():
    """Session-teardown hook (``state.reset`` / ``smp.shutdown``): clear
    preemption triggers and chaos rule state, stop the failure detector,
    and give SIGTERM back its previous disposition — ``smp.init`` installs
    the deferring handler, so a process that has shut the session down
    must die normally on TERM instead of flagging an edge no step loop
    will ever reach."""
    preemption.reset()
    preemption.uninstall()
    supervisor.reset()
    chaos.reset()
