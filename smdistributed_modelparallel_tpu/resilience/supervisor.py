"""In-job failure recovery: heartbeat failure detector + shrink-to-survivors
restart supervisor.

PR 4 made failures *survivable* (emergency checkpoints, elastic
reshard-on-resume); this module makes them *automatic*. Without it, a rank
that dies or wedges is only noticed when a peer's send fails
(``SMPPeerLost``) or a watchdog trips, and recovery means an external
scheduler restarting the whole world. With ``SMP_SUPERVISOR=on`` the job
detects, reforms, and keeps training on its own:

**Failure detector.** A daemon thread per process exchanges heartbeats
over the native message bus on reserved control tx ``-4`` (next to the
exit relay ``-1``, preempt notice ``-2``, step-edge exchange ``-3``) every
``SMP_HEARTBEAT_INTERVAL`` seconds. Each beat carries the sender's step
edge. Per-peer last-seen tracking classifies failures into three kinds:

- **dead** — the bus marked the link down in either direction (sender
  thread gave up / incoming stream hit EOF: ``smp_peer_down``), or the
  peer missed ``SMP_HEARTBEAT_MISS_BUDGET`` consecutive beats. A peer that
  resumes beating before recovery begins is un-marked (``flap_cleared``) —
  transient drops below the budget never classify at all.
- **wedged** — beats still arrive but the peer's reported step edge has
  not advanced for ``SMP_WEDGE_TIMEOUT`` seconds while OUR step edge moved
  past it (a globally-idle world wedges nobody; that is watchdog
  territory). Distinguishes "gone" from "stuck inside one dispatch".
- **preempted** — the peer posted the existing preemption notice (tx
  ``-2``): the preemption flow owns that path (coordinated emergency save,
  exit 0) and the supervisor only reports it.

Detections land in ``smp_failures_detected_total{kind=}`` and the flight
recorder (``supervisor`` events). Heartbeats are host-thread traffic only:
nothing runs inside the compiled step program (HLO fingerprints are
untouched), and ``SMP_SUPERVISOR=off`` (the default) starts no thread,
sends no bytes, and leaves the step path at a single attribute test.

**Recovery protocol** (``supervisor.recover()``, called by the training
loop when a step raises or the step-edge check throws ``SMPPeerLost``):

1. *Detect*: wait (bounded) for the detector to classify at least one
   failure; a caller-supplied ``SMPPeerLost`` is accepted as direct
   evidence.
2. *Rendezvous*: the presumed survivors meet at a grace-bounded host-bus
   barrier (the PR 4 seam — never a device collective) and exchange views:
   failed-set union, step edges, newest committed checkpoint, and — from
   the lowest survivor — the new coordinator endpoint. Two rounds bound
   the case where survivors disagree about who is alive.
3. *Agree*: the recovery checkpoint is the newest tag committed on EVERY
   survivor (normally identical — the single-commit protocol already
   guarantees all-ranks-or-nothing); evicted-but-alive peers (a wedge that
   outlived its timeout) get a best-effort eviction notice (tx ``-5``) so
   they exit (``SMPEvicted``) instead of training on as a split-brain
   singleton.
4. *Reform*: tear down the native bus and the jax distributed runtime,
   re-initialize both at the shrunken world (``jax.distributed`` + mesh +
   a config that fits the surviving device count), and
   ``resume_from_checkpoint(elastic=True)`` from the agreed checkpoint —
   in-job, exit-free. The step engine restarts from the checkpoint's step
   edge; the caller rebuilds its model/optimizer/step objects (the loaded
   state applies to them on their first step, exactly like a process
   restart would).

MTTR is observable end to end: ``smp_recoveries_total``,
``smp_recovery_seconds`` (detection -> first step trained in the new
world) and ``smp_recovery_phase_seconds{phase=detect|rendezvous|
reshard_load|first_step}``; ``scripts/resilience_probe.py --recovery``
joins the telemetry + flight-recorder dumps into a recovery report. Any
unrecoverable abort dumps the detector state and the flight-recorder ring
first.

**jax runtime caveat (important).** The stock ``jax.distributed
.initialize`` client TERMINATES the process when the coordination service
reports any task failure — the exact event this module exists to survive.
Supervised jobs must bring the runtime up through
``smp.supervisor.initialize_distributed(...)``, which configures the
coordination service/client with an effectively-infinite heartbeat budget
(this module's own detector replaces that machinery) and without
shutdown-on-destruction, so the old incarnation can be *abandoned* (leaked
— one client/service pair per recovery, never destroyed: live arrays keep
the old backend alive anyway, and destroying either object fires the
runtime's fatal error path) rather than torn down through a shutdown
barrier that dead peers can never join. Recovery of a world whose
COORDINATOR process died is not supported in-job (the survivors' grpc
channels fail closed): that case degrades to the PR 4 behavior — typed
errors, committed checkpoint, external restart.

Import-hygiene contract: stdlib + package modules only at import time; jax
is imported lazily inside functions.
"""

import json
import os
import socket
import threading
import time

from smdistributed_modelparallel_tpu.resilience.chaos import chaos
from smdistributed_modelparallel_tpu.resilience.preemption import (
    PREEMPT_NOTICE_TX,
)
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPEvicted,
    SMPPeerLost,
    SMPRecoveryError,
    SMPWatchdogTimeout,
)
from smdistributed_modelparallel_tpu.utils.flight_recorder import (
    flight_recorder,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import (
    record_failure_detected,
    record_recovery,
    watchdog,
)

logger = get_logger()

SUPERVISOR_ENV = "SMP_SUPERVISOR"
HEARTBEAT_INTERVAL_ENV = "SMP_HEARTBEAT_INTERVAL"
MISS_BUDGET_ENV = "SMP_HEARTBEAT_MISS_BUDGET"
WEDGE_TIMEOUT_ENV = "SMP_WEDGE_TIMEOUT"

# Reserved control txs (-1..-33 namespace; see resilience/preemption.py):
# exit relay -1, preempt notice -2, step-edge exchange -3.
HEARTBEAT_TX = -4
RECOVERY_TX = -5

# Failure kinds.
DEAD = "dead"
WEDGED = "wedged"
PREEMPTED = "preempted"


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s.",
                       name, os.environ.get(name), default)
        return float(default)


def supervisor_enabled():
    return os.environ.get(SUPERVISOR_ENV, "off").lower() in ("on", "1", "true")


def heartbeat_interval():
    return max(_env_float(HEARTBEAT_INTERVAL_ENV, 0.5), 0.01)


def miss_budget():
    return max(int(_env_float(MISS_BUDGET_ENV, 5)), 1)


def wedge_timeout():
    return max(_env_float(WEDGE_TIMEOUT_ENV, 60.0), 0.1)


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _PeerState:
    __slots__ = ("last_beat", "last_step", "last_advance", "kind",
                 "detected_at", "link_dead", "beats")

    def __init__(self):
        self.last_beat = None      # monotonic time of the last beat
        self.last_step = None      # peer's reported step edge
        self.last_advance = None   # monotonic time the edge last moved
        self.kind = None           # None=healthy, else DEAD/WEDGED/PREEMPTED
        self.detected_at = None
        self.link_dead = False
        self.beats = 0

    def snapshot(self):
        return {
            "kind": self.kind, "beats": self.beats,
            "last_beat": self.last_beat, "last_step": self.last_step,
            "last_advance": self.last_advance,
            "detected_at": self.detected_at, "link_dead": self.link_dead,
        }


class FailureDetector:
    """Heartbeat sender + per-peer classifier.

    One ``_tick`` per interval: send a beat to every peer (chaos seam:
    ``heartbeat_drop``), drain every peer's pending beats, classify.
    ``clock`` and manual ``_tick`` calls exist for the unit tests; the
    production path runs ``_tick`` on a daemon thread.
    """

    def __init__(self, bus, my_step, interval=None, budget=None,
                 wedge_s=None, clock=time.monotonic):
        self.bus = bus
        self.world = bus.world
        self.rank = bus.rank
        self.interval = heartbeat_interval() if interval is None else interval
        self.budget = miss_budget() if budget is None else budget
        self.wedge_s = wedge_timeout() if wedge_s is None else wedge_s
        self._my_step = my_step
        self._clock = clock
        self.peers = {
            p: _PeerState() for p in range(self.world) if p != self.rank
        }
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        self.recovering = False  # suspends flap-clearing mid-recovery
        # Peers currently carrying ANY classification (incl. preempted):
        # the step-edge hook short-circuits on this instead of walking
        # every peer per step (O(world) matters at pod scale).
        self.marked_count = 0

    # -- lifecycle ------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="smp-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self):
        """Stop the heartbeat thread and WAIT for it: the caller tears
        the native bus down next, and a straggling tick still inside a
        ctypes bus call would touch freed C state. Ticks check the stop
        event between bus operations, so the join normally returns in
        milliseconds; a thread that outlives the full wait is logged
        loudly (teardown proceeds — the alternative is hanging recovery
        forever)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
            if t.is_alive():
                t.join(timeout=25.0)
            if t.is_alive():
                logger.error(
                    "heartbeat detector thread failed to stop within 30s; "
                    "proceeding with teardown (native bus calls from the "
                    "straggler may crash)."
                )

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # pragma: no cover - must never die
                logger.warning("heartbeat detector tick failed: %s", e)
            self._stop.wait(self.interval)

    # -- one scan -------------------------------------------------------

    def _tick(self, now=None):
        now = self._clock() if now is None else now
        my_step = int(self._my_step())
        self._seq += 1
        payload = b"%d:%d" % (self._seq, my_step)
        for p, st in self.peers.items():
            if self._stop.is_set():
                # stop() is about to tear the bus down under us.
                return
            if not chaos.on_heartbeat(p):
                rc = self.bus.send_raw(p, payload, HEARTBEAT_TX)
                if rc == -2:
                    st.link_dead = True
            for raw in self.bus.drain_bytes(p, HEARTBEAT_TX):
                try:
                    _, _, step_s = raw.partition(b":")
                    step = int(step_s)
                except ValueError:
                    continue
                st.beats += 1
                st.last_beat = now
                st.link_dead = False  # a live frame is proof of life
                if st.last_step is None or step != st.last_step:
                    st.last_step = step
                    st.last_advance = now
            if self.bus.peer_down(p):
                st.link_dead = True
            self._classify(p, st, now, my_step)

    def _classify(self, p, st, now, my_step):
        if st.kind == PREEMPTED:
            return
        if st.kind is not None:
            # Flap suppression, part 2: a peer marked failed that shows
            # fresh life BEFORE recovery starts is un-marked (a marked
            # peer whose beats resume mid-recovery stays excluded — the
            # survivors already committed to a world without it; it gets
            # an eviction notice instead).
            if self.recovering:
                return
            revived = (
                st.kind == DEAD
                and not st.link_dead
                and st.last_beat is not None
                and st.detected_at is not None
                and st.last_beat > st.detected_at
            ) or (
                st.kind == WEDGED
                and st.last_advance is not None
                and st.detected_at is not None
                and st.last_advance > st.detected_at
            )
            if revived:
                logger.warning(
                    "failure detector: process %d revived (%s cleared).",
                    p, st.kind,
                )
                record_failure_detected("flap_cleared", p, detail=st.kind)
                st.kind = None
                st.detected_at = None
                self.marked_count = max(self.marked_count - 1, 0)
            return
        if self.bus.poll(p, PREEMPT_NOTICE_TX):
            # Frame deliberately left in the inbox: the preemption listener
            # consumes it at the next step edge and drives the coordinated
            # emergency save. The supervisor only classifies/reports.
            self._mark(p, st, now, PREEMPTED, "preempt notice pending")
            return
        if st.link_dead:
            self._mark(p, st, now, DEAD, "link marked down")
        elif (
            st.last_beat is not None
            and now - st.last_beat > self.interval * self.budget
        ):
            self._mark(
                p, st, now, DEAD,
                f"missed-beat budget exhausted "
                f"({now - st.last_beat:.2f}s > {self.budget}x"
                f"{self.interval:g}s)",
            )
        elif (
            st.last_beat is not None
            and st.last_advance is not None
            and st.last_step is not None
            and my_step > st.last_step
            and now - st.last_advance > self.wedge_s
        ):
            self._mark(
                p, st, now, WEDGED,
                f"step edge stuck at {st.last_step} for "
                f"{now - st.last_advance:.2f}s (> {self.wedge_s:g}s) while "
                f"this rank reached {my_step}",
            )

    def _mark(self, p, st, now, kind, why):
        st.kind = kind
        st.detected_at = now
        self.marked_count += 1
        logger.error(
            "failure detector: process %d classified %s (%s).", p, kind, why
        )
        record_failure_detected(kind, p, detail=why)

    # -- queries --------------------------------------------------------

    def failures(self, kinds=(DEAD, WEDGED)):
        return {p: st.kind for p, st in self.peers.items()
                if st.kind in kinds}

    def force_dead(self, p, why="caller evidence"):
        st = self.peers.get(p)
        if st is not None and st.kind is None:
            self._mark(p, st, self._clock(), DEAD, why)

    def snapshot(self):
        return {
            "rank": self.rank, "world": self.world,
            "interval": self.interval, "budget": self.budget,
            "wedge_timeout": self.wedge_s, "seq": self._seq,
            "peers": {p: st.snapshot() for p, st in self.peers.items()},
        }


class Supervisor:
    """Singleton driving detection + in-job shrink-to-survivors recovery."""

    def __init__(self):
        self.active = False          # step.py's one-attribute-test guard
        self.detector = None
        self._recovering = False
        self._await_first_step = None   # pending MTTR closure
        self._leaked = []               # abandoned jax client/service pairs
        self._owns_distributed = False
        self._recover_ckpt_path = None  # set per recover() call
        self.last_report = None

    # -- lifecycle (state.initialize / smp.shutdown) --------------------

    def start(self):
        """Arm the detector if ``SMP_SUPERVISOR=on``, the world is
        multi-process, and the native bus is up. Idempotent; re-arms on a
        re-initialized world. A disabled supervisor starts nothing and
        leaves ``active`` False — the step path stays at one attribute
        test and the bus carries zero heartbeat traffic."""
        if not supervisor_enabled():
            self._stop_detector()
            self.active = bool(self._await_first_step)
            return False
        from smdistributed_modelparallel_tpu.backend.state import state

        bus = None
        comm = state._comm
        if comm is not None:
            bus = comm._bus
        if bus is None or bus.world <= 1:
            self._stop_detector()
            # Still "active" for the step-edge seam: a pending recovery's
            # first-step closure (world may have shrunk to 1), and the
            # eviction check need the edge hook.
            self.active = True
            return False
        self._stop_detector()
        self.detector = FailureDetector(
            bus, my_step=lambda: _state().step_count
        )
        try:
            # Private jax surface, advisory only: if it moves in a jax
            # upgrade, skip the warning rather than break smp.init.
            from jax._src import distributed as jdist

            stock_client = (
                jdist.global_state.client is not None
                and not self._owns_distributed
            )
        except Exception:
            stock_client = False
        if stock_client:
            logger.warning(
                "SMP_SUPERVISOR=on but the jax distributed runtime was "
                "brought up by jax.distributed.initialize: its client "
                "TERMINATES the process when the coordinator reports a "
                "peer failure, which defeats in-job recovery. Use "
                "smp.supervisor.initialize_distributed(...) instead."
            )
        self.detector.start()
        self.active = True
        flight_recorder.record_supervisor(
            "armed", detail=f"world={bus.world} interval="
            f"{self.detector.interval:g}s budget={self.detector.budget}"
        )
        return True

    def stop(self):
        self._stop_detector()
        self.active = False

    def _stop_detector(self):
        d, self.detector = self.detector, None
        if d is not None:
            d.stop()

    def reset(self):
        """Session-teardown hook (resilience.reset)."""
        self.stop()
        self._recovering = False
        self._await_first_step = None
        self.last_report = None

    # -- step-edge seam (step.py; guarded by `.active`) -----------------

    def on_step_edge(self):
        """Called once per completed step when ``active``: closes a
        pending recovery's MTTR measurement, surfaces eviction notices,
        and turns a pending failure into a typed raise so the training
        loop never enters a doomed dispatch."""
        pending = self._await_first_step
        if pending is not None:
            now = time.monotonic()
            pending["phases"]["first_step"] = now - pending["t_resume_done"]
            # Split the first_step phase's compile cost by source: a
            # pre-warmed executable cache makes recovery's recompile a
            # deserialize (compile_from_cache), and the gauges prove the
            # availability win instead of assuming it.
            from smdistributed_modelparallel_tpu.utils import exec_cache

            mark = pending.pop("compile_mark", None)
            events = (
                exec_cache.compile_events_since(mark)
                if mark is not None else []
            )
            if events:
                pending["phases"]["compile_from_cache"] = sum(
                    e["seconds"] for e in events
                    if e["source"] == "disk_cache"
                )
                pending["phases"]["compile_fresh"] = sum(
                    e["seconds"] for e in events if e["source"] == "fresh"
                )
            mttr = now - pending["t_detect"]
            record_recovery(
                mttr, phases=pending["phases"],
                survivors=pending["survivors"],
            )
            logger.warning(
                "RECOVERY complete: first step trained %.2fs after "
                "detection (phases: %s).", mttr,
                {k: round(v, 3) for k, v in pending["phases"].items()},
            )
            self._await_first_step = None
            self.last_report = pending
            if self.detector is None:
                self.active = supervisor_enabled()
        if self.detector is None:
            return
        if not self.detector.marked_count:
            # Steady state: one integer test per edge. Eviction notices
            # can only await a rank the survivors classified failed — by
            # then THIS rank's links to them are down and marked.
            return
        self._check_evicted()
        failures = self.detector.failures()
        if failures and not self._recovering:
            peer, kind = next(iter(failures.items()))
            raise SMPPeerLost(
                peer,
                f"failure detector: process {peer} is {kind} (all: "
                f"{failures}); call smp.supervisor.recover() to reform "
                "the world from the survivors.",
            )

    def _check_evicted(self):
        bus = self.detector.bus if self.detector else None
        if bus is None:
            return
        for p in range(bus.world):
            if p == bus.rank:
                continue
            while bus.poll(p, RECOVERY_TX):
                try:
                    frame = json.loads(bus.recv_bytes(p, RECOVERY_TX, 0))
                except Exception:
                    break
                if frame.get("evict"):
                    flight_recorder.record_supervisor(
                        "evicted", peer=p,
                        detail=f"survivors={frame.get('survivors')}",
                    )
                    raise SMPEvicted(
                        f"process {bus.rank} was classified "
                        f"{frame.get('kind', 'failed')} and the survivors "
                        f"({frame.get('survivors')}) reformed the world "
                        "without it; exiting instead of training split-"
                        "brain."
                    )

    def failures(self):
        return dict(self.detector.failures()) if self.detector else {}

    # -- supervised jax.distributed bring-up ----------------------------

    def initialize_distributed(self, coordinator_address, num_processes,
                               process_id, init_timeout=120):
        """Bring up the jax distributed runtime for a supervised job: same
        wiring as ``jax.distributed.initialize`` but with the coordination
        service's own failure detection effectively disabled (the bus
        heartbeats replace it) and no shutdown-on-destruction, so a failed
        world can be abandoned without tripping the runtime's
        process-terminating error paths (see module docstring)."""
        from jax._src import distributed as jdist
        from jax._src.lib import xla_extension as xe

        st = jdist.global_state
        if st.client is not None:
            raise SMPRecoveryError(
                "jax distributed runtime is already initialized; "
                "supervised bring-up must happen before any other "
                "jax.distributed.initialize call."
            )
        if process_id == 0:
            bind = "[::]:" + coordinator_address.rsplit(":", 1)[1]
            st.service = xe.get_distributed_runtime_service(
                bind, num_processes,
                heartbeat_interval=10, max_missing_heartbeats=10_000_000,
            )
        st.client = xe.get_distributed_runtime_client(
            coordinator_address, process_id,
            init_timeout=int(init_timeout),
            heartbeat_interval=10, max_missing_heartbeats=10_000_000,
            shutdown_on_destruction=False, use_compression=True,
        )
        st.client.connect()
        st.coordinator_address = coordinator_address
        st.process_id = process_id
        st.num_processes = num_processes
        self._owns_distributed = True
        logger.info(
            "supervised jax distributed runtime up: %s (%d/%d).",
            coordinator_address, process_id, num_processes,
        )

    # -- recovery -------------------------------------------------------

    def recover(self, error=None, new_config=None, ckpt_path=None,
                grace=None):
        """Reform the world from the survivors and resume from the agreed
        committed checkpoint. Returns a report dict (survivors, agreed
        tag/step, phase durations). The caller rebuilds its model/
        optimizer/step objects afterwards — the resumed state applies to
        them on their first step. Raises ``SMPRecoveryError`` (after
        dumping detector state + the flight ring) when the world cannot
        be reformed; re-raises ``error`` when no peer failure exists."""
        from smdistributed_modelparallel_tpu.backend.collectives import (
            _collective_timeout,
        )

        if self.detector is None:
            if error is not None:
                raise error
            raise SMPRecoveryError(
                "supervisor.recover() called with no armed detector "
                "(SMP_SUPERVISOR=off, single-process world, or bus down)."
            )
        if self._recovering:
            raise SMPRecoveryError("recovery already in progress.")
        grace = grace if grace is not None else (
            _collective_timeout() or 60.0
        )
        t_enter = time.monotonic()
        self._recovering = True
        self.detector.recovering = True
        try:
            return self._recover(error, new_config, ckpt_path, grace,
                                 t_enter)
        except SMPRecoveryError as e:
            self._abort(str(e))
            raise
        except SMPEvicted:
            raise  # peers reformed without this rank: exit, don't wrap
        except Exception as e:
            if e is error:
                # No peer failure behind it: the caller's original error
                # goes back UNTOUCHED (no abort dump, no wrapper) — an
                # ordinary OOM/bug is not a recovery failure.
                raise
            self._abort(f"{type(e).__name__}: {e}")
            raise SMPRecoveryError(
                f"in-job recovery failed: {type(e).__name__}: {e}"
            ) from e
        finally:
            self._recovering = False
            # The detector survives a FAILED recovery attempt (success
            # stops it before the world re-init): re-enable flap-clearing
            # or a transiently-marked peer could never be un-marked and
            # every later step edge would re-raise forever.
            if self.detector is not None:
                self.detector.recovering = False

    def _recover(self, error, new_config, ckpt_path, grace, t_enter):
        from smdistributed_modelparallel_tpu.backend.state import state
        from smdistributed_modelparallel_tpu.resilience.preemption import (
            EMERGENCY_PATH_ENV,
        )

        detector = self.detector
        bus = detector.bus
        old_rank, old_world = bus.rank, bus.world
        ckpt_path = ckpt_path or os.environ.get(EMERGENCY_PATH_ENV)
        if not ckpt_path:
            raise SMPRecoveryError(
                "recovery needs a checkpoint root: pass "
                "recover(ckpt_path=...) or set SMP_EMERGENCY_CKPT_PATH."
            )
        self._recover_ckpt_path = ckpt_path
        flight_recorder.record_supervisor(
            "recover_begin", detail=f"world={old_world} error="
            f"{type(error).__name__ if error else None}"
        )
        from smdistributed_modelparallel_tpu.utils.goodput import goodput

        # Phase 1: detection. Bounded wait for a classification; a typed
        # SMPPeerLost from the caller is direct evidence.
        with goodput.scope("recovery_detect"):
            failures = self._await_detection(detector, error)
        if not failures:
            if error is not None:
                raise error
            raise SMPRecoveryError("no peer failure detected or supplied.")
        now = time.monotonic()
        detect_s = max(
            (now - (detector.peers[p].detected_at or now))
            for p in failures
        )
        t_detect = now - detect_s
        logger.error(
            "RECOVERY: failures %s at world=%d; reforming from the "
            "survivors.", failures, old_world,
        )
        # Phase 2: survivor rendezvous over the (still-live) old bus.
        t0 = time.monotonic()
        with goodput.scope("recovery_rendezvous"):
            survivors = sorted(
                p for p in range(old_world) if p not in failures
            )
            survivors, infos = self._rendezvous(
                bus, survivors, failures, grace
            )
            tag, step = self._agree_checkpoint(infos, survivors)
            coord = next(
                (i.get("coord") for i in infos.values() if i.get("coord")),
                None,
            )
            self._notify_evicted(bus, failures, survivors)
        rendezvous_s = time.monotonic() - t0
        flight_recorder.record_supervisor(
            "rendezvous_ok",
            detail=f"survivors={survivors} tag={tag} step={step}",
        )
        # Phase 3: tear down the failed world, re-initialize at the
        # shrunken one, resume from the agreed checkpoint.
        t0 = time.monotonic()
        with goodput.scope("recovery_reshard_load"):
            self._stop_detector()
            self._teardown_world(state)
            if old_rank not in survivors:
                raise SMPEvicted(
                    f"process {old_rank} is not in the agreed survivor set "
                    f"{survivors}; exiting instead of training split-brain."
                )
            new_world = len(survivors)
            my_new_rank = survivors.index(old_rank)
            self._abandon_distributed()
            self._clear_jax_runtime(new_world)
            if new_world > 1:
                if not coord:
                    raise SMPRecoveryError(
                        "multi-survivor recovery without an agreed "
                        "coordinator endpoint (rendezvous info incomplete)."
                    )
                self.initialize_distributed(coord, new_world, my_new_rank)
            self._reinit_framework(state, new_config)
            from smdistributed_modelparallel_tpu.checkpoint import (
                resume_from_checkpoint,
            )
            from smdistributed_modelparallel_tpu.utils import exec_cache

            # Warm-start consult: count the persistent-executable-cache
            # entries available to the shrunken world BEFORE first_step pays
            # (or skips) the recompile, and mark the compile-event ledger so
            # the MTTR closure can split first_step into compile_from_cache
            # vs compile_fresh.
            exec_cache.note_warm_start("recovery")
            compile_mark = exec_cache.compile_event_mark()

            resume_from_checkpoint(ckpt_path, tag=tag, partial=True,
                                   elastic=True)
            if step >= 0:
                state.step_count = int(step)
        reshard_s = time.monotonic() - t0
        flight_recorder.record_supervisor(
            "resume_done", detail=f"tag={tag} step={step} world={new_world}"
        )
        report = {
            "survivors": len(survivors), "survivor_ranks": survivors,
            "old_world": old_world, "rank": my_new_rank,
            "tag": tag, "step": int(step), "ckpt_path": ckpt_path,
            "failures": {int(k): v for k, v in failures.items()},
            "t_detect": t_detect,
            "t_resume_done": time.monotonic(),
            "compile_mark": compile_mark,
            "phases": {
                "detect": detect_s,
                "rendezvous": rendezvous_s,
                "reshard_load": reshard_s,
            },
        }
        # MTTR closes at the first trained step (on_step_edge).
        self._await_first_step = report
        # The ledger sits in recovery_first_step until the resumed loop's
        # next ambient step/trace phase moves it (same closure point).
        goodput.enter("recovery_first_step")
        self.active = True
        logger.warning(
            "RECOVERY: world reformed %d -> %d (rank %d -> %d), resumed "
            "'%s' at step %d; training continues in-job.",
            old_world, new_world, old_rank, my_new_rank, tag, step,
        )
        return report

    # -- recovery phases ------------------------------------------------

    def _await_detection(self, detector, error):
        deadline = time.monotonic() + max(
            3 * detector.budget * detector.interval, 1.0
        )
        while True:
            failures = detector.failures()
            if failures:
                return failures
            if isinstance(error, SMPPeerLost):
                detector.force_dead(error.peer, why=str(error))
                error = None  # consumed; unknown peers fall to the deadline
                continue
            if time.monotonic() > deadline:
                return {}
            time.sleep(detector.interval / 2)

    def _rendezvous(self, bus, survivors, failures, grace):
        """Grace-bounded barrier + view exchange among the survivors over
        the old bus (per-pair TCP links — dead peers don't affect them).
        Survivors that die DURING the rendezvous (barrier, or between the
        barrier and their info landing) are dropped and the round retried;
        bounded rounds cover cascading deaths and view disagreement. A
        rank that finds ITSELF in the exchanged failed-union raises
        ``SMPEvicted`` (its peers are reforming without it)."""
        me = bus.rank

        def _solo():
            return [me], {me: {
                "rank": me, "failed": sorted(failures),
                "step": _state().step_count,
                "ckpt": latest_committed_checkpoint(self._ckpt_root),
            }}

        if len(survivors) <= 1:
            return _solo()
        timeout_ms = max(int(grace * 1000), 1000)
        max_rounds = len(survivors) + 1  # absorbs a full death cascade
        for _round in range(max_rounds):
            if len(survivors) <= 1:
                return _solo()

            def _drop(peer, why):
                self.detector_note_failure(peer)
                failures[peer] = DEAD
                logger.warning(
                    "rendezvous: dropping survivor %d (%s); retrying with "
                    "%s.", peer, why,
                    [s for s in survivors if s != peer],
                )

            # Drain stale RECOVERY_TX frames (an aborted earlier round's
            # exchange, a late eviction echo) so this round's recv pairs
            # with this round's sends.
            for p in survivors:
                if p != me:
                    try:
                        bus.drain_bytes(p, RECOVERY_TX)
                    except Exception:
                        pass
            lost = None
            try:
                bus.barrier(survivors, timeout_ms=timeout_ms)
            except SMPPeerLost as e:
                lost = e.peer
            except (OSError, SMPWatchdogTimeout) as e:
                # An armed watchdog can tighten the bus-level timeout and
                # raise its own type; either way the barrier did not
                # complete and no peer is attributable.
                raise SMPRecoveryError(
                    f"survivor rendezvous barrier failed: {e}"
                ) from e
            if lost is not None:
                if lost not in survivors:
                    raise SMPRecoveryError(
                        f"rendezvous barrier lost non-member {lost}."
                    )
                _drop(lost, "died at the rendezvous barrier")
                survivors = [s for s in survivors if s != lost]
                continue
            info = {
                "rank": me, "failed": sorted(failures),
                "step": _state().step_count,
                "ckpt": latest_committed_checkpoint(self._ckpt_root),
            }
            if me == min(survivors):
                info["coord"] = f"{self._local_ip()}:{_free_port()}"
            payload = json.dumps(info).encode()
            for p in survivors:
                if p != me:
                    bus.send_bytes(p, payload, RECOVERY_TX)
            infos = {me: info}
            for p in survivors:
                if p == me:
                    continue
                try:
                    infos[p] = json.loads(
                        bus.recv_bytes(p, RECOVERY_TX,
                                       timeout_ms=timeout_ms)
                    )
                except (SMPPeerLost, TimeoutError, OSError,
                        SMPWatchdogTimeout) as e:
                    lost = getattr(e, "peer", p)
                    break
            if lost is not None:
                _drop(lost, "died before its rendezvous info landed")
                survivors = [s for s in survivors if s != lost]
                continue
            union = set()
            for i in infos.values():
                union.update(int(f) for f in i.get("failed", ()))
            if me in union:
                raise SMPEvicted(
                    f"process {me} is in the survivors' failed-set union "
                    f"({sorted(union)}): the peers are reforming the "
                    "world without this rank; exiting instead of "
                    "training split-brain."
                )
            for f in union:
                failures.setdefault(f, DEAD)
            new_survivors = [s for s in survivors if s not in union]
            if new_survivors == survivors:
                return survivors, infos
            survivors = new_survivors
        raise SMPRecoveryError(
            f"survivor rendezvous did not converge within {max_rounds} "
            f"rounds (last view: {survivors})."
        )

    def detector_note_failure(self, peer):
        if self.detector is not None:
            self.detector.force_dead(peer, why="died during rendezvous")

    def _agree_checkpoint(self, infos, survivors):
        """The newest checkpoint committed on EVERY survivor. On the
        shared filesystems the checkpoint machinery assumes, every rank
        reports the same newest tag; under lag, the weakest report (the
        lowest step) is the safe agreement — anything newer is not proven
        visible everywhere."""
        reports = [infos[s].get("ckpt") for s in survivors if s in infos]
        if not reports or any(r is None for r in reports):
            raise SMPRecoveryError(
                "no committed checkpoint visible on every survivor under "
                f"'{self._ckpt_root}' — nothing consistent to recover "
                "from (save checkpoints, or lower the save interval)."
            )
        tag, step = min(
            ((r[0], int(r[1])) for r in reports), key=lambda r: (r[1], r[0])
        )
        flight_recorder.record_supervisor(
            "ckpt_agreed", detail=f"tag={tag} step={step}"
        )
        return tag, step

    def _notify_evicted(self, bus, failures, survivors):
        """Best-effort eviction notice to every failed-but-maybe-alive
        peer (a WEDGED rank can outlive its classification): it must exit
        (``SMPEvicted``) instead of recovering into a split brain."""
        for p, kind in failures.items():
            try:
                bus.send_raw(p, json.dumps({
                    "evict": True, "kind": kind,
                    "survivors": survivors,
                }).encode(), RECOVERY_TX)
            except Exception:
                pass

    def _teardown_world(self, state):
        from smdistributed_modelparallel_tpu.checkpoint import (
            wait_for_checkpoints,
        )

        try:
            wait_for_checkpoints()
        except Exception as e:
            logger.error("pending async save failed pre-recovery: %s", e)
        comm = state._comm
        if comm is not None:
            try:
                comm.shutdown()
            except Exception as e:
                logger.warning("bus shutdown during recovery failed: %s", e)
        state._comm = None
        # The rebuilt model/optimizer arrive from the caller after
        # recovery; the old ones hold arrays on the torn-down backend —
        # as does the device-carried step RNG key (its sharding spans the
        # DEAD world's devices and would poison the first rebuilt step).
        state.model = None
        state.optimizer = None
        state.module_manager = None
        state.step_rng = None
        state.loaded_model_state = None
        state.loaded_optimizer_state = None

    def _abandon_distributed(self):
        from jax._src import distributed as jdist

        st = jdist.global_state
        if st.client is not None or st.service is not None:
            # Deliberately leaked (see module docstring): destroying
            # either object fires the runtime's fatal error paths, and
            # live arrays pin the old backend (and through it the client)
            # anyway. One abandoned pair per recovery event. The refcount
            # bump makes the leak IMMORTAL: interpreter shutdown clears
            # module globals in arbitrary order, and a GC'd service under
            # a still-polling client aborts the process at exit.
            import ctypes

            for obj in (st.client, st.service):
                if obj is not None:
                    ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
            self._leaked.append((st.client, st.service))
        st.client = None
        st.service = None
        st.coordinator_address = None
        st.process_id = 0
        st.num_processes = 1  # backend factories read this as num_nodes
        st.preemption_sync_manager = None

    def _clear_jax_runtime(self, new_world):
        import jax
        from jax._src import xla_bridge as xb

        try:
            impl = jax.config._read("jax_cpu_collectives_implementation")
        except Exception:
            impl = None
        if new_world == 1 and impl == "gloo":
            # gloo collectives need a distributed client; a world of one
            # has neither. (Multi-survivor worlds keep gloo — the new
            # client exists by the time backends rebuild.)
            jax.config.update("jax_cpu_collectives_implementation", "none")
        xb._clear_backends()
        # Everything cached against the old device set must go:
        # process_count/process_index and friends are lru_cached at module
        # scope, and compiled computations hold old-backend executables.
        for mod in (xb, jax):
            for name in dir(mod):
                try:
                    fn = getattr(mod, name, None)
                except Exception:
                    continue
                if callable(fn) and hasattr(fn, "cache_clear"):
                    try:
                        fn.cache_clear()
                    except Exception:
                        pass
        jax.clear_caches()

    def _reinit_framework(self, state, new_config):
        import jax

        from smdistributed_modelparallel_tpu.backend.config import (
            ModelParallelConfig,
        )
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPValidationError,
        )

        devices = len(jax.devices())
        if new_config is not None:
            cfg = (new_config if isinstance(new_config, ModelParallelConfig)
                   else ModelParallelConfig(new_config))
            state.initialize(cfg)
            return
        cfg = state.cfg
        try:
            state.initialize(cfg)
            return
        except SMPValidationError as e:
            logger.warning(
                "previous config does not fit the %d surviving device(s) "
                "(%s); falling back to plain data parallelism.", devices, e,
            )
        state.initialize(ModelParallelConfig({
            "ddp": True, "microbatches": cfg.microbatches,
        }))

    # -- misc -----------------------------------------------------------

    @property
    def _ckpt_root(self):
        from smdistributed_modelparallel_tpu.resilience.preemption import (
            EMERGENCY_PATH_ENV,
        )

        return self._recover_ckpt_path or os.environ.get(EMERGENCY_PATH_ENV)

    @staticmethod
    def _local_ip():
        from smdistributed_modelparallel_tpu.backend.collectives import (
            _local_ip,
        )

        return _local_ip()

    def _abort(self, reason):
        """Unrecoverable: dump the detector state + flight ring before the
        typed raise so the post-mortem has the whole story."""
        snap = self.detector.snapshot() if self.detector else None
        logger.error(
            "UNRECOVERABLE recovery abort: %s\ndetector state: %s",
            reason, json.dumps(snap, default=str),
        )
        flight_recorder.record_supervisor("abort", detail=reason[:200])
        try:
            watchdog.dump(f"supervisor: unrecoverable recovery abort "
                          f"({reason})")
        except Exception:
            pass


def _state():
    from smdistributed_modelparallel_tpu.backend.state import state

    return state


def latest_committed_checkpoint(root):
    """(tag, step) of the newest COMMITTED partial checkpoint under
    ``root``, or None. Step comes from the saved config snapshot's
    ``step_count`` (stamped by ``save_checkpoint``), falling back to a
    ``step_<N>`` tag parse, then -1. "Newest" prefers the ``newest``
    pointer when it names a committed dir, else the highest step, else
    mtime."""
    import pickle
    import re

    if not root or not os.path.isdir(root):
        return None

    def _step_of(ckpt_dir, tag):
        cfg_path = os.path.join(ckpt_dir, "smp_config.pt")
        try:
            with open(cfg_path, "rb") as fh:
                snap = pickle.load(fh)
            if isinstance(snap, dict) and "step_count" in snap:
                return int(snap["step_count"])
        except Exception:
            pass
        m = re.search(r"step_?(\d+)", tag)
        return int(m.group(1)) if m else -1

    committed = []
    for d in sorted(os.listdir(root)):
        if not d.endswith("_partial"):
            continue
        full = os.path.join(root, d)
        if not os.path.isdir(full):
            continue
        if not os.path.exists(os.path.join(full, ".committed")):
            continue
        tag = d[: -len("_partial")]
        try:
            mtime = os.path.getmtime(full)
        except OSError:
            mtime = 0.0
        committed.append((tag, _step_of(full, tag), mtime))
    if not committed:
        return None
    newest_path = os.path.join(root, "newest")
    if os.path.exists(newest_path):
        try:
            with open(newest_path) as fh:
                newest = fh.read().strip()
            for tag, step, _ in committed:
                if tag == newest:
                    return (tag, step)
        except OSError:
            pass
    tag, step, _ = max(committed, key=lambda c: (c[1], c[2]))
    return (tag, step)


supervisor = Supervisor()


def classify_failed(bus, peers, kinds=(DEAD, WEDGED)):
    """Classify which of ``peers`` have failed, as ``{peer: kind}``.

    Combines the heartbeat detector's verdicts (when the supervisor is
    on) with the bus's own link-death signal — ``peer_down`` catches a
    closed socket before the miss budget expires, and is the only
    signal when ``SMP_SUPERVISOR=off``. Shared by replica failover
    (serving/replica.py) and fleet aggregator election (utils/fleet.py)
    so both planes agree on who is alive.
    """
    peers = set(peers)
    failed = {}
    detector = supervisor.detector
    if detector is not None:
        failed.update(detector.failures(kinds=kinds))
    if bus is not None and DEAD in kinds:
        for p in peers:
            if p not in failed and bus.peer_down(p):
                failed[p] = DEAD
    return {p: k for p, k in failed.items() if p in peers}
