"""smdistributed_modelparallel_tpu — TPU-native model-parallelism framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of AWS SageMaker's
``smdistributed.modelparallel`` (reference surveyed in /root/repo/SURVEY.md):
pipeline, tensor, data, context and sharded-data parallelism behind the
``smp.init`` / ``@smp.step`` / ``smp.DistributedModel`` /
``smp.DistributedOptimizer`` API, lowered to a single SPMD program over a
``jax.sharding.Mesh`` instead of the reference's MPMD module-server runtime.

Typical use::

    import smdistributed_modelparallel_tpu as smp

    smp.init({"pipeline_parallel_degree": 4, "microbatches": 8, "ddp": True})
    model = smp.DistributedModel(module, loss_fn=...)
    optimizer = smp.DistributedOptimizer(optax.adamw(1e-4), model)

    @smp.step
    def train_step(model, batch):
        loss = model(batch)
        model.backward(loss)
        return loss

    losses = train_step(model, batch)   # StepOutput
    optimizer.step()
"""

import jax as _jax

if not hasattr(_jax, "set_mesh"):
    # jax < 0.5 compat: the step/model/generation engines (and the test
    # suite / graft entry points) bind the mesh at jit call sites via
    # ``with jax.set_mesh(mesh):``. On older jax the Mesh object itself is
    # the context manager with the same scoping semantics (the explicit
    # NamedShardings those engines compute do the real work). Deliberately
    # patched onto the jax namespace — callers outside this package need it
    # too. Limitation: newer jax also allows STATEMENT-style global
    # ``jax.set_mesh(m)``; under this shim that form is a no-op, so only
    # the with-block form is supported on old jax.
    _jax.set_mesh = lambda mesh: mesh

from smdistributed_modelparallel_tpu.backend.config import ModelParallelConfig
from smdistributed_modelparallel_tpu.backend.collectives import (
    CollectiveCommunicator,
    CommGroup,
    RankType,
)
from smdistributed_modelparallel_tpu.backend.split import StepOutput, TensorSplitter
from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.utils import exceptions
from smdistributed_modelparallel_tpu.utils.exceptions import (
    SMPError,
    SMPRuntimeError,
    SMPUnsupportedError,
    SMPValidationError,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger
from smdistributed_modelparallel_tpu.utils.telemetry import telemetry, watchdog
from smdistributed_modelparallel_tpu.utils.flight_recorder import flight_recorder
from smdistributed_modelparallel_tpu.utils import health
from smdistributed_modelparallel_tpu.utils import hlo_audit as xray
from smdistributed_modelparallel_tpu.utils import exec_cache
from smdistributed_modelparallel_tpu.utils import profiling
from smdistributed_modelparallel_tpu import resilience
from smdistributed_modelparallel_tpu.resilience.supervisor import supervisor
from smdistributed_modelparallel_tpu.utils.fleet import fleet
from smdistributed_modelparallel_tpu.utils.goodput import goodput
from smdistributed_modelparallel_tpu.model import DistributedModel
from smdistributed_modelparallel_tpu.optimizer import DistributedOptimizer
from smdistributed_modelparallel_tpu.step import step
from smdistributed_modelparallel_tpu.checkpoint import (
    load,
    resume_from_checkpoint,
    save,
    save_checkpoint,
    wait_for_checkpoints,
)
from smdistributed_modelparallel_tpu.nn.tp_registry import (
    tp_register,
    tp_register_with_module,
)
from smdistributed_modelparallel_tpu.nn.huggingface import from_hf
from smdistributed_modelparallel_tpu.generation import generate
from smdistributed_modelparallel_tpu import serving
from smdistributed_modelparallel_tpu.utils.data import (
    dataloader,
    prefetch_to_device,
    shard_batches,
)
from smdistributed_modelparallel_tpu import amp
from smdistributed_modelparallel_tpu import nn

__version__ = "0.1.0"

WORLD = CommGroup.WORLD
PP_GROUP = CommGroup.PP_GROUP
TP_GROUP = CommGroup.TP_GROUP
DP_GROUP = CommGroup.DP_GROUP
RDP_GROUP = CommGroup.RDP_GROUP
MP_GROUP = CommGroup.MP_GROUP


def init(config=None, devices=None):
    """Initialize the framework.

    Parity: reference ``torch/__init__.py:88-176`` (``smp.init``) — config
    validation, backend init, topology construction. The reference also
    launches a C++ listener thread and patches ``nn.Module``; neither has a
    TPU counterpart (there are no in-flight requests, and module recording
    happens at DistributedModel construction).
    """
    cfg = config if isinstance(config, ModelParallelConfig) else ModelParallelConfig(config)
    state.initialize(cfg, devices=devices)
    return cfg


def is_initialized():
    return state.initialized


def shutdown():
    # Decode the last step's pending health word before the session dies:
    # cheap mode is one step behind by design, and a run whose FINAL step
    # went non-finite should still say so (utils/health.py).
    try:
        health.monitor.flush()
    except Exception:
        pass
    state.core.shutdown()
    state.reset()


def reset():
    """Testing hook: drop model/optimizer/step registrations."""
    from smdistributed_modelparallel_tpu.generation import _COMPILED

    _COMPILED.clear()
    state.reset()


# -- rank / size / group queries (parity: backend/core.py:434-489) ------

def rank():
    return state.core.rank()


def size():
    return state.core.size()


def local_rank():
    return state.core.local_rank()


def local_size():
    return state.core.local_size()


def pp_rank():
    return state.core.pp_rank()


def tp_rank():
    return state.core.tp_rank()


def rdp_rank():
    return state.core.rdp_rank()


def dp_rank():
    return state.core.dp_rank()


def mp_rank():
    return state.core.mp_rank()


def cp_rank():
    return state.core.cp_rank()


def pp_size():
    return state.core.pp_size()


def tp_size():
    return state.core.tp_size()


def rdp_size():
    return state.core.rdp_size()


def dp_size():
    return state.core.dp_size()


def mp_size():
    return state.core.mp_size()


def cp_size():
    return state.core.cp_size()


def num_microbatches():
    return state.cfg.microbatches if state.cfg else 1


def get_pp_group():
    return state.core.get_pp_group()


def get_tp_group():
    return state.core.get_tp_group()


def get_dp_group():
    return state.core.get_dp_group()


def get_rdp_group():
    return state.core.get_rdp_group()


def get_mp_group():
    return state.core.get_mp_group()


def get_world_group():
    return state.core.get_world_group()


def get_mesh():
    """The jax.sharding.Mesh for the current topology (TPU-native addition)."""
    return state.mesh


def barrier(group=CommGroup.WORLD):
    """Barrier over the host processes of `group` (subgroup barriers ride
    the native message bus; see backend/collectives.py)."""
    state.comm.barrier(group=group)


def mp_barrier():
    barrier(CommGroup.MP_GROUP)


def pp_barrier():
    barrier(CommGroup.PP_GROUP)


def dp_barrier():
    barrier(CommGroup.DP_GROUP)


def tp_barrier():
    barrier(CommGroup.TP_GROUP)


def rdp_barrier():
    barrier(CommGroup.RDP_GROUP)


def broadcast(obj, group=CommGroup.WORLD, src=0):
    """Broadcast a picklable object across the processes of `group`.
    Parity: reference ``smp.broadcast`` (``backend/collectives.py``)."""
    return state.comm.broadcast(obj, group=group, src=src)


def allgather(obj, group=CommGroup.WORLD):
    """Gather a picklable object from every process of `group`."""
    return state.comm.allgather(obj, group=group)


def send(obj, dest, group=CommGroup.WORLD):
    """Async-send a picklable object to process `dest` of `group` over the
    native message bus. Parity: reference ``smp.send``."""
    state.comm.send(obj, dest, group=group)


def recv_from(src, group=CommGroup.WORLD):
    """Receive the next in-order object from process `src` of `group`.
    Parity: reference ``smp.recv_from``."""
    return state.comm.recv_from(src, group=group)


def is_tracing():
    """True inside the first-step init/trace pass (parity: reference
    ``smp.is_tracing`` — the module-server trace phase; here the eager
    microbatch-0 run that materializes params and discovers backward)."""
    return bool(getattr(state, "_tracing", False))


def process_index():
    return state.core.process_index()


def process_count():
    return state.core.process_count()


def pp_rank_to_rank(pp_rank):
    """World rank of pipeline stage ``pp_rank`` in this rank's tp x rdp
    group. Parity: reference ``backend/core.py:439-446``."""
    return state.core.pp_rank_to_rank(pp_rank)


def tp_rank_to_rank(tp_rank):
    return state.core.tp_rank_to_rank(tp_rank)


def rdp_rank_to_rank(rdp_rank):
    return state.core.rdp_rank_to_rank(rdp_rank)


def dp_rank_to_rank(dp_rank):
    return state.core.dp_rank_to_rank(dp_rank)


def mp_rank_to_rank(mp_rank):
    return state.core.mp_rank_to_rank(mp_rank)


def instance_id(rank=None):
    """Host (instance) id of device ``rank`` (default: this process's).
    Parity: reference ``smp.instance_id`` (backend/core.py:486-489)."""
    return state.core.instance_id(rank)


def is_in_same_instance(rank):
    """Whether device ``rank`` is on this process's host. Parity:
    reference ``smp.is_in_same_instance`` (backend/core.py:479-481)."""
    return state.core.is_in_same_instance(rank)


def is_multi_node():
    """Parity: reference ``smp.is_multi_node`` (backend/core.py:483-485)."""
    return state.core.is_multi_node()


# Process-group aliases (reference naming: get_*_process_group).
get_pp_process_group = get_pp_group
get_tp_process_group = get_tp_group
get_dp_process_group = get_dp_group
get_rdp_process_group = get_rdp_group
get_mp_process_group = get_mp_group
get_world_process_group = get_world_group


# -- partition / tp / checkpoint annotation APIs ------------------------
# Parity: reference smp.partition / smp.set_partition /
# smp.tensor_parallelism / smp.set_tensor_parallelism /
# smp.set_activation_checkpointing (torch/module_manager.py:969-1161).

def _module_manager():
    from smdistributed_modelparallel_tpu.module_manager import ModuleManager

    if state.module_manager is None:
        state.module_manager = ModuleManager(None)
    return state.module_manager


def partition(stage):
    """Context manager: flax modules constructed inside are assigned to
    pipeline stage `stage` (stamped at construction; harvested when
    DistributedModel walks the tree). Parity: reference ``smp.partition(i)``
    (``torch/module_manager.py:1161``)."""
    return _module_manager().partition(stage)


def set_partition(module_prefix, stage):
    _module_manager().set_partition(module_prefix, stage)


def get_partition(module_prefix):
    if not isinstance(module_prefix, str):
        raise SMPValidationError(
            "get_partition expects a '/'-joined module path string "
            f"(got {type(module_prefix).__name__})."
        )
    return _module_manager().stage_of(_module_manager_norm(module_prefix))


def _module_manager_norm(prefix):
    from smdistributed_modelparallel_tpu.module_manager import _normalize_prefix

    return _normalize_prefix(prefix)


def set_tensor_parallelism(module_prefix, enabled=True, **tp_config):
    _module_manager().set_tensor_parallelism(module_prefix, enabled, **tp_config)


from contextlib import contextmanager as _contextmanager


@_contextmanager
def tensor_parallelism(enabled=True, **tp_config):
    """Context manager: flax modules constructed inside are marked for TP
    distribution (stamped at construction; swapped for their registered
    smp.nn counterparts when DistributedModel walks the tree). Parity:
    reference ``smp.tensor_parallelism`` (``torch/module_manager.py:1095``).
    """
    mm = _module_manager()
    prev = getattr(mm, "_active_tp", None)
    mm._active_tp = {"enabled": enabled, **tp_config}
    try:
        yield
    finally:
        mm._active_tp = prev


@_contextmanager
def delay_param_initialization(enabled=True):
    """Parity: reference ``smp.delay_param_initialization``
    (``torch/parameter.py``). In this framework delayed initialization is
    STRUCTURAL, not opt-in: flax modules are declarative, and parameters
    materialize directly into their mesh shardings on the first step (or
    ``state_dict`` load) via ``eval_shape`` + ``jit(init, out_shardings)``
    — no full-size host tensor ever exists (``model.py``,
    ``tests/test_delayed_init.py``). The context is accepted for source
    compatibility; ``enabled=False`` cannot force eager host-side init
    and raises rather than silently diverging from the reference
    semantics.
    """
    if not enabled:
        from smdistributed_modelparallel_tpu.utils.exceptions import (
            SMPUnsupportedError,
        )

        raise SMPUnsupportedError(
            "delay_param_initialization(enabled=False) is not supported: "
            "parameters always initialize lazily and sharded under the "
            "JAX runtime (there is no eager host-side init to restore)."
        )
    yield


@_contextmanager
def model_creation(tensor_parallelism=False, dtype=None,
                   **tensor_parallel_config):
    """Parity: reference ``smp.model_creation`` (``torch/model.py:79``).

    Bundles the reference's model-construction concerns the way they map
    to this runtime: parameter initialization is always delayed (see
    ``delay_param_initialization``), and the training compute dtype is
    the ``bf16``/``fp16`` config (parameters stay fp32 master copies, as
    the reference's FP16_Module keeps). ``dtype`` must therefore agree
    with the configured half dtype — a mismatch raises instead of
    silently creating a model the step would cast differently. With
    ``tensor_parallelism=True``, modules constructed inside the context
    are marked for auto-distribution (``smp.tensor_parallelism``).
    """
    if dtype is not None:
        import jax.numpy as _jnp

        # state.cfg survives shutdown()/reset() (other surfaces read it
        # as a last-known config); the dtype check must only ever consult
        # the LIVE config, so an uninitialized session is an error rather
        # than a comparison against a dead or absent config.
        if not state.initialized:
            from smdistributed_modelparallel_tpu.utils.exceptions import (
                NotInitializedError,
            )

            raise NotInitializedError("smp.model_creation(dtype=...)")
        half = state.cfg.half_dtype
        want = _jnp.dtype(dtype)
        allowed = {_jnp.dtype(_jnp.float32)}
        if half is not None:
            allowed.add(_jnp.dtype(half))
        if want not in allowed:
            raise SMPValidationError(
                f"model_creation(dtype={want}) conflicts with the "
                f"configured compute dtype ({half or 'float32'}); set the "
                "bf16/fp16 config key instead of a per-model dtype."
            )
    # The parameter shadows the module-level context manager of the same
    # name (the reference's signature dictates both names).
    tp_ctx = globals()["tensor_parallelism"]
    with tp_ctx(enabled=tensor_parallelism, **tensor_parallel_config):
        with delay_param_initialization():
            yield


def set_activation_checkpointing(module_prefix, **config):
    _module_manager().set_activation_checkpointing(module_prefix, **config)


def checkpoint(fn, *args, **kwargs):
    """Rematerialize `fn` (parity: reference ``smp.checkpoint``)."""
    from smdistributed_modelparallel_tpu.parallel.memory import checkpoint as _ckpt

    return _ckpt(fn, *args, **kwargs)


def checkpoint_sequential(fns, input, strategy="each"):
    """Remat a chain (parity: reference ``smp.checkpoint_sequential``)."""
    from smdistributed_modelparallel_tpu.parallel.memory import (
        checkpoint_sequential as _ckpt_seq,
    )

    return _ckpt_seq(fns, input, strategy)
