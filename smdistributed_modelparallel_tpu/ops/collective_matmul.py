"""Ring-decomposed collective matmuls — overlapped tensor parallelism.

The GSPMD tp layers (``nn/linear.py``, ``nn/transformer.py``) express the
Megatron collectives as sharding constraints and let XLA insert
synchronous all-gather / reduce-scatter / all-reduce instructions around
the matmuls (GSPMD, arXiv 2105.04663). Those collectives sit on the
critical path: the matmul cannot start until the gather completes, and
the reduce cannot start until the matmul does. The pjit/TPUv4 paper
(arXiv 2204.06514, §3.3 "overlapping communication with computation")
attributes a large fraction of its MFU headroom to DECOMPOSING exactly
these collectives into per-shard steps whose transfers hide under the
partial matmuls — the "collective matmul" transformation.

``tp_overlap: "ring"`` (env alias ``SMP_TP_OVERLAP``) applies that
transformation here, with the same building blocks the repo already
trusts:

- the ``ops/context_parallel.py`` ring pattern: a full-manual
  ``shard_map`` region over the tp axis whose body rotates blocks with
  ``lax.ppermute`` (point-to-point ICI neighbor traffic);
- the PR-5/PR-12 transfer-register trick: each ring hop is issued
  BEFORE the partial matmul on the block already in hand, tied together
  with an ``optimization_barrier`` (wrapped in a ``custom_vjp`` identity
  so the scheduling pin never enters the transpose program) and parked
  in the loop carry — the X-ray's ``tp_ring_evidence`` proves the hop
  feeds only data movement into the next step's matmul operand;
- GSPMD-level ``custom_vjp`` (the ``pallas_ce.py`` composition): the
  manual regions appear only inside the fwd/bwd implementations and are
  never differentiated through — the backward ring runs the mirrored
  decomposition explicitly.

Two primitives cover the transformer block family:

- ``ring_ag_matmul`` — column-parallel layer consuming a
  SEQUENCE-sharded input: ``y = allgather_seq(x) @ W`` with W sharded on
  an output dim. The ring rotates x's sequence blocks; each step matmuls
  the block in hand against the local weight shard while the next hop is
  in flight. Backward: one ring rotating x re-derives dW per block while
  a second accumulator ring reduce-scatters dx — the mirrored
  decomposition, two permutes per step like the forward's one plus the
  saved gather.
- ``ring_rs_matmul`` — row-parallel layer producing a SEQUENCE-sharded
  output: ``y = reduce_scatter_seq(x @ W)`` with x sharded on a
  contraction dim. The ring rotates the accumulator; each step adds the
  local partial for the chunk in transit. Backward: one ring rotating dy
  blocks computes dx (all-gather-matmul) and dW per block.

Together a [col -> elementwise -> row] block (attention QKV..proj, MLP
fc..proj) runs with ZERO tp-axis all-gather/reduce-scatter instructions
— only tp-attributed collective-permutes — which is exactly what the
``tp_overlap`` fingerprint block gates.

Hop-count note: each ring's fori_loop issues tp hops where the ring
algorithm needs tp-1 — the final iteration's hop is parked in the carry
and dropped at loop exit. That last transfer rides under the final
partial matmul like every other hop, so it costs ICI bandwidth during
that matmul, never latency (tp/(tp-1) extra permute bytes; 2x at tp=2).
It is deliberate: hoisting the last chunk into a loop epilogue would
drop the trip count to tp-1, and at the gated tp=2 tier XLA's
trip-count-1 while-loop simplifier then inlines the body — erasing the
very loop-carry structure ``tp_ring_evidence`` proves double-buffering
by. Revisit if a tp>4 profile shows the tail hop contending.

Multi-axis caveat: the ring regions currently own ONLY the tp axis —
the entry constraints spec tp alone (non-tp dims pinned replicated) and
the in/out specs name no batch axes, so on a dp x tp mesh activations
replicate over dp around every ring matmul, on every jax version (the
jax-0.4 full-manual shard_map fallback, utils/jax_compat.py, gathers
the unnamed axes at region entry too). On a pure-tp mesh (the tp=2
parity/golden tier) this is exact and free; on multi-axis meshes it is
semantically correct but pays dp gather traffic + replicated activation
memory — making the rings batch-sharded (lead-dim axes in the specs and
axis_names) is the ROADMAP follow-up before ring defaults on for dp x tp
jobs. The CPU tier additionally serializes the ring hops, so CPU A/B
timings only prove plumbing (BENCH_NOTES Round 15).
"""

import functools

import jax
import jax.numpy as jnp

from smdistributed_modelparallel_tpu.backend.state import state
from smdistributed_modelparallel_tpu.backend.topology import TP_AXIS
from smdistributed_modelparallel_tpu.utils.jax_compat import (
    ensure_optimization_barrier_rules,
    shard_map,
)
from smdistributed_modelparallel_tpu.utils.logger import get_logger

from smdistributed_modelparallel_tpu.parallel.sharding import (
    single_axis_spec,
)

logger = get_logger()

OVERLAP_ENV = "SMP_TP_OVERLAP"

# One warning per distinct (reason, detail) when the ring path is
# requested but cannot engage and dispatch falls back to GSPMD.
_FALLBACK_WARNED = set()


def _warn_once(key, msg, *args):
    if key in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(key)
    logger.warning(msg, *args)


def tp_overlap_mode(cfg=None):
    """The effective tp_overlap mode: the config knob, canonicalized to
    "off" whenever it cannot change the program (tp degree 1, cp > 1 —
    the ring owns the sequence axis and does not compose with cp's
    sequence sharding). Keyed into the step cache / exec-cache knob
    facts in this canonical form so an idle knob never moves a key."""
    cfg = cfg if cfg is not None else state.cfg
    if cfg is None:
        return "off"
    mode = getattr(cfg, "tp_overlap", "off") or "off"
    if mode == "off":
        return "off"
    if getattr(cfg, "tensor_parallel_degree", 1) <= 1:
        return "off"
    if getattr(cfg, "context_parallel_degree", 1) > 1:
        _warn_once(
            ("cp", mode),
            "tp_overlap=%s requested with context_parallel_degree > 1; "
            "the ring owns the sequence axis and does not compose with "
            "cp — keeping the GSPMD tp path.", mode,
        )
        return "off"
    return mode


def fused_qkv_effective(cfg=None):
    """The cache-key-canonical fused_qkv knob: the config flag,
    canonicalized to False whenever CONFIG alone proves it cannot change
    the program — ``use_pallas_kernels`` disabled, or tp > 1 without the
    ring (``pallas_qkv.fused_qkv_ok`` never passes there; the GSPMD tp
    path keeps the einsum). Same discipline as ``tp_overlap_mode``: an
    idle knob never moves a key. Deliberately config-only: the kernel's
    backend/VMEM preconditions stay OUT of the canonicalization so keys
    never depend on the live backend."""
    cfg = cfg if cfg is not None else state.cfg
    if cfg is None or not bool(getattr(cfg, "fused_qkv", False)):
        return False
    if not bool(getattr(cfg, "use_pallas_kernels", True)):
        return False
    tp = getattr(cfg, "tensor_parallel_degree", 1) or 1
    return tp <= 1 or tp_overlap_mode(cfg) == "ring"


def tp_overlap_active():
    """Whether the tp layers should take the ring path right now: knob
    resolved to "ring" and an initialized mesh with a nontrivial tp
    axis."""
    if tp_overlap_mode() != "ring":
        return False
    if not state.initialized or state.mesh is None:
        return False
    return state.mesh.shape.get(TP_AXIS, 1) > 1


# ----------------------------------------------------------------------
# The transfer-register barrier (PR-5 / PR-12 trick): ties the in-flight
# hop to the operand of the current partial matmul so XLA cannot sink
# the ppermute below the compute it should overlap. Identity on both
# operands; custom_vjp keeps the pin out of the transpose program (the
# backward builds its own mirrored rings with their own pins).
# ----------------------------------------------------------------------


@jax.custom_vjp
def _issue_before(nxt, cur):
    return jax.lax.optimization_barrier((nxt, cur))


def _issue_fwd(nxt, cur):
    return _issue_before(nxt, cur), None


def _issue_bwd(_, ct):
    return ct


_issue_before.defvjp(_issue_fwd, _issue_bwd)


def _chunk_mm(a, w2d, bias, use_pallas, interpret):
    """One partial matmul of the ring: ``a @ w2d (+ bias)`` contracting
    a's last dim. ``use_pallas`` routes through the fused matmul+bias
    kernel (``ops/pallas_qkv.py``) — the "ring + fusions" rung."""
    lead = a.shape[:-1]
    if use_pallas:
        from smdistributed_modelparallel_tpu.ops.pallas_qkv import (
            matmul_bias,
        )

        out = matmul_bias(
            a.reshape(-1, a.shape[-1]), w2d, bias, interpret=interpret
        )
        return out.reshape(lead + (w2d.shape[-1],))
    out = jnp.matmul(a, w2d)
    if bias is not None:
        out = out + bias
    return out


def _maybe_fp8_operands(x, w, site):
    """The ring's fp8 seam (matmul_precision: fp8): round both operands
    to the fp8 grid with the site's delayed scales at the RING BOUNDARY
    — inside, the shard_map/fori_loop bodies trace separately, so amax
    observations recorded there could never escape to the step's
    QuantState. Operand-level fp8: the partial matmuls consume the
    e4m3-gridded values (exactly the values a native-f8 MXU pass would
    see), the f32 ring accumulators and the mirrored backward stay as
    built. No-op outside a quant step trace."""
    from smdistributed_modelparallel_tpu import quant

    if not quant.fp8_trace_active():
        return x, w
    from smdistributed_modelparallel_tpu.utils.telemetry import (
        record_quant_dispatch,
    )

    record_quant_dispatch(site, "fp8")
    return (
        quant.fake_quant(x, site + ".x"),
        quant.fake_quant(w, site + ".w"),
    )


# ----------------------------------------------------------------------
# ring all-gather matmul (column-parallel)
# ----------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _build_ag(mesh, tp, x_ndim, w_ndim, w_tp_dim, has_bias, use_pallas,
              interpret, axis_name=TP_AXIS):
    """custom_vjp ``allgather_seq(x) @ w`` with the gather decomposed
    into a tp-step ring. x: [*lead, S, D] sequence-sharded over tp;
    w: [D, *out] with tp on ``w_tp_dim``; bias (optional): w.shape[1:]
    with tp on ``w_tp_dim - 1``. Output [*lead, S, *out], tp on the out
    dim. See module docstring for the decomposition."""
    ensure_optimization_barrier_rules()
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    seq_dim = x_ndim - 2

    def fwd_body(x, w, b):
        # Local blocks: x [*lead, Sl, D]; w has its tp dim divided.
        Sl = x.shape[seq_dim]
        wl = w.reshape(w.shape[0], -1)                   # [D, Fl]
        bl = b.reshape(-1) if b is not None else None
        me = jax.lax.axis_index(axis_name)
        y0 = jnp.zeros(
            x.shape[:seq_dim] + (Sl * tp, wl.shape[1]), x.dtype
        )

        def body(i, carry):
            y, x_cur = carry
            # Issue the hop FIRST, then pin it next to the matmul operand
            # so the transfer rides under the partial matmul. The hopped
            # block is PARKED in the carry — consumed only by the next
            # iteration's matmul (tp_ring_evidence proves this
            # structurally in the compiled program).
            x_nxt = jax.lax.ppermute(x_cur, axis_name, perm)
            x_nxt, x_cur = _issue_before(x_nxt, x_cur)
            chunk = _chunk_mm(x_cur, wl, bl, use_pallas, interpret)
            src = (me - i) % tp           # whose sequence block we hold
            y = jax.lax.dynamic_update_slice_in_dim(
                y, chunk.astype(y.dtype), src * Sl, axis=seq_dim
            )
            return (y, x_nxt)

        y, _ = jax.lax.fori_loop(0, tp, body, (y0, x))
        return y.reshape(
            x.shape[:seq_dim] + (Sl * tp,) + w.shape[1:]
        )

    def bwd_body(x, w, dy):
        # The mirrored decomposition: x blocks re-rotate to accumulate
        # dW per sequence block while a second ring reduce-scatters dx.
        Sl = x.shape[seq_dim]
        D = x.shape[-1]
        wl = w.reshape(D, -1)
        dyl = dy.reshape(dy.shape[:seq_dim] + (Sl * tp, wl.shape[1]))
        me = jax.lax.axis_index(axis_name)
        dw0 = jnp.zeros(wl.shape, jnp.float32)
        dx0 = jnp.zeros(x.shape, jnp.float32)

        def body(i, carry):
            x_cur, dacc, dwl = carry
            x_nxt = jax.lax.ppermute(x_cur, axis_name, perm)
            x_nxt, x_cur = _issue_before(x_nxt, x_cur)
            src = (me - i) % tp
            dy_src = jax.lax.dynamic_slice_in_dim(
                dyl, src * Sl, Sl, axis=seq_dim
            )
            dwl = dwl + jnp.matmul(
                x_cur.reshape(-1, D).T.astype(jnp.float32),
                dy_src.reshape(-1, wl.shape[1]).astype(jnp.float32),
            )
            # dx reduce-scatter ring: the accumulator hops first (chunk
            # (me - i - 1) is in transit), then gains this device's
            # partial for it.
            dacc = jax.lax.ppermute(dacc, axis_name, perm)
            c = (me - i - 1) % tp
            dy_c = jax.lax.dynamic_slice_in_dim(
                dyl, c * Sl, Sl, axis=seq_dim
            )
            dacc = dacc + jnp.matmul(dy_c, wl.T).astype(jnp.float32)
            return (x_nxt, dacc, dwl)

        _, dx, dwl = jax.lax.fori_loop(0, tp, body, (x, dx0, dw0))
        dw = dwl.reshape(w.shape).astype(w.dtype)
        grads = (dx.astype(x.dtype), dw)
        if has_bias:
            db = jnp.sum(
                dyl.astype(jnp.float32),
                axis=tuple(range(dyl.ndim - 1)),
            )
            grads = grads + (db.reshape(w.shape[1:]).astype(dy.dtype),)
        return grads

    x_spec = single_axis_spec(x_ndim, seq_dim, axis_name)
    w_spec = single_axis_spec(w_ndim, w_tp_dim, axis_name)
    # Output dims: [*lead(seq_dim), S, *w.shape[1:]] — w dim k lands at
    # output dim seq_dim + k.
    out_spec = single_axis_spec(
        seq_dim + w_ndim, seq_dim + w_tp_dim, axis_name
    )
    b_spec = single_axis_spec(w_ndim - 1, w_tp_dim - 1, axis_name)

    fwd_specs = (x_spec, w_spec) + ((b_spec,) if has_bias else ())
    fwd_fn = shard_map(
        (lambda x, w, b: fwd_body(x, w, b)) if has_bias
        else (lambda x, w: fwd_body(x, w, None)),
        mesh=mesh, in_specs=fwd_specs, out_specs=out_spec,
        axis_names={axis_name}, check_vma=False,
    )
    bwd_out = (x_spec, w_spec) + ((b_spec,) if has_bias else ())
    bwd_fn = shard_map(
        bwd_body, mesh=mesh, in_specs=(x_spec, w_spec, out_spec),
        out_specs=bwd_out, axis_names={axis_name}, check_vma=False,
    )

    if has_bias:
        @jax.custom_vjp
        def ag(x, w, b):
            return fwd_fn(x, w, b)

        ag.defvjp(
            lambda x, w, b: (fwd_fn(x, w, b), (x, w)),
            lambda res, dy: bwd_fn(res[0], res[1], dy),
        )
    else:
        @jax.custom_vjp
        def ag(x, w):
            return fwd_fn(x, w)

        ag.defvjp(
            lambda x, w: (fwd_fn(x, w), (x, w)),
            lambda res, dy: bwd_fn(res[0], res[1], dy),
        )
    # Staged under jit so eager callers (init/trace passes) compile once
    # instead of rejecting the manual region (same as _build_cp_call).
    return jax.jit(ag)


def ring_ag_matmul(x, w, bias=None, *, w_tp_dim=1, fused=False):
    """Column-parallel ``allgather_seq(x) @ w (+ bias)`` as a ring, or
    None when the decomposition cannot apply (caller keeps the GSPMD
    einsum). x: [*lead, S, D]; w: [D, *out] with tp on ``w_tp_dim``;
    bias: w.shape[1:]. ``fused`` routes the partial matmuls through the
    Pallas fused matmul+bias kernel."""
    mesh = state.mesh
    tp = mesh.shape.get(TP_AXIS, 1)
    S = x.shape[-2]
    if S % tp != 0:
        _warn_once(("ag", S, tp),
                   "tp_overlap: sequence length %d not divisible by tp=%d"
                   " — GSPMD path for this matmul.", S, tp)
        return None
    if w.shape[w_tp_dim] % tp != 0:
        _warn_once(("ag_feature", w.shape[w_tp_dim], tp),
                   "tp_overlap: output-feature dim %d not divisible by "
                   "tp=%d — GSPMD path for this column-parallel matmul.",
                   w.shape[w_tp_dim], tp)
        return None
    from smdistributed_modelparallel_tpu.nn.utils import shard_activation

    x, w = _maybe_fp8_operands(x, w, "ring_ag")
    x = shard_activation(
        x, *([None] * (x.ndim - 2) + [TP_AXIS, None])
    )
    interpret = jax.default_backend() != "tpu"
    fn = _build_ag(mesh, tp, x.ndim, w.ndim, w_tp_dim,
                   bias is not None, bool(fused), interpret)
    return fn(x, w, bias) if bias is not None else fn(x, w)


# ----------------------------------------------------------------------
# ring reduce-scatter matmul (row-parallel)
# ----------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _build_rs(mesh, tp, x_ndim, n_contract, x_tp_dim, w_ndim,
              interpret, axis_name=TP_AXIS):
    """custom_vjp ``reduce_scatter_seq(x @ w)`` with the reduction
    decomposed into a tp-step accumulator ring. x: [*lead, S, *contract]
    with tp on ``x_tp_dim`` (a contract dim); w: [*contract, *out] with
    tp on the matching dim. Output [*lead, S, *out] sequence-sharded
    over tp."""
    ensure_optimization_barrier_rules()
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    seq_dim = x_ndim - n_contract - 1
    w_tp_dim = x_tp_dim - seq_dim - 1      # position inside w's contract dims

    def fwd_body(x, w):
        # Local blocks: contract dims divided on the tp one; S full.
        S = x.shape[seq_dim]
        Sl = S // tp
        lead = x.shape[:seq_dim]
        xl = x.reshape(lead + (S, -1))                   # [*lead, S, Kl]
        wl = w.reshape(xl.shape[-1], -1)                 # [Kl, Fo]
        me = jax.lax.axis_index(axis_name)
        acc0 = jnp.zeros(lead + (Sl, wl.shape[1]), x.dtype)

        def body(i, acc):
            # The accumulator hops first (the chunk in transit), then the
            # local partial for it is computed and added — hop hidden
            # under the partial matmul.
            acc = jax.lax.ppermute(acc, axis_name, perm)
            c = (me - i - 1) % tp
            x_c = jax.lax.dynamic_slice_in_dim(
                xl, c * Sl, Sl, axis=seq_dim
            )
            acc, x_c = _issue_before(acc, x_c)
            acc = acc + jnp.matmul(x_c, wl).astype(acc.dtype)
            return acc

        acc = jax.lax.fori_loop(0, tp, body, acc0)
        return acc.reshape(lead + (Sl,) + w.shape[n_contract:])

    def bwd_body(x, w, dy):
        # Mirrored: dy blocks ride the ring; each step derives dx rows
        # for the block's owner (all-gather-matmul of dy @ w^T) and that
        # block's dW contribution.
        S = x.shape[seq_dim]
        Sl = S // tp
        lead = x.shape[:seq_dim]
        xl = x.reshape(lead + (S, -1))
        wl = w.reshape(xl.shape[-1], -1)
        dyl = dy.reshape(lead + (Sl, wl.shape[1]))
        me = jax.lax.axis_index(axis_name)
        dx0 = jnp.zeros(xl.shape, jnp.float32)
        dw0 = jnp.zeros(wl.shape, jnp.float32)

        def body(i, carry):
            dy_cur, dx, dwl = carry
            dy_nxt = jax.lax.ppermute(dy_cur, axis_name, perm)
            dy_nxt, dy_cur = _issue_before(dy_nxt, dy_cur)
            src = (me - i) % tp           # whose dy block we hold
            dx = jax.lax.dynamic_update_slice_in_dim(
                dx, jnp.matmul(dy_cur, wl.T).astype(jnp.float32),
                src * Sl, axis=seq_dim,
            )
            x_src = jax.lax.dynamic_slice_in_dim(
                xl, src * Sl, Sl, axis=seq_dim
            )
            dwl = dwl + jnp.matmul(
                x_src.reshape(-1, xl.shape[-1]).T.astype(jnp.float32),
                dy_cur.reshape(-1, wl.shape[1]).astype(jnp.float32),
            )
            return (dy_nxt, dx, dwl)

        _, dx, dwl = jax.lax.fori_loop(0, tp, body, (dyl, dx0, dw0))
        return (
            dx.reshape(x.shape).astype(x.dtype),
            dwl.reshape(w.shape).astype(w.dtype),
        )

    x_spec = single_axis_spec(x_ndim, x_tp_dim, axis_name)
    w_spec = single_axis_spec(w_ndim, w_tp_dim, axis_name)
    out_ndim = seq_dim + 1 + (w_ndim - n_contract)
    out_spec = single_axis_spec(out_ndim, seq_dim, axis_name)

    fwd_fn = shard_map(
        fwd_body, mesh=mesh, in_specs=(x_spec, w_spec),
        out_specs=out_spec, axis_names={axis_name}, check_vma=False,
    )
    bwd_fn = shard_map(
        bwd_body, mesh=mesh, in_specs=(x_spec, w_spec, out_spec),
        out_specs=(x_spec, w_spec), axis_names={axis_name},
        check_vma=False,
    )

    @jax.custom_vjp
    def rs(x, w):
        return fwd_fn(x, w)

    rs.defvjp(
        lambda x, w: (fwd_fn(x, w), (x, w)),
        lambda res, dy: bwd_fn(res[0], res[1], dy),
    )
    return jax.jit(rs)


def ring_rs_matmul(x, w, *, n_contract=1, x_tp_dim=None):
    """Row-parallel ``reduce_scatter_seq(x @ w)`` as a ring, or None
    when the decomposition cannot apply. x: [*lead, S, *contract] with
    tp on ``x_tp_dim`` (default: the first contract dim); w:
    [*contract, *out]; output [*lead, S, *out] sequence-sharded over tp.
    The row-parallel bias is NOT folded here — it must be added once,
    after the reduction, by the caller."""
    mesh = state.mesh
    tp = mesh.shape.get(TP_AXIS, 1)
    seq_dim = x.ndim - n_contract - 1
    if x_tp_dim is None:
        x_tp_dim = seq_dim + 1
    S = x.shape[seq_dim]
    if S % tp != 0:
        _warn_once(("rs", S, tp),
                   "tp_overlap: sequence length %d not divisible by tp=%d"
                   " — GSPMD path for this matmul.", S, tp)
        return None
    if x.shape[x_tp_dim] % tp != 0:
        _warn_once(("rs_contract", x.shape[x_tp_dim], tp),
                   "tp_overlap: contract dim %d not divisible by tp=%d — "
                   "GSPMD all-reduce for this row-parallel matmul.",
                   x.shape[x_tp_dim], tp)
        return None
    from smdistributed_modelparallel_tpu.nn.utils import shard_activation

    x, w = _maybe_fp8_operands(x, w, "ring_rs")
    x = shard_activation(
        x, *[TP_AXIS if d == x_tp_dim else None for d in range(x.ndim)]
    )
    interpret = jax.default_backend() != "tpu"
    fn = _build_rs(mesh, tp, x.ndim, n_contract, x_tp_dim, w.ndim,
                   interpret)
    return fn(x, w)
